"""One-shot workflow-serving A/B — fused DAG vs stage-by-stage, raced.

Builds a fitted 3-stage iris pipeline (StandardScaler -> PCA -> KMeans),
wraps it as a :class:`ServedWorkflow`, warms the bucket ladder, then
drives the SAME predict through both serving modes, interleaved
round-robin (so OS-level drift hits both arms equally):

* **fused**   ``OTPU_WORKFLOW_SERVE=1`` — the whole DAG is ONE bucketed
  AOT executable; a request pads once at the DAG boundary and dispatches
  once;
* **staged**  ``OTPU_WORKFLOW_SERVE=0`` — the kill-switch baseline: each
  stage re-enters the per-model serving path individually (K pads, K
  dispatches, K host round trips).

The knob is read per request, so the arms flip by environment variable —
same process, same models, same rows, same warmed executables. Device
dispatches per request are pinned from the serve-counter deltas (fused
must be 1, staged must be ``n_stages``), and cross-arm parity is checked
to float tolerance (XLA's cross-stage fusion reorders float ops, so the
fused arm differs from staged in the last ulp or two — never more).

Importable: ``run_ab(...)`` returns the parsed record (tier-1 smoke in
tests/test_workflow_serve.py). CLI prints it as JSON on stdout.

Usage:
    python tools/workflow_ab.py [--rows 256] [--iters 40]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from contextlib import contextmanager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ARMS = (
    ("fused", {"OTPU_WORKFLOW_SERVE": "1"}),
    ("staged", {"OTPU_WORKFLOW_SERVE": "0"}),
)


@contextmanager
def _env(overrides: dict):
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _dispatches(counters: dict) -> int:
    return counters.get("bucket_hits", 0) + counters.get("bucket_misses", 0)


def run_ab(session=None, *, rows: int = 256, iters: int = 40,
           warmup: int = 3) -> dict:
    """Race fused vs stage-by-stage serving of one 3-stage DAG; return
    ``{"metric": "workflow_ab", ...}`` with per-arm p50s, the speedup,
    and the per-request dispatch counts."""
    import numpy as np

    from orange3_spark_tpu.core.session import TpuSession
    from orange3_spark_tpu.core.table import TpuTable
    from orange3_spark_tpu.datasets import load_iris
    from orange3_spark_tpu.serve import (
        BucketLadder, ServedWorkflow, ServingContext,
    )
    from orange3_spark_tpu.models.kmeans import KMeans
    from orange3_spark_tpu.models.pca import PCA
    from orange3_spark_tpu.models.preprocess import StandardScaler
    from orange3_spark_tpu.utils.profiling import (
        reset_serve_counters, serve_counters,
    )

    session = session or TpuSession.builder_get_or_create()
    iris = load_iris(session)
    scaler = StandardScaler().fit(iris)
    scaled = scaler.transform(iris)
    pca = PCA(k=2).fit(scaled)
    km = KMeans(k=3, seed=0).fit(pca.transform(scaled))
    wf = ServedWorkflow.from_stages([scaler, pca, km], iris, name="ab-wf")

    rng = np.random.default_rng(7)
    idx = rng.integers(0, iris.n_rows, rows)
    X = np.asarray(iris.X)[idx].astype(np.float32)
    Y = np.asarray(iris.Y)[idx].astype(np.float32)
    t = TpuTable.from_numpy(iris.domain, X, Y, session=session)

    with ServingContext(BucketLadder(min_bucket=64, max_bucket=1 << 12)):
        expect = None
        disp: dict[str, int] = {}
        for name, env in ARMS:      # warm both arms (and check parity)
            with _env(env):
                for _ in range(max(warmup, 1)):
                    out = np.asarray(wf.predict(t))
                reset_serve_counters()
                out = np.asarray(wf.predict(t))
                disp[name] = _dispatches(serve_counters())
                if expect is None:
                    expect = out
                elif not np.allclose(out, expect, atol=1e-5):
                    raise AssertionError(
                        f"workflow arm {name} diverged beyond float "
                        "tolerance from the fused prediction")
        lat: dict[str, list] = {name: [] for name, _ in ARMS}
        for _ in range(max(iters, 1)):
            for name, env in ARMS:  # interleaved: drift hits both arms
                with _env(env):
                    t0 = time.perf_counter()
                    wf.predict(t)
                    lat[name].append((time.perf_counter() - t0) * 1e3)
    p50 = {n: round(statistics.median(v), 4) for n, v in lat.items()}
    return {
        "metric": "workflow_ab",
        "value": round(p50["staged"] / max(p50["fused"], 1e-9), 3),
        "unit": "x_staged_over_fused",
        "vs_baseline": None,
        "rows": rows,
        "iters": iters,
        "n_stages": wf.n_stages,
        "fused_p50_ms": p50["fused"],
        "staged_p50_ms": p50["staged"],
        "workflow_fused_speedup": round(
            p50["staged"] / max(p50["fused"], 1e-9), 3),
        "dispatch_fused": disp["fused"],
        "dispatch_staged": disp["staged"],
        "parity": True,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=256)
    ap.add_argument("--iters", type=int, default=40)
    args = ap.parse_args()
    sys.path.insert(0, REPO)
    print(json.dumps(run_ab(rows=args.rows, iters=args.iters)))


if __name__ == "__main__":
    main()
