"""One-shot goodput & device-memory viewer — "where did the time and the
HBM go", rendered.

Renders the obs/prof.py attribution surfaces as a readable report: the
five-way wall decomposition as an ASCII bar per stage, the per-epoch
bottleneck classification, and the device-memory ledger table (per-owner
bytes + the largest named entries + the runtime reconciliation delta).

Three input shapes, sniffed automatically:

* a ``RunReport`` JSON (``model.run_report_.to_json(path)``) — renders
  its ``goodput`` + ``device_memory`` sections;
* a deep-capture ``snapshot.json`` (or the capture DIRECTORY holding
  one — ``prof.capture()`` / ``POST /debug/profile`` artifacts);
* no argument: **demo mode** — fit a tiny hashed CTR model in-process
  and render its report (the zero-setup smoke, and the tier-1 test).

Importable: ``run_view(path=None, ...) -> dict`` (the summary the CLI
prints as its one JSON line).

Usage:
    python tools/goodput_view.py [REPORT.json | CAPTURE_DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

_BAR_W = 36


def _bar(frac: float) -> str:
    n = int(round(max(0.0, min(frac, 1.0)) * _BAR_W))
    return "#" * n + "." * (_BAR_W - n)


def ledger_lines(device_memory: dict, *, max_entries: int = 10) -> list:
    """The ONE device-memory-ledger table rendering (shared with
    tools/flight_view.py — a ledger-schema change edits one place):
    per-owner totals, the largest named entries, the reconciliation
    delta (reported, never asserted)."""
    dm = device_memory
    lines = [f"device-memory ledger "
             f"(live {dm.get('total_bytes', 0)/1e6:.2f} MB, "
             f"peak {dm.get('peak_bytes', 0)/1e6:.2f} MB)"]
    for owner, nbytes in sorted((dm.get("owners") or {}).items()):
        lines.append(f"  {owner:<20} {nbytes/1e6:10.3f} MB")
    for e in (dm.get("entries") or [])[:max_entries]:
        lines.append(f"    {e['owner']}/{e['name']:<26} "
                     f"{e['bytes']/1e6:10.3f} MB")
    rec = dm.get("reconciliation") or {}
    if rec.get("jax_live_bytes") is not None:
        lines.append(f"  reconcile: ledger={rec['ledger_bytes']} "
                     f"jax_live={rec['jax_live_bytes']} "
                     f"delta={rec.get('delta_vs_live_bytes')} "
                     f"(reported, never asserted)")
    return lines


def render(goodput: dict | None, device_memory: dict | None,
           out=sys.stderr) -> None:
    """Print the human-readable report (stderr — stdout carries the one
    summary JSON line, the tools convention)."""
    if goodput:
        print(f"[goodput] wall {goodput.get('wall_s', 0):.3f}s  "
              f"bottleneck: {goodput.get('bottleneck')}", file=out)
        for stage, frac in (goodput.get("fractions") or {}).items():
            secs = (goodput.get("seconds") or {}).get(stage, 0.0)
            print(f"[goodput]   {stage:<15} {_bar(frac)} "
                  f"{100 * frac:5.1f}%  {secs:.3f}s", file=out)
        epochs = goodput.get("epochs") or []
        if epochs:
            print("[goodput] per-epoch bottleneck: "
                  + " ".join(f"e{e['epoch']}={e['bottleneck']}"
                             for e in epochs), file=out)
    else:
        print("[goodput] no goodput section (OTPU_PROF=0 run, or a "
              "pre-prof report)", file=out)
    if device_memory:
        for line in ledger_lines(device_memory):
            print(f"[ledger] {line}", file=out)


def _load(path: str) -> tuple[dict | None, dict | None, str]:
    """(goodput, device_memory, source kind) from any of the three input
    shapes."""
    if os.path.isdir(path):
        snap_path = os.path.join(path, "snapshot.json")
        if not os.path.exists(snap_path):
            raise FileNotFoundError(
                f"{path} is a directory without a snapshot.json — not a "
                f"deep-capture artifact (prof.capture / /debug/profile)")
        path = snap_path
    with open(path) as f:
        d = json.load(f)
    if "prof_schema" in d and "ledger" in d:      # capture snapshot.json
        led = dict(d.get("ledger") or {})
        # captures store reconciliation as the ledger's SIBLING; fold
        # it in so the renderer's one shape covers both input kinds
        if "reconciliation" in d:
            led.setdefault("reconciliation", d["reconciliation"])
        return d.get("goodput"), led, "capture"
    # RunReport dict: goodput/device_memory sections (absent under
    # OTPU_PROF=0 — rendered as such, never a crash)
    return d.get("goodput"), d.get("device_memory"), "report"


def _demo_report(session=None, rows: int = 4096) -> dict:
    """Demo mode: a tiny hashed CTR fit, cache-device on, report back."""
    import numpy as np

    from orange3_spark_tpu.core.session import TpuSession
    from orange3_spark_tpu.io.streaming import array_chunk_source
    from orange3_spark_tpu.models.hashed_linear import (
        StreamingHashedLinearEstimator,
    )

    session = session or TpuSession.builder_get_or_create()
    rng = np.random.default_rng(11)
    X = np.concatenate([
        rng.standard_normal((rows, 4)).astype(np.float32),
        rng.integers(0, 500, (rows, 4)).astype(np.float32),
    ], axis=1)
    y = (rng.random(rows) < 0.3).astype(np.float32)
    model = StreamingHashedLinearEstimator(
        n_dims=1 << 12, n_dense=4, n_cat=4, epochs=3, step_size=0.05,
        chunk_rows=512,
    ).fit_stream(array_chunk_source(X, y, chunk_rows=512),
                 session=session, cache_device=True)
    rep = getattr(model, "run_report_", None)
    return rep.to_dict() if rep is not None else {}


def run_view(path: str | None = None, session=None,
             rows: int = 4096) -> dict:
    """Render one goodput/ledger view; returns the summary dict."""
    if path is not None:
        goodput, device_memory, source = _load(path)
    else:
        d = _demo_report(session, rows)
        goodput, device_memory, source = (
            d.get("goodput"), d.get("device_memory"), "demo")
    render(goodput, device_memory)
    fracs = (goodput or {}).get("fractions") or {}
    return {
        "metric": "goodput_view",
        "source": source,
        "bottleneck": (goodput or {}).get("bottleneck"),
        "fractions": fracs,
        "fractions_sum": round(sum(fracs.values()), 4) if fracs else None,
        "ledger_owners": (device_memory or {}).get("owners"),
        "ledger_total_bytes": (device_memory or {}).get("total_bytes"),
        "ledger_peak_bytes": (device_memory or {}).get("peak_bytes"),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", nargs="?", default=None,
                    help="RunReport JSON or deep-capture dir/snapshot "
                         "(default: demo fit)")
    ap.add_argument("--rows", type=int, default=4096)
    args = ap.parse_args()
    out = run_view(args.path, rows=args.rows)
    print(json.dumps(out, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
