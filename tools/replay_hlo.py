"""Fused-replay fault mechanism experiment: HLO-dump comparison.

Round-4 established (tools/replay_fault_diag.py, banked verdict in
BENCH_HW_r4.jsonl): the giant fused-replay scan dies UNAVAILABLE whenever
ANY program executed before it in the same process, while the identical
Python call runs clean standalone — and n_epochs=1 scans are immune in
every order. What round 4 could NOT say is *why*: does the poisoned
process compile a *different* XLA program (program-content hypothesis:
e.g. donation/aliasing or layout decisions change once other buffers are
live), or the *same* program that only the runtime then fails to run
(runtime-state hypothesis: allocator fragmentation, tunnel stream state)?

This tool answers with XLA's own dump: two fresh subprocess cells run the
replay scan with ``--xla_dump_to`` — one standalone (clean), one after a
one-chunk ``fit_stream`` (poisoned, expected to fault AFTER compile; the
dump is written at compile time so the fault does not cost the evidence).
The dumped ``after_optimizations`` HLO of the replay modules is compared
modulo volatile ids:

* identical HLO + fault reproduced  => RUNTIME-STATE: the same compiled
  program faults only when executions preceded it — fence it (per-epoch
  granularity stays the hardware default), nothing to fix in our lowering.
* different HLO                     => PROGRAM-CONTENT: diff the dumps,
  the divergence names the mechanism.

Prints one ``{"metric": "replay_fault_hlo", ...}`` JSON line for the
capture watcher to bank.
"""

from __future__ import annotations

import argparse
import glob
import hashlib
import json
import os
import re
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: pgids of in-flight cell subprocesses — killed by the SIGTERM handler so
#: the watcher's graceful preempt (SIGTERM + grace, then SIGKILL) cannot
#: orphan a live TPU cell into colliding with the round-end bench
_LIVE_CELLS: set[int] = set()


def _sigterm_handler(signum, frame):  # noqa: ARG001
    import signal as _signal

    for pid in list(_LIVE_CELLS):
        try:
            os.killpg(pid, _signal.SIGKILL)
        except ProcessLookupError:
            pass
    os._exit(143)


_CELL_SRC = r"""
import sys, time
sys.path.insert(0, __REPO__)
import jax
import numpy as np

chunk_rows = __CHUNK_ROWS__
stages = __STAGES__

from orange3_spark_tpu.core.session import TpuSession
from orange3_spark_tpu.models.hashed_linear import (
    StreamingHashedLinearEstimator,
)

sess = TpuSession.builder_get_or_create()
assert jax.default_backend() == "tpu", jax.default_backend()

def make_est(e):
    return StreamingHashedLinearEstimator(
        n_dims=1 << 22, n_dense=13, n_cat=26, epochs=e,
        chunk_rows=chunk_rows, label_in_chunk=True, prefetch_depth=2,
        emb_update="sorted",
    )

for stage in stages:
    t0 = time.perf_counter()
    if stage == "fitnp":
        Xnp = np.zeros((chunk_rows, 40), np.float32)
        def np_source():
            yield Xnp
        make_est(1).fit_stream(
            np_source, session=sess, cache_device=True, holdout_chunks=0)
    elif stage == "replay":
        make_est(100).warm_replay(6, session=sess)
    else:
        raise ValueError(stage)
    print(f"STAGE_OK {stage} {time.perf_counter()-t0:.1f}s", flush=True)
print("CELL_OK", flush=True)
"""


def run_cell(name: str, stages: list, dump_dir: str, chunk_rows: int,
             wall_s: float) -> dict:
    shutil.rmtree(dump_dir, ignore_errors=True)
    os.makedirs(dump_dir, exist_ok=True)
    src = (_CELL_SRC
           .replace("__REPO__", repr(REPO))
           .replace("__CHUNK_ROWS__", str(chunk_rows))
           .replace("__STAGES__", repr(list(stages))))
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_dump_to={dump_dir}"
                        + " --xla_dump_hlo_as_text").strip()
    t0 = time.time()
    # own process group + group kill + bounded second wait: a wedged cell
    # spawns tunnel-helper descendants that inherit the pipes, and a plain
    # subprocess.run would block forever in its post-kill communicate()
    # while we hold the device lock (the round-4 probe lesson). The cell's
    # pgid is tracked in _LIVE_CELLS so OUR OWN SIGTERM (the watcher's
    # graceful preempt kill) can take the cell down with us — otherwise a
    # preempted replay_hlo would orphan a live TPU cell to collide with
    # the round-end bench, lock-less.
    proc = subprocess.Popen([sys.executable, "-c", src],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, cwd=REPO, env=env,
                            start_new_session=True)
    _LIVE_CELLS.add(proc.pid)
    try:
        try:
            out, err = proc.communicate(timeout=wall_s)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            import signal as _signal

            rc = "wall-timeout"
            try:
                os.killpg(proc.pid, _signal.SIGKILL)
            except ProcessLookupError:
                pass
            try:
                out, err = proc.communicate(timeout=30)
            except subprocess.TimeoutExpired as e2:
                def _dec(b):
                    return (b or b"").decode("utf-8", "replace") \
                        if isinstance(b, bytes) else (b or "")
                out, err = _dec(e2.stdout), _dec(e2.stderr)
    finally:
        _LIVE_CELLS.discard(proc.pid)
    out, err = out or "", err or ""
    res = {
        "cell": name, "stages": stages,
        "ok": rc == 0 and "CELL_OK" in out,
        "stages_completed": [ln.split()[1] for ln in out.splitlines()
                             if ln.startswith("STAGE_OK ")],
        "rc": rc,
        "device_fault": "UNAVAILABLE" in err or "UNAVAILABLE" in out,
        "wall_s": round(time.time() - t0, 1),
    }
    if not res["ok"]:
        tail = err.strip().splitlines()[-1:] if err.strip() else []
        res["error_tail"] = tail[0][-200:] if tail else ""
    return res


#: volatile tokens in dumped HLO text: module/computation/op unique ids
#: (``jit_foo.123``, ``%fusion.4``) — anchored to an identifier character
#: before the dot so FLOAT LITERALS (``1.25``, digit before the dot)
#: survive canonicalization: a constant that differs between the clean and
#: poisoned programs is exactly the evidence this tool must not erase
_ID_RE = re.compile(r"(?<=[A-Za-z_])\.\d+")
_META_RE = re.compile(r"metadata=\{[^}]*\}")
#: dump FILENAMES additionally carry a per-process module counter prefix
_MODNUM_RE = re.compile(r"^module_\d+\.")


def _canon_hlo(text: str) -> str:
    return _META_RE.sub("", _ID_RE.sub("", text))


def replay_dumps(dump_dir: str) -> dict[str, str]:
    """{canonical module key -> sha256 of canonicalized after-optimizations
    HLO} for every dumped module belonging to the replay scan program."""
    out = {}
    for p in sorted(glob.glob(os.path.join(
            dump_dir, "*replay*after_optimizations*.txt"))):
        base = _ID_RE.sub("", _MODNUM_RE.sub("", os.path.basename(p)))
        with open(p) as f:
            out[base] = hashlib.sha256(
                _canon_hlo(f.read()).encode()).hexdigest()
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--chunk-rows", type=int, default=1 << 18)
    ap.add_argument("--wall-s", type=float, default=600.0)
    ap.add_argument("--dump-root", default="/tmp/otpu_hlo")
    args = ap.parse_args()

    import signal

    signal.signal(signal.SIGTERM, _sigterm_handler)

    sys.path.insert(0, REPO)
    from orange3_spark_tpu.utils.devlock import tpu_device_lock

    # serialize against any other TPU harness for BOTH cells (the cells
    # are this process's children and take no lock of their own)
    with tpu_device_lock(name="replay_hlo"):
        _main_locked(args)


def _main_locked(args) -> None:
    clean_dir = f"{args.dump_root}_clean"
    poison_dir = f"{args.dump_root}_poisoned"
    cells = [
        ("clean", ["replay"], clean_dir),
        ("poisoned", ["fitnp", "replay"], poison_dir),
    ]
    results = []
    for name, stages, dump_dir in cells:
        res = run_cell(name, stages, dump_dir, args.chunk_rows, args.wall_s)
        print(json.dumps(res), flush=True)
        results.append(res)
    by = {r["cell"]: r for r in results}

    clean = replay_dumps(clean_dir)
    poison = replay_dumps(poison_dir)
    shared = sorted(set(clean) & set(poison))
    differing = [k for k in shared if clean[k] != poison[k]]
    only_clean = sorted(set(clean) - set(poison))
    only_poison = sorted(set(poison) - set(clean))
    identical = bool(shared) and not differing \
        and not only_clean and not only_poison
    reproduced = by["poisoned"]["device_fault"]
    if not shared:
        verdict = "inconclusive: no replay modules dumped in both cells"
    elif identical and reproduced:
        verdict = ("runtime-state: identical optimized HLO faults only "
                   "when executions preceded it")
    elif identical:
        verdict = ("fault not reproduced this window; HLO identical "
                   "(consistent with runtime-state)")
    elif differing:
        verdict = (f"program-content: {len(differing)} replay module(s) "
                   f"differ — diff the dumps")
    else:
        # all shared modules hash equal but one cell dumped extra replay
        # modules — a lowering-set difference, not a same-module rewrite
        verdict = (f"module-set-mismatch: only-clean={only_clean[:4]} "
                   f"only-poisoned={only_poison[:4]} (shared modules "
                   f"identical)")
    print(json.dumps({
        "metric": "replay_fault_hlo",
        "value": len(shared) or 1,   # nonzero: the watcher banks it even
        "unit": "modules_compared",  # when the comparison is inconclusive
        "vs_baseline": None,
        "backend": "tpu",
        "clean_ok": by["clean"]["ok"],
        "poisoned_fault": reproduced,
        "hlo_identical": identical,
        "modules_clean": len(clean),
        "modules_poisoned": len(poison),
        "differing_modules": differing[:8],
        "modules_only_clean": only_clean[:8],
        "modules_only_poisoned": only_poison[:8],
        "verdict": verdict,
        "dump_dirs": [clean_dir, poison_dir],
        "cells": results,
    }), flush=True)


if __name__ == "__main__":
    main()
