"""Round preflight: one command that checks everything the round driver
touches, so a fresh session (or a pre-round-end sanity pass) knows the
repo's state in ~3 minutes without re-deriving it.

    PYTHONPATH= python tools/preflight.py        # CPU-only, tunnel-safe

Checks (all in subprocesses, none touches the tunnel):
  1. test collection count (the suite itself takes ~13 min — not run)
  2. the driver-facing bench contract, via its canonical pytest module
     (tests/test_bench_contract.py — ONE set of assertions, no drift)
  3. __graft_entry__ dryrun_multichip(8) on the CPU mesh
  4. capture watcher state + banked hardware lines summary
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.capture_watcher import OUT as BANK_PATH          # noqa: E402
from tools.capture_watcher import STATE as STATE_PATH       # noqa: E402


def run(argv, env_extra=None, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = ""          # axon sitecustomize wedge-proof
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    t0 = time.time()
    try:
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout, cwd=REPO, env=env)
        return r.returncode, r.stdout, r.stderr, time.time() - t0
    except subprocess.TimeoutExpired as e:
        def _s(b):
            return (b.decode("utf-8", "replace")
                    if isinstance(b, bytes) else (b or ""))
        return "timeout", _s(e.stdout), _s(e.stderr), time.time() - t0


def _err_tail(out: str, err: str) -> None:
    tail = (err.strip() or out.strip())[-2000:]
    if tail:
        print("    --- failure tail ---")
        for ln in tail.splitlines()[-12:]:
            print(f"    {ln}")


def main() -> int:
    ok = True

    rc, out, err, dt = run([sys.executable, "-m", "pytest", "tests/",
                            "--collect-only", "-q"], timeout=300)
    n_tests = next((ln.split()[0] for ln in reversed(out.splitlines())
                    if "tests collected" in ln or "test collected" in ln),
                   "?")
    print(f"[1] test collection: {n_tests} tests ({dt:.0f}s, rc={rc})")
    if rc != 0:
        ok = False
        _err_tail(out, err)

    # the canonical contract assertions; OTPU_CHILD=1 skips the device
    # lock in the spawned harnesses — preflight's runs are CPU-pinned and
    # never touch the tunnel, so contending with a live capture step
    # would only manufacture a false FAILED. (bench.py's retry ladder is
    # also OTPU_CHILD-gated, but the CPU fallback path preflight takes
    # never reaches it.)
    rc, out, err, dt = run(
        [sys.executable, "-m", "pytest", "tests/test_bench_contract.py",
         "-q"], env_extra={"OTPU_CHILD": "1"})
    print(f"[2] bench contract (canonical tests): rc={rc} ({dt:.0f}s)")
    if rc != 0:
        ok = False
        _err_tail(out, err)

    code = ("import sys; sys.path.insert(0, '.');"
            "import __graft_entry__ as g; g.dryrun_multichip(8)")
    rc, out, err, dt = run(
        [sys.executable, "-c", code],
        env_extra={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    line = next((ln for ln in out.splitlines()
                 if ln.startswith("dryrun_multichip OK")), "(no OK line)")
    print(f"[3] dryrun_multichip(8): rc={rc} ({dt:.0f}s) {line[:90]}")
    if rc != 0:
        ok = False
        _err_tail(out, err)

    try:
        st = json.load(open(STATE_PATH))
    except (OSError, ValueError):
        st = {}
    try:
        with open(BANK_PATH) as f:
            banked = [json.loads(ln) for ln in f if ln.strip()]
    except (OSError, ValueError):
        banked = []
    watcher_alive = subprocess.run(
        ["pgrep", "-f", "tools/capture_watcher"], capture_output=True
    ).returncode == 0
    print(f"[4] watcher: {'RUNNING' if watcher_alive else 'NOT running'}; "
          f"state={ {k: v.get('done') for k, v in st.items()} }; "
          f"banked hardware lines={len(banked)} "
          f"({[d.get('metric') for d in banked]})")

    print("PREFLIGHT", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
