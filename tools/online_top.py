"""One-shot continuous-learning status probe — ``top`` for the online loop.

Runs a miniature train-while-serve drill in-process (tiny CTR fit, tapped
traffic through a ServingContext, the incremental trainer over the real
OTPURQL1 log, one storeside publish cycle through the drift/shadow
gates) and renders the loop's status the way an operator would read it
off a live deployment: trainer goodput, label-join accounting, log lag,
store/quarantine state, last promotion outcome.

The table goes to stderr; ONE JSON line goes to stdout (the
capture-watcher banking convention, like tools/fault_matrix.py).
Importable: ``run_status(session=...)`` returns the status dict (the
not-slow smoke test in tests/test_online.py calls it directly).

Usage:
    python tools/online_top.py [--rows 1024]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_status(rows: int = 1024, session=None) -> dict:
    import numpy as np

    from orange3_spark_tpu.core.session import TpuSession
    from orange3_spark_tpu.io.streaming import array_chunk_source
    from orange3_spark_tpu.models.hashed_linear import (
        StreamingHashedLinearEstimator,
    )
    from orange3_spark_tpu.online import OnlineLoop
    from orange3_spark_tpu.serve import BucketLadder, ServingContext

    session = session or TpuSession.builder_get_or_create()
    rng = np.random.default_rng(0)
    n_dense = n_cat = 2
    chunk = 128
    X = np.concatenate([
        rng.standard_normal((rows, n_dense)).astype(np.float32),
        rng.integers(0, 50, (rows, n_cat)).astype(np.float32),
    ], axis=1)
    y = (X[:, 0] > 0).astype(np.float32)
    model = StreamingHashedLinearEstimator(
        n_dims=1 << 8, n_dense=n_dense, n_cat=n_cat, epochs=1,
        step_size=0.05, chunk_rows=chunk,
    ).fit_stream(array_chunk_source(X, y, chunk_rows=chunk),
                 session=session)
    root = tempfile.mkdtemp(prefix="otpu_online_top_")
    try:
        loop = OnlineLoop(
            model, os.path.join(root, "store"),
            os.path.join(root, "req.log"), session=session,
            reference_X=X,
            holdout_source=array_chunk_source(X, y, chunk_rows=chunk),
            min_examples=chunk,
            trainer_kw={"chunk_rows": chunk, "join_window": 32,
                        "ckpt_steps": 2},
            shadow_kw={"disagree_threshold": 0.95})
        with ServingContext(BucketLadder(min_bucket=32,
                                         max_bucket=chunk)), loop:
            for i in range(0, rows, chunk):
                model.predict(X[i:i + chunk])
                rid = loop.tap.last_request_id()
                if rid is not None:
                    loop.tap.tap_label(rid, y[i:i + chunk])
            deadline = time.monotonic() + 120
            while (time.monotonic() < deadline
                   and loop.trainer.status()["steps"] < rows // chunk
                   and not loop.trainer.status()["died"]):
                time.sleep(0.05)
            loop.publish_cycle()
            status = loop.status()
        return status
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _render(status: dict) -> None:
    tr = status["trainer"]
    st = status["store"]
    print("online loop — one-shot status", file=sys.stderr)
    print(f"  trainer   steps {tr['steps']}  examples {tr['examples']}  "
          f"ex/s {tr['examples_per_s']}  last_loss "
          f"{tr['last_loss'] if tr['last_loss'] is None else round(tr['last_loss'], 4)}",
          file=sys.stderr)
    print(f"            lag {tr['lag_bytes']} B  buffered "
          f"{tr['buffered_rows']} rows  resumed_from "
          f"{tr['resumed_from_step']}  alive {tr['alive']}",
          file=sys.stderr)
    jc = tr["join_counts"]
    print(f"  joiner    joined {jc['joined']}  late {jc['late']}  "
          f"orphan {jc['orphan']}", file=sys.stderr)
    print(f"  log       {status['log_bytes']} B on disk", file=sys.stderr)
    print(f"  store     CURRENT {st['current']}  versions "
          f"{len(st['versions'])}  quarantined {st['quarantined']}",
          file=sys.stderr)
    print(f"  cycles    {status['cycles']}  last outcome "
          f"{status['last_outcome']}", file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1024)
    args = ap.parse_args()
    sys.path.insert(0, REPO)
    status = run_status(rows=args.rows)
    _render(status)
    tr = status["trainer"]
    ok = (tr["steps"] > 0 and not tr["died"]
          and status["last_outcome"] is not None)
    print(json.dumps({
        "metric": "online_top",
        "value": tr["steps"],
        "unit": "trainer_steps",
        "vs_baseline": None,
        "last_outcome": status["last_outcome"],
        "join_counts": tr["join_counts"],
        "quarantined": status["store"]["quarantined"],
        "ok": ok,
    }))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
