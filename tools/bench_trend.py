"""One-shot comparator over banked bench rounds — ``BENCH_r*.json``.

Each round the driver banks one ``BENCH_rNN.json`` per capture: a dict
whose ``parsed`` key holds the bench's single stdout JSON record (some
rounds bank a LIST of such captures). This tool aligns those records
across rounds by their ``metric`` name and prints per-metric deltas —
and flags regressions **only on same-run ratio metrics**: absolute
rows/s are not cross-container comparable (the ROUND notes' standing
caveat — r05's host measured ~14x slower than r03's on identical code),
but a ratio both arms of which ran in the SAME process (speedups,
compression, scaling factors) carries across containers. A ratio that
drops more than ``threshold`` (default 20%) vs the previous round it
appeared in is flagged.

Importable: ``run_trend(paths=None, root=REPO, threshold=0.2) -> dict``
(the tier-1 smoke calls it on synthetic rounds and on the real bank).

Usage:
    python tools/bench_trend.py [--root DIR] [--threshold 0.2]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

#: higher-is-better SAME-RUN ratios — the only metrics whose cross-round
#: drop is a regression signal rather than a container artifact
RATIO_KEYS = frozenset({
    "vs_baseline",
    "optim_step_speedup",
    "cache_step_speedup",
    "compression_ratio",
    "compile_reduction",
    "mb_merge_factor",
    "overlap_pct",
    "scaling_factor",
    "p99_bound_factor",
    "trace_coverage",
    "multihost_scaling",
    # r8: whole-workflow fused serving (taxi_pipeline config) — the
    # fused-vs-stagewise serving p50 ratio and the staged fit/transform
    # ratios promoted from bench_suite config 5
    "workflow_fused_speedup",
    "staged_speedup",
    "fit_staged_speedup",
    # r20: multi-tenant control plane (tenancy config) — weighted-fair
    # light-tenant p99 bound and the autoscaler's peak/min breathing
    # ratio, both same-run A/Bs
    "fairness_p99_bound_factor",
    "elasticity_factor",
})

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _round_paths(root: str) -> list[tuple[int, str]]:
    out = []
    for p in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out)


def _records(path: str) -> list[dict]:
    """The parsed bench records inside one round file (dict or list of
    capture dicts; a malformed/empty file contributes nothing)."""
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return []
    captures = d if isinstance(d, list) else [d]
    out = []
    for c in captures:
        p = c.get("parsed") if isinstance(c, dict) else None
        if isinstance(p, dict) and p.get("metric"):
            out.append(p)
    return out


def run_trend(paths: list[str] | None = None, *, root: str = REPO,
              threshold: float = 0.2) -> dict:
    """Align rounds, diff numerics, flag ratio regressions. Returns::

        {"rounds": [n, ...],
         "metrics": {metric: {"rounds": [n, ...],
                              "keys": {key: {"values": {n: v},
                                             "delta_pct": f | None}}}},
         "regressions": [{"metric", "key", "round", "prev_round",
                          "prev", "value", "drop_pct"}]}
    """
    if paths is not None:
        rounds = []
        for i, p in enumerate(paths):
            m = _ROUND_RE.search(os.path.basename(p))
            rounds.append((int(m.group(1)) if m else i + 1, p))
        rounds.sort()
    else:
        rounds = _round_paths(root)
    metrics: dict[str, dict] = {}
    for n, path in rounds:
        for rec in _records(path):
            name = rec["metric"]
            m = metrics.setdefault(name, {"rounds": [], "keys": {}})
            if n not in m["rounds"]:
                m["rounds"].append(n)
            for k, v in rec.items():
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    continue
                m["keys"].setdefault(k, {"values": {}})["values"][n] = v
    regressions: list[dict] = []
    for name, m in metrics.items():
        for k, info in m["keys"].items():
            vals = sorted(info["values"].items())
            if len(vals) >= 2:
                (pn, pv), (cn, cv) = vals[-2], vals[-1]
                info["delta_pct"] = (round((cv - pv) / pv * 100.0, 2)
                                     if pv else None)
            else:
                info["delta_pct"] = None
            if k not in RATIO_KEYS:
                continue
            # walk CONSECUTIVE appearances: a regression that healed in
            # the latest round still happened, and the table should say
            # in which round it landed
            for (pn, pv), (cn, cv) in zip(vals, vals[1:]):
                if pv and (pv - cv) / pv > threshold:
                    regressions.append({
                        "metric": name, "key": k,
                        "round": cn, "prev_round": pn,
                        "prev": pv, "value": cv,
                        "drop_pct": round((pv - cv) / pv * 100.0, 1),
                    })
    return {"rounds": [n for n, _ in rounds], "metrics": metrics,
            "regressions": regressions}


def _print_table(trend: dict, out=sys.stderr) -> None:
    for name, m in sorted(trend["metrics"].items()):
        print(f"[trend] == {name} (rounds {m['rounds']}) ==", file=out)
        for k, info in sorted(m["keys"].items()):
            vals = sorted(info["values"].items())
            series = " -> ".join(f"r{n}:{v:g}" for n, v in vals)
            flag = " [ratio]" if k in RATIO_KEYS else ""
            delta = (f"  ({info['delta_pct']:+.1f}%)"
                     if info["delta_pct"] is not None else "")
            print(f"[trend]   {k:<42} {series}{delta}{flag}", file=out)
    for r in trend["regressions"]:
        print(f"[trend] REGRESSION {r['metric']}.{r['key']}: "
              f"r{r['prev_round']} {r['prev']:g} -> r{r['round']} "
              f"{r['value']:g} (-{r['drop_pct']}%)", file=out)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=REPO,
                    help="directory holding BENCH_r*.json (default: repo)")
    ap.add_argument("--threshold", type=float, default=0.2,
                    help="ratio-drop fraction that flags a regression")
    args = ap.parse_args()
    trend = run_trend(root=args.root, threshold=args.threshold)
    _print_table(trend)
    print(json.dumps({
        "metric": "bench_trend",
        "value": len(trend["metrics"]),
        "unit": "metrics",
        "vs_baseline": None,
        "rounds": trend["rounds"],
        "regressions": trend["regressions"],
    }, default=str))
    return 1 if trend["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
