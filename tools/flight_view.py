"""One-shot flight-bundle viewer — a post-mortem without a notebook.

Renders an anomaly flight bundle (obs/flight.py) as a readable report:
the anomaly line, the trace it killed, each thread's open spans and
Python stack tail, the control-plane state (breakers, queue depths,
brownout), non-default knobs, and the slowest traces in the ring at dump
time.

Usage:
    python tools/flight_view.py /tmp/otpu_flight/flight-<ns>-<reason>.json
    python tools/flight_view.py --latest [--dir /tmp/otpu_flight]

Importable: ``render(bundle) -> str`` (the tier-1 smoke calls it on a
freshly-dumped bundle).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _tree_lines(node: dict, depth: int = 0, out: list | None = None) -> list:
    if out is None:
        out = []
    args = node.get("args") or {}
    arg_s = (" " + ", ".join(f"{k}={v}" for k, v in args.items())
             if args else "")
    out.append(f"{'  ' * depth}{node['name']} "
               f"{node['dur_ms']:.3f}ms{arg_s}")
    for child in node.get("children", ()):
        _tree_lines(child, depth + 1, out)
    if node.get("truncated"):
        out.append(f"{'  ' * (depth + 1)}... {node['truncated']} more")
    return out


def render_fleet(bundle: dict, *, stack_tail: int = 6) -> str:
    """Human-readable report of one FLEET incident bundle
    (obs/fleetobs.py ``fleet-*.json``): the alert line, per-replica
    flight summaries, the digest, then the router's own bundle in
    full."""
    lines = [f"== fleet incident bundle "
             f"(schema {bundle.get('fleet_flight_schema')}) "
             f"pid {bundle.get('pid')} ==",
             f"reason:   {bundle.get('reason')}",
             f"live:     {bundle.get('live_replicas')}"]
    alert = (bundle.get("extra") or {}).get("alert")
    if alert:
        lines.append(f"alert:    slo={alert.get('slo')} "
                     f"rule={alert.get('rule')} "
                     f"burn={alert.get('burn_long'):.2f} "
                     f"budget={alert.get('budget_remaining'):.3f}")
    digest = bundle.get("digest") or {}
    for r in digest.get("replicas", ()):
        lines.append(
            f"  {r['replica']:<14} up={r['up']} stale={r['stale']} "
            f"inflight={r['inflight']:.0f} queue={r['queue_depth']:.0f} "
            f"shed={r['shed_total']:.0f} brownout="
            f"{r['brownout_level']:.0f}")
    for name, rb in sorted((bundle.get("replicas") or {}).items()):
        if "pull_error" in rb:
            lines.append(f"-- {name}: UNREACHABLE ({rb['pull_error']}) --")
            continue
        lines.append(f"-- {name}: reason={rb.get('reason')} "
                     f"trace={rb.get('trace_id')} "
                     f"open_spans={len(rb.get('open_spans') or [])} "
                     f"events={len(rb.get('events') or [])} --")
    router = bundle.get("router")
    if router:
        lines.append("== router-side bundle ==")
        lines.append(render(router, stack_tail=stack_tail))
    return "\n".join(lines)


def render(bundle: dict, *, stack_tail: int = 6) -> str:
    """Human-readable report of one flight bundle."""
    if "fleet_flight_schema" in bundle:
        return render_fleet(bundle, stack_tail=stack_tail)
    lines = []
    err = bundle.get("error") or {}
    lines.append(f"== flight bundle (schema {bundle.get('flight_schema')}) "
                 f"pid {bundle.get('pid')} ==")
    lines.append(f"reason:   {bundle.get('reason')}")
    if err:
        lines.append(f"error:    {err.get('type')}: "
                     f"{str(err.get('message'))[:200]}")
    lines.append(f"trace_id: {bundle.get('trace_id')}")
    lines.append(f"control:  brownout={bundle.get('brownout_level')} "
                 f"sheds={bundle.get('sheds')} "
                 f"mb_queue={bundle.get('mb_queue_depth')} "
                 f"admission={bundle.get('admission')}")
    breakers = bundle.get("breakers") or {}
    if breakers:
        lines.append("breakers: " + ", ".join(
            f"{k}={v}" for k, v in sorted(breakers.items())))
    open_spans = bundle.get("open_spans") or []
    if open_spans:
        lines.append("-- open spans (what each thread was inside) --")
        for s in open_spans:
            lines.append(f"  [{s['thread']}] {s['name']} "
                         f"open {s['age_ms']:.1f}ms "
                         f"trace={s.get('trace_id')}")
    dm = bundle.get("device_memory")   # additive: old bundles render fine
    if dm:
        # ONE table definition for both viewers (tools/goodput_view.py)
        from tools.goodput_view import ledger_lines

        table = ledger_lines(dm, max_entries=8)
        lines.append(f"-- {table[0]} --")
        lines.extend(table[1:])
    slow = bundle.get("slow_traces") or []
    if slow:
        lines.append("-- slowest traces --")
        for t in slow:
            lines.append(f"  {t['trace_id']}  {t['dur_ms']:.3f}ms  "
                         f"({t['n_spans']} spans)")
            lines.extend("    " + ln for ln in _tree_lines(t["tree"]))
    stacks = bundle.get("stacks") or {}
    if stacks:
        lines.append("-- thread stacks (tails) --")
        for name, frames in sorted(stacks.items()):
            lines.append(f"  {name}:")
            lines.extend(f"    {ln}" for ln in frames[-stack_tail:])
    knobs = bundle.get("knobs") or {}
    if knobs:
        from orange3_spark_tpu.utils.knobs import KNOBS

        non_default = {
            k: v for k, v in sorted(knobs.items())
            if k in KNOBS and _differs(KNOBS[k], v)
        }
        lines.append(f"-- knobs ({len(non_default)} non-default) --")
        for k, v in non_default.items():
            lines.append(f"  {k} = {v!r} (default {KNOBS[k].default!r})")
    n_events = len(bundle.get("events") or [])
    lines.append(f"-- {n_events} ring events in bundle "
                 f"(export with tools/obs_dump.py for Perfetto) --")
    return "\n".join(lines)


def _differs(knob, value) -> bool:
    d = knob.default
    if knob.type == "flag":
        return value is not (str(d) != "0")
    if knob.type == "marker":
        return value is not None
    return value != d


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("bundle", nargs="?", help="path to a flight-*.json")
    ap.add_argument("--latest", action="store_true",
                    help="render the newest bundle in --dir")
    ap.add_argument("--dir", default=None,
                    help="bundle directory (default: OTPU_FLIGHT_DIR)")
    args = ap.parse_args()
    path = args.bundle
    if path is None:
        if not args.latest:
            ap.error("give a bundle path or --latest")
        from orange3_spark_tpu.utils import knobs as _knobs

        directory = args.dir or _knobs.get_str("OTPU_FLIGHT_DIR")
        names = [n for n in os.listdir(directory)
                 if (n.startswith("flight-") or n.startswith("fleet-"))
                 and n.endswith(".json")] if os.path.isdir(directory) else []
        if not names:
            print(f"no flight bundles in {directory}", file=sys.stderr)
            return 1

        def _ns(name: str) -> int:
            # flight-<ns>-<reason>.json / fleet-<ns>-<reason>.json —
            # newest across BOTH families, by write timestamp not by the
            # prefix's alphabetical accident
            try:
                return int(name.split("-", 2)[1])
            except (IndexError, ValueError):
                return 0

        path = os.path.join(directory, max(names, key=_ns))
    with open(path) as f:
        bundle = json.load(f)
    print(render(bundle))
    return 0


if __name__ == "__main__":
    sys.exit(main())
