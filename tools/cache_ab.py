"""One-shot cache-codec A/B — f32 vs compressed chunk cache on a synthetic
Criteo-shaped stream: fit wall per arm, measured cache bytes / compression
ratio, and the max-|theta| divergence between the arms (the packed int
layer is LOSSLESS, so with n_dense=0 the divergence must be exactly 0.0;
with dense columns it is the bounded bf16 rounding).

Sized to run inside the tier-1 test budget (a few seconds on the CPU test
mesh) — tests/test_cache_codec.py runs it as a smoke. For the full ladder
(f32/bf16/packed, replay walls, encode seconds) use
``bench_suite.py --config 9``; for the Criteo-scale capacity record,
``bench.py`` (``compression_ratio`` / ``cache_rows_capacity`` fields).

Run: python tools/cache_ab.py [--rows 40960] [--dims 16384] [--n-dense 4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(
        globals().get("__file__", "tools/cache_ab.py"))))
)


def run(rows: int = 40960, dims: int = 1 << 14, n_dense: int = 4,
        n_cat: int = 8, epochs: int = 5, chunk_rows: int = 1 << 13,
        optim_update: str = "sparse_adagrad") -> dict:
    import jax
    import numpy as np

    from orange3_spark_tpu.core.session import TpuSession
    from orange3_spark_tpu.io.codec import force_cache_dtype
    from orange3_spark_tpu.io.streaming import array_chunk_source
    from orange3_spark_tpu.models.hashed_linear import (
        StreamingHashedLinearEstimator,
    )

    session = TpuSession.builder_get_or_create()
    rng = np.random.default_rng(23)
    dense = rng.lognormal(size=(rows, n_dense)).astype(np.float32)
    cats = rng.integers(0, 60_000, (rows, n_cat)).astype(np.float32)
    y = (cats[:, 0] % 5 == 0).astype(np.float32)
    Xall = np.concatenate([dense, cats], axis=1)
    src = array_chunk_source(Xall, y, chunk_rows=chunk_rows)

    def arm(cache: str) -> tuple:
        with force_cache_dtype(cache):
            est = StreamingHashedLinearEstimator(
                n_dims=dims, n_dense=n_dense, n_cat=n_cat, epochs=epochs,
                step_size=0.05, reg_param=1e-4, chunk_rows=chunk_rows,
                optim_update=optim_update,
            )
            est.fit_stream(src, session=session, cache_device=True)  # warm
            st: dict = {}
            t0 = time.perf_counter()
            model = est.fit_stream(src, session=session, cache_device=True,
                                   stage_times=st)
            jax.block_until_ready(model.theta["emb"])
            return model, round(time.perf_counter() - t0, 3), st

    m32, wall32, _ = arm("f32")
    mpk, wallpk, st = arm("packed")
    diff = float(np.abs(np.asarray(mpk.theta["emb"])
                        - np.asarray(m32.theta["emb"])).max())
    return {
        "metric": "cache_codec_ab",
        "rows": rows, "n_hashed_dims": dims, "epochs": epochs,
        "n_dense": n_dense, "n_cat": n_cat,
        "optim_update": st.get("optim_update"),
        "cache_dtype": st.get("cache_dtype"),
        "wall_s_f32": wall32, "wall_s_compressed": wallpk,
        "cache_bytes_compressed": st.get("cache_bytes"),
        "compression_ratio": (round(st["cache_raw_bytes"]
                                    / st["cache_bytes"], 3)
                              if st.get("cache_bytes") else None),
        "max_theta_diff": diff,
        # with no dense block every stored quantity is lossless-packed:
        # the arms must agree BITWISE
        "lossless_config": n_dense == 0,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=40960)
    ap.add_argument("--dims", type=int, default=1 << 14)
    ap.add_argument("--n-dense", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=5)
    args = ap.parse_args()
    print(json.dumps(run(rows=args.rows, dims=args.dims,
                         n_dense=args.n_dense, epochs=args.epochs)))


if __name__ == "__main__":
    main()
