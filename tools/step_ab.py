"""A/B the hashed-step embedding-update formulations on real hardware.

The Criteo step is scatter-OP-bound (BASELINE.md roofline). Three
numerically-identical lowerings exist behind ``HashedLinearParams.emb_update``
('fused' | 'per_column' | 'sorted'); this tool times each on the current
backend and prints one JSON line so the winner can be promoted to the bench
default. Run on the TPU host:

    python tools/step_ab.py [--rows 262144] [--dims 4194304] [--steps 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(
        globals().get("__file__", "tools/step_ab.py"))))
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1 << 18)
    ap.add_argument("--dims", type=int, default=1 << 22)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    # serialize against any other TPU harness (see utils/devlock.py)
    from orange3_spark_tpu.utils.devlock import tpu_device_lock

    with tpu_device_lock(name="step_ab"):
        _main_locked(args)


def _main_locked(args):

    import jax
    import jax.numpy as jnp
    import numpy as np

    from orange3_spark_tpu.models.hashed_linear import (
        _ADAM_UNIT,
        _hashed_step,
    )
    from orange3_spark_tpu.ops.hashing import column_salts

    n_dense, n_cat = 13, 26
    rng = np.random.default_rng(0)
    Xall = np.concatenate(
        [rng.integers(0, 2, (args.rows, 1)).astype(np.float32),
         rng.lognormal(0, 1, (args.rows, n_dense)).astype(np.float32),
         rng.integers(0, 200_000, (args.rows, n_cat)).astype(np.float32)],
        axis=1,
    )
    Xd = jax.device_put(Xall)
    salts = jnp.asarray(column_salts(n_cat, 0))
    zero = jnp.zeros((1,), jnp.float32)
    out = {"metric": "hashed_step_ms_by_emb_update", "unit": "ms/step",
           "rows": args.rows, "dims": args.dims,
           "backend": jax.default_backend()}
    variants = [(v, "float32") for v in ("fused", "per_column", "sorted")]
    # dtype axis: bfloat16 halves the gather/matmul bytes of the two
    # leading formulations — the next hardware window should decide
    # whether the table can live in bf16 (adam state stays f32 via optax)
    variants += [("fused", "bfloat16"), ("sorted", "bfloat16")]
    for variant, dt in variants:
        key = variant if dt == "float32" else f"{variant}_{dt}"
        theta = {"emb": jnp.zeros((args.dims, 1), jnp.float32),
                 "coef": jnp.zeros((n_dense, 1), jnp.float32),
                 "intercept": jnp.zeros((1,), jnp.float32)}
        opt = _ADAM_UNIT.init(theta)
        kw = dict(loss_kind="binary_logistic", n_dims=args.dims,
                  n_dense=n_dense, label_in_chunk=True, emb_update=variant,
                  compute_dtype=jnp.dtype(dt))
        theta, opt, loss = _hashed_step(
            theta, opt, Xd, jnp.int32(args.rows), zero, zero, salts,
            jnp.float32(0.0), jnp.float32(0.04), **kw)
        jax.block_until_ready(loss)     # compile
        t0 = time.perf_counter()
        for _ in range(args.steps):
            theta, opt, loss = _hashed_step(
                theta, opt, Xd, jnp.int32(args.rows), zero, zero, salts,
                jnp.float32(0.0), jnp.float32(0.04), **kw)
        jax.block_until_ready(loss)
        ms = (time.perf_counter() - t0) / args.steps * 1e3
        out[key] = round(ms, 2)
        out[f"{key}_rows_per_sec"] = round(args.rows / ms * 1e3, 1)
    best = min(("fused", "per_column", "sorted"), key=lambda v: out[v])
    out["best"] = best
    # "value" (truthy) is the capture watcher's banking contract — the
    # winning variant's step time carries it
    out["value"] = out[best]
    # print + flush the A/B line BEFORE the scan cell below: that cell
    # dispatches a multi-chunk multi-epoch scan, the one program shape
    # with a known device-fault history — it must not be able to cost
    # the five measurements already in hand
    print(json.dumps(out), flush=True)

    # in-scan step time: the same step executed INSIDE the replay scan
    # program (_hashed_replay_epochs), one dispatch for stack_chunks x
    # scan_epochs steps. The 2026-07-31 window measured ~0.5 s/step
    # in-scan on a 1-chunk stack vs 0.27 ms standalone at 02:04 — this
    # cell decides whether that 2000x gap is the scan lowering (would
    # reproduce here) or window-to-window device variance (would not).
    # Emitted as its OWN JSON line, in a fault guard, for the same reason.
    try:
        from orange3_spark_tpu.models.hashed_linear import (
            _hashed_replay_epochs,
        )

        stack_chunks, scan_epochs = 4, 5
        theta = {"emb": jnp.zeros((args.dims, 1), jnp.float32),
                 "coef": jnp.zeros((n_dense, 1), jnp.float32),
                 "intercept": jnp.zeros((1,), jnp.float32)}
        opt = _ADAM_UNIT.init(theta)
        kw = dict(loss_kind="binary_logistic", n_dims=args.dims,
                  n_dense=n_dense, label_in_chunk=True, emb_update="fused",
                  compute_dtype=jnp.dtype("float32"))
        stacks = (jnp.stack([Xd] * stack_chunks),
                  jnp.full((stack_chunks,), args.rows, jnp.int32),
                  jnp.zeros((stack_chunks, 1), jnp.float32),
                  jnp.zeros((stack_chunks, 1), jnp.float32))
        theta, opt, losses = _hashed_replay_epochs(
            theta, opt, stacks, salts, jnp.float32(0.0), jnp.float32(0.04),
            n_epochs=scan_epochs, **kw)
        jax.block_until_ready(losses)       # compile + first run
        t0 = time.perf_counter()            # stacks are not donated; reuse
        theta, opt, losses = _hashed_replay_epochs(
            theta, opt, stacks, salts, jnp.float32(0.0), jnp.float32(0.04),
            n_epochs=scan_epochs, **kw)
        jax.block_until_ready(losses)
        n_in_scan = stack_chunks * scan_epochs
        ms = (time.perf_counter() - t0) / n_in_scan * 1e3
        print(json.dumps({
            "metric": "hashed_step_in_scan_ms", "value": round(ms, 2),
            "unit": "ms/step", "rows": args.rows, "dims": args.dims,
            "backend": jax.default_backend(),
            "steps_per_dispatch": n_in_scan,
            "standalone_fused_ms": out["fused"],
        }), flush=True)
    except Exception as e:  # noqa: BLE001 — the A/B line is already out
        print(f"in-scan cell died (A/B line unaffected): "
              f"{type(e).__name__}: {e}"[:300], file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
