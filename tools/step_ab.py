"""A/B the hashed-step embedding-update formulations on real hardware.

The Criteo step is scatter-OP-bound (BASELINE.md roofline). Three
numerically-identical lowerings exist behind ``HashedLinearParams.emb_update``
('fused' | 'per_column' | 'sorted'); this tool times each on the current
backend and prints one JSON line so the winner can be promoted to the bench
default. Run on the TPU host:

    python tools/step_ab.py [--rows 262144] [--dims 4194304] [--steps 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(
        globals().get("__file__", "tools/step_ab.py"))))
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1 << 18)
    ap.add_argument("--dims", type=int, default=1 << 22)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from orange3_spark_tpu.models.hashed_linear import (
        _ADAM_UNIT,
        _hashed_step,
    )
    from orange3_spark_tpu.ops.hashing import column_salts

    n_dense, n_cat = 13, 26
    rng = np.random.default_rng(0)
    Xall = np.concatenate(
        [rng.integers(0, 2, (args.rows, 1)).astype(np.float32),
         rng.lognormal(0, 1, (args.rows, n_dense)).astype(np.float32),
         rng.integers(0, 200_000, (args.rows, n_cat)).astype(np.float32)],
        axis=1,
    )
    Xd = jax.device_put(Xall)
    salts = jnp.asarray(column_salts(n_cat, 0))
    zero = jnp.zeros((1,), jnp.float32)
    out = {"metric": "hashed_step_ms_by_emb_update", "unit": "ms/step",
           "rows": args.rows, "dims": args.dims,
           "backend": jax.default_backend()}
    variants = [(v, "float32") for v in ("fused", "per_column", "sorted")]
    # dtype axis: bfloat16 halves the gather/matmul bytes of the two
    # leading formulations — the next hardware window should decide
    # whether the table can live in bf16 (adam state stays f32 via optax)
    variants += [("fused", "bfloat16"), ("sorted", "bfloat16")]
    for variant, dt in variants:
        key = variant if dt == "float32" else f"{variant}_{dt}"
        theta = {"emb": jnp.zeros((args.dims, 1), jnp.float32),
                 "coef": jnp.zeros((n_dense, 1), jnp.float32),
                 "intercept": jnp.zeros((1,), jnp.float32)}
        opt = _ADAM_UNIT.init(theta)
        kw = dict(loss_kind="binary_logistic", n_dims=args.dims,
                  n_dense=n_dense, label_in_chunk=True, emb_update=variant,
                  compute_dtype=jnp.dtype(dt))
        theta, opt, loss = _hashed_step(
            theta, opt, Xd, jnp.int32(args.rows), zero, zero, salts,
            jnp.float32(0.0), jnp.float32(0.04), **kw)
        jax.block_until_ready(loss)     # compile
        t0 = time.perf_counter()
        for _ in range(args.steps):
            theta, opt, loss = _hashed_step(
                theta, opt, Xd, jnp.int32(args.rows), zero, zero, salts,
                jnp.float32(0.0), jnp.float32(0.04), **kw)
        jax.block_until_ready(loss)
        ms = (time.perf_counter() - t0) / args.steps * 1e3
        out[key] = round(ms, 2)
        out[f"{key}_rows_per_sec"] = round(args.rows / ms * 1e3, 1)
    best = min(("fused", "per_column", "sorted"), key=lambda v: out[v])
    out["best"] = best
    print(json.dumps(out))


if __name__ == "__main__":
    main()
