"""One-shot serving-fleet drill — watch the multi-replica ladder work.

Spins a REAL local fleet (replica subprocesses, fleet/supervisor.py)
behind a health-aware hedged router and walks the three serving-fleet
failure drills (docs/serving.md §fleet), printing each rung:

  burst+kill  a closed-loop burst while one replica is SIGKILLed
              mid-flight: every request completes via failover (or
              fails typed) — zero lost, zero hung — and the supervisor
              restarts the replica, which re-admits itself via /readyz
  rollout     publish a new model version and roll it one replica at a
              time under continuous traffic: zero failed requests, then
              a poisoned version auto-rolls back with CURRENT untouched
  drain       graceful stop: POST /drain finishes in-flight work and
              the replica exits 0

Importable: ``run_drill(session=...)`` returns the row dicts (the
not-slow smoke test in tests/test_fleet.py calls it directly).

Usage:
    python tools/fleet_drill.py [--replicas 2] [--requests 16]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_drill(session=None, replicas: int = 2, requests: int = 16) -> list:
    import concurrent.futures

    import numpy as np

    from orange3_spark_tpu.core.session import TpuSession
    from orange3_spark_tpu.fleet.rollout import (
        Rollout, publish_version, read_current,
    )
    from orange3_spark_tpu.fleet.router import FleetRouter
    from orange3_spark_tpu.fleet.rpc import (
        NoReplicaAvailableError, ReplicaDrainingError,
        ReplicaUnavailableError,
    )
    from orange3_spark_tpu.fleet.supervisor import ReplicaManager
    from orange3_spark_tpu.io.streaming import array_chunk_source
    from orange3_spark_tpu.models.hashed_linear import (
        StreamingHashedLinearEstimator,
    )
    from orange3_spark_tpu.obs.registry import REGISTRY

    session = session or TpuSession.builder_get_or_create()
    rng = np.random.default_rng(3)
    X = np.concatenate([
        rng.standard_normal((4096, 4)).astype(np.float32),
        rng.integers(0, 500, (4096, 4)).astype(np.float32),
    ], axis=1)
    y = (rng.random(4096) < 0.3).astype(np.float32)

    def fit(epochs):
        return StreamingHashedLinearEstimator(
            n_dims=1 << 10, n_dense=4, n_cat=4, epochs=epochs,
            step_size=0.05, chunk_rows=1024,
        ).fit_stream(array_chunk_source(X, y, chunk_rows=1024),
                     session=session)

    def say(msg):
        print(f"[drill] {msg}", file=sys.stderr)

    model = fit(1)
    root = tempfile.mkdtemp(prefix="otpu-fleet-drill-")
    publish_version(model, root, n_cols=8)
    rows_out: list = []
    say(f"starting {replicas} replicas ...")
    mgr = ReplicaManager(
        root, n_replicas=replicas, ladder_max=256,
        env={"JAX_PLATFORMS": "cpu", "OTPU_ADMISSION_MAX_INFLIGHT": "1",
             "OTPU_FAULT_SPEC": "overload:delay_ms=25"})
    mgr.start()
    try:
        if not mgr.wait_ready(timeout_s=120):
            raise RuntimeError(f"fleet never ready; see {mgr.log_dir}")
        router = FleetRouter(mgr.endpoints(), hedging=False)
        router.refresh()
        # reference from the HEALTHY FLEET itself: replicas pin CPU while
        # this parent may sit on a TPU backend, and a cross-backend
        # bitwise compare would flip threshold-adjacent labels
        expect = np.asarray(router.predict(X[:64]))

        # ---- rung 1: SIGKILL mid-burst, failover + supervised restart ----
        restarts0 = int(REGISTRY.get(
            "otpu_fleet_replica_restarts_total").total())

        def one(i):
            time.sleep(i * 0.01)
            try:
                out = router.predict(X[:64])
                return "ok" if np.array_equal(out, expect) else "wrong"
            except (ReplicaUnavailableError, ReplicaDrainingError,
                    NoReplicaAvailableError):
                return "typed"

        with concurrent.futures.ThreadPoolExecutor(6) as ex:
            futs = [ex.submit(one, i) for i in range(requests)]
            time.sleep(0.08)
            mgr.kill(0)                      # no warning, whole group
            done, pending = concurrent.futures.wait(futs, timeout=60)
            outcomes = [f.result() for f in done]
        deadline = time.monotonic() + 60
        readmitted = False
        while time.monotonic() < deadline:
            router.refresh()
            ep = router.endpoint(0)
            if ep.ready and ep.breaker.state() != "open":
                readmitted = True
                break
            time.sleep(0.2)
        restarted = int(REGISTRY.get(
            "otpu_fleet_replica_restarts_total").total()) > restarts0
        say(f"burst+kill: {outcomes.count('ok')} ok / "
            f"{outcomes.count('typed')} typed / {len(pending)} hung; "
            f"restarted={restarted} readmitted={readmitted}")
        rows_out.append({
            "rung": "burst_kill", "completed": outcomes.count("ok"),
            "typed": outcomes.count("typed"), "hung": len(pending),
            "restarted": restarted, "readmitted": readmitted,
            "ok": (len(pending) == 0 and outcomes.count("wrong") == 0
                   and outcomes.count("ok") + outcomes.count("typed")
                   == requests and restarted and readmitted)})

        # ---- rung 2: zero-downtime rollout + poisoned-version rollback ----
        model2 = fit(2)
        v2 = publish_version(model2, root, n_cols=8)
        stop = threading.Event()
        fails: list = []

        def traffic():
            while not stop.is_set():
                try:
                    router.predict(X[:64])
                except Exception as e:  # noqa: BLE001 - the claim is zero
                    fails.append(repr(e))
                time.sleep(0.02)

        th = threading.Thread(target=traffic)
        th.start()
        try:
            res = Rollout(router, root, canary_input=X[:16]).roll(v2)
        finally:
            stop.set()
            th.join(timeout=10)
        bad = os.path.join(root, ".staging-bad")
        os.makedirs(bad, exist_ok=True)
        with open(os.path.join(bad, "model.pkl"), "wb") as f:
            f.write(b"poisoned")
        os.replace(bad, os.path.join(root, "v0099"))
        rb = Rollout(router, root, canary_input=X[:16]).roll("v0099")
        say(f"rollout: {res['outcome']} with {len(fails)} failed "
            f"requests; poisoned version {rb['outcome']}, CURRENT="
            f"{read_current(root)}")
        rows_out.append({
            "rung": "rollout", "outcome": res["outcome"],
            "failed_requests": len(fails),
            "rollback_outcome": rb["outcome"],
            "ok": (res["outcome"] == "completed" and not fails
                   and rb["outcome"] == "rolled_back"
                   and read_current(root) == v2)})
        router.close()
    finally:
        # ---- rung 3: graceful drain — every replica exits 0 ----
        rcs = mgr.stop_all()
    clean = all(rc == 0 for rc in rcs.values() if rc is not None)
    say(f"drain: exit codes {rcs} (clean={clean})")
    rows_out.append({"rung": "drain", "exit_codes": rcs, "ok": clean})
    return rows_out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=16)
    args = ap.parse_args()
    sys.path.insert(0, REPO)
    results = run_drill(replicas=args.replicas, requests=args.requests)
    bad = [r for r in results if not r["ok"]]
    print(json.dumps({
        "metric": "fleet_drill",
        "value": len(results),
        "unit": "rungs_run",
        "vs_baseline": None,
        "rungs_ok": len(results) - len(bad),
        "rungs": results,
    }, default=str))
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()
