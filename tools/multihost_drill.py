"""One-shot multihost lost-host drill — the failure ladder's top rung.

Rungs (docs/multihost.md):

  1. GANG UP: ``MultihostLauncher`` spawns N training processes
     (``parallel/mh_worker.py``; ``jax.distributed`` rendezvous when
     N > 1) over one shared CSV, each parsing only its row block.
  2. REFERENCE: the uninterrupted gang fits to completion -> theta_ref,
     plus per-host goodput/ledger attribution (the PR-12 digest).
  3. KILL: a fresh gang runs with ``--die-after-saves 1`` — the last rank
     SIGKILLs itself the instant its first epoch-boundary checkpoint
     lands (the worst moment: some ranks have saved, the victim just
     did).
  4. RECOVER: the launcher detects the lost host TYPED (no hang), aligns
     every rank's checkpoint to the common step, and gang-restarts with
     seeded backoff; each worker fast-forwards its shard through the
     checkpointed prefix.
  5. VERIFY: the resumed fit's theta must equal theta_ref bitwise and
     resume exactly at the snapshot (0 lost work).

Importable: ``run_drill(procs=1, rows=2048, epochs=3, chunk_rows=256,
out_root=None) -> dict`` (the tier-1 smoke and ``bench.py --config
multihost`` both call it). N > 1 needs cross-process CPU collectives —
gate on ``parallel.launcher.cross_process_collectives_supported``.

Usage:
    python tools/multihost_drill.py [--procs 1] [--rows 2048]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def say(msg: str) -> None:
    print(f"[mh-drill] {msg}", file=sys.stderr, flush=True)


def _write_csv(path: str, rows: int, d: int = 8, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, d)).astype(np.float32)
    w_true = rng.normal(size=(d,)).astype(np.float32)
    y = (X @ w_true + 0.1 * rng.normal(size=rows).astype(np.float32)
         > 0).astype(np.float32)
    header = ",".join([f"f{j}" for j in range(d)] + ["y"])
    np.savetxt(path, np.column_stack([X, y]), delimiter=",", fmt="%.9g",
               header=header, comments="")


def _worker_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p and ".axon_site" not in p and p != REPO])
    return env


def _gang(csv: str, n_total: int, d: int, out_dir: str, ckpt_dir: str, *,
          procs: int, epochs: int, chunk_rows: int, die: bool):
    from orange3_spark_tpu.parallel.launcher import MultihostLauncher

    os.makedirs(out_dir, exist_ok=True)
    os.makedirs(ckpt_dir, exist_ok=True)

    def argv(rank: int, n: int, coord: str) -> list:
        a = [sys.executable, "-m", "orange3_spark_tpu.parallel.mh_worker",
             "--rank", str(rank), "--nprocs", str(n), "--coord", coord,
             "--csv", csv, "--class-col", "y",
             "--n-total", str(n_total), "--n-features", str(d),
             "--chunk-rows", str(chunk_rows), "--epochs", str(epochs),
             "--step-size", "0.1", "--out-dir", out_dir,
             "--ckpt-dir", ckpt_dir]
        if die and rank == n - 1:
            a += ["--die-after-saves", "1"]
        return a

    lau = MultihostLauncher(argv, procs, env=_worker_env(),
                            log_dir=os.path.join(out_dir, "logs"),
                            align_ckpt_dir=ckpt_dir)
    res = lau.run()
    theta = dict(np.load(os.path.join(out_dir, "theta.npz")))
    hosts = {}
    for p in sorted(glob.glob(os.path.join(out_dir, "host_*.json"))):
        with open(p) as f:
            hosts[os.path.splitext(os.path.basename(p))[0]] = json.load(f)
    return res, theta, hosts


def run_drill(procs: int = 1, rows: int = 2048, epochs: int = 3,
              chunk_rows: int = 256, out_root: str | None = None) -> dict:
    """Run all five rungs; returns the drill record (see bench keys)."""
    root = out_root or tempfile.mkdtemp(prefix="otpu-mh-drill-")
    made_root = out_root is None
    d = 8
    try:
        csv = os.path.join(root, "drill.csv")
        _write_csv(csv, rows, d)
        say(f"gang A (uninterrupted, {procs} proc): fit {rows} rows "
            f"x {epochs} epochs")
        res_a, theta_a, hosts = _gang(
            csv, rows, d, os.path.join(root, "a"),
            os.path.join(root, "a_ck"), procs=procs, epochs=epochs,
            chunk_rows=chunk_rows, die=False)
        say(f"gang B (+SIGKILL rank {procs - 1} after its first "
            "epoch snapshot)")
        res_b, theta_b, hosts_b = _gang(
            csv, rows, d, os.path.join(root, "b"),
            os.path.join(root, "b_ck"), procs=procs, epochs=epochs,
            chunk_rows=chunk_rows, die=True)
        parity = (np.array_equal(theta_a["coef"], theta_b["coef"])
                  and np.array_equal(theta_a["intercept"],
                                     theta_b["intercept"]))
        local_rows = -(-rows // procs)                # lockstep per-host rows
        spe = -(-local_rows // chunk_rows)            # steps per epoch
        resumed = max(h.get("resumed_from_step", 0)
                      for h in hosts_b.values())
        # 0 lost work: the resumed fit starts exactly at the snapshot the
        # kill followed (one trained epoch = spe steps)
        lost_steps = spe - resumed
        say(f"parity={parity} resumed_from={resumed} "
            f"lost_steps={lost_steps} restarts={res_b.gang_restarts}")
        return {
            "procs": procs,
            "rows": rows,
            "epochs": epochs,
            "hosts_lost": res_b.hosts_lost,
            "gang_restarts": res_b.gang_restarts,
            "gang_starts": res_b.gang_starts,
            "resume_parity_bitwise": bool(parity),
            "resumed_from_step": int(resumed),
            "lost_work_steps": int(lost_steps),
            "ref_steps": int(theta_a["n_steps"]),
            "hosts": hosts,
        }
    finally:
        if made_root:
            shutil.rmtree(root, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--procs", type=int, default=1)
    ap.add_argument("--rows", type=int, default=2048)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--chunk-rows", type=int, default=256)
    args = ap.parse_args()
    out = run_drill(procs=args.procs, rows=args.rows, epochs=args.epochs,
                    chunk_rows=args.chunk_rows)
    ok = (out["resume_parity_bitwise"] and out["lost_work_steps"] == 0
          and out["hosts_lost"] >= 1)
    print(json.dumps({"metric": "multihost_drill",
                      "value": 1 if ok else 0, "unit": "ok",
                      "vs_baseline": None, **{k: v for k, v in out.items()
                                              if k != "hosts"}}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
