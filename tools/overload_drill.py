"""One-shot overload & brownout drill — watch the degrade ladder fire.

Runs the full graceful-degradation surface (resilience/overload.py)
against injected faults and prints each rung as it fires:

  admission   an open-loop burst of predicts against an injected-slow
              serving path: early requests complete, the rest shed with
              typed OverloadShedError (queue depth + wait estimate in
              the message) — never hung
  breaker     a flaky-AOT backend trips the serving circuit breaker
              (raw fallback while open), then a half-open probe
              re-admits it once the injected failures stop
  brownout    an injected memory-pressure fraction walks a cache_device
              fit down the ladder: shrink admission -> force spill ->
              degrade the HBM replay cache — the fit completes instead
              of dying

Importable: ``run_drill(session=...)`` returns the row dicts (the
not-slow smoke test in tests/test_overload.py calls it directly).

Usage:
    python tools/overload_drill.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_drill(session=None, requests: int = 24,
              service_ms: float = 20.0) -> list:
    import concurrent.futures

    import numpy as np

    from orange3_spark_tpu.core.session import TpuSession
    from orange3_spark_tpu.io.streaming import (
        StreamingLinearEstimator, array_chunk_source,
    )
    from orange3_spark_tpu.models.hashed_linear import (
        StreamingHashedLinearEstimator,
    )
    from orange3_spark_tpu.resilience import OverloadShedError, inject_faults
    from orange3_spark_tpu.resilience.overload import current_brownout_level
    from orange3_spark_tpu.serve import BucketLadder, ServingContext

    session = session or TpuSession.builder_get_or_create()
    rng = np.random.default_rng(3)
    n_dense, n_cat = 4, 4
    X = np.concatenate([
        rng.standard_normal((4096, n_dense)).astype(np.float32),
        rng.integers(0, 500, (4096, n_cat)).astype(np.float32),
    ], axis=1)
    y = (rng.random(4096) < 0.3).astype(np.float32)
    model = StreamingHashedLinearEstimator(
        n_dims=1 << 12, n_dense=n_dense, n_cat=n_cat, epochs=1,
        step_size=0.05, chunk_rows=1024,
    ).fit_stream(array_chunk_source(X, y, chunk_rows=1024),
                 session=session)
    rows_out: list = []

    def say(msg):
        print(f"[drill] {msg}", file=sys.stderr)

    # ---- rung 1: admission control sheds an injected overload burst ----
    saved = {k: os.environ.get(k) for k in (
        "OTPU_ADMISSION_DEADLINE_S", "OTPU_ADMISSION_SERVICE_MS")}
    os.environ["OTPU_ADMISSION_DEADLINE_S"] = "0.08"
    os.environ["OTPU_ADMISSION_SERVICE_MS"] = str(service_ms)
    ladder = BucketLadder(min_bucket=64, max_bucket=1 << 11)
    ok = sheds = 0
    try:
        with ServingContext(ladder, micro_batch=True, max_batch=128,
                            max_wait_ms=1.0) as ctx:
            ctx.warmup(model, n_cols=n_dense + n_cat, kinds=("array",),
                       session=session)

            def one(i):
                time.sleep(i * 0.002)
                try:
                    model.predict(X[:96])
                    return "ok"
                except OverloadShedError as e:
                    if i == requests - 1:
                        say(f"shed example: {e}")
                    return "shed"

            with inject_faults(f"overload:delay_ms={service_ms}"):
                with concurrent.futures.ThreadPoolExecutor(requests) as ex:
                    outcomes = list(ex.map(one, range(requests)))
            ok = outcomes.count("ok")
            sheds = outcomes.count("shed")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    say(f"admission: {ok} completed, {sheds} shed typed (of {requests})")
    rows_out.append({"rung": "admission", "completed": ok, "sheds": sheds,
                     "ok": ok >= 1 and sheds >= 1
                     and ok + sheds == requests})

    # ---- rung 2: circuit breaker opens, half-open probe re-admits ----
    clk = [0.0]
    with ServingContext(ladder, breaker_clock=lambda: clk[0]) as ctx:
        with inject_faults("aot_build:fails=4,key=array"):
            model.predict(X[:64])            # retries exhaust -> open
        opened = ctx.breaker_states().get("HashedLinearModel:array")
        clk[0] += 30.0                       # past the seeded cooldown
        model.predict(X[:64])                # probe build succeeds
        closed = ctx.breaker_states().get("HashedLinearModel:array")
    say(f"breaker: {opened} -> {closed} (half-open probe re-admitted)")
    rows_out.append({"rung": "breaker", "opened": opened, "closed": closed,
                     "ok": opened == "open" and closed == "closed"})

    # ---- rung 3: memory-pressure brownout degrades the chunk cache ----
    Xs = rng.standard_normal((8192, 8)).astype(np.float32)
    ys = (Xs @ rng.standard_normal(8).astype(np.float32) > 0
          ).astype(np.float32)
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")      # the overflow warning IS the
        #                                      scenario under drill
        with inject_faults("mem_pressure:frac=0.97,after=2"):
            m = StreamingLinearEstimator(
                loss="logistic", epochs=2, step_size=0.05, chunk_rows=1024,
            ).fit_stream(array_chunk_source(Xs, ys, chunk_rows=1024),
                         n_features=8, session=session, cache_device=True)
    level = current_brownout_level()
    say(f"brownout: level {level} reached; fit completed "
        f"(n_steps={m.n_steps_}) instead of dying")
    rows_out.append({"rung": "brownout", "level_reached": level,
                     "fit_steps": m.n_steps_,
                     "ok": level >= 2 and (m.n_steps_ or 0) > 0})
    return rows_out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=24)
    args = ap.parse_args()
    sys.path.insert(0, REPO)
    results = run_drill(requests=args.requests)
    bad = [r for r in results if not r["ok"]]
    print(json.dumps({
        "metric": "overload_drill",
        "value": len(results),
        "unit": "rungs_run",
        "vs_baseline": None,
        "rungs_ok": len(results) - len(bad),
        "rungs": results,
    }))
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()
