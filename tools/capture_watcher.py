"""Persistent TPU-window capture watcher.

The axon tunnel dies and resurrects in short windows (observed rounds 2-4;
this boot: answered 00:59-01:04, wedged the first full bench mid-fit). This
watcher probes the backend in a subprocess every few minutes and, the moment
a probe succeeds, runs the capture ladder below — smallest first, so even a
two-minute window banks a real hardware number before the full-scale runs
are attempted. Each step runs with the harness's own stall watchdog armed
(OTPU_STALL_S) plus a hard wall timeout, so a mid-run tunnel death costs one
bounded attempt, not the watcher.

    nohup python tools/capture_watcher.py > /tmp/capture_watcher.log 2>&1 &

Results append to BENCH_HW_r4.jsonl (one labeled JSON line per success);
per-step logs land in /tmp/capture_<name>.log; progress/state in
/tmp/otpu_capture_state.json (attempts survive watcher restarts).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from orange3_spark_tpu.utils.devlock import try_tpu_device_lock  # noqa: E402

STATE = "/tmp/otpu_capture_state.json"
OUT = os.path.join(REPO, "BENCH_HW_r4.jsonl")
PROBE_EVERY_S = 150
MAX_ATTEMPTS = 3

#: (name, argv, wall timeout s) — smallest first; the ladder resumes at the
#: first uncompleted step each window
STEPS = [
    ("bench_2m", [sys.executable, "bench.py", "--rows", "2000000"], 1200),
    # the fused-replay fault experiment matrix (tools/replay_fault_diag.py)
    # — 5 bounded subprocess cells (420 s each, worst case 2100 s); its
    # verdict decides whether round 5 can re-enable fused replay on
    # hardware, which improves EVERY later capture (one scan dispatch per
    # 99 epochs instead of 99) — so it outranks the long benches. Wall
    # must exceed cells x --wall-s.
    ("replay_diag", [sys.executable, "tools/replay_fault_diag.py"], 2400),
    # 3300 s: on a 2 MB/s-h2d window the 8M run is ~600 s of DMA + up to
    # ~1500 s of per-epoch replay dispatches before eval — 2700 was
    # borderline (the 08:12 attempt burned 1808 s on two rungs alone)
    ("bench_8m", [sys.executable, "bench.py"], 3300),
    # 1500 s: six tunnel compiles (five variants + the in-scan cell's
    # replay program) plus 140 dispatched steps at up to ~1 s each on a
    # degraded window
    ("step_ab", [sys.executable, "tools/step_ab.py"], 1500),
    # quarter scale on purpose: windows are scarce and degraded (2 MB/s
    # h2d, ~1 s dispatches on 2026-07-31); a banked TPU line with its row
    # counts in the JSON beats three full-scale wall timeouts. Full-scale
    # TPU runs remain a manual follow-up for a long healthy window.
    ("suite_c3", [sys.executable, "bench_suite.py", "--config", "3",
                  "--rows-scale", "0.25"], 3000),
    ("suite_c4", [sys.executable, "bench_suite.py", "--config", "4",
                  "--rows-scale", "0.25"], 2400),
    ("suite_c5", [sys.executable, "bench_suite.py", "--config", "5",
                  "--rows-scale", "0.25"], 2400),
]


def log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def load_state() -> dict:
    try:
        with open(STATE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def save_state(st: dict) -> None:
    tmp = STATE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(st, f, indent=1)
    os.replace(tmp, STATE)


def probe() -> str:
    """'live' | 'down' | 'wedged' | 'busy'.

    'live' iff the TPU answers AND executes a matmul (this boot the tunnel
    answered jax.devices() then wedged real work a minute later); 'wedged'
    when the probe subprocess TIMED OUT (the mode where `import jax` hangs
    at interpreter start) rather than failing fast — the caller backs way
    off then, because a wedged probe burns its full 90 s holding the
    device lock and a normal cadence would starve any other harness
    (observed flaking the bench contract test).

    Holds the harness device lock for the probe's duration and reports
    'busy' WITHOUT probing when another harness (e.g. the driver's
    round-end bench) owns the device — a probe poking a busy tunnel is
    exactly the two-process collision the lock exists to prevent. The
    probe child runs in its own process group and a timeout kills the
    GROUP: the wedge spawns tunnel-helper descendants that would
    otherwise outlive the direct child and keep poking the tunnel
    lock-less after the lock is released (same reasoning as run_step)."""
    with try_tpu_device_lock(name="watcher-probe") as lk:
        if not lk.held:
            log("device lock held by another harness; deferring probe")
            return "busy"
        code = ("import jax, jax.numpy as jnp; d = jax.devices(); "
                "x = jnp.ones((256, 256)); jax.block_until_ready(x @ x); "
                "print('OTPU_LIVE', d[0].platform)")
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True,
                                cwd=REPO, start_new_session=True)
        try:
            out, _ = proc.communicate(timeout=90)
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            try:
                proc.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                pass
            return "wedged"
        return ("live" if any(ln.startswith("OTPU_LIVE tpu")
                              for ln in (out or "").splitlines())
                else "down")


def bank(name: str, lines: list, attempt: int, partial: bool) -> int:
    """Append measurement lines to OUT with capture provenance
    (capture_step / capture_attempt / capture_partial), skipping lines
    whose measurement content is already banked. A retried step that
    re-measures produces near-duplicates with different timings — the
    provenance fields keep them distinguishable (prefer the line without
    capture_partial; among clean lines, the highest attempt)."""
    def canon(d: dict) -> str:
        return json.dumps({k: v for k, v in d.items()
                           if not k.startswith("capture_")}, sort_keys=True)

    seen = set()
    try:
        with open(OUT) as f:
            for ln in f.read().splitlines():
                if ln.strip():
                    try:
                        seen.add(canon(json.loads(ln)))
                    except ValueError:
                        pass
    except OSError:
        pass
    n = 0
    with open(OUT, "a") as f:
        for ln in lines:
            d = json.loads(ln)
            c = canon(d)
            if c in seen:
                continue
            seen.add(c)     # also dedupe within this batch
            d["capture_step"] = name
            d["capture_attempt"] = attempt
            if partial:
                d["capture_partial"] = True
            f.write(json.dumps(d) + "\n")
            n += 1
    return n


def run_step(name: str, argv: list, wall_s: int, attempt: int = 0) -> bool:
    env = dict(os.environ)
    # the watcher only launches after a live probe — don't re-probe for
    # 30 min inside the harness; fail fast and return to the probe loop
    # OTPU_STALL_S stays at the 900 s default: the heartbeat only ticks on
    # dispatch events, so the FIRST tunnel compile of a big suite program
    # (trees/ALS single-dispatch fits, worst observed ~3 min, headroom for
    # worse) must not read as a stall; the wall timeout bounds the step.
    env.pop("OTPU_STALL_S", None)   # pin the documented 900 s default
    env.update({"OTPU_TUNNEL_WAIT_S": "120", "OTPU_TUNNEL_RETRY_S": "60"})
    # the step child acquires the device lock itself; bound its wait well
    # below the wall so lock contention (another harness grabbed the lock
    # in the probe->step gap) fails FAST and visibly instead of idling
    # the whole wall away and reading as a wedge
    env.setdefault("OTPU_LOCK_WAIT_S", str(max(60, int(wall_s / 4))))
    logp = f"/tmp/capture_{name}.log"
    log(f"running {name}: {' '.join(argv)} (wall {wall_s}s, log {logp})")
    t0 = time.time()
    rc: object
    with open(logp, "w") as lf:
        # new session => own process group, so a wall timeout kills the
        # WHOLE tree: bench.py's retry-ladder rungs are grandchildren that
        # would otherwise survive the direct child's death, keep driving
        # the TPU with the lock already released, and recreate the
        # two-process collision the lock exists to prevent
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE, stderr=lf,
                                text=True, cwd=REPO, env=env,
                                start_new_session=True)
        try:
            out, _ = proc.communicate(timeout=wall_s)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            # keep whatever the step printed before the wall: multi-line
            # tools (step_ab) flush each measurement as its own complete
            # JSON line precisely so an end-of-run wedge cannot cost the
            # early lines
            try:
                out, _ = proc.communicate(timeout=30)
            except subprocess.TimeoutExpired as e2:
                # an escaped descendant can hold the pipe open past the
                # group kill; the exception still carries what was read —
                # never discard lines already flushed
                ob = e2.stdout or ""
                out = ob.decode("utf-8", "replace") \
                    if isinstance(ob, bytes) else ob
            rc = "wall-timeout"
        out = out or ""
    dt = time.time() - t0
    lines = [ln for ln in out.splitlines()
             if ln.startswith("{") and '"metric"' in ln]
    ok_lines = []
    for ln in lines:
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if d.get("rc") or not d.get("value"):
            log(f"{name}: harness error line: {ln[:200]}")
            continue
        # only bank HARDWARE lines; a cpu-fallback line here means the
        # tunnel died between the probe and the run
        if d.get("backend") not in (None, "tpu"):
            log(f"{name}: non-tpu backend {d.get('backend')!r}, not banking")
            continue
        ok_lines.append(ln)
    # bank every complete measurement line even from a failed/wedged run —
    # each line is self-contained — but only a clean exit marks the step
    # done (a retry may add lines a mid-run death cost this attempt)
    n_banked = (bank(name, ok_lines, attempt, partial=(rc != 0))
                if ok_lines else 0)
    if rc == 0 and ok_lines:
        log(f"{name}: SUCCESS in {dt:.0f}s — {n_banked} new line(s) banked")
        return True
    log(f"{name}: rc={rc}, {n_banked} line(s) banked from partial output, "
        f"{dt:.0f}s — see {logp}")
    return False


def main() -> None:
    st = load_state()
    log(f"watcher up; state: {st or 'fresh'}")
    while True:
        pending = [s for s in STEPS
                   if not st.get(s[0], {}).get("done")
                   and st.get(s[0], {}).get("attempts", 0) < MAX_ATTEMPTS]
        if not pending:
            log("ALL DONE (or attempts exhausted); exiting")
            return
        status = probe()
        if status != "live":
            # 'wedged' backs off 4x (see probe()); 'busy'/'down' keep the
            # normal cadence
            sleep_s = PROBE_EVERY_S * (4 if status == "wedged" else 1)
            log(f"tunnel {status} ({len(pending)} steps pending); "
                f"sleeping {sleep_s}s")
            time.sleep(sleep_s)
            continue
        name, argv, wall_s = pending[0]
        rec = st.setdefault(name, {"attempts": 0, "done": False})
        rec["attempts"] += 1
        save_state(st)
        rec["done"] = run_step(name, argv, wall_s, attempt=rec["attempts"])
        save_state(st)


if __name__ == "__main__":
    main()
