"""Persistent TPU-window capture watcher (round 5).

The axon tunnel dies and resurrects in short windows (observed rounds
2-4). This watcher probes the backend in a subprocess every few minutes
and, the moment a probe succeeds, runs the capture ladder below. Round-5
changes over the r4 watcher:

* every probe also measures blocked h2d bandwidth and publishes the
  verdict to the shared tunnel-status file (utils/tunnel.py) — the
  round-end bench reads it to skip its probe window when the tunnel has
  been dead for hours (round-4 verdict item 1);
* ladder steps carry a minimum window quality (``min_h2d_mbps``): on a
  HEALTHY window (h2d > 20 MB/s) the 8M config-2 bench runs FIRST (the
  round's highest-value capture, round-4 verdict item 2); on a degraded
  window the cheaper diagnostics run instead, and an ungated final 8M
  attempt backstops the round if no healthy window ever appears;
* while the round-end driver bench holds the preempt flag
  (utils/tunnel.py), in-flight steps are killed within ~20 s and probes
  pause — the driver's budget must never drain behind a 3000 s suite
  step.

    setsid bash -c 'exec python tools/capture_watcher.py \
        >> /tmp/capture_watcher.log 2>&1' &

Results append to BENCH_HW_r5.jsonl (one labeled JSON line per success);
per-step logs land in /tmp/capture_<name>.log; progress/state in
/tmp/otpu_capture_state_r5.json (attempts survive watcher restarts).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from orange3_spark_tpu.utils.devlock import try_tpu_device_lock  # noqa: E402
from orange3_spark_tpu.utils.tunnel import (  # noqa: E402
    preempt_active, write_tunnel_status,
)

STATE = "/tmp/otpu_capture_state_r5.json"
OUT = os.path.join(REPO, "BENCH_HW_r5.jsonl")
PROBE_EVERY_S = 150
MAX_ATTEMPTS = 3

#: (name, argv, wall timeout s, min_h2d_mbps) — the ladder picks the FIRST
#: pending step whose window-quality gate passes, so priority is list
#: order restricted to what the current window can carry.
STEPS = [
    # the round's headline ask: a GOOD-window 8M config-2 TPU line
    # (round-4's only 8M-adjacent number rode a ~2 MB/s dying tunnel).
    # Gated at 20 MB/s; the ungated *_any twin at the bottom backstops a
    # round with no healthy window.
    ("bench_8m", [sys.executable, "bench.py"], 3300, 20.0),
    # configs 3-5 at quarter scale: trees + the Pallas histogram A/B
    # (bench_suite emits hist_pallas/xla_ms on TPU), the staged
    # refit/transform TPU measurement (c5), ALS (c4). In-memory fits are
    # few-dispatch, so a degraded window mostly costs the dataset DMA —
    # any live window qualifies (gate 1 MB/s).
    ("suite_c3", [sys.executable, "bench_suite.py", "--config", "3",
                  "--rows-scale", "0.25"], 3000, 1.0),
    ("suite_c5", [sys.executable, "bench_suite.py", "--config", "5",
                  "--rows-scale", "0.25"], 2400, 1.0),
    ("suite_c4", [sys.executable, "bench_suite.py", "--config", "4",
                  "--rows-scale", "0.25"], 2400, 1.0),
    # fused-replay fault mechanism experiment: HLO-dump comparison of the
    # poisoned vs clean giant-scan execution (round-4 verdict item 6)
    ("replay_hlo", [sys.executable, "tools/replay_hlo.py"], 1800, 0.0),
    ("bench_8m_any", [sys.executable, "bench.py"], 3300, 0.0),
]


def log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def load_state() -> dict:
    try:
        with open(STATE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def save_state(st: dict) -> None:
    tmp = STATE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(st, f, indent=1)
    os.replace(tmp, STATE)


def probe() -> tuple[str, float]:
    """('live'|'down'|'wedged'|'busy', h2d_mbps).

    'live' iff the TPU answers AND executes a matmul; the probe then also
    measures one blocked 16 MB device_put — the window-quality number the
    ladder gates on and the status file publishes. 'wedged' when the
    probe subprocess TIMED OUT (the mode where ``import jax`` hangs at
    interpreter start) — the caller backs way off then. Holds the harness
    device lock for the probe's duration and reports 'busy' WITHOUT
    probing when another harness owns the device. The probe child runs in
    its own process group and a timeout kills the GROUP (wedge spawns
    tunnel-helper descendants that would otherwise keep poking the
    tunnel lock-less)."""
    with try_tpu_device_lock(name="watcher-probe") as lk:
        if not lk.held:
            log("device lock held by another harness; deferring probe")
            return "busy", 0.0
        code = (
            "import time, jax, jax.numpy as jnp, numpy as np\n"
            "d = jax.devices()\n"
            "x = jnp.ones((256, 256)); jax.block_until_ready(x @ x)\n"
            "buf = np.ones((4_000_000,), np.float32)\n"
            "t0 = time.perf_counter()\n"
            "jax.block_until_ready(jax.device_put(buf))\n"
            "mbps = buf.nbytes / (time.perf_counter() - t0) / 1e6\n"
            "print('OTPU_LIVE', d[0].platform, round(mbps, 1))"
        )
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True,
                                cwd=REPO, start_new_session=True)
        try:
            # 90 s, not more: a WEDGED probe burns this whole timeout (+30 s
            # drain) HOLDING the device lock, and the bench contract test's
            # bounded lock wait (150 s) must always span one probe's release
            out, _ = proc.communicate(timeout=90)
        except subprocess.TimeoutExpired:
            _kill_group(proc)
            write_tunnel_status("wedged", source="watcher")
            return "wedged", 0.0
        for ln in (out or "").splitlines():
            parts = ln.split()
            if ln.startswith("OTPU_LIVE tpu") and len(parts) >= 3:
                try:
                    mbps = float(parts[2])
                except ValueError:
                    mbps = 0.0
                write_tunnel_status("live", h2d_mbps=mbps, source="watcher")
                return "live", mbps
        write_tunnel_status("down", source="watcher")
        return "down", 0.0


def bank(name: str, lines: list, attempt: int, partial: bool) -> int:
    """Append measurement lines to OUT with capture provenance
    (capture_step / capture_attempt / capture_partial), skipping lines
    whose measurement content is already banked. A retried step that
    re-measures produces near-duplicates with different timings — the
    provenance fields keep them distinguishable (prefer the line without
    capture_partial; among clean lines, the highest attempt)."""
    def canon(d: dict) -> str:
        return json.dumps({k: v for k, v in d.items()
                           if not k.startswith("capture_")}, sort_keys=True)

    seen = set()
    try:
        with open(OUT) as f:
            for ln in f.read().splitlines():
                if ln.strip():
                    try:
                        seen.add(canon(json.loads(ln)))
                    except ValueError:
                        pass
    except OSError:
        pass
    n = 0
    with open(OUT, "a") as f:
        for ln in lines:
            d = json.loads(ln)
            c = canon(d)
            if c in seen:
                continue
            seen.add(c)     # also dedupe within this batch
            d["capture_step"] = name
            d["capture_attempt"] = attempt
            if partial:
                d["capture_partial"] = True
            f.write(json.dumps(d) + "\n")
            n += 1
    return n


def _kill_group(proc, grace_s: float = 10.0) -> str:
    """SIGTERM-with-grace first: a step like tools/replay_hlo.py runs its
    TPU cells in their OWN sessions (so its wall timeout can group-kill
    them without suiciding) — only the step itself can reach them, via
    its SIGTERM handler. A straight SIGKILL would orphan a live cell to
    keep driving the tunnel lock-less (round-5 review finding)."""
    from orange3_spark_tpu.utils.procs import kill_process_group

    return kill_process_group(proc, grace_s=grace_s)


def run_step(name: str, argv: list, wall_s: int, attempt: int = 0) -> str:
    """Returns 'done' | 'failed' | 'preempted'."""
    env = dict(os.environ)
    # the watcher only launches after a live probe — don't re-probe for
    # long inside the harness; fail fast and return to the probe loop.
    # OTPU_STALL_S stays at the 900 s default: the heartbeat only ticks on
    # dispatch events, so the FIRST tunnel compile of a big suite program
    # (trees/ALS single-dispatch fits, worst observed ~3 min, headroom for
    # worse) must not read as a stall; the wall timeout bounds the step.
    env.pop("OTPU_STALL_S", None)   # pin the documented 900 s default
    env.update({"OTPU_TUNNEL_WAIT_S": "120", "OTPU_TUNNEL_RETRY_S": "60"})
    # watcher children must not raise the round-end preempt flag (bench.py
    # gates preemption on this), and get the full wall as their own budget
    env["OTPU_WATCHER"] = "1"
    env["OTPU_BENCH_BUDGET_S"] = str(wall_s)
    # the step child acquires the device lock itself; bound its wait well
    # below the wall so lock contention (another harness grabbed the lock
    # in the probe->step gap) fails FAST and visibly instead of idling
    # the whole wall away and reading as a wedge
    env.setdefault("OTPU_LOCK_WAIT_S", str(max(60, int(wall_s / 4))))
    logp = f"/tmp/capture_{name}.log"
    log(f"running {name}: {' '.join(argv)} (wall {wall_s}s, log {logp})")
    t0 = time.time()
    rc: object
    out = ""
    with open(logp, "w") as lf:
        # new session => own process group, so a wall timeout kills the
        # WHOLE tree: bench.py's retry-ladder rungs are grandchildren that
        # would otherwise survive the direct child's death, keep driving
        # the TPU with the lock already released, and recreate the
        # two-process collision the lock exists to prevent
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE, stderr=lf,
                                text=True, cwd=REPO, env=env,
                                start_new_session=True)
        deadline = t0 + wall_s
        while True:
            try:
                out, _ = proc.communicate(
                    timeout=min(20.0, max(deadline - time.time(), 0.1)))
                rc = proc.returncode
                break
            except subprocess.TimeoutExpired:
                if time.time() >= deadline:
                    out = _kill_group(proc)
                    rc = "wall-timeout"
                    break
                who = preempt_active()
                if who:
                    log(f"{name}: preempted by '{who}' (round-end bench "
                        f"wants the device); killing step")
                    out = _kill_group(proc)
                    rc = "preempted"
                    break
        out = out or ""
    dt = time.time() - t0
    lines = [ln for ln in out.splitlines()
             if ln.startswith("{") and '"metric"' in ln]
    ok_lines = []
    for ln in lines:
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if d.get("rc") or not d.get("value"):
            log(f"{name}: harness error line: {ln[:200]}")
            continue
        # only bank HARDWARE lines; a cpu-fallback line here means the
        # tunnel died between the probe and the run
        if d.get("backend") not in (None, "tpu"):
            log(f"{name}: non-tpu backend {d.get('backend')!r}, not banking")
            continue
        ok_lines.append(ln)
    # bank every complete measurement line even from a failed/wedged run —
    # each line is self-contained — but only a clean exit marks the step
    # done (a retry may add lines a mid-run death cost this attempt)
    n_banked = (bank(name, ok_lines, attempt, partial=(rc != 0))
                if ok_lines else 0)
    if rc == 0 and ok_lines:
        log(f"{name}: SUCCESS in {dt:.0f}s — {n_banked} new line(s) banked")
        return "done"
    log(f"{name}: rc={rc}, {n_banked} line(s) banked from partial output, "
        f"{dt:.0f}s — see {logp}")
    return "preempted" if rc == "preempted" else "failed"


def pending_steps(st: dict) -> list:
    """Steps still worth running: not done, attempts left — and the
    ungated 8M backstop drops out once the gated 8M line is banked (it
    exists only for a round with NO healthy window)."""
    pending = [s for s in STEPS
               if not st.get(s[0], {}).get("done")
               and st.get(s[0], {}).get("attempts", 0) < MAX_ATTEMPTS]
    if st.get("bench_8m", {}).get("done"):
        pending = [s for s in pending if s[0] != "bench_8m_any"]
    return pending


def eligible_step(pending: list, h2d_mbps: float):
    """First pending step whose window-quality gate passes, or None —
    priority is list order restricted to what this window can carry."""
    for s in pending:
        if h2d_mbps >= s[3]:
            return s
    return None


def main() -> None:
    # a leaked OTPU_CHILD would no-op the BLOCKING lock paths in our step
    # children (they'd run lock-less); refuse to start that way
    assert not os.environ.get("OTPU_CHILD"), \
        "capture_watcher must not run with OTPU_CHILD set"
    st = load_state()
    log(f"watcher up (r5); state: {st or 'fresh'}")
    while True:
        pending = pending_steps(st)
        if not pending:
            log("ALL DONE (or attempts exhausted); exiting")
            return
        who = preempt_active()
        if who:
            log(f"round-end preempt flag up ('{who}'); pausing probes")
            time.sleep(60)
            continue
        status, h2d = probe()
        if status != "live":
            # 'wedged' backs off 4x (see probe()); 'busy'/'down' keep the
            # normal cadence
            sleep_s = PROBE_EVERY_S * (4 if status == "wedged" else 1)
            log(f"tunnel {status} ({len(pending)} steps pending); "
                f"sleeping {sleep_s}s")
            time.sleep(sleep_s)
            continue
        step = eligible_step(pending, h2d)
        if step is None:
            log(f"tunnel live but degraded (h2d {h2d:.1f} MB/s); "
                f"{len(pending)} gated steps pending; sleeping")
            time.sleep(PROBE_EVERY_S)
            continue
        name, argv, wall_s, _gate = step
        log(f"window open (h2d {h2d:.1f} MB/s); step {name}")
        rec = st.setdefault(name, {"attempts": 0, "done": False})
        rec["attempts"] += 1
        save_state(st)
        outcome = run_step(name, argv, wall_s, attempt=rec["attempts"])
        if outcome == "preempted":
            # not the step's fault — don't burn an attempt; resume after
            # the round-end bench releases the device
            rec["attempts"] -= 1
        rec["done"] = outcome == "done"
        save_state(st)


if __name__ == "__main__":
    main()
