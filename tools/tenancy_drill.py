"""One-shot multi-tenant control-plane drill — watch fairness fire.

Runs the fleet control plane (serve/tenancy.py, fleet/control.py)
against synthetic skewed load and prints each rung as it fires:

  fairness    3 tenants (gold/silver/bronze, weights 4/2/1, bronze
              capped at 1 in-flight slot) contend for a 2-slot
              admission controller; a holder pins bronze at its cap
              while bronze offers ~3x everyone else's load: gold and
              silver complete everything, the burster sheds typed
              TenantQuotaShedError — the per-tenant fairness table
              (weights, grants, sheds) is the printed artifact
  autoscale   a deterministic digest timeline (ramp up, then idle)
              drives a real Autoscaler over a fake supervisor on a
              fake clock: the fleet grows 1 -> 3 under pressure
              through the cooldown bands, then drains back to min —
              the decision timeline is the printed artifact

Importable: ``run_drill()`` returns the row dicts (the not-slow smoke
test in tests/test_tenancy.py calls it directly).

Usage:
    python tools/tenancy_drill.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPEC = "gold:weight=4;silver:weight=2;bronze:weight=1,max_inflight=1"


def run_drill(service_ms: float = 8.0, per_tenant: int = 8) -> list:
    import concurrent.futures
    import threading

    from orange3_spark_tpu.resilience.overload import (
        AdmissionController, OverloadShedError,
    )
    from orange3_spark_tpu.serve.tenancy import (
        TenantQuotaShedError, reset_tenant_sheds, tenant_scope,
    )

    rows_out: list = []

    def say(msg):
        print(f"[drill] {msg}", file=sys.stderr)

    # ---- rung 1: weighted-fair admission under a 3-tenant skew ----
    saved = {k: os.environ.get(k) for k in (
        "OTPU_TENANCY", "OTPU_TENANT_SPEC", "OTPU_RESILIENCE",
        "OTPU_ADMISSION_MAX_INFLIGHT", "OTPU_ADMISSION_MAX_QUEUE")}
    os.environ.update({
        "OTPU_TENANCY": "1", "OTPU_TENANT_SPEC": SPEC,
        "OTPU_RESILIENCE": "1", "OTPU_ADMISSION_MAX_INFLIGHT": "2",
        "OTPU_ADMISSION_MAX_QUEUE": "64",
    })
    outcomes: list = []
    lock = threading.Lock()
    try:
        reset_tenant_sheds()
        ac = AdmissionController()
        jobs = (["gold"] * per_tenant + ["silver"] * per_tenant
                + ["bronze"] * (3 * per_tenant))

        def one(tenant: str):
            try:
                with tenant_scope(tenant):
                    with ac.slot():
                        time.sleep(service_ms / 1e3)  # the "dispatch"
                kind = "ok"
            except TenantQuotaShedError:
                kind = "tenant_shed"
            except OverloadShedError:
                kind = "shed"
            with lock:
                outcomes.append((tenant, kind))

        # Pin bronze's single in-flight slot for the whole burst so the
        # cap hit is deterministic: the burster sits *at* its quota
        # while it offers 3x everyone else's load, instead of racing
        # the thread scheduler to overlap two 5ms dispatches.
        entered = threading.Event()
        release = threading.Event()

        def hold_bronze():
            try:
                with tenant_scope("bronze"):
                    with ac.slot():
                        entered.set()
                        release.wait(30.0)
                kind = "ok"
            except OverloadShedError:
                kind = "shed"
            finally:
                entered.set()
            with lock:
                outcomes.append(("bronze", kind))

        holder = threading.Thread(target=hold_bronze)
        holder.start()
        entered.wait(10.0)
        try:
            with concurrent.futures.ThreadPoolExecutor(len(jobs)) as ex:
                list(ex.map(one, jobs))
        finally:
            release.set()
            holder.join(30.0)
        table = ac.tenancy_snapshot()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def count(tenant, kind):
        return sum(1 for t, k in outcomes if t == tenant and k == kind)

    for t in ("gold", "silver", "bronze"):
        say(f"fairness: {t:<7} weight={table[t]['weight']} "
            f"ok={count(t, 'ok')} tenant_shed={count(t, 'tenant_shed')} "
            f"granted={table[t]['granted']}")
    fairness_ok = (
        count("gold", "ok") == per_tenant
        and count("silver", "ok") == per_tenant
        and count("bronze", "tenant_shed") >= 1
        # every caller accounted for (pool jobs + the bronze holder)
        and len(outcomes) == len(jobs) + 1)
    rows_out.append({
        "rung": "fairness", "outcomes": len(outcomes),
        "gold_ok": count("gold", "ok"),
        "silver_ok": count("silver", "ok"),
        "bronze_ok": count("bronze", "ok"),
        "bronze_typed_sheds": count("bronze", "tenant_shed"),
        "table": table, "ok": fairness_ok,
    })

    # ---- rung 2: digest timeline breathes a fake fleet 1 -> 3 -> 1 ----
    from orange3_spark_tpu.fleet.control import Autoscaler

    class _Handle:
        def __init__(self, rid):
            self.replica_id = rid

    class _FakeSupervisor:
        """add/remove_replica surface only — no subprocesses spawned."""

        def __init__(self):
            self.handles = [_Handle(0)]

        def add_replica(self):
            rid = max(h.replica_id for h in self.handles) + 1
            self.handles.append(_Handle(rid))
            return rid

        def remove_replica(self, rid):
            self.handles = [h for h in self.handles
                            if h.replica_id != rid]
            return 0

    clk = [0.0]
    sup = _FakeSupervisor()
    saved_as = os.environ.get("OTPU_AUTOSCALE")
    os.environ["OTPU_AUTOSCALE"] = "1"
    try:
        scaler = Autoscaler(sup, None, min_replicas=1, max_replicas=3,
                            up_x=2.0, down_x=0.5, cooldown_s=2.0,
                            clock=lambda: clk[0])

        def digest(load):
            n = len(sup.handles)
            per = load // n
            return {"replicas": {
                f"replica-{h.replica_id}": {
                    "up": True, "stale": False, "queue_depth": per,
                    "inflight": 0, "shed_total": 0, "brownout_level": 0,
                } for h in sup.handles}}

        timeline = []
        peak = 1
        for step in range(20):
            load = 16 if step < 10 else 0      # ramp, then idle
            decision = scaler.step(digest(load))
            peak = max(peak, len(sup.handles))
            timeline.append({
                "t": clk[0], "load": load,
                "replicas": len(sup.handles),
                "decision": decision.to_dict() if decision else None,
            })
            clk[0] += 1.0
        final = len(sup.handles)
    finally:
        if saved_as is None:
            os.environ.pop("OTPU_AUTOSCALE", None)
        else:
            os.environ["OTPU_AUTOSCALE"] = saved_as
    dirs = [t["decision"]["direction"] for t in timeline if t["decision"]]
    say(f"autoscale: peak={peak} final={final} decisions={dirs}")
    rows_out.append({
        "rung": "autoscale", "peak_replicas": peak,
        "final_replicas": final, "decisions": dirs,
        "timeline": timeline,
        "ok": peak >= 2 and final == 1 and "up" in dirs
        and "down" in dirs,
    })
    return rows_out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--per-tenant", type=int, default=8)
    args = ap.parse_args()
    sys.path.insert(0, REPO)
    results = run_drill(per_tenant=args.per_tenant)
    bad = [r for r in results if not r["ok"]]
    print(json.dumps({
        "metric": "tenancy_drill",
        "value": len(results),
        "unit": "rungs_run",
        "vs_baseline": None,
        "rungs_ok": len(results) - len(bad),
        "rungs": results,
    }))
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()
