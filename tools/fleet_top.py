"""One-shot fleet telemetry dashboard — the FleetDigest, rendered.

``top`` for the serving fleet: scrape every replica's ``/metrics``
through the fleet collector (obs/fleetobs.py), then print one table of
the load signals ROADMAP item 3's autoscaler consumes — per-replica
in-flight, admission queue depth, shed total, brownout rung, RPC count,
scrape staleness — plus the router's EWMA-p95 and the SLO burn-rate
verdicts.

Two modes:

* **attach** (``--endpoints host:port,host:port``): scrape a LIVE fleet
  you already run — no model, no subprocesses, read-only;
* **demo** (default): fit a tiny CTR model, serve it from an in-process
  replica runtime on a loopback port, drive a few predicts through a
  hedged router with an SLO engine attached, and render the digest that
  produces — the zero-setup way to see the fleet plane work (and the
  tier-1 smoke in tests/test_fleetobs.py).

Importable: ``run_top(...)`` returns ``{"digest", "slo", "staleness",
"fleetz"}``.

Usage:
    python tools/fleet_top.py [--endpoints H:P,H:P] [--requests 8]
                              [--watch SECONDS]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _render(digest: dict, slo: list, fair_share: dict | None = None,
            out=sys.stderr) -> None:
    rows = digest["replicas"]
    hdr = (f"{'replica':<14} {'up':<3} {'stale':<5} {'age_s':>6} "
           f"{'inflt':>5} {'queue':>5} {'shed':>6} {'brown':>5} "
           f"{'rpc':>8} {'devMB':>7} {'goodput':<14}")
    print(f"[fleet-top] {hdr}", file=out)
    for r in rows:
        age = "-" if r["scrape_age_s"] is None else f"{r['scrape_age_s']:.1f}"
        dev_mb = sum((r.get("device_bytes") or {}).values()) / 1e6
        gp = r.get("goodput") or {}
        # fleet-wide goodput at a glance: the dominant stage of each
        # replica's last fit (obs/prof.py decomposition), '-' until one ran
        gp_s = (max(gp, key=gp.get) if gp else "-")
        print(f"[fleet-top] {r['replica']:<14} "
              f"{'y' if r['up'] else 'n':<3} "
              f"{'Y' if r['stale'] else '.':<5} {age:>6} "
              f"{r['inflight']:>5.0f} {r['queue_depth']:>5.0f} "
              f"{r['shed_total']:>6.0f} {r['brownout_level']:>5.0f} "
              f"{r['rpc_requests']:>8.0f} {dev_mb:>7.1f} {gp_s:<14}",
              file=out)
    p95 = digest.get("ewma_p95_ms")
    print(f"[fleet-top] router ewma_p95_ms="
          f"{'-' if p95 is None else p95} "
          f"stale_replicas={digest['stale_replicas']}", file=out)
    wire = digest.get("wire") or {}
    if wire:
        # the data-plane fast path at a glance (fleet/fastwire.py):
        # connection reuse %, coalescer merge factor, SHM bytes moved
        conn = wire.get("conn") or {}
        co = wire.get("coalesce") or {}
        shm = wire.get("shm") or {}
        print(f"[fleet-top] wire conn_reuse="
              f"{conn.get('reuse_pct', 0.0):.1f}% "
              f"(opened={conn.get('opened', 0)} "
              f"reused={conn.get('reused', 0)} "
              f"stale_retries={conn.get('stale_retries', 0)}) "
              f"merge_factor={co.get('merge_factor', 0.0):.2f} "
              f"(members={co.get('members', 0)} "
              f"dispatches={co.get('dispatches', 0)} "
              f"sheds={co.get('sheds', 0)}) "
              f"shm_mb={shm.get('bytes_total', 0.0) / 1e6:.2f} "
              f"shm_fallbacks={shm.get('fallbacks', 0)}", file=out)
    # per-tenant control-plane table (serve/tenancy.py): slots held,
    # grants and typed quota sheds aggregated across the fleet; weight
    # comes from the local fair-share table when one exists ('-' when
    # attached to a remote fleet whose spec we cannot see)
    tenants: dict = {}
    for r in rows:
        for key in ("tenant_inflight", "tenant_granted", "tenant_sheds"):
            for t, v in (r.get(key) or {}).items():
                tenants.setdefault(t, {})[key] = (
                    tenants.get(t, {}).get(key, 0.0) + v)
    if tenants:
        print(f"[fleet-top] {'tenant':<14} {'weight':>6} {'inflt':>5} "
              f"{'granted':>8} {'sheds':>6}", file=out)
        fair = fair_share or {}
        for t in sorted(tenants):
            row = tenants[t]
            w = fair.get(t, {}).get("weight", "-")
            print(f"[fleet-top] {t:<14} {w!s:>6} "
                  f"{row.get('tenant_inflight', 0.0):>5.0f} "
                  f"{row.get('tenant_granted', 0.0):>8.0f} "
                  f"{row.get('tenant_sheds', 0.0):>6.0f}", file=out)
    scaler = digest.get("autoscaler")
    if scaler:
        last = scaler.get("last_decision") or {}
        print(f"[fleet-top] autoscaler replicas={scaler['replicas']} "
              f"bounds=[{scaler['min']},{scaler['max']}] "
              f"decisions={scaler['decisions']} "
              f"last={last.get('direction', '-')}"
              f"{'/' + str(last.get('reason')) if last else ''} "
              f"cooldown_s={scaler['cooldown_remaining_s']}", file=out)
    for v in slo:
        fast = v["rules"]["fast"]
        print(f"[fleet-top] slo {v['slo']:<14} ({v['kind']}) "
              f"burn_fast={fast['burn_long']:.2f} "
              f"budget={v['budget_remaining']:.3f} "
              f"{'ALERT' if v['alerting'] else 'ok'}", file=out)


def run_top(session=None, *, requests: int = 8, endpoints=None,
            scrape_s: float = 0.5) -> dict:
    """One collection cycle → rendered table + the structured views."""
    import numpy as np

    from orange3_spark_tpu.fleet.rpc import FleetClient
    from orange3_spark_tpu.fleet.router import FleetRouter
    from orange3_spark_tpu.obs.fleetobs import FleetCollector, SLOEngine

    runtime = router = None
    tmp_root = None
    try:
        if endpoints:
            clients = [FleetClient(h, int(p), name=f"{h}:{p}")
                       for h, p in (e.split(":") for e in endpoints)]
            collector = FleetCollector(clients, scrape_s=scrape_s)
        else:
            # demo fleet: one in-process replica runtime on loopback
            from orange3_spark_tpu.core.session import TpuSession
            from orange3_spark_tpu.fleet.replica import ReplicaRuntime
            from orange3_spark_tpu.fleet.rollout import publish_version
            from orange3_spark_tpu.io.streaming import array_chunk_source
            from orange3_spark_tpu.models.hashed_linear import (
                StreamingHashedLinearEstimator,
            )
            from orange3_spark_tpu.serve import BucketLadder

            session = session or TpuSession.builder_get_or_create()
            rng = np.random.default_rng(5)
            X = np.concatenate([
                rng.standard_normal((2048, 4)).astype(np.float32),
                rng.integers(0, 500, (2048, 4)).astype(np.float32),
            ], axis=1)
            y = (rng.random(2048) < 0.3).astype(np.float32)
            model = StreamingHashedLinearEstimator(
                n_dims=1 << 10, n_dense=4, n_cat=4, epochs=1,
                step_size=0.05, chunk_rows=1024,
            ).fit_stream(array_chunk_source(X, y, chunk_rows=1024),
                         session=session)
            tmp_root = tempfile.mkdtemp(prefix="otpu-fleet-top-")
            publish_version(model, tmp_root, n_cols=8)
            runtime = ReplicaRuntime(
                tmp_root, name="replica-0", session=session,
                ladder=BucketLadder(min_bucket=64, max_bucket=256))
            runtime.activate()
            server = runtime.serve_background()
            slo = SLOEngine()
            router = FleetRouter([(0, "127.0.0.1", server.port)],
                                 hedging=False, slo=slo)
            router.refresh()
            collector = FleetCollector(
                router.endpoints, router=router, slo=slo,
                scrape_s=scrape_s)
            # half the demo predicts ride a tenant scope so the
            # per-tenant table has rows to render
            from orange3_spark_tpu.serve.tenancy import tenant_scope

            for i in range(max(requests, 1)):
                if i % 2:
                    with tenant_scope("demo-gold"):
                        router.predict(X[:96])
                else:
                    router.predict(X[:96])
        digest = collector.scrape_once()
        fleetz = collector.fleetz()
        # the local fair-share table (weights) when the serving context
        # runs in THIS process — attach mode has no view into it
        fair = None
        if runtime is not None:
            ctx = getattr(runtime, "serving_context", None)
            adm = getattr(ctx, "admission", None)
            if adm is not None:
                fair = adm.tenancy_snapshot()
        _render(digest.to_dict(), fleetz["slo"], fair)
        return {
            "digest": digest.to_dict(),
            "slo": fleetz["slo"],
            "staleness": collector.staleness(),
            "fleetz": fleetz,
            "tenants": fleetz.get("tenants"),
            "autoscaler": digest.autoscaler,
        }
    finally:
        if router is not None:
            router.close()
        if runtime is not None:
            runtime.close()
        if tmp_root is not None:
            import shutil

            shutil.rmtree(tmp_root, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--endpoints", default="",
                    help="comma-separated host:port list of a LIVE fleet "
                         "to attach to (default: spin the demo fleet)")
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--watch", type=float, default=0.0,
                    help="re-render every N seconds until ^C (attach "
                         "mode only; 0 = one shot)")
    args = ap.parse_args()
    sys.path.insert(0, REPO)
    eps = [e for e in args.endpoints.split(",") if e.strip()]
    if args.watch > 0 and eps:
        try:
            while True:
                run_top(endpoints=eps, requests=args.requests)
                time.sleep(args.watch)
        except KeyboardInterrupt:
            return
    out = run_top(endpoints=eps or None, requests=args.requests)
    print(json.dumps({
        "metric": "fleet_top",
        "value": len(out["digest"]["replicas"]),
        "unit": "replicas",
        "vs_baseline": None,
        "stale_replicas": out["digest"]["stale_replicas"],
        "slo_alerting": any(v["alerting"] for v in out["slo"]),
    }, default=str))


if __name__ == "__main__":
    main()
