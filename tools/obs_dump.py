"""One-shot observability smoke — metrics snapshot + trace export.

Runs a small streaming fit plus a short served predict trace, then:

* prints the full metrics-registry snapshot (the same structure bench.py
  embeds under its ``obs`` key),
* exports the recorded spans as Chrome trace-event JSON and validates it
  against the format's object-form rules (the file loads in Perfetto /
  ``chrome://tracing``),
* prints a one-line summary JSON (the capture-watcher banking convention).

The quick "is the whole obs surface wired?" probe: fit/epoch/chunk/
dispatch spans from the fit, serve spans + aot/bucket counters from the
trace, and a parseable export — all in a few seconds on CPU.

Importable: ``run_dump(rows=..., session=...)`` returns the summary dict
(the not-slow smoke test in tests/test_obs.py calls it directly).

``--flight`` additionally exercises the anomaly flight recorder: a
manual ``obs.flight.dump()`` after the fit+serve window, the bundle
re-read and schema-checked, its path in the summary line.

``--profile`` pulls one deep-profile capture (obs/prof.py): a short
``jax.profiler`` window plus the goodput+ledger+registry snapshot into
an atomic ``capture-*`` dir under ``OTPU_PROF_DIR`` — the manual twin
of ``POST /debug/profile``; render it with ``tools/goodput_view.py``.

Usage:
    python tools/obs_dump.py [--rows 8192] [--trace-out /tmp/otpu_trace.json]
                             [--flight] [--profile]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def run_dump(rows: int = 8192, session=None,
             trace_out: str | None = None,
             flight: bool = False, profile: bool = False) -> dict:
    import numpy as np

    from orange3_spark_tpu.core.session import TpuSession
    from orange3_spark_tpu.io.streaming import (
        StreamingLinearEstimator, array_chunk_source,
    )
    from orange3_spark_tpu.obs import REGISTRY, trace
    from orange3_spark_tpu.serve import BucketLadder, ServingContext

    session = session or TpuSession.builder_get_or_create()
    chunk_rows = 512
    n_features = 4
    rng = np.random.default_rng(0)
    X = rng.standard_normal((rows, n_features)).astype(np.float32)
    y = (X @ rng.standard_normal(n_features).astype(np.float32) > 0
         ).astype(np.float32)
    src = array_chunk_source(X, y, chunk_rows=chunk_rows)

    trace.clear()
    model = StreamingLinearEstimator(
        loss="logistic", epochs=2, step_size=0.1, chunk_rows=chunk_rows,
    ).fit_stream(src, n_features=n_features, session=session,
                 cache_device=True)

    # short served trace: three mixed-size predicts through the bucketed
    # AOT path (ticks the serve counters and records "serve" spans)
    from orange3_spark_tpu.core.domain import (
        ContinuousVariable, DiscreteVariable, Domain,
    )
    from orange3_spark_tpu.core.table import TpuTable

    domain = Domain([ContinuousVariable(f"f{i}") for i in range(n_features)],
                    DiscreteVariable("y", ("0", "1")))
    ctx = ServingContext(BucketLadder(min_bucket=64,
                                      max_bucket=max(chunk_rows, 64)))
    with ctx:
        for n in (32, 100, min(rows, chunk_rows)):
            t = TpuTable.from_numpy(domain, X[:n], y[:n], session=session)
            model.predict(t)
        serve_report = ctx.report()

    exported = trace.export_chrome_trace(trace_out)
    events = trace.validate_chrome_trace(exported)   # raises if malformed
    span_names = sorted({e["name"] for e in events if e["ph"] == "X"})
    snapshot = REGISTRY.snapshot()
    # under OTPU_OBS=0 there are no spans and no run report — the tool
    # still dumps the registry (live by design) instead of crashing
    fit_report = getattr(model, "run_report_", None)
    flight_path = flight_valid = None
    if flight:
        from orange3_spark_tpu.obs import flight as _flight

        flight_path = _flight.dump("obs_dump_smoke")
        if flight_path is not None:      # None under the kill-switches
            with open(flight_path) as f:
                bundle = json.load(f)     # bundle must be valid JSON
            flight_valid = (
                bundle.get("flight_schema") == _flight.FLIGHT_SCHEMA_VERSION
                and bool(bundle.get("stacks"))
                and "registry" in bundle and "knobs" in bundle)
    profile_path = profile_valid = None
    if profile:
        from orange3_spark_tpu.obs import prof as _prof

        try:
            cap = _prof.capture(duration_ms=10, reason="obs_dump")
        except (_prof.CaptureDisabledError, _prof.CaptureBusyError,
                _prof.CaptureRateLimitedError):
            # OTPU_PROF=0 / another capture running / inside the rate
            # window: the dump DEGRADES (path stays None) — the metrics
            # snapshot and trace already gathered must still land
            cap = None
        if cap is not None:
            profile_path = cap["path"]
            snap_path = os.path.join(profile_path, "snapshot.json")
            with open(snap_path) as f:
                snap = json.load(f)          # must be complete, valid JSON
            profile_valid = (
                snap.get("prof_schema") == _prof.PROF_SCHEMA_VERSION
                and "ledger" in snap and "registry" in snap
                and "knobs" in snap)
    return {
        "metric": "obs_dump",
        "rows": rows,
        "obs_enabled": trace.enabled(),
        "fit_report": fit_report.to_dict() if fit_report else None,
        "serve_report": serve_report,
        "trace_events": len(events),
        "span_names": span_names,
        "trace_valid": True,
        "trace_path": trace_out,
        "flight_path": flight_path,
        "flight_valid": flight_valid,
        "profile_path": profile_path,
        "profile_valid": profile_valid,
        "snapshot_metrics": len(snapshot),
        "snapshot": snapshot,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=8192)
    ap.add_argument("--trace-out", default="/tmp/otpu_trace.json")
    ap.add_argument("--flight", action="store_true",
                    help="also exercise a manual flight-recorder dump")
    ap.add_argument("--profile", action="store_true",
                    help="also pull one deep-profile capture (obs/prof.py)")
    args = ap.parse_args()
    out = run_dump(rows=args.rows, trace_out=args.trace_out,
                   flight=args.flight, profile=args.profile)
    print("== metrics snapshot ==")
    print(json.dumps(out["snapshot"], indent=2))
    print(f"== trace: {out['trace_events']} events "
          f"({', '.join(out['span_names'])}) -> {out['trace_path']} ==")
    summary = {k: v for k, v in out.items()
               if k not in ("snapshot", "fit_report", "serve_report")}
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
