"""Diagnose the round-4 fused-replay device fault on the live TPU.

Observed (2026-07-31, axon tunnel to 1x v5e): executing ANY
`fit_stream` (even a single zero chunk from numpy, prefetch on or off)
followed by the big `_hashed_replay_epochs` scan program in the SAME
process kills the device program with
`jax.errors.JaxRuntimeError: UNAVAILABLE: TPU device error` — while the
identical replay program runs clean standalone, and per-chunk replay of
the same cached epochs is unaffected (bench.py's OTPU_FUSED_REPLAY=0
retry rung exists because of this). The fault is NOT the tunnel dying:
probes keep succeeding after it.

This tool runs a small experiment matrix, each cell in a fresh
subprocess (a faulted cell must not poison the next), and prints one
JSON line per cell plus a summary — so one short tunnel window answers:

  base       fitnp -> replay with emb_update='sorted' (the faulting
             round-4 config; expect FAULT — reproduces the signature)
  embfused   fitnp -> replay with emb_update='fused' (the new 'auto'
             winner): does the sorted custom-vjp inside the scan carry
             the fault?
  cached     replay -> fitnp -> replay2: does a replay EXECUTABLE
             compiled before any step survive re-execution after steps?
             (If yes, bench.py can hoist warm_replay first and keep
             fused replay on hardware.)
  delwarm    fitnp -> free the warm model -> replay: is it live-buffer /
             memory-pressure related?

Usage (watcher runs it automatically in a window):
    python tools/replay_fault_diag.py [--chunk-rows 262144]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CELL_SRC = r"""
import sys, time
sys.path.insert(0, __REPO__)
import jax
import numpy as np

chunk_rows = __CHUNK_ROWS__
emb = __EMB__
stages = __STAGES__

from orange3_spark_tpu.core.session import TpuSession
from orange3_spark_tpu.models.hashed_linear import (
    StreamingHashedLinearEstimator,
)

sess = TpuSession.builder_get_or_create()
assert jax.default_backend() == "tpu", jax.default_backend()

def make_est(e, gran="all"):
    return StreamingHashedLinearEstimator(
        n_dims=1 << 22, n_dense=13, n_cat=26, epochs=e,
        chunk_rows=chunk_rows, label_in_chunk=True, prefetch_depth=2,
        emb_update=emb, replay_granularity=gran,
    )

warm = None
for stage in stages:
    t0 = time.perf_counter()
    if stage == "fitnp":
        Xnp = np.zeros((chunk_rows, 40), np.float32)
        def np_source():
            yield Xnp
        warm = make_est(1).fit_stream(
            np_source, session=sess, cache_device=True, holdout_chunks=0)
    elif stage == "delwarm":
        warm = None
        import gc; gc.collect()
    elif stage in ("replay", "replay2"):
        make_est(100).warm_replay(6, session=sess)
    elif stage == "replayepoch":
        # the bench's rung-2 lowering: n_epochs=1 scans over the stack,
        # dispatched REPEATEDLY like the real per-epoch replay loop (the
        # fault might need repeated execution / cumulative device state —
        # one dispatch would under-power the verdict). warm_replay with
        # granularity 'epoch' compiles + executes the n_epochs=1 program;
        # repeats hit the jit cache, so 8 rounds ~= 8 executions.
        est = make_est(100, gran="epoch")
        for _ in range(8):
            est.warm_replay(6, session=sess)
    else:
        raise ValueError(stage)
    print(f"STAGE_OK {stage} {time.perf_counter()-t0:.1f}s", flush=True)
print("CELL_OK", flush=True)
"""

CELLS = [
    # (name, emb_update, stages)
    ("base", "sorted", ["fitnp", "replay"]),
    ("embfused", "fused", ["fitnp", "replay"]),
    ("epochwise", "fused", ["fitnp", "replayepoch"]),  # bench rung 2
    ("cached", "sorted", ["replay", "fitnp", "replay2"]),
    ("delwarm", "sorted", ["fitnp", "delwarm", "replay"]),
]


# --smoke cell: exercises the subprocess/JSON plumbing (spawn, STAGE_OK
# parsing, verdict emission) without importing jax or touching a device —
# the not-slow tier-1 smoke test runs this so a refactor that breaks the
# matrix harness fails in CI instead of in a scarce tunnel window
_SMOKE_SRC = r"""
import time
print("STAGE_OK noop 0.0s", flush=True)
print("CELL_OK", flush=True)
"""


def run_cell(name: str, emb: str, stages: list, chunk_rows: int,
             wall_s: float, src_override: str | None = None) -> dict:
    src = src_override if src_override is not None else (
        _CELL_SRC
        .replace("__REPO__", repr(REPO))
        .replace("__CHUNK_ROWS__", str(chunk_rows))
        .replace("__EMB__", repr(emb))
        .replace("__STAGES__", repr(list(stages))))
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, "-c", src],
                           capture_output=True, text=True, timeout=wall_s,
                           cwd=REPO)
        rc, out, err = r.returncode, r.stdout or "", r.stderr or ""
    except subprocess.TimeoutExpired as e:
        rc = "wall-timeout"

        def _dec(b):
            return (b or b"").decode("utf-8", "replace") \
                if isinstance(b, bytes) else (b or "")
        out, err = _dec(e.stdout), _dec(e.stderr)
    ok_stages = [ln.split()[1] for ln in out.splitlines()
                 if ln.startswith("STAGE_OK ")]
    fault = "UNAVAILABLE" in err or "UNAVAILABLE" in out
    res = {
        "cell": name, "emb_update": emb, "stages": stages,
        "ok": rc == 0 and "CELL_OK" in out,
        "stages_completed": ok_stages, "rc": rc,
        "device_fault": fault, "wall_s": round(time.time() - t0, 1),
    }
    if not res["ok"]:
        tail = err.strip().splitlines()[-1:] if err.strip() else []
        res["error_tail"] = tail[0][-200:] if tail else ""
    return res


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--chunk-rows", type=int, default=1 << 18)
    ap.add_argument("--wall-s", type=float, default=420.0)
    ap.add_argument("--smoke", action="store_true",
                    help="plumbing smoke: one trivial no-jax cell, no "
                         "device lock (the tier-1 not-slow smoke test)")
    args = ap.parse_args()

    if args.smoke:
        res = run_cell("smoke", "none", ["noop"], args.chunk_rows,
                       60.0, src_override=_SMOKE_SRC)
        print(json.dumps(res), flush=True)
        print(json.dumps(_verdict([res], backend="none")), flush=True)
        sys.exit(0 if res["ok"] else 1)

    # serialize against any other TPU harness for the WHOLE matrix (the
    # cells are this process's children and take no lock of their own —
    # see utils/devlock.py)
    sys.path.insert(0, REPO)
    from orange3_spark_tpu.utils.devlock import tpu_device_lock

    with tpu_device_lock(name="replay_diag"):
        _main_locked(args)


def _verdict(results: list, backend: str = "tpu") -> dict:
    by = {r["cell"]: r for r in results}

    def ok(cell):
        r = by.get(cell)
        return None if r is None else r["ok"]

    base = by.get("base")
    return {
        "metric": "replay_fault_diag",
        # value = cells RUN (nonzero whenever the matrix executed), so an
        # all-cells-fault outcome — a perfectly valid result — still
        # passes capture_watcher's `rc or not value` banking filter
        "value": len(results),
        "unit": "cells_run",
        "cells_ok": sum(r["ok"] for r in results),
        "vs_baseline": None,
        "backend": backend,
        "reproduced": (None if base is None
                       else (not base["ok"] and base["device_fault"])),
        "fixed_by_fused_emb": ok("embfused"),
        "fixed_by_epoch_granularity": ok("epochwise"),
        "fixed_by_precompile": ok("cached"),
        "fixed_by_freeing_warm": ok("delwarm"),
        # full per-cell records ride inside the banked line — the watcher
        # keeps only '"metric"' lines, and stdout is otherwise discarded
        "cells": results,
    }


def _main_locked(args) -> None:
    results = []
    for name, emb, stages in CELLS:
        res = run_cell(name, emb, stages, args.chunk_rows, args.wall_s)
        print(json.dumps(res), flush=True)
        results.append(res)
    print(json.dumps(_verdict(results)), flush=True)


if __name__ == "__main__":
    main()
