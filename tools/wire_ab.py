"""One-shot wire-mode A/B — the fleet data plane's three wires, raced.

Serves a tiny CTR model from an in-process replica on loopback, then
drives the SAME predict through each wire mode, interleaved round-robin
(so OS-level drift hits every arm equally), and reports per-arm p50:

* **fresh**      ``OTPU_FLEET_FASTWIRE=0`` — the PR-13 wire: one TCP
  connect + npy body per request (the kill-switch baseline);
* **keepalive**  fast path with SHM off — pooled persistent connection,
  npy body;
* **shm**        pooled connection + shared-memory zero-copy body (the
  HTTP payload shrinks to a JSON segment descriptor).

Knobs are read per call, so the arms flip by environment variable
between requests — no restarts, same replica, same model, same rows.

Importable: ``run_ab(...)`` returns the parsed record (tier-1 smoke in
tests/test_fastwire.py). CLI prints it as JSON on stdout.

Usage:
    python tools/wire_ab.py [--rows 256] [--iters 40]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time
from contextlib import contextmanager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ARMS = (
    ("fresh", {"OTPU_FLEET_FASTWIRE": "0"}),
    ("keepalive", {"OTPU_FLEET_FASTWIRE": "1", "OTPU_FLEET_SHM": "0"}),
    ("shm", {"OTPU_FLEET_FASTWIRE": "1", "OTPU_FLEET_SHM": "1"}),
)


@contextmanager
def _env(overrides: dict):
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_ab(session=None, *, rows: int = 256, cols: int = 8,
           iters: int = 40, warmup: int = 5) -> dict:
    """Serve one replica, race the three wire modes over it, return
    ``{"metric": "wire_ab", ...}`` with per-arm p50s and speedups."""
    import numpy as np

    from orange3_spark_tpu.core.session import TpuSession
    from orange3_spark_tpu.fleet.replica import ReplicaRuntime
    from orange3_spark_tpu.fleet.rollout import publish_version
    from orange3_spark_tpu.fleet.rpc import FleetClient
    from orange3_spark_tpu.io.streaming import array_chunk_source
    from orange3_spark_tpu.models.hashed_linear import (
        StreamingHashedLinearEstimator,
    )
    from orange3_spark_tpu.serve import BucketLadder

    session = session or TpuSession.builder_get_or_create()
    rng = np.random.default_rng(11)
    Xf = np.concatenate([
        rng.standard_normal((2048, cols // 2)).astype(np.float32),
        rng.integers(0, 500, (2048, cols - cols // 2)).astype(np.float32),
    ], axis=1)
    y = (rng.random(2048) < 0.3).astype(np.float32)
    model = StreamingHashedLinearEstimator(
        n_dims=1 << 10, n_dense=cols // 2, n_cat=cols - cols // 2,
        epochs=1, step_size=0.05, chunk_rows=1024,
    ).fit_stream(array_chunk_source(Xf, y, chunk_rows=1024),
                 session=session)
    X = Xf[:rows]
    tmp_root = tempfile.mkdtemp(prefix="otpu-wire-ab-")
    runtime = None
    client = None
    try:
        publish_version(model, tmp_root, n_cols=cols)
        runtime = ReplicaRuntime(
            tmp_root, name="wire-ab", session=session,
            ladder=BucketLadder(min_bucket=64, max_bucket=1 << 10))
        runtime.activate()
        server = runtime.serve_background()
        client = FleetClient("127.0.0.1", server.port, name="wire-ab")
        expect = None
        for name, env in ARMS:       # warm every arm (and check parity)
            with _env(env):
                for _ in range(max(warmup, 1)):
                    out, _h = client.predict(X)
                if expect is None:
                    expect = out
                parity = bool((out == expect).all())
                if not parity:
                    raise AssertionError(
                        f"wire arm {name} changed the prediction bytes")
        lat: dict[str, list] = {name: [] for name, _ in ARMS}
        for _ in range(max(iters, 1)):
            for name, env in ARMS:   # interleaved: drift hits all arms
                with _env(env):
                    t0 = time.perf_counter()
                    client.predict(X)
                    lat[name].append((time.perf_counter() - t0) * 1e3)
        p50 = {n: round(statistics.median(v), 4) for n, v in lat.items()}
        pool = client.pool.stats()
        return {
            "metric": "wire_ab",
            "value": round(p50["fresh"] / max(p50["shm"], 1e-9), 3),
            "unit": "x_fresh_over_shm",
            "vs_baseline": None,
            "rows": rows,
            "iters": iters,
            "fresh_p50_ms": p50["fresh"],
            "keepalive_p50_ms": p50["keepalive"],
            "shm_p50_ms": p50["shm"],
            "keepalive_speedup": round(
                p50["fresh"] / max(p50["keepalive"], 1e-9), 3),
            "shm_speedup": round(p50["fresh"] / max(p50["shm"], 1e-9), 3),
            "conn_reuse_pct": pool["reuse_pct"],
            "parity": True,
        }
    finally:
        if client is not None:
            client.close()
        if runtime is not None:
            runtime.close()
        import shutil

        shutil.rmtree(tmp_root, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=256)
    ap.add_argument("--iters", type=int, default=40)
    args = ap.parse_args()
    sys.path.insert(0, REPO)
    print(json.dumps(run_ab(rows=args.rows, iters=args.iters)))


if __name__ == "__main__":
    main()
