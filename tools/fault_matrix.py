"""One-shot fault-injection matrix — every injector against a small fit.

Runs each resilience/faults.py injector kind against the same tiny
streaming fit and prints a table of outcome / retries / overhead, plus
one JSON line (the capture-watcher banking convention). The matrix is the
quick "is the whole resilience surface wired?" probe:

  clean           no faults — the overhead denominator
  source_io       fail-twice-then-succeed chunk read -> recovered,
                  bitwise-equal theta, 2 retries
  source_fatal    fail-always chunk read -> bounded attempts, then raises
  straggler       slow chunks -> recovered, measured overhead
  spill_corrupt   bit-flipped spill record -> SpillCorruptionError naming
                  the ordinal (fit with an overflowed cache + disk spill)
  wedge           never-returning dispatch -> DispatchWedgedError within
                  the watchdog budget
  aot_build       transient serving AOT build failure -> recovered with
                  one retry through ExecutableCache
  overload        injected service delay under a full admission slot ->
                  typed OverloadShedError (never a queue)
  mem_pressure    synthetic memory-pressure fraction -> brownout level
                  raised, typed degradation not an OOM
  drift           injected feature shift on a tapped stream -> the online
                  drift gate raises DriftDetectedError naming columns
  label_skew      seeded label flips -> deterministic mask (the same rows
                  flip in-process and in a subprocess bench arm)
  trainer_crash   Nth incremental-trainer device step dies ->
                  TrainerCrashInjected (the checkpoint-resume drill hook)

Importable: ``run_matrix(rows=..., session=...)`` returns the row dicts
(the not-slow smoke test in tests/test_resilience.py calls it directly).

Usage:
    python tools/fault_matrix.py [--rows 16384]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_matrix(rows: int = 16384, session=None) -> list:
    import numpy as np

    from orange3_spark_tpu.core.session import TpuSession
    from orange3_spark_tpu.io.codec import SpillCorruptionError
    from orange3_spark_tpu.io.streaming import (
        StreamingLinearEstimator, array_chunk_source,
    )
    from orange3_spark_tpu.resilience import (
        DispatchWedgedError, TransientSourceError, inject_faults,
    )
    from orange3_spark_tpu.utils.profiling import (
        reset_resilience_counters, resilience_counters,
    )

    session = session or TpuSession.builder_get_or_create()
    chunk_rows = 512
    n_features = 4
    rng = np.random.default_rng(0)
    X = rng.standard_normal((rows, n_features)).astype(np.float32)
    y = (X @ rng.standard_normal(n_features).astype(np.float32) > 0
         ).astype(np.float32)
    src = array_chunk_source(X, y, chunk_rows=chunk_rows)
    # epochs x chunks must clear the period-16 dispatch sync (rows/512
    # chunks per epoch) or the wedge cell's guarded sync never runs
    est_kw = dict(loss="logistic", epochs=max(4, (17 * 512) // rows + 1),
                  step_size=0.1, chunk_rows=chunk_rows)
    # short backoff: the matrix measures recovery, not sleep policy
    os.environ.setdefault("OTPU_RETRY_BASE_S", "0.005")

    def fit(**kw):
        return StreamingLinearEstimator(**est_kw).fit_stream(
            src, n_features=n_features, session=session, **kw)

    import jax

    jax.block_until_ready(fit().coef)     # compile out of band

    rows_out: list = []
    t0 = time.perf_counter()
    ref = fit()
    wall_clean = time.perf_counter() - t0
    rows_out.append({"cell": "clean", "outcome": "ok", "retries": 0,
                     "faults_injected": 0,
                     "wall_s": round(wall_clean, 3), "overhead_pct": 0.0})

    def cell(name, spec, fn, expect=None):
        reset_resilience_counters()
        t0 = time.perf_counter()
        outcome = "recovered"
        try:
            with inject_faults(spec):
                fn()
        except Exception as e:  # noqa: BLE001 - the outcome under test
            outcome = f"raised:{type(e).__name__}"
            if expect is not None and not isinstance(e, expect):
                outcome = f"UNEXPECTED:{type(e).__name__}: {e}"
        else:
            if expect is not None:
                outcome = "UNEXPECTED:no error raised"
        wall = time.perf_counter() - t0
        res = resilience_counters()
        rows_out.append({
            "cell": name, "outcome": outcome,
            "retries": res["retries"],
            "faults_injected": res["faults_injected"],
            "wall_s": round(wall, 3),
            "overhead_pct": round(
                100.0 * (wall - wall_clean) / max(wall_clean, 1e-9), 1),
        })

    def parity_fit():
        m = fit()
        import numpy as _np

        if not _np.array_equal(_np.asarray(m.coef), _np.asarray(ref.coef)):
            raise AssertionError("recovered fit != fault-free fit")

    cell("source_io", "source_io:chunk=2,fails=2", parity_fit)
    cell("source_fatal", "source_io:chunk=1,fails=-1", fit,
         expect=TransientSourceError)
    cell("straggler", "slow_source:every=4,delay_ms=5", parity_fit)

    spill_dir = tempfile.mkdtemp(prefix="otpu_fault_matrix_")

    def spill_fit():
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")   # the overflow warning is
            #                                   the scenario, not a bug
            fit(cache_device=True, cache_device_bytes=1,
                cache_spill_dir=spill_dir)

    cell("spill_corrupt", "spill_corrupt:record=1,mode=flip", spill_fit,
         expect=SpillCorruptionError)

    old = os.environ.get("OTPU_DISPATCH_BUDGET_S")
    os.environ["OTPU_DISPATCH_BUDGET_S"] = "0.2"
    try:
        cell("wedge", "wedge:at=1,hold_s=20", fit,
             expect=DispatchWedgedError)
    finally:
        if old is None:
            os.environ.pop("OTPU_DISPATCH_BUDGET_S", None)
        else:
            os.environ["OTPU_DISPATCH_BUDGET_S"] = old

    def aot_fit():
        from orange3_spark_tpu.serve.cache import ExecutableCache

        cache = ExecutableCache(max_entries=4)
        built = cache.get_or_build(("fault-matrix-key",), lambda: "entry")
        if built != "entry":
            raise AssertionError(f"unexpected build product {built!r}")

    cell("aot_build", "aot_build:fails=1", aot_fit)

    # ---- online / overload injectors (lightweight wiring probes: the
    # full gate drills live in bench.py --config online and tests/) ----
    from orange3_spark_tpu.online.drift import DriftDetectedError
    from orange3_spark_tpu.online.trainer import TrainerCrashInjected
    from orange3_spark_tpu.resilience.faults import active_fault_spec
    from orange3_spark_tpu.resilience.overload import (
        AdmissionController, OverloadShedError, request_deadline,
    )

    def overload_drill():
        import threading

        from orange3_spark_tpu.resilience.overload import (
            maybe_injected_service_delay,
        )

        adm = AdmissionController(max_inflight=1)
        entered = threading.Event()
        release = threading.Event()

        def holder():
            with adm.slot():
                entered.set()
                maybe_injected_service_delay()   # the injected service time
                release.wait(5)

        th = threading.Thread(target=holder)
        th.start()
        entered.wait(5)
        try:
            with request_deadline(0.001), adm.slot():
                pass
        finally:
            release.set()
            th.join(5)

    cell("overload", "overload:delay_ms=30", overload_drill,
         expect=OverloadShedError)

    def mem_pressure_drill():
        from orange3_spark_tpu.resilience.overload import brownout_level

        level = brownout_level()
        if level < 1:
            raise AssertionError(
                f"brownout level {level} under injected pressure")

    cell("mem_pressure", "mem_pressure:frac=0.97", mem_pressure_drill)

    def drift_drill():
        from orange3_spark_tpu.online.drift import (
            DriftDetector, feature_stats,
        )

        det = DriftDetector(feature_stats(X), z_threshold=6.0)
        shift = active_fault_spec().take_drift_shift(0)
        det.check_features(X[:chunk_rows] + np.float32(shift))

    cell("drift", "drift:shift=8", drift_drill,
         expect=DriftDetectedError)

    def label_skew_drill():
        import zlib

        mask = active_fault_spec().take_label_flip(0, 512)
        mask2 = [
            zlib.crc32(f"0:0:{r}".encode()) / 0xFFFFFFFF < 0.5
            for r in range(512)
        ]
        frac = sum(mask) / len(mask)
        if mask != mask2 or not 0.3 < frac < 0.7:
            raise AssertionError(
                f"label flip mask not the seeded coin (frac {frac})")

    cell("label_skew", "label_skew:flip=0.5,seed=0", label_skew_drill)

    def trainer_crash_drill():
        # the take-hook drives the REAL trainer's per-step check; here it
        # is probed directly so the matrix stays sub-second
        if active_fault_spec().take_trainer_crash():
            raise TrainerCrashInjected("injected trainer crash at step 1")

    cell("trainer_crash", "trainer_crash:at=1", trainer_crash_drill,
         expect=TrainerCrashInjected)
    return rows_out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=16384)
    args = ap.parse_args()
    sys.path.insert(0, REPO)
    results = run_matrix(rows=args.rows)
    w = max(len(r["cell"]) for r in results)
    print(f"{'cell':<{w}}  {'outcome':<28} {'retries':>7} "
          f"{'faults':>6} {'wall_s':>7} {'overhead%':>9}", file=sys.stderr)
    for r in results:
        print(f"{r['cell']:<{w}}  {r['outcome']:<28} {r['retries']:>7} "
              f"{r['faults_injected']:>6} {r['wall_s']:>7.3f} "
              f"{r['overhead_pct']:>9.1f}", file=sys.stderr)
    bad = [r for r in results if r["outcome"].startswith("UNEXPECTED")]
    print(json.dumps({
        "metric": "fault_matrix",
        "value": len(results),
        "unit": "cells_run",
        "vs_baseline": None,
        "cells_ok": len(results) - len(bad),
        "cells": results,
    }))
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()
