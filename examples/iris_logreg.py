"""Smallest end-to-end example: Iris → LogisticRegression → metrics.

Run:  PYTHONPATH=.:$PYTHONPATH python examples/iris_logreg.py
(CPU works; on a TPU host the same script runs unchanged.)
"""

import numpy as np

import orange3_spark_tpu as otpu
from orange3_spark_tpu.datasets import load_iris
from orange3_spark_tpu.models.evaluation import MulticlassClassificationEvaluator
from orange3_spark_tpu.models.logistic_regression import LogisticRegression


def main() -> None:
    sess = otpu.TpuSession.builder_get_or_create()
    iris = load_iris(sess)

    model = LogisticRegression(max_iter=200, reg_param=1e-4).fit(iris)
    scored = model.transform(iris)

    acc = MulticlassClassificationEvaluator(metric_name="accuracy").evaluate(scored)
    f1 = MulticlassClassificationEvaluator(metric_name="f1").evaluate(scored)
    print(f"n_iter={model.n_iter_}  accuracy={acc:.3f}  f1={f1:.3f}")
    assert acc > 0.9
    print("head of scored table:")
    print(np.round(scored.head(3), 3))


if __name__ == "__main__":
    main()
