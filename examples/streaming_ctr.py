"""Criteo-scale streaming CTR fit on one chip — the BASELINE config-2
pipeline at example scale: CSV on disk → native C++ parse → device DMA →
hashed-sparse minibatch steps → HBM-cached fused replay → on-device eval.

Run:  PYTHONPATH=.:$PYTHONPATH python examples/streaming_ctr.py
"""

import os
import tempfile

import numpy as np

import orange3_spark_tpu as otpu
from orange3_spark_tpu.io.streaming import csv_raw_chunk_source
from orange3_spark_tpu.models.hashed_linear import StreamingHashedLinearEstimator

N_ROWS, N_DENSE, N_CAT = 200_000, 5, 8


def write_csv(path: str) -> None:
    rng = np.random.default_rng(0)
    eff = rng.normal(0, 0.8, (N_CAT, 64)).astype(np.float32)
    dense = rng.lognormal(0, 1, (N_ROWS, N_DENSE)).astype(np.float32)
    cats = rng.integers(0, 5000, (N_ROWS, N_CAT))
    logit = 0.1 * dense.sum(1) + eff[np.arange(N_CAT), cats % 64].sum(1) - 2.0
    y = (rng.random(N_ROWS) < 1 / (1 + np.exp(-logit))).astype(np.int32)
    cols = [y] + [dense[:, j] for j in range(N_DENSE)] \
        + [cats[:, j] for j in range(N_CAT)]
    header = ",".join(["label"] + [f"i{j}" for j in range(N_DENSE)]
                      + [f"c{j}" for j in range(N_CAT)])
    np.savetxt(path, np.column_stack(cols), delimiter=",", header=header,
               comments="", fmt="%.6g")


def main() -> None:
    otpu.TpuSession.builder_get_or_create()
    # regenerate every run, atomically (a killed prior run must not leave
    # a truncated file that poisons later runs)
    path = os.path.join(tempfile.gettempdir(), "example_ctr.csv")
    tmp = path + f".tmp{os.getpid()}"
    write_csv(tmp)
    os.replace(tmp, path)

    est = StreamingHashedLinearEstimator(
        n_dims=1 << 18, n_dense=N_DENSE, n_cat=N_CAT, epochs=8,
        chunk_rows=1 << 15, label_in_chunk=True, step_size=0.05,
        # defer_epoch1: the streaming pass is pure ingest and ALL epochs
        # train inside the fused replay program — bit-identical to the
        # interleaved schedule, but zero per-chunk step dispatches (each
        # costs ~an RTT on tunneled hosts). replay_granularity='epoch'
        # (one dispatch per epoch) additionally composes with a
        # StreamCheckpointer for kill-and-resume at epoch boundaries.
        defer_epoch1=True,
    )
    model = est.fit_stream(
        csv_raw_chunk_source(path, chunk_rows=1 << 15),
        cache_device=True,      # Spark's persist(): epochs 2+ replay HBM
        holdout_chunks=1,
    )
    ev = model.evaluate_device(model.holdout_chunks_)
    print(f"steps={model.n_steps_}  holdout: logloss={ev['logloss']:.3f} "
          f"acc={ev['accuracy']:.3f} auc={ev['auc']:.3f}")
    assert ev["auc"] > 0.65


if __name__ == "__main__":
    main()
