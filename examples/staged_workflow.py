"""Whole-workflow staging: an Orange-style widget DAG fused into ONE
jitted XLA program, with estimator fits INSIDE the trace (refit=True).

Builds  source → StandardScaler → PCA → KMeans,  stages it, and re-fits
+ re-scores the entire pipeline on NEW data in one dispatch.

Run:  PYTHONPATH=.:$PYTHONPATH python examples/staged_workflow.py
"""

import numpy as np

import orange3_spark_tpu as otpu
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.widgets.catalog import WIDGET_REGISTRY, OWTable
from orange3_spark_tpu.workflow.graph import WorkflowGraph
from orange3_spark_tpu.workflow.staging import stage_graph


def make_table(sess, seed: int) -> TpuTable:
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 6, (3, 8))
    labels = rng.integers(0, 3, 6000)
    X = centers[labels] + rng.normal(0, 1, (6000, 8))
    return TpuTable.from_arrays(X.astype(np.float32), session=sess)


def main() -> None:
    sess = otpu.TpuSession.builder_get_or_create()
    table = make_table(sess, seed=0)

    g = WorkflowGraph()
    src = g.add(OWTable(table))
    scale = g.add(WIDGET_REGISTRY["OWStandardScaler"](with_mean=True))
    pca = g.add(WIDGET_REGISTRY["OWPCA"](k=3))
    km = g.add(WIDGET_REGISTRY["OWKMeans"](k=3, seed=1))
    g.connect(src, "data", scale, "data")
    g.connect(scale, "data", pca, "data")
    g.connect(pca, "data", km, "data")

    staged = stage_graph(g, km, refit=True)
    print("non-stageable frontier:",
          [f["widget"] for f in staged.frontier] or "none",
          "| refit fallbacks:", staged.refit_fallbacks or "none")

    out1 = staged()
    # swap the source: the WHOLE pipeline re-fits on the new table in one
    # XLA dispatch — scaler stats, PCA basis, KMeans centers, all inside
    new_table = make_table(sess, seed=7)
    out2 = staged(replacements={src: new_table})
    for tag, out in (("original", out1), ("replaced", out2)):
        pred = np.asarray(out.column("cluster"))[: len(out)]
        sizes = np.bincount(pred.astype(int), minlength=3)
        print(f"{tag}: cluster sizes {sizes.tolist()}")
        assert min(sizes) > 500  # three real clusters were found

    # a picture of what just ran (workflow/render.py — no Qt, no graphviz)
    from orange3_spark_tpu.workflow.render import save_workflow_view

    save_workflow_view(g, "/tmp/staged_workflow.html",
                       title="staged_workflow example")
    print("workflow view written to /tmp/staged_workflow.html")


if __name__ == "__main__":
    main()
