"""Headless widget protocol — the OWSpark* widget layer without Qt.

The reference's widgets are Orange OWWidget subclasses: declared input/output
signals, GUI-bound settings, and a handler that fires when inputs arrive
(SURVEY.md §2 layer 4; reconstructed, mount empty). The redesign keeps
exactly the signal semantics — named, typed input/output ports consumed by a
signal manager — and drops the GUI: settings are the estimator's frozen
params dataclass (the same introspection surface a GUI would bind to), and
``process()`` is a pure function of (inputs, settings) returning its output
signals. That purity is what lets the workflow graph stage the whole data
path into one XLA computation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from orange3_spark_tpu.models.base import Params


@dataclasses.dataclass(frozen=True)
class Input:
    name: str
    type: type | None = None
    required: bool = True


@dataclasses.dataclass(frozen=True)
class Output:
    name: str
    type: type | None = None


class Widget:
    """Base headless widget. Subclasses declare:

    * ``name``     — registry key (stable across serialization)
    * ``inputs``   — tuple[Input, ...]
    * ``outputs``  — tuple[Output, ...]
    * ``ParamsCls``— settings dataclass (may be plain ``Params`` for none)
    * ``process(**inputs) -> dict[output_name, value]``
    """

    name: str = "widget"
    inputs: tuple[Input, ...] = ()
    outputs: tuple[Output, ...] = ()
    ParamsCls: type[Params] = Params

    def __init__(self, params: Params | None = None, **kwargs):
        if params is None:
            params = self.ParamsCls(**kwargs)
        elif kwargs:
            params = params.replace(**kwargs)
        self.params = params

    # ------------------------------------------------------------ protocol
    def process(self, **inputs) -> dict[str, Any]:
        raise NotImplementedError

    def input_names(self) -> list[str]:
        return [i.name for i in self.inputs]

    def output_names(self) -> list[str]:
        return [o.name for o in self.outputs]

    # -------------------------------------------------------- serialization
    def settings_dict(self) -> dict[str, Any]:
        return self.params.to_dict()

    @classmethod
    def from_settings(cls, settings: dict[str, Any]) -> "Widget":
        # tuples serialize as lists in JSON; coerce back by field type
        kwargs = {}
        fields = {f.name: f for f in dataclasses.fields(cls.ParamsCls)}
        for k, v in settings.items():
            if k not in fields:
                continue
            if isinstance(v, list):
                v = tuple(v)
            kwargs[k] = v
        return cls(cls.ParamsCls(**kwargs))

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} {self.params}>"


class FunctionWidget(Widget):
    """Wrap a plain callable as a single-output widget (ad-hoc nodes)."""

    def __init__(self, fn: Callable[..., Any], name: str = "function",
                 inputs: tuple[Input, ...] = (Input("data"),),
                 outputs: tuple[Output, ...] = (Output("data"),)):
        super().__init__(Params())
        self.fn = fn
        self.name = name
        self.inputs = inputs
        self.outputs = outputs

    def process(self, **kw) -> dict[str, Any]:
        result = self.fn(**kw)
        if not isinstance(result, dict):
            result = {self.outputs[0].name: result}
        return result
