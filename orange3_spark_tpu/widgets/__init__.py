from orange3_spark_tpu.widgets.base import Input, Output, Widget
from orange3_spark_tpu.widgets.catalog import WIDGET_REGISTRY, widget_for_estimator

__all__ = ["Input", "Output", "Widget", "WIDGET_REGISTRY", "widget_for_estimator"]
