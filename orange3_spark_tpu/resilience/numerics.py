"""Non-finite training guard — typed divergence instead of silent NaN.

A too-hot step size (or a single Inf cell in a billion-row stream) turns
a streaming fit into a NaN factory that trains to completion and ships a
useless model — the failure is silent until evaluation. The guard is one
cheap check per EPOCH (never per step — a per-step host sync would
serialize the async dispatch pipeline): the epoch's last loss scalar,
falling back to a single fused all-finite reduction over theta when no
loss exists (pure-ingest defer passes, k-means centers). A non-finite
value raises :class:`NumericalDivergenceError` naming the epoch and
chunk ordinal, ticks ``otpu_divergence_total`` and lands an instant on
the obs timeline. Inert under ``OTPU_RESILIENCE=0`` (the legacy
train-to-NaN behavior, read per call)."""

from __future__ import annotations

import math

from orange3_spark_tpu.obs.registry import REGISTRY
from orange3_spark_tpu.resilience.faults import resilience_enabled

__all__ = ["NumericalDivergenceError", "check_finite_training"]

_M_DIVERGENCE = REGISTRY.counter(
    "otpu_divergence_total",
    "streaming fits aborted by the non-finite training guard")


class NumericalDivergenceError(FloatingPointError):
    """Training state went non-finite. ``what`` names the tripping value
    ('loss' or 'theta'), ``epoch``/``chunk`` locate it in the stream,
    ``trace_id`` names the fit's run id (obs/context.py)."""

    def __init__(self, *, what: str, epoch: int, chunk: int,
                 estimator: str = "", trace_id: str | None = None):
        self.what = what
        self.epoch = epoch
        self.chunk = chunk
        self.estimator = estimator
        self.trace_id = trace_id
        who = f"{estimator} " if estimator else ""
        tr = f" [trace {trace_id}]" if trace_id else ""
        super().__init__(
            f"{who}training diverged: non-finite {what} at epoch {epoch}, "
            f"chunk ordinal {chunk}{tr}. Lower step_size / raise "
            "reg_param, or check the stream for Inf/NaN features. "
            "OTPU_RESILIENCE=0 restores the legacy silent-NaN behavior."
        )


def _tree_finite(tree) -> bool:
    # sum-of-sums: any Inf/NaN leaf poisons the total (+Inf + -Inf = NaN,
    # so cancellation cannot hide it); one tiny reduction dispatch per
    # leaf per epoch, synced once at the float()
    import jax.numpy as jnp
    from jax import tree as jtree

    total = 0.0
    for leaf in jtree.leaves(tree):
        total += float(jnp.sum(jnp.asarray(leaf)))
        if not math.isfinite(total):
            return False
    return True


def check_finite_training(loss=None, theta=None, *, epoch: int, chunk: int,
                          estimator: str = "", final: bool = False) -> None:
    """The per-epoch guard every streaming fit loop calls at its epoch
    boundary. Prefers the (already-materializing) loss scalar; checks
    ``theta`` only when no loss exists for the epoch — EXCEPT on the
    fit's ``final`` check, which always sweeps theta too: the step's
    loss is computed from theta BEFORE its update, so a last-step
    divergence leaves a finite loss and only theta carries the NaN (one
    extra reduction per fit, not per epoch). No-op under the
    kill-switch."""
    if not resilience_enabled():
        return
    what = None
    if loss is not None and not math.isfinite(float(loss)):
        what = "loss"
    elif (theta is not None and (loss is None or final)
            and not _tree_finite(theta)):
        what = "theta"
    if what is None:
        return
    _M_DIVERGENCE.inc()
    from orange3_spark_tpu.obs import trace as _trace
    from orange3_spark_tpu.obs.context import (
        current_trace_id, flag_current_trace,
    )

    _trace.instant("divergence", what=what, epoch=epoch, chunk=chunk)
    flag_current_trace()
    err = NumericalDivergenceError(
        what=what, epoch=epoch, chunk=chunk, estimator=estimator,
        trace_id=current_trace_id())
    # black box (obs/flight.py): the fit's spans, registry state and knob
    # table at the moment of divergence — BEFORE any checkpoint/caller
    # cleanup can disturb them
    from orange3_spark_tpu.obs.flight import auto_dump

    auto_dump("divergence", err)
    raise err
