"""Retry policy: exponential backoff + jitter + max-attempts.

Two call shapes, both no-ops under the ``OTPU_RESILIENCE=0`` kill-switch:

* ``retry_call(fn, cause=...)`` — bounded retries of an idempotent
  callable (the serving ``ExecutableCache`` wraps its AOT builds in it).
* ``resilient_source(source)`` — wraps a re-iterable zero-arg chunk-source
  factory: every streaming fit routes its source through this ONE
  chokepoint at fit entry, so transient read errors (NFS blip, injected
  ``source_io`` fault) are absorbed by re-opening the source and
  fast-forwarding to the failed chunk instead of killing a 100-epoch fit.
  Sources are re-iterable by the streaming contract (epochs restart them),
  which is exactly what makes the re-open + skip recovery sound — the
  replayed prefix is bit-identical, so a recovered fit matches the
  fault-free fit bitwise (pinned in tests/test_resilience.py).

Backoff: ``delay(i) = min(base * multiplier**i, max) * (1 + jitter * u)``
with ``u`` a deterministic per-(seed, i) uniform — seeded jitter keeps the
schedule test-pinnable while still decorrelating real fleet retries.
Every retry ticks a per-cause counter in
``utils.profiling.resilience_counters()`` and, when a ``PipelineStats`` is
threaded in, ``stats.retries`` — the bench's ``retries`` field.
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Callable, Iterator

from orange3_spark_tpu.resilience.faults import (
    active_fault_spec,
    resilience_enabled,
)

__all__ = [
    "RetryPolicy",
    "is_transient",
    "resilient_source",
    "retry_call",
]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule knobs (env twins: OTPU_RETRY_*)."""

    max_attempts: int = 4        # total tries (1 first + 3 retries)
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25         # + up to this fraction of the delay
    seed: int = 0                # deterministic jitter stream

    @classmethod
    def from_env(cls, **overrides) -> "RetryPolicy":
        from orange3_spark_tpu.utils import knobs

        kw = dict(
            max_attempts=knobs.get_int("OTPU_RETRY_ATTEMPTS"),
            base_delay_s=knobs.get_float("OTPU_RETRY_BASE_S"),
            max_delay_s=knobs.get_float("OTPU_RETRY_MAX_S"),
            multiplier=knobs.get_float("OTPU_RETRY_MULTIPLIER"),
            jitter=knobs.get_float("OTPU_RETRY_JITTER"),
        )
        kw.update(overrides)
        return cls(**kw)

    def delay(self, retry_index: int) -> float:
        """Seconds to wait before retry ``retry_index`` (0-based)."""
        d = min(self.base_delay_s * self.multiplier ** retry_index,
                self.max_delay_s)
        if self.jitter > 0:
            u = zlib.crc32(
                f"{self.seed}:{retry_index}".encode()) / 0xFFFFFFFF
            d *= 1.0 + self.jitter * u
        return d


def is_transient(exc: BaseException) -> bool:
    """The retry classifier — deliberately conservative: OS-level I/O
    errors (which the injected ``TransientSourceError`` subclasses) and
    runtime errors carrying the grpc-style transient status words (the
    round-4 tunnel fault surfaced as ``UNAVAILABLE``). Everything else —
    shape mismatches, bad labels, corruption, and the PERMANENT OSError
    family (a mistyped path will not appear on retry 3) — must fail
    fast."""
    if isinstance(exc, (FileNotFoundError, PermissionError,
                        IsADirectoryError, NotADirectoryError)):
        return False
    if isinstance(exc, OSError):
        return True
    from orange3_spark_tpu.resilience.faults import TransientBuildError

    if isinstance(exc, TransientBuildError):
        return True
    msg = f"{type(exc).__name__}: {exc}"
    return "UNAVAILABLE" in msg or "DEADLINE_EXCEEDED" in msg


def _record(cause: str, wait_s: float, stats) -> None:
    from orange3_spark_tpu.utils.profiling import record_retry

    record_retry(cause, wait_s)
    if stats is not None:
        stats.retries += 1


def retry_call(fn: Callable, *, cause: str, policy: RetryPolicy | None = None,
               sleep: Callable[[float], None] = time.sleep,
               classify: Callable = is_transient, stats=None):
    """``fn()`` with bounded transient-error retries. Fail-fast (one
    attempt, no classification) under the kill-switch."""
    if not resilience_enabled():
        return fn()
    policy = policy or RetryPolicy.from_env()
    retries = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - classified below
            if not classify(e) or retries + 1 >= policy.max_attempts:
                raise
            d = policy.delay(retries)
            _record(cause, d, stats)
            retries += 1
            sleep(d)


def _injected(source: Callable[[], Iterator]) -> Callable[[], Iterator]:
    """Wrap a source factory with the fault-injection layer (active
    regardless of the kill-switch — injection is the test driver)."""

    def opener():
        for ordinal, chunk in enumerate(source()):
            spec = active_fault_spec()
            if spec is not None:
                spec.on_source_chunk(ordinal)
            yield chunk

    return opener


def resilient_source(source: Callable[[], Iterator], *,
                     policy: RetryPolicy | None = None, stats=None,
                     sleep: Callable[[float], None] = time.sleep,
                     ) -> Callable[[], Iterator]:
    """THE source chokepoint: every streaming fit wraps its chunk-source
    factory here at fit entry. Returns a factory with the same re-iterable
    zero-arg contract. Recovery protocol on a transient read error at
    chunk i: close the broken iterator, back off per the policy, re-open
    the source and fast-forward the i already-delivered chunks, then
    resume — the consumer sees an uninterrupted, identical stream.
    ``max_attempts`` bounds consecutive failures while repositioning on
    one chunk; a successful yield resets the count. Under the
    kill-switch the stream is injection-wrapped but fail-fast."""
    spec = active_fault_spec()
    if spec is None and not resilience_enabled():
        return source
    injected = _injected(source)
    if not resilience_enabled():
        return injected

    def opener():
        pol = policy or RetryPolicy.from_env()

        def skipping(start: int) -> Iterator:
            it = injected()
            for i, chunk in enumerate(it):
                if i >= start:
                    yield chunk

        ordinal = 0
        failures = 0
        it = None
        while True:
            if it is None:
                it = skipping(ordinal)
            try:
                chunk = next(it)
            except StopIteration:
                return
            except Exception as e:  # noqa: BLE001 - classified below
                if not is_transient(e):
                    raise
                failures += 1
                if failures >= pol.max_attempts:
                    raise
                d = pol.delay(failures - 1)
                _record("source", d, stats)
                try:
                    it.close()
                except Exception:  # noqa: BLE001 - already broken
                    pass
                it = None
                sleep(d)
                continue
            yield chunk
            ordinal += 1
            failures = 0

    return opener
