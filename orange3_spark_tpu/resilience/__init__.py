"""resilience/ — fault injection, bounded retries, dispatch watchdog,
crash-resumable fits (docs/resilience.md).

Spark's real production moat is not throughput, it is that a 100-epoch job
survives a flaky executor (RDD lineage recompute, straggler re-launch —
PAPERS.md: Zaharia et al.; Dean & Barroso tail-tolerance). This repo's own
round history shows the opposite failure mode: wedged tunnels killing bench
runs at rc=124, aborted mid-epoch fits, whole rounds lost to hangs. This
package makes every long-running path survive *injected* faults with
measured, bounded overhead:

* ``faults``   — deterministic, seedable injectors (transient chunk-source
  IOErrors, straggler chunks, corrupted spill records, wedged dispatches,
  flaky AOT builds), activated programmatically via ``inject_faults(...)``
  or process-wide via ``OTPU_FAULT_SPEC`` so the same tier-1 tests and
  bench arms drive them.
* ``retry``    — exponential backoff + jitter + max-attempts, applied to
  chunk-source reads (``resilient_source`` wraps every streaming fit's
  source at entry) and to ``ExecutableCache`` AOT builds. Per-cause
  counters land in ``utils.profiling.resilience_counters()`` and
  ``exec.PipelineStats.retries``.
* ``watchdog`` — budget-bounded device syncs: a dispatch that exceeds
  ``OTPU_DISPATCH_BUDGET_S`` raises a typed ``DispatchWedgedError``
  carrying stage/step/beat diagnostics instead of hanging the process
  forever (the round-4 tunnel-wedge signature).
* ``overload`` — overload protection & graceful degradation: admission
  control with projected-wait shedding (``OverloadShedError``), the
  closed/open/half-open ``CircuitBreaker`` (replacing the serving
  first-failure blacklist and fast-failing repeated wedges), adaptive
  micro-batch coalescing, and memory-pressure brownout watermarks
  feeding the ``_DeviceCache`` degrade ladder.
* ``numerics`` — the per-epoch non-finite training guard
  (``NumericalDivergenceError`` naming epoch and chunk ordinal instead
  of silently training to NaN).

Crash-resumable fits: ``checkpoint_every_epochs`` on
``StreamingLinearParams``/``HashedLinearParams`` snapshots training state
atomically at epoch boundaries (``utils.fault.StreamCheckpointer``,
write-to-temp + rename), so a fit SIGKILLed mid-epoch resumes at the last
boundary and converges to the uninterrupted result.

Kill-switch: ``OTPU_RESILIENCE=0`` restores legacy fail-fast behavior
everywhere — no retries, no watchdog budget, no CRC verification, no
epoch-cadence snapshots. Fault *injection* stays active under the
kill-switch (the injectors are the test driver; the mitigations are what
the switch disables), which is what lets the acceptance tests demonstrate
that they FAIL without the subsystem.
"""

from __future__ import annotations

from orange3_spark_tpu.resilience.faults import (
    FaultSpec,
    TransientBuildError,
    TransientSourceError,
    active_fault_spec,
    inject_faults,
    resilience_enabled,
)
from orange3_spark_tpu.resilience.retry import (
    RetryPolicy,
    is_transient,
    resilient_source,
    retry_call,
)
from orange3_spark_tpu.resilience.numerics import (
    NumericalDivergenceError,
    check_finite_training,
)
from orange3_spark_tpu.resilience.overload import (
    AdaptiveCoalescer,
    AdmissionController,
    CircuitBreaker,
    OverloadShedError,
    brownout_level,
    request_deadline,
)
from orange3_spark_tpu.resilience.watchdog import (
    DispatchWedgedError,
    dispatch_budget_s,
    guarded_block_until_ready,
)
from orange3_spark_tpu.utils.fault import StreamCheckpointer

__all__ = [
    "AdaptiveCoalescer",
    "AdmissionController",
    "CircuitBreaker",
    "DispatchWedgedError",
    "FaultSpec",
    "NumericalDivergenceError",
    "OverloadShedError",
    "RetryPolicy",
    "StreamCheckpointer",
    "TransientBuildError",
    "TransientSourceError",
    "active_fault_spec",
    "brownout_level",
    "check_finite_training",
    "dispatch_budget_s",
    "guarded_block_until_ready",
    "inject_faults",
    "is_transient",
    "request_deadline",
    "resilience_enabled",
    "resilient_source",
    "retry_call",
]
