"""Dispatch watchdog — typed errors instead of infinite hangs.

The round-4 tunnel-wedge signature: a jitted step (or its periodic
``block_until_ready`` sync) simply never returns, and the whole harness
hangs until an outer ``timeout -k`` reaps it at rc=124 — losing the run
AND the diagnostics. Python cannot interrupt a blocked C call, so the
watchdog inverts the wait: the potentially-wedging sync runs on a daemon
monitor thread while the CALLING thread waits on it with a budget
(``OTPU_DISPATCH_BUDGET_S``). On budget exhaustion the caller raises a
typed ``DispatchWedgedError`` carrying stage timings and last-good-chunk
diagnostics (the ``utils.profiling`` exec counters + the liveness beat
age) and moves on — fall back, checkpoint, or exit cleanly; the abandoned
waiter thread parks harmlessly in the runtime. The budget is OFF by
default (0 = a long compile must never be misread as a wedge on a slow
host) and inert under the ``OTPU_RESILIENCE=0`` kill-switch.

``utils.dispatch.bound_dispatch`` routes every step loop's periodic sync
through ``maybe_guarded_block`` — one chokepoint, zero overhead when no
budget and no fault spec are active. The ``wedge`` fault kind
(resilience/faults.py) injects the never-returning dispatch here: the
monitor thread holds for ``hold_s`` before syncing, which under a budget
reproduces the hang signature deterministically and without a budget
degrades to a finite stall (legacy behavior, finitely simulated — tests
must be able to demonstrate the fail-fast ladder without hanging CI).
"""

from __future__ import annotations

import threading
import time

import jax

from orange3_spark_tpu.resilience.faults import (
    active_fault_spec,
    resilience_enabled,
)

__all__ = [
    "DispatchWedgedError",
    "dispatch_budget_s",
    "guarded_block_until_ready",
    "maybe_guarded_block",
]


class DispatchWedgedError(RuntimeError):
    """A device dispatch/sync exceeded its budget — the process would
    previously have hung forever. Carries the evidence a post-mortem
    needs: ``stage``/``step`` locate the wedge, ``budget_s``/``waited_s``
    quantify it, and ``diagnostics`` holds the last-good-progress
    counters (dispatches issued, chunks prefetched, seconds since the
    last liveness beat)."""

    def __init__(self, *, stage: str, step: int | None, budget_s: float,
                 waited_s: float, diagnostics: dict,
                 trace_id: str | None = None):
        self.stage = stage
        self.step = step
        self.budget_s = budget_s
        self.waited_s = waited_s
        self.diagnostics = diagnostics
        self.trace_id = trace_id
        at = f" at step {step}" if step is not None else ""
        if trace_id:
            at += f" [trace {trace_id}]"
        super().__init__(
            f"device dispatch wedged: {stage}{at} exceeded its "
            f"{budget_s:.3g}s budget (waited {waited_s:.3g}s; last "
            f"liveness beat {diagnostics.get('last_beat_age_s', '?')}s "
            f"ago, {diagnostics.get('dispatches', '?')} dispatches / "
            f"{diagnostics.get('prefetch_items', '?')} chunks completed "
            "before the wedge). The process is still alive — fall back, "
            "resume from the last checkpoint, or set "
            "OTPU_DISPATCH_BUDGET_S=0 to restore unbounded waits."
        )


def dispatch_budget_s() -> float:
    """Seconds a guarded sync may block (0 = watchdog disabled). Env
    ``OTPU_DISPATCH_BUDGET_S`` (utils/knobs.py — malformed values fall
    back to the declared 0 default); forced to 0 by the kill-switch."""
    if not resilience_enabled():
        return 0.0
    from orange3_spark_tpu.utils import knobs

    return float(knobs.get_float("OTPU_DISPATCH_BUDGET_S"))


def _diagnostics() -> dict:
    from orange3_spark_tpu.utils.dispatch import last_beat
    from orange3_spark_tpu.utils.profiling import exec_counters

    c = exec_counters()
    return {
        "last_beat_age_s": round(time.monotonic() - last_beat(), 3),
        "dispatches": c["dispatches"],
        "prefetch_items": c["prefetch_items"],
        "prefetch_prep_s": round(c["prefetch_prep_s"], 3),
        "prefetch_wait_s": round(c["prefetch_wait_s"], 3),
    }


def guarded_block_until_ready(token, *, step: int | None = None,
                              stage: str = "step",
                              budget_s: float | None = None):
    """``jax.block_until_ready(token)`` bounded by the watchdog budget.

    The sync runs on a daemon monitor thread; this thread waits up to the
    budget and raises ``DispatchWedgedError`` on exhaustion (the waiter is
    abandoned — it is blocked in the runtime and cannot be interrupted,
    but the PROCESS can now act). A worker-side exception re-raises here;
    an injected ``wedge`` hold is applied on the worker, so the budget
    clock genuinely races it."""
    spec = active_fault_spec()
    hold = spec.take_wedge() if spec is not None else None
    budget = dispatch_budget_s() if budget_s is None else (
        budget_s if resilience_enabled() else 0.0)
    if budget <= 0:
        # legacy unbounded wait; an injected wedge degrades to a finite
        # stall so the fail-fast ladder stays testable without hanging CI
        if hold is not None:
            time.sleep(hold)
        return jax.block_until_ready(token)
    # circuit breaker on repeated wedges (resilience/overload.py): once a
    # budgeted sync has wedged, later guarded syncs fast-fail typed in
    # ~0 s instead of each burning the full budget — until the breaker's
    # seeded cooldown admits a half-open probe sync, whose success
    # re-admits the backend automatically
    from orange3_spark_tpu.resilience.overload import wedge_breaker

    from orange3_spark_tpu.obs.context import (
        current_trace_id, flag_current_trace,
    )

    breaker = wedge_breaker()
    if not breaker.allow():
        diag = _diagnostics()
        diag["breaker_state"] = breaker.state()
        flag_current_trace()     # tail retention keeps the killed trace
        raise DispatchWedgedError(
            stage=stage, step=step, budget_s=budget, waited_s=0.0,
            diagnostics=diag, trace_id=current_trace_id(),
        )
    done = threading.Event()
    err: list = []

    def waiter():
        try:
            if hold is not None:
                time.sleep(hold)
            jax.block_until_ready(token)
        except BaseException as e:  # noqa: BLE001 - re-raised on caller
            err.append(e)
        finally:
            done.set()

    t0 = time.perf_counter()
    threading.Thread(target=waiter, daemon=True,
                     name="otpu-dispatch-waiter").start()
    if not done.wait(budget):
        from orange3_spark_tpu.utils.profiling import record_wedge

        record_wedge()
        breaker.record_failure()
        flag_current_trace()
        # a DISTINCT name: `err` is the waiter closure's result list, and
        # rebinding it here would turn the abandoned waiter's eventual
        # err.append(e) into an AttributeError on this exception object
        wedge_err = DispatchWedgedError(
            stage=stage, step=step, budget_s=budget,
            waited_s=time.perf_counter() - t0, diagnostics=_diagnostics(),
            trace_id=current_trace_id(),
        )
        # black box (obs/flight.py): the waiter thread is still parked in
        # the runtime RIGHT NOW, so the bundle's stacks catch it, and the
        # wedged dispatch span is still open on this thread
        from orange3_spark_tpu.obs.flight import auto_dump

        auto_dump("dispatch_wedged", wedge_err)
        raise wedge_err
    if err:
        raise err[0]
    breaker.record_success()
    return token


def maybe_guarded_block(token, *, step: int | None = None,
                        stage: str = "step"):
    """The ``bound_dispatch`` hook: plain ``block_until_ready`` when no
    budget and no fault spec are active (the common case — two dict
    lookups of overhead), the guarded path otherwise."""
    if active_fault_spec() is None and dispatch_budget_s() <= 0:
        return jax.block_until_ready(token)
    return guarded_block_until_ready(token, step=step, stage=stage)
