"""Overload protection & graceful degradation (docs/resilience.md).

The paper's Spark substrate survives overload by elastic cluster
scheduling — a swamped executor just makes the stage slower. A
single-process TPU runtime has no scheduler to lean on: unbounded queues
turn a traffic spike into unbounded p99, a process-lifetime blacklist is
the only serving failure ladder, and an over-budget fit dies on OOM.
This module is the missing control plane, four pieces:

* **AdmissionController** — bounded in-flight serving work with optional
  per-request deadline budgets. A request whose PROJECTED queue wait
  (queue depth x EWMA service time / parallelism) exceeds its deadline is
  shed *immediately* with a typed :class:`OverloadShedError` carrying the
  queue depth and wait estimate — never parked behind a queue it cannot
  clear. Deadlines resolve explicit arg > :func:`request_deadline`
  thread-local > ``OTPU_ADMISSION_DEADLINE_S`` (0 = none).
* **CircuitBreaker** — closed -> open -> half-open with a seeded probe
  cadence. Replaces the serving ``_unservable`` first-failure
  process-lifetime blacklist and guards repeated ``DispatchWedgedError``
  syncs: a transient bad spell stops costing work (open = fast-fail),
  but a recovered backend is re-admitted automatically (half-open probe
  succeeds -> closed). Under ``OTPU_RESILIENCE=0`` the breaker IS the
  legacy latch: the first failure opens it and it never half-opens.
* **AdaptiveCoalescer** — the micro-batcher's wait/merge dial: sustained
  queue depth grows ``max_wait_ms`` and the merge target (never past the
  bucket ladder's top rung / ``OTPU_MB_MAX_WAIT_MS``), an idle queue
  shrinks both back to their configured base.
* **BrownoutMonitor** (:func:`brownout_level`) — memory-pressure
  watermarks over host RSS (``OTPU_MEM_BUDGET_MB``) and the injected
  ``mem_pressure`` fault fraction. The level feeds the ``_DeviceCache``
  brownout ladder during fits: 1 = shrink chunk admission (half the HBM
  budget), 2 = stop admitting (force the disk spill / re-stream path),
  3 = degrade the HBM replay cache entirely — a typed, measured degrade
  instead of an opaque OOM.

Everything is deterministic-testable through the ``overload`` and
``mem_pressure`` fault injectors (resilience/faults.py) and inert under
the ``OTPU_RESILIENCE=0`` kill-switch (legacy unbounded queues, the
first-failure latch, fixed micro-batch wait, no brownout). Breaker
state, queue depth, shed counts and the brownout level all export
through the obs registry (``otpu_shed_total{reason=}``,
``otpu_breaker_state{name=}``, ``otpu_admission_inflight``,
``otpu_brownout_level``) and ``/healthz`` reports the brownout level.
"""

from __future__ import annotations

import logging
import math
import threading
import time
import zlib
from contextlib import contextmanager

from orange3_spark_tpu.obs.registry import REGISTRY
from orange3_spark_tpu.resilience.faults import (
    active_fault_spec,
    resilience_enabled,
)

__all__ = [
    "AdaptiveCoalescer",
    "AdmissionController",
    "CircuitBreaker",
    "OverloadShedError",
    "brownout_level",
    "host_rss_bytes",
    "maybe_injected_service_delay",
    "request_deadline",
    "reset_wedge_breaker",
    "shed_total",
    "wedge_breaker",
]

log = logging.getLogger("orange3_spark_tpu")

_M_SHED = REGISTRY.counter(
    "otpu_shed_total",
    "requests shed by admission control, by reason")
_M_INFLIGHT = REGISTRY.gauge(
    "otpu_admission_inflight",
    "serving dispatches currently holding an admission slot")
_M_QUEUE_DEPTH = REGISTRY.gauge(
    "otpu_admission_queue_depth",
    "callers waiting on an admission slot")
_M_BREAKER_STATE = REGISTRY.gauge(
    "otpu_breaker_state",
    "circuit-breaker state by name (0=closed, 1=half-open, 2=open)")
_M_MB_ADAPT = REGISTRY.gauge(
    "otpu_mb_adapt_factor",
    "adaptive micro-batch wait/merge growth factor (1.0 = base)")
_M_BROWNOUT = REGISTRY.gauge(
    "otpu_brownout_level",
    "memory-pressure brownout level (0=normal, 1=shrink chunk admission, "
    "2=force spill, 3=degrade HBM replay cache)")


# --------------------------------------------------------------- shedding
class OverloadShedError(RuntimeError):
    """A serving request was shed by admission control instead of being
    queued past its deadline (or past the hard queue bound). Carries the
    live evidence — ``queue_depth``, ``inflight``, ``est_wait_s``,
    ``deadline_s``, the request's ``trace_id`` (obs/context.py) and a
    ``diagnostics`` dict (breaker states when the owning context provides
    them) — so a shed in production logs is self-explaining."""

    def __init__(self, *, reason: str, queue_depth: int, inflight: int,
                 est_wait_s: float, deadline_s: float | None,
                 diagnostics: dict | None = None,
                 trace_id: str | None = None):
        self.reason = reason
        self.queue_depth = queue_depth
        self.inflight = inflight
        self.est_wait_s = est_wait_s
        self.deadline_s = deadline_s
        self.diagnostics = diagnostics or {}
        self.trace_id = trace_id
        dl = (f"{deadline_s:.3g}s deadline" if deadline_s is not None
              else "no deadline")
        extra = (f"; {self.diagnostics}" if self.diagnostics else "")
        if trace_id:
            extra = f" [trace {trace_id}]" + extra
        super().__init__(
            f"request shed ({reason}): projected queue wait "
            f"{est_wait_s:.3g}s vs {dl} at queue depth {queue_depth} "
            f"with {inflight} in flight{extra}. Raise "
            "OTPU_ADMISSION_MAX_INFLIGHT / the request deadline to admit "
            "more, or OTPU_RESILIENCE=0 to restore legacy unbounded "
            "queueing."
        )


def _record_shed(reason: str) -> None:
    _M_SHED.inc(1, reason=reason)
    from orange3_spark_tpu.obs import trace as _trace

    _trace.instant("shed", reason=reason)


def shed_total() -> int:
    """Total requests shed by admission control (all reasons)."""
    return int(_M_SHED.total())


# per-thread request deadline budget (the caller-facing knob an endpoint
# wrapper sets around its predicts); explicit args and this both outrank
# the OTPU_ADMISSION_DEADLINE_S process default
_TLS = threading.local()


@contextmanager
def request_deadline(seconds: float | None):
    """Scope a per-request deadline budget over a block of serve calls::

        with request_deadline(0.050):
            model.predict(batch)    # shed if projected wait > 50 ms

    ``None`` restores "no per-request deadline" inside an outer scope."""
    prev = getattr(_TLS, "deadline_s", None)
    _TLS.deadline_s = seconds
    try:
        yield
    finally:
        _TLS.deadline_s = prev


def _ambient_deadline_s() -> float | None:
    d = getattr(_TLS, "deadline_s", None)
    if d is not None:
        return float(d)
    from orange3_spark_tpu.utils import knobs

    d = float(knobs.get_float("OTPU_ADMISSION_DEADLINE_S"))
    return d if d > 0 else None


# ---------------------------------------------------- admission control
class AdmissionController:
    """Bounded in-flight serving work + projected-wait shedding.

    ``slot()`` brackets one device dispatch: at most ``max_inflight``
    callers hold a slot; a caller that would wait past its deadline (or
    that finds ``max_queue`` callers already waiting) is shed with a
    typed :class:`OverloadShedError` instead of queueing. ``check_queue``
    is the slotless variant the micro-batcher's ``submit`` uses against
    its own queue depth. Service time is an EWMA fed by every released
    slot (``observe_service``), seeded/floored by
    ``OTPU_ADMISSION_SERVICE_MS`` so the first burst after a cold start
    is not admitted on a zero estimate. A no-op (legacy unbounded) under
    ``OTPU_RESILIENCE=0`` or ``max_inflight <= 0``."""

    def __init__(self, *, max_inflight: int | None = None,
                 max_queue: int | None = None,
                 clock=time.monotonic):
        from orange3_spark_tpu.utils import knobs

        self.max_inflight = int(
            max_inflight if max_inflight is not None
            else knobs.get_int("OTPU_ADMISSION_MAX_INFLIGHT"))
        self.max_queue = int(
            max_queue if max_queue is not None
            else knobs.get_int("OTPU_ADMISSION_MAX_QUEUE"))
        self._clock = clock
        self._cv = threading.Condition()
        self._inflight = 0
        self._waiters = 0
        self._ewma_s = 0.0
        # the owning context may attach a richer diagnostics provider
        # (breaker states) that shed errors carry
        self.diagnostics_hook = None
        # weighted-fair tenancy state (serve/tenancy.py), built lazily on
        # the first tenant-scoped acquire; None = anonymous single-tenant
        # admission, bitwise the pre-tenancy behavior
        self._fair_share = None

    # ------------------------------------------------------------ state
    def enabled(self) -> bool:
        return resilience_enabled() and self.max_inflight > 0

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def queue_depth(self) -> int:
        return self._waiters

    def observe_service(self, dt_s: float) -> None:
        """Fold one completed dispatch's wall seconds into the EWMA."""
        with self._cv:
            self._ewma_s = (dt_s if self._ewma_s == 0.0
                            else 0.8 * self._ewma_s + 0.2 * dt_s)

    def service_estimate_s(self) -> float:
        from orange3_spark_tpu.utils import knobs

        floor = float(knobs.get_float("OTPU_ADMISSION_SERVICE_MS")) / 1e3
        return max(self._ewma_s, floor)

    def estimate_wait_s(self, queue_depth: int,
                        parallelism: int | None = None) -> float:
        """Projected wait for a request arriving behind ``queue_depth``
        others: depth x EWMA service / parallelism (default: the
        in-flight bound; the single-worker micro-batcher passes 1). An
        estimate for shedding decisions, not a promise."""
        par = parallelism if parallelism is not None else self.max_inflight
        return queue_depth * self.service_estimate_s() / max(par, 1)

    def _diag(self) -> dict:
        hook = self.diagnostics_hook
        if hook is None:
            return {}
        try:
            return dict(hook())
        except Exception:  # noqa: BLE001 - diagnostics must never mask
            return {}

    def _shed(self, reason: str, queue_depth: int, est: float,
              deadline_s: float | None):
        from orange3_spark_tpu.obs.context import (
            current_trace_id, flag_current_trace,
        )

        _record_shed(reason)
        # tail retention keeps the shed trace whole in the ring. The
        # flight-recorder dump happens at the PUBLIC entry points
        # (_dump_shed), outside the admission condition variable —
        # slot() sheds from inside `with self._cv:`, and a bundle write
        # (stacks + registry + disk IO) under that lock would stall
        # every other caller at exactly the moment of peak overload.
        flag_current_trace()
        raise OverloadShedError(
            reason=reason, queue_depth=queue_depth, inflight=self._inflight,
            est_wait_s=est, deadline_s=deadline_s, diagnostics=self._diag(),
            trace_id=current_trace_id())

    def _shed_tenant(self, tenant: str, reason: str, usage: float,
                     quota: float, d: float | None):
        """Typed per-tenant quota shed (cv held — same discipline as
        ``_shed``: the flight dump happens outside, in ``slot``)."""
        from orange3_spark_tpu.obs.context import (
            current_trace_id, flag_current_trace,
        )
        from orange3_spark_tpu.serve.tenancy import (
            TenantQuotaShedError, _record_tenant_shed,
        )

        _record_shed(reason)
        _record_tenant_shed(tenant, reason)
        flag_current_trace()
        raise TenantQuotaShedError(
            tenant=tenant, reason=reason, usage=usage, quota=quota,
            queue_depth=self._waiters, inflight=self._inflight,
            est_wait_s=self.estimate_wait_s(self._waiters),
            deadline_s=d, diagnostics=self._diag(),
            trace_id=current_trace_id())

    def _fair(self):
        """The weighted-fair tenancy state, (re)built when the
        ``OTPU_TENANT_SPEC`` arm changes (bench A/B flips it live).
        Callers hold the returned object for one acquire/release pair so
        a mid-flight rebuild never mismatches grant and release."""
        from orange3_spark_tpu.serve.tenancy import TenantFairShare
        from orange3_spark_tpu.utils import knobs

        raw = knobs.get_str("OTPU_TENANT_SPEC")
        fair = self._fair_share
        if fair is None or fair.spec_raw != raw:
            fair = TenantFairShare(clock=self._clock)
            self._fair_share = fair
        return fair

    def tenancy_snapshot(self) -> dict:
        """Live per-tenant fairness table ({} until a tenant-scoped
        request arrives) — the /fleetz and fleet_top surface."""
        fair = self._fair_share
        return fair.snapshot() if fair is not None else {}

    @staticmethod
    def _dump_shed(err: "OverloadShedError") -> None:
        """Black box (obs/flight.py): the first shed of an overload spell
        freezes queue depths/breakers/stacks; the rate limit keeps a shed
        storm from becoming an IO storm. Called with NO locks held."""
        from orange3_spark_tpu.obs.flight import auto_dump

        auto_dump("overload_shed", err)

    # ------------------------------------------------------- entrypoints
    def check_queue(self, queue_depth: int,
                    deadline_s: float | None = None,
                    parallelism: int = 1) -> None:
        """Slotless admission check against an EXTERNAL queue (the
        micro-batcher's — drained by ONE worker, hence the default
        parallelism of 1): sheds when the projected wait exceeds the
        request's deadline, or when the queue itself is past
        ``max_queue``. No-op when disabled or no deadline applies (the
        queue's own bound then sheds to direct dispatch, legacy-style —
        deadline-free callers must see no new exception type)."""
        if not self.enabled():
            return
        d = deadline_s if deadline_s is not None else _ambient_deadline_s()
        if d is None or math.isinf(d):
            return
        try:
            if queue_depth >= self.max_queue:
                self._shed("queue_full", queue_depth,
                           self.estimate_wait_s(queue_depth, parallelism), d)
            est = self.estimate_wait_s(queue_depth, parallelism)
            if est > d:
                self._shed("projected_wait", queue_depth, est, d)
        except OverloadShedError as e:
            self._dump_shed(e)
            raise

    @contextmanager
    def slot(self, deadline_s: float | None = None):
        """Hold one in-flight slot around a device dispatch. Sheds
        immediately on a hopeless projected wait, sheds on deadline
        expiry while waiting, and NEVER leaves a caller parked forever
        when a deadline applies."""
        if not self.enabled():
            yield
            return
        from orange3_spark_tpu.serve.tenancy import (
            current_tenant, tenancy_enabled,
        )

        tenant = current_tenant() if tenancy_enabled() else None
        fair = self._fair() if tenant is not None else None
        d = deadline_s if deadline_s is not None else _ambient_deadline_s()
        if d is None and fair is not None:
            # the tenant's declared default deadline applies only when
            # neither the call nor the ambient scope set one
            d = fair.tenant_deadline_s(tenant)
        if d is not None and math.isinf(d):
            d = None    # request_deadline(inf): admitted work (the mb
            #             worker) waits for a slot but is never shed
        try:
            self._acquire(d, tenant=tenant, fair=fair)
        except OverloadShedError as e:
            # the raise already released self._cv — the flight dump's
            # stack/registry/disk work must never run under it
            self._dump_shed(e)
            raise
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe_service(time.perf_counter() - t0)
            with self._cv:
                self._inflight -= 1
                _M_INFLIGHT.set(self._inflight)
                if self._fair_share is not None:
                    # tenant-gated waiters sit behind a DRR grant check:
                    # a single notify could wake a waiter the DRR head
                    # is NOT, which re-waits and swallows the wakeup —
                    # wake everyone and let may_grant() pick
                    if fair is not None:
                        fair.release(tenant)
                    self._cv.notify_all()
                else:
                    self._cv.notify()

    def _acquire(self, d: float | None, *, tenant: str | None = None,
                 fair=None) -> None:
        with self._cv:
            if fair is not None:
                quota = fair.try_admit(
                    tenant, max_inflight=self.max_inflight,
                    max_queue=self.max_queue)
                if quota is not None:
                    reason, usage, cap = quota
                    self._shed_tenant(tenant, reason, usage, cap, d)
            depth = self._waiters
            backlog = depth + max(self._inflight - self.max_inflight + 1, 0)
            # both sheds apply only to deadline-carrying requests — a
            # deadline-free legacy caller (and the mb worker flushing
            # ALREADY-admitted requests) must never see a new exception
            # type; it waits, bounded by the slot holders' progress
            if d is not None and depth >= self.max_queue:
                self._shed("queue_full", depth,
                           self.estimate_wait_s(depth), d)
            if d is not None and self._inflight >= self.max_inflight:
                est = self.estimate_wait_s(backlog)
                if est > d:
                    self._shed("projected_wait", depth, est, d)
            self._waiters += 1
            _M_QUEUE_DEPTH.set(self._waiters)
            if fair is not None:
                fair.note_waiting(tenant, +1)
            t_deadline = (self._clock() + d) if d is not None else None
            try:
                # the DRR gate only runs when a slot is actually free
                # (`or` short-circuits) and only against WAITING tenants,
                # so some waiter always passes — no gate deadlock
                while (self._inflight >= self.max_inflight
                       or (fair is not None
                           and not fair.may_grant(tenant))):
                    remaining = (t_deadline - self._clock()
                                 if t_deadline is not None else None)
                    if remaining is not None and remaining <= 0:
                        # we may have CONSUMED a release's single
                        # notify() to get here — pass it on, or another
                        # waiter (e.g. the deadline-free mb worker)
                        # sleeps forever on a slot that is actually free
                        if self._fair_share is not None:
                            self._cv.notify_all()
                        else:
                            self._cv.notify()
                        self._shed("deadline", self._waiters - 1,
                                   self.estimate_wait_s(self._waiters), d)
                    self._cv.wait(timeout=remaining)
            finally:
                self._waiters -= 1
                _M_QUEUE_DEPTH.set(self._waiters)
                if fair is not None:
                    fair.note_waiting(tenant, -1)
            self._inflight += 1
            _M_INFLIGHT.set(self._inflight)
            if fair is not None:
                fair.granted(tenant)


# ----------------------------------------------------- circuit breaker
_BREAKER_STATES = {"closed": 0, "half-open": 1, "open": 2}


class CircuitBreaker:
    """closed -> open -> half-open failure gate with a seeded probe
    cadence (docs/resilience.md).

    ``allow()`` answers "may this attempt proceed?": closed = yes;
    open = no until the cooldown elapses, at which point ONE probe is
    admitted (half-open); a probe success (``record_success``) after
    ``probe_successes`` closes the breaker, a probe failure re-opens it
    with the next cooldown. The cooldown carries deterministic seeded
    jitter (crc32 of (seed, open count) — the retry-policy convention)
    so fleet probes decorrelate while tests stay exactly pinnable.

    Under ``OTPU_RESILIENCE=0`` (read per call) the breaker reproduces
    the legacy first-failure process-lifetime latch: one failure opens
    it and ``allow()`` never half-opens."""

    def __init__(self, name: str = "", *,
                 failure_threshold: int | None = None,
                 cooldown_s: float | None = None,
                 probe_successes: int | None = None,
                 jitter: float = 0.25, seed: int = 0,
                 clock=time.monotonic):
        from orange3_spark_tpu.utils import knobs

        self.name = name
        self.failure_threshold = int(
            failure_threshold if failure_threshold is not None
            else knobs.get_int("OTPU_BREAKER_THRESHOLD"))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else knobs.get_float("OTPU_BREAKER_COOLDOWN_S"))
        self.probe_successes = int(
            probe_successes if probe_successes is not None
            else knobs.get_int("OTPU_BREAKER_PROBES"))
        self.jitter = jitter
        self.seed = seed
        self.clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consec_failures = 0
        self._opened_at = 0.0
        self._open_count = 0
        self._probe_inflight = False
        self._probe_started_at = 0.0
        self._probe_ok = 0

    # ----------------------------------------------------------- plumbing
    def _set_state(self, state: str) -> None:
        self._state = state
        if self.name:
            _M_BREAKER_STATE.set(_BREAKER_STATES[state], name=self.name)

    def _current_cooldown_s(self) -> float:
        d = self.cooldown_s
        if self.jitter > 0:
            u = zlib.crc32(
                f"{self.seed}:{self._open_count}".encode()) / 0xFFFFFFFF
            d *= 1.0 + self.jitter * u
        return d

    def state(self) -> str:
        """'closed' | 'open' | 'half-open' (open reads as half-open once
        its cooldown has elapsed and a probe could be admitted)."""
        with self._lock:
            if (self._state == "open" and resilience_enabled()
                    and self.clock() - self._opened_at
                    >= self._current_cooldown_s()):
                return "half-open"
            return self._state

    # --------------------------------------------------------- the gate
    def allow(self) -> bool:
        with self._lock:
            if self._state == "closed":
                return True
            if not resilience_enabled():
                return False            # legacy latch: never re-admit
            if self._state == "open":
                if (self.clock() - self._opened_at
                        < self._current_cooldown_s()):
                    return False
                self._set_state("half-open")
                self._probe_inflight = True
                self._probe_started_at = self.clock()
                self._probe_ok = 0
                return True
            # half-open: one probe at a time — but a probe whose attempt
            # aborted before reaching record_success/record_failure (a
            # shed mid-path, a dead worker) must not wedge the breaker
            # half-open forever, so a stale probe's claim expires after
            # one cooldown and the next caller takes it over
            if (self._probe_inflight
                    and self.clock() - self._probe_started_at
                    < self._current_cooldown_s()):
                return False
            self._probe_inflight = True
            self._probe_started_at = self.clock()
            return True

    def record_success(self) -> None:
        with self._lock:
            if self._state == "half-open":
                self._probe_inflight = False
                self._probe_ok += 1
                if self._probe_ok >= self.probe_successes:
                    self._set_state("closed")
                    self._consec_failures = 0
            elif self._state == "closed":
                self._consec_failures = 0

    def record_failure(self) -> None:
        with self._lock:
            now = self.clock()
            if not resilience_enabled():
                # legacy: first failure latches for the process lifetime
                self._set_state("open")
                self._opened_at = now
                return
            if self._state == "half-open":
                self._probe_inflight = False
                self._open_count += 1
                self._set_state("open")
                self._opened_at = now
                return
            self._consec_failures += 1
            if (self._state == "closed"
                    and self._consec_failures >= self.failure_threshold):
                self._open_count += 1
                self._set_state("open")
                self._opened_at = now


# process-wide breaker guarding repeated DispatchWedgedErrors: once a
# budgeted sync wedges, later guarded syncs fast-fail (typed, ~0 s)
# instead of each burning the full watchdog budget, until a half-open
# probe sync completes and re-admits the backend
_wedge_breaker: CircuitBreaker | None = None
_wedge_lock = threading.Lock()


def wedge_breaker() -> CircuitBreaker:
    global _wedge_breaker
    if _wedge_breaker is None:
        with _wedge_lock:
            if _wedge_breaker is None:
                _wedge_breaker = CircuitBreaker("dispatch")
    return _wedge_breaker


def reset_wedge_breaker() -> None:
    """Drop the process-wide dispatch breaker (tests / post-mortem)."""
    global _wedge_breaker
    with _wedge_lock:
        _wedge_breaker = None


# ------------------------------------------------- adaptive coalescing
class AdaptiveCoalescer:
    """The micro-batcher's load-adaptive wait/merge dial.

    One growth factor drives both knobs: sustained queue depth
    (``update(depth)`` with depth >= ``high_depth`` after a flush)
    doubles it, an empty queue halves it back toward 1.0. The effective
    wait is ``base_wait * factor`` capped at ``OTPU_MB_MAX_WAIT_MS``;
    the effective merge target is ``base_batch * factor`` capped at the
    bucket ladder's top rung (``batch_cap``) — adaptivity can never
    merge past a shape the ladder compiles. Fixed base values under
    ``OTPU_RESILIENCE=0`` / ``OTPU_MB_ADAPT=0`` (read per call)."""

    def __init__(self, base_wait_s: float, base_batch: int,
                 batch_cap: int | None = None, *, high_depth: int = 4,
                 growth: float = 2.0, max_wait_s: float | None = None):
        from orange3_spark_tpu.utils import knobs

        self.base_wait_s = base_wait_s
        self.base_batch = base_batch
        self.batch_cap = int(batch_cap if batch_cap is not None
                             else base_batch)
        self.high_depth = high_depth
        self.growth = growth
        cap = (max_wait_s if max_wait_s is not None
               else float(knobs.get_float("OTPU_MB_MAX_WAIT_MS")) / 1e3)
        self.max_wait_s = max(cap, base_wait_s)
        self._max_factor = (self.max_wait_s / base_wait_s
                            if base_wait_s > 0 else 1.0)
        self._factor = 1.0

    def enabled(self) -> bool:
        from orange3_spark_tpu.utils import knobs

        return resilience_enabled() and knobs.get_bool("OTPU_MB_ADAPT")

    @property
    def factor(self) -> float:
        return self._factor

    def current_wait_s(self) -> float:
        if not self.enabled():
            return self.base_wait_s
        return min(self.base_wait_s * self._factor, self.max_wait_s)

    def current_batch(self) -> int:
        if not self.enabled():
            return self.base_batch
        return min(int(self.base_batch * self._factor), self.batch_cap)

    def update(self, queue_depth: int) -> None:
        """Post-flush feedback: the queue depth the flush left behind."""
        if not self.enabled():
            return
        if queue_depth >= self.high_depth:
            self._factor = min(self._factor * self.growth, self._max_factor)
        elif queue_depth == 0:
            self._factor = max(self._factor / self.growth, 1.0)
        _M_MB_ADAPT.set(self._factor)


# ------------------------------------------------ injected service load
def maybe_injected_service_delay() -> None:
    """The ``overload`` fault injector's consumption point: serving
    dispatch paths call this so an injected per-dispatch service delay
    builds a deterministic queue for admission-control tests/bench.
    Injection is live regardless of the kill-switch (the PR-6
    convention: injectors drive the tests, mitigations ride the
    switch)."""
    spec = active_fault_spec()
    if spec is None:
        return
    d = spec.take_overload_delay()
    if d:
        time.sleep(d)


# ------------------------------------------------- memory-pressure brownout
def host_rss_bytes() -> int:
    """This process's resident set size. /proc on linux; the ru_maxrss
    high-water mark elsewhere (conservative: brownout then considers the
    worst the process has been, which is the safe direction)."""
    try:
        with open("/proc/self/status", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # noqa: BLE001 - no RSS source on this platform
        return 0


def _watermarks() -> tuple[float, float, float]:
    from orange3_spark_tpu.utils import knobs

    raw = knobs.get_str("OTPU_MEM_WATERMARKS")
    try:
        parts = [float(p) for p in raw.split(",")]
        if len(parts) == 3 and 0 < parts[0] <= parts[1] <= parts[2]:
            return parts[0], parts[1], parts[2]
    except ValueError:
        pass
    return 0.75, 0.88, 0.96


_BROWNOUT_ACTIONS = {
    1: "shrinking HBM chunk admission to half budget",
    2: "forcing new chunks to the spill/stream path",
    3: "degrading the HBM replay cache",
}
_last_brownout_level = 0
_brownout_lock = threading.Lock()


def memory_pressure_fraction(consume: bool = True) -> float | None:
    """Current memory-pressure fraction: the injected ``mem_pressure``
    fault fraction when one is active, else host RSS over the
    ``OTPU_MEM_BUDGET_MB`` budget. None = no pressure source configured
    (watermarks inert — the common case costs two cheap checks).
    ``consume=False`` = a side observer (/healthz): never advances the
    injector's ``after=`` budget."""
    spec = active_fault_spec()
    if spec is not None:
        frac = spec.mem_pressure_frac(consume=consume)
        if frac is not None:
            return frac
    from orange3_spark_tpu.utils import knobs

    budget_mb = float(knobs.get_float("OTPU_MEM_BUDGET_MB"))
    if budget_mb <= 0:
        return None
    return host_rss_bytes() / (budget_mb * 1024 * 1024)


def brownout_level(consume: bool = True) -> int:
    """The brownout ladder rung the current memory pressure lands on:
    0 normal, 1 shrink chunk admission, 2 force spill, 3 degrade the
    HBM replay cache. 0 whenever no pressure source is configured or
    the kill-switch is on (legacy: fits die on OOM instead). Level
    transitions land on the obs timeline and the
    ``otpu_brownout_level`` gauge, and warn once per escalation.
    ``consume=False`` (health scrapes) never advances an injected
    spec's ``after=`` budget."""
    global _last_brownout_level
    frac = memory_pressure_fraction(consume=consume)
    if frac is None or not resilience_enabled():
        level = 0
    else:
        w1, w2, w3 = _watermarks()
        level = 3 if frac >= w3 else 2 if frac >= w2 else \
            1 if frac >= w1 else 0
    if level != _last_brownout_level:
        with _brownout_lock:
            prev, _last_brownout_level = _last_brownout_level, level
        if level != prev:
            _M_BROWNOUT.set(level)
            from orange3_spark_tpu.obs import trace as _trace

            _trace.instant("brownout", level=level,
                           frac=round(frac or 0.0, 4))
            if level > prev:
                log.warning(
                    "memory pressure %.0f%%: brownout level %d (%s); "
                    "OTPU_MEM_WATERMARKS tunes the ladder, "
                    "OTPU_RESILIENCE=0 disables it",
                    100.0 * (frac or 0.0), level,
                    _BROWNOUT_ACTIONS.get(level, "recovering"))
    return level


def current_brownout_level() -> int:
    """The last level :func:`brownout_level` computed (no re-read) —
    the /healthz report field."""
    return _last_brownout_level
