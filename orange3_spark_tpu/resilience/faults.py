"""Deterministic, seedable fault injectors (docs/resilience.md).

One spec string drives every injector so the SAME tier-1 tests, tools and
bench arms can exercise the whole failure surface:

    OTPU_FAULT_SPEC = clause [ ';' clause ... ]
    clause          = kind [ ':' key '=' value [ ',' key '=' value ... ] ]

Kinds (all ordinals 0-based; every targeting rule is deterministic —
either explicit ordinals or a seeded hash, never wall-clock or id()):

* ``source_io``     transient ``TransientSourceError`` (an ``IOError``) on
  chunk-source reads. Targeting: ``chunk=N`` (that ordinal), ``every=K``
  (ordinals K-1, 2K-1, ...), or ``p=F,seed=S`` (seeded per-ordinal coin).
  ``fails=N`` — each targeted ordinal fails its first N reads then
  succeeds (the fail-N-then-succeed pattern retries must absorb);
  ``fails=-1`` = always fails (the retry-exhaustion pattern).
* ``slow_source``   straggler chunks: sleep ``delay_ms`` before serving
  targeted ordinals (``every=K`` / ``chunk=N``; every read, no budget).
* ``spill_corrupt`` corrupt spill record ``record=N`` at WRITE time:
  ``mode=flip`` XORs one payload byte after the CRC was computed (so the
  v2 read-side check trips), ``mode=truncate`` writes only half the
  record (a crash-mid-write; caught by the finalize/attach size check).
* ``wedge``         the ``at=N``-th guarded dispatch sync (1-based) holds
  for ``hold_s`` seconds (default 3600) instead of completing — the
  never-returning-dispatch signature the watchdog must convert into a
  typed ``DispatchWedgedError``. Consumed once per matching ordinal.
* ``aot_build``     the first ``fails=N`` AOT builds in the serving
  ``ExecutableCache`` raise ``TransientBuildError`` (optionally only for
  keys whose repr contains ``key=SUBSTR``).
* ``overload``      sleep ``delay_ms`` inside each of the first
  ``requests=N`` serving dispatches (``-1`` = every dispatch, the
  default) — the deterministic slow-service load the admission
  controller's shed/deadline logic is tested and benched against
  (resilience/overload.py).
* ``mem_pressure``  report a synthetic memory-pressure fraction
  ``frac=F`` to the brownout watermarks (after the first ``after=K``
  queries, default 0) — drives the shrink-admission/force-spill/degrade
  ladder without actually exhausting host RAM.
* ``drift``         the serving tap (online/tap.py) shifts the features
  it logs by ``shift=S`` (default 3.0) from tapped-chunk ordinal
  ``after=K`` (default 0) on — the deterministic distribution-shift the
  promotion drift gate must reject before any replica flips.
* ``label_skew``    the label joiner flips a ``flip=F`` fraction of
  joined labels (seeded per-example crc32 coin, ``seed=S``) from joined
  chunk ``after=K`` on — feature stats stay clean, so only the holdout
  regression bound can catch the poisoned candidate.
* ``trainer_crash`` the ``at=N``-th incremental-trainer device step
  (1-based) raises instead of running — the SIGKILL stand-in the
  checkpoint-resume drill kills the online trainer thread with.
  Consumed once.

State (per-ordinal fail budgets, sync counters) lives on the ``FaultSpec``
instance, so a retried read observes the budget already consumed — that is
what makes fail-twice-then-succeed deterministic. Programmatic activation
(``inject_faults``) takes precedence over the env var; the env-derived
spec is parsed once per distinct ``OTPU_FAULT_SPEC`` value and kept, so
its state also persists across calls within the process.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import zlib

__all__ = [
    "FaultSpec",
    "TransientBuildError",
    "TransientSourceError",
    "active_fault_spec",
    "inject_faults",
    "resilience_enabled",
]


def resilience_enabled() -> bool:
    """THE kill-switch (read per call, the ``OTPU_DONATE`` convention):
    ``OTPU_RESILIENCE=0`` restores legacy fail-fast behavior — no
    retries, no watchdog budget, no spill CRC verification, no
    epoch-cadence snapshots. Injection stays active (see module doc)."""
    from orange3_spark_tpu.utils import knobs

    return knobs.get_bool("OTPU_RESILIENCE")


class TransientSourceError(IOError):
    """Injected transient chunk-source failure (retryable by contract)."""


class TransientBuildError(RuntimeError):
    """Injected transient AOT-build failure (retryable by contract)."""


_KINDS = ("source_io", "slow_source", "spill_corrupt", "wedge", "aot_build",
          "overload", "mem_pressure", "drift", "label_skew",
          "trainer_crash")


def _record_fault(kind: str) -> None:
    from orange3_spark_tpu.utils.profiling import record_fault

    record_fault(kind)


class _Clause:
    """One parsed ``kind:args`` clause plus its mutable injection state."""

    def __init__(self, kind: str, args: dict):
        self.kind = kind
        self.args = args
        self.fail_left: dict[int, int] = {}   # ordinal -> remaining fails
        self.sync_seen = 0                    # wedge/overload/mem_pressure:
        #                                       consuming queries seen
        self.build_fails_done = 0             # aot_build: raises so far
        self.fired = False                    # mem_pressure: counter ticked

    def _arg(self, key, default=None, cast=float):
        v = self.args.get(key)
        return default if v is None else cast(v)

    def targets(self, ordinal: int) -> bool:
        """Deterministic ordinal targeting shared by the source kinds."""
        if "chunk" in self.args:
            return ordinal == int(self.args["chunk"])
        if "every" in self.args:
            k = max(1, int(self.args["every"]))
            return ordinal % k == k - 1
        if "p" in self.args:
            p = float(self.args["p"])
            seed = int(self.args.get("seed", 0))
            # seeded per-ordinal coin: crc32 is stable across processes
            # (unlike hash()), so the same spec targets the same chunks
            # in a subprocess bench arm and an in-process test
            h = zlib.crc32(f"{seed}:{ordinal}".encode()) / 0xFFFFFFFF
            return h < p
        return True                           # bare kind: every ordinal


class FaultSpec:
    """Parsed, stateful fault-injection spec (see the module docstring)."""

    def __init__(self, clauses: list[_Clause], text: str = ""):
        self.clauses = clauses
        self.text = text
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        clauses = []
        for raw in text.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            kind, _, rest = raw.partition(":")
            kind = kind.strip()
            if kind not in _KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} in OTPU_FAULT_SPEC "
                    f"(known: {_KINDS}); spec grammar: docs/resilience.md"
                )
            args = {}
            for kv in rest.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                k, sep, v = kv.partition("=")
                if not sep:
                    raise ValueError(
                        f"malformed fault arg {kv!r} in clause {raw!r} "
                        "(expected key=value)"
                    )
                args[k.strip()] = v.strip()
            clauses.append(_Clause(kind, args))
        return cls(clauses, text)

    def _of(self, kind: str):
        return [c for c in self.clauses if c.kind == kind]

    # ------------------------------------------------------ source hooks
    @property
    def has_source_faults(self) -> bool:
        return any(c.kind in ("source_io", "slow_source")
                   for c in self.clauses)

    def on_source_chunk(self, ordinal: int) -> None:
        """Called by the injected source wrapper before yielding chunk
        ``ordinal``: may sleep (straggler) and/or raise (transient IO)."""
        for c in self._of("slow_source"):
            if c.targets(ordinal):
                _record_fault("slow_source")
                time.sleep(c._arg("delay_ms", 10.0) / 1e3)
        for c in self._of("source_io"):
            if not c.targets(ordinal):
                continue
            fails = int(c._arg("fails", 1, cast=int))
            with self._lock:
                if fails < 0:
                    left = -1
                else:
                    left = c.fail_left.setdefault(ordinal, fails)
                    if left > 0:
                        c.fail_left[ordinal] = left - 1
            if left != 0:
                _record_fault("source_io")
                raise TransientSourceError(
                    f"injected transient source fault at chunk {ordinal}"
                    f" ({'always' if fails < 0 else f'{left} left'})"
                )

    # ----------------------------------------------------- storage hooks
    def take_spill_corrupt(self, record: int) -> str | None:
        """'flip' / 'truncate' when record ``record`` should be corrupted
        at write time (consumed: each clause fires once)."""
        for c in self._of("spill_corrupt"):
            with self._lock:
                if c.fail_left.get(record, 1) == 0:
                    continue
                if record == int(c._arg("record", 0, cast=int)):
                    c.fail_left[record] = 0
                    _record_fault("spill_corrupt")
                    return str(c.args.get("mode", "flip"))
        return None

    # ---------------------------------------------------- dispatch hooks
    def take_wedge(self) -> float | None:
        """hold-seconds when THIS guarded dispatch sync should wedge
        (the Nth sync since the spec was installed), else None."""
        for c in self._of("wedge"):
            with self._lock:
                c.sync_seen += 1
                if c.sync_seen == int(c._arg("at", 1, cast=int)):
                    _record_fault("wedge")
                    return c._arg("hold_s", 3600.0)
        return None

    def take_overload_delay(self) -> float | None:
        """Seconds of injected service delay for THIS serving dispatch
        (the Nth since the spec was installed), else None. ``requests=N``
        bounds the slow spell (default -1 = every dispatch)."""
        for c in self._of("overload"):
            with self._lock:
                c.sync_seen += 1
                budget = int(c._arg("requests", -1, cast=int))
                if 0 <= budget < c.sync_seen:
                    continue
            _record_fault("overload")
            return c._arg("delay_ms", 10.0) / 1e3
        return None

    def mem_pressure_frac(self, consume: bool = True) -> float | None:
        """Synthetic memory-pressure fraction for the brownout
        watermarks, else None. ``after=K`` keeps the first K CONSUMING
        queries (chunk offers) pressure-free so a ladder test can cache
        a prefix before the squeeze; side observers (/healthz scrapes)
        pass ``consume=False`` and never advance the budget — a load
        balancer polling health must not shift deterministic targeting.
        The fault counter ticks once per clause, at first activation."""
        for c in self._of("mem_pressure"):
            fire = False
            with self._lock:
                if consume:
                    c.sync_seen += 1
                if c.sync_seen <= int(c._arg("after", 0, cast=int)):
                    continue
                if consume and not c.fired:
                    c.fired = True
                    fire = True
            if fire:
                _record_fault("mem_pressure")
            return c._arg("frac", 1.0)
        return None

    # ------------------------------------------------------ online hooks
    def take_drift_shift(self, ordinal: int) -> float | None:
        """Feature shift to apply to tapped chunk ``ordinal`` (0-based),
        else None. The counter ticks once per clause, at first
        activation (a sustained shift is one fault, not N)."""
        for c in self._of("drift"):
            fire = False
            with self._lock:
                if ordinal < int(c._arg("after", 0, cast=int)):
                    continue
                if not c.fired:
                    c.fired = True
                    fire = True
            if fire:
                _record_fault("drift")
            return c._arg("shift", 3.0)
        return None

    def take_label_flip(self, ordinal: int, n_rows: int):
        """Boolean mask of labels to flip in joined chunk ``ordinal``,
        else None. Seeded per-(chunk, row) crc32 coin so the SAME rows
        flip in a subprocess bench arm and an in-process test; counter
        ticks once per clause."""
        for c in self._of("label_skew"):
            fire = False
            with self._lock:
                if ordinal < int(c._arg("after", 0, cast=int)):
                    continue
                if not c.fired:
                    c.fired = True
                    fire = True
            if fire:
                _record_fault("label_skew")
            frac = c._arg("flip", 0.5)
            seed = int(c._arg("seed", 0, cast=int))
            mask = [
                zlib.crc32(f"{seed}:{ordinal}:{r}".encode()) / 0xFFFFFFFF
                < frac for r in range(n_rows)
            ]
            return mask
        return None

    def take_trainer_crash(self) -> bool:
        """True when THIS trainer device step (the Nth since the spec was
        installed, 1-based ``at=N``) should die. Consumed once per
        matching clause."""
        for c in self._of("trainer_crash"):
            with self._lock:
                c.sync_seen += 1
                if c.sync_seen == int(c._arg("at", 1, cast=int)):
                    _record_fault("trainer_crash")
                    return True
        return False

    # ----------------------------------------------------- serving hooks
    def maybe_fail_aot_build(self, key) -> None:
        for c in self._of("aot_build"):
            sub = c.args.get("key")
            if sub is not None and sub not in repr(key):
                continue
            with self._lock:
                if c.build_fails_done >= int(c._arg("fails", 1, cast=int)):
                    continue
                c.build_fails_done += 1
            _record_fault("aot_build")
            raise TransientBuildError(
                f"injected transient AOT build fault ({c.build_fails_done}"
                f"/{int(c._arg('fails', 1, cast=int))}) for key {key!r}"
            )


# programmatic install (innermost wins) > env-derived spec. The env spec
# is parsed once per distinct string and KEPT so its per-ordinal budgets
# persist across reads within the process.
_installed: list[FaultSpec] = []
_env_cache: tuple[str, FaultSpec | None] = ("", None)


def active_fault_spec() -> FaultSpec | None:
    """The currently active spec, or None when no faults are configured."""
    if _installed:
        return _installed[-1]
    global _env_cache
    text = os.environ.get("OTPU_FAULT_SPEC", "")
    if not text:
        return None
    if _env_cache[0] != text:
        _env_cache = (text, FaultSpec.parse(text))
    return _env_cache[1]


@contextlib.contextmanager
def inject_faults(spec: "FaultSpec | str"):
    """Scope a fault spec over a block (tests / tools / bench arms):

        with inject_faults("source_io:chunk=2,fails=2"):
            model = est.fit_stream(source, ...)
    """
    if isinstance(spec, str):
        spec = FaultSpec.parse(spec)
    _installed.append(spec)
    try:
        yield spec
    finally:
        _installed.remove(spec)
