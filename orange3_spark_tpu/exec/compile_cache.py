"""Persistent XLA compilation cache wiring.

The bench's hot programs (the fused replay scan, the L-BFGS while_loop, the
eval fold) each cost seconds-to-minutes of XLA compile per PROCESS — paid
again on every bench run, every retry-ladder rung, every tunnel window.
``jax_compilation_cache_dir`` persists compiled executables keyed by
(program, backend, flags): the first run pays the compile and writes an
entry; every later process with the same shapes loads the binary instead.

One wiring point (``enable_compilation_cache``, surfaced as
``TpuSession.enable_compilation_cache``) so the thresholds are set once:
the min-compile-time and min-entry-size gates are zeroed because this
workload has few, large, endlessly re-used programs — exactly what the
cache is for. ``OTPU_COMPILE_CACHE`` overrides the directory ("0"
disables). ``cache_report`` turns a pre-run snapshot into the bench line's
``cache_hit``/``cache_entries`` fields.
"""

from __future__ import annotations

import os
import tempfile

import jax


def default_cache_dir() -> str:
    """Per-user cache dir (compiled programs are user data; a shared
    world-writable dir would be the devlock squatting story again)."""
    env = os.environ.get("OTPU_COMPILE_CACHE", "")
    if env and env != "0":
        return env
    return os.path.join(tempfile.gettempdir(),
                        f"otpu_compile_cache_{os.getuid()}")


def cache_entries(cache_dir: str) -> int:
    """Number of persisted executables under ``cache_dir`` (0 if absent)."""
    n = 0
    for _root, _dirs, files in os.walk(cache_dir):
        n += len(files)
    return n


def enable_compilation_cache(cache_dir: str | None = None) -> dict:
    """Point jax's persistent compilation cache at ``cache_dir`` (default:
    ``default_cache_dir()``; ``OTPU_COMPILE_CACHE=0`` disables).

    Returns ``{"enabled", "dir", "pre_entries"}`` — keep the dict and hand
    it to ``cache_report`` after the measured work to learn whether the run
    compiled anything new. Failures to configure (an old jax without the
    option, an unwritable dir) degrade to ``enabled: False`` rather than
    raising: the cache is an accelerator, never a correctness dependency.
    """
    if os.environ.get("OTPU_COMPILE_CACHE", "") == "0":
        return {"enabled": False, "dir": None, "pre_entries": 0,
                "reason": "disabled by OTPU_COMPILE_CACHE=0"}
    d = cache_dir or default_cache_dir()
    try:
        os.makedirs(d, mode=0o700, exist_ok=True)
        pre = cache_entries(d)
        jax.config.update("jax_compilation_cache_dir", d)
        # few, large, endlessly re-used programs: cache everything
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:  # noqa: BLE001 - option absent on older jax
            pass
        # the cache module LATCHES its initialized/disabled state at the
        # process's first compile — if anything compiled before this call
        # (a probe, a warm-up), the new dir would silently never be used;
        # reset so the next compile re-initializes against the configured
        # dir (private API, hence guarded)
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 - best-effort on jax internals
            pass
    except Exception as e:  # noqa: BLE001 - cache is best-effort
        return {"enabled": False, "dir": None, "pre_entries": 0,
                "reason": f"{type(e).__name__}: {e}"}
    return {"enabled": True, "dir": d, "pre_entries": pre}


def cache_report(info: dict) -> dict:
    """``{"cache_hit", "cache_entries"}`` for the bench JSON line.

    ``cache_hit`` is True when the run found a warm cache AND wrote no new
    entries (every program it compiled was served from disk); False when it
    had to compile something (first run, or changed shapes/flags); None
    when the cache is disabled/unavailable.
    """
    if not info.get("enabled"):
        return {"cache_hit": None, "cache_entries": None}
    post = cache_entries(info["dir"])
    pre = info.get("pre_entries", 0)
    return {"cache_hit": bool(pre > 0 and post <= pre),
            "cache_entries": post}
