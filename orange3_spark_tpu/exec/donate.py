"""Uniform buffer donation for the fused training loops.

``donate_argnums`` tells XLA an input buffer may be aliased to an output —
for a training step whose ``(params, opt_state)`` round-trip through every
dispatch, donation removes one full parameter copy per step and halves the
peak parameter footprint on backends that implement aliasing (TPU does;
XLA:CPU accepts the annotation and ignores it, so CPU tests exercise the
same code path at zero risk). Donation is pure aliasing — it must never
change a single bit of the result, and ``tests/test_donation.py`` pins that
by fitting every swept model donation-on and donation-off.

``donating_jit`` is the ONE way loops declare donation, with a global
switch (``OTPU_DONATE=0``) that disables every donation at once: the
parity tests flip it, and it is the escape hatch if a backend ever
miscompiles an aliased program.

Sweep record (which loop donates what, and why the exceptions are
exceptions):

* ``models/hashed_linear._hashed_step`` / ``_hashed_replay_epochs``
  (per-chunk step, fused/epoch/disk-group replay) — donate
  ``(theta, opt_state)``; under the optim/ subsystem ``opt_state`` is the
  sparse state ``(slots, timestamps, step)``, donated identically. The
  per-chunk touched-row PLANS are scan xs (reused every epoch) and are
  deliberately NOT donated.
* ``io/streaming._stream_step`` / ``_stream_replay_epochs`` — donate
  ``(theta, opt_state)``; ``_kmeans_stream_step`` /
  ``_kmeans_replay_epochs`` — donate ``(centers, counts)``.
* ``io/streaming._feature_stats_step[_missing]`` (the scaler/Imputer/PCA
  ``fit_stream`` accumulator) — donate the running stats dict.
* ``models/kmeans._lloyd`` — donate ``centers0`` (every caller builds the
  seed centers fresh); the ``n_init>1`` restart path calls the undonated
  twin because donation inside ``vmap`` tracing is a no-op.
* ``models/evaluation`` streaming folds — donate the accumulator.
* ``models/_linear.fit_linear`` — inputs are table-BORROWED (``table.X`` /
  ``table.W`` outlive the fit), so donation is opt-in via
  ``donate_data=True`` for callers that own transient batches.
* ``workflow/staging`` — staged-program inputs default to the cached eager
  tables (reused across calls), so donation is opt-in via
  ``donate_inputs=True`` for one-shot/refit-loop executions feeding fresh
  tables each call.
"""

from __future__ import annotations

import functools
import os

import jax


def donation_enabled() -> bool:
    """Global donation switch — ``OTPU_DONATE=0`` disables every
    ``donating_jit`` donation at once (read per call, so a test can flip
    it mid-process)."""
    from orange3_spark_tpu.utils import knobs

    return knobs.get_bool("OTPU_DONATE")


def donating_jit(fn=None, *, donate_argnums=(), static_argnames=(),
                 static_argnums=()):
    """``jax.jit`` with donation declared the uniform way.

    Returns a wrapper that dispatches to the donating compilation when
    ``donation_enabled()`` and to an undonated twin otherwise. Both are
    exposed (``wrapper.donated`` / ``wrapper.plain``) for call sites that
    must force one — e.g. under ``vmap`` tracing, where an inner jit's
    donation is silently dropped, the ``.plain`` twin avoids compiling a
    donating executable that can never donate.
    """

    def deco(f):
        kw = {}
        if static_argnames:
            kw["static_argnames"] = static_argnames
        if static_argnums:
            kw["static_argnums"] = static_argnums
        donated = jax.jit(f, donate_argnums=tuple(donate_argnums), **kw)
        plain = jax.jit(f, **kw)

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return (donated if donation_enabled() else plain)(*args, **kwargs)

        wrapper.donated = donated
        wrapper.plain = plain
        wrapper.donate_argnums = tuple(donate_argnums)
        return wrapper

    return deco(fn) if fn is not None else deco
