"""Execution pipeline subsystem — async host/device overlap, uniform buffer
donation, and persistent compiled-program reuse.

The streaming fits' three systemic costs, each owned by one module here:

* ``pipeline``      — ``PipelinedExecutor``: a bounded background-thread
  prefetcher that parses/rechunks/``device_put``s chunk t+1 while the device
  runs step t (double buffering), with MEASURED overlap efficiency
  (``overlap_pct``) instead of assumed overlap.
* ``donate``        — ``donating_jit``: the one way every fused training
  loop declares ``donate_argnums``, with a global ``OTPU_DONATE=0`` switch
  so donation-on/off parity is testable bit-for-bit.
* ``compile_cache`` — persistent XLA compilation cache wiring
  (``jax_compilation_cache_dir``) so re-runs skip the scan/L-BFGS compiles
  entirely; surfaced through ``TpuSession.enable_compilation_cache``.

Spark lineage: Spark wins on ingest-heavy workloads by pipelining input
partitions with task compute; this package is that idea at the TPU host
boundary, measured end to end in ``bench.py``'s ``overlap_pct`` /
``dispatches`` / ``cache_hit`` fields.
"""

# Lazy re-exports (PEP 562): model modules import ``exec.donate`` at their
# own import time, and an eager ``exec.pipeline`` import here would pull in
# utils -> workflow -> widgets -> models — a circular-import magnet. Each
# submodule loads only when its symbol is first touched.
_EXPORTS = {
    "cache_entries": "compile_cache",
    "cache_report": "compile_cache",
    "default_cache_dir": "compile_cache",
    "enable_compilation_cache": "compile_cache",
    "donating_jit": "donate",
    "donation_enabled": "donate",
    "PipelinedExecutor": "pipeline",
    "PipelineStats": "pipeline",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(
        importlib.import_module(f"orange3_spark_tpu.exec.{mod}"), name
    )
