"""PipelinedExecutor — the chunk pipeline's measured overlap engine.

JAX dispatch is async, so a streaming fit gets double buffering "for free"
only if the host work (parse, pad, ``device_put`` enqueue) for chunk t+1
actually runs while the device executes step t. This module makes that
overlap a first-class, MEASURED property instead of a hoped-for one:

* a bounded daemon-thread producer runs ``prep`` over the item stream and
  hands results through a ``depth``-bounded queue (depth 2 = classic double
  buffering: one chunk on device, one staged);
* the producer's busy time (``prep_s``) and the consumer's blocked time
  (``wait_s``) are accumulated; their ratio is the overlap efficiency:

      overlap_pct = 100 * max(0, 1 - wait_s / prep_s)

  100% means every second of host prep was hidden behind device compute
  (the consumer never waited); 0% means the pipeline degenerated to serial
  (the consumer waited out every prep). The pipeline-fill wait for the
  first item counts against overlap — that prep is genuinely exposed.

Semantics preserved from the old ``io.streaming.prefetch_map`` (which now
delegates here): results are yielded in order; a producer exception
re-raises at the consuming ``next()``; closing the generator early stops
the worker. ``prep`` and the native parser both release the GIL, so the
worker genuinely overlaps even on a single-core host.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Callable, Iterator

from orange3_spark_tpu.obs import context as obs_context
from orange3_spark_tpu.obs import prof
from orange3_spark_tpu.obs.trace import span
from orange3_spark_tpu.utils.dispatch import beat

_EOF = object()


@dataclasses.dataclass
class PipelineStats:
    """Counters for one pipelined stream (final once ``done`` is True)."""

    items: int = 0        # results yielded to the consumer
    prep_s: float = 0.0   # producer time inside prep (parse/pad/device_put)
    wait_s: float = 0.0   # consumer time blocked waiting on the queue
    wall_s: float = 0.0   # consumer wall from first wait to stream end
    # producer time spent ENCODING chunks for the compressed cache
    # (io/codec.py) — a subset of prep_s, attributed by the prep callback
    # itself so the cache-codec cost is visible next to parse/DMA
    encode_s: float = 0.0
    # transient source reads retried by the resilience layer
    # (resilience/retry.resilient_source threads this stats object in)
    retries: int = 0
    done: bool = False

    @property
    def overlap_pct(self) -> float:
        """Share of producer time hidden behind consumer compute, 0-100."""
        if self.prep_s <= 0.0:
            return 0.0
        return 100.0 * min(max(1.0 - self.wait_s / self.prep_s, 0.0), 1.0)

    def merge(self, other: "PipelineStats") -> "PipelineStats":
        """Fold another stream's counters in (multi-phase fits aggregate
        their per-phase pipelines into one fit-level overlap number)."""
        self.items += other.items
        self.prep_s += other.prep_s
        self.wait_s += other.wait_s
        self.wall_s += other.wall_s
        self.encode_s += other.encode_s
        self.retries += other.retries
        return self


class PipelinedExecutor:
    """Bounded background-thread prefetch with measured overlap.

    ``prep(item)`` runs on the worker thread — for the streaming fits it is
    parse+pad+``device_put``, so the DMA enqueue of chunk t+1 overlaps the
    device step on chunk t. ``depth`` bounds how far the producer runs
    ahead (double buffering at the default 2); ``depth=0`` still prefetches
    with a queue of one.

    Stats land on ``self.stats`` as the stream progresses and are recorded
    into the process-wide ``utils.profiling`` aggregate when the stream
    ends (``record=False`` opts out — e.g. microbenches that must not
    pollute a surrounding fit's numbers).
    """

    def __init__(self, prep: Callable, *, depth: int = 2,
                 name: str = "chunk-prefetch", record: bool = True):
        self.prep = prep
        self.depth = max(1, depth)
        self.name = name
        self.record = record
        self.stats = PipelineStats()

    def run(self, items: Iterator) -> Iterator:
        """Yield ``prep(item)`` for every item, in order, prefetched."""
        stats = self.stats
        q: queue.Queue = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        prep = self.prep
        # the consumer's trace context (the fit's run id) — the worker
        # thread adopts it so its "prefetch" spans carry the same trace
        # id as the fit/epoch/chunk spans they feed (obs/context.py)
        trace_ctx = obs_context.current_trace()

        def worker():
            with obs_context.adopt(trace_ctx):
                self._produce(iter(items), q, stop, prep, stats)

        t = threading.Thread(target=worker, daemon=True, name=self.name)
        t.start()
        t_start = time.perf_counter()
        try:
            while True:
                t0 = time.perf_counter()
                got = q.get()
                dt_wait = time.perf_counter() - t0
                stats.wait_s += dt_wait
                # goodput attribution (obs/prof.py): the consumer is the
                # fit's thread of control, so this wait IS input_wait —
                # fed live (not at stream end) so per-epoch bottleneck
                # classification sees intra-epoch waits
                prof.note_input_wait(dt_wait)
                if (isinstance(got, tuple) and len(got) == 2
                        and got[0] is _EOF):
                    if got[1] is not None:
                        raise got[1]
                    return
                stats.items += 1
                yield got
        finally:
            stop.set()
            stats.wall_s = time.perf_counter() - t_start
            stats.done = True
            if self.record:
                from orange3_spark_tpu.utils.profiling import record_pipeline

                record_pipeline(stats)

    @staticmethod
    def _produce(it, q, stop, prep, stats) -> None:
        """The worker-thread body (runs under the adopted trace context)."""
        try:
            while True:
                # time the PULL too: the upstream iterator is where the
                # parse/rechunk work lives (prep is only pad+device_put),
                # and both run on this thread — prep_s must carry the
                # whole host-side cost or overlap_pct overstates waits
                t0 = time.perf_counter()
                with span("prefetch", stats.items):
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                    out = prep(item)
                stats.prep_s += time.perf_counter() - t0
                beat()  # parse/DMA progress feeds the stall watchdog
                while not stop.is_set():
                    try:
                        q.put(out, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            payload = (_EOF, None)
        except BaseException as e:  # noqa: BLE001 - re-raised on consumer
            payload = (_EOF, e)
        while not stop.is_set():
            try:
                q.put(payload, timeout=0.1)
                return
            except queue.Full:
                continue


def prefetch_iter(prep: Callable, items: Iterator, *, depth: int = 2,
                  stats_into: PipelineStats | None = None) -> Iterator:
    """One-shot functional form: run ``items`` through a fresh
    ``PipelinedExecutor``; ``stats_into`` receives the stream's counters
    (merged) when it ends."""
    ex = PipelinedExecutor(prep, depth=depth)
    try:
        yield from ex.run(items)
    finally:
        if stats_into is not None:
            stats_into.merge(ex.stats)
