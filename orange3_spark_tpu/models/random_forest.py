"""RandomForest — parity with ``pyspark.ml.classification.RandomForestClassifier``
(and RandomForestRegressor).

MLlib grows all trees together with distributed binned histograms
(SURVEY.md §2b; reconstructed, mount empty). Here the ENTIRE forest fits as
one XLA program: ``jax.vmap`` of the fixed-shape tree grower over a tree
axis — per-tree Poisson bootstrap weights (the with-replacement resample in
expectation) and per-(tree, level) Bernoulli feature masks (MLlib's
featureSubsetStrategy, applied per level rather than per node) come from a
split PRNG key, so T trees cost one fused device program, not T dispatches.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models._tree import (
    Tree,
    normalize_importances,
    bin_features,
    compute_bin_edges,
    grow_tree,
    leaf_class_probs,
    tree_apply,
)
from orange3_spark_tpu.models.base import Estimator, Model, Params, infer_class_values


def _subset_fraction(strategy: str, d: int, is_classification: bool) -> float:
    if strategy == "auto":
        strategy = "sqrt" if is_classification else "onethird"
    return {
        "all": 1.0,
        "sqrt": np.sqrt(d) / d,
        "log2": max(np.log2(max(d, 2)) / d, 1.0 / d),
        "onethird": 1.0 / 3.0,
    }[strategy]


@dataclasses.dataclass(frozen=True)
class RandomForestParams(Params):
    num_trees: int = 20            # MLlib numTrees
    max_depth: int = 5             # MLlib maxDepth
    max_bins: int = 32             # MLlib maxBins
    min_instances_per_node: float = 1.0  # MLlib minInstancesPerNode
    min_info_gain: float = 0.0     # MLlib minInfoGain
    subsampling_rate: float = 1.0  # MLlib subsamplingRate (Poisson lambda)
    feature_subset_strategy: str = "auto"  # MLlib featureSubsetStrategy
    seed: int = 0                  # MLlib seed


@partial(
    jax.jit,
    static_argnames=("num_trees", "depth", "n_bins", "k", "gain_mode",
                     "min_instances"),
)
def _fit_forest(B, edges, Ystats, W, keep_p, min_gain, seed, *, num_trees: int,
                depth: int, n_bins: int, k: int, gain_mode: str,
                min_instances: float, subsample: float):
    d = B.shape[1]
    key = jax.random.PRNGKey(seed)

    def fit_one(tkey):
        kb, kf = jax.random.split(tkey)
        boot = jax.random.poisson(kb, subsample, (B.shape[0],)).astype(jnp.float32)
        w_t = W * boot
        keep = jax.random.bernoulli(kf, keep_p, (depth, d)).astype(jnp.float32)
        # never mask every feature of a level
        keep = jnp.where(jnp.sum(keep, 1, keepdims=True) > 0, keep, 1.0)
        S = Ystats * w_t[:, None]
        tree, _, imp = grow_tree(
            B, S, edges, keep, min_gain,
            depth=depth, n_bins=n_bins, gain_mode=gain_mode,
            min_instances=min_instances,
        )
        # MLlib featureImportances: normalize PER TREE before averaging
        return tree, normalize_importances(imp)

    return jax.vmap(fit_one)(jax.random.split(key, num_trees))


@jax.jit
def _forest_probs(X, forest: Tree):
    """Mean of per-tree leaf class distributions (MLlib probability vote)."""
    leaves = jax.vmap(lambda t: tree_apply(X, t))(forest)          # [T, N]
    probs = leaf_class_probs(forest.leaf_value)                    # [T, L, k]
    per_tree = jnp.take_along_axis(probs, leaves[:, :, None], 1)   # [T, N, k]
    return jnp.mean(per_tree, axis=0)


class RandomForestClassifierModel(Model):
    def __init__(self, params, forest: Tree, class_values):
        self.params = params
        self.forest = forest
        self.class_values = tuple(class_values)

    @property
    def state_pytree(self):
        return dict(self.forest._asdict())

    def predict_proba(self, table: TpuTable) -> np.ndarray:
        return np.asarray(_forest_probs(table.X, self.forest))[: table.n_rows]

    def predict(self, table: TpuTable) -> np.ndarray:
        probs = _forest_probs(table.X, self.forest)
        return np.asarray(jnp.argmax(probs, 1).astype(jnp.float32))[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        probs = _forest_probs(table.X, self.forest)
        pred = jnp.argmax(probs, axis=1).astype(jnp.float32)
        new_attrs = list(table.domain.attributes) + [
            ContinuousVariable(f"probability_{c}") for c in self.class_values
        ] + [DiscreteVariable("prediction", self.class_values)]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, probs, pred[:, None]], axis=1)
        return table.with_X(X, new_domain)


class RandomForestClassifier(Estimator):
    ParamsCls = RandomForestParams
    params: RandomForestParams

    def _fit(self, table: TpuTable) -> RandomForestClassifierModel:
        p = self.params
        y = table.y
        class_values = infer_class_values(table)
        k = len(class_values)
        edges = compute_bin_edges(table.X, table.W, p.max_bins)
        B = bin_features(table.X, edges)
        Ystats = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=jnp.float32)
        keep_p = _subset_fraction(p.feature_subset_strategy, table.n_attrs, True)
        forest = _fit_forest(
            B, edges, Ystats, table.W, keep_p,
            jnp.float32(p.min_info_gain), p.seed,
            num_trees=p.num_trees, depth=p.max_depth, n_bins=p.max_bins,
            k=k, gain_mode="gini", min_instances=p.min_instances_per_node,
            subsample=p.subsampling_rate,
        )
        forest, tree_imps = forest
        model = RandomForestClassifierModel(p, forest, class_values)
        # MLlib: average the per-tree-normalized importances, renormalize
        model.feature_importances_ = normalize_importances(
            jnp.mean(tree_imps, axis=0))
        return model


# ---------------------------------------------------------------- regressor
@jax.jit
def _forest_means(X, forest: Tree):
    leaves = jax.vmap(lambda t: tree_apply(X, t))(forest)          # [T, N]
    s1 = forest.leaf_value[..., 0]
    c = jnp.maximum(forest.leaf_value[..., 2], 1e-12)
    means = s1 / c                                                  # [T, L]
    per_tree = jnp.take_along_axis(means, leaves, axis=1)           # [T, N]
    return jnp.mean(per_tree, axis=0)


class RandomForestRegressorModel(Model):
    def __init__(self, params, forest: Tree):
        self.params = params
        self.forest = forest

    @property
    def state_pytree(self):
        return dict(self.forest._asdict())

    def predict(self, table: TpuTable) -> np.ndarray:
        return np.asarray(_forest_means(table.X, self.forest))[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        yhat = _forest_means(table.X, self.forest)
        new_domain = Domain(
            list(table.domain.attributes) + [ContinuousVariable("prediction")],
            table.domain.class_vars, table.domain.metas,
        )
        X = jnp.concatenate([table.X, yhat[:, None]], axis=1)
        return table.with_X(X, new_domain)


class RandomForestRegressor(Estimator):
    ParamsCls = RandomForestParams
    params: RandomForestParams

    def _fit(self, table: TpuTable) -> RandomForestRegressorModel:
        p = self.params
        y = table.y
        edges = compute_bin_edges(table.X, table.W, p.max_bins)
        B = bin_features(table.X, edges)
        Ystats = jnp.stack([y, y * y, jnp.ones_like(y)], axis=1)  # [Σwy,Σwy²,Σw]
        keep_p = _subset_fraction(p.feature_subset_strategy, table.n_attrs, False)
        forest = _fit_forest(
            B, edges, Ystats, table.W, keep_p,
            jnp.float32(p.min_info_gain), p.seed,
            num_trees=p.num_trees, depth=p.max_depth, n_bins=p.max_bins,
            k=3, gain_mode="variance", min_instances=p.min_instances_per_node,
            subsample=p.subsampling_rate,
        )
        forest, tree_imps = forest
        model = RandomForestRegressorModel(p, forest)
        model.feature_importances_ = normalize_importances(
            jnp.mean(tree_imps, axis=0))
        return model
