"""RFormula — parity with ``pyspark.ml.feature.RFormula``.

MLlib's RFormula compiles an R-style model formula into a feature/label
preparation pipeline on the JVM (SURVEY.md §2b "Feature transformers";
reconstructed, mount empty). Supported formula surface (the same subset
MLlib documents): ``~``, ``+``, ``-`` (term removal, ``- 1`` drops the
intercept flag), ``.`` (all non-label columns), ``:`` (interaction).

TPU-native redesign: fit compiles the formula against the table's Domain
into a static column PLAN (indices, one-hot widths, interaction products);
transform executes the plan as pure jnp gathers/one-hots/products — a
device-only re-layout that fuses into whatever model consumes it (and
stages into whole-workflow XLA programs like every other transformer).
Categorical terms expand to reference-level dummy columns — the FIRST level
is dropped, R's default treatment contrasts (MLlib instead drops the last
frequency-ordered index; same rank, different reference level). With
``- 1`` (no intercept) the first categorical main-effect term is full-coded,
as in R. Interactions multiply the encoded blocks columnwise. The label
moves to the table's class variable, as MLlib moves it to ``labelCol``.
"""

from __future__ import annotations

import dataclasses
import itertools

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import (
    ContinuousVariable,
    DiscreteVariable,
    Domain,
)
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params


@dataclasses.dataclass(frozen=True)
class RFormulaParams(Params):
    formula: str = ""


def _parse(formula: str):
    """-> (label, included term tuples, excluded term tuples, intercept)."""
    if "~" not in formula:
        raise ValueError(f"formula needs '~': {formula!r}")
    lhs, rhs = formula.split("~", 1)
    label = lhs.strip()
    if not label:
        raise ValueError("formula needs a label on the left of '~'")
    include, exclude, intercept = [], [], True
    # '+' separates terms; a '-' flips the following terms to removals
    for signed in rhs.replace("-", "+-").split("+"):
        t = signed.strip()
        if not t:
            continue
        neg = t.startswith("-")
        t = t.lstrip("-").strip()
        if t == "1":
            if neg:
                intercept = False
            continue
        factors = tuple(f.strip() for f in t.split(":") if f.strip())
        if not factors:
            continue
        (exclude if neg else include).append(factors)
    return label, include, exclude, intercept


class RFormulaModel(Model):
    def __init__(self, params, plan, out_domain, label_var, label_src):
        self.params = params
        self.plan = plan            # [(name, [(col_idx, n_onehot|0), ...])]
        self.out_domain = out_domain
        self.label_var = label_var
        self.label_src = label_src  # ('attr', j) | ('class', j)
        self.has_intercept = True   # '- 1' in the formula flips this

    @property
    def state_pytree(self):
        return {}

    def transform(self, table: TpuTable) -> TpuTable:
        X = table.X
        blocks = []
        for _, factors in self.plan:
            encoded = []
            for j, width in factors:
                col = X[:, j]
                if width < 0:    # full coding (no-intercept first factor)
                    encoded.append(
                        jax.nn.one_hot(col.astype(jnp.int32), -width,
                                       dtype=jnp.float32)
                    )
                elif width:
                    encoded.append(
                        jax.nn.one_hot(col.astype(jnp.int32), width + 1,
                                       dtype=jnp.float32)[:, 1:]
                    )  # drop the FIRST level: R treatment contrasts
                else:
                    encoded.append(col[:, None])
            block = encoded[0]
            for nxt in encoded[1:]:
                # interaction: columnwise cross product of the blocks
                block = (block[:, :, None] * nxt[:, None, :]).reshape(
                    block.shape[0], -1
                )
            blocks.append(block)
        feats = (jnp.concatenate(blocks, axis=1) if blocks
                 else jnp.zeros((X.shape[0], 0), jnp.float32))
        kind, j = self.label_src
        ycol = table.Y[:, j] if kind == "class" else X[:, j]
        return TpuTable(
            self.out_domain, feats, ycol[:, None], table.W, table.metas,
            table.n_rows, table.session,
        )


class RFormula(Estimator):
    ParamsCls = RFormulaParams
    params: RFormulaParams

    def _fit(self, table: TpuTable) -> RFormulaModel:
        label, include, exclude, intercept = _parse(self.params.formula)
        domain = table.domain
        attr_names = [v.name for v in domain.attributes]
        class_names = [v.name for v in domain.class_vars]
        if label in attr_names:
            label_src = ("attr", attr_names.index(label))
            label_var = domain.attributes[label_src[1]]
        elif label in class_names:
            label_src = ("class", class_names.index(label))
            label_var = domain.class_vars[label_src[1]]
        else:
            raise ValueError(f"label {label!r} not in table columns")

        # '.' expands to every attribute except the label, in domain order
        expanded: list[tuple[str, ...]] = []
        for t in include:
            if t == (".",):
                expanded.extend(
                    (n,) for n in attr_names if n != label
                )
            else:
                expanded.append(t)
        for t in exclude:
            for f in t:
                if f not in attr_names:
                    raise ValueError(
                        f"unknown column {f!r} in formula exclusion"
                    )
        removed = {t for t in exclude}
        terms = [t for t in expanded if t not in removed]
        # dedupe, preserving first occurrence (R keeps term order)
        seen: set = set()
        terms = [t for t in terms if not (t in seen or seen.add(t))]
        if not terms:
            raise ValueError(f"formula {self.params.formula!r} selects no terms")

        plan = []
        out_vars: list[ContinuousVariable] = []
        # R rule: without an intercept, the FIRST categorical main effect is
        # full-coded (all k levels) so the column space still spans the mean
        full_code_budget = 0 if intercept else 1
        for t in terms:
            factors = []
            factor_names: list[list[str]] = []
            for f in t:
                if f == label:
                    raise ValueError(f"label {label!r} cannot be a feature term")
                if f not in attr_names:
                    raise ValueError(f"unknown column {f!r} in formula")
                j = attr_names.index(f)
                var = domain.attributes[j]
                if isinstance(var, DiscreteVariable) and var.values:
                    k = len(var.values)
                    if len(t) == 1 and full_code_budget:
                        full_code_budget = 0
                        factors.append((j, -k))       # full coding marker
                        factor_names.append(
                            [f"{f}_{v}" for v in var.values]
                        )
                    else:
                        factors.append((j, k - 1))
                        factor_names.append(
                            [f"{f}_{v}" for v in var.values[1:]]
                        )
                else:
                    factors.append((j, 0))
                    factor_names.append([f])
            plan.append((":".join(t), factors))
            for combo in itertools.product(*factor_names):
                out_vars.append(ContinuousVariable(":".join(combo)))
        out_domain = Domain(out_vars, label_var, domain.metas)
        model = RFormulaModel(self.params, plan, out_domain, label_var, label_src)
        model.has_intercept = intercept
        return model
