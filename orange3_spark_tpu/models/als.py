"""ALS matrix factorization — parity with ``pyspark.ml.recommendation.ALS``.

MLlib's ALS partitions users/items into blocks, shuffles rating blocks
between executors each half-iteration, and solves per-entity normal equations
with ALS-WR weighted regularization (SURVEY.md §2b row "ALS"; reconstructed,
mount empty). TPU-native redesign:

* ratings live as three row-sharded vectors (user_idx, item_idx, rating) —
  COO, P('data') — never a dense matrix;
* each half-step gathers the fixed side's factors for every rating, forms
  per-rating outer products and ``segment_sum``s them into per-entity normal
  equations A·x=b — XLA turns the segment reduction over the sharded row axis
  into local scatter-adds plus one ICI all-reduce (MLlib's block shuffle,
  collapsed into a collective);
* the rating stream is processed in fixed-size chunks under ``lax.scan`` so
  the [chunk, k, k] outer-product tensor stays HBM-resident at chunk size,
  never [N, k, k];
* all per-entity solves are one batched Cholesky (``jnp.linalg.solve`` on
  [n_entities, k, k]) — MXU-batched, no per-user Python;
* the full fit (both sides × max_iter) is a single jitted ``lax.scan``.

Implicit feedback uses MLlib's confidence weighting c = 1 + alpha·r with the
VᵀV precompute trick (one [k,k] Gramian + corrections only for observed
entries).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params


@dataclasses.dataclass(frozen=True)
class ALSParams(Params):
    rank: int = 10                 # MLlib rank
    max_iter: int = 10             # MLlib maxIter
    reg_param: float = 0.1         # MLlib regParam (ALS-WR: scaled by n_ratings)
    implicit_prefs: bool = False   # MLlib implicitPrefs
    alpha: float = 1.0             # MLlib alpha (implicit confidence)
    nonnegative: bool = False      # MLlib nonnegative: batched NNLS solves
    nnls_sweeps: int = 48          # coordinate-descent sweeps per NNLS solve
    n_users: int = 0               # explicit user-dim (0 = infer from data max)
    n_items: int = 0               # explicit item-dim (0 = infer from data max)
    seed: int = 0                  # MLlib seed
    user_col: str = "user"         # MLlib userCol
    item_col: str = "item"         # MLlib itemCol
    rating_col: str = "rating"     # MLlib ratingCol
    cold_start_strategy: str = "nan"  # MLlib coldStartStrategy: 'nan' | 'drop'
    chunk_size: int = 1 << 18      # ratings per scan chunk (HBM knob)
    # 'auto': shard the factor tables over the mesh's model axis whenever
    # the session has one wider than 1 (the scale-out story for factor
    # tables wider than one chip's HBM — MLlib's user/item blocks, as
    # GSPMD shardings + one reduce-scatter instead of a block shuffle);
    # 'model' demands it (raises without a model axis); 'replicated'
    # pins the round-3 behavior.
    factor_sharding: str = "auto"  # 'auto' | 'model' | 'replicated'


def _nnls_cd(A, b, x0, sweeps: int):
    """Batched NNLS: min_x 0.5 xᵀAx - bᵀx s.t. x >= 0, for PSD A.

    Cyclic projected coordinate descent (x_j <- max(0, x_j - g_j/A_jj)),
    ``sweeps`` full cycles, vectorized over all entities at once — the
    TPU-shaped replacement for MLlib's per-entity active-set NNLS (one
    [n_entities]-wide VPU update per coordinate, no data-dependent loops).
    Warm-started from the clipped unconstrained solve, convergence is linear;
    48 sweeps puts KKT residuals below 1e-4 at rank<=64 in practice.

    A: [n, k, k], b: [n, k], x0: [n, k] -> [n, k]
    """
    k = b.shape[1]
    diag = jnp.maximum(jnp.diagonal(A, axis1=1, axis2=2), 1e-12)  # [n, k]

    def coord(j, x):
        Aj = jax.lax.dynamic_slice_in_dim(A, j, 1, axis=1)[:, 0, :]  # [n, k]
        g = jnp.sum(Aj * x, axis=1) - jax.lax.dynamic_slice_in_dim(
            b, j, 1, axis=1)[:, 0]
        dj = jax.lax.dynamic_slice_in_dim(diag, j, 1, axis=1)[:, 0]
        xj = jax.lax.dynamic_slice_in_dim(x, j, 1, axis=1)[:, 0]
        new = jnp.maximum(0.0, xj - g / dj)
        return jax.lax.dynamic_update_slice_in_dim(
            x, new[:, None], j, axis=1
        )

    def sweep(_, x):
        return jax.lax.fori_loop(0, k, coord, x)

    return jax.lax.fori_loop(0, sweeps, sweep, jnp.maximum(x0, 0.0))


def _solve_side(idx, other_idx, rating, w, other_factors, n_entities: int,
                reg: float, implicit: bool, alpha: float, chunk: int,
                nonnegative: bool = False, nnls_sweeps: int = 48):
    """Normal-equation solve for one side given the other side's factors."""
    k = other_factors.shape[1]
    n = idx.shape[0]
    n_chunks = max(1, -(-n // chunk))
    pad = n_chunks * chunk - n
    idx_p = jnp.pad(idx, (0, pad)).reshape(n_chunks, chunk)
    oidx_p = jnp.pad(other_idx, (0, pad)).reshape(n_chunks, chunk)
    r_p = jnp.pad(rating, (0, pad)).reshape(n_chunks, chunk)
    w_p = jnp.pad(w, (0, pad)).reshape(n_chunks, chunk)  # 0 on padding

    def body(carry, args):
        A, b, cnt = carry
        ci, coi, cr, cw = args
        V = other_factors[coi]                       # [chunk, k] gather
        if implicit:
            # MLlib implicit: confidence c = 1 + alpha*|r| (negative feedback
            # raises confidence too), preference p = 1 iff r > 0
            conf = 1.0 + alpha * jnp.abs(cr)
            pref = (cr > 0).astype(jnp.float32)
            outer = jnp.einsum("ni,nj->nij", V, V) * ((conf - 1.0) * cw)[:, None, None]
            rhs = V * (conf * pref * cw)[:, None]
        else:
            outer = jnp.einsum("ni,nj->nij", V, V) * cw[:, None, None]
            rhs = V * (cr * cw)[:, None]
        A = A + jax.ops.segment_sum(outer.reshape(chunk, k * k), ci,
                                    num_segments=n_entities).reshape(n_entities, k, k)
        b = b + jax.ops.segment_sum(rhs, ci, num_segments=n_entities)
        cnt = cnt + jax.ops.segment_sum(cw, ci, num_segments=n_entities)
        return (A, b, cnt), None

    A0 = jnp.zeros((n_entities, k, k), jnp.float32)
    b0 = jnp.zeros((n_entities, k), jnp.float32)
    c0 = jnp.zeros((n_entities,), jnp.float32)
    (A, b, cnt), _ = jax.lax.scan(body, (A0, b0, c0), (idx_p, oidx_p, r_p, w_p))

    if implicit:
        # global VᵀV base + per-entry corrections already in A
        VtV = other_factors.T @ other_factors
        A = A + VtV[None, :, :]
        lam = reg  # implicit MLlib: plain lambda (no WR scaling)
    else:
        lam = reg  # multiplied by per-entity rating count below (ALS-WR)
    eye = jnp.eye(k, dtype=jnp.float32)
    reg_scale = cnt if not implicit else jnp.ones_like(cnt)
    A = A + (lam * jnp.maximum(reg_scale, 1.0))[:, None, None] * eye
    x = jnp.linalg.solve(A, b[..., None])[..., 0]  # [n_entities, k]
    if nonnegative:
        x = _nnls_cd(A, b, x, nnls_sweeps)
    return x


def _als_init(seed: int, n_users: int, n_items: int, rank: int):
    """Factor init, EAGER on purpose: generated inside ``_als_fit`` the
    GSPMD sharding constraint on the factors propagates backward into the
    ``jax.random.normal`` lowering, and with this jaxlib's default
    non-partitionable threefry the generated BITS then depend on the
    factor sharding — a model-axis-sharded fit started from a different
    random init than the replicated fit and diverged wholesale (the
    round-5 'ALS-sharding drift' failures, root-caused this round).
    Outside any jit the generation is never partitioned, so every layout
    starts from identical factors."""
    key_u, key_v = jax.random.split(jax.random.PRNGKey(seed))
    # MLlib init: abs(normal)/sqrt(rank) keeps initial predictions positive
    U = jnp.abs(jax.random.normal(key_u, (n_users, rank))) / jnp.sqrt(rank)
    V = jnp.abs(jax.random.normal(key_v, (n_items, rank))) / jnp.sqrt(rank)
    return U, V


@partial(
    jax.jit,
    static_argnames=("n_users", "n_items", "rank", "max_iter", "implicit",
                     "chunk", "nonnegative", "nnls_sweeps", "factor_sharding"),
)
def _als_fit(user_idx, item_idx, rating, w, U, V, *, n_users: int,
             n_items: int, rank: int, max_iter: int, reg: float,
             implicit: bool, alpha: float, chunk: int,
             nonnegative: bool = False, nnls_sweeps: int = 48,
             factor_sharding=None):
    """factor_sharding: optional NamedSharding (hashable, static) pinning the
    factor tables over the mesh's 'model' axis — entities shard, so each
    half-step's batched Cholesky/NNLS solves run model-parallel and GSPMD
    reduce-scatters the segment-summed normal equations (MLlib's rating-block
    shuffle, as one collective over ICI). ``U``/``V`` arrive pre-initialized
    (``_als_init`` — see its docstring for why init must stay eager)."""

    def pin(F):
        if factor_sharding is None:
            return F
        return jax.lax.with_sharding_constraint(F, factor_sharding)

    U, V = pin(U), pin(V)

    def one_iter(carry, _):
        U, V = carry
        U = pin(_solve_side(user_idx, item_idx, rating, w, V, n_users,
                            reg, implicit, alpha, chunk,
                            nonnegative, nnls_sweeps))
        V = pin(_solve_side(item_idx, user_idx, rating, w, U, n_items,
                            reg, implicit, alpha, chunk,
                            nonnegative, nnls_sweeps))
        return (U, V), None

    (U, V), _ = jax.lax.scan(one_iter, (U, V), None, length=max_iter)
    return U, V


@jax.jit
def _predict_pairs(U, V, user_idx, item_idx):
    return jnp.sum(U[user_idx] * V[item_idx], axis=1)


class ALSModel(Model):
    def __init__(self, params, user_factors, item_factors):
        self.params = params
        self.user_factors = user_factors  # f32[n_users, k]
        self.item_factors = item_factors  # f32[n_items, k]

    @property
    def state_pytree(self):
        return {"user_factors": self.user_factors, "item_factors": self.item_factors}

    def _cols(self, table: TpuTable):
        p = self.params
        u = table.column(p.user_col).astype(jnp.int32)
        i = table.column(p.item_col).astype(jnp.int32)
        return u, i

    def transform(self, table: TpuTable) -> TpuTable:
        """Append 'prediction' (Spark: predicted rating per (user,item) row).

        Cold-start rows (unseen user/item index) follow cold_start_strategy:
        'nan' marks them NaN; 'drop' zero-weights them (static shapes — the
        Spark row-drop equivalent under our filter semantics).
        """
        u, i = self._cols(table)
        n_u = self.user_factors.shape[0]
        n_i = self.item_factors.shape[0]
        pred = _predict_pairs(self.user_factors, self.item_factors,
                              jnp.clip(u, 0, n_u - 1), jnp.clip(i, 0, n_i - 1))
        cold = (u < 0) | (u >= n_u) | (i < 0) | (i >= n_i)
        W = table.W
        if self.params.cold_start_strategy == "drop":
            W = jnp.where(cold, 0.0, W)
        else:
            pred = jnp.where(cold, jnp.nan, pred)
        new_domain = Domain(
            list(table.domain.attributes) + [ContinuousVariable("prediction")],
            table.domain.class_vars, table.domain.metas,
        )
        X = jnp.concatenate([table.X, pred[:, None]], axis=1)
        out = table.with_X(X, new_domain)
        return out.with_weights(W)

    def recommend_for_all_users(self, num_items: int) -> np.ndarray:
        """Top-N items per user: one U@Vᵀ MXU matmul + device top_k.

        Returns int32 [n_users, num_items]. (MLlib recommendForAllUsers.)
        """
        scores = self.user_factors @ self.item_factors.T
        _, top = jax.lax.top_k(scores, num_items)
        return np.asarray(top)

    def recommend_for_all_items(self, num_users: int) -> np.ndarray:
        scores = self.item_factors @ self.user_factors.T
        _, top = jax.lax.top_k(scores, num_users)
        return np.asarray(top)


class ALS(Estimator):
    ParamsCls = ALSParams
    params: ALSParams

    def _fit(self, table: TpuTable) -> ALSModel:
        p = self.params
        u = table.column(p.user_col).astype(jnp.int32)
        i = table.column(p.item_col).astype(jnp.int32)
        r = table.column(p.rating_col)
        # one device->host sync for the observed index range; with explicit
        # dims it becomes a RANGE CHECK (a fit that silently clipped or
        # under-sized its factor tables would be quietly wrong)
        max_u = int(np.asarray(jnp.max(jnp.where(table.W > 0, u, 0))).item())
        max_i = int(np.asarray(jnp.max(jnp.where(table.W > 0, i, 0))).item())
        if p.n_users > 0:
            if max_u >= p.n_users:
                raise ValueError(
                    f"user index {max_u} out of range for n_users={p.n_users}"
                )
            n_users = p.n_users
        else:
            n_users = max_u + 1
        if p.n_items > 0:
            if max_i >= p.n_items:
                raise ValueError(
                    f"item index {max_i} out of range for n_items={p.n_items}"
                )
            n_items = p.n_items
        else:
            n_items = max_i + 1
        session = table.session
        if p.factor_sharding not in ("auto", "model", "replicated"):
            raise ValueError(
                f"factor_sharding must be 'auto' | 'model' | 'replicated', "
                f"got {p.factor_sharding!r}")
        has_model_axis = (session is not None
                          and session.model_axis is not None
                          and session.mesh.shape.get(session.model_axis, 1) > 1)
        if p.factor_sharding == "model" and not has_model_axis:
            raise ValueError(
                "factor_sharding='model' needs a session mesh with a model "
                "axis wider than 1 (e.g. jax.make_mesh((dp, mp), "
                "('data', 'model')))")
        factor_sharding = None
        if p.factor_sharding != "replicated" and has_model_axis:
            factor_sharding = session.sharding(session.model_axis, None)
        U0, V0 = _als_init(p.seed, n_users, n_items, p.rank)
        U, V = _als_fit(
            u, i, r, table.W, U0, V0,
            n_users=n_users, n_items=n_items, rank=p.rank, max_iter=p.max_iter,
            reg=p.reg_param, implicit=p.implicit_prefs, alpha=p.alpha,
            chunk=min(p.chunk_size, table.n_pad),
            nonnegative=p.nonnegative, nnls_sweeps=p.nnls_sweeps,
            factor_sharding=factor_sharding,
        )
        return ALSModel(p, U, V)


def ratings_table(ratings: np.ndarray, session=None, *,
                  user_col="user", item_col="item", rating_col="rating") -> TpuTable:
    """[n,3] (user, item, rating) float array -> ALS-ready TpuTable."""
    domain = Domain([
        ContinuousVariable(user_col),
        ContinuousVariable(item_col),
        ContinuousVariable(rating_col),
    ])
    return TpuTable.from_numpy(domain, ratings, session=session)
