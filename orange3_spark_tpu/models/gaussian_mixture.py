"""GaussianMixture — parity with ``pyspark.ml.clustering.GaussianMixture``.

MLlib runs full-covariance EM, one treeAggregate per iteration to sum the
expected sufficient statistics (SURVEY.md §2b; reconstructed, mount empty —
public API: k, maxIter=100, tol=0.01, seed, weightCol; model exposes
``weights``, ``gaussiansDF`` (mean, cov), ``predict``, ``predictProbability``,
``summary.logLikelihood``). TPU-native redesign:

* E-step log-densities via one batched Cholesky: ``cholesky([k,d,d])`` then a
  batched triangular solve of ``[k,d,N]`` — the quadratic forms and the
  responsibilities are MXU-batched, no per-component Python loop;
* M-step sufficient statistics are two matmuls (``RᵀX`` for means,
  ``einsum('nk,nd,ne->kde')`` for scatter) whose row-axis contraction GSPMD
  all-reduces over ICI — the treeAggregate moment;
* the whole EM loop is a single jitted ``lax.while_loop`` with MLlib's
  convergence test (|Δ mean log-likelihood| < tol).

Row weights ``W`` fold into the responsibilities, so padding/filtered rows
(W == 0) contribute nothing to any statistic.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import concrete_or_none, Estimator, Model, Params

_LOG2PI = float(np.log(2.0 * np.pi))


@dataclasses.dataclass(frozen=True)
class GaussianMixtureParams(Params):
    k: int = 2                 # MLlib k
    max_iter: int = 100        # MLlib maxIter
    tol: float = 0.01          # MLlib tol (mean log-likelihood delta)
    seed: int = 0              # MLlib seed
    reg_covar: float = 1e-6    # diagonal jitter (beyond MLlib; keeps Cholesky sane)
    init_sample_size: int = 8192


@partial(jax.jit, static_argnames=("k",))
def _log_resp(X, W, weights, means, chols, *, k: int):
    """Per-row component log-joints and the weighted total log-likelihood.

    chols: f32[k,d,d] lower Cholesky factors of the covariances.
    Returns (log_joint [N,k], loglik scalar).
    """
    d = X.shape[1]
    diff = X[None, :, :] - means[:, None, :]                      # [k,N,d]
    # batched triangular solve: z_c = L_c^{-1} (x - mu_c)^T  -> [k,d,N]
    z = jax.lax.linalg.triangular_solve(
        chols, jnp.swapaxes(diff, 1, 2), left_side=True, lower=True
    )
    quad = jnp.sum(z * z, axis=1)                                  # [k,N]
    logdet = 2.0 * jnp.sum(
        jnp.log(jnp.diagonal(chols, axis1=1, axis2=2)), axis=1
    )                                                              # [k]
    log_pdf = -0.5 * (d * _LOG2PI + logdet[:, None] + quad)        # [k,N]
    log_joint = log_pdf.T + jnp.log(weights)[None, :]              # [N,k]
    lse = jax.scipy.special.logsumexp(log_joint, axis=1)
    loglik = jnp.sum(jnp.where(W > 0, lse * W, 0.0))
    return log_joint, loglik


@partial(jax.jit, static_argnames=("k", "max_iter"))
def _em(X, W, weights0, means0, covs0, tol, reg, *, k: int, max_iter: int):
    d = X.shape[1]
    eye = jnp.eye(d, dtype=X.dtype)
    w_total = jnp.sum(W)

    def e_then_m(weights, means, covs):
        chols = jnp.linalg.cholesky(covs + reg * eye[None])
        log_joint, loglik = _log_resp(X, W, weights, means, chols, k=k)
        resp = jax.nn.softmax(log_joint, axis=1) * W[:, None]      # [N,k]
        nk = jnp.sum(resp, axis=0)                                 # [k]
        nk_safe = jnp.maximum(nk, 1e-12)
        new_means = (resp.T @ X) / nk_safe[:, None]                # [k,d] MXU
        # per-component scatter (X·diag(r_c)·X) via lax.map keeps the
        # intermediate at O(N·d) instead of the O(k·N·d) / O(N·d²) tensor a
        # three-operand einsum would materialize each EM iteration
        scatter = jax.lax.map(
            lambda rc: jnp.dot(
                (X * rc[:, None]).T, X, preferred_element_type=jnp.float32
            ),
            resp.T,
        )                                                          # [k,d,d]
        new_covs = scatter / nk_safe[:, None, None] - jnp.einsum(
            "kd,ke->kde", new_means, new_means
        )
        new_weights = nk / jnp.maximum(w_total, 1e-12)
        return new_weights, new_means, new_covs, loglik

    def body(carry):
        weights, means, covs, prev_ll, _, it = carry
        weights, means, covs, ll = e_then_m(weights, means, covs)
        converged = jnp.abs(ll - prev_ll) / jnp.maximum(w_total, 1.0) < tol
        return weights, means, covs, ll, converged, it + 1

    def keep_going(carry):
        _, _, _, _, converged, it = carry
        return (it < max_iter) & ~converged

    weights, means, covs, ll, _, n_iter = jax.lax.while_loop(
        keep_going, body,
        (weights0, means0, covs0, jnp.float32(-jnp.inf), False, 0),
    )
    return weights, means, covs + reg * eye[None], ll, n_iter


class GaussianMixtureModel(Model):
    def __init__(self, params, weights, means, covs):
        self.params = params
        self.weights = weights   # f32[k]
        self.means = means       # f32[k,d]
        self.covs = covs         # f32[k,d,d]
        self.n_iter_: int | None = None
        self.log_likelihood_: float | None = None  # summary.logLikelihood

    @property
    def state_pytree(self):
        return {"weights": self.weights, "means": self.means, "covs": self.covs}

    def _log_joint(self, table: TpuTable):
        chols = jnp.linalg.cholesky(self.covs)
        log_joint, _ = _log_resp(
            table.X, table.W, self.weights, self.means, chols,
            k=self.params.k,
        )
        return log_joint

    def predict(self, table: TpuTable) -> np.ndarray:
        return np.asarray(jnp.argmax(self._log_joint(table), axis=1))[: table.n_rows]

    def predict_probability(self, table: TpuTable) -> np.ndarray:
        """MLlib predictProbability — posterior responsibilities [n, k]."""
        probs = jax.nn.softmax(self._log_joint(table), axis=1)
        return np.asarray(probs)[: table.n_rows]

    def log_likelihood(self, table: TpuTable) -> float:
        chols = jnp.linalg.cholesky(self.covs)
        _, ll = _log_resp(
            table.X, table.W, self.weights, self.means, chols, k=self.params.k
        )
        return float(ll)

    def transform(self, table: TpuTable) -> TpuTable:
        """Append 'prediction' + per-component 'probability_i' columns."""
        log_joint = self._log_joint(table)
        probs = jax.nn.softmax(log_joint, axis=1)
        pred = jnp.argmax(log_joint, axis=1).astype(jnp.float32)
        k = self.params.k
        new_attrs = (
            list(table.domain.attributes)
            + [DiscreteVariable("prediction", tuple(str(i) for i in range(k)))]
            + [ContinuousVariable(f"probability_{i}") for i in range(k)]
        )
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, pred[:, None], probs], axis=1)
        return table.with_X(X, new_domain)


class GaussianMixture(Estimator):
    ParamsCls = GaussianMixtureParams
    params: GaussianMixtureParams

    def _device_init(self, table: TpuTable):
        """Tracer-safe init for staged refit (workflow/staging.py): means
        by device-pure D²-categorical seeding (models/kmeans.py
        ``device_d2_seed``), shared diagonal covariance from the weighted
        full-data variance. Deterministic per seed, but a different random
        stream than the host-sample init (same documented caveat as
        KMeans)."""
        from orange3_spark_tpu.models.kmeans import (
            device_d2_seed, device_sample_live,
        )

        p = self.params
        X, W = table.X, table.W
        k0, k1 = jax.random.split(jax.random.PRNGKey(p.seed))
        # D² seeding on a live subsample, like the eager host init — full-
        # data seeding costs k distance passes over N rows inside the trace
        ks, k0b = jax.random.split(k0)
        Xs, Ws = device_sample_live(X, W, p.init_sample_size, ks)
        means0 = device_d2_seed(Xs, Ws, p.k, k0b, k1)
        wsum = jnp.maximum(jnp.sum(W), 1e-12)
        mean = jnp.sum(X * W[:, None], axis=0) / wsum
        var = jnp.maximum(
            jnp.sum(((X - mean) ** 2) * W[:, None], axis=0) / wsum, 1e-3
        )
        covs0 = jnp.tile(jnp.diag(var)[None], (p.k, 1, 1))
        weights0 = jnp.full((p.k,), 1.0 / p.k, dtype=jnp.float32)
        return weights0, means0, covs0

    def _init(self, table: TpuTable):
        """kmeans++-style seeding on a host sample; shared covariance init."""
        p = self.params
        if isinstance(table.X, jax.core.Tracer):
            return self._device_init(table)
        rng = np.random.default_rng(p.seed)
        live = np.flatnonzero(np.asarray(jax.device_get(table.W)) > 0)
        if len(live) == 0:
            raise ValueError("cannot fit GaussianMixture: table has no live rows")
        m = min(len(live), p.init_sample_size)
        idx = live[rng.choice(len(live), size=m, replace=False)] if m < len(live) else live
        sample = np.asarray(jax.device_get(table.X[np.sort(idx)]))
        centers = [sample[rng.integers(m)]]
        d2 = np.sum((sample - centers[0]) ** 2, axis=1)
        for _ in range(1, p.k):
            s = d2.sum()
            c = sample[rng.choice(m, p=d2 / s)] if s > 0 else sample[rng.integers(m)]
            centers.append(c)
            d2 = np.minimum(d2, np.sum((sample - c) ** 2, axis=1))
        means0 = np.stack(centers).astype(np.float32)
        var = np.maximum(sample.var(axis=0), 1e-3).astype(np.float32)
        covs0 = np.tile(np.diag(var)[None], (p.k, 1, 1))
        weights0 = np.full((p.k,), 1.0 / p.k, dtype=np.float32)
        rep = table.session.replicated
        return (
            jax.device_put(weights0, rep),
            jax.device_put(means0, rep),
            jax.device_put(covs0, rep),
        )

    def _fit(self, table: TpuTable) -> GaussianMixtureModel:
        p = self.params
        weights0, means0, covs0 = self._init(table)
        weights, means, covs, ll, n_iter = _em(
            table.X, table.W, weights0, means0, covs0,
            jnp.float32(p.tol), jnp.float32(p.reg_covar),
            k=p.k, max_iter=p.max_iter,
        )
        model = GaussianMixtureModel(p, weights, means, covs)
        model.n_iter_ = concrete_or_none(n_iter, int)
        model.log_likelihood_ = concrete_or_none(ll)
        # MLlib summary.clusterSizes, through model._log_joint so sizes
        # can never disagree with model.predict. The extra E-step pass is
        # deliberate eager work: Spark's GaussianMixtureSummary likewise
        # materializes its predictions at fit time (~1 EM iteration cost).
        from orange3_spark_tpu.models.kmeans import live_cluster_sizes

        assign = jnp.argmax(model._log_joint(table), axis=1)
        model.cluster_sizes_ = live_cluster_sizes(table.W, assign, p.k)
        return model
