"""AFTSurvivalRegression — parity with ``pyspark.ml.regression.AFTSurvivalRegression``.

MLlib fits a Weibull accelerated-failure-time model by L-BFGS on the
censored log-likelihood, one treeAggregate of (loss, grad) per iteration
(SURVEY.md §2b; reconstructed, mount empty — public API: censorCol (1 =
event/uncensored, 0 = right-censored), quantileProbabilities, quantilesCol,
maxIter=100, tol=1e-6, fitIntercept; model exposes coefficients, intercept,
scale, predict = exp(x·b + b0), predictQuantiles). TPU-native redesign: the
entire L-BFGS loop (optax.lbfgs with zoom linesearch) runs inside one jitted
``lax.while_loop``; the row-axis loss contraction GSPMD all-reduces over ICI
— same fused-trainer shape as ``_linear.fit_linear`` with the AFT loss:

    eps_i = (log t_i - x_i·beta - b0) / sigma
    logL  = sum_i  delta_i * (eps_i - log sigma) - exp(eps_i)

optimized over (beta, b0, log sigma) — log-parameterizing sigma keeps the
problem unconstrained exactly as MLlib does.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from orange3_spark_tpu.models._linear import lbfgs_minimize
from orange3_spark_tpu.core.domain import ContinuousVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import concrete_or_none, Estimator, Model, Params


@dataclasses.dataclass(frozen=True)
class AFTSurvivalRegressionParams(Params):
    censor_col: str = "censor"   # MLlib censorCol (1=event, 0=censored)
    max_iter: int = 100          # MLlib maxIter
    tol: float = 1e-6            # MLlib tol
    fit_intercept: bool = True
    quantile_probabilities: tuple = (0.01, 0.05, 0.1, 0.25, 0.5,
                                     0.75, 0.9, 0.95, 0.99)  # MLlib default


@partial(jax.jit, static_argnames=("fit_intercept", "max_iter"))
def _fit_aft(X, logt, delta, w, tol, *, fit_intercept: bool, max_iter: int):
    d = X.shape[1]
    sum_w = jnp.maximum(jnp.sum(w), 1e-12)

    def neg_loglik(theta):
        eta = X @ theta["beta"] + (theta["b0"] if fit_intercept else 0.0)
        log_sigma = theta["log_sigma"]
        eps = (logt - eta) * jnp.exp(-log_sigma)
        # guard exp overflow on padding rows (w=0 zeroes them anyway)
        ll_rows = delta * (eps - log_sigma) - jnp.exp(jnp.clip(eps, -50.0, 50.0))
        return -jnp.sum(w * ll_rows) / sum_w

    theta0 = {
        "beta": jnp.zeros((d,), jnp.float32),
        "b0": jnp.float32(0.0),
        "log_sigma": jnp.float32(0.0),
    }
    theta, n_iter, _ = lbfgs_minimize(neg_loglik, theta0, tol, max_iter)
    return theta, n_iter


class AFTSurvivalRegressionModel(Model):
    def __init__(self, params, coef, intercept, scale, feature_indices=None):
        self.params = params
        self.coef = coef            # f32[d]
        self.intercept = intercept  # f32[]
        self.scale = scale          # f32[] Weibull scale sigma
        self.feature_indices = feature_indices  # columns used (censor col excluded)
        self.n_iter_: int | None = None

    def _features(self, table: TpuTable):
        if self.feature_indices is None:
            return table.X
        return table.X[:, jnp.asarray(self.feature_indices)]

    @property
    def state_pytree(self):
        return {"coef": self.coef, "intercept": self.intercept, "scale": self.scale}

    def predict(self, table: TpuTable) -> np.ndarray:
        """Expected scale of survival time: exp(x·b + b0) (MLlib predict)."""
        eta = self._features(table) @ self.coef + self.intercept
        return np.asarray(jnp.exp(eta))[: table.n_rows]

    def predict_quantiles(self, table: TpuTable) -> np.ndarray:
        """MLlib predictQuantiles: t_p = exp(eta) * (-log(1-p))^sigma."""
        eta = self._features(table) @ self.coef + self.intercept
        probs = jnp.asarray(self.params.quantile_probabilities, dtype=jnp.float32)
        q = jnp.exp(eta)[:, None] * (-jnp.log1p(-probs)) ** self.scale
        return np.asarray(q)[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        eta = self._features(table) @ self.coef + self.intercept
        new_attrs = list(table.domain.attributes) + [ContinuousVariable("prediction")]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        return table.with_X(
            jnp.concatenate([table.X, jnp.exp(eta)[:, None]], axis=1), new_domain
        )


class AFTSurvivalRegression(Estimator):
    ParamsCls = AFTSurvivalRegressionParams
    params: AFTSurvivalRegressionParams

    def _fit(self, table: TpuTable) -> AFTSurvivalRegressionModel:
        p = self.params
        if table.y is None:
            raise ValueError("AFTSurvivalRegression needs a survival-time target")
        names = [v.name for v in table.domain.attributes]
        if p.censor_col not in names:
            raise ValueError(
                f"censor column {p.censor_col!r} not among attributes {names}"
            )
        ci = names.index(p.censor_col)
        delta = table.X[:, ci]
        keep = [i for i in range(len(names)) if i != ci]
        X = table.X[:, jnp.asarray(keep)]
        logt = jnp.log(jnp.maximum(table.y, 1e-12))
        theta, n_iter = _fit_aft(
            X, logt, delta, table.W, jnp.float32(p.tol),
            fit_intercept=p.fit_intercept, max_iter=p.max_iter,
        )
        model = AFTSurvivalRegressionModel(
            p, theta["beta"], theta["b0"], jnp.exp(theta["log_sigma"]),
            feature_indices=keep,
        )
        model.n_iter_ = concrete_or_none(n_iter, int)
        return model
