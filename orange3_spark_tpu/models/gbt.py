"""Gradient-boosted trees — parity with ``pyspark.ml.classification.GBTClassifier``
and GBTRegressor.

MLlib boosts depth-limited trees on residuals with variance-based splits
(SURVEY.md §2b; reconstructed, mount empty). This implementation boosts on
GRADIENT/HESSIAN histograms (XGBoost-style second-order gains and leaf
values) — a strict quality upgrade at identical per-round cost, since the
histogram machinery (_tree.py) is shared with RandomForest. Each round is one
jitted device program (bin lookup reused, no rebinning); the margin vector F
stays device-resident across rounds.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models._tree import (
    normalize_importances,
    Tree,
    bin_features,
    compute_bin_edges,
    grow_tree,
    leaf_newton_values,
    tree_apply,
)
from orange3_spark_tpu.models.base import Estimator, Model, Params
from orange3_spark_tpu.utils.dispatch import bound_dispatch

EPS = 1e-12


@dataclasses.dataclass(frozen=True)
class GBTParams(Params):
    max_iter: int = 20            # MLlib maxIter (number of trees)
    max_depth: int = 5            # MLlib maxDepth
    step_size: float = 0.1        # MLlib stepSize (learning rate)
    max_bins: int = 32            # MLlib maxBins
    min_instances_per_node: float = 1.0
    min_info_gain: float = 0.0
    subsampling_rate: float = 1.0 # MLlib subsamplingRate
    reg_lambda: float = 1.0       # newton leaf regularization (beyond MLlib)
    seed: int = 0


from functools import partial  # noqa: E402


@partial(jax.jit, static_argnames=("p", "loss", "depth", "n_bins"))
def _gbt_round(F, B, edges, W, y, boot_key, *, p: GBTParams, loss: str,
               depth: int, n_bins: int):
    """One boosting round. Module-level + GBTParams as a static arg (frozen
    dataclass, hashable) so repeated fits with the same hyper-params and
    shapes hit the jit cache instead of recompiling."""
    N, d = B.shape
    feat_keep = jnp.ones((depth, d), jnp.float32)
    boot = (
        jax.random.poisson(boot_key, p.subsampling_rate, (N,)).astype(jnp.float32)
        if p.subsampling_rate != 1.0 else jnp.ones((N,), jnp.float32)
    )
    w = W * boot
    if loss == "logistic":
        prob = jax.nn.sigmoid(F)
        g = (prob - y) * w
        h = jnp.maximum(prob * (1 - prob), 1e-6) * w
    else:  # squared
        g = (F - y) * w
        h = w
    S = jnp.stack([g, h, w], axis=1)
    tree, leaf_idx, imp = grow_tree(
        B, S, edges, feat_keep, jnp.float32(p.min_info_gain),
        depth=depth, n_bins=n_bins, gain_mode="newton", reg=p.reg_lambda,
        min_instances=p.min_instances_per_node,
    )
    values = leaf_newton_values(tree.leaf_value, p.reg_lambda)  # [L]
    F_new = F + p.step_size * values[leaf_idx]
    # store leaf scalar values in leaf_value[..., :1] for serving
    tree = tree._replace(leaf_value=values[:, None])
    # per-tree-normalized, as MLlib's ensemble featureImportances expects
    return F_new, tree, normalize_importances(imp)


def _boost(B, edges, W, y, depth, n_bins, p: GBTParams, loss: str):
    """Sequential boosting loop; rounds share one cached jitted program."""
    N, _ = B.shape
    key = jax.random.PRNGKey(p.seed)
    if loss == "logistic":
        pos_w = jnp.sum(jnp.where(y > 0, W, 0.0))
        tot_w = jnp.maximum(jnp.sum(W), EPS)
        prior = jnp.clip(pos_w / tot_w, 1e-6, 1 - 1e-6)
        f0 = jnp.log(prior / (1 - prior))
    else:
        f0 = jnp.sum(y * W) / jnp.maximum(jnp.sum(W), EPS)
    F = jnp.full((N,), f0)

    trees = []
    imps = []
    for r in range(p.max_iter):
        key, sub = jax.random.split(key)
        F, tree, imp = _gbt_round(F, B, edges, W, y, sub, p=p, loss=loss,
                                  depth=depth, n_bins=n_bins)
        trees.append(tree)
        imps.append(imp)
        # rounds are heavyweight: keep at most 4 in flight
        # (utils/dispatch.py has the full story on the XLA:CPU rendezvous
        # wedge this prevents)
        bound_dispatch(r + 1, F, period=4)
    jax.block_until_ready(trees)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
    # MLlib ensemble featureImportances: mean of per-tree-normalized,
    # renormalized
    imp = normalize_importances(jnp.mean(jnp.stack(imps), axis=0))
    return float(f0), stacked, imp


@jax.jit
def _gbt_margin(X, f0, step_size, forest: Tree):
    leaves = jax.vmap(lambda t: tree_apply(X, t))(forest)            # [T, N]
    vals = jnp.take_along_axis(forest.leaf_value[..., 0], leaves, 1)  # [T, N]
    return f0 + step_size * jnp.sum(vals, axis=0)


class GBTClassifierModel(Model):
    def __init__(self, params, f0, forest: Tree, class_values):
        self.params = params
        self.f0 = f0
        self.forest = forest
        self.class_values = tuple(class_values)

    @property
    def state_pytree(self):
        return {"f0": jnp.float32(self.f0), **self.forest._asdict()}

    def _margin(self, X):
        return _gbt_margin(X, self.f0, self.params.step_size, self.forest)

    def predict_proba(self, table: TpuTable) -> np.ndarray:
        p1 = jax.nn.sigmoid(self._margin(table.X))
        return np.asarray(jnp.stack([1 - p1, p1], 1))[: table.n_rows]

    def predict(self, table: TpuTable) -> np.ndarray:
        return np.asarray((self._margin(table.X) > 0).astype(jnp.float32))[
            : table.n_rows
        ]

    def transform(self, table: TpuTable) -> TpuTable:
        p1 = jax.nn.sigmoid(self._margin(table.X))
        pred = (p1 > 0.5).astype(jnp.float32)
        new_attrs = list(table.domain.attributes) + [
            ContinuousVariable(f"probability_{self.class_values[0]}"),
            ContinuousVariable(f"probability_{self.class_values[1]}"),
            DiscreteVariable("prediction", self.class_values),
        ]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate(
            [table.X, (1 - p1)[:, None], p1[:, None], pred[:, None]], axis=1
        )
        return table.with_X(X, new_domain)


class GBTClassifier(Estimator):
    """Binary classifier (MLlib GBTClassifier is binary-only too)."""

    ParamsCls = GBTParams
    params: GBTParams

    def _fit(self, table: TpuTable) -> GBTClassifierModel:
        p = self.params
        y = table.y
        cvar = table.domain.class_var
        class_values = (
            cvar.values if isinstance(cvar, DiscreteVariable) and cvar.values
            else ("0", "1")
        )
        if len(class_values) != 2:
            raise ValueError("GBTClassifier is binary (MLlib parity)")
        edges = compute_bin_edges(table.X, table.W, p.max_bins)
        B = bin_features(table.X, edges)
        f0, forest, imp = _boost(B, edges, table.W, y, p.max_depth, p.max_bins, p,
                            loss="logistic")
        model = GBTClassifierModel(p, f0, forest, class_values)
        model.feature_importances_ = imp   # MLlib featureImportances
        return model


class GBTRegressorModel(Model):
    def __init__(self, params, f0, forest: Tree):
        self.params = params
        self.f0 = f0
        self.forest = forest

    @property
    def state_pytree(self):
        return {"f0": jnp.float32(self.f0), **self.forest._asdict()}

    def predict(self, table: TpuTable) -> np.ndarray:
        m = _gbt_margin(table.X, self.f0, self.params.step_size, self.forest)
        return np.asarray(m)[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        yhat = _gbt_margin(table.X, self.f0, self.params.step_size, self.forest)
        new_domain = Domain(
            list(table.domain.attributes) + [ContinuousVariable("prediction")],
            table.domain.class_vars, table.domain.metas,
        )
        X = jnp.concatenate([table.X, yhat[:, None]], axis=1)
        return table.with_X(X, new_domain)


class GBTRegressor(Estimator):
    ParamsCls = GBTParams
    params: GBTParams

    def _fit(self, table: TpuTable) -> GBTRegressorModel:
        p = self.params
        edges = compute_bin_edges(table.X, table.W, p.max_bins)
        B = bin_features(table.X, edges)
        f0, forest, imp = _boost(B, edges, table.W, table.y, p.max_depth, p.max_bins,
                            p, loss="squared")
        model = GBTRegressorModel(p, f0, forest)
        model.feature_importances_ = imp   # MLlib featureImportances
        return model
