"""GeneralizedLinearRegression — parity with ``pyspark.ml.regression.GeneralizedLinearRegression``.

MLlib fits GLMs with IRLS: each iteration is one distributed weighted
least-squares solve where the ``XᵀWX`` Gram matrix is a treeAggregate
(SURVEY.md §2b/§3; reconstructed, mount empty — public API: family
gaussian|binomial|poisson|gamma|tweedie, link per family, maxIter=25,
tol=1e-6, regParam, fitIntercept, weightCol, offsetCol, variancePower/
linkPower for tweedie; summary exposes deviance, nullDeviance, aic,
dispersion, and — unregularized IRLS only — coefficientStandardErrors /
tValues / pValues). TPU-native redesign:

* one IRLS iteration = two MXU matmuls (``Xᵀ·diag(ω)·X`` Gram with the
  intercept column folded in, and ``Xᵀ·diag(ω)·z``) whose row contraction
  GSPMD all-reduces over ICI, plus a tiny replicated [d+1,d+1] Cholesky
  solve — the treeAggregate and the driver-side solve of MLlib, fused;
* the whole IRLS loop is a single jitted ``lax.while_loop`` with MLlib's
  relative-deviance convergence test;
* family/link algebra is traced inline (static strings), so XLA fuses the
  mean/variance/link derivatives into the matmul epilogues.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import concrete_or_none, Estimator, Model, Params

_CANONICAL_LINK = {
    "gaussian": "identity",
    "binomial": "logit",
    "poisson": "log",
    "gamma": "inverse",
    "tweedie": "log",
}


@dataclasses.dataclass(frozen=True)
class GeneralizedLinearRegressionParams(Params):
    family: str = "gaussian"     # MLlib family
    link: str = ""               # MLlib link; "" => canonical for family
    max_iter: int = 25           # MLlib maxIter
    tol: float = 1e-6            # MLlib tol (relative deviance change)
    reg_param: float = 0.0       # MLlib regParam (L2 on coef, not intercept)
    fit_intercept: bool = True
    variance_power: float = 0.0  # MLlib variancePower (tweedie)
    link_power: float | None = None  # MLlib linkPower; None => 1-variancePower (tweedie)


def _link_fns(link: str, link_power: float):
    """(g(mu)=eta, g^-1(eta)=mu, dmu/deta) for the named link."""
    if link == "identity":
        return (lambda m: m, lambda e: e, lambda e: jnp.ones_like(e))
    if link == "log":
        return (lambda m: jnp.log(m), jnp.exp, jnp.exp)
    if link == "logit":
        inv = jax.nn.sigmoid
        return (lambda m: jnp.log(m / (1 - m)), inv, lambda e: inv(e) * (1 - inv(e)))
    if link == "inverse":
        return (lambda m: 1.0 / m, lambda e: 1.0 / e, lambda e: -1.0 / (e * e))
    if link == "sqrt":
        return (lambda m: jnp.sqrt(m), lambda e: e * e, lambda e: 2.0 * e)
    if link == "probit":
        from jax.scipy.stats import norm

        return (
            lambda m: norm.ppf(m),
            lambda e: norm.cdf(e),
            lambda e: norm.pdf(e),
        )
    if link == "cloglog":
        return (
            lambda m: jnp.log(-jnp.log(1 - m)),
            lambda e: 1.0 - jnp.exp(-jnp.exp(e)),
            lambda e: jnp.exp(e - jnp.exp(e)),
        )
    if link == "power":  # tweedie with arbitrary linkPower
        lp = link_power
        if lp == 0.0:
            return (lambda m: jnp.log(m), jnp.exp, jnp.exp)
        return (
            lambda m: m**lp,
            lambda e: e ** (1.0 / lp),
            lambda e: (1.0 / lp) * e ** (1.0 / lp - 1.0),
        )
    raise ValueError(f"unknown link {link!r}")


def _variance_fn(family: str, variance_power: float):
    if family == "gaussian":
        return lambda m: jnp.ones_like(m)
    if family == "binomial":
        return lambda m: m * (1 - m)
    if family == "poisson":
        return lambda m: m
    if family == "gamma":
        return lambda m: m * m
    if family == "tweedie":
        return lambda m: m**variance_power
    raise ValueError(f"unknown family {family!r}")


def _deviance_fn(family: str, variance_power: float):
    """Unit deviance d(y, mu); total deviance = sum w * d."""
    if family == "gaussian":
        return lambda y, m: (y - m) ** 2
    if family == "binomial":
        def dev(y, m):
            m = jnp.clip(m, 1e-10, 1 - 1e-10)
            return 2.0 * (
                jnp.where(y > 0, y * jnp.log(y / m), 0.0)
                + jnp.where(y < 1, (1 - y) * jnp.log((1 - y) / (1 - m)), 0.0)
            )
        return dev
    if family == "poisson":
        def dev(y, m):
            return 2.0 * (jnp.where(y > 0, y * jnp.log(y / m), 0.0) - (y - m))
        return dev
    if family == "gamma":
        # y>0 guard: padded rows carry y=0, w=0 — without the where, the
        # log produces inf and 0*inf poisons the deviance sum with NaN
        return lambda y, m: 2.0 * (
            jnp.where(y > 0, -jnp.log(jnp.maximum(y, 1e-30) / m), 0.0) + (y - m) / m
        )
    if family == "tweedie":
        p = variance_power
        if p == 0.0:
            return lambda y, m: (y - m) ** 2
        if p == 1.0:
            return _deviance_fn("poisson", 0.0)
        if p == 2.0:
            return _deviance_fn("gamma", 0.0)

        def dev(y, m):
            yp = jnp.maximum(y, 0.0)
            t1 = jnp.where(
                yp > 0, yp ** (2 - p) / ((1 - p) * (2 - p)), 0.0
            )
            return 2.0 * (t1 - yp * m ** (1 - p) / (1 - p) + m ** (2 - p) / (2 - p))
        return dev
    raise ValueError(family)


def _mu_init(family: str):
    """MLlib's IRLS starting mean."""
    if family == "binomial":
        return lambda y, ybar: (y + 0.5) / 2.0
    if family in ("poisson", "gamma", "tweedie"):
        return lambda y, ybar: jnp.maximum(y, 0.1)
    return lambda y, ybar: y  # gaussian: eta0 = y


@partial(jax.jit, static_argnames=("family", "link", "fit_intercept", "max_iter",
                                   "variance_power", "link_power",
                                   "want_inference"))
def _irls(X, y, w, offset, reg, tol, *, family: str, link: str,
          fit_intercept: bool, max_iter: int,
          variance_power: float, link_power: float,
          want_inference: bool = True):
    n, d = X.shape
    link_f, link_inv, dmu_deta = _link_fns(link, link_power)
    var_f = _variance_fn(family, variance_power)
    dev_f = _deviance_fn(family, variance_power)
    ones = jnp.ones((n, 1), dtype=X.dtype)
    Xa = jnp.concatenate([X, ones], axis=1) if fit_intercept else X
    da = Xa.shape[1]
    sum_w = jnp.maximum(jnp.sum(w), 1e-12)
    # regularize coef but never the intercept (MLlib convention)
    reg_diag = jnp.concatenate(
        [jnp.full((d,), 1.0, X.dtype), jnp.zeros((da - d,), X.dtype)]
    )

    def deviance(beta):
        mu = link_inv(Xa @ beta + offset)
        return jnp.sum(w * dev_f(y, mu))

    def irls_weights(eta, mu):
        """THE working-weight definition: w·g²/V(mu). The inference-stat
        covariance uses the same helper, so standard errors can never use
        a different weight formula than the coefficients they describe."""
        g = dmu_deta(eta)
        return g, w * g * g / jnp.maximum(var_f(mu), 1e-12)

    def cho_solve_gram(gram, rhs):
        chol = jax.scipy.linalg.cho_factor(
            gram + 1e-8 * jnp.eye(da, dtype=X.dtype))
        return jax.scipy.linalg.cho_solve(chol, rhs)

    def wls(eta, mu):
        g, irls_w = irls_weights(eta, mu)
        z = eta - offset + (y - mu) / jnp.where(jnp.abs(g) > 1e-12, g, 1e-12)
        Xw = Xa * irls_w[:, None]
        gram = Xw.T @ Xa + (reg * sum_w) * jnp.diag(reg_diag)   # [da,da], psum'd
        rhs = Xw.T @ z                                          # [da], psum'd
        return cho_solve_gram(gram, rhs)

    mu0 = _mu_init(family)(y, None)
    eta0 = link_f(mu0)
    beta0 = wls(eta0, mu0)

    def body(carry):
        beta, prev_dev, _, it = carry
        eta = Xa @ beta + offset
        mu = link_inv(eta)
        new_beta = wls(eta, mu)
        new_dev = deviance(new_beta)
        rel = jnp.abs(new_dev - prev_dev) / jnp.maximum(jnp.abs(new_dev), 1e-12)
        return new_beta, new_dev, rel < tol, it + 1

    def keep_going(carry):
        _, _, converged, it = carry
        return (it < max_iter) & ~converged

    beta, dev, _, n_iter = jax.lax.while_loop(
        keep_going, body, (beta0, deviance(beta0), False, 0)
    )
    # null deviance: intercept-only model mean (weighted link-mean of y)
    ybar = jnp.sum(w * y) / sum_w
    null_dev = jnp.sum(w * dev_f(y, ybar))
    # Pearson chi-square statistic sum w·(y-mu)²/V(mu) (MLlib dispersion base)
    eta_hat = Xa @ beta + offset
    mu_hat = link_inv(eta_hat)
    pearson = jnp.sum(w * (y - mu_hat) ** 2 / jnp.maximum(var_f(mu_hat), 1e-12))
    # unscaled covariance diag(inv(X' W_irls X)) at the optimum — the base
    # of MLlib summary's coefficientStandardErrors (× dispersion). Skipped
    # (statically) for regularized fits, which carry no inference stats:
    # the extra Gram + Cholesky inverse would be pure dead weight there.
    cov_diag = None
    if want_inference:
        _, w_hat = irls_weights(eta_hat, mu_hat)
        gram_hat = (Xa * w_hat[:, None]).T @ Xa
        cov_diag = jnp.diag(
            cho_solve_gram(gram_hat, jnp.eye(da, dtype=X.dtype)))
    return beta, dev, null_dev, pearson, n_iter, sum_w, cov_diag


class GeneralizedLinearRegressionModel(Model):
    def __init__(self, params, coef, intercept, link: str, link_power: float = 1.0):
        self.params = params
        self.coef = coef            # f32[d]
        self.intercept = intercept  # f32[]
        self.link = link
        self.link_power = link_power  # resolved (params.link_power may be None)
        self.n_iter_: int | None = None
        self.deviance_: float | None = None       # summary.deviance
        self.null_deviance_: float | None = None  # summary.nullDeviance
        self.dispersion_: float | None = None     # summary.dispersion
        self.aic_: float | None = None
        # summary inference stats (unregularized IRLS only, like MLlib —
        # None when reg_param > 0). Device arrays ordered
        # [coefficients..., intercept]; z-test for binomial/poisson,
        # t-test (df = n - rank) otherwise.
        self.coefficient_standard_errors_ = None
        self.t_values_ = None
        self.p_values_ = None

    @property
    def state_pytree(self):
        return {"coef": self.coef, "intercept": self.intercept}

    def _eta(self, table: TpuTable):
        return table.X @ self.coef + self.intercept

    def predict(self, table: TpuTable) -> np.ndarray:
        """Mean prediction mu = g^-1(x·b) — MLlib's predictionCol."""
        _, link_inv, _ = _link_fns(self.link, self.link_power)
        return np.asarray(link_inv(self._eta(table)))[: table.n_rows]

    def predict_link(self, table: TpuTable) -> np.ndarray:
        """Linear predictor eta — MLlib's linkPredictionCol."""
        return np.asarray(self._eta(table))[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        _, link_inv, _ = _link_fns(self.link, self.link_power)
        eta = self._eta(table)
        new_attrs = list(table.domain.attributes) + [
            ContinuousVariable("prediction"), ContinuousVariable("linkPrediction")
        ]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, link_inv(eta)[:, None], eta[:, None]], axis=1)
        return table.with_X(X, new_domain)


class GeneralizedLinearRegression(Estimator):
    ParamsCls = GeneralizedLinearRegressionParams
    params: GeneralizedLinearRegressionParams

    def _fit(self, table: TpuTable) -> GeneralizedLinearRegressionModel:
        p = self.params
        if p.family not in _CANONICAL_LINK:
            raise ValueError(f"unknown family {p.family!r}")
        link = p.link or _CANONICAL_LINK[p.family]
        if p.family == "tweedie" and not p.link:
            link = "power"
        y = table.y
        if y is None:
            raise ValueError("GeneralizedLinearRegression needs a target column")
        # MLlib: linkPower defaults to 1 - variancePower for tweedie
        if p.link_power is not None:
            link_power = float(p.link_power)
        elif p.family == "tweedie":
            link_power = 1.0 - p.variance_power
        else:
            link_power = 1.0
        offset = jnp.zeros_like(y)
        beta, dev, null_dev, pearson, n_iter, sum_w, cov_diag = _irls(
            table.X, y, table.W, offset,
            jnp.float32(p.reg_param), jnp.float32(p.tol),
            family=p.family, link=link, fit_intercept=p.fit_intercept,
            max_iter=p.max_iter,
            variance_power=p.variance_power, link_power=link_power,
            want_inference=(p.reg_param == 0.0),
        )
        d = table.X.shape[1]
        coef = beta[:d]
        intercept = beta[d] if p.fit_intercept else jnp.float32(0.0)
        model = GeneralizedLinearRegressionModel(p, coef, intercept, link, link_power)
        model.n_iter_ = concrete_or_none(n_iter, int)
        # diagnostics concretize only OUTSIDE a trace — under staged refit
        # (workflow/staging.py refit=True) the honest value is None, and a
        # float() here would make every GLM fit refit-in-trace INELIGIBLE
        model.deviance_ = concrete_or_none(dev)
        model.null_deviance_ = concrete_or_none(null_dev)
        # dispersion (MLlib): fixed at 1 for binomial/poisson, else the
        # Pearson chi-square statistic over residual degrees of freedom —
        # ONE device-side formula, concretized for the summary float
        n_eff = concrete_or_none(sum_w)
        rank = d + (1 if p.fit_intercept else 0)
        fixed_disp = p.family in ("binomial", "poisson")
        disp = (jnp.float32(1.0) if fixed_disp
                else pearson / jnp.maximum(sum_w - rank, 1.0))
        model.dispersion_ = 1.0 if fixed_disp else concrete_or_none(disp)
        model.aic_ = (
            None if n_eff is None or model.deviance_ is None
            else self._aic(p.family, model.deviance_, n_eff, rank, table,
                           model)
        )
        if p.reg_param == 0.0:
            # MLlib summary inference stats (coefficientStandardErrors /
            # tValues / pValues) exist only for the unregularized IRLS fit
            # — Spark raises on regParam > 0; here they stay None then.
            # Order matches Spark: [coefficients..., intercept last].
            from orange3_spark_tpu.ops.stats import (
                two_sided_t_pvalue, two_sided_z_pvalue,
            )

            se = jnp.sqrt(cov_diag[:rank] * disp)
            tval = beta[:rank] / jnp.maximum(se, 1e-30)
            if p.family in ("binomial", "poisson"):
                pval = two_sided_z_pvalue(tval)
            else:
                pval = two_sided_t_pvalue(tval, sum_w - rank)
            model.coefficient_standard_errors_ = se
            model.t_values_ = tval
            model.p_values_ = pval
        return model

    @staticmethod
    def _aic(family: str, dev: float, n: float, rank: int, table, model) -> float:
        """-2·loglik + 2·k, per family (MLlib summary.aic). Tweedie has no
        closed-form likelihood — returns nan, as Spark raises there."""
        mu = model.predict(table)
        w = np.asarray(jax.device_get(table.W))[: table.n_rows]
        y = np.asarray(jax.device_get(table.y))[: table.n_rows]
        if family == "gaussian":
            sigma2 = dev / n
            ll = -0.5 * n * (np.log(2 * np.pi * sigma2) + 1.0)
            return float(-2 * ll + 2 * (rank + 1))
        if family == "binomial":
            # clip in float64: in float32, 1 - 1e-10 rounds to exactly 1.0 and
            # the top-end clip is a no-op, sending log(1-mu) to log(0)
            mu_c = np.clip(np.asarray(mu, np.float64), 1e-10, 1 - 1e-10)
            ll = np.sum(w * (y * np.log(mu_c) + (1 - y) * np.log(1 - mu_c)))
            return float(-2 * ll + 2 * rank)
        if family == "poisson":
            from scipy.special import gammaln

            ll = np.sum(w * (y * np.log(np.maximum(mu, 1e-30)) - mu - gammaln(y + 1)))
            return float(-2 * ll + 2 * rank)
        if family == "gamma":
            # shape k̂ = 1/dispersion; Spark uses the deviance-based estimate
            disp = max(dev / max(n - rank, 1.0), 1e-12)
            shape = 1.0 / disp
            from scipy.special import gammaln

            yp = np.maximum(y, 1e-30)
            ll = np.sum(
                w * (shape * np.log(shape * yp / np.maximum(mu, 1e-30))
                     - shape * yp / np.maximum(mu, 1e-30)
                     - np.log(yp) - gammaln(shape))
            )
            return float(-2 * ll + 2 * (rank + 1))
        return float("nan")
