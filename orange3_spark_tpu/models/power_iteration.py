"""PowerIterationClustering — parity with ``pyspark.ml.clustering.PowerIterationClustering``.

MLlib's PIC (Lin & Cohen 2010) runs power iteration on the degree-normalized
affinity matrix of a similarity graph, then k-means on the resulting
pseudo-eigenvector (SURVEY.md §2b; reconstructed, mount empty — public API:
k, maxIter, initMode 'random'|'degree', srcCol/dstCol/weightCol;
``assignClusters(dataset) -> (id, cluster)``). TPU-native redesign:

* the graph stays in **edge-list COO form**; the sparse matvec
  ``v' = D⁻¹ A v`` is a gather + ``segment_sum`` over edges — XLA lowers
  both to efficient one-pass scatter/gather kernels, and the edge axis can be
  sharded with the segment ids psum-reduced across devices;
* the power loop is one jitted ``lax.fori_loop`` (normalize with an
  all-reduced L1 norm each step — MLlib's exact update);
* the final 1-D k-means reuses the jitted Lloyd kernel from ``kmeans.py``.

Edges are treated as undirected (both directions inserted), matching MLlib's
symmetric-affinity requirement.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import HasParams, Params
from orange3_spark_tpu.models.kmeans import _assign, _lloyd


@dataclasses.dataclass(frozen=True)
class PowerIterationClusteringParams(Params):
    k: int = 2                 # MLlib k
    max_iter: int = 20         # MLlib maxIter
    init_mode: str = "random"  # MLlib initMode: 'random' | 'degree'
    seed: int = 0
    src_col: str = "src"
    dst_col: str = "dst"
    weight_col: str = "weight"


@partial(jax.jit, static_argnames=("n", "max_iter"))
def _power_iterate(src, dst, w, v0, *, n: int, max_iter: int):
    deg = jax.ops.segment_sum(w, src, num_segments=n)
    inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1e-30), 0.0)

    def body(_, v):
        # v' = D^-1 A v : gather neighbor values, weight, reduce per source row
        contrib = w * v[dst]
        av = jax.ops.segment_sum(contrib, src, num_segments=n)
        v = inv_deg * av
        return v / jnp.maximum(jnp.sum(jnp.abs(v)), 1e-30)

    return jax.lax.fori_loop(0, max_iter, body, v0)


class PowerIterationClustering(HasParams):
    """Not an Estimator — mirrors MLlib, where PIC has only assignClusters()."""

    ParamsCls = PowerIterationClusteringParams

    def assign_clusters(self, dataset) -> np.ndarray:
        """dataset: TpuTable with src/dst/weight attribute columns, or a
        (src, dst, weight) triple of arrays. Returns int cluster id per vertex
        (index = vertex id), the (id, cluster) frame of MLlib."""
        p = self.params
        if isinstance(dataset, TpuTable):
            names = [v.name for v in dataset.domain.attributes]
            X = np.asarray(jax.device_get(dataset.X))[: dataset.n_rows]
            live = np.asarray(jax.device_get(dataset.W))[: dataset.n_rows] > 0
            X = X[live]  # honor filter(): W==0 edges must not shape the graph
            src = X[:, names.index(p.src_col)].astype(np.int64)
            dst = X[:, names.index(p.dst_col)].astype(np.int64)
            if len(src) and max(src.max(), dst.max()) >= (1 << 24):
                # f32 storage cannot represent ids above 2^24 exactly —
                # distinct vertices would silently collapse
                raise ValueError(
                    "vertex ids >= 2^24 cannot come from float32 table columns; "
                    "pass (src, dst, weight) integer arrays instead"
                )
            w = (
                X[:, names.index(p.weight_col)].astype(np.float32)
                if p.weight_col in names
                else np.ones(len(src), dtype=np.float32)
            )
        else:
            src, dst, w = dataset
            src = np.asarray(src, dtype=np.int64)
            dst = np.asarray(dst, dtype=np.int64)
            w = (np.ones(len(src), dtype=np.float32) if w is None
                 else np.asarray(w, dtype=np.float32))
        if np.any(w < 0):
            raise ValueError("PIC requires nonnegative similarities")
        n = int(max(src.max(), dst.max())) + 1 if len(src) else 0
        if n == 0:
            return np.zeros((0,), dtype=np.int64)
        # symmetrize: undirected affinity
        s2 = np.concatenate([src, dst])
        d2 = np.concatenate([dst, src])
        w2 = np.concatenate([w, w])
        deg = np.zeros(n, dtype=np.float64)
        np.add.at(deg, s2, w2)
        rng = np.random.default_rng(p.seed)
        if p.init_mode == "degree":
            v0 = (deg / max(deg.sum(), 1e-30)).astype(np.float32)
        elif p.init_mode == "random":
            v0 = rng.random(n).astype(np.float32)
            v0 /= max(np.abs(v0).sum(), 1e-30)
        else:
            raise ValueError(f"unknown init_mode {p.init_mode!r}")
        v = _power_iterate(
            jnp.asarray(s2), jnp.asarray(d2), jnp.asarray(w2), jnp.asarray(v0),
            n=n, max_iter=p.max_iter,
        )
        # 1-D k-means on the pseudo-eigenvector
        vv = v[:, None]
        live = np.ones(n, dtype=np.float32)
        q = np.quantile(np.asarray(v), np.linspace(0.05, 0.95, p.k))
        centers0 = jnp.asarray(q[:, None].astype(np.float32))
        centers, _, _, _ = _lloyd(
            vv, jnp.asarray(live), centers0, jnp.float32(1e-6),
            k=p.k, max_iter=50,
        )
        assign, _ = _assign(vv, centers, jnp.asarray(live))
        return np.asarray(assign).astype(np.int64)
