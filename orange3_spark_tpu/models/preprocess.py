"""Feature transformers — ``pyspark.ml.feature`` capability parity.

The reference's transformer widgets wrap MLlib feature Estimators/Transformers
(SURVEY.md §2b row "Feature transformers"; reconstructed, mount empty).
TPU-native redesign: every fitted state is a small pytree of device arrays;
every transform is a jitted columnar op over the one sharded X matrix, so a
chain of transformers fuses into a single XLA program when staged.

Column addressing: ``input_cols=None`` means "all continuous attributes" for
scalers/imputer, matching the common Spark VectorAssembler-then-scale idiom
without needing an assembled vector column (our table IS the assembled
matrix). VectorAssembler is therefore a thin select/concat for API parity.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import (
    ContinuousVariable,
    DiscreteVariable,
    Domain,
    StringVariable,
)
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params, Transformer
from orange3_spark_tpu.ops.stats import weighted_moments, weighted_quantiles


def _col_indices(table: TpuTable, input_cols: Sequence[str] | None) -> np.ndarray:
    if input_cols is None:
        idxs = [
            i for i, v in enumerate(table.domain.attributes)
            if isinstance(v, ContinuousVariable)
        ]
    else:
        idxs = [table.domain.index(c) for c in input_cols]
    return np.asarray(idxs, dtype=np.int32)


def _scale_transform(X, idxs, shift, scale):
    """X'[:, idxs] = (X[:, idxs] - shift) * scale, fused as one scatter-free op."""
    full_shift = jnp.zeros((X.shape[1],), X.dtype).at[idxs].set(shift)
    full_scale = jnp.ones((X.shape[1],), X.dtype).at[idxs].set(scale)
    return (X - full_shift) * full_scale


_scale_transform_jit = jax.jit(_scale_transform)


# ---------------------------------------------------------------------------
# Scalers
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class StandardScalerParams(Params):
    with_mean: bool = False  # MLlib withMean (False default, like Spark)
    with_std: bool = True    # MLlib withStd
    input_cols: tuple | None = None


class _ColumnScaleModel(Model):
    """Shared shift-and-scale fitted state."""

    def __init__(self, params, idxs, shift, scale):
        self.params = params
        self.idxs = idxs
        self.shift = shift
        self.scale = scale

    @property
    def state_pytree(self):
        return {"idxs": self.idxs, "shift": self.shift, "scale": self.scale}

    def transform(self, table: TpuTable) -> TpuTable:
        X = _scale_transform_jit(table.X, self.idxs, self.shift, self.scale)
        return table.with_X(X)


class StandardScalerModel(_ColumnScaleModel):
    @property
    def mean(self):
        return self.shift

    @property
    def std(self):
        return 1.0 / self.scale


class StandardScaler(Estimator):
    ParamsCls = StandardScalerParams
    params: StandardScalerParams

    def _fit(self, table: TpuTable) -> StandardScalerModel:
        p = self.params
        idxs = _col_indices(table, p.input_cols)
        Xsel = jnp.take(table.X, idxs, axis=1)
        mean, var, _ = weighted_moments(Xsel, table.W)
        return self._finalize(mean, var, jnp.asarray(idxs))

    def _finalize(self, mean, var, idxs) -> StandardScalerModel:
        p = self.params
        mean = jnp.asarray(mean, jnp.float32)
        std = jnp.sqrt(jnp.asarray(var, jnp.float32))
        scale = jnp.where(std > 1e-12, 1.0 / std, 1.0) if p.with_std \
            else jnp.ones_like(std)
        shift = mean if p.with_mean else jnp.zeros_like(mean)
        return StandardScalerModel(p, idxs, shift, scale)

    def fit_stream(self, source, *, session=None,
                   chunk_rows: int = 1 << 18) -> StandardScalerModel:
        """Out-of-core fit: ONE pass of per-column moments over a chunk
        stream (io/streaming.stream_feature_stats) — same population-
        variance convention as the in-memory fit, at any row count. The
        stream's columns are the features (``input_cols`` must be unset;
        select columns in the source)."""
        if self.params.input_cols is not None:
            raise ValueError("fit_stream scales every stream column; "
                             "select columns in the source instead of "
                             "input_cols")
        from orange3_spark_tpu.io.streaming import stream_feature_stats

        st = stream_feature_stats(source, session=session,
                                  chunk_rows=chunk_rows)
        return self._finalize(st["mean"], st["var"],
                              jnp.arange(len(st["mean"]), dtype=jnp.int32))


@dataclasses.dataclass(frozen=True)
class MinMaxScalerParams(Params):
    min: float = 0.0  # MLlib min
    max: float = 1.0  # MLlib max
    input_cols: tuple | None = None


class MinMaxScaler(Estimator):
    ParamsCls = MinMaxScalerParams
    params: MinMaxScalerParams

    def _fit(self, table: TpuTable) -> _ColumnScaleModel:
        p = self.params
        idxs = _col_indices(table, p.input_cols)
        Xsel = jnp.take(table.X, idxs, axis=1)
        live = (table.W > 0)[:, None]
        big = jnp.float32(np.finfo(np.float32).max)
        mn = jnp.min(jnp.where(live, Xsel, big), axis=0)
        mx = jnp.max(jnp.where(live, Xsel, -big), axis=0)
        return self._finalize(mn, mx, jnp.asarray(idxs))

    def _finalize(self, mn, mx, idxs) -> "MinMaxScalerModel":
        p = self.params
        mn = jnp.asarray(mn, jnp.float32)
        rng = jnp.asarray(mx, jnp.float32) - mn
        scale = jnp.where(rng > 1e-12, (p.max - p.min) / rng, 0.0)
        return MinMaxScalerModel(p, idxs, mn, scale)

    def fit_stream(self, source, *, session=None,
                   chunk_rows: int = 1 << 18) -> "MinMaxScalerModel":
        """Out-of-core fit: one pass of per-column min/max over a chunk
        stream; see ``StandardScaler.fit_stream`` for the column rule."""
        if self.params.input_cols is not None:
            raise ValueError("fit_stream scales every stream column; "
                             "select columns in the source instead of "
                             "input_cols")
        from orange3_spark_tpu.io.streaming import stream_feature_stats

        st = stream_feature_stats(source, session=session,
                                  chunk_rows=chunk_rows)
        return self._finalize(st["min"], st["max"],
                              jnp.arange(len(st["min"]), dtype=jnp.int32))


class MinMaxScalerModel(_ColumnScaleModel):
    params: "MinMaxScalerParams"

    def transform(self, table: TpuTable) -> TpuTable:
        X = table.X
        p = self.params
        idxs, mn, scale = self.idxs, self.shift, self.scale
        Xsel = jnp.take(X, idxs, axis=1)
        # Spark maps constant columns (scale==0) to the output-range midpoint;
        # both constants derive from params so checkpoint restore is lossless
        mid_fill = p.min + 0.5 * (p.max - p.min)
        scaled = jnp.where(scale > 0, (Xsel - mn) * scale + p.min, mid_fill)
        Xout = X.at[:, idxs].set(scaled)
        return table.with_X(Xout)


@dataclasses.dataclass(frozen=True)
class MaxAbsScalerParams(Params):
    input_cols: tuple | None = None


class MaxAbsScaler(Estimator):
    ParamsCls = MaxAbsScalerParams

    def _fit(self, table: TpuTable) -> _ColumnScaleModel:
        p = self.params
        idxs = _col_indices(table, p.input_cols)
        Xsel = jnp.take(table.X, idxs, axis=1)
        live = (table.W > 0)[:, None]
        mabs = jnp.max(jnp.where(live, jnp.abs(Xsel), 0.0), axis=0)
        scale = jnp.where(mabs > 1e-12, 1.0 / mabs, 1.0)
        return _ColumnScaleModel(p, jnp.asarray(idxs), jnp.zeros_like(scale), scale)


# ---------------------------------------------------------------------------
# Imputer
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class ImputerParams(Params):
    strategy: str = "mean"       # MLlib strategy: 'mean' | 'median' | 'mode'
    missing_value: float = float("nan")  # MLlib missingValue
    input_cols: tuple | None = None


class ImputerModel(Model):
    def __init__(self, params, idxs, fill):
        self.params = params
        self.idxs = idxs
        self.fill = fill  # f32[len(idxs)]

    @property
    def state_pytree(self):
        return {"idxs": self.idxs, "fill": self.fill}

    def transform(self, table: TpuTable) -> TpuTable:
        X = table.X
        Xsel = jnp.take(X, self.idxs, axis=1)
        mv = self.params.missing_value
        miss = jnp.isnan(Xsel) if np.isnan(mv) else (Xsel == mv)
        Xout = X.at[:, self.idxs].set(jnp.where(miss, self.fill, Xsel))
        return table.with_X(Xout)


class Imputer(Estimator):
    ParamsCls = ImputerParams
    params: ImputerParams

    def _fit(self, table: TpuTable) -> ImputerModel:
        p = self.params
        idxs = _col_indices(table, p.input_cols)
        Xsel = jnp.take(table.X, idxs, axis=1)
        mv = p.missing_value
        miss = jnp.isnan(Xsel) if np.isnan(mv) else (Xsel == mv)
        w_eff = jnp.where(miss, 0.0, table.W[:, None])
        if p.strategy == "mean":
            tot = jnp.maximum(jnp.sum(w_eff, axis=0), 1e-12)
            fill = jnp.sum(jnp.where(miss, 0.0, Xsel) * w_eff, axis=0) / tot
        elif p.strategy == "median":
            # one batched weighted-quantile call; per-cell weights zero out
            # each column's own missing entries
            Xclean = jnp.where(miss, 0.0, Xsel)
            fill = weighted_quantiles(Xclean, w_eff, jnp.asarray([0.5]))[0]
        elif p.strategy == "mode":
            # mode over observed values: host-side exact (small unique sets)
            Xh = np.asarray(jax.device_get(Xsel))
            Wh = np.asarray(jax.device_get(w_eff))
            fills = []
            for j in range(Xh.shape[1]):
                vals = Xh[Wh[:, j] > 0, j]
                if len(vals) == 0:
                    fills.append(0.0)
                else:
                    uniq, counts = np.unique(vals, return_counts=True)
                    fills.append(float(uniq[np.argmax(counts)]))
            fill = jnp.asarray(fills, dtype=jnp.float32)
        else:
            raise ValueError(f"unknown strategy {p.strategy!r}")
        return ImputerModel(p, jnp.asarray(idxs), fill)

    def fit_stream(self, source, *, session=None,
                   chunk_rows: int = 1 << 18) -> ImputerModel:
        """Out-of-core mean-imputer fit: one missing-aware stats pass
        (per-CELL observation masks — a missing cell drops out of its
        column only). 'median'/'mode' need a sketch or a value table and
        stay in-memory; column rule as in ``StandardScaler.fit_stream``."""
        p = self.params
        if p.strategy != "mean":
            raise ValueError(
                f"fit_stream supports strategy='mean' only (got "
                f"{p.strategy!r}); median/mode need the rows in memory")
        if p.input_cols is not None:
            raise ValueError("fit_stream imputes every stream column; "
                             "select columns in the source instead of "
                             "input_cols")
        from orange3_spark_tpu.io.streaming import stream_feature_stats

        st = stream_feature_stats(source, session=session,
                                  chunk_rows=chunk_rows,
                                  missing_value=p.missing_value)
        fill = jnp.asarray(st["mean"], jnp.float32)
        return ImputerModel(p, jnp.arange(len(st["mean"]), dtype=jnp.int32),
                            fill)


# ---------------------------------------------------------------------------
# Discretization & encoding
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class BucketizerParams(Params):
    splits: tuple = ()           # MLlib splits: boundaries incl. +-inf allowed
    input_col: str = ""


class Bucketizer(Transformer):
    """Stateless: bin one column by explicit split points (MLlib Bucketizer)."""

    ParamsCls = BucketizerParams

    def __init__(self, params: BucketizerParams | None = None, **kwargs):
        self.params = params or BucketizerParams(**kwargs)
        if len(self.params.splits) < 3:
            raise ValueError("need >= 3 split points (>= 2 buckets)")

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        j = table.domain.index(p.input_col)
        splits = jnp.asarray(p.splits, dtype=jnp.float32)
        binned = jnp.clip(
            jnp.searchsorted(splits, table.X[:, j], side="right") - 1,
            0, len(p.splits) - 2,
        ).astype(jnp.float32)
        n_bins = len(p.splits) - 1
        var = DiscreteVariable(
            f"{p.input_col}_binned", tuple(str(i) for i in range(n_bins))
        )
        new_domain = Domain(
            list(table.domain.attributes) + [var],
            table.domain.class_vars, table.domain.metas,
        )
        X = jnp.concatenate([table.X, binned[:, None]], axis=1)
        return table.with_X(X, new_domain)


@dataclasses.dataclass(frozen=True)
class QuantileDiscretizerParams(Params):
    num_buckets: int = 2         # MLlib numBuckets
    input_col: str = ""


class QuantileDiscretizer(Estimator):
    """Fit quantile split points, return a Bucketizer (MLlib behavior)."""

    ParamsCls = QuantileDiscretizerParams
    params: QuantileDiscretizerParams

    def _fit(self, table: TpuTable) -> Bucketizer:
        p = self.params
        j = table.domain.index(p.input_col)
        qs = jnp.linspace(0.0, 1.0, p.num_buckets + 1)[1:-1]
        inner = weighted_quantiles(table.X[:, j : j + 1], table.W, qs)[:, 0]
        splits = (-np.inf,) + tuple(np.unique(np.asarray(inner)).tolist()) + (np.inf,)
        return Bucketizer(BucketizerParams(splits=splits, input_col=p.input_col))


@dataclasses.dataclass(frozen=True)
class OneHotEncoderParams(Params):
    input_cols: tuple = ()       # discrete attribute names
    drop_last: bool = True       # MLlib dropLast
    handle_invalid: str = "error"  # MLlib handleInvalid: 'error' | 'keep'


class OneHotEncoderModel(Model):
    def __init__(self, params, col_idx, sizes):
        self.params = params
        self.col_idx = col_idx   # list[int]
        self.sizes = sizes       # list[int] categories per column

    @property
    def state_pytree(self):
        return {}

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        pieces, new_vars = [], []
        keep = [
            i for i in range(table.n_attrs) if i not in set(self.col_idx)
        ]
        Xkeep = jnp.take(table.X, jnp.asarray(keep, dtype=jnp.int32), axis=1)
        pieces.append(Xkeep)
        new_vars.extend(table.domain.attributes[i] for i in keep)
        for j, size, name in zip(
            self.col_idx, self.sizes, p.input_cols, strict=True
        ):
            if p.handle_invalid == "error":
                # under drop_last an unseen index would silently alias the
                # dropped last category (one_hot -> all zeros), so check
                live_vals = jnp.where(table.W > 0, table.X[:, j], 0.0)
                mx = int(np.asarray(jnp.max(live_vals)).item())
                if mx >= size:
                    raise ValueError(
                        f"column {name!r} has category index {mx} >= {size} "
                        "unseen at fit (handle_invalid='error')"
                    )
            width = size - 1 if p.drop_last else size
            var = table.domain.attributes[j]
            values = (
                var.values if isinstance(var, DiscreteVariable) and var.values
                else tuple(str(i) for i in range(size))
            )
            onehot = jax.nn.one_hot(
                table.X[:, j].astype(jnp.int32), size, dtype=jnp.float32
            )[:, :width]
            pieces.append(onehot)
            new_vars.extend(
                ContinuousVariable(f"{name}_{values[c]}") for c in range(width)
            )
        new_domain = Domain(new_vars, table.domain.class_vars, table.domain.metas)
        return table.with_X(jnp.concatenate(pieces, axis=1), new_domain)


class OneHotEncoder(Estimator):
    ParamsCls = OneHotEncoderParams
    params: OneHotEncoderParams

    def _fit(self, table: TpuTable) -> OneHotEncoderModel:
        p = self.params
        if not p.input_cols:
            raise ValueError("OneHotEncoder needs input_cols")
        col_idx, sizes = [], []
        for name in p.input_cols:
            var = table.domain[name]
            j = table.domain.index(name)
            col_idx.append(j)
            if isinstance(var, DiscreteVariable) and var.values:
                sizes.append(len(var.values))
            else:  # infer category count from data (Spark OHE fit behavior)
                sizes.append(int(np.asarray(jnp.max(table.X[:, j])).item()) + 1)
        return OneHotEncoderModel(p, col_idx, sizes)


@dataclasses.dataclass(frozen=True)
class StringIndexerParams(Params):
    input_col: str = ""          # a meta (string) column
    order: str = "frequencyDesc" # MLlib stringOrderType
    handle_invalid: str = "error" # 'error' | 'keep' (maps unseen -> n)


class StringIndexerModel(Model):
    def __init__(self, params, labels):
        self.params = params
        self.labels = tuple(labels)

    @property
    def state_pytree(self):
        return {}

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        meta_names = [v.name for v in table.domain.metas]
        mj = meta_names.index(p.input_col)
        strings = np.asarray(table.metas[:, mj], dtype=object)
        live = np.asarray(jax.device_get(table.W))[: len(strings)] > 0
        lut = {s: i for i, s in enumerate(self.labels)}
        out = np.zeros(len(strings), dtype=np.float32)
        for i, s in enumerate(strings):
            if s in lut:
                out[i] = lut[s]
            elif not live[i]:
                out[i] = 0.0  # dead (filtered) rows never error
            elif p.handle_invalid == "keep":
                out[i] = len(self.labels)
            else:
                raise ValueError(f"unseen label {s!r} (handle_invalid='error')")
        pad = np.zeros(table.n_pad, dtype=np.float32)
        pad[: len(out)] = out
        col = jax.device_put(pad, table.session.vector_sharding)
        values = self.labels + (("__unknown__",) if p.handle_invalid == "keep" else ())
        var = DiscreteVariable(f"{p.input_col}_idx", values)
        new_domain = Domain(
            list(table.domain.attributes) + [var],
            table.domain.class_vars, table.domain.metas,
        )
        X = jnp.concatenate([table.X, col[:, None]], axis=1)
        return table.with_X(X, new_domain)


class StringIndexer(Estimator):
    """Meta string column -> discrete index attribute (host-side fit: strings
    never live on device — same boundary Orange draws for metas)."""

    ParamsCls = StringIndexerParams
    params: StringIndexerParams

    def _fit(self, table: TpuTable) -> StringIndexerModel:
        p = self.params
        if table.metas is None:
            raise ValueError("table has no meta columns")
        meta_names = [v.name for v in table.domain.metas]
        if p.input_col not in meta_names:
            raise ValueError(f"no meta column {p.input_col!r}")
        strings = np.asarray(table.metas[:, meta_names.index(p.input_col)], dtype=object)
        # frequency ordering counts only live rows (filter semantics — the
        # scalers/imputer honor W the same way)
        live = np.asarray(jax.device_get(table.W))[: len(strings)] > 0
        uniq, counts = np.unique(strings[live].astype(str), return_counts=True)
        if p.order == "frequencyDesc":
            order = np.lexsort((uniq, -counts))
        elif p.order == "alphabetAsc":
            order = np.argsort(uniq)
        else:
            raise ValueError(f"unknown order {p.order!r}")
        return StringIndexerModel(p, uniq[order].tolist())


# ---------------------------------------------------------------------------
# Stateless transformers
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class NormalizerParams(Params):
    p: float = 2.0               # MLlib p (row norm)


class Normalizer(Transformer):
    ParamsCls = NormalizerParams

    def transform(self, table: TpuTable) -> TpuTable:
        ord_ = self.params.p
        norms = jnp.linalg.norm(table.X, ord=ord_, axis=1, keepdims=True)
        X = table.X / jnp.maximum(norms, 1e-12)
        return table.with_X(X)


@dataclasses.dataclass(frozen=True)
class BinarizerParams(Params):
    threshold: float = 0.0       # MLlib threshold
    input_cols: tuple | None = None


class Binarizer(Transformer):
    ParamsCls = BinarizerParams

    def transform(self, table: TpuTable) -> TpuTable:
        idxs = jnp.asarray(_col_indices(table, self.params.input_cols))
        Xsel = jnp.take(table.X, idxs, axis=1)
        binz = (Xsel > self.params.threshold).astype(jnp.float32)
        return table.with_X(table.X.at[:, idxs].set(binz))


class VectorAssembler(Transformer):
    """Column projection for API parity: our table IS the assembled matrix."""

    def __init__(self, input_cols: Sequence[str]):
        self.params = Params()
        self.input_cols = tuple(input_cols)

    def transform(self, table: TpuTable) -> TpuTable:
        return table.select(self.input_cols)


@dataclasses.dataclass(frozen=True)
class FeatureHasherParams(Params):
    num_features: int = 256      # MLlib numFeatures (power of two)
    input_cols: tuple = ()       # continuous and/or discrete attribute names


class FeatureHasher(Transformer):
    ParamsCls = FeatureHasherParams
    """MLlib FeatureHasher: continuous cols add their value at hash(name);
    discrete cols add 1.0 at hash(name + '=' + category).

    Hash buckets are computed host-side from column METADATA only (names and
    category sets — tiny), then the row-wise scatter happens on device as a
    dense [n_cols_or_cats, num_features] matmul: one-hot-via-matmul keeps the
    op on the MXU instead of a gather/scatter.
    """

    def transform(self, table: TpuTable) -> TpuTable:
        import zlib

        p = self.params
        nf = p.num_features
        cols = p.input_cols or tuple(v.name for v in table.domain.attributes)
        cont_idx, cont_bucket = [], []
        disc_idx, disc_maps = [], []
        for name in cols:
            var = table.domain[name]
            j = table.domain.index(name)
            if isinstance(var, DiscreteVariable):
                buckets = [
                    zlib.crc32(f"{name}={v}".encode()) % nf for v in var.values
                ]
                disc_idx.append(j)
                disc_maps.append(buckets)
            else:
                cont_idx.append(j)
                cont_bucket.append(zlib.crc32(name.encode()) % nf)
        out = jnp.zeros((table.n_pad, nf), dtype=jnp.float32)
        if cont_idx:
            # projection matrix [n_cont, nf]: row j has 1 at its bucket
            Pm = np.zeros((len(cont_idx), nf), dtype=np.float32)
            for r, b in enumerate(cont_bucket):
                Pm[r, b] = 1.0
            Xc = jnp.take(table.X, jnp.asarray(cont_idx, dtype=jnp.int32), axis=1)
            out = out + Xc @ jnp.asarray(Pm)
        for j, buckets in zip(disc_idx, disc_maps, strict=True):
            k = len(buckets)
            onehot = jax.nn.one_hot(table.X[:, j].astype(jnp.int32), k, dtype=jnp.float32)
            Pm = np.zeros((k, nf), dtype=np.float32)
            for r, b in enumerate(buckets):
                Pm[r, b] = 1.0
            out = out + onehot @ jnp.asarray(Pm)
        new_domain = Domain(
            [ContinuousVariable(f"hash_{i}") for i in range(nf)],
            table.domain.class_vars, table.domain.metas,
        )
        return table.with_X(out, new_domain)


# ---------------------------------------------------------------------------
# Target encoding (pyspark.ml.feature.TargetEncoder, Spark 4.0)
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class TargetEncoderParams(Params):
    input_cols: tuple = ()        # discrete attribute names
    target_type: str = "binary"   # MLlib targetType: 'binary' | 'continuous'
    smoothing: float = 0.0        # MLlib smoothing (shrink toward the prior)
    handle_invalid: str = "error" # 'error' | 'keep' (unseen -> global prior)


class TargetEncoderModel(Model):
    """Per-category target means, smoothing-shrunk toward the global prior:
    enc[c] = (sum_y[c] + smoothing * prior) / (count[c] + smoothing)."""

    def __init__(self, params, col_idx, tables, prior):
        self.params = params
        self.col_idx = col_idx     # list[int]
        self.tables = tables       # list[f32[k+1]] (last slot = unseen)
        self.prior = prior

    @property
    def state_pytree(self):
        return {f"enc_{j}": t for j, t in zip(self.col_idx, self.tables)}

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        X = table.X
        new_attrs = list(table.domain.attributes)
        for j, enc, name in zip(self.col_idx, self.tables,
                                p.input_cols, strict=True):
            k = enc.shape[0] - 1
            raw = X[:, j].astype(jnp.int32)
            if p.handle_invalid == "error":
                live = jnp.where(table.W > 0, raw, 0)
                mx = int(np.asarray(jnp.max(live)).item())
                if mx >= k:
                    raise ValueError(
                        f"column {name!r} has unseen category {mx} "
                        "(handle_invalid='error')"
                    )
            idx = jnp.clip(raw, 0, k - 1)
            idx = jnp.where((raw < 0) | (raw >= k), k, idx)  # unseen slot
            X = X.at[:, j].set(jnp.take(enc, idx))
            new_attrs[j] = ContinuousVariable(f"{name}_te")
        domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        return table.with_X(X, domain)


class TargetEncoder(Estimator):
    """Mean target encoding per category — the hashed/one-hot alternative
    for high-cardinality categoricals (segment_sum over the sharded rows;
    the per-category reduction GSPMD all-reduces over ICI)."""

    ParamsCls = TargetEncoderParams
    params: TargetEncoderParams

    def _fit(self, table: TpuTable) -> TargetEncoderModel:
        p = self.params
        if not p.input_cols:
            raise ValueError("TargetEncoder needs input_cols")
        y = table.y
        W = table.W
        prior = float(jnp.sum(y * W) / jnp.maximum(jnp.sum(W), 1e-12))
        col_idx, tables = [], []
        for name in p.input_cols:
            var = table.domain[name]
            j = table.domain.index(var)
            col_idx.append(j)
            if isinstance(var, DiscreteVariable) and var.values:
                k = len(var.values)
            else:
                k = int(np.asarray(
                    jnp.max(jnp.where(W > 0, table.X[:, j], 0.0))).item()) + 1
            idx = jnp.clip(table.X[:, j].astype(jnp.int32), 0, k - 1)
            sum_y = jax.ops.segment_sum(y * W, idx, num_segments=k)
            cnt = jax.ops.segment_sum(W, idx, num_segments=k)
            enc = (sum_y + p.smoothing * prior) / jnp.maximum(
                cnt + p.smoothing, 1e-12
            )
            enc = jnp.where(cnt > 0, enc, prior)
            # slot k serves unseen categories at transform time
            tables.append(jnp.concatenate([enc, jnp.asarray([prior])]))
        return TargetEncoderModel(p, col_idx, tables, prior)
