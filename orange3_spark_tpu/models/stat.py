"""Statistics — parity with ``pyspark.ml.stat`` (Correlation, ChiSquareTest,
Summarizer, KolmogorovSmirnovTest).

MLlib computes these with one treeAggregate pass per statistic (Pearson via
a Gramian aggregate, chi-square via per-feature contingency counts;
SURVEY.md §2b/§5 — reconstructed, mount empty). TPU-native redesign: each
statistic is a single jitted program whose row-axis contractions are MXU
matmuls / segment-sums that GSPMD all-reduces over ICI. Spearman's rank
transform — a full shuffle-sort in Spark — is a device ``argsort`` chain
with tie-averaging via segment ops, no host round-trip. P-values come from
``jax.scipy.special`` on device (no scipy dependency).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.ops.stats import EPS_TOTAL_WEIGHT, weighted_moments

_BIG = jnp.float32(np.finfo(np.float32).max)


# ------------------------------------------------------------- correlation
@jax.jit
def _pearson_kernel(X, w):
    """Weighted Pearson correlation matrix [d, d] of row-sharded X."""
    mean, var, tot = weighted_moments(X, w)
    Xc = X - mean
    cov = (Xc * w[:, None]).T @ Xc / tot            # [d,d] MXU Gramian
    std = jnp.sqrt(jnp.maximum(var, 0.0))
    denom = jnp.outer(std, std)
    corr = jnp.where(denom > EPS_TOTAL_WEIGHT, cov / jnp.maximum(denom, EPS_TOTAL_WEIGHT), 0.0)
    # exact 1.0 diagonal regardless of fp rounding
    return jnp.fill_diagonal(jnp.clip(corr, -1.0, 1.0), 1.0, inplace=False)


@jax.jit
def _tie_averaged_ranks(X, w):
    """Per-column fractional (tie-averaged) ranks of the LIVE rows.

    Padding/filtered rows (w == 0) are pushed to +inf so they occupy the top
    ranks and never perturb live-row ranks; callers must mask them out via w.
    """
    N = X.shape[0]
    Xm = jnp.where(w[:, None] > 0, X, _BIG)
    order = jnp.argsort(Xm, axis=0)                            # [N, d]
    Xs = jnp.take_along_axis(Xm, order, axis=0)
    pos = jnp.arange(1, N + 1, dtype=jnp.float32)[:, None] * jnp.ones_like(Xs)
    new_group = jnp.concatenate(
        [jnp.ones((1, X.shape[1]), bool), Xs[1:] != Xs[:-1]], axis=0
    )
    gid = jnp.cumsum(new_group.astype(jnp.int32), axis=0) - 1  # [N, d]
    def per_col(g, p):
        s = jax.ops.segment_sum(p, g, num_segments=N)
        c = jax.ops.segment_sum(jnp.ones_like(p), g, num_segments=N)
        return (s / jnp.maximum(c, 1.0))[g]
    avg_sorted = jax.vmap(per_col, in_axes=1, out_axes=1)(gid, pos)
    inv = jnp.argsort(order, axis=0)                           # undo the sort
    return jnp.take_along_axis(avg_sorted, inv, axis=0)


class Correlation:
    """``pyspark.ml.stat.Correlation.corr`` equivalent."""

    @staticmethod
    def corr(table: TpuTable, method: str = "pearson") -> np.ndarray:
        X, w = table.X, table.W
        if method == "pearson":
            return np.asarray(_pearson_kernel(X, w))
        if method == "spearman":
            ranks = _tie_averaged_ranks(X, w)
            return np.asarray(_pearson_kernel(ranks, w))
        raise ValueError(f"method must be 'pearson' or 'spearman', got {method!r}")


# ----------------------------------------------------------- chi-square test
class ChiSquareResult(NamedTuple):
    p_values: np.ndarray          # f64[n_features]
    degrees_of_freedom: np.ndarray  # i64[n_features]
    statistics: np.ndarray        # f64[n_features]


def _chi2_sf(stat, dof):
    """Chi-square survival function via the regularized upper gamma."""
    return jax.scipy.special.gammaincc(jnp.maximum(dof, 1.0) / 2.0, stat / 2.0)


@partial(jax.jit, static_argnames=("m", "k"))
def _contingency(f, y, w, *, m: int, k: int):
    """Weighted [m, k] contingency table of one categorical feature vs label."""
    fh = jax.nn.one_hot(f.astype(jnp.int32), m, dtype=jnp.float32) * w[:, None]
    yh = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=jnp.float32)
    return fh.T @ yh                                           # [m, k] on MXU


class ChiSquareTest:
    """``pyspark.ml.stat.ChiSquareTest.test`` equivalent.

    Pearson's independence test of each categorical feature column against
    the (categorical) class column; feature values must be small nonnegative
    integers (bin with Bucketizer/QuantileDiscretizer first, as in Spark).
    """

    @staticmethod
    def test(table: TpuTable, feature_cols: Sequence[str] | None = None) -> ChiSquareResult:
        y = table.y
        w = table.W
        names = list(feature_cols) if feature_cols is not None else [
            v.name for v in table.domain.attributes
        ]
        # ONE host sync for every cardinality, ONE compile of the contingency
        # kernel: all maxes in a fused device call, m shared across features
        # (padded; empty categories drop out of the statistic below)
        cols = [table.column(name) for name in names]
        live = w > 0
        maxes = np.asarray(jax.jit(
            lambda cs, yy: jnp.stack(
                [jnp.max(jnp.where(live, c, 0.0)) for c in cs]
                + [jnp.max(jnp.where(live, yy, 0.0))]
            )
        )(cols, y))
        k = int(maxes[-1]) + 1
        m = int(maxes[:-1].max()) + 1 if names else 1
        stats, dofs, ps = [], [], []
        for f in cols:
            obs = _contingency(f, y, w, m=m, k=k)
            obs_np = np.asarray(obs, dtype=np.float64)
            row = obs_np.sum(1, keepdims=True)
            col = obs_np.sum(0, keepdims=True)
            tot = max(obs_np.sum(), EPS_TOTAL_WEIGHT)
            exp = row @ col / tot
            live = (row > 0) & (col > 0)
            stat = float(((obs_np - exp) ** 2 / np.where(live, exp, 1.0))[live].sum())
            dof = max((int((row > 0).sum()) - 1) * (int((col > 0).sum()) - 1), 0)
            p = float(_chi2_sf(jnp.float32(stat), jnp.float32(dof))) if dof > 0 else 1.0
            stats.append(stat)
            dofs.append(dof)
            ps.append(p)
        return ChiSquareResult(np.array(ps), np.array(dofs), np.array(stats))


# ---------------------------------------------------------------- summarizer
class Summary(NamedTuple):
    mean: np.ndarray        # weighted mean per column
    variance: np.ndarray    # unbiased weighted variance (MLlib convention)
    std: np.ndarray
    count: int              # live row count
    weight_sum: float
    num_non_zeros: np.ndarray
    max: np.ndarray
    min: np.ndarray
    norm_l1: np.ndarray     # Σ w·|x|
    norm_l2: np.ndarray     # sqrt(Σ w·x²)
    sum: np.ndarray         # Σ w·x


@jax.jit
def _summary_kernel(X, w):
    mean, var_pop, tot = weighted_moments(X, w)
    wcol = w[:, None]
    live = wcol > 0
    count = jnp.sum(live.astype(jnp.float32)[:, 0])
    # MLlib MultivariateOnlineSummarizer divides M2 by (Σw - 1): unbiased
    var = var_pop * tot / jnp.maximum(tot - 1.0, EPS_TOTAL_WEIGHT)
    nnz = jnp.sum((jnp.abs(X) > 0) & live, axis=0).astype(jnp.float32)
    mx = jnp.max(jnp.where(live, X, -_BIG), axis=0)
    mn = jnp.min(jnp.where(live, X, _BIG), axis=0)
    l1 = jnp.sum(jnp.abs(X) * wcol, axis=0)
    l2 = jnp.sqrt(jnp.sum(X * X * wcol, axis=0))
    s = jnp.sum(X * wcol, axis=0)
    return mean, var, count, tot, nnz, mx, mn, l1, l2, s


class Summarizer:
    """``pyspark.ml.stat.Summarizer`` equivalent — one fused pass."""

    @staticmethod
    def metrics(table: TpuTable) -> Summary:
        mean, var, count, tot, nnz, mx, mn, l1, l2, s = _summary_kernel(
            table.X, table.W
        )
        return Summary(
            mean=np.asarray(mean), variance=np.asarray(var),
            std=np.sqrt(np.maximum(np.asarray(var), 0.0)),
            count=int(count), weight_sum=float(tot),
            num_non_zeros=np.asarray(nnz), max=np.asarray(mx), min=np.asarray(mn),
            norm_l1=np.asarray(l1), norm_l2=np.asarray(l2), sum=np.asarray(s),
        )


# ------------------------------------------------------ Kolmogorov–Smirnov
class KSTestResult(NamedTuple):
    p_value: float
    statistic: float


@jax.jit
def _ks_kernel(x, w, mu, sigma):
    """One-sample KS statistic vs Normal(mu, sigma) over live rows."""
    N = x.shape[0]
    live = w > 0
    n = jnp.maximum(jnp.sum(live.astype(jnp.float32)), 1.0)
    xs = jnp.sort(jnp.where(live, x, _BIG))           # live values first
    cdf = jax.scipy.stats.norm.cdf(xs, loc=mu, scale=sigma)
    i = jnp.arange(1, N + 1, dtype=jnp.float32)
    in_range = i <= n                                  # ignore padding slots
    d_plus = jnp.where(in_range, i / n - cdf, -1.0)
    d_minus = jnp.where(in_range, cdf - (i - 1.0) / n, -1.0)
    return jnp.maximum(jnp.max(d_plus), jnp.max(d_minus)), n


def _ks_pvalue(d: float, n: float) -> float:
    """Asymptotic Kolmogorov distribution tail, Q(√n·D)."""
    t = (np.sqrt(n) + 0.12 + 0.11 / np.sqrt(n)) * d
    j = np.arange(1, 101)
    return float(np.clip(2.0 * np.sum((-1.0) ** (j - 1) * np.exp(-2.0 * j**2 * t**2)), 0.0, 1.0))


class KolmogorovSmirnovTest:
    """``pyspark.ml.stat.KolmogorovSmirnovTest.test`` equivalent ('norm')."""

    @staticmethod
    def test(table: TpuTable, col: str, dist: str = "norm",
             loc: float = 0.0, scale: float = 1.0) -> KSTestResult:
        if dist != "norm":
            raise ValueError(f"only dist='norm' is supported, got {dist!r}")
        d, n = _ks_kernel(table.column(col), table.W,
                          jnp.float32(loc), jnp.float32(scale))
        d, n = float(d), float(n)
        return KSTestResult(p_value=_ks_pvalue(d, n), statistic=d)
