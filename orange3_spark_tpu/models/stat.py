"""Statistics — parity with ``pyspark.ml.stat`` (Correlation, ChiSquareTest,
Summarizer, KolmogorovSmirnovTest, ANOVATest, FValueTest,
MultivariateGaussian).

MLlib computes these with one treeAggregate pass per statistic (Pearson via
a Gramian aggregate, chi-square via per-feature contingency counts;
SURVEY.md §2b/§5 — reconstructed, mount empty). TPU-native redesign: each
statistic is a single jitted program whose row-axis contractions are MXU
matmuls / segment-sums that GSPMD all-reduces over ICI. Spearman's rank
transform — a full shuffle-sort in Spark — is a device ``argsort`` chain
with tie-averaging via segment ops, no host round-trip. P-values come from
``jax.scipy.special`` on device (no scipy dependency).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.ops.stats import EPS_TOTAL_WEIGHT, weighted_moments

_BIG = jnp.float32(np.finfo(np.float32).max)


# ------------------------------------------------------------- correlation
@jax.jit
def _pearson_kernel(X, w):
    """Weighted Pearson correlation matrix [d, d] of row-sharded X."""
    mean, var, tot = weighted_moments(X, w)
    Xc = X - mean
    cov = (Xc * w[:, None]).T @ Xc / tot            # [d,d] MXU Gramian
    std = jnp.sqrt(jnp.maximum(var, 0.0))
    denom = jnp.outer(std, std)
    corr = jnp.where(denom > EPS_TOTAL_WEIGHT, cov / jnp.maximum(denom, EPS_TOTAL_WEIGHT), 0.0)
    # exact 1.0 diagonal regardless of fp rounding
    return jnp.fill_diagonal(jnp.clip(corr, -1.0, 1.0), 1.0, inplace=False)


@jax.jit
def _tie_averaged_ranks(X, w):
    """Per-column fractional (tie-averaged) ranks of the LIVE rows.

    Padding/filtered rows (w == 0) are pushed to +inf so they occupy the top
    ranks and never perturb live-row ranks; callers must mask them out via w.
    """
    N = X.shape[0]
    Xm = jnp.where(w[:, None] > 0, X, _BIG)
    order = jnp.argsort(Xm, axis=0)                            # [N, d]
    Xs = jnp.take_along_axis(Xm, order, axis=0)
    pos = jnp.arange(1, N + 1, dtype=jnp.float32)[:, None] * jnp.ones_like(Xs)
    new_group = jnp.concatenate(
        [jnp.ones((1, X.shape[1]), bool), Xs[1:] != Xs[:-1]], axis=0
    )
    gid = jnp.cumsum(new_group.astype(jnp.int32), axis=0) - 1  # [N, d]
    def per_col(g, p):
        s = jax.ops.segment_sum(p, g, num_segments=N)
        c = jax.ops.segment_sum(jnp.ones_like(p), g, num_segments=N)
        return (s / jnp.maximum(c, 1.0))[g]
    avg_sorted = jax.vmap(per_col, in_axes=1, out_axes=1)(gid, pos)
    inv = jnp.argsort(order, axis=0)                           # undo the sort
    return jnp.take_along_axis(avg_sorted, inv, axis=0)


class Correlation:
    """``pyspark.ml.stat.Correlation.corr`` equivalent."""

    @staticmethod
    def corr(table: TpuTable, method: str = "pearson") -> np.ndarray:
        X, w = table.X, table.W
        if method == "pearson":
            return np.asarray(_pearson_kernel(X, w))
        if method == "spearman":
            ranks = _tie_averaged_ranks(X, w)
            return np.asarray(_pearson_kernel(ranks, w))
        raise ValueError(f"method must be 'pearson' or 'spearman', got {method!r}")


# ----------------------------------------------------------- chi-square test
class ChiSquareResult(NamedTuple):
    p_values: np.ndarray          # f64[n_features]
    degrees_of_freedom: np.ndarray  # i64[n_features]
    statistics: np.ndarray        # f64[n_features]


def _chi2_sf(stat, dof):
    """Chi-square survival function via the regularized upper gamma."""
    return jax.scipy.special.gammaincc(jnp.maximum(dof, 1.0) / 2.0, stat / 2.0)


@partial(jax.jit, static_argnames=("m", "k"))
def _contingency(f, y, w, *, m: int, k: int):
    """Weighted [m, k] contingency table of one categorical feature vs label."""
    fh = jax.nn.one_hot(f.astype(jnp.int32), m, dtype=jnp.float32) * w[:, None]
    yh = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=jnp.float32)
    return fh.T @ yh                                           # [m, k] on MXU


class ChiSquareTest:
    """``pyspark.ml.stat.ChiSquareTest.test`` equivalent.

    Pearson's independence test of each categorical feature column against
    the (categorical) class column; feature values must be small nonnegative
    integers (bin with Bucketizer/QuantileDiscretizer first, as in Spark).
    """

    @staticmethod
    def test(table: TpuTable, feature_cols: Sequence[str] | None = None) -> ChiSquareResult:
        y = table.y
        w = table.W
        names = list(feature_cols) if feature_cols is not None else [
            v.name for v in table.domain.attributes
        ]
        # ONE host sync for every cardinality, ONE compile of the contingency
        # kernel: all maxes in a fused device call, m shared across features
        # (padded; empty categories drop out of the statistic below)
        cols = [table.column(name) for name in names]
        live = w > 0
        maxes = np.asarray(jax.jit(
            lambda cs, yy: jnp.stack(
                [jnp.max(jnp.where(live, c, 0.0)) for c in cs]
                + [jnp.max(jnp.where(live, yy, 0.0))]
            )
        )(cols, y))
        k = int(maxes[-1]) + 1
        m = int(maxes[:-1].max()) + 1 if names else 1
        stats, dofs, ps = [], [], []
        for f in cols:
            obs = _contingency(f, y, w, m=m, k=k)
            obs_np = np.asarray(obs, dtype=np.float64)
            row = obs_np.sum(1, keepdims=True)
            col = obs_np.sum(0, keepdims=True)
            tot = max(obs_np.sum(), EPS_TOTAL_WEIGHT)
            exp = row @ col / tot
            live = (row > 0) & (col > 0)
            stat = float(((obs_np - exp) ** 2 / np.where(live, exp, 1.0))[live].sum())
            dof = max((int((row > 0).sum()) - 1) * (int((col > 0).sum()) - 1), 0)
            p = float(_chi2_sf(jnp.float32(stat), jnp.float32(dof))) if dof > 0 else 1.0
            stats.append(stat)
            dofs.append(dof)
            ps.append(p)
        return ChiSquareResult(np.array(ps), np.array(dofs), np.array(stats))


# ---------------------------------------------------------------- summarizer
class Summary(NamedTuple):
    mean: np.ndarray        # weighted mean per column
    variance: np.ndarray    # unbiased weighted variance (MLlib convention)
    std: np.ndarray
    count: int              # live row count
    weight_sum: float
    num_non_zeros: np.ndarray
    max: np.ndarray
    min: np.ndarray
    norm_l1: np.ndarray     # Σ w·|x|
    norm_l2: np.ndarray     # sqrt(Σ w·x²)
    sum: np.ndarray         # Σ w·x


@jax.jit
def _summary_kernel(X, w):
    mean, var_pop, tot = weighted_moments(X, w)
    wcol = w[:, None]
    live = wcol > 0
    count = jnp.sum(live.astype(jnp.float32)[:, 0])
    # MLlib MultivariateOnlineSummarizer divides M2 by (Σw - 1): unbiased
    var = var_pop * tot / jnp.maximum(tot - 1.0, EPS_TOTAL_WEIGHT)
    nnz = jnp.sum((jnp.abs(X) > 0) & live, axis=0).astype(jnp.float32)
    mx = jnp.max(jnp.where(live, X, -_BIG), axis=0)
    mn = jnp.min(jnp.where(live, X, _BIG), axis=0)
    l1 = jnp.sum(jnp.abs(X) * wcol, axis=0)
    l2 = jnp.sqrt(jnp.sum(X * X * wcol, axis=0))
    s = jnp.sum(X * wcol, axis=0)
    return mean, var, count, tot, nnz, mx, mn, l1, l2, s


class Summarizer:
    """``pyspark.ml.stat.Summarizer`` equivalent — one fused pass."""

    @staticmethod
    def metrics(table: TpuTable) -> Summary:
        mean, var, count, tot, nnz, mx, mn, l1, l2, s = _summary_kernel(
            table.X, table.W
        )
        return Summary(
            mean=np.asarray(mean), variance=np.asarray(var),
            std=np.sqrt(np.maximum(np.asarray(var), 0.0)),
            count=int(count), weight_sum=float(tot),
            num_non_zeros=np.asarray(nnz), max=np.asarray(mx), min=np.asarray(mn),
            norm_l1=np.asarray(l1), norm_l2=np.asarray(l2), sum=np.asarray(s),
        )


# ------------------------------------------------------ Kolmogorov–Smirnov
class KSTestResult(NamedTuple):
    p_value: float
    statistic: float


@jax.jit
def _ks_kernel(x, w, mu, sigma):
    """One-sample KS statistic vs Normal(mu, sigma) over live rows."""
    N = x.shape[0]
    live = w > 0
    n = jnp.maximum(jnp.sum(live.astype(jnp.float32)), 1.0)
    xs = jnp.sort(jnp.where(live, x, _BIG))           # live values first
    cdf = jax.scipy.stats.norm.cdf(xs, loc=mu, scale=sigma)
    i = jnp.arange(1, N + 1, dtype=jnp.float32)
    in_range = i <= n                                  # ignore padding slots
    d_plus = jnp.where(in_range, i / n - cdf, -1.0)
    d_minus = jnp.where(in_range, cdf - (i - 1.0) / n, -1.0)
    return jnp.maximum(jnp.max(d_plus), jnp.max(d_minus)), n


def _ks_pvalue(d: float, n: float) -> float:
    """Asymptotic Kolmogorov distribution tail, Q(√n·D)."""
    t = (np.sqrt(n) + 0.12 + 0.11 / np.sqrt(n)) * d
    j = np.arange(1, 101)
    return float(np.clip(2.0 * np.sum((-1.0) ** (j - 1) * np.exp(-2.0 * j**2 * t**2)), 0.0, 1.0))


class KolmogorovSmirnovTest:
    """``pyspark.ml.stat.KolmogorovSmirnovTest.test`` equivalent ('norm')."""

    @staticmethod
    def test(table: TpuTable, col: str, dist: str = "norm",
             loc: float = 0.0, scale: float = 1.0) -> KSTestResult:
        if dist != "norm":
            raise ValueError(f"only dist='norm' is supported, got {dist!r}")
        d, n = _ks_kernel(table.column(col), table.W,
                          jnp.float32(loc), jnp.float32(scale))
        d, n = float(d), float(n)
        return KSTestResult(p_value=_ks_pvalue(d, n), statistic=d)


# ------------------------------------------------------- ANOVA / F-value
class FTestResult(NamedTuple):
    p_values: np.ndarray            # f64[n_features]
    degrees_of_freedom: np.ndarray  # i64[n_features, 2] — (df_between, df_within)
    f_values: np.ndarray            # f64[n_features]


def _f_sf(f, d1, d2):
    """F-distribution survival function via the regularized incomplete
    beta: sf(f; d1, d2) = I_{d2/(d2 + d1 f)}(d2/2, d1/2)."""
    x = d2 / (d2 + d1 * jnp.maximum(f, 0.0))
    return jax.scipy.special.betainc(d2 / 2.0, d1 / 2.0, x)


@partial(jax.jit, static_argnames=("k",))
def _anova_kernel(X, y, w, *, k: int):
    """Per-column one-way ANOVA F + dfs of continuous features vs a k-class
    label (weighted; padding rows carry w=0). THE one ANOVA kernel —
    feature_extra._anova_f (UnivariateFeatureSelector) delegates here."""
    yi = y.astype(jnp.int32)
    onehot = jax.nn.one_hot(yi, k, dtype=jnp.float32) * w[:, None]    # [N,k]
    raw_cnt = jnp.sum(onehot, axis=0)                                 # [k]
    cnt = jnp.maximum(raw_cnt, 1e-12)
    tot_w = jnp.maximum(jnp.sum(w), 1e-12)
    grand = jnp.sum(X * w[:, None], axis=0) / tot_w                   # [d]
    grp_sum = onehot.T @ X                                            # [k,d]
    grp_mean = grp_sum / cnt[:, None]
    ss_between = jnp.sum(cnt[:, None] * (grp_mean - grand[None, :]) ** 2,
                         axis=0)
    ex2 = jnp.sum((X * X) * w[:, None], axis=0)
    ss_within = ex2 - jnp.sum(cnt[:, None] * grp_mean**2, axis=0)
    # dfs count OBSERVED groups (sklearn/Spark use distinct present
    # classes): an unobserved class index must not inflate df_between —
    # its empty group contributes ~0 to ss_between, so k-1 would halve F
    n_grp = jnp.sum(raw_cnt > 1e-6).astype(jnp.float32)
    df_b = jnp.maximum(n_grp - 1.0, 1.0)
    df_w = jnp.maximum(tot_w - n_grp, 1.0)
    f = (ss_between / df_b) / jnp.maximum(ss_within / df_w, 1e-12)
    return f, df_b, df_w, _f_sf(f, df_b, df_w)


class ANOVATest:
    """``pyspark.ml.stat.ANOVATest.test`` equivalent (Spark 3.1).

    One-way ANOVA F-test of each continuous feature column against the
    categorical class column. One jitted program: class one-hot ridden on
    the MXU for the group sums (MLlib aggregates per-class sums/counts in
    a treeAggregate pass; SURVEY §2b — reconstructed, mount empty), the
    F survival function evaluated on device via the regularized
    incomplete beta. Matches sklearn.feature_selection.f_classif on
    uniform weights (pinned in tests/test_batch1.py).
    """

    @staticmethod
    def test(table: TpuTable,
             feature_cols: Sequence[str] | None = None) -> FTestResult:
        names = list(feature_cols) if feature_cols is not None else [
            v.name for v in table.domain.attributes
        ]
        X = (table.X if feature_cols is None
             else jnp.stack([table.column(n) for n in names], axis=1))
        y, w = table.y, table.W
        k = int(np.asarray(jnp.max(jnp.where(w > 0, y, 0.0)))) + 1
        f, df_b, df_w, p = _anova_kernel(X, y, w, k=k)
        d = len(names)
        dofs = np.stack([np.full(d, int(df_b)),
                         np.full(d, int(np.asarray(df_w)))], axis=1)
        return FTestResult(np.asarray(p, np.float64), dofs,
                           np.asarray(f, np.float64))


@jax.jit
def _fvalue_kernel(X, y, w):
    """Per-column regression F-test of continuous features vs a continuous
    label: F = r^2/(1-r^2) * df2 with df (1, n-2), r the weighted Pearson
    correlation — one pass of weighted moments, all columns at once."""
    tot_w = jnp.maximum(jnp.sum(w), 1e-12)
    xm = jnp.sum(X * w[:, None], axis=0) / tot_w
    ym = jnp.sum(y * w) / tot_w
    xc = X - xm[None, :]
    yc = y - ym
    cov = jnp.sum(xc * (yc * w)[:, None], axis=0)
    vx = jnp.maximum(jnp.sum(xc * xc * w[:, None], axis=0), 1e-12)
    vy = jnp.maximum(jnp.sum(yc * yc * w), 1e-12)
    r2 = jnp.clip(cov * cov / (vx * vy), 0.0, 1.0 - 1e-9)
    df2 = jnp.maximum(tot_w - 2.0, 1.0)
    f = r2 / (1.0 - r2) * df2
    return f, df2, _f_sf(f, jnp.float32(1.0), df2)


class FValueTest:
    """``pyspark.ml.stat.FValueTest.test`` equivalent (Spark 3.1).

    F-test of each continuous feature against a CONTINUOUS label via the
    squared weighted Pearson correlation, df (1, n-2). Matches
    sklearn.feature_selection.f_regression on uniform weights (pinned in
    tests/test_batch1.py).
    """

    @staticmethod
    def test(table: TpuTable,
             feature_cols: Sequence[str] | None = None) -> FTestResult:
        names = list(feature_cols) if feature_cols is not None else [
            v.name for v in table.domain.attributes
        ]
        X = (table.X if feature_cols is None
             else jnp.stack([table.column(n) for n in names], axis=1))
        f, df2, p = _fvalue_kernel(X, table.y, table.W)
        d = len(names)
        dofs = np.stack([np.ones(d, np.int64),
                         np.full(d, int(np.asarray(df2)))], axis=1)
        return FTestResult(np.asarray(p, np.float64), dofs,
                           np.asarray(f, np.float64))


# -------------------------------------------------- multivariate gaussian
class MultivariateGaussian:
    """``pyspark.ml.stat.distribution.MultivariateGaussian`` equivalent.

    Density of N(mean, cov) with the degenerate-covariance handling MLlib
    documents (pseudo-inverse via eigendecomposition, pseudo-determinant
    over eigenvalues above the numerical tolerance). The decomposition
    happens once at construction; ``pdf``/``logpdf`` evaluate batches of
    points as one jitted program (rows stay sharded over the data axis).
    """

    def __init__(self, mean, cov):
        mean64 = np.asarray(mean, np.float64)
        cov64 = np.asarray(cov, np.float64)
        d = mean64.shape[0]
        if cov64.shape != (d, d):
            raise ValueError(f"cov must be ({d},{d}), got {cov64.shape}")
        # construction-time [d,d] decomposition on the HOST in float64,
        # but with a FLOAT32-scaled rank tolerance: this framework's
        # tables are f32, so a covariance that was ever rounded through
        # f32 carries ~1e-9 noise eigenvalues — a float64-eps tolerance
        # would count that noise as real rank and poison the
        # pseudo-determinant (scipy upcasting f32 input shows exactly
        # this failure). MLlib runs eps*d*max|λ| at its working
        # precision (doubles); ours is f32, so scale accordingly.
        evals, evecs = np.linalg.eigh(cov64)
        tol = (np.finfo(np.float32).eps * d) * np.max(np.abs(evals))
        live = evals > tol
        if not live.any():
            # MLlib convention: a covariance with no eigenvalue above the
            # tolerance is an error, not a rank-0 'density'
            raise ValueError("covariance matrix has no non-zero eigenvalue")
        inv = np.zeros(d)
        inv[live] = 1.0 / evals[live]
        self.mean = jnp.asarray(mean64, jnp.float32)
        self.cov = jnp.asarray(cov64, jnp.float32)
        # rootSigmaInv rows scaled by 1/sqrt(eigenvalue) on the live spectrum
        self._root_inv = jnp.asarray(evecs * np.sqrt(inv)[None, :],
                                     jnp.float32)                  # [d, d]
        log_pseudo_det = float(np.sum(np.log(evals[live])))
        # MLlib normalizes by the FULL dimension (mean.size * log(2π) +
        # log pseudo-det), not by the rank as scipy's allow_singular
        # does — on a rank-r covariance the two differ by
        # 0.5*(d-r)*log(2π). We follow the MLlib (parity) convention.
        self._log_norm = -0.5 * (d * float(np.log(2.0 * np.pi))
                                 + log_pseudo_det)

    def logpdf(self, x) -> jax.Array:
        """log N(x; mean, cov) for one point [d] or a batch [n, d]."""
        x = jnp.asarray(x, jnp.float32)
        out = _mvn_logpdf_kernel(jnp.atleast_2d(x), self.mean,
                                 self._root_inv,
                                 jnp.float32(self._log_norm))
        return out[0] if x.ndim == 1 else out

    def pdf(self, x) -> jax.Array:
        return jnp.exp(self.logpdf(x))


@jax.jit
def _mvn_logpdf_kernel(x, mean, root_inv, log_norm):
    """One fused program: rows of ``x`` stay sharded over the data axis;
    the Mahalanobis contraction rides the MXU."""
    z = (x - mean[None, :]) @ root_inv                             # [n, d]
    return log_norm - 0.5 * jnp.sum(z * z, axis=1)
