"""LinearRegression — parity with ``pyspark.ml.regression.LinearRegression``.

MLlib solves either by WLS normal equations (small d) or L-BFGS; we provide
both: ``solver='normal'`` builds the Gramian with one ICI all-reduce and
solves host-free via Cholesky, ``solver='l-bfgs'`` reuses the fused trainer.
(SURVEY.md §2b; reconstructed — reference mount empty.)
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models._linear import fit_linear
from orange3_spark_tpu.models.base import concrete_or_none, Estimator, Model, Params
from orange3_spark_tpu.ops.stats import EPS_TOTAL_WEIGHT


@dataclasses.dataclass(frozen=True)
class LinearRegressionParams(Params):
    max_iter: int = 100
    reg_param: float = 0.0
    elastic_net_param: float = 0.0  # MLlib elasticNetParam (L1 mixing, OWLQN)
    tol: float = 1e-6
    fit_intercept: bool = True
    solver: str = "normal"  # 'normal' | 'l-bfgs'  (MLlib solver param)
    compute_dtype: str = "float32"


@jax.jit
def _normal_equations(X, y, w):
    """Weighted ridge normal equations with one all-reduce over the row axis.

    Returns (XtX[d,d], Xty[d], x_sum[d], y_sum[], tot[]) so the intercept can
    be folded in without materializing a bias column.
    """
    wc = w[:, None]
    XtX = (X * wc).T @ X
    Xty = (X * wc).T @ (y * 1.0)
    x_sum = jnp.sum(X * wc, axis=0)
    y_sum = jnp.sum(y * w)
    tot = jnp.maximum(jnp.sum(w), EPS_TOTAL_WEIGHT)
    return XtX, Xty, x_sum, y_sum, tot


class LinearRegressionModel(Model):
    def __init__(self, params, coef, intercept):
        self.params = params
        self.coef = coef            # f32[d]
        self.intercept = intercept  # f32[]
        self.n_iter_: int | None = None

    @property
    def state_pytree(self):
        return {"coef": self.coef, "intercept": self.intercept}

    @staticmethod
    @jax.jit
    def _predict_kernel(X, coef, intercept):
        return X @ coef + intercept

    def predict(self, table: TpuTable) -> np.ndarray:
        yhat = self._predict_kernel(table.X, self.coef, self.intercept)
        return np.asarray(yhat)[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        yhat = self._predict_kernel(table.X, self.coef, self.intercept)
        new_attrs = list(table.domain.attributes) + [ContinuousVariable("prediction")]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, yhat[:, None]], axis=1)
        return table.with_X(X, new_domain)


class LinearRegression(Estimator):
    ParamsCls = LinearRegressionParams
    params: LinearRegressionParams

    def _fit(self, table: TpuTable) -> LinearRegressionModel:
        p = self.params
        if not 0.0 <= p.elastic_net_param <= 1.0:
            raise ValueError(
                f"elastic_net_param must be in [0, 1], got {p.elastic_net_param}"
            )
        y, X, w = table.y, table.X, table.W
        # L1 has no closed form — normal equations only serve fits whose
        # EFFECTIVE L1 strength reg_param*alpha is zero (MLlib's WLS solver
        # makes the same quasi-newton fallback)
        if p.solver == "normal" and p.reg_param * p.elastic_net_param == 0.0:
            XtX, Xty, x_sum, y_sum, tot = _normal_equations(X, y, w)
            d = X.shape[1]
            if p.fit_intercept:
                # center via the accumulated sums: solve on centered moments
                mean_x = x_sum / tot
                mean_y = y_sum / tot
                A = XtX - tot * jnp.outer(mean_x, mean_x)
                b = Xty - tot * mean_x * mean_y
            else:
                A, b = XtX, Xty
            # MLlib regParam scales the normalized objective; normal equations
            # are on the un-normalized sums, so multiply by total weight.
            A = A + p.reg_param * tot * jnp.eye(d, dtype=A.dtype)
            coef = jax.scipy.linalg.solve(A, b, assume_a="pos")
            intercept = (mean_y - coef @ mean_x) if p.fit_intercept else jnp.float32(0.0)
            model = LinearRegressionModel(p, coef, intercept)
            model.n_iter_ = 1
            return model
        alpha = p.elastic_net_param
        result = fit_linear(
            X, y, w,
            jnp.float32(p.reg_param * (1.0 - alpha)),
            jnp.float32(p.tol), jnp.int32(p.max_iter),
            None,
            jnp.float32(p.reg_param * alpha) if p.reg_param * alpha > 0.0 else None,
            loss_kind="squared", k=1, fit_intercept=p.fit_intercept,
            compute_dtype=jnp.dtype(p.compute_dtype),
        )
        model = LinearRegressionModel(p, result.coef[:, 0], result.intercept[0])
        model.n_iter_ = concrete_or_none(result.n_iter, int)
        return model
