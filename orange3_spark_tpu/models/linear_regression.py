"""LinearRegression — parity with ``pyspark.ml.regression.LinearRegression``.

MLlib solves either by WLS normal equations (small d) or L-BFGS; we provide
both: ``solver='normal'`` builds the Gramian with one ICI all-reduce and
solves host-free via Cholesky, ``solver='l-bfgs'`` reuses the fused trainer.
(SURVEY.md §2b; reconstructed — reference mount empty.)
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models._linear import fit_linear
from orange3_spark_tpu.models.base import concrete_or_none, Estimator, Model, Params
from orange3_spark_tpu.ops.stats import EPS_TOTAL_WEIGHT


@dataclasses.dataclass(frozen=True)
class LinearRegressionParams(Params):
    max_iter: int = 100
    reg_param: float = 0.0
    elastic_net_param: float = 0.0  # MLlib elasticNetParam (L1 mixing, OWLQN)
    tol: float = 1e-6
    fit_intercept: bool = True
    solver: str = "normal"  # 'normal' | 'l-bfgs'  (MLlib solver param)
    compute_dtype: str = "float32"


@jax.jit
def _training_summary(X, y, w, coef, intercept):
    """One fused pass over the training rows for MLlib's
    LinearRegressionTrainingSummary scalars (weighted r2 / RMSE / MAE /
    explainedVariance) — rows stay sharded; GSPMD reduces over ICI."""
    tot = jnp.maximum(jnp.sum(w), EPS_TOTAL_WEIGHT)
    yhat = X @ coef + intercept
    resid = y - yhat
    rss = jnp.sum(w * resid * resid)
    ybar = jnp.sum(w * y) / tot
    tss = jnp.maximum(jnp.sum(w * (y - ybar) ** 2), EPS_TOTAL_WEIGHT)
    mae = jnp.sum(w * jnp.abs(resid)) / tot
    # Spark's RegressionMetrics centers SSreg on the LABEL mean (not the
    # prediction mean) — the two differ for through-origin or
    # early-stopped fits whose predictions are biased
    expl = jnp.sum(w * (yhat - ybar) ** 2) / tot
    return rss, 1.0 - rss / tss, jnp.sqrt(rss / tot), mae, expl


@jax.jit
def _normal_equations(X, y, w):
    """Weighted ridge normal equations with one all-reduce over the row axis.

    Returns (XtX[d,d], Xty[d], x_sum[d], y_sum[], tot[]) so the intercept can
    be folded in without materializing a bias column.
    """
    wc = w[:, None]
    XtX = (X * wc).T @ X
    Xty = (X * wc).T @ (y * 1.0)
    x_sum = jnp.sum(X * wc, axis=0)
    y_sum = jnp.sum(y * w)
    tot = jnp.maximum(jnp.sum(w), EPS_TOTAL_WEIGHT)
    return XtX, Xty, x_sum, y_sum, tot


class LinearRegressionModel(Model):
    def __init__(self, params, coef, intercept):
        self.params = params
        self.coef = coef            # f32[d]
        self.intercept = intercept  # f32[]
        self.n_iter_: int | None = None
        # MLlib LinearRegressionTrainingSummary (filled at fit on the
        # training data; device scalars/arrays, trace-safe):
        self.r2_ = None                    # summary.r2
        self.root_mean_squared_error_ = None
        self.mean_absolute_error_ = None
        self.explained_variance_ = None
        # inference stats — solver='normal' with reg_param == 0 only
        # (MLlib raises elsewhere); order [coefficients..., intercept]
        self.coefficient_standard_errors_ = None
        self.t_values_ = None
        self.p_values_ = None

    @property
    def state_pytree(self):
        return {"coef": self.coef, "intercept": self.intercept}

    @staticmethod
    @jax.jit
    def _predict_kernel(X, coef, intercept):
        return X @ coef + intercept

    def predict(self, table: TpuTable) -> np.ndarray:
        yhat = self._predict_kernel(table.X, self.coef, self.intercept)
        return np.asarray(yhat)[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        yhat = self._predict_kernel(table.X, self.coef, self.intercept)
        new_attrs = list(table.domain.attributes) + [ContinuousVariable("prediction")]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, yhat[:, None]], axis=1)
        return table.with_X(X, new_domain)


class LinearRegression(Estimator):
    ParamsCls = LinearRegressionParams
    params: LinearRegressionParams

    def _fit(self, table: TpuTable) -> LinearRegressionModel:
        p = self.params
        if not 0.0 <= p.elastic_net_param <= 1.0:
            raise ValueError(
                f"elastic_net_param must be in [0, 1], got {p.elastic_net_param}"
            )
        y, X, w = table.y, table.X, table.W
        # L1 has no closed form — normal equations only serve fits whose
        # EFFECTIVE L1 strength reg_param*alpha is zero (MLlib's WLS solver
        # makes the same quasi-newton fallback)
        if p.solver == "normal" and p.reg_param * p.elastic_net_param == 0.0:
            XtX, Xty, x_sum, y_sum, tot = _normal_equations(X, y, w)
            d = X.shape[1]
            if p.fit_intercept:
                # center via the accumulated sums: solve on centered moments
                mean_x = x_sum / tot
                mean_y = y_sum / tot
                A = XtX - tot * jnp.outer(mean_x, mean_x)
                b = Xty - tot * mean_x * mean_y
            else:
                A, b = XtX, Xty
            # MLlib regParam scales the normalized objective; normal equations
            # are on the un-normalized sums, so multiply by total weight.
            A = A + p.reg_param * tot * jnp.eye(d, dtype=A.dtype)
            coef = jax.scipy.linalg.solve(A, b, assume_a="pos")
            intercept = (mean_y - coef @ mean_x) if p.fit_intercept else jnp.float32(0.0)
            model = LinearRegressionModel(p, coef, intercept)
            model.n_iter_ = 1
            rss = self._fill_summary(model, X, y, w)
            if p.reg_param == 0.0:
                # inference stats on the unregularized normal solve (MLlib
                # raises on any regularization): sigma^2 = RSS/(n - rank),
                # coef covariance from inv(A) on the centered moments, the
                # intercept variance folding the mean back in
                from orange3_spark_tpu.ops.stats import two_sided_t_pvalue

                rank = d + (1 if p.fit_intercept else 0)
                df = jnp.maximum(tot - rank, 1.0)
                sigma2 = rss / df
                inv_A = jax.scipy.linalg.solve(
                    A + 1e-8 * jnp.eye(d, dtype=A.dtype),
                    jnp.eye(d, dtype=A.dtype), assume_a="pos")
                se_coef = jnp.sqrt(jnp.diag(inv_A) * sigma2)
                if p.fit_intercept:
                    se_int = jnp.sqrt(sigma2 * (1.0 / tot
                                                + mean_x @ inv_A @ mean_x))
                    se = jnp.concatenate([se_coef, se_int[None]])
                    beta = jnp.concatenate([coef, intercept[None]])
                else:
                    se, beta = se_coef, coef
                tval = beta / jnp.maximum(se, 1e-30)
                model.coefficient_standard_errors_ = se
                model.t_values_ = tval
                model.p_values_ = two_sided_t_pvalue(tval, df)
            return model
        alpha = p.elastic_net_param
        result = fit_linear(
            X, y, w,
            jnp.float32(p.reg_param * (1.0 - alpha)),
            jnp.float32(p.tol), jnp.int32(p.max_iter),
            None,
            jnp.float32(p.reg_param * alpha) if p.reg_param * alpha > 0.0 else None,
            loss_kind="squared", k=1, fit_intercept=p.fit_intercept,
            compute_dtype=jnp.dtype(p.compute_dtype),
        )
        model = LinearRegressionModel(p, result.coef[:, 0], result.intercept[0])
        model.n_iter_ = concrete_or_none(result.n_iter, int)
        self._fill_summary(model, X, y, w)
        return model

    @staticmethod
    def _fill_summary(model, X, y, w):
        """One summary pass; returns rss so the inference block need not
        repeat the full-data reduction."""
        rss, r2, rmse, mae, expl = _training_summary(
            X, y, w, model.coef, model.intercept)
        model.r2_ = r2
        model.root_mean_squared_error_ = rmse
        model.mean_absolute_error_ = mae
        model.explained_variance_ = expl
        return rss
