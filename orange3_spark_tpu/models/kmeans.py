"""KMeans — parity with ``pyspark.ml.clustering.KMeans``.

MLlib runs Lloyd's algorithm with k-means|| initialization, one
treeAggregate per iteration to sum per-cluster centroids (SURVEY.md §2b row
"KMeans"; reconstructed, mount empty). TPU-native redesign:

* assignment = argmin of pairwise squared distances computed with the matmul
  identity  |x-c|² = |x|² - 2x·c + |c|²  — the 2x·c term is an [N,d]@[d,k]
  MXU matmul, not a broadcast subtract (HBM-bandwidth friendly);
* center update = one-hot(assign)ᵀ @ X — another MXU matmul whose row-axis
  contraction GSPMD all-reduces over ICI (the treeAggregate moment);
* the whole Lloyd loop is a single jitted ``lax.while_loop`` with the MLlib
  convergence test (all center moves < tol).

Init: 'random' samples k distinct live rows; 'k-means||' is served by
kmeans++ on a host-side sample (≤ init_sample_size rows) — same quality goal
(spread seeds) without a multi-round distributed sampling pass.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.exec.donate import donating_jit
from orange3_spark_tpu.models.base import concrete_or_none, Estimator, Model, Params


@dataclasses.dataclass(frozen=True)
class KMeansParams(Params):
    k: int = 2                    # MLlib k
    max_iter: int = 20            # MLlib maxIter
    tol: float = 1e-4             # MLlib tol (center movement)
    init_mode: str = "k-means||"  # MLlib initMode: 'random' | 'k-means||'
    seed: int = 0                 # MLlib seed
    n_init: int = 1               # restarts, best-cost wins (vmapped — beyond
                                  # MLlib, which is single-init; ~free on TPU)
    init_sample_size: int = 8192  # host sample for the ++-style init
    compute_dtype: str = "float32"


def live_cluster_sizes(W, assign, num_segments: int):
    """MLlib ``summary.clusterSizes``: live ROW counts per cluster (Spark
    counts rows, not weights — W only gates padding/filtered membership).
    THE one implementation, shared by KMeans / BisectingKMeans / GMM."""
    return jax.ops.segment_sum(
        (W > 0).astype(jnp.float32), assign.astype(jnp.int32),
        num_segments=num_segments)


@partial(jax.jit, static_argnames=("compute_dtype",))
def _assign(X, centers, w, compute_dtype=jnp.float32):
    """Nearest-center ids + weighted cost. Distances via the matmul identity."""
    Xc = X.astype(compute_dtype)
    Cc = centers.astype(compute_dtype)
    cross = jnp.dot(Xc, Cc.T, preferred_element_type=jnp.float32)  # [N,k] on MXU
    x2 = jnp.sum(X * X, axis=1, keepdims=True)
    c2 = jnp.sum(centers * centers, axis=1)
    d2 = x2 - 2.0 * cross + c2
    assign = jnp.argmin(d2, axis=1)
    cost = jnp.sum(jnp.min(d2, axis=1) * w)
    return assign, cost


@donating_jit(static_argnames=("k", "max_iter", "compute_dtype"),
              donate_argnums=(2,))
def _lloyd(X, w, centers0, tol, *, k: int, max_iter: int, compute_dtype=jnp.float32):
    """Fused Lloyd loop. ``centers0`` is DONATED — every caller builds the
    seed centers fresh (host kmeans++ / device D² sampling), and the loop
    round-trips a same-shaped centers array, so XLA reuses the buffer. The
    vmapped restart path calls ``_lloyd.plain`` (donation under vmap
    tracing is a no-op)."""
    def body(carry):
        centers, _, it, _ = carry
        assign, cost = _assign(X, centers, w, compute_dtype)
        onehot = jax.nn.one_hot(assign, k, dtype=jnp.float32) * w[:, None]  # [N,k]
        sums = onehot.T @ X          # [k,d] MXU matmul, all-reduced by GSPMD
        counts = jnp.sum(onehot, axis=0)
        new_centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1e-12)[:, None], centers
        )
        move = jnp.sqrt(jnp.sum((new_centers - centers) ** 2, axis=1))
        converged = jnp.all(move < tol)
        return new_centers, cost, it + 1, converged

    def keep_going(carry):
        _, _, it, converged = carry
        return (it < max_iter) & ~converged

    centers, cost, n_iter, _ = jax.lax.while_loop(
        keep_going, body, (centers0, jnp.float32(jnp.inf), 0, False)
    )
    # final stats at the converged centers
    assign, cost = _assign(X, centers, w, compute_dtype)
    return centers, assign, cost, n_iter


def kmeanspp_seed(sample: np.ndarray, k: int, rng) -> np.ndarray:
    """kmeans++ seeding on a host-side sample -> f32[k, d] centers.

    Distances/probabilities run in float64 (float32 D² vectors can fail
    numpy's choice() sum-to-1 tolerance on large samples) and the result is
    jitter-padded when the sample has fewer than k distinct points (exact
    duplicate centers would never win an argmin tie and stay empty forever).
    Shared by KMeans._init_centers and io.streaming.StreamingKMeans.
    """
    sample = np.asarray(sample, dtype=np.float64)
    m = len(sample)
    centers = [sample[rng.integers(m)]]
    d2 = np.sum((sample - centers[0]) ** 2, axis=1)
    for _ in range(1, min(k, m)):
        s = d2.sum()
        if s > 0:
            p = d2 / s
            p = p / p.sum()  # exact renormalization for choice()
            centers.append(sample[rng.choice(m, p=p)])
        else:  # all remaining points identical to a seed: pick uniformly
            centers.append(sample[rng.integers(m)])
        d2 = np.minimum(d2, np.sum((sample - centers[-1]) ** 2, axis=1))
    out = np.stack(centers)
    if out.shape[0] < k:  # fewer rows than k: pad with PER-ROW random jitter
        # (a shared constant offset would make the pads exact duplicates of
        # each other — precisely the dead-center failure this guards against)
        extra = out[rng.integers(out.shape[0], size=k - out.shape[0])]
        # jitter scaled to the value's magnitude: an absolute 1e-3 rounds
        # away in float32 when |center| ~ 1e5+ and the pads collapse back
        # into exact duplicates
        jitter = rng.normal(size=extra.shape) * 1e-3 * (1.0 + np.abs(extra))
        out = np.concatenate([out, extra + jitter], axis=0)
    return out.astype(np.float32)


class KMeansModel(Model):
    def __init__(self, params, centers):
        self.params = params
        self.centers = centers  # f32[k, d]
        self.n_iter_: int | None = None
        self.training_cost_: float | None = None  # MLlib summary.trainingCost

    @property
    def state_pytree(self):
        return {"centers": self.centers}

    @property
    def cluster_centers_(self) -> np.ndarray:
        return np.asarray(self.centers)

    def predict(self, table: TpuTable) -> np.ndarray:
        assign, _ = _assign(table.X, self.centers, table.W)
        return np.asarray(assign)[: table.n_rows]

    def _device_predict(self, table: TpuTable):
        """Serving hook (serve/context.py): per-row cluster ids, device-pure
        — assignment is row-wise (argmin over centers), so bucket padding
        cannot perturb live rows."""
        assign, _ = _assign(table.X, self.centers, table.W)
        return assign

    def compute_cost(self, table: TpuTable) -> float:
        _, cost = _assign(table.X, self.centers, table.W)
        return float(cost)

    def transform(self, table: TpuTable) -> TpuTable:
        """Append the 'cluster' prediction column (Spark's predictionCol)."""
        assign, _ = _assign(table.X, self.centers, table.W)
        k = self.centers.shape[0]
        new_attrs = list(table.domain.attributes) + [
            DiscreteVariable("cluster", tuple(str(i) for i in range(k)))
        ]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, assign[:, None].astype(jnp.float32)], axis=1)
        return table.with_X(X, new_domain)


def device_sample_live(X, W, cap: int, key):
    """Tracer-safe uniform subsample of up to ``cap`` LIVE rows (gumbel-max
    top-k over the live mask): the device twin of the eager inits'
    host-side 8192-row sampling. Seeding on the sample instead of the full
    data turns the D² init's k distance passes from k x N rows into
    k x cap rows — at 10M rows that was the dominant cost of a staged
    REFIT (round-4 measurement: the fused fit program spent more time
    seeding than Lloyd's took to converge). Returns (Xs [cap, d],
    Ws [cap]) where dead/past-live picks carry Ws=0."""
    N = X.shape[0]
    live = W > 0
    g = jnp.where(live, jax.random.gumbel(key, (N,)), -jnp.inf)
    gv, idx = jax.lax.top_k(g, min(cap, N))
    return X[idx], jnp.isfinite(gv).astype(jnp.float32)


def device_d2_seed(X, W, k: int, k0, k1) -> jnp.ndarray:
    """Device-pure categorical D²-sampling (kmeans++) seeding — tracer-safe,
    shared by KMeans (k-means|| init) and GaussianMixture (means init)
    under staged refit, where the host-sample init cannot run."""
    N, d = X.shape
    live = W > 0
    # first center: uniform over live rows via gumbel-max
    g = jax.random.gumbel(k0, (N,))
    i0 = jnp.argmax(jnp.where(live, g, -jnp.inf))
    centers = jnp.zeros((k, d), X.dtype).at[0].set(X[i0])
    d2 = jnp.where(live, jnp.sum((X - X[i0]) ** 2, axis=1), 0.0)

    def body(c, carry):
        centers, d2, key = carry
        key, kc, ku = jax.random.split(key, 3)
        mask = live & (d2 > 0)
        logits = jnp.where(mask, jnp.log(jnp.maximum(d2, 1e-30)), -jnp.inf)
        cat = jax.random.categorical(kc, logits)
        # all remaining live points coincide with a seed: uniform pick
        gu = jax.random.gumbel(ku, (N,))
        uni = jnp.argmax(jnp.where(live, gu, -jnp.inf))
        idx = jnp.where(jnp.any(mask), cat, uni)
        # duplicate centers get per-coordinate jitter scaled to
        # magnitude (same dead-center guard as kmeanspp_seed)
        newc = X[idx] + jnp.where(
            jnp.any(mask), 0.0,
            1e-3 * (1.0 + jnp.abs(X[idx]))
            * jax.random.normal(ku, (d,), X.dtype),
        )
        centers = centers.at[c].set(newc)
        d2 = jnp.minimum(d2, jnp.sum((X - newc) ** 2, axis=1))
        d2 = jnp.where(live, d2, 0.0)
        return centers, d2, key

    centers, _, _ = jax.lax.fori_loop(1, k, body, (centers, d2, k1))
    return centers


class KMeans(Estimator):
    ParamsCls = KMeansParams
    params: KMeansParams

    def _device_init_centers(self, X, W) -> jnp.ndarray:
        """Device-pure center init — used when the fit itself is being
        TRACED (staged refit, workflow/staging.py): the host-sample init
        below cannot run on tracers. Also the right shape for this
        hardware — the eager init ships a sample device→host, the slowest
        link on the tunneled bench host. Honors ``init_mode``: 'random' is
        a gumbel-max uniform draw of k live rows; 'k-means||' is
        categorical D²-sampling (kmeans++) in a fori_loop. Seeded and
        deterministic, but a different random stream than the host init
        (documented)."""
        p = self.params
        key = jax.random.PRNGKey(p.seed)
        k0, k1 = jax.random.split(key)
        if p.init_mode == "random":
            # k distinct uniform live rows (device_sample_live's gumbel-max
            # top-k). Picks past the live count would land on DEAD rows —
            # the exact stranded-center failure the eager path guards
            # against — so they are replaced by jittered duplicates of the
            # first (live) pick, mirroring the eager live-center padding.
            centers, ws = device_sample_live(X, W, p.k, k0)
            dead = ws == 0
            base = centers[0]                     # live whenever any row is
            jit_ = (1e-3 * (1.0 + jnp.abs(base))
                    * jax.random.normal(k1, centers.shape, X.dtype))
            return jnp.where(dead[:, None], base[None, :] + jit_, centers)
        if p.init_mode != "k-means||":
            raise ValueError(f"unknown init_mode {p.init_mode!r}")
        # seed on a uniform live subsample (the eager path's
        # init_sample_size-row sampling, on device): D² passes then cost
        # k x sample rows, not k x N — the difference between a staged
        # refit that beats the eager walk and one that loses to it at
        # 10M rows
        ks, k0b = jax.random.split(k0)
        Xs, Ws = device_sample_live(X, W, p.init_sample_size, ks)
        return device_d2_seed(Xs, Ws, p.k, k0b, k1)

    def _init_centers(self, table: TpuTable) -> jnp.ndarray:
        p = self.params
        if isinstance(table.X, jax.core.Tracer):
            return self._device_init_centers(table.X, table.W)
        rng = np.random.default_rng(p.seed)
        # sample only live rows — filtered (w=0) rows must not seed centers,
        # or a center stranded on a dead outlier never receives points and
        # Lloyd's keeps it forever
        live = np.flatnonzero(np.asarray(jax.device_get(table.W)) > 0)
        n = len(live)
        if n == 0:
            raise ValueError("cannot fit KMeans: table has no live rows")
        if p.init_mode == "random":
            idx = live[rng.choice(n, size=min(p.k, n), replace=False)]
            centers = np.asarray(jax.device_get(table.X[np.sort(idx)]))
        elif p.init_mode == "k-means||":
            # kmeans++ on a host sample: same seed-spreading intent as
            # MLlib's distributed k-means|| oversampling rounds.
            m = min(n, p.init_sample_size)
            idx = live[rng.choice(n, size=m, replace=False)] if m < n else live
            # gather the sample ON DEVICE, then pull only those m rows host-ward
            # (never device_get the full [N,d] table)
            sample = np.asarray(jax.device_get(table.X[np.sort(idx)]))
            centers = kmeanspp_seed(sample, p.k, rng)
        else:
            raise ValueError(f"unknown init_mode {p.init_mode!r}")
        if centers.shape[0] < p.k:  # fewer rows than k: pad with jitter
            extra = centers[rng.integers(centers.shape[0], size=p.k - centers.shape[0])]
            centers = np.concatenate([centers, extra + 1e-3], axis=0)
        return jax.device_put(centers.astype(np.float32), table.session.replicated)

    def _fit(self, table: TpuTable) -> KMeansModel:
        p = self.params
        lloyd_kw = dict(k=p.k, max_iter=p.max_iter,
                        compute_dtype=jnp.dtype(p.compute_dtype))
        tol = jnp.float32(p.tol)
        if p.n_init <= 1:
            centers, assign, cost, n_iter = _lloyd(
                table.X, table.W, self._init_centers(table), tol, **lloyd_kw)
        else:
            # all restarts advance in lockstep inside one vmapped while_loop —
            # n_init independent Lloyd runs for roughly the cost of one.
            # Donation under a vmap trace is a silent no-op, so call the
            # undonated twin rather than compile a donating executable
            # whose aliasing can never engage.
            inits = jnp.stack([
                self.replace_seed(s)._init_centers(table)
                for s in range(p.seed, p.seed + p.n_init)
            ])
            centers_v, assign_v, cost_v, iter_v = jax.vmap(
                lambda c0: _lloyd.plain(table.X, table.W, c0, tol, **lloyd_kw)
            )(inits)
            best = jnp.argmin(cost_v)
            centers, cost, n_iter = centers_v[best], cost_v[best], iter_v[best]
            assign = assign_v[best]
        model = KMeansModel(p, centers)
        model.n_iter_ = concrete_or_none(n_iter, int)
        model.training_cost_ = concrete_or_none(cost)
        # reuses the converged Lloyd assignment — no extra distance pass
        model.cluster_sizes_ = live_cluster_sizes(table.W, assign, p.k)
        return model

    def replace_seed(self, seed: int) -> "KMeans":
        return KMeans(self.params.replace(seed=seed))
