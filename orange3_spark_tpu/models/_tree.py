"""Histogram-based decision-tree induction as fixed-shape XLA.

MLlib grows RandomForest/GBT trees with distributed binned histograms: rows
carry a node id, each iteration aggregates per-(node, feature, bin) label
statistics across executors, the driver picks best splits, repeat per level
(SURVEY.md §2b row "RandomForest / GBTClassifier"; reconstructed, mount
empty). That design is ALREADY the TPU-shaped one — everything here keeps it
but removes the driver round-trip:

* features are quantile-binned once to int32 bins (max_bins ≤ 256);
* the tree is a PERFECT binary tree of static depth D — no data-dependent
  shapes, dead nodes just stop splitting (split_bin = n_bins routes all rows
  left), so the whole growth loop jits;
* per-level histograms are ``segment_sum``s keyed by (node, bin), scanned
  over features so the transient is [N] not [N·d]; the row-axis reduction is
  GSPMD's ICI all-reduce (MLlib's executor→driver aggregate);
* split selection = cumsum over bins + argmax over (feature, bin) — all on
  device, no host in the loop;
* the whole per-tree growth is vmappable: a forest fits as ONE program over a
  tree axis (bootstrap weights + per-node feature masks differ by RNG key).

Gain modes: 'gini' (classification, stats = per-class weighted counts),
'variance' (MLlib regression/GBT residual splits, stats = [Σwy, Σwy², Σw]),
'newton' (XGBoost-style grad/hess, stats = [G, H, Σw]) — GBT here uses
newton gains + leaf values, a strict upgrade over MLlib's variance splits.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from orange3_spark_tpu.ops.histogram import node_histograms
from orange3_spark_tpu.ops.stats import weighted_quantiles

EPS = 1e-12


class Tree(NamedTuple):
    """Perfect binary tree of depth D over binned features.

    feature:    i32[2^D - 1]   split feature per internal node (level order)
    split_bin:  i32[2^D - 1]   go left iff bin <= split_bin (n_bins => leaf)
    threshold:  f32[2^D - 1]   raw-value threshold (edges[feature, split_bin])
    leaf_value: f32[2^D, s_out] value at each depth-D leaf
    """

    feature: jax.Array
    split_bin: jax.Array
    threshold: jax.Array
    leaf_value: jax.Array

    @property
    def depth(self) -> int:
        # leaf axis is second-to-last so this stays correct on stacked
        # forests ([T, L, s]) as well as single trees ([L, s])
        return self.leaf_value.shape[-2].bit_length() - 1


def compute_bin_edges(X, W, max_bins: int):
    """Weighted-quantile bin boundaries: f32[d, max_bins - 1]."""
    qs = jnp.linspace(0.0, 1.0, max_bins + 1)[1:-1]
    return weighted_quantiles(X, W, qs).T  # [d, max_bins-1]


@jax.jit
def bin_features(X, edges):
    """int32 bins: B[n, f] = #edges strictly below X[n, f]  (0..max_bins-1)."""
    return jnp.sum(X[:, :, None] > edges[None, :, :], axis=2).astype(jnp.int32)


def _impurity_gain(Hc, gain_mode: str, reg: float, min_instances: float):
    """Split gains from cumulative histograms.

    Hc: f32[d, nodes, bins, s] cumulative-over-bins stats; candidate 'split at
    bin b' sends bins <= b left. Returns gains f32[d, nodes, bins] with
    invalid candidates at -inf.
    """
    total = Hc[:, :, -1:, :]
    left, right = Hc, total - Hc

    if gain_mode == "gini":
        def gini_w(S):  # S [..., k] class counts -> weighted gini * count
            c = jnp.sum(S, axis=-1)
            p2 = jnp.sum(S * S, axis=-1) / jnp.maximum(c, EPS)
            return c - p2  # = c * (1 - sum p_i^2)
        gain = gini_w(total)[..., 0][:, :, None] - gini_w(left) - gini_w(right)
        wl = jnp.sum(left, -1)
        wr = jnp.sum(right, -1)
    elif gain_mode == "variance":
        def var_w(S):  # [Σwy, Σwy², Σw] -> weighted variance * count
            s1, s2, c = S[..., 0], S[..., 1], S[..., 2]
            return s2 - s1 * s1 / jnp.maximum(c, EPS)
        gain = var_w(total)[..., 0][:, :, None] - var_w(left) - var_w(right)
        wl, wr = left[..., 2], right[..., 2]
    elif gain_mode == "newton":
        def score(S):  # [G, H, Σw] -> -loss reduction potential
            return S[..., 0] ** 2 / jnp.maximum(S[..., 1] + reg, EPS)
        gain = 0.5 * (score(left) + score(right) - score(total)[..., 0][:, :, None])
        wl, wr = left[..., 2], right[..., 2]
    else:  # pragma: no cover
        raise ValueError(gain_mode)

    valid = (wl >= min_instances) & (wr >= min_instances)
    # node weight for MLlib-style NORMALIZED min-gain thresholds: minInfoGain
    # compares the per-weight impurity decrease, not the count-scaled sum
    if gain_mode == "gini":
        node_w = jnp.sum(total, axis=-1)[0, :, 0]  # [nodes]
    else:
        node_w = total[0, :, 0, 2]                 # [nodes]
    return jnp.where(valid, gain, -jnp.inf), node_w


@partial(
    jax.jit,
    static_argnames=("depth", "n_bins", "gain_mode", "min_instances"),
)
def grow_tree(
    B,            # i32[N, d] binned features
    S,            # f32[N, s] per-row stats (class one-hots * w, or [g, h, w])
    edges,        # f32[d, n_bins - 1] raw bin boundaries
    feat_keep,    # f32[depth_levels_max, d] per-level feature masks (1 keep)
                  # pass ones for no subsetting; [2^l-wide masks broadcast]
    min_gain,     # f32[] minimum gain to split (MLlib minInfoGain)
    *,
    depth: int,
    n_bins: int,
    gain_mode: str,
    reg: float = 1.0,
    min_instances: float = 1.0,
):
    """Grow one depth-D tree; vmap over (B-bootstrap stats, keys) for forests."""
    N, d = B.shape
    s = S.shape[1]
    n_internal = 2**depth - 1
    feature = jnp.zeros((n_internal,), jnp.int32)
    split_bin = jnp.full((n_internal,), n_bins, jnp.int32)  # default: leaf
    threshold = jnp.full((n_internal,), jnp.inf, jnp.float32)
    pos = jnp.zeros((N,), jnp.int32)  # node position within current level
    # per-feature importance: Σ over chosen splits of the (weight-scaled)
    # impurity decrease — MLlib's featureImportances accumulator (its
    # per-node gain × node count equals this absolute gain)
    imp = jnp.zeros((d,), jnp.float32)

    for level in range(depth):
        nodes = 2**level
        # ---- histograms: Pallas MXU kernel on TPU, segment_sum elsewhere
        # (ops/histogram.py — the findBestSplits treeAggregate equivalent)
        H = node_histograms(B, S, pos, nodes=nodes, n_bins=n_bins)
        H = H.reshape(d, nodes, n_bins, s)
        Hc = jnp.cumsum(H, axis=2)
        gains, node_w = _impurity_gain(Hc, gain_mode, reg, min_instances)
        gains = jnp.where(feat_keep[level][:, None, None] > 0, gains, -jnp.inf)
        flat = gains.transpose(1, 0, 2).reshape(nodes, d * n_bins)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
        bf = (best // n_bins).astype(jnp.int32)              # [nodes]
        bb = (best % n_bins).astype(jnp.int32)
        # MLlib minInfoGain semantics: threshold the PER-WEIGHT gain
        do_split = best_gain > min_gain * jnp.maximum(node_w, EPS)
        bf = jnp.where(do_split, bf, 0)
        bb = jnp.where(do_split, bb, n_bins)                 # leaf: all go left
        # raw threshold (last bin index means +inf)
        thr = jnp.where(
            bb < n_bins - 1,
            edges[bf, jnp.clip(bb, 0, n_bins - 2)],
            jnp.inf,
        )
        thr = jnp.where(do_split, thr, jnp.inf)
        imp = imp.at[bf].add(jnp.where(do_split, best_gain, 0.0))
        off = nodes - 1  # level-order offset of this level
        feature = jax.lax.dynamic_update_slice(feature, bf, (off,))
        split_bin = jax.lax.dynamic_update_slice(split_bin, bb, (off,))
        threshold = jax.lax.dynamic_update_slice(threshold, thr, (off,))
        # ---- route rows ----
        go_right = B[jnp.arange(N), bf[pos]] > bb[pos]
        pos = 2 * pos + go_right.astype(jnp.int32)

    leaf_stats = jax.ops.segment_sum(S, pos, num_segments=2**depth)
    return Tree(feature, split_bin, threshold, leaf_value=leaf_stats), pos, imp


def normalize_importances(imp):
    """MLlib featureImportances normalization: scale to sum 1 (all-zero —
    no split anywhere — stays zero). Works on [d] or stacked [T, d]."""
    s = jnp.sum(imp, axis=-1, keepdims=True)
    return jnp.where(s > 0, imp / jnp.maximum(s, EPS), 0.0)


@jax.jit
def tree_apply(X, tree: Tree):
    """Leaf index per row on RAW features (serving path, no binning needed)."""
    N = X.shape[0]
    depth = tree.leaf_value.shape[0].bit_length() - 1
    node = jnp.zeros((N,), jnp.int32)  # global level-order node id
    for _ in range(depth):
        f = tree.feature[node]
        thr = tree.threshold[node]
        go_right = X[jnp.arange(N), f] > thr
        node = 2 * node + 1 + go_right.astype(jnp.int32)
    return node - (tree.leaf_value.shape[0] - 1)  # leaf index in [0, 2^D)


def leaf_class_probs(leaf_stats):
    """Per-leaf class distribution from one-hot count stats."""
    tot = jnp.sum(leaf_stats, axis=-1, keepdims=True)
    k = leaf_stats.shape[-1]
    return jnp.where(tot > 0, leaf_stats / jnp.maximum(tot, EPS), 1.0 / k)


def leaf_newton_values(leaf_stats, reg: float):
    """-G/(H + reg) per leaf from [G, H, w] stats."""
    G, H = leaf_stats[..., 0], leaf_stats[..., 1]
    return -G / jnp.maximum(H + reg, EPS)
