"""MultilayerPerceptronClassifier — parity with
``pyspark.ml.classification.MultilayerPerceptronClassifier``.

MLlib trains a feed-forward net (sigmoid hidden layers, softmax output —
fixed topology, no activation choice) with L-BFGS by default, one
treeAggregate of (loss, grad) per iteration (SURVEY.md §2b; reconstructed,
mount empty — public API: layers=[in, h..., out], maxIter=100, tol=1e-6,
blockSize=128, seed, solver 'l-bfgs'|'gd', stepSize). TPU-native redesign:

* forward pass = a chain of [N,h]@[h,h'] MXU matmuls over the sharded batch;
  MLlib's blockSize row-batching exists to amortize JVM BLAS dispatch — on
  TPU the whole sharded batch is one fused XLA computation, so blockSize is
  accepted for parity and ignored;
* the full L-BFGS loop (optax.lbfgs + zoom linesearch) is one jitted
  ``lax.while_loop``; the loss's row contraction GSPMD all-reduces over ICI;
* glorot-uniform init per layer from a single folded PRNG key.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from orange3_spark_tpu.models._linear import lbfgs_minimize
from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import concrete_or_none, Estimator, Model, Params, infer_class_values


@dataclasses.dataclass(frozen=True)
class MLPParams(Params):
    layers: tuple = ()        # MLlib layers: (in, hidden..., out); () => infer (in, out)
    max_iter: int = 100       # MLlib maxIter
    tol: float = 1e-6         # MLlib tol
    seed: int = 0             # MLlib seed
    solver: str = "l-bfgs"    # MLlib solver: 'l-bfgs' | 'gd'
    step_size: float = 0.03   # MLlib stepSize (gd only)
    block_size: int = 128     # parity; whole sharded batch is one XLA program


def _init_net(layers, seed):
    key = jax.random.PRNGKey(seed)
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(layers[:-1], layers[1:])):
        key, k1 = jax.random.split(key)
        limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
        W = jax.random.uniform(k1, (fan_in, fan_out), jnp.float32, -limit, limit)
        params.append({"W": W, "b": jnp.zeros((fan_out,), jnp.float32)})
    return params


def _forward(net, X):
    """Sigmoid hidden layers, linear output (softmax applied in the loss)."""
    h = X
    for layer in net[:-1]:
        h = jax.nn.sigmoid(h @ layer["W"] + layer["b"])
    return h @ net[-1]["W"] + net[-1]["b"]


@partial(jax.jit, static_argnames=("layers", "solver", "max_iter"))
def _fit_mlp(X, y, w, tol, step_size, *, layers: tuple, solver: str,
             max_iter: int, seed: int = 0):
    sum_w = jnp.maximum(jnp.sum(w), 1e-12)
    net0 = _init_net(layers, seed)

    def loss_fn(net):
        logits = _forward(net, X)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, y.astype(jnp.int32)[:, None], axis=1)[:, 0]
        return jnp.sum(nll * w) / sum_w

    if solver == "l-bfgs":
        net, n_iter, _ = lbfgs_minimize(loss_fn, net0, tol, max_iter)
    elif solver == "gd":
        opt = optax.sgd(step_size)

        def body(_, carry):
            net, state = carry
            updates, state = opt.update(jax.grad(loss_fn)(net), state, net)
            return optax.apply_updates(net, updates), state

        net, _ = jax.lax.fori_loop(0, max_iter, body, (net0, opt.init(net0)))
        n_iter = jnp.int32(max_iter)
    else:
        raise ValueError(f"unknown solver {solver!r}")
    return net, n_iter, loss_fn(net)


class MultilayerPerceptronClassifierModel(Model):
    def __init__(self, params, net, class_values):
        self.params = params
        self.net = net
        self.class_values = class_values

    @property
    def state_pytree(self):
        return {"net": self.net}

    def _logits(self, table: TpuTable):
        return _forward(self.net, table.X)

    def predict(self, table: TpuTable) -> np.ndarray:
        return np.asarray(jnp.argmax(self._logits(table), axis=1))[: table.n_rows]

    def predict_probability(self, table: TpuTable) -> np.ndarray:
        return np.asarray(jax.nn.softmax(self._logits(table), axis=1))[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        logits = self._logits(table)
        probs = jax.nn.softmax(logits, axis=1)
        pred = jnp.argmax(logits, axis=1).astype(jnp.float32)
        k = len(self.class_values)
        new_attrs = (
            list(table.domain.attributes)
            + [ContinuousVariable(f"probability_{i}") for i in range(k)]
            + [DiscreteVariable("prediction", tuple(self.class_values))]
        )
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, probs, pred[:, None]], axis=1)
        return table.with_X(X, new_domain)


class MultilayerPerceptronClassifier(Estimator):
    ParamsCls = MLPParams
    params: MLPParams

    def _fit(self, table: TpuTable) -> MultilayerPerceptronClassifierModel:
        p = self.params
        class_values = infer_class_values(table)
        k = len(class_values)
        d = table.X.shape[1]
        layers = tuple(int(x) for x in p.layers) or (d, k)
        if layers[0] != d:
            raise ValueError(f"layers[0]={layers[0]} must equal n_features={d}")
        if layers[-1] != k:
            raise ValueError(f"layers[-1]={layers[-1]} must equal n_classes={k}")
        net, n_iter, loss = _fit_mlp(
            table.X, table.y, table.W, jnp.float32(p.tol),
            jnp.float32(p.step_size),
            layers=layers, solver=p.solver, max_iter=p.max_iter, seed=p.seed,
        )
        model = MultilayerPerceptronClassifierModel(p, net, class_values)
        model.n_iter_ = concrete_or_none(n_iter, int)
        model.final_loss_ = concrete_or_none(loss)
        return model
