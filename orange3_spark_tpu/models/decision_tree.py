"""DecisionTree — parity with ``pyspark.ml.classification.DecisionTreeClassifier``
and ``pyspark.ml.regression.DecisionTreeRegressor``.

MLlib's single tree is the degenerate forest (numTrees=1, no bootstrap, all
features at every node); it shares the distributed binned-histogram grower
(SURVEY.md §2b row "RandomForest / GBTClassifier" — reconstructed, mount
empty). Same here: one call into the fixed-shape ``grow_tree`` program of
``_tree.py`` with unit weights and a full feature mask — the whole induction
is a single jitted XLA computation whose per-level ``segment_sum`` histograms
all-reduce over ICI via GSPMD.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models._tree import (
    Tree,
    bin_features,
    compute_bin_edges,
    grow_tree,
    leaf_class_probs,
    normalize_importances,
    tree_apply,
)
from orange3_spark_tpu.models.base import Estimator, Model, Params, infer_class_values


@dataclasses.dataclass(frozen=True)
class DecisionTreeParams(Params):
    max_depth: int = 5                   # MLlib maxDepth
    max_bins: int = 32                   # MLlib maxBins
    min_instances_per_node: float = 1.0  # MLlib minInstancesPerNode
    min_info_gain: float = 0.0           # MLlib minInfoGain
    impurity: str = "auto"               # 'gini' (clf) / 'variance' (reg)
    seed: int = 0


def _grow_single(table: TpuTable, Ystats, p: DecisionTreeParams, gain_mode: str):
    edges = compute_bin_edges(table.X, table.W, p.max_bins)
    B = bin_features(table.X, edges)
    keep = jnp.ones((p.max_depth, table.n_attrs), jnp.float32)
    tree, _, imp = grow_tree(
        B, Ystats * table.W[:, None], edges, keep,
        jnp.float32(p.min_info_gain),
        depth=p.max_depth, n_bins=p.max_bins, gain_mode=gain_mode,
        min_instances=p.min_instances_per_node,
    )
    return tree, normalize_importances(imp)


class DecisionTreeClassifierModel(Model):
    def __init__(self, params, tree: Tree, class_values):
        self.params = params
        self.tree = tree
        self.class_values = tuple(class_values)

    @property
    def state_pytree(self):
        return dict(self.tree._asdict())

    def load_state_pytree(self, state):
        self.tree = Tree(**{k: state[k] for k in Tree._fields})
        self._touch_serving_state()

    def _probs(self, X):
        leaves = tree_apply(X, self.tree)                    # [N]
        probs = leaf_class_probs(self.tree.leaf_value)       # [L, k]
        return probs[leaves]

    def predict_proba(self, table: TpuTable) -> np.ndarray:
        return np.asarray(self._probs(table.X))[: table.n_rows]

    def predict(self, table: TpuTable) -> np.ndarray:
        probs = self._probs(table.X)
        return np.asarray(jnp.argmax(probs, 1).astype(jnp.float32))[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        probs = self._probs(table.X)
        pred = jnp.argmax(probs, axis=1).astype(jnp.float32)
        new_attrs = list(table.domain.attributes) + [
            ContinuousVariable(f"probability_{c}") for c in self.class_values
        ] + [DiscreteVariable("prediction", self.class_values)]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, probs, pred[:, None]], axis=1)
        return table.with_X(X, new_domain)


class DecisionTreeClassifier(Estimator):
    ParamsCls = DecisionTreeParams
    params: DecisionTreeParams

    def _fit(self, table: TpuTable) -> DecisionTreeClassifierModel:
        p = self.params
        if p.impurity not in ("auto", "gini"):
            raise ValueError(f"classifier impurity must be 'gini', got {p.impurity!r}")
        y = table.y
        class_values = infer_class_values(table)
        k = len(class_values)
        Ystats = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=jnp.float32)
        tree, imp = _grow_single(table, Ystats, p, "gini")
        model = DecisionTreeClassifierModel(p, tree, class_values)
        model.feature_importances_ = imp   # MLlib featureImportances
        return model


class DecisionTreeRegressorModel(Model):
    def __init__(self, params, tree: Tree):
        self.params = params
        self.tree = tree

    @property
    def state_pytree(self):
        return dict(self.tree._asdict())

    def load_state_pytree(self, state):
        self.tree = Tree(**{k: state[k] for k in Tree._fields})
        self._touch_serving_state()

    def predict(self, table: TpuTable) -> np.ndarray:
        leaves = tree_apply(table.X, self.tree)
        s1 = self.tree.leaf_value[:, 0]
        c = jnp.maximum(self.tree.leaf_value[:, 2], 1e-12)
        return np.asarray((s1 / c)[leaves])[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        leaves = tree_apply(table.X, self.tree)
        s1 = self.tree.leaf_value[:, 0]
        c = jnp.maximum(self.tree.leaf_value[:, 2], 1e-12)
        yhat = (s1 / c)[leaves]
        new_domain = Domain(
            list(table.domain.attributes) + [ContinuousVariable("prediction")],
            table.domain.class_vars, table.domain.metas,
        )
        X = jnp.concatenate([table.X, yhat[:, None]], axis=1)
        return table.with_X(X, new_domain)


class DecisionTreeRegressor(Estimator):
    ParamsCls = DecisionTreeParams
    params: DecisionTreeParams

    def _fit(self, table: TpuTable) -> DecisionTreeRegressorModel:
        p = self.params
        if p.impurity not in ("auto", "variance"):
            raise ValueError(
                f"regressor impurity must be 'variance', got {p.impurity!r}"
            )
        y = table.y
        Ystats = jnp.stack([y, y * y, jnp.ones_like(y)], axis=1)
        tree, imp = _grow_single(table, Ystats, p, "variance")
        model = DecisionTreeRegressorModel(p, tree)
        model.feature_importances_ = imp   # MLlib featureImportances
        return model
