"""NaiveBayes — parity with ``pyspark.ml.classification.NaiveBayes``.

MLlib supports modelType ∈ {multinomial, bernoulli, gaussian, complement}
and fits by one pass of per-class aggregation over the data (a treeAggregate
summing per-class feature counts; SURVEY.md §2b pattern — reconstructed,
mount empty). TPU-native redesign: every per-class aggregate is the single
matmul ``one_hot(y)ᵀ @ X`` ([k,N]@[N,d] on the MXU) whose row-axis
contraction GSPMD all-reduces over ICI — the entire fit is one fused XLA
program, and prediction is one ``X @ thetaᵀ`` matmul against the log-factor
matrix (no per-row Python, no per-class loop).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params, infer_class_values

_EPS = 1e-12


@dataclasses.dataclass(frozen=True)
class NaiveBayesParams(Params):
    smoothing: float = 1.0        # MLlib smoothing (Laplace/Lidstone)
    model_type: str = "multinomial"  # MLlib modelType:
                                  # multinomial | bernoulli | gaussian | complement
    seed: int = 0


@partial(jax.jit, static_argnames=("k",))
def _class_aggregates(X, y, w, *, k: int):
    """Per-class weighted sums via MXU matmuls: counts[k], sums[k,d], sq[k,d]."""
    onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=jnp.float32) * w[:, None]
    counts = jnp.sum(onehot, axis=0)                     # [k]  Σw per class
    sums = onehot.T @ X                                  # [k,d] Σw·x per class
    sq = onehot.T @ (X * X)                              # [k,d] Σw·x² per class
    return counts, sums, sq


def _fit_factors(counts, sums, sq, smoothing: float, model_type: str):
    """log-prior pi[k] and the per-class log factors used at predict time."""
    pi = jnp.log(jnp.maximum(counts, _EPS)) - jnp.log(
        jnp.maximum(jnp.sum(counts), _EPS)
    )
    if model_type == "multinomial":
        num = sums + smoothing
        theta = jnp.log(num) - jnp.log(jnp.sum(num, axis=1, keepdims=True))
        return pi, {"theta": theta}
    if model_type == "complement":
        # CNB (Rennie et al. 2003, as in MLlib): weight by counts of all OTHER
        # classes, negated so argmax semantics match multinomial's.
        comp = jnp.sum(sums, axis=0, keepdims=True) - sums
        num = comp + smoothing
        theta = -(jnp.log(num) - jnp.log(jnp.sum(num, axis=1, keepdims=True)))
        return pi, {"theta": theta}
    if model_type == "bernoulli":
        p1 = (sums + smoothing) / (counts[:, None] + 2.0 * smoothing)
        return pi, {"log_p1": jnp.log(p1), "log_p0": jnp.log1p(-p1)}
    if model_type == "gaussian":
        mean = sums / jnp.maximum(counts[:, None], _EPS)
        var = sq / jnp.maximum(counts[:, None], _EPS) - mean * mean
        # MLlib-style variance flooring: epsilon scaled to the largest variance
        var_floor = 1e-9 * jnp.maximum(jnp.max(var), _EPS)
        var = jnp.maximum(var, var_floor)
        return pi, {"mean": mean, "var": var}
    raise ValueError(f"unknown model_type {model_type!r}")


@partial(jax.jit, static_argnames=("model_type",))
def _log_joint(X, pi, factors, *, model_type: str):
    """Per-row per-class log joint likelihood — all matmul-shaped."""
    if model_type in ("multinomial", "complement"):
        return X @ factors["theta"].T + pi
    if model_type == "bernoulli":
        lp1, lp0 = factors["log_p1"], factors["log_p0"]
        return X @ (lp1 - lp0).T + jnp.sum(lp0, axis=1) + pi
    # gaussian: Σ_j -(x-μ)²/(2σ²) - ½log(2πσ²), expanded so the x-dependent
    # terms are two matmuls (x² @ a + x @ b) instead of an [N,k,d] broadcast
    mean, var = factors["mean"], factors["var"]
    a = -0.5 / var                                       # [k,d]
    b = mean / var                                       # [k,d]
    const = jnp.sum(-0.5 * mean * mean / var - 0.5 * jnp.log(2.0 * jnp.pi * var), 1)
    return (X * X) @ a.T + X @ b.T + const + pi


class NaiveBayesModel(Model):
    def __init__(self, params, pi, factors, class_values):
        self.params = params
        self.pi = pi                    # f32[k] log prior
        self.factors = factors          # dict of f32[k,d] log-factor arrays
        self.class_values = tuple(class_values)

    @property
    def state_pytree(self):
        return {"pi": self.pi, **self.factors}

    def load_state_pytree(self, state):
        state = dict(state)
        self.pi = state.pop("pi")
        self.factors = state
        self._touch_serving_state()

    def _scores(self, X):
        return _log_joint(X, self.pi, self.factors,
                          model_type=self.params.model_type)

    def predict(self, table: TpuTable) -> np.ndarray:
        s = self._scores(table.X)
        return np.asarray(jnp.argmax(s, 1).astype(jnp.float32))[: table.n_rows]

    def predict_proba(self, table: TpuTable) -> np.ndarray:
        s = self._scores(table.X)
        return np.asarray(jax.nn.softmax(s, axis=-1))[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        s = self._scores(table.X)
        prob = jax.nn.softmax(s, axis=-1)
        pred = jnp.argmax(s, axis=1).astype(jnp.float32)
        new_attrs = list(table.domain.attributes) + [
            ContinuousVariable(f"probability_{c}") for c in self.class_values
        ] + [DiscreteVariable("prediction", self.class_values)]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, prob, pred[:, None]], axis=1)
        return table.with_X(X, new_domain)


class NaiveBayes(Estimator):
    ParamsCls = NaiveBayesParams
    params: NaiveBayesParams

    def _fit(self, table: TpuTable) -> NaiveBayesModel:
        p = self.params
        y = table.y
        class_values = infer_class_values(table)
        k = len(class_values)
        if p.model_type in ("multinomial", "complement", "bernoulli"):
            # MLlib requires nonnegative features for these model types
            if bool(jnp.any((table.X < 0) & (table.W[:, None] > 0))):
                raise ValueError(
                    f"model_type={p.model_type!r} requires nonnegative features"
                )
        if p.model_type == "bernoulli":
            # MLlib raises on non-0/1 values for bernoulli (p1 > 1 would turn
            # log1p(-p1) into NaN and poison every posterior)
            live = table.W[:, None] > 0
            if bool(jnp.any(live & (table.X != 0.0) & (table.X != 1.0))):
                raise ValueError(
                    "model_type='bernoulli' requires 0/1 features; "
                    "binarize first (Binarizer)"
                )
        counts, sums, sq = _class_aggregates(table.X, y, table.W, k=k)
        pi, factors = _fit_factors(counts, sums, sq, p.smoothing, p.model_type)
        return NaiveBayesModel(p, pi, factors, class_values)
