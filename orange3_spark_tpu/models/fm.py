"""Factorization machines — parity with ``pyspark.ml.classification.FMClassifier``
and ``pyspark.ml.regression.FMRegressor``.

MLlib trains 2-way FMs (Rendle 2010) with minibatch gradient descent / adamW,
one treeAggregate per step (SURVEY.md §2b; reconstructed, mount empty —
public API: factorSize=8, fitIntercept, fitLinear, regParam, miniBatchFraction,
initStd=0.01, maxIter=100, stepSize=0.01, tol, solver 'adamW'|'gd', seed).
TPU-native redesign:

* the pairwise term uses Rendle's O(N·d·k) identity
  ``0.5·Σ_f [(X v_f)² − (X²)(v_f²)]`` — two [N,d]@[d,k] MXU matmuls, never
  the O(d²) interaction expansion;
* full-batch adamW steps inside one jitted ``lax.fori_loop`` (on TPU the
  full batch IS the minibatch — HBM feeds the MXU faster than a sampling
  pass would; miniBatchFraction is accepted for API parity);
* the gradient's row contraction GSPMD all-reduces over ICI.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params, infer_class_values


@dataclasses.dataclass(frozen=True)
class FMParams(Params):
    factor_size: int = 8          # MLlib factorSize
    fit_intercept: bool = True    # MLlib fitIntercept
    fit_linear: bool = True       # MLlib fitLinear
    reg_param: float = 0.0        # MLlib regParam (L2)
    init_std: float = 0.01        # MLlib initStd
    max_iter: int = 100           # MLlib maxIter
    step_size: float = 0.01       # MLlib stepSize
    tol: float = 1e-6
    solver: str = "adamW"         # MLlib solver: 'adamW' | 'gd'
    seed: int = 0
    mini_batch_fraction: float = 1.0  # parity; full batch used


def _fm_raw(theta, X):
    """FM score: w0 + X·w + 0.5 Σ_f[(Xv_f)² − X²·v_f²]  (Rendle's identity)."""
    lin = X @ theta["w"] + theta["w0"]
    xv = X @ theta["V"]                       # [N,k] MXU
    x2v2 = (X * X) @ (theta["V"] * theta["V"])  # [N,k] MXU
    return lin + 0.5 * jnp.sum(xv * xv - x2v2, axis=1)


@partial(jax.jit, static_argnames=("loss_kind", "factor_size", "fit_intercept",
                                   "fit_linear", "solver", "max_iter"))
def _fit_fm(X, y, w, reg, step_size, init_std, tol, seed, *, loss_kind: str,
            factor_size: int, fit_intercept: bool, fit_linear: bool,
            solver: str, max_iter: int):
    n, d = X.shape
    sum_w = jnp.maximum(jnp.sum(w), 1e-12)
    key = jax.random.PRNGKey(seed)
    theta = {
        "w0": jnp.float32(0.0),
        "w": jnp.zeros((d,), jnp.float32),
        "V": init_std * jax.random.normal(key, (d, factor_size), jnp.float32),
    }

    def loss_fn(theta):
        raw = _fm_raw(theta, X)
        if loss_kind == "logistic":
            sign = 2.0 * y - 1.0
            row = jnp.logaddexp(0.0, -sign * raw)
        else:  # squared
            row = 0.5 * (raw - y) ** 2
        reg_term = 0.5 * reg * (
            jnp.sum(theta["w"] ** 2) + jnp.sum(theta["V"] ** 2)
        )
        return jnp.sum(row * w) / sum_w + reg_term

    if solver == "adamW":
        opt = optax.adamw(step_size, weight_decay=0.0)  # reg is in the loss
    elif solver == "gd":
        opt = optax.sgd(step_size)
    else:
        raise ValueError(f"unknown solver {solver!r}")

    # freeze disabled parts by zeroing their gradients
    def mask_grads(g):
        if not fit_intercept:
            g = {**g, "w0": jnp.zeros_like(g["w0"])}
        if not fit_linear:
            g = {**g, "w": jnp.zeros_like(g["w"])}
        return g

    def body(carry):
        theta, state, prev_loss, _, it = carry
        loss, g = jax.value_and_grad(loss_fn)(theta)
        updates, state = opt.update(mask_grads(g), state, theta)
        theta = optax.apply_updates(theta, updates)
        rel = jnp.abs(loss - prev_loss) / jnp.maximum(jnp.abs(loss), 1e-12)
        return theta, state, loss, rel < tol, it + 1

    def keep_going(carry):
        _, _, _, converged, it = carry
        return (it < max_iter) & ~converged

    theta, _, _, _, n_iter = jax.lax.while_loop(
        keep_going, body,
        (theta, opt.init(theta), jnp.float32(jnp.inf), False, 0),
    )
    return theta, loss_fn(theta), n_iter


class _FMModelBase(Model):
    def __init__(self, params, theta):
        self.params = params
        self.theta = theta  # {'w0', 'w'[d], 'V'[d,k]}

    @property
    def state_pytree(self):
        return self.theta

    def _raw(self, table: TpuTable):
        return _fm_raw(self.theta, table.X)


class FMRegressorModel(_FMModelBase):
    def predict(self, table: TpuTable) -> np.ndarray:
        return np.asarray(self._raw(table))[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        pred = self._raw(table)
        new_attrs = list(table.domain.attributes) + [ContinuousVariable("prediction")]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        return table.with_X(
            jnp.concatenate([table.X, pred[:, None]], axis=1), new_domain
        )


class FMClassifierModel(_FMModelBase):
    def __init__(self, params, theta, class_values):
        super().__init__(params, theta)
        self.class_values = class_values

    def predict(self, table: TpuTable) -> np.ndarray:
        return np.asarray(self._raw(table) > 0).astype(np.int32)[: table.n_rows]

    def predict_probability(self, table: TpuTable) -> np.ndarray:
        p1 = jax.nn.sigmoid(self._raw(table))
        return np.asarray(jnp.stack([1 - p1, p1], axis=1))[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        raw = self._raw(table)
        p1 = jax.nn.sigmoid(raw)
        new_attrs = list(table.domain.attributes) + [
            ContinuousVariable("rawPrediction"),
            ContinuousVariable("probability"),
            DiscreteVariable("prediction", tuple(self.class_values)),
        ]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate(
            [table.X, raw[:, None], p1[:, None],
             (raw > 0).astype(jnp.float32)[:, None]], axis=1
        )
        return table.with_X(X, new_domain)


class FMRegressor(Estimator):
    ParamsCls = FMParams
    params: FMParams

    def _fit(self, table: TpuTable) -> FMRegressorModel:
        p = self.params
        if table.y is None:
            raise ValueError("FMRegressor needs a target column")
        theta, _, _ = _fit_fm(
            table.X, table.y, table.W,
            jnp.float32(p.reg_param), jnp.float32(p.step_size),
            jnp.float32(p.init_std), jnp.float32(p.tol), p.seed,
            loss_kind="squared", factor_size=p.factor_size,
            fit_intercept=p.fit_intercept, fit_linear=p.fit_linear,
            solver=p.solver, max_iter=p.max_iter,
        )
        return FMRegressorModel(p, theta)


class FMClassifier(Estimator):
    ParamsCls = FMParams
    params: FMParams

    def _fit(self, table: TpuTable) -> FMClassifierModel:
        p = self.params
        class_values = infer_class_values(table)
        if len(class_values) != 2:
            raise ValueError("FMClassifier is binary (MLlib parity); "
                             f"got {len(class_values)} classes")
        theta, _, _ = _fit_fm(
            table.X, table.y, table.W,
            jnp.float32(p.reg_param), jnp.float32(p.step_size),
            jnp.float32(p.init_std), jnp.float32(p.tol), p.seed,
            loss_kind="logistic", factor_size=p.factor_size,
            fit_intercept=p.fit_intercept, fit_linear=p.fit_linear,
            solver=p.solver, max_iter=p.max_iter,
        )
        return FMClassifierModel(p, theta, class_values)
