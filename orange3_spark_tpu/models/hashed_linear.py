"""Hashed-sparse linear models — the Criteo-scale categorical path.

BASELINE config 2 (the headline metric) is Criteo click-through: 13 dense
numerics + 26 categoricals hashed to millions of dimensions. A dense design
matrix is unrepresentable; MLlib fits it as a SparseVector pipeline
(FeatureHasher -> LogisticRegression over treeAggregate; SURVEY.md §2b rows
"Distributed dataframe"/"LogReg"; reconstructed, mount empty).

TPU-native redesign — fixed-nnz-per-row, not CSR:

* every row has EXACTLY n_cat categorical slots (Criteo's shape), so the
  sparse structure is two static-shape arrays: raw codes [N, C] (hashed to
  indices on device, ops/hashing.py) and an embedding table [n_dims, k].
  Static shapes mean ONE compiled step for the whole stream — CSR's ragged
  rows would force re-compilation or host-side bucketing.
* the forward is an embedding gather ``take(emb, idx)`` + a dense matmul for
  the numeric block; the backward is XLA's scatter-add. No SpMV kernel to
  hand-write — gather/scatter are native TPU ops.
* binary targets use the k=1 sigmoid formulation (``binary_logistic`` in
  models/_linear.py) — identical optimum to 2-column softmax at HALF the
  gather/scatter bytes, the step's dominant cost (measured 3.3x faster on a
  v5e chip).
* the chunk arrives as ONE [N, 1+n_dense+n_cat] f32 array straight from
  fastcsv — label column INCLUDED (``label_in_chunk``) — so the host does
  zero per-cell work, zero column splits, and the transfer is a single DMA;
  label/dense/categorical split happens inside the jit. Padding rows are
  masked by a traced ``n_valid`` scalar instead of a shipped weight vector.
* epoch overlap: parse+DMA of chunk t+1 runs on a prefetch thread while the
  device runs step t (io/streaming.py ``prefetch_map``).
* ``cache_device=True`` retains each device-put chunk in HBM and replays it
  for epochs 2+, exactly Spark's ``dataset.persist()`` before an iterative
  fit (MLlib LogisticRegression caches its input RDD): later epochs run at
  pure step speed with ZERO host involvement. Configs that exceed
  ``cache_device_bytes`` (the 1B-row regime) degrade to pure streaming for
  EVERY epoch — a partial replay would reorder/double-count chunks, and a
  CSV source cannot seek past its cached prefix, so the host parse (the
  actual bottleneck) would be paid anyway.
* data parallelism: rows sharded P('data'); the embedding table is
  replicated (4 MB at 2^20 x 1) and its gradient all-reduces over ICI by
  GSPMD — treeAggregate without the shuffle. A 'model'-axis sharded table
  variant lives in ``emb_sharding`` (factor tables wider than HBM shard
  P('model', None)).
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from functools import partial
from typing import Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
import optax

from orange3_spark_tpu.core.session import TpuSession
from orange3_spark_tpu.exec.donate import donating_jit
from orange3_spark_tpu.exec.pipeline import PipelineStats
from orange3_spark_tpu.io.codec import (
    BF16, bit_width, pack_rows_np, resolve_cache_dtype, unpack_rows,
)
from orange3_spark_tpu.io.multihost import put_sharded
from orange3_spark_tpu.models._linear import EPS_TOTAL_WEIGHT, per_row_loss
from orange3_spark_tpu.models.base import Estimator, Model, Params
from orange3_spark_tpu.ops.hashing import (
    column_salts, hash_columns, hash_columns_np,
)
from orange3_spark_tpu.optim.sparse import (
    build_plan_np, dense_update, finalize_lazy_decay, init_optim_state,
    is_sparse_update, optim_kind, pack_plan_np, plan_field_shapes,
    plan_packed_field_shapes, resolve_optim_update, resolve_sparse_lowering,
    sparse_embedding_update, unpack_plan,
)
from orange3_spark_tpu.obs import prof
from orange3_spark_tpu.obs.report import RunReport
from orange3_spark_tpu.obs.trace import span, span_iter, traced
from orange3_spark_tpu.obs.trace import refreshed_enabled as obs_enabled
from orange3_spark_tpu.resilience.numerics import check_finite_training
from orange3_spark_tpu.utils.dispatch import bound_dispatch
from orange3_spark_tpu.utils.profiling import count_dispatch

# unit-lr adam; the traced lr scales its updates (see io/streaming.py)
_ADAM_UNIT = optax.adam(1.0)

#: per-process ledger-entry numbering for hashed fits (obs/prof.py)
_FIT_LEDGER_SEQ = itertools.count()


@dataclasses.dataclass(frozen=True)
class HashedLinearParams(Params):
    n_dims: int = 1 << 20        # hashed feature space (power of two)
    n_dense: int = 13            # leading numeric columns (Criteo I1-I13)
    n_cat: int = 26              # trailing categorical columns (C1-C26)
    loss: str = "logistic"       # 'logistic' | 'squared' | 'squared_hinge'
    n_classes: int = 2
    epochs: int = 1
    step_size: float = 0.02
    reg_param: float = 0.0       # L2 on emb + coef
    chunk_rows: int = 1 << 18
    threshold: float = 0.5
    seed: int = 0
    compute_dtype: str = "float32"
    label_in_chunk: bool = False  # chunks carry the label as column 0
    prefetch_depth: int = 2       # host->device pipeline depth (0 disables)
    # 'auto' resolves at fit time via resolve_emb_update (currently
    # 'fused' on every backend — the 2026-07-31 on-chip A/B winner).
    # Explicit values force a specific scatter lowering.
    emb_update: str = "auto"     # 'auto' | 'fused' | 'per_column' | 'sorted'
    # Optimizer rule + lowering (optim/ subsystem, docs/optim.md):
    # 'adam' is the legacy dense optax path (in-loss L2, full-table moment
    # sweeps every step). The sparse_* rules update ONLY the rows a step
    # touches — per-row f32 slots, lazy decoupled weight decay via
    # last-seen timestamps — and each has a dense_* twin (same math, full
    # sweeps) for parity/A-B. OTPU_SPARSE_UPDATE=0 resolves sparse_* to
    # dense_* at fit entry (the kill-switch, donation-sweep conventions).
    # Note: the non-adam rules treat reg_param as DECOUPLED weight decay
    # (FTRL: its closed-form L2), not an in-loss term, and report the
    # pure data loss.
    optim_update: str = "adam"   # 'adam' | '{dense,sparse}_{sgd,adagrad,ftrl}'
    # Dedup lowering for sparse_* rules: 'plan' pre-sorts each chunk's
    # touched rows on the HOST at ingest (replayed every epoch, gather-
    # based writeback — CPU default); 'sort' dedups in-step (argsort in
    # the jit, no per-chunk aux memory — TPU default). 'auto' resolves
    # per backend via optim.resolve_sparse_lowering.
    sparse_lowering: str = "auto"   # 'auto' | 'plan' | 'sort'
    l1_param: float = 0.0        # FTRL-proximal l1 (sparse/dense ftrl only)
    fused_replay: bool = True    # cache replay epochs as scan program(s)
    # Granularity of the fused replay dispatches: 'all' lowers epochs 2+
    # to ONE scan (n_epochs-1 trip count — cheapest, one dispatch);
    # 'epoch' dispatches one n_epochs=1 scan PER epoch (n_epochs-1
    # dispatches over the same chunk stack). 'epoch' exists for tunneled
    # hosts where the single giant program is fragile (the round-4
    # UNAVAILABLE fault) but per-chunk dispatch overhead (~hundreds of ms
    # per RPC) would dominate the wall: 99 epoch dispatches cost seconds,
    # 2900 chunk dispatches cost minutes.
    replay_granularity: str = "all"   # 'all' | 'epoch'
    # With replay_granularity='epoch': fold K epochs into each scan
    # dispatch — ceil(n_replay/K) dispatches instead of n_replay, the
    # amortization dial between 'epoch' (K=1, most robust, most RPCs) and
    # 'all' (one giant program, the round-4 fault's shape). Step sequence
    # is identical at every K, and checkpoint cadence is preserved (groups
    # clamp at snapshot boundaries — io/streaming.run_epoch_replay).
    epochs_per_dispatch: int = 1
    # Defer epoch-1 training into the replay program: the streaming pass
    # becomes pure ingest (parse -> pad -> DMA -> cache/spill, NO step
    # dispatches) and the replay then runs ``epochs`` full passes instead
    # of ``epochs - 1``. The step sequence is IDENTICAL (epoch 1's
    # per-chunk steps visit the same chunks in the same order the first
    # replay pass does), so results are bit-identical to the default —
    # pinned by tests/test_hashed_defer.py. Wins on tunneled/high-RTT
    # hosts twice over: (a) epoch 1 sheds n_chunks step dispatches
    # (~hundreds of ms EACH over a tunnel) and overlaps nothing but
    # DMA, and (b) no per-chunk step program ever executes before the
    # fused scan — the round-4 UNAVAILABLE device fault's observed
    # precondition (see tools/replay_fault_diag.py). Requires
    # cache_device. Checkpointing composes ONLY with
    # replay_granularity='epoch' (snapshots land at epoch boundaries
    # between the per-epoch replay dispatches; resume re-ingests the
    # cache step-free and fast-forwards checkpointed epochs — see
    # tests/test_hashed_defer.py kill-and-resume); with granularity
    # 'all' a checkpointered fit silently keeps the default schedule,
    # whose per-chunk dispatches give step-granular snapshots.
    defer_epoch1: bool = False
    # Crash-resumable fits (docs/resilience.md): with a checkpointer
    # passed to fit_stream, K > 0 switches the snapshot cadence from
    # per-step (checkpointer.every_steps) to EPOCH BOUNDARIES every K
    # epochs — atomic write-to-temp + rename, so a fit SIGKILLed
    # mid-epoch resumes at the last boundary and replays the identical
    # step sequence. Inert under OTPU_RESILIENCE=0 and without a
    # checkpointer (same contract as StreamingLinearParams).
    checkpoint_every_epochs: int = 0
    # value-weighted sparse rows (MLlib SparseVector semantics): chunks
    # carry n_cat (index, value) PAIRS — [label?, idx..., val...] — and the
    # forward is sum(emb[hash(idx)] * val), io/libsvm.py's fixed-nnz
    # layout. Requires n_dense == 0; -1 index padding is inert because its
    # value is 0 (zero forward contribution, zero gradient).
    value_weighted: bool = False
    # Missing-value semantics (real Criteo TSV ships EMPTY cells in both
    # dense and categorical columns; fastcsv parses empty dense -> NaN and
    # empty marked-categorical -> crc32("")==0, the reserved code):
    # 'zero' (default) imputes NaN dense cells to 0 and NaN categorical
    # cells to the reserved code 0 INSIDE the jit (fused, free); 'keep'
    # passes NaN through for an upstream imputer to handle — a NaN
    # reaching the step then poisons the loss, visibly.
    missing: str = "zero"        # 'zero' | 'keep'
    # Cache/spill storage precision (io/codec.py; resolved ONCE at fit
    # entry via resolve_chunk_codec, OTPU_CACHE_DTYPE kill-switch —
    # '=f32' restores the legacy cache exactly):
    #   'f32'    legacy padded-f32 chunks, bit-for-bit.
    #   'bf16'   dense numeric block stored bfloat16 (lossy, bounded:
    #            RTNE, rel. err <= 2^-8); label stored u8 where exact
    #            (classification losses); categorical codes stay f32.
    #   'packed' bf16 PLUS lossless integer packing: categorical columns
    #            pre-hash on the prefetch thread (the host hash twin is
    #            pinned bit-identical to the device's) and store at
    #            log2(n_dims) bits; the sparse 'plan' arrays bit-pack at
    #            their static widths (optim/sparse.pack_plan_np). Decode
    #            is static shifts/masks INSIDE the step — HBM, disk spill
    #            and h2d DMA all move ~2x fewer bytes, and the cache/
    #            fusion-gate capacity roughly doubles.
    #   'auto'   the session policy knob (TpuSession.default_cache_dtype,
    #            'packed').
    # value_weighted fits keep 'f32' (explicit (idx, val) pairs carry
    # their own -1/0 padding the codec must not re-encode), and 'packed'
    # degrades to 'bf16' under missing='keep' (NaN codes must reach the
    # in-jit hash to poison visibly — pre-hashing would hide them).
    cache_dtype: str = "f32"     # 'f32' | 'bf16' | 'packed' | 'auto'


def _effective_k(p: HashedLinearParams) -> int:
    """Width of theta's class dimension: binary logistic collapses to k=1
    (sigmoid) — half the embedding traffic of the 2-column softmax."""
    if p.loss != "logistic":
        return 1
    return 1 if p.n_classes == 2 else p.n_classes


def resolve_emb_update(p: HashedLinearParams) -> str:
    """The concrete scatter lowering for this fit — 'auto' picks the
    measured-best per backend. THE one resolver: anything handing
    ``emb_update`` to a jitted step must go through it.

    Currently 'fused' everywhere: the 2026-07-31 on-chip A/B on the
    round-4 step (BENCH_HW_r4.jsonl: fused 0.27 ms/step < sorted 0.41 <
    per_column 0.75 at 2^18 rows x 2^22 dims) reversed round 3's verdict
    (sorted 0.95 < fused 2.38 on the pre-rewrite step) — the SWAR parse /
    arena work also made the fused scatter the cheapest lowering on TPU,
    and XLA:CPU always sorted slowly. 'sorted' (conflict-free custom-vjp
    scatter) remains available by explicit request."""
    if p.emb_update == "auto":
        return "fused"
    return p.emb_update


def _impute_flag(p: HashedLinearParams) -> bool:
    """Static impute flag for the jitted functions; value-weighted rows
    carry explicit (index, value) pairs with their own -1/0 padding
    convention, so 'zero' imputation only applies to the dense+categorical
    layout."""
    if p.missing not in ("zero", "keep"):
        raise ValueError(f"missing must be 'zero' or 'keep', got {p.missing!r}")
    return p.missing == "zero" and not p.value_weighted


def _row_loss_kind(p: HashedLinearParams) -> str:
    if p.loss == "logistic" and p.n_classes == 2:
        return "binary_logistic"
    return p.loss


@jax.custom_vjp
def _emb_sum_sorted_grad(emb, idx):
    """Same forward as take+sum; the BACKWARD sorts the flattened
    (index, grad) pairs and scatter-adds with indices_are_sorted=True — the
    classic TPU trade of one O(M log M) sort for a conflict-free scatter.
    An A/B lever against the plain scatter (emb_update='sorted')."""
    return jnp.sum(jnp.take(emb, idx, axis=0), axis=1, dtype=jnp.float32)


def _emb_sum_sorted_fwd(emb, idx):
    # dtype travels as a zero-size array (a bare dtype is not a JAX type)
    proto = jnp.zeros((0,), emb.dtype)
    return _emb_sum_sorted_grad(emb, idx), (idx, emb.shape, proto)


def _sorted_scatter(flat_idx, flat_g, D: int, k: int, dtype):
    """Sort (index, grad) pairs, then a conflict-free ordered scatter-add —
    the shared backward of both sorted lowerings."""
    order = jnp.argsort(flat_idx)
    return jnp.zeros((D, k), dtype).at[flat_idx[order]].add(
        flat_g[order].astype(dtype),
        indices_are_sorted=True, unique_indices=False,
    )


def _emb_sum_sorted_bwd(res, g):
    idx, (D, k), proto = res
    N, C = idx.shape
    flat_g = jnp.broadcast_to(g[:, None, :], (N, C, k)).reshape(N * C, k)
    return _sorted_scatter(idx.reshape(-1), flat_g, D, k, proto.dtype), None


_emb_sum_sorted_grad.defvjp(_emb_sum_sorted_fwd, _emb_sum_sorted_bwd)


@jax.custom_vjp
def _emb_wsum_sorted_grad(emb, idx, vals):
    """Value-weighted twin of ``_emb_sum_sorted_grad``: forward
    sum(emb[idx] * vals), backward sorts (index, g*val) pairs into a
    conflict-free scatter. vals gets no gradient (data, not parameters)."""
    return jnp.sum(
        jnp.take(emb, idx, axis=0) * vals[:, :, None], axis=1,
        dtype=jnp.float32,
    )


def _emb_wsum_sorted_fwd(emb, idx, vals):
    proto = jnp.zeros((0,), emb.dtype)
    return _emb_wsum_sorted_grad(emb, idx, vals), (idx, vals, emb.shape, proto)


def _emb_wsum_sorted_bwd(res, g):
    idx, vals, (D, k), proto = res
    N, C = idx.shape
    flat_g = (g[:, None, :] * vals[:, :, None]).reshape(N * C, k)
    return (_sorted_scatter(idx.reshape(-1), flat_g, D, k, proto.dtype),
            None, None)


_emb_wsum_sorted_grad.defvjp(_emb_wsum_sorted_fwd, _emb_wsum_sorted_bwd)


def _hashed_logits(theta, dense, idx, compute_dtype, emb_update: str = "fused",
                   vals=None):
    """emb_update selects the gather/scatter formulation — all numerically
    identical, different XLA lowerings (the step is scatter-bound; see
    tools/step_ab.py for the on-hardware A/B):
      'fused'      one [N, C] gather; autodiff emits one fused scatter
      'per_column' C independent [N] gathers/scatters
      'sorted'     custom-vjp backward: sort pairs, conflict-free scatter
    ``vals`` (value-weighted sparse mode): per-pair multipliers — the
    forward becomes sum(emb[idx] * val), MLlib SparseVector semantics.
    """
    emb = theta["emb"].astype(compute_dtype)
    if emb_update == "per_column":
        logits = jnp.zeros((idx.shape[0], emb.shape[1]), jnp.float32)
        for c in range(idx.shape[1]):
            col = jnp.take(emb, idx[:, c], axis=0)
            if vals is not None:
                col = col * vals[:, c, None]
            logits = logits + col
    elif emb_update == "sorted":
        logits = (_emb_sum_sorted_grad(emb, idx) if vals is None
                  else _emb_wsum_sorted_grad(emb, idx, vals))
    elif emb_update != "fused":
        raise ValueError(
            f"emb_update must be 'fused' | 'per_column' | 'sorted', "
            f"got {emb_update!r}"
        )
    else:
        emb_rows = jnp.take(emb, idx, axis=0)
        if vals is not None:
            emb_rows = emb_rows * vals[:, :, None]
        logits = jnp.sum(emb_rows, axis=1, dtype=jnp.float32)    # [N, k]
    if theta["coef"].shape[0]:
        logits = logits + jnp.dot(
            dense.astype(compute_dtype),
            theta["coef"].astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
    return logits + theta["intercept"]


def _split_chunk(Xall, n_valid, y, w, *, label_in_chunk: bool, n_dense: int,
                 value_weighted: bool = False, impute_missing: bool = False):
    """In-jit chunk anatomy. label_in_chunk: column 0 is the label and the
    row mask is iota < n_valid (no y/w host vectors shipped at all).
    value_weighted: the feature block is C (index, value) PAIRS —
    [idx..., val...] — instead of dense+categorical columns.
    impute_missing: NaN dense cells -> 0, NaN categorical cells -> the
    reserved code 0 (== crc32 of the empty string, what fastcsv emits for
    an empty marked-categorical cell) — Criteo-TSV missing-cell semantics,
    fused into the step for free."""
    if label_in_chunk:
        yv = Xall[:, 0]
        feat = Xall[:, 1:]
        wv = (jnp.arange(Xall.shape[0], dtype=jnp.int32)
              < n_valid).astype(jnp.float32)
    else:
        yv = y
        feat = Xall
        wv = w
    if value_weighted:
        C = feat.shape[1] // 2
        return yv, feat[:, :0], feat[:, :C], wv, feat[:, C:]
    dense, cats = feat[:, :n_dense], feat[:, n_dense:]
    if impute_missing:
        dense = jnp.where(jnp.isnan(dense), 0.0, dense)
        cats = jnp.where(jnp.isnan(cats), 0.0, cats)
    return yv, dense, cats, wv, None


def _step_core(
    theta, opt_state, Xall, n_valid, y, w, salts, reg, lr, plan=None, l1=0.0,
    *, loss_kind: str, n_dims: int, n_dense: int, compute_dtype=jnp.float32,
    label_in_chunk: bool = False, emb_update: str = "fused",
    value_weighted: bool = False, impute_missing: bool = False,
    optim_update: str = "adam", sparse_lowering: str = "none",
    use_decay: bool = False, codec=None,
):
    """One optimizer step on one chunk — traced by both the per-chunk jit
    (`_hashed_step`) and the fused replay scan (`_hashed_replay_epochs`).

    optim_update == 'adam' is the legacy path: in-loss L2 + a dense optax
    adam sweep over the whole table. Every other rule (optim/ subsystem)
    reports the pure data loss, treats reg as decoupled weight decay, and
    — for the sparse_* rules — updates only the touched rows, with ``plan``
    carrying the host-presorted dedup under the 'plan' lowering.

    codec (io/codec.py, resolved once at fit entry): None is the legacy
    f32 chunk; otherwise ``Xall`` is the compressed block dict and the
    decode (bf16 widen / static bit-unpack, fused by XLA) happens HERE, so
    the replay scan reads compressed HBM bytes. A packed plan unpacks here
    too — bit-exact, so the plan-lowering update is unchanged math."""
    if codec is None:
        yv, dense, cats, wv, vals = _split_chunk(
            Xall, n_valid, y, w, label_in_chunk=label_in_chunk,
            n_dense=n_dense, value_weighted=value_weighted,
            impute_missing=impute_missing,
        )
        idx = hash_columns(cats, salts, n_dims)
    else:
        yv, dense, idx, wv = _decode_chunk(codec, Xall, n_valid, y, w, salts)
        cats = None
        vals = None
        if plan is not None and codec.mode == "packed":
            plan = unpack_plan(plan, Xall["cats"].shape[0], codec.n_cat,
                               n_dims)

    if optim_update == "adam":
        def loss_fn(theta):
            logits = _hashed_logits(theta, dense, idx, compute_dtype,
                                    emb_update, vals)
            row = per_row_loss(loss_kind, logits, yv)
            sw = jnp.maximum(jnp.sum(wv), EPS_TOTAL_WEIGHT)
            data = jnp.sum(row * wv) / sw
            return data + 0.5 * reg * (
                jnp.sum(theta["emb"] ** 2) + jnp.sum(theta["coef"] ** 2)
            )

        loss, g = jax.value_and_grad(loss_fn)(theta)
        updates, opt_state = _ADAM_UNIT.update(g, opt_state, theta)
        updates = jax.tree.map(lambda u: lr * u, updates)
        return optax.apply_updates(theta, updates), opt_state, loss

    kind = optim_kind(optim_update)
    decay = 1.0 - lr * reg
    step = opt_state["step"]
    slots = opt_state["slots"]
    if is_sparse_update(optim_update):
        # forward only — no autodiff through the table: the [N, k] logits
        # gradient is all the touched-row engine needs (the plain 'fused'
        # gather forward; emb_update scatter lowerings are a BACKWARD
        # concern and only apply to the dense paths)
        logits = _hashed_logits(theta, dense, idx, compute_dtype, "fused",
                                vals)

        def data_loss(z):
            row = per_row_loss(loss_kind, z, yv)
            sw = jnp.maximum(jnp.sum(wv), EPS_TOTAL_WEIGHT)
            return jnp.sum(row * wv) / sw

        loss, dl = jax.value_and_grad(data_loss)(logits)
        emb, t, eslots = sparse_embedding_update(
            kind, theta["emb"], opt_state["t"], slots["emb"], dl, idx,
            lr, decay, reg, l1, step, lowering=sparse_lowering,
            use_decay=use_decay, plan=plan, n_valid=n_valid,
            raw_cats=(cats if value_weighted else None), vals=vals,
        )
        # dense small parameters: the same rule, full-array (they are tiny)
        if theta["coef"].shape[0]:
            g_coef = jnp.dot(dense.astype(compute_dtype).T, dl,
                             preferred_element_type=jnp.float32)
        else:
            g_coef = jnp.zeros_like(theta["coef"])
        g_int = jnp.sum(dl, axis=0)
    else:
        # dense twin: autodiff through the table (the emb_update scatter
        # lowering applies), then a full-array rule sweep — the parity
        # baseline the sparse path is measured against
        def loss_fn(theta):
            logits = _hashed_logits(theta, dense, idx, compute_dtype,
                                    emb_update, vals)
            row = per_row_loss(loss_kind, logits, yv)
            sw = jnp.maximum(jnp.sum(wv), EPS_TOTAL_WEIGHT)
            return jnp.sum(row * wv) / sw

        loss, g = jax.value_and_grad(loss_fn)(theta)
        t = opt_state["t"]
        emb, eslots = dense_update(
            kind, theta["emb"], slots["emb"], g["emb"], lr, decay, reg, l1,
            use_decay=use_decay)
        g_coef, g_int = g["coef"], g["intercept"]
    coef, cslots = dense_update(
        kind, theta["coef"], slots["coef"], g_coef, lr, decay, reg, l1,
        use_decay=use_decay)
    intercept, islots = dense_update(
        kind, theta["intercept"], slots["intercept"], g_int, lr, decay,
        reg, l1, use_decay=False)    # reg never touched the intercept
    theta = {"emb": emb, "coef": coef, "intercept": intercept}
    opt_state = {"step": step + 1, "t": t,
                 "slots": {"emb": eslots, "coef": cslots,
                           "intercept": islots}}
    return theta, opt_state, loss


_STEP_STATICS = (
    "loss_kind", "n_dims", "n_dense", "compute_dtype", "label_in_chunk",
    "emb_update", "value_weighted", "impute_missing", "optim_update",
    "sparse_lowering", "use_decay", "codec",
)


@donating_jit(static_argnames=_STEP_STATICS, donate_argnums=(0, 1))
def _hashed_step(
    theta, opt_state, Xall, n_valid, y, w, salts, reg, lr, plan=None,
    l1=0.0,
    *, loss_kind: str, n_dims: int, n_dense: int, compute_dtype=jnp.float32,
    label_in_chunk: bool = False, emb_update: str = "fused",
    value_weighted: bool = False, impute_missing: bool = False,
    optim_update: str = "adam", sparse_lowering: str = "none",
    use_decay: bool = False, codec=None,
):
    return _step_core(
        theta, opt_state, Xall, n_valid, y, w, salts, reg, lr, plan, l1,
        loss_kind=loss_kind, n_dims=n_dims, n_dense=n_dense,
        compute_dtype=compute_dtype, label_in_chunk=label_in_chunk,
        emb_update=emb_update, value_weighted=value_weighted,
        impute_missing=impute_missing, optim_update=optim_update,
        sparse_lowering=sparse_lowering, use_decay=use_decay, codec=codec,
    )


@donating_jit(static_argnames=_STEP_STATICS + ("n_epochs",),
              donate_argnums=(0, 1))
def _hashed_replay_epochs(
    theta, opt_state, stacks, salts, reg, lr, l1=0.0,
    *, loss_kind: str, n_dims: int, n_dense: int, compute_dtype=jnp.float32,
    label_in_chunk: bool = False, emb_update: str = "fused",
    value_weighted: bool = False, impute_missing: bool = False,
    optim_update: str = "adam", sparse_lowering: str = "none",
    use_decay: bool = False, codec=None,
    n_epochs: int,
):
    """Epochs 2+ of a cached fit as ONE XLA program: an epoch-level scan
    around a chunk-level scan over the HBM-resident chunk stack.

    ``stacks`` is the chunk stack as one pytree — ``(Xstack, n_valid_vec,
    ystack, wstack)`` plus, when the sparse 'plan' lowering is active, a
    fifth element holding the stacked per-chunk touched-row plans (each
    leaf [n_chunks, ...]); the scan slices all of them in lockstep.

    Rationale (measured round 3, BASELINE.md roofline): the per-chunk jit
    replay paid ~275 ms/step of per-dispatch/sync overhead on the tunneled
    bench host while the step itself runs in 0.95 ms pipelined. Fusing the
    whole replay phase into one dispatch removes that overhead by
    construction — and is the idiomatic XLA shape for a fixed iteration
    over fixed data (compiler-visible loop, no host round trips).
    Returns per-epoch mean losses ([n_epochs], one small d2h at the end).
    """
    kw = dict(loss_kind=loss_kind, n_dims=n_dims, n_dense=n_dense,
              compute_dtype=compute_dtype, label_in_chunk=label_in_chunk,
              emb_update=emb_update, value_weighted=value_weighted,
              impute_missing=impute_missing, optim_update=optim_update,
              sparse_lowering=sparse_lowering, use_decay=use_decay,
              codec=codec)

    def chunk_body(carry, xs):
        theta, opt = carry
        Xall, n_valid, y, w = xs[:4]
        plan = xs[4] if len(xs) > 4 else None
        theta, opt, loss = _step_core(
            theta, opt, Xall, n_valid, y, w, salts, reg, lr, plan, l1, **kw
        )
        return (theta, opt), loss

    def epoch_body(carry, _):
        carry, losses = jax.lax.scan(chunk_body, carry, tuple(stacks))
        return carry, losses

    (theta, opt_state), chunk_losses = jax.lax.scan(
        epoch_body, (theta, opt_state), None, length=n_epochs
    )
    # [n_epochs, n_chunks]: [-1, -1] is the last chunk's loss — the same
    # value the per-step loop path reports as final_loss_
    return theta, opt_state, chunk_losses


@partial(jax.jit, static_argnames=("n_dims", "n_dense", "value_weighted",
                                       "impute_missing"))
def _hashed_predict(theta, Xall, salts, *, n_dims: int, n_dense: int,
                    value_weighted: bool = False,
                    impute_missing: bool = False):
    # one layout authority: the same _split_chunk the training step uses
    _, dense, cats, _, vals = _split_chunk(
        Xall, 0, None, None, label_in_chunk=False, n_dense=n_dense,
        value_weighted=value_weighted, impute_missing=impute_missing,
    )
    idx = hash_columns(cats, salts, n_dims)
    return _hashed_logits(theta, dense, idx, jnp.float32, vals=vals)


@partial(
    jax.jit,
    static_argnames=("loss_kind", "n_dims", "n_dense", "label_in_chunk",
                     "value_weighted", "impute_missing", "codec"),
)
def _hashed_eval_chunk(
    theta, Xall, n_valid, y, w, salts,
    *, loss_kind: str, n_dims: int, n_dense: int, label_in_chunk: bool,
    value_weighted: bool = False, impute_missing: bool = False, codec=None,
):
    """Device-side eval accumulators for one chunk: (weighted logloss sum,
    weighted correct sum, weight sum, pos/neg score histograms for AUC).
    Nothing but these small arrays ever crosses back to the host — device->
    host bandwidth is the scarcest resource in the whole pipeline.
    ``codec``: the fit's cache codec when evaluating compressed cached
    chunks (decode-in-jit, same contract as the step)."""
    if codec is None:
        yv, dense, cats, wv, vals = _split_chunk(
            Xall, n_valid, y, w, label_in_chunk=label_in_chunk,
            n_dense=n_dense, value_weighted=value_weighted,
            impute_missing=impute_missing,
        )
        idx = hash_columns(cats, salts, n_dims)
        vals_arg = vals
    else:
        yv, dense, idx, wv = _decode_chunk(codec, Xall, n_valid, y, w, salts)
        vals_arg = None
    logits = _hashed_logits(theta, dense, idx, jnp.float32, vals=vals_arg)
    row = per_row_loss(loss_kind, logits, yv)
    loss_sum = jnp.sum(row * wv)
    if loss_kind == "binary_logistic":
        score = jax.nn.sigmoid(logits[:, 0])
        pred = (score > 0.5).astype(jnp.float32)
    elif loss_kind == "logistic":
        score = jax.nn.softmax(logits, axis=-1)[:, -1]
        pred = jnp.argmax(logits, axis=-1).astype(jnp.float32)
    else:
        score = logits[:, 0]
        pred = (logits[:, 0] > 0).astype(jnp.float32)
    correct = jnp.sum((pred == yv).astype(jnp.float32) * wv)
    bins = 4096
    b = jnp.clip((score * bins).astype(jnp.int32), 0, bins - 1)
    pos = jnp.zeros((bins,), jnp.float32).at[b].add(wv * (yv > 0.5))
    neg = jnp.zeros((bins,), jnp.float32).at[b].add(wv * (yv <= 0.5))
    return loss_sum, correct, jnp.sum(wv), pos, neg


def _auc_from_hists(pos_h: np.ndarray, neg_h: np.ndarray) -> float | None:
    npos, nneg = pos_h.sum(), neg_h.sum()
    if not (npos and nneg):
        return None
    cum_neg = np.concatenate([[0.0], np.cumsum(neg_h)[:-1]])
    return float((pos_h * (cum_neg + 0.5 * neg_h)).sum() / (npos * nneg))


class HashedLinearModel(Model):
    """Fitted hashed-sparse linear model; predicts on raw (dense+categorical)
    chunks — the hashing travels with the model via its salts."""

    def __init__(self, params: HashedLinearParams, theta, salts, class_values):
        self.params = params
        self.theta = theta            # {'emb': [D,k], 'coef': [dd,k], 'intercept': [k]}
        self.salts = np.asarray(salts, np.uint32)
        self.class_values = tuple(class_values) if class_values else None
        self.n_steps_: int | None = None
        self.final_loss_: float | None = None
        # the cache codec of the producing fit (None = raw f32 chunks):
        # evaluate_device's default decode key for device_chunks_
        self.cache_codec_ = None

    @property
    def state_pytree(self):
        return dict(self.theta)

    @property
    def _binary(self) -> bool:
        return _row_loss_kind(self.params) == "binary_logistic"

    def _serve_array_state(self):
        """Serving hook (serve/context.py served_array): the state pytree
        the AOT executable takes as ARGUMENTS — the embedding table is the
        big-state case where closing over constants would duplicate it
        into every bucket's executable."""
        return {"theta": self.theta, "salts": np.asarray(self.salts)}

    def _serve_array_fn(self, state, Xp):
        """Device fn for the bucketed logits executable: row-wise (hash +
        gather + matmul), so bucket padding cannot perturb live rows."""
        p = self.params
        return _hashed_predict(
            state["theta"], Xp, state["salts"], n_dims=p.n_dims,
            n_dense=p.n_dense, value_weighted=p.value_weighted,
            impute_missing=_impute_flag(p),
        )

    def _logits(self, Xall: np.ndarray) -> np.ndarray:
        from orange3_spark_tpu.serve.context import (
            _reentrant, active_serving_context,
        )

        ctx = active_serving_context()
        if ctx is not None and not _reentrant():
            out = ctx.served_array(self, np.asarray(Xall, np.float32))
            if out is not None:
                return out
        p = self.params
        out = _hashed_predict(
            self.theta, jnp.asarray(Xall, jnp.float32),
            jnp.asarray(self.salts), n_dims=p.n_dims, n_dense=p.n_dense,
            value_weighted=p.value_weighted, impute_missing=_impute_flag(p),
        )
        return np.asarray(out)

    def predict(self, Xall: np.ndarray) -> np.ndarray:
        p = self.params
        logits = self._logits(Xall)
        if p.loss == "logistic":
            if self._binary:
                prob = 1.0 / (1.0 + np.exp(-logits[:, 0]))
                return (prob > p.threshold).astype(np.float32)
            if logits.shape[1] == 2:
                prob = 1.0 / (1.0 + np.exp(logits[:, 0] - logits[:, 1]))
                return (prob > p.threshold).astype(np.float32)
            return np.argmax(logits, axis=-1).astype(np.float32)
        if p.loss == "squared":
            return logits[:, 0]
        return (logits[:, 0] > 0).astype(np.float32)  # hinge margins

    def predict_proba(self, Xall: np.ndarray) -> np.ndarray:
        z = self._logits(Xall)
        if self._binary:
            p1 = 1.0 / (1.0 + np.exp(-z[:, 0]))
            return np.stack([1.0 - p1, p1], axis=1)
        z = z - z.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)

    def evaluate_stream(self, source: Callable[[], Iterator]) -> dict:
        """Stream logloss + accuracy (+AUC when binary) without collecting
        the dataset: exact running sums, fixed memory. Host-side loop — fine
        for tests/small tails; at bench scale use ``evaluate_device``."""
        p = self.params
        n = 0
        loss_sum = 0.0
        correct = 0
        # binary AUC via 4096-bin score histograms (rank-sum on bins)
        bins = 4096
        pos_h = np.zeros(bins)
        neg_h = np.zeros(bins)
        for chunk in source():
            Xall, y = chunk[0], chunk[1]
            if y is None:
                raise ValueError("evaluate_stream needs labeled chunks")
            prob = self.predict_proba(Xall)
            yi = np.asarray(y).astype(int)
            pi = np.clip(prob[np.arange(len(yi)), yi], 1e-12, 1.0)
            loss_sum += float(-np.log(pi).sum())
            correct += int((prob.argmax(1) == yi).sum())
            n += len(yi)
            if prob.shape[1] == 2:
                b = np.minimum((prob[:, 1] * bins).astype(int), bins - 1)
                pos_h += np.bincount(b[yi == 1], minlength=bins)
                neg_h += np.bincount(b[yi == 0], minlength=bins)
        out = {"logloss": loss_sum / max(n, 1), "accuracy": correct / max(n, 1)}
        auc = _auc_from_hists(pos_h, neg_h)
        if auc is not None:
            out["auc"] = auc
        return out

    def evaluate_device(self, device_chunks, *, codec="auto") -> dict:
        """Evaluate over device-resident chunks (as cached/returned by
        ``fit_stream(..., cache_device=True)``: (Xall, n_valid, y, w)
        tuples — ``Xall`` is the compressed block dict when the fit cached
        compressed, see ``cache_dtype``). All reduction happens on device;
        only five small arrays come home at the END — no per-chunk
        device->host round trips. ``codec='auto'`` reads the codec the
        producing fit recorded on this model (``cache_codec_``); pass
        ``None`` explicitly for raw f32 chunks built by hand."""
        p = self.params
        if codec == "auto":
            codec = getattr(self, "cache_codec_", None)
        salts = jnp.asarray(self.salts)
        kind = _row_loss_kind(p)
        tot = None
        for chunk in device_chunks:
            # sparse-plan fits cache 5-tuples (the touched-row plan rides
            # along for replay); eval only needs the data quadruple
            Xd, n_valid, yd, wd = chunk[:4]
            count_dispatch()
            out = _hashed_eval_chunk(
                self.theta, Xd, n_valid, yd, wd, salts,
                loss_kind=kind, n_dims=p.n_dims, n_dense=p.n_dense,
                label_in_chunk=p.label_in_chunk,
                value_weighted=p.value_weighted,
                impute_missing=_impute_flag(p), codec=codec,
            )
            tot = out if tot is None else tuple(
                a + b for a, b in zip(tot, out)
            )
        if tot is None:
            raise ValueError("no chunks to evaluate")
        loss_sum, correct, wsum, pos, neg = jax.device_get(tot)
        out = {
            "logloss": float(loss_sum / max(wsum, 1e-12)),
            "accuracy": float(correct / max(wsum, 1e-12)),
        }
        # AUC only for probability-calibrated scores (matching
        # evaluate_stream): margin losses produce unbounded scores whose
        # [0,1]-binned histogram would mass-tie at the edge bins
        if kind in ("binary_logistic", "logistic"):
            auc = _auc_from_hists(np.asarray(pos), np.asarray(neg))
            if auc is not None:
                out["auc"] = auc
        return out


#: spill serialization order of the touched-row plan's arrays ('val' only
#: in value-weighted mode) — shared with the DiskChunkCache record layout
_PLAN_ORDER = ("row", "seg", "uniq", "inv", "val")
#: spill order of the PACKED plan's u32 carriers (cache_dtype='packed')
_PLAN_PACKED_ORDER = ("rowp", "segb", "uniqp", "invp")


@dataclasses.dataclass(frozen=True)
class _ChunkCodec:
    """STATIC description of a fit's compressed chunk layout — a hashable
    jit argument resolved once at fit entry (``resolve_chunk_codec``), so
    the compile cache is keyed on the resolution, never on the env var.
    ``None`` stands for the legacy f32 layout everywhere."""

    mode: str             # 'bf16' | 'packed'
    label_in_chunk: bool
    n_dense: int
    n_cat: int
    n_dims: int
    label_u8: bool        # classification labels stored u8 (exact)
    impute: bool          # NaN -> 0 semantics live in the decode

    @property
    def idx_bits(self) -> int:
        return bit_width(self.n_dims)

    @property
    def cat_words(self) -> int:
        return -(-(self.n_cat * self.idx_bits) // 32)


def resolve_chunk_codec(p: HashedLinearParams,
                        session: TpuSession | None = None):
    """The concrete cache codec for this fit — THE one resolver (the
    ``resolve_optim_update`` convention; ``OTPU_CACHE_DTYPE=f32`` is the
    kill-switch back to the legacy layout). Returns ``None`` for f32."""
    mode = resolve_cache_dtype(p.cache_dtype, session)
    if mode == "f32" or p.value_weighted:
        # vw chunks are explicit (idx, val) PAIRS with their own -1/0
        # padding convention — kept f32 (see the Params docstring)
        return None
    impute = _impute_flag(p)
    if mode == "packed" and not impute and p.n_cat:
        # missing='keep': NaN codes must reach the in-jit hash and poison
        # visibly; pre-hash packing would silently launder them
        mode = "bf16"
    kind = _row_loss_kind(p)
    return _ChunkCodec(
        mode=mode, label_in_chunk=p.label_in_chunk, n_dense=p.n_dense,
        n_cat=p.n_cat, n_dims=p.n_dims,
        # classification labels are small ints — u8-exact — but only
        # while every class id fits a byte: a 300-class logistic fit
        # keeps f32 labels instead of refusing the compressed cache
        label_u8=(p.label_in_chunk
                  and (kind in ("binary_logistic", "hinge", "squared_hinge")
                       or (kind == "logistic" and p.n_classes <= 256))),
        impute=impute,
    )


def _encode_chunk_np(codec: _ChunkCodec, Xp: np.ndarray,
                     salts_np: np.ndarray,
                     idx: np.ndarray | None = None) -> dict:
    """Host-side encode of one PADDED chunk on the prefetch thread: the
    dict this returns is what the HBM cache, the disk spill and the h2d
    DMA all carry — compressed bytes, decoded only inside the step.
    ``idx``: the pre-hashed [N, C] indices when the caller already built
    them (the sparse-plan path shares ONE host hash per chunk)."""
    off = 1 if codec.label_in_chunk else 0
    enc = {}
    if codec.label_in_chunk:
        lab = Xp[:, 0]
        if codec.label_u8:
            lab8 = lab.astype(np.uint8)
            if not np.array_equal(lab8.astype(np.float32), lab):
                raise ValueError(
                    "cache_dtype compression stores classification labels "
                    "as u8, but a label is not an integer in [0, 255] — "
                    "soft/duplicated-range labels need cache_dtype='f32' "
                    "(or OTPU_CACHE_DTYPE=f32)"
                )
            enc["y"] = lab8
        else:
            enc["y"] = np.ascontiguousarray(lab, np.float32)
    if codec.n_dense:
        enc["dense"] = np.asarray(
            Xp[:, off:off + codec.n_dense]).astype(BF16)
    cats = Xp[:, off + codec.n_dense:]
    if codec.mode == "packed":
        if idx is None:
            if codec.impute:
                cats = np.where(np.isnan(cats), np.float32(0.0), cats)
            idx = hash_columns_np(cats, salts_np, codec.n_dims)
        enc["cats"] = pack_rows_np(idx, codec.idx_bits)
    else:
        enc["cats"] = np.ascontiguousarray(cats, np.float32)
    return enc


def _decode_chunk(codec: _ChunkCodec, enc: dict, n_valid, y, w, salts):
    """In-jit decode: compressed blocks -> (yv, dense f32, idx i32, wv).
    A widen-on-load XLA fuses into the consumers (the embedding gather,
    the dense matmul) — HBM holds compressed bytes, the math stays f32.
    The packed mode's indices were pre-hashed on the host (the host twin
    is pinned bit-identical to ``hash_columns``), so the step skips the
    hash entirely; bf16 mode hashes exactly as the legacy step does."""
    N = enc["cats"].shape[0]
    if codec.label_in_chunk:
        yv = enc["y"].astype(jnp.float32)
        wv = (jnp.arange(N, dtype=jnp.int32) < n_valid).astype(jnp.float32)
    else:
        yv, wv = y, w
    if codec.n_dense:
        dense = enc["dense"].astype(jnp.float32)
        if codec.impute:
            dense = jnp.where(jnp.isnan(dense), 0.0, dense)
    else:
        dense = jnp.zeros((N, 0), jnp.float32)
    if codec.mode == "packed":
        idx = unpack_rows(enc["cats"], codec.idx_bits, codec.n_cat)
    else:
        cats = enc["cats"]
        if codec.impute:
            cats = jnp.where(jnp.isnan(cats), 0.0, cats)
        idx = hash_columns(cats, salts, codec.n_dims)
    return yv, dense, idx, wv


def _put_encoded(enc: dict, session: TpuSession) -> dict:
    """Device-put an encoded block dict: [N] vectors on the vector
    sharding, [N, k] blocks row-sharded — compressed bytes over the DMA.
    THE one leaf->sharding rule: fit ingest, disk replay and the warm
    builders must produce identical avals or the warm compiles miss."""
    return {k: put_sharded(v, session.row_sharding if v.ndim == 2
                           else session.vector_sharding)
            for k, v in enc.items()}


def _chunk_field_specs(p: HashedLinearParams, codec, pad_rows: int) -> tuple:
    """Ordered (name, shape, dtype) of one spill record's CHUNK payload —
    the one authority the spill writer/reader and the warm-path builders
    share (plan fields, when the sparse 'plan' lowering is active, append
    after these via ``_plan_store_specs``)."""
    if codec is None:
        n_cols = _chunk_cols(p)
        fields = [("x", (pad_rows, n_cols), np.dtype(np.float32))]
        if not p.label_in_chunk:
            fields += [("yv", (pad_rows,), np.dtype(np.float32)),
                       ("wv", (pad_rows,), np.dtype(np.float32))]
        return tuple(fields)
    fields = []
    if codec.label_in_chunk:
        fields.append(("y", (pad_rows,),
                       np.dtype(np.uint8 if codec.label_u8 else np.float32)))
    if codec.n_dense:
        fields.append(("dense", (pad_rows, codec.n_dense), np.dtype(BF16)))
    if codec.mode == "packed":
        fields.append(("cats", (pad_rows, codec.cat_words),
                       np.dtype(np.uint32)))
    else:
        fields.append(("cats", (pad_rows, codec.n_cat),
                       np.dtype(np.float32)))
    if not codec.label_in_chunk:
        fields += [("yv", (pad_rows,), np.dtype(np.float32)),
                   ("wv", (pad_rows,), np.dtype(np.float32))]
    return tuple(fields)


def _plan_store_specs(p: HashedLinearParams, codec, pad_rows: int) -> tuple:
    """Ordered (name, shape, dtype) of the plan's spill fields — packed
    u32 carriers under the 'packed' codec, raw i32 (+ f32 'val') else."""
    if codec is not None and codec.mode == "packed":
        d = plan_packed_field_shapes(pad_rows, p.n_cat, p.n_dims)
        return tuple((k, d[k][0], np.dtype(d[k][1]))
                     for k in _PLAN_PACKED_ORDER)
    shapes = plan_field_shapes(pad_rows, p.n_cat, p.n_dims, p.value_weighted)
    return tuple(
        (k, shapes[k],
         np.dtype(np.float32 if k == "val" else np.int32))
        for k in _PLAN_ORDER if k in shapes
    )


def _plan_device_form(codec, plan_np: dict, pad_rows: int,
                      p: HashedLinearParams) -> dict:
    """The plan dict as it travels with the chunk (cache/spill/device):
    bit-packed under the 'packed' codec, raw otherwise."""
    if codec is not None and codec.mode == "packed":
        return pack_plan_np(plan_np, pad_rows, p.n_cat, p.n_dims)
    return plan_np


def _raw_chunk_bytes(p: HashedLinearParams, pad_rows: int,
                     sparse_plan: bool) -> int:
    """f32-layout bytes of one cached chunk (+ its raw plan) — the
    denominator of the bench's ``compression_ratio`` and the legacy term
    in capacity estimates."""
    n = pad_rows * _chunk_cols(p) * 4
    if not p.label_in_chunk:
        n += 2 * pad_rows * 4
    if sparse_plan:
        shapes = plan_field_shapes(pad_rows, p.n_cat, p.n_dims,
                                   p.value_weighted)
        n += 4 * sum(int(np.prod(s)) for s in shapes.values())
    return n


def estimate_cached_chunk_bytes(p: HashedLinearParams,
                                session: TpuSession) -> int:
    """Per-chunk HBM cache bytes under the RESOLVED codec/lowering — the
    estimate bench.py's overflow/fusion pre-gates use; it must agree with
    what ``fit_stream``'s cache accounting will actually see or the two
    gates disagree in a boundary window."""
    pad_rows = session.pad_rows(p.chunk_rows)
    codec = resolve_chunk_codec(p, session)
    optim = resolve_optim_update(p.optim_update)
    sparse_plan = (is_sparse_update(optim)
                   and resolve_sparse_lowering(p.sparse_lowering) == "plan")
    specs = _chunk_field_specs(p, codec, pad_rows)
    if sparse_plan:
        specs = specs + _plan_store_specs(p, codec, pad_rows)
    return sum(int(np.prod(s)) * dt.itemsize for _, s, dt in specs)


def warm_eval_chunk(p: HashedLinearParams, session: TpuSession) -> tuple:
    """A zero device chunk in the fit's CACHE layout (encoded under the
    resolved codec) — bench.py warms the eval program against it so the
    eval compile never lands inside the timed window. Mirrors the fit's
    salts derivation so the encode path is byte-compatible."""
    pad_rows = session.pad_rows(p.chunk_rows)
    codec = resolve_chunk_codec(p, session)
    Xp0 = np.zeros((pad_rows, _chunk_cols(p)), np.float32)
    if codec is None:
        Xd = put_sharded(Xp0, session.row_sharding)
    else:
        # codec is never active for value_weighted fits (resolve_chunk_codec
        # returns None there), so the fit's plain per-column salts apply
        salts_np = column_salts(p.n_cat, p.seed)
        Xd = _put_encoded(_encode_chunk_np(codec, Xp0, salts_np), session)
    if p.label_in_chunk:
        zy = zw = jnp.zeros((1,), jnp.float32)
    else:
        zy = put_sharded(np.zeros((pad_rows,), np.float32),
                         session.vector_sharding)
        zw = zy
    return (Xd, jnp.int32(1), zy, zw)


def _chunk_cols(p: HashedLinearParams) -> int:
    """Expected chunk width — THE one place that knows the layout:
    [label?] + (idx..., val...) pairs in value-weighted mode, or
    [label?] + dense + categorical columns otherwise."""
    return ((2 if p.value_weighted else 1) * p.n_cat + p.n_dense
            + (1 if p.label_in_chunk else 0))


def _init_fit_state(p: HashedLinearParams, session: TpuSession):
    """Fresh (theta, opt_state, salts_np, salts_dev, static_kw) exactly as a
    fit starts — shared by fit_stream and warm_replay so the warm program's
    avals/statics can never drift from the real fit's (a silent-drift bug
    class: a mismatch just misses the jit cache and moves the scan compile
    back into the timed fit)."""
    k = _effective_k(p)
    theta = {
        "emb": jnp.zeros((p.n_dims, k), jnp.float32),
        "coef": jnp.zeros((p.n_dense, k), jnp.float32),
        "intercept": jnp.zeros((k,), jnp.float32),
    }
    if session.model_axis is not None and \
            session.mesh.shape.get(session.model_axis, 1) > 1:
        # model-parallel embedding: the table (the one large parameter)
        # shards its rows over 'model' — P('model', None) — so HBM holds
        # 1/mp of it per device; GSPMD turns the in-jit gather/scatter
        # into collective-assisted lookups over ICI. Adam state inherits
        # the placement via zeros_like.
        theta["emb"] = jax.device_put(
            theta["emb"], session.sharding(session.model_axis, None)
        )
    optim = resolve_optim_update(p.optim_update)
    lowering = (resolve_sparse_lowering(p.sparse_lowering)
                if is_sparse_update(optim) else "none")
    if optim == "adam":
        opt_state = _ADAM_UNIT.init(theta)
    else:
        opt_state = init_optim_state(optim, theta)
    if p.value_weighted:
        # position-INDEPENDENT hashing: libsvm-style sources pack
        # (idx, val) pairs positionally, so every slot must share ONE salt
        # or a single feature fragments across slot-dependent buckets
        salts_np = np.repeat(column_salts(1, p.seed), p.n_cat)
    else:
        salts_np = column_salts(p.n_cat, p.seed)
    salts = jax.device_put(salts_np, session.replicated)
    if p.value_weighted and p.n_dense:
        raise ValueError(
            "value_weighted mode carries (index, value) pairs only — "
            f"n_dense must be 0, got {p.n_dense}"
        )
    static_kw = dict(
        loss_kind=_row_loss_kind(p), n_dims=p.n_dims, n_dense=p.n_dense,
        compute_dtype=jnp.dtype(p.compute_dtype),
        label_in_chunk=p.label_in_chunk, emb_update=resolve_emb_update(p),
        value_weighted=p.value_weighted, impute_missing=_impute_flag(p),
        optim_update=optim, sparse_lowering=lowering,
        # static decay gate: reg == 0 compiles the sparse step without the
        # timestamp gathers/pow (and ftrl owns its L2 in closed form)
        use_decay=(p.reg_param != 0.0 and optim_kind(optim) != "ftrl"),
        # cache codec (io/codec.py): resolved HERE, once, like the
        # optimizer rule — the OTPU_CACHE_DTYPE kill-switch can never
        # poison the jit cache key space mid-process
        codec=resolve_chunk_codec(p, session),
    )
    return theta, opt_state, salts_np, salts, static_kw


class StreamingHashedLinearEstimator(Estimator):
    """Out-of-core hashed-sparse fit over (fastcsv) chunk streams.

    ``fit_stream(source)`` consumes chunks of ``(Xall [n, n_dense+n_cat], y)``
    — exactly what ``io.streaming.csv_chunk_source`` yields — or, with
    ``label_in_chunk=True``, raw ``[n, 1+n_dense+n_cat]`` arrays from
    ``csv_raw_chunk_source``. The full Criteo pipeline is therefore:
    ``csv_raw_chunk_source(path) -> fit_stream -> model.evaluate_device``.
    """

    ParamsCls = HashedLinearParams
    params: HashedLinearParams

    def _fit(self, table):  # Estimator protocol: in-memory fallback
        from orange3_spark_tpu.io.streaming import array_chunk_source
        from orange3_spark_tpu.models.base import infer_class_values

        if self.params.value_weighted:
            # a TpuTable's feature matrix is DENSE columns, never the
            # (idx..., val...) pair layout — feeding it through would hash
            # feature VALUES as indices and train a nonsense model
            raise ValueError(
                "value_weighted fits consume (index, value) pair chunks "
                "(io.libsvm.libsvm_chunk_source) via fit_stream, not "
                "dense tables"
            )
        X, Y, W = table.to_numpy()
        y = Y[:, 0] if Y is not None else None
        class_values = (
            infer_class_values(table) if self.params.loss == "logistic" else None
        )
        return self.fit_stream(
            array_chunk_source(X, y, W, chunk_rows=self.params.chunk_rows),
            session=table.session,
            class_values=class_values,
        )

    def warm_replay(self, n_chunks: int, *,
                    session: TpuSession | None = None):
        """Pre-compile the fused replay program for a fit whose cache will
        hold ``n_chunks`` train chunks, so a subsequent (timed) fit_stream
        hits the jit cache instead of paying the scan compile mid-fit.
        ``n_epochs`` and the chunk-stack shape are static to that program,
        so the warm shapes must match the real fit's (bench.py computes
        n_chunks = total chunks - holdout chunks). Device-side zeros only —
        one chunk-sized host transfer, no data pass.

        Returns ``(theta, salts_np)`` from the executed warm scan (or None
        when no replay program applies): scan-OUTPUT provenance, which is
        exactly what a defer fit's post-fit ``evaluate_device`` sees — so a
        caller can warm the eval program against it and hit the jit cache
        in the timed run (bench.py does).

        The warmed program mirrors ``defer_epoch1`` as configured on the
        params; the subsequent fit must use the SAME effective schedule.
        With ``replay_granularity='epoch'`` a checkpointered defer fit
        keeps the fused schedule (epoch-boundary snapshots), so warming it
        is correct; with granularity 'all' a checkpointered fit silently
        falls back to the default schedule (as does any fit without
        cache_device), and the warm would compile a program that fit
        never dispatches."""
        p = self.params
        from orange3_spark_tpu.io.streaming import check_replay_granularity

        check_replay_granularity(p.replay_granularity)
        session = session or TpuSession.active()
        if not (p.fused_replay and (p.epochs > 1 or p.defer_epoch1)
                and n_chunks > 0):
            return None
        n_cols = _chunk_cols(p)
        pad_rows = session.pad_rows(p.chunk_rows)
        theta, opt, salts_np, salts, kw = _init_fit_state(p, session)
        codec = kw["codec"]
        # one zero chunk through the SAME encode + device-put path as the
        # real fit, so the stacked avals (incl. dtypes/shardings of the
        # compressed blocks) match the timed run's
        Xp0 = np.zeros((pad_rows, n_cols), np.float32)
        if codec is None:
            z = put_sharded(Xp0, session.row_sharding)
        else:
            z = _put_encoded(_encode_chunk_np(codec, Xp0, salts_np),
                             session)
        nv = jnp.int32(pad_rows)
        if p.label_in_chunk:
            zy = zw = jnp.zeros((1,), jnp.float32)
        else:
            zy = put_sharded(np.zeros((pad_rows,), np.float32),
                             session.vector_sharding)
            zw = zy
        plan = None
        if kw["sparse_lowering"] == "plan":
            # the zero chunk's touched-row plan, through the same builder
            # as the real fit (zero codes hash to one bucket per column —
            # the skew is irrelevant to the compiled shapes)
            zc = np.zeros((pad_rows, p.n_cat), np.float32)
            plan_np0 = build_plan_np(
                zc, salts_np, p.n_dims, pad_rows,
                vals=(np.zeros((pad_rows, p.n_cat), np.float32)
                      if p.value_weighted else None),
                impute_missing=kw["impute_missing"])
            plan = jax.device_put(
                _plan_device_form(codec, plan_np0, pad_rows, p),
                session.replicated)
        l1 = jnp.float32(p.l1_param)
        if not p.defer_epoch1:
            # theta/opt must have step-OUTPUT provenance (GSPMD-placed),
            # like the real replay's inputs after a per-chunk epoch 1. A
            # defer fit hands the replay _init_fit_state outputs directly,
            # so its warm must NOT run a step — which also keeps the warm
            # phase free of the step-then-scan sequence the round-4 device
            # fault needs.
            theta, opt, _ = _hashed_step(
                theta, opt, z, nv, zy, zw, salts,
                jnp.float32(p.reg_param), jnp.float32(p.step_size),
                plan, l1, **kw)
        n_rep = p.epochs - 1 + (1 if p.defer_epoch1 else 0)
        stacks = (
            jax.tree.map(lambda a: jnp.stack([a] * n_chunks), z),
            jnp.stack([nv] * n_chunks),
            jnp.stack([zy] * n_chunks), jnp.stack([zw] * n_chunks),
        )
        if plan is not None:
            stacks = stacks + (jax.tree.map(
                lambda a: jnp.stack([a] * n_chunks), plan),)
        theta, opt, losses = _hashed_replay_epochs(
            theta, opt, stacks, salts,
            jnp.float32(p.reg_param), jnp.float32(p.step_size), l1,
            # 'epoch' granularity dispatches n_epochs=K scans (the
            # epochs_per_dispatch group size, clamped to the replay span)
            n_epochs=(min(max(1, p.epochs_per_dispatch), n_rep)
                      if p.replay_granularity == "epoch" else n_rep),
            **kw)
        jax.block_until_ready(losses)
        return theta, np.asarray(salts)

    @traced("fit", model="hashed_linear")
    def fit_stream(
        self,
        source: Callable[[], Iterator],
        *,
        session: TpuSession | None = None,
        class_values: tuple | None = None,
        checkpointer=None,
        cache_device: bool = False,
        cache_device_bytes: int = 8 << 30,
        cache_spill_dir: str | None = None,
        holdout_chunks: int = 0,
        stage_times: dict | None = None,
    ) -> HashedLinearModel:
        """Fit over a re-iterable chunk source.

        cache_device: retain device-put chunks in HBM and replay them for
          epochs 2+ (Spark's ``persist()`` before MLlib's iterative fit).
          If the stream outgrows ``cache_device_bytes`` the fit degrades
          (no partial replay — see the module docstring): with
          ``cache_spill_dir`` set, epochs 2+ replay padded records
          (encoded per ``cache_dtype``) from an on-disk cache written
          during epoch 1 (read + DMA, no re-parse — the 1B-row regime);
          without it, every epoch re-runs
          the source, which for a CSV source means re-PARSING the file
          per epoch — a loud ``warnings.warn`` says so once. The cached
          chunk list is exposed on the returned model as
          ``model.device_chunks_``.
        cache_spill_dir: directory for the epoch-1 disk spill (written on
          the prefetch thread, sequential f32, released when the fit
          returns). The write happens during epoch 1 WHETHER OR NOT the
          cache ends up overflowing (the overflow point is unknowable
          mid-stream, and device->host readback to recover dropped
          chunks is the slowest path on tunneled hosts) — arm it when
          the dataset is expected to exceed ``cache_device_bytes``, as
          bench.py does from its known row count.
        holdout_chunks: exclude the LAST n device batches of each epoch from
          training; with cache_device they are retained (and exposed as
          ``model.holdout_chunks_``) for ``evaluate_device``.
        stage_times: optional dict that receives host-side stage seconds
          ('parse_s', 'h2d_s' — accumulated on the PREFETCH thread, so they
          overlap device work and may sum past wall) plus 'epoch_s', the
          measured phase walls. With ``fused_replay`` off this is one wall
          per epoch (epoch 1 = streaming, later cached epochs = pure
          device); with it ON (the default) epochs 2+ run as ONE fused
          dispatch, so 'epoch_s' is ``[epoch1_wall, whole_replay_wall]``
          and 'replay_fused_s' carries that second number explicitly.
        """
        from orange3_spark_tpu.io.streaming import (
            DiskChunkCache, _pad_chunk, _rechunk, check_replay_granularity,
            epoch_boundary_snapshot, resolve_epoch_checkpointing,
            warn_cache_overflow,
        )

        p = self.params
        check_replay_granularity(p.replay_granularity)
        # the run report rides the OTPU_OBS kill-switch (its two counter
        # snapshots are this path's only per-fit obs cost)
        report = (RunReport("fit_stream", estimator=type(self).__name__,
                            n_dims=p.n_dims, epochs=p.epochs)
                  if obs_enabled() else None)
        # goodput accountant (obs/prof.py): per-epoch bottleneck
        # classification + the five-way wall decomposition; None under
        # OTPU_PROF=0 (every downstream hook no-ops on the contextvar)
        acc = prof.begin_fit()
        session = session or TpuSession.active()
        k = _effective_k(p)
        n_cols = _chunk_cols(p)
        theta, opt_state, salts_np, salts, static_kw = _init_fit_state(
            p, session
        )
        # device-memory ledger: the table + optimizer slots are the
        # other big HBM tenant beside the chunk cache — named so an
        # OOM-adjacent post-mortem can tell table growth from cache
        # growth. Re-set to theta-only at fit end (slots die with the
        # fit); released when the fitted model itself dies.
        state_key = f"hashed-{next(_FIT_LEDGER_SEQ)}"
        prof.ledger_set("model_state", state_key,
                        prof.tree_device_bytes((theta, opt_state)))
        # frame-scoped guard: a fit that ABORTS (divergence, wedge,
        # retry exhaustion) must not strand its model_state entry — the
        # guard's death releases it; the success tail detaches it and
        # hands ownership to the model's own finalizer
        _state_guard = prof.ledger_guard("model_state", state_key)
        resume_from = 0
        ckpt_meta = {"params": p.to_dict(), "k": k}
        # epoch-cadence snapshots (checkpoint_every_epochs): the shared
        # arming rule — see StreamingLinearParams for the contract
        ckpt_epochs = resolve_epoch_checkpointing(p, checkpointer)
        if checkpointer is not None:
            step0, saved = checkpointer.load(expect_meta=ckpt_meta)
            if saved is not None:
                theta = jax.tree.map(jnp.asarray, saved["theta"])
                opt_state = jax.tree.map(
                    lambda tmpl, v: jnp.asarray(v)
                    if isinstance(tmpl, (jax.Array, np.ndarray)) else v,
                    opt_state, saved["opt_state"],
                )
                resume_from = step0

        pad_rows = session.pad_rows(p.chunk_rows)
        row_sh = session.row_sharding
        vec_sh = session.vector_sharding
        reg = jnp.float32(p.reg_param)
        lr = jnp.float32(p.step_size)
        l1 = jnp.float32(p.l1_param)
        # sparse-optimizer plumbing (optim/ subsystem): under the 'plan'
        # lowering every device chunk carries its host-presorted
        # touched-row plan as a 5th tuple element — built once on the
        # prefetch thread, cached/spilled/stacked alongside the chunk
        optim_resolved = static_kw["optim_update"]
        sparse_plan = static_kw["sparse_lowering"] == "plan"
        # cache codec (io/codec.py), resolved once in _init_fit_state: all
        # storage surfaces — HBM cache, disk spill, h2d DMA — carry the
        # encoded blocks; decode happens inside the jitted step
        codec = static_kw["codec"]
        chunk_specs = _chunk_field_specs(p, codec, pad_rows)
        plan_specs = (_plan_store_specs(p, codec, pad_rows)
                      if sparse_plan else ())
        # categorical block offset in the padded chunk ([label?] + dense +
        # cats, or [label?] + idx pairs; n_dense == 0 in vw mode)
        cats_off = (1 if p.label_in_chunk else 0) + p.n_dense
        # stage timings collect for the caller's stage_times= dict AND for
        # the run report (obs/report.py) — under OTPU_OBS=0 with no caller
        # dict, collection reverts to the legacy zero-instrumentation path.
        # honest_walls: only an EXPLICIT stage_times= caller (bench) pays
        # the per-epoch block_until_ready that makes epoch walls exact;
        # report-only collection must not add epoch-boundary device syncs
        # to every default fit
        times = ({"parse_s": 0.0, "h2d_s": 0.0}
                 if stage_times is not None or obs_enabled() else None)
        honest_walls = stage_times is not None
        # fit-level pipeline counters: every prefetch stream (live ingest,
        # disk replay, grouped disk replay) folds in, so overlap_pct is the
        # measured host-prep/device-compute overlap of the WHOLE fit
        pipe_stats = PipelineStats()
        # THE source chokepoint (docs/resilience.md): fault injection +
        # bounded transient-read retries on the prefetch thread; retries
        # count into pipe_stats (the bench line's `retries` field)
        from orange3_spark_tpu.resilience.retry import resilient_source

        source = resilient_source(source, stats=pipe_stats)

        def put_payload(payload):
            """Device-put one chunk payload: the raw [N, cols] array, or
            the encoded block dict via the shared leaf->sharding rule."""
            if codec is None:
                return put_sharded(payload, row_sh)
            return _put_encoded(payload, session)

        def record_arrays(payload, yp, wp, plan_store):
            """Spill-record field tuple in ``chunk_specs``(+``plan_specs``)
            declaration order."""
            if codec is None:
                rec = (payload,) if p.label_in_chunk else (payload, yp, wp)
            else:
                rec = tuple(
                    yp if name == "yv" else wp if name == "wv"
                    else payload[name]
                    for name, _, _ in chunk_specs
                )
            if plan_store is not None:
                rec = rec + tuple(plan_store[name]
                                  for name, _, _ in plan_specs)
            return rec

        def record_to_host(arrays):
            """Typed spill-record views -> (payload, y, w, plan) host
            arrays — the inverse of ``record_arrays``."""
            chunk_arr = arrays[:len(chunk_specs)]
            y_np = w_np = None
            if codec is None:
                payload = np.asarray(chunk_arr[0])
                if not p.label_in_chunk:
                    y_np = np.asarray(chunk_arr[1])
                    w_np = np.asarray(chunk_arr[2])
            else:
                payload = {}
                for (name, _, _), a in zip(chunk_specs, chunk_arr):
                    if name == "yv":
                        y_np = np.asarray(a)
                    elif name == "wv":
                        w_np = np.asarray(a)
                    else:
                        payload[name] = np.asarray(a)
            plan_np = None
            if plan_specs:
                plan_np = {name: np.asarray(a) for (name, _, _), a
                           in zip(plan_specs, arrays[len(chunk_specs):])}
            return payload, y_np, w_np, plan_np

        def to_device(host_chunk):
            """parse-thread side: pad + device_put one chunk."""
            if p.label_in_chunk:
                X_np = host_chunk if isinstance(
                    host_chunk, np.ndarray) else host_chunk[0]
                y_np = w_np = None
            else:
                X_np, y_np, w_np = (tuple(host_chunk) + (None, None))[:3]
            if X_np.shape[1] != n_cols:
                raise ValueError(
                    f"chunk has {X_np.shape[1]} columns, expected {n_cols}"
                )
            n = X_np.shape[0]
            if p.label_in_chunk:
                if n == pad_rows:
                    Xp = np.ascontiguousarray(X_np, dtype=np.float32)
                else:
                    Xp = np.zeros((pad_rows, n_cols), np.float32)
                    Xp[:n] = X_np
                yp = wp = None
            else:
                Xp, yp, wp = _pad_chunk(X_np, y_np, w_np, pad_rows,
                                        n_cols)
            # under the packed codec the chunk's indices are hashed ONCE
            # on this thread and shared by the plan builder and the encode
            idx_np = None
            if codec is not None and codec.mode == "packed":
                c = Xp[:, cats_off:cats_off + p.n_cat]
                if codec.impute:
                    c = np.where(np.isnan(c), np.float32(0.0), c)
                idx_np = hash_columns_np(c, salts_np, p.n_dims)
            plan_np = None
            if sparse_plan:
                # host-presorted touched-row plan (optim/sparse.py) —
                # the stable argsort runs here on the prefetch thread,
                # overlapping device steps, and is replayed every epoch
                t_pl = time.perf_counter() if times is not None else 0.0
                plan_np = build_plan_np(
                    Xp[:, cats_off:cats_off + p.n_cat], salts_np,
                    p.n_dims, n,
                    vals=(Xp[:, cats_off + p.n_cat:]
                          if p.value_weighted else None),
                    impute_missing=static_kw["impute_missing"],
                    idx=idx_np)
                if times is not None:
                    times["plan_s"] = (times.get("plan_s", 0.0)
                                       + time.perf_counter() - t_pl)
            # encode on the prefetch thread (io/codec.py): bf16 / u8 /
            # bit-packed blocks — the cache, the spill AND the DMA all
            # carry the compressed bytes from here on
            payload = Xp
            plan_store = plan_np
            if codec is not None:
                t_en = time.perf_counter()
                payload = _encode_chunk_np(codec, Xp, salts_np, idx=idx_np)
                if plan_np is not None:
                    plan_store = _plan_device_form(codec, plan_np,
                                                   pad_rows, p)
                dt_en = time.perf_counter() - t_en
                pipe_stats.encode_s += dt_en
                if times is not None:
                    times["encode_s"] = times.get("encode_s", 0.0) + dt_en
            if spill_active[0]:
                # sequential write of the already-encoded chunk — still
                # on the prefetch thread, overlapping device steps. Plan
                # arrays ride the same record, typed (packed u32 under
                # the 'packed' codec).
                t_sp = time.perf_counter() if times is not None else 0.0
                spill.append(record_arrays(payload, yp, wp, plan_store), n)
                if times is not None:
                    times["spill_s"] = (times.get("spill_s", 0.0)
                                        + time.perf_counter() - t_sp)
            t0 = time.perf_counter() if times is not None else 0.0
            Xd = put_payload(payload)
            if p.label_in_chunk:
                yd = wd = _ZERO
            else:
                yd = put_sharded(yp, vec_sh)
                wd = put_sharded(wp, vec_sh)
            out = (Xd, jnp.int32(n), yd, wd)
            if plan_store is not None:
                out = out + (jax.device_put(plan_store, session.replicated),)
            if times is not None:
                times["h2d_s"] += time.perf_counter() - t0
            return out

        _ZERO = jnp.zeros((1,), jnp.float32)

        def host_chunks():
            """Rechunked host stream, with parse time attributed."""
            if p.label_in_chunk:
                it = _rechunk(((c, None) for c in source()), pad_rows)
            else:
                it = _rechunk(source(), pad_rows)
            if times is None:
                yield from ((x if not p.label_in_chunk else x[0]) for x in it)
            else:
                while True:
                    t0 = time.perf_counter()
                    try:
                        item = next(it)
                    except StopIteration:
                        return
                    times["parse_s"] += time.perf_counter() - t0
                    yield item if not p.label_in_chunk else item[0]

        def device_chunk_iter():
            from orange3_spark_tpu.io.streaming import prefetch_map

            if p.prefetch_depth > 0:
                yield from prefetch_map(
                    to_device, host_chunks(), depth=p.prefetch_depth,
                    stats_into=pipe_stats,
                )
            else:
                for c in host_chunks():
                    yield to_device(c)

        from orange3_spark_tpu.io.streaming import _DeviceCache

        # device-resident training chunks; shared budget/degrade rule with
        # the other streaming estimators. Enabled even at epochs=1 because
        # the cache doubles as the model's exposed device_chunks_.
        # may_exclude_tail: an over-budget offer within the last
        # holdout_chunks offers may later be excluded (the un-latch); any
        # earlier miss is final and the cache drops the moment that is
        # known, legacy-style
        cache = _DeviceCache(cache_device, cache_device_bytes,
                             may_exclude_tail=holdout_chunks)
        # Defer-epoch-1 schedule (see the Params docstring): the streaming
        # pass is pure ingest and ALL p.epochs training passes run off the
        # cache/spill/stream afterwards. Bit-identical step sequence; the
        # epoch loop below runs one extra iteration to compensate for the
        # step-free pass 0. Falls back silently when its preconditions
        # don't hold. Computed up here because a defer fit has replay
        # passes even at epochs == 1, so the spill/overflow gates below
        # must read `epochs > 1 or defer`.
        #
        # Checkpointing: per-STEP snapshots need per-chunk dispatches, so a
        # checkpointered fit normally keeps the interleaved schedule — but
        # with replay_granularity='epoch' the replay is one dispatch PER
        # EPOCH, which gives a natural epoch-boundary snapshot cadence:
        # defer + checkpointer compose there (resume re-ingests the cache
        # step-free, fast-forwards whole checkpointed epochs, and resumes
        # dispatching — bit-identical, pinned by the kill-and-resume test).
        ckpt_epoch_ok = p.replay_granularity == "epoch"
        defer = (
            p.defer_epoch1 and cache_device and p.epochs > 0
            and (checkpointer is None or ckpt_epoch_ok)
            and (resume_from == 0 or ckpt_epoch_ok)
        )
        spill: DiskChunkCache | None = None
        spill_active = [False]      # toggled by the epoch loop; read by
        #                             to_device on the prefetch thread
        if (cache_device and cache_spill_dir is not None
                and (p.epochs > 1 or defer)):
            # the spill records carry the SAME encoded fields as the HBM
            # cache (typed, versioned header — io/streaming.DiskChunkCache)
            # so spill I/O shrinks with the cache under a compressed codec
            specs = chunk_specs + plan_specs
            spill = DiskChunkCache(cache_spill_dir,
                                   tuple(s for _, s, _ in specs),
                                   tuple(dt for _, _, dt in specs))
            spill_active[0] = True
        use_disk = False
        holdout: list = []         # device-resident holdout chunks
        n_steps = 0
        last_loss = None

        # dispatch-queue depth coupled to the staging depth: queueing more
        # steps than the prefetcher can stage starves nothing and lets the
        # consumer sprint arbitrarily far ahead of the device — which both
        # un-bounds in-flight memory and blinds the overlap measurement
        # (queue-wait only reflects device pace while the consumer is
        # paced by the device)
        step_period = max(2, 2 * p.prefetch_depth)

        def run_step(dev_chunk):
            nonlocal theta, opt_state, n_steps, last_loss
            Xd, n_valid, yd, wd = dev_chunk[:4]
            plan = dev_chunk[4] if len(dev_chunk) > 4 else None
            with span("chunk", n_steps):
                theta, opt_state, loss = _hashed_step(
                    theta, opt_state, Xd, n_valid, yd, wd, salts, reg, lr,
                    plan, l1, **static_kw,
                )
                n_steps += 1
                last_loss = loss
                bound_dispatch(n_steps, loss, period=step_period)
            if checkpointer is not None and not ckpt_epochs:
                checkpointer.maybe_save(
                    n_steps, {"theta": theta, "opt_state": opt_state},
                    meta=ckpt_meta,
                )

        epoch_walls: list = []
        replay_fused_s = None
        # fused replay: epochs 2+ lower to ONE dispatch (see
        # _hashed_replay_epochs). Requires the full cache (same chunk set
        # every epoch) and no per-step checkpoint/resume bookkeeping.
        # The chunk stack is a SECOND device copy of the cache, so fusion
        # only engages while stack+cache fit the cache budget together —
        # past half the budget it falls back to the per-chunk loop.
        fuse_replay = (
            p.fused_replay and cache_device and p.epochs > 1
            and ((checkpointer is None and resume_from == 0)
                 # per-epoch dispatches snapshot/resume at epoch
                 # boundaries — fusion stays available (see defer above)
                 or ckpt_epoch_ok)
        )
        if defer:
            # a defer fit fuses even at epochs == 1 (the single training
            # pass IS the replay)
            fuse_replay = p.fused_replay
        def disk_chunk_iter(start: int = 0):
            """Device feed for an overflow replay epoch: padded records
            straight off the spill memmap (no parsing), prefetch-overlapped
            like the live stream. Skips the holdout tail — those records
            were never trained in epoch 1 either. ``start`` lets the
            grouped path hand its partial tail here."""
            from orange3_spark_tpu.io.streaming import prefetch_map

            def rec_to_device(i):
                arrays, n = spill.read(i)
                payload, y_np, w_np, plan_np = record_to_host(arrays)
                t0 = time.perf_counter() if times is not None else 0.0
                Xd = put_payload(payload)
                if p.label_in_chunk:
                    yd = wd = _ZERO
                else:
                    yd = put_sharded(y_np, vec_sh)
                    wd = put_sharded(w_np, vec_sh)
                out = (Xd, jnp.int32(n), yd, wd)
                if plan_np is not None:
                    out = out + (jax.device_put(plan_np,
                                                session.replicated),)
                if times is not None:
                    times["h2d_s"] += time.perf_counter() - t0
                return out

            idxs = iter(range(start, spill.n_records - holdout_chunks))
            if p.prefetch_depth > 0:
                yield from prefetch_map(rec_to_device, idxs,
                                        depth=p.prefetch_depth,
                                        stats_into=pipe_stats)
            else:
                for i in idxs:
                    yield rec_to_device(i)

        def disk_group_iter(group: int):
            """Grouped feed for fused disk replay: G records stacked into
            one [G, pad_rows, ...] device batch per item — one scan
            dispatch trains the whole group (see the replay branch).
            Yields FULL groups only; the partial tail (a different leading
            shape that would force a second scan compile) goes through the
            per-chunk step, which is already compiled from epoch 1."""
            from orange3_spark_tpu.io.streaming import prefetch_map

            n_train = spill.n_records - holdout_chunks
            n_full = (n_train // group) * group

            def grp_to_device(start):
                g = group
                recs = [spill.read(start + j) for j in range(g)]
                hosts = [record_to_host(r[0]) for r in recs]
                t0 = time.perf_counter() if times is not None else 0.0

                def stack_put(leaves):
                    a = np.stack(leaves)
                    spec = ((None, session.data_axis)
                            + (None,) * (a.ndim - 2))
                    return put_sharded(a, session.sharding(*spec))

                if codec is None:
                    Xs = stack_put([h[0] for h in hosts])
                else:
                    Xs = {k2: stack_put([h[0][k2] for h in hosts])
                          for k2 in hosts[0][0]}
                nv = jnp.asarray([r[1] for r in recs], jnp.int32)
                if p.label_in_chunk:
                    ys = ws = jnp.zeros((g, 1), jnp.float32)
                else:
                    ys = stack_put([h[1] for h in hosts])
                    ws = stack_put([h[2] for h in hosts])
                stacks = (Xs, nv, ys, ws)
                if sparse_plan:
                    plans = [h[3] for h in hosts]
                    stacks = stacks + (jax.device_put(
                        jax.tree.map(lambda *a: np.stack(a), *plans),
                        session.replicated),)
                if times is not None:
                    times["h2d_s"] += time.perf_counter() - t0
                return g, stacks

            starts = iter(range(0, n_full, group))
            if p.prefetch_depth > 0:
                yield from prefetch_map(grp_to_device, starts, depth=1,
                                        stats_into=pipe_stats)
            else:
                for s in starts:
                    yield grp_to_device(s)

        for epoch in span_iter("epoch", range(p.epochs + (1 if defer else 0))):
            t_epoch = time.perf_counter()
            if epoch == 0 or not (cache.enabled or use_disk):
                # stream from the source; a look-ahead window keeps the LAST
                # holdout_chunks device batches out of training
                window: list = []
                for dev_chunk in device_chunk_iter():
                    if epoch == 0:
                        cache.offer(dev_chunk)
                    if holdout_chunks > 0:
                        window.append(dev_chunk)
                        if len(window) <= holdout_chunks:
                            continue
                        dev_chunk = window.pop(0)
                    if epoch == 0 and defer:
                        continue        # ingest-only pass: no step dispatch
                    if n_steps < resume_from:
                        n_steps += 1
                        continue
                    run_step(dev_chunk)
                if epoch == 0 and holdout_chunks > 0:
                    holdout = window[-holdout_chunks:]
                    if cache.enabled:
                        # the tail chunks live in the cache too — they must
                        # never be trained on in replay epochs (exclude()
                        # keeps nbytes honest for the fuse_replay gate) —
                        # and misses confined to this excluded tail never
                        # degrade the run (the un-latch)
                        cache.exclude({id(c[0]) for c in holdout})
                        cache.forgive_tail(holdout_chunks)
                if epoch == 0:
                    spill_active[0] = False   # prefetch thread has exited
                    if spill is not None:
                        spill.finalize()
                    # an incomplete cache drops whole here; one whose
                    # misses were all holdout-excluded keeps replaying
                    # from HBM (the un-latch the exclude() covers)
                    cache.settle()
                    if cache.degraded and (p.epochs > 1 or defer):
                        use_disk = (spill is not None
                                    and spill.n_records > holdout_chunks)
                        if not use_disk:
                            warn_cache_overflow(
                                cache_device_bytes,
                                p.epochs - 1 + (1 if defer else 0),
                                detail=(
                                    "The disk spill has no trainable "
                                    "records (fewer chunks than the "
                                    "holdout tail)."
                                    if spill is not None else
                                    "Set cache_spill_dir= to replay "
                                    "parsed chunks at disk bandwidth "
                                    "instead."
                                ),
                            )
            elif cache.enabled:
                # pure-HBM epoch: replay the cached chunks, no host at all
                for dev_chunk in cache.batches:
                    if n_steps < resume_from:
                        n_steps += 1
                        continue
                    run_step(dev_chunk)
            else:
                # overflow epoch off the disk spill: read + DMA, no parse.
                # When no per-step checkpoint granularity is needed, G
                # records stack into one device batch and train as ONE
                # scan dispatch (_hashed_replay_epochs, n_epochs=1) —
                # dispatch count drops G-fold, which matters on tunneled
                # hosts where each dispatch costs ~hundreds of ms. G is
                # sized so current group + prefetched group + transient
                # scan copies stay inside the cache budget.
                rec_bytes = spill.payload_bytes
                group = max(1, min(spill.n_records,
                                   cache_device_bytes // (4 * rec_bytes)))
                if (p.fused_replay and checkpointer is None
                        and resume_from == 0 and group > 1):
                    if times is not None:
                        times["disk_replay_group"] = group
                    n_groups = 0
                    for g, stacks in disk_group_iter(group):
                        theta, opt_state, losses = _hashed_replay_epochs(
                            theta, opt_state, stacks, salts, reg, lr, l1,
                            n_epochs=1, **static_kw,
                        )
                        n_steps += g
                        n_groups += 1
                        last_loss = losses[-1, -1]
                        # bound by GROUPS, not steps: each in-flight group
                        # dispatch pins a budget/4-byte input stack, so 16
                        # unsynced groups would hold ~4x the cache budget
                        # in HBM; period=2 keeps one executing + one queued
                        # (+ the prefetched next group) <= 3/4 budget
                        bound_dispatch(n_groups, last_loss, period=2)
                    # partial tail group (different leading shape would
                    # recompile the scan): per-chunk steps — compiled in
                    # epoch 1, or on first use here under defer_epoch1
                    n_train_recs = spill.n_records - holdout_chunks
                    for dev_chunk in disk_chunk_iter(
                            start=(n_train_recs // group) * group):
                        run_step(dev_chunk)
                else:
                    for dev_chunk in disk_chunk_iter():
                        if n_steps < resume_from:
                            n_steps += 1
                            continue
                        run_step(dev_chunk)
            # non-finite guard (resilience/numerics.py) BEFORE the save:
            # a divergent epoch raises typed, never checkpoints NaN state
            check_finite_training(
                last_loss, theta, epoch=epoch, chunk=n_steps,
                estimator="StreamingHashedLinearEstimator")
            # epoch-boundary snapshot (checkpoint_every_epochs cadence):
            # the shared save decision covers every epoch path above
            epoch_boundary_snapshot(
                checkpointer, ckpt_epochs, epoch, defer, n_steps,
                resume_from,
                lambda: {"theta": theta, "opt_state": opt_state},
                ckpt_meta,
            )
            if times is not None:
                if honest_walls and last_loss is not None:
                    t_bar = time.perf_counter()
                    jax.block_until_ready(last_loss)  # honest epoch wall
                    # an explicit epoch barrier is synchronization, not
                    # device pace (the periodic sync already charged that)
                    prof.note_sync(time.perf_counter() - t_bar,
                                   barrier=True)
                epoch_walls.append(time.perf_counter() - t_epoch)
            if acc is not None:
                # close the goodput window: per-epoch stage deltas +
                # hysteresis bottleneck classification (obs/prof.py)
                acc.epoch_boundary(
                    epoch,
                    encode_s=pipe_stats.encode_s
                    + (times or {}).get("plan_s", 0.0))
            if (epoch == 0 and fuse_replay and cache.enabled
                    and cache.batches
                    and 2 * cache.nbytes <= cache_device_bytes
                    # epoch-granular resume can only fast-forward WHOLE
                    # epochs; a snapshot written off an epoch boundary
                    # (e.g. by a per-chunk phase of an earlier run whose
                    # fusion gate differed) must take the per-chunk replay
                    # below, which skips at step grain — entering the
                    # fused path would re-apply the partial epoch's steps
                    and resume_from % len(cache.batches) == 0):
                # remaining epochs in one program: stack the cache (HBM->
                # HBM copy; the per-chunk list stays live for evaluate_device
                # / bench probes) and scan
                n_rep = p.epochs - 1 + (1 if defer else 0)
                spe = len(cache.batches)          # steps per replay epoch
                if n_steps + n_rep * spe <= resume_from:
                    # snapshot already covers every replay epoch: skip
                    # without building the (second-HBM-copy) stack; the
                    # model is complete, final_loss_ stays None, and no
                    # replay wall is recorded for this
                    # resume-at-completion edge
                    n_steps += n_rep * spe
                    break
                t_rep = time.perf_counter()
                # stack the WHOLE chunk tuple as one pytree — the 5th
                # (plan) element's dict leaves stack right along under
                # the sparse 'plan' lowering
                stacks = jax.tree.map(
                    lambda *xs: jnp.stack(xs), *cache.batches)
                # the stack is a SECOND device copy of the cache (chunk
                # arrays + sparse plans) — a distinct ledger tenant for
                # exactly as long as it lives. Name keyed per FIT (two
                # concurrent replays must not share one entry); the
                # guard releases on an aborted replay (device OOM while
                # holding the copy is THE likely failure here), the
                # explicit release below makes its firing a no-op
                rp_key = f"replay_stack-{state_key}"
                _rp_guard = prof.ledger_guard("replay_plans", rp_key)
                prof.ledger_set("replay_plans", rp_key,
                                prof.tree_device_bytes(stacks))
                if p.replay_granularity == "epoch":
                    # one n_epochs=1 scan dispatch per epoch over the same
                    # stack — the tunnel-fragility middle ground (see the
                    # Params docstring). Epoch boundaries are the
                    # snapshot/resume grain; the skip/save protocol is the
                    # shared run_epoch_replay.
                    from orange3_spark_tpu.io.streaming import (
                        run_epoch_replay,
                    )

                    def _disp(n_ep):
                        nonlocal theta, opt_state
                        theta, opt_state, chunk_losses = \
                            _hashed_replay_epochs(
                                theta, opt_state, stacks, salts, reg, lr,
                                l1, n_epochs=n_ep, **static_kw,
                            )
                        return chunk_losses[-1, -1]

                    n_steps, last, _ = run_epoch_replay(
                        n_rep, spe, n_steps, resume_from, checkpointer,
                        _disp,
                        lambda: {"theta": theta, "opt_state": opt_state},
                        ckpt_meta,
                        epochs_per_dispatch=p.epochs_per_dispatch,
                        every_epochs=ckpt_epochs,
                    )
                    if last is not None:
                        last_loss = last
                else:
                    theta, opt_state, chunk_losses = _hashed_replay_epochs(
                        theta, opt_state, stacks, salts, reg, lr, l1,
                        n_epochs=n_rep, **static_kw,
                    )
                    count_dispatch()   # one-shot fused scan: no loop ticks
                    last_loss = chunk_losses[-1, -1]
                    n_steps += n_rep * spe
                del stacks
                prof.ledger_release("replay_plans", rp_key)
                t_bar = time.perf_counter()
                jax.block_until_ready(last_loss)
                # this block drains the WHOLE fused replay — it is the
                # one place the driver observes the replay's device
                # compute, so it charges device_compute, not sync_wait
                prof.note_sync(time.perf_counter() - t_bar)
                replay_fused_s = time.perf_counter() - t_rep
                if acc is not None:
                    acc.epoch_boundary(
                        p.epochs - 1,
                        encode_s=pipe_stats.encode_s
                        + (times or {}).get("plan_s", 0.0))
                if times is not None:
                    epoch_walls.append(replay_fused_s)
                break

        if spill is not None:
            spill.delete()
        # fused replay breaks out past the per-epoch guard: final check
        # (loss AND theta — a last-step divergence only shows in theta)
        check_finite_training(
            last_loss, theta, epoch=p.epochs - 1, chunk=n_steps,
            final=True, estimator="StreamingHashedLinearEstimator")
        if is_sparse_update(optim_resolved):
            # settle the lazy decay the table still owes (rows untouched
            # since their last step) so the returned model equals the
            # dense schedule's — predictions/serving read theta directly
            theta = finalize_lazy_decay(
                theta, opt_state, p.step_size, p.reg_param, optim_resolved)
        if times is not None:
            st = dict(times)
            # the resolved lowerings, so A/B records are self-describing
            # (the 'auto' decisions are otherwise invisible post-hoc)
            st["emb_update"] = static_kw["emb_update"]
            st["optim_update"] = optim_resolved
            st["sparse_lowering"] = static_kw["sparse_lowering"]
            # cache economics (io/codec.py): what the HBM cache actually
            # held, and what the same chunks would cost at f32 — the
            # bench's compression_ratio/capacity fields read these
            st["cache_dtype"] = codec.mode if codec else "f32"
            if cache_device:
                st["cache_bytes"] = cache.nbytes
                st["cache_chunks"] = len(cache.batches)
                st["cache_raw_bytes"] = (
                    len(cache.batches)
                    * _raw_chunk_bytes(p, pad_rows, sparse_plan))
            st["epoch_s"] = [round(t, 3) for t in epoch_walls]
            if pipe_stats.items:
                # measured prefetch overlap (exec/pipeline.py): 100% = all
                # host prep hidden behind device work, 0% = serial
                st["overlap_pct"] = round(pipe_stats.overlap_pct, 1)
                st["prefetch_prep_s"] = round(pipe_stats.prep_s, 3)
                st["prefetch_wait_s"] = round(pipe_stats.wait_s, 3)
            if replay_fused_s is not None:
                # one wall for ALL replay epochs (single fused dispatch)
                st["replay_fused_s"] = round(replay_fused_s, 3)
            st["cache_overflow"] = cache.degraded
            st["replay_source"] = (
                None if (p.epochs <= 1 and not defer)
                else ("fused" if p.replay_granularity != "epoch"
                      else "fused_epoch") if replay_fused_s is not None
                else "disk" if use_disk
                else "hbm" if cache.enabled
                else "stream"
            )
            # ONE stage dict feeds both consumers: the caller's legacy
            # stage_times= plumbing and the structured run report below
            if report is not None:
                report.stage_times.update(st)
            if stage_times is not None:
                stage_times.update(st)
        model = HashedLinearModel(
            p, theta, salts_np,
            class_values or (tuple(str(i) for i in range(p.n_classes))
                             if p.loss == "logistic" else None),
        )
        model.n_steps_ = n_steps
        model.final_loss_ = float(last_loss) if last_loss is not None else None
        model.device_chunks_ = cache.batches if cache_device else None
        model.holdout_chunks_ = holdout if holdout_chunks > 0 else None
        model.cache_codec_ = codec   # evaluate_device's decode key
        # ledger: the optimizer slots die with the fit — the entry
        # shrinks to the table itself and lives as long as the model
        # (the abort guard hands ownership to the model's finalizer)
        _state_guard.finalizer.detach()
        prof.ledger_set("model_state", state_key,
                        prof.tree_device_bytes(theta))
        import weakref

        weakref.finalize(model, prof.ledger_release_on_gc, "model_state",
                         state_key)
        # freeze the goodput decomposition + the ledger view into the
        # report's goodput/device_memory sections (obs/prof.py);
        # cache_key names THIS fit's cache entry so the bench can
        # cross-check it against the legacy cache_bytes stage key
        prof.attach_fit_report(
            report, acc,
            encode_s=pipe_stats.encode_s + (times or {}).get("plan_s", 0.0),
            cache_key=cache.ledger_key)
        if report is not None:
            model.run_report_ = report.add(n_steps=n_steps).finish()
        if checkpointer is not None:
            checkpointer.delete()
        return model
