"""Hashed-sparse linear models — the Criteo-scale categorical path.

BASELINE config 2 (the headline metric) is Criteo click-through: 13 dense
numerics + 26 categoricals hashed to millions of dimensions. A dense design
matrix is unrepresentable; MLlib fits it as a SparseVector pipeline
(FeatureHasher -> LogisticRegression over treeAggregate; SURVEY.md §2b rows
"Distributed dataframe"/"LogReg"; reconstructed, mount empty).

TPU-native redesign — fixed-nnz-per-row, not CSR:

* every row has EXACTLY n_cat categorical slots (Criteo's shape), so the
  sparse structure is two static-shape arrays: raw codes [N, C] (hashed to
  indices on device, ops/hashing.py) and an embedding table [n_dims, k].
  Static shapes mean ONE compiled step for the whole stream — CSR's ragged
  rows would force re-compilation or host-side bucketing.
* the forward is an embedding gather ``take(emb, idx)`` + a dense matmul for
  the numeric block; the backward is XLA's scatter-add. No SpMV kernel to
  hand-write — gather/scatter are native TPU ops.
* the chunk arrives as ONE [N, n_dense+n_cat] f32 array straight from
  fastcsv (ints < 2^24 are exact in f32), so the host does zero per-cell
  work and the transfer is a single DMA; dense/categorical split happens
  inside the jit.
* data parallelism: rows sharded P('data'); the embedding table is
  replicated (8 MB at 2^20 x 2) and its gradient all-reduces over ICI by
  GSPMD — treeAggregate without the shuffle.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
import optax

from orange3_spark_tpu.core.session import TpuSession
from orange3_spark_tpu.models._linear import EPS_TOTAL_WEIGHT, per_row_loss
from orange3_spark_tpu.models.base import Estimator, Model, Params
from orange3_spark_tpu.ops.hashing import column_salts, hash_columns

# unit-lr adam; the traced lr scales its updates (see io/streaming.py)
_ADAM_UNIT = optax.adam(1.0)


@dataclasses.dataclass(frozen=True)
class HashedLinearParams(Params):
    n_dims: int = 1 << 20        # hashed feature space (power of two)
    n_dense: int = 13            # leading numeric columns (Criteo I1-I13)
    n_cat: int = 26              # trailing categorical columns (C1-C26)
    loss: str = "logistic"       # 'logistic' | 'squared' | 'squared_hinge'
    n_classes: int = 2
    epochs: int = 1
    step_size: float = 0.02
    reg_param: float = 0.0       # L2 on emb + coef
    chunk_rows: int = 1 << 18
    threshold: float = 0.5
    seed: int = 0
    compute_dtype: str = "float32"


def _hashed_logits(theta, dense, idx, compute_dtype):
    emb_rows = jnp.take(theta["emb"].astype(compute_dtype), idx, axis=0)
    logits = jnp.sum(emb_rows, axis=1, dtype=jnp.float32)       # [N, k]
    if theta["coef"].shape[0]:
        logits = logits + jnp.dot(
            dense.astype(compute_dtype),
            theta["coef"].astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
    return logits + theta["intercept"]


@partial(
    jax.jit,
    static_argnames=("loss_kind", "n_dims", "n_dense", "compute_dtype"),
    donate_argnums=(0, 1),
)
def _hashed_step(
    theta, opt_state, Xall, y, w, salts, reg, lr,
    *, loss_kind: str, n_dims: int, n_dense: int, compute_dtype=jnp.float32,
):
    dense = Xall[:, :n_dense]
    idx = hash_columns(Xall[:, n_dense:], salts, n_dims)

    def loss_fn(theta):
        logits = _hashed_logits(theta, dense, idx, compute_dtype)
        row = per_row_loss(loss_kind, logits, y)
        sw = jnp.maximum(jnp.sum(w), EPS_TOTAL_WEIGHT)
        data = jnp.sum(row * w) / sw
        return data + 0.5 * reg * (
            jnp.sum(theta["emb"] ** 2) + jnp.sum(theta["coef"] ** 2)
        )

    loss, g = jax.value_and_grad(loss_fn)(theta)
    updates, opt_state = _ADAM_UNIT.update(g, opt_state, theta)
    updates = jax.tree.map(lambda u: lr * u, updates)
    return optax.apply_updates(theta, updates), opt_state, loss


@partial(jax.jit, static_argnames=("n_dims", "n_dense"))
def _hashed_predict(theta, Xall, salts, *, n_dims: int, n_dense: int):
    dense = Xall[:, :n_dense]
    idx = hash_columns(Xall[:, n_dense:], salts, n_dims)
    return _hashed_logits(theta, dense, idx, jnp.float32)


class HashedLinearModel(Model):
    """Fitted hashed-sparse linear model; predicts on raw (dense+categorical)
    chunks — the hashing travels with the model via its salts."""

    def __init__(self, params: HashedLinearParams, theta, salts, class_values):
        self.params = params
        self.theta = theta            # {'emb': [D,k], 'coef': [dd,k], 'intercept': [k]}
        self.salts = np.asarray(salts, np.uint32)
        self.class_values = tuple(class_values) if class_values else None
        self.n_steps_: int | None = None
        self.final_loss_: float | None = None

    @property
    def state_pytree(self):
        return dict(self.theta)

    def _logits(self, Xall: np.ndarray) -> np.ndarray:
        p = self.params
        out = _hashed_predict(
            self.theta, jnp.asarray(Xall, jnp.float32),
            jnp.asarray(self.salts), n_dims=p.n_dims, n_dense=p.n_dense,
        )
        return np.asarray(out)

    def predict(self, Xall: np.ndarray) -> np.ndarray:
        p = self.params
        logits = self._logits(Xall)
        if p.loss == "logistic":
            if logits.shape[1] == 2:
                prob = 1.0 / (1.0 + np.exp(logits[:, 0] - logits[:, 1]))
                return (prob > p.threshold).astype(np.float32)
            return np.argmax(logits, axis=-1).astype(np.float32)
        if p.loss == "squared":
            return logits[:, 0]
        return (logits[:, 0] > 0).astype(np.float32)  # hinge margins

    def predict_proba(self, Xall: np.ndarray) -> np.ndarray:
        z = self._logits(Xall)
        z = z - z.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)

    def evaluate_stream(self, source: Callable[[], Iterator]) -> dict:
        """Stream logloss + accuracy (+AUC when binary) without collecting
        the dataset: exact running sums, fixed memory."""
        p = self.params
        n = 0
        loss_sum = 0.0
        correct = 0
        # binary AUC via 4096-bin score histograms (rank-sum on bins)
        bins = 4096
        pos_h = np.zeros(bins)
        neg_h = np.zeros(bins)
        for chunk in source():
            Xall, y = chunk[0], chunk[1]
            if y is None:
                raise ValueError("evaluate_stream needs labeled chunks")
            prob = self.predict_proba(Xall)
            yi = np.asarray(y).astype(int)
            pi = np.clip(prob[np.arange(len(yi)), yi], 1e-12, 1.0)
            loss_sum += float(-np.log(pi).sum())
            correct += int((prob.argmax(1) == yi).sum())
            n += len(yi)
            if prob.shape[1] == 2:
                b = np.minimum((prob[:, 1] * bins).astype(int), bins - 1)
                pos_h += np.bincount(b[yi == 1], minlength=bins)
                neg_h += np.bincount(b[yi == 0], minlength=bins)
        out = {"logloss": loss_sum / max(n, 1), "accuracy": correct / max(n, 1)}
        npos, nneg = pos_h.sum(), neg_h.sum()
        if npos and nneg:
            # P(score_pos > score_neg) + 0.5 P(tie), binned
            cum_neg = np.concatenate([[0.0], np.cumsum(neg_h)[:-1]])
            out["auc"] = float(
                (pos_h * (cum_neg + 0.5 * neg_h)).sum() / (npos * nneg)
            )
        return out


class StreamingHashedLinearEstimator(Estimator):
    """Out-of-core hashed-sparse fit over (fastcsv) chunk streams.

    ``fit_stream(source)`` consumes chunks of ``(Xall [n, n_dense+n_cat], y)``
    — exactly what ``io.streaming.csv_chunk_source`` yields — and returns a
    HashedLinearModel. The full Criteo pipeline is therefore:
    ``csv_chunk_source(path, 'label') -> fit_stream -> model.evaluate_stream``.
    """

    ParamsCls = HashedLinearParams
    params: HashedLinearParams

    def _fit(self, table):  # Estimator protocol: in-memory fallback
        from orange3_spark_tpu.io.streaming import array_chunk_source

        X, Y, W = table.to_numpy()
        y = Y[:, 0] if Y is not None else None
        return self.fit_stream(
            array_chunk_source(X, y, W, chunk_rows=self.params.chunk_rows),
            session=table.session,
        )

    def fit_stream(
        self,
        source: Callable[[], Iterator],
        *,
        session: TpuSession | None = None,
        class_values: tuple | None = None,
        checkpointer=None,
    ) -> HashedLinearModel:
        from orange3_spark_tpu.io.streaming import _pad_chunk, _rechunk

        p = self.params
        session = session or TpuSession.active()
        k = p.n_classes if p.loss == "logistic" else 1
        n_cols = p.n_dense + p.n_cat
        theta = {
            "emb": jnp.zeros((p.n_dims, k), jnp.float32),
            "coef": jnp.zeros((p.n_dense, k), jnp.float32),
            "intercept": jnp.zeros((k,), jnp.float32),
        }
        opt_state = _ADAM_UNIT.init(theta)
        salts_np = column_salts(p.n_cat, p.seed)
        salts = jax.device_put(salts_np, session.replicated)
        resume_from = 0
        ckpt_meta = {"params": p.to_dict(), "k": k}
        if checkpointer is not None:
            step0, saved = checkpointer.load(expect_meta=ckpt_meta)
            if saved is not None:
                theta = jax.tree.map(jnp.asarray, saved["theta"])
                opt_state = jax.tree.map(
                    lambda tmpl, v: jnp.asarray(v)
                    if isinstance(tmpl, (jax.Array, np.ndarray)) else v,
                    opt_state, saved["opt_state"],
                )
                resume_from = step0

        pad_rows = session.pad_rows(p.chunk_rows)
        row_sh = session.row_sharding
        vec_sh = session.vector_sharding
        reg = jnp.float32(p.reg_param)
        lr = jnp.float32(p.step_size)
        compute_dtype = jnp.dtype(p.compute_dtype)
        n_steps = 0
        last_loss = None
        for _ in range(p.epochs):
            for X_np, y_np, w_np in _rechunk(source(), pad_rows):
                if n_steps < resume_from:
                    n_steps += 1
                    continue
                if X_np.shape[1] != n_cols:
                    raise ValueError(
                        f"chunk has {X_np.shape[1]} columns, expected "
                        f"n_dense+n_cat={n_cols}"
                    )
                Xp, yp, wp = _pad_chunk(X_np, y_np, w_np, pad_rows, n_cols)
                Xd = jax.device_put(Xp, row_sh)
                yd = jax.device_put(yp, vec_sh)
                wd = jax.device_put(wp, vec_sh)
                theta, opt_state, loss = _hashed_step(
                    theta, opt_state, Xd, yd, wd, salts, reg, lr,
                    loss_kind=p.loss, n_dims=p.n_dims, n_dense=p.n_dense,
                    compute_dtype=compute_dtype,
                )
                n_steps += 1
                last_loss = loss
                if checkpointer is not None:
                    checkpointer.maybe_save(
                        n_steps, {"theta": theta, "opt_state": opt_state},
                        meta=ckpt_meta,
                    )
        model = HashedLinearModel(
            p, theta, salts_np,
            class_values or (tuple(str(i) for i in range(k)) if k > 1 else None),
        )
        model.n_steps_ = n_steps
        model.final_loss_ = float(last_loss) if last_loss is not None else None
        if checkpointer is not None:
            checkpointer.delete()
        return model
