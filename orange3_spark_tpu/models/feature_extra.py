"""Remaining ``pyspark.ml.feature`` parity: RobustScaler, PolynomialExpansion,
DCT, Interaction, ElementwiseProduct, VectorSlicer, IndexToString,
VectorIndexer, VarianceThresholdSelector, ChiSqSelector /
UnivariateFeatureSelector, SQLTransformer, and the two LSH families
(BucketedRandomProjectionLSH, MinHashLSH).

All numeric paths are jitted device compute over the sharded X matrix
(SURVEY.md §2b "Feature transformers" row; reconstructed, mount empty):
reductions (quantiles, variances, chi², hash mins) contract over the sharded
row axis so GSPMD inserts the ICI all-reduce where MLlib ran a treeAggregate;
per-row maps (polynomial terms, DCT, random projections) are fused
elementwise/matmul work for the MXU. Only name/metadata juggling stays host.
"""

from __future__ import annotations

import ast
import dataclasses
import itertools
import re

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import (
    ContinuousVariable,
    DiscreteVariable,
    Domain,
    StringVariable,
)
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params, Transformer
from orange3_spark_tpu.models.text import _append_meta


def _attr_names(table: TpuTable) -> list[str]:
    return [v.name for v in table.domain.attributes]


def _col_idx(table: TpuTable, cols) -> np.ndarray:
    names = _attr_names(table)
    return np.asarray([names.index(c) for c in cols], dtype=np.int32)


def _append_cols(table: TpuTable, new_vars, cols) -> TpuTable:
    domain = Domain(
        list(table.domain.attributes) + list(new_vars),
        table.domain.class_vars, table.domain.metas,
    )
    return table.with_X(jnp.concatenate([table.X, cols], axis=1), domain)


# -------------------------------------------------------------- RobustScaler
@dataclasses.dataclass(frozen=True)
class RobustScalerParams(Params):
    lower: float = 0.25          # MLlib lower quantile
    upper: float = 0.75          # MLlib upper
    with_centering: bool = False # MLlib withCentering
    with_scaling: bool = True    # MLlib withScaling
    input_cols: tuple = ()       # () => all attributes


class RobustScalerModel(Model):
    def __init__(self, params, median, iqr, idx):
        self.params = params
        self.median = median
        self.iqr = iqr
        self.idx = idx

    @property
    def state_pytree(self):
        return {"median": self.median, "iqr": self.iqr}

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        X = table.X
        sub = X[:, self.idx]
        if p.with_centering:
            sub = sub - self.median[None, :]
        if p.with_scaling:
            sub = sub / jnp.maximum(self.iqr, 1e-12)[None, :]
        return table.with_X(X.at[:, self.idx].set(sub), table.domain)


class RobustScaler(Estimator):
    """Median/IQR scaling — quantiles of live rows only (W>0), computed by a
    device-side masked sort per column."""

    ParamsCls = RobustScalerParams
    params: RobustScalerParams

    def _fit(self, table: TpuTable) -> RobustScalerModel:
        p = self.params
        cols = list(p.input_cols) if p.input_cols else _attr_names(table)
        idx = jnp.asarray(_col_idx(table, cols))
        X, W = table.X, table.W
        sub = X[:, idx]
        live = W > 0
        n_live = jnp.sum(live.astype(jnp.float32))
        # masked quantile: push dead rows to +inf, sort, index at q*(n_live-1)
        masked = jnp.where(live[:, None], sub, jnp.inf)
        srt = jnp.sort(masked, axis=0)

        def q_at(q):
            pos = q * jnp.maximum(n_live - 1.0, 0.0)
            lo = jnp.floor(pos).astype(jnp.int32)
            hi = jnp.ceil(pos).astype(jnp.int32)
            frac = pos - lo.astype(jnp.float32)
            return srt[lo] * (1 - frac) + srt[hi] * frac

        med = q_at(jnp.float32(0.5))
        iqr = q_at(jnp.float32(p.upper)) - q_at(jnp.float32(p.lower))
        return RobustScalerModel(p, med, iqr, idx)


# ------------------------------------------------------ PolynomialExpansion
@dataclasses.dataclass(frozen=True)
class PolynomialExpansionParams(Params):
    degree: int = 2              # MLlib degree
    input_cols: tuple = ()       # () => all attributes


class PolynomialExpansion(Transformer):
    """All monomials of the inputs up to ``degree`` (MLlib's expansion, minus
    the constant term). Term list is built from column METADATA host-side;
    each term is a fused product of column slices on device."""

    ParamsCls = PolynomialExpansionParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        cols = list(p.input_cols) if p.input_cols else _attr_names(table)
        idx = _col_idx(table, cols)
        X = table.X
        new_cols, new_vars = [], []
        for deg in range(2, p.degree + 1):
            for combo in itertools.combinations_with_replacement(range(len(cols)), deg):
                prod = X[:, idx[combo[0]]]
                for j in combo[1:]:
                    prod = prod * X[:, idx[j]]
                new_cols.append(prod[:, None])
                new_vars.append(ContinuousVariable("*".join(cols[j] for j in combo)))
        if not new_cols:
            return table
        return _append_cols(table, new_vars, jnp.concatenate(new_cols, axis=1))


# ------------------------------------------------------------------- DCT
@dataclasses.dataclass(frozen=True)
class DCTParams(Params):
    inverse: bool = False        # MLlib inverse
    input_cols: tuple = ()


class DCT(Transformer):
    """DCT-II across the feature axis as one [N,d]@[d,d] MXU matmul with the
    orthonormal cosine basis (MLlib delegates to jTransforms; a matmul IS the
    TPU-native FFT-free formulation at tabular widths)."""

    ParamsCls = DCTParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        cols = list(p.input_cols) if p.input_cols else _attr_names(table)
        idx = jnp.asarray(_col_idx(table, cols))
        d = len(cols)
        n = np.arange(d)
        basis = np.sqrt(2.0 / d) * np.cos(
            np.pi * (n[:, None] + 0.5) * n[None, :] / d
        )
        basis[:, 0] = 1.0 / np.sqrt(d)
        B = jnp.asarray(basis.astype(np.float32))       # orthonormal DCT-II
        if p.inverse:
            B = B.T
        X = table.X
        out = X[:, idx] @ B
        return table.with_X(X.at[:, idx].set(out), table.domain)


# -------------------------------------------------------------- Interaction
@dataclasses.dataclass(frozen=True)
class InteractionParams(Params):
    input_cols: tuple = ()       # columns whose product forms the interaction
    output_col: str = "interaction"


class Interaction(Transformer):
    """Product of the named columns (MLlib's Interaction over scalar columns;
    its vector-column cross products are covered by PolynomialExpansion)."""

    ParamsCls = InteractionParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        if len(p.input_cols) < 2:
            raise ValueError("Interaction needs >= 2 input_cols")
        idx = _col_idx(table, p.input_cols)
        prod = table.X[:, idx[0]]
        for j in idx[1:]:
            prod = prod * table.X[:, j]
        return _append_cols(
            table, [ContinuousVariable(p.output_col)], prod[:, None]
        )


# -------------------------------------------------------- ElementwiseProduct
@dataclasses.dataclass(frozen=True)
class ElementwiseProductParams(Params):
    scaling_vec: tuple = ()      # MLlib scalingVec
    input_cols: tuple = ()


class ElementwiseProduct(Transformer):
    ParamsCls = ElementwiseProductParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        cols = list(p.input_cols) if p.input_cols else _attr_names(table)
        if len(p.scaling_vec) != len(cols):
            raise ValueError(
                f"scaling_vec has {len(p.scaling_vec)} entries for {len(cols)} columns"
            )
        idx = jnp.asarray(_col_idx(table, cols))
        v = jnp.asarray(np.asarray(p.scaling_vec, dtype=np.float32))
        X = table.X
        return table.with_X(X.at[:, idx].set(X[:, idx] * v[None, :]), table.domain)


# ------------------------------------------------------------- VectorSlicer
@dataclasses.dataclass(frozen=True)
class VectorSlicerParams(Params):
    names: tuple = ()            # MLlib names
    indices: tuple = ()          # MLlib indices


class VectorSlicer(Transformer):
    ParamsCls = VectorSlicerParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        names = _attr_names(table)
        keep = list(p.names) + [names[i] for i in p.indices]
        if not keep:
            raise ValueError("VectorSlicer needs names and/or indices")
        return table.select(keep)


# ------------------------------------------------------------ IndexToString
@dataclasses.dataclass(frozen=True)
class IndexToStringParams(Params):
    input_col: str = ""
    output_col: str = ""
    labels: tuple = ()           # () => use the DiscreteVariable's values


class IndexToString(Transformer):
    """Inverse StringIndexer: discrete index attribute -> host meta strings."""

    ParamsCls = IndexToStringParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        names = _attr_names(table)
        j = names.index(p.input_col)
        var = table.domain.attributes[j]
        labels = p.labels or getattr(var, "values", ())
        if not labels:
            raise ValueError(f"{p.input_col!r} has no labels; pass labels=")
        vals = np.asarray(jax.device_get(table.X[:, j]))[: table.n_rows]
        out = np.empty(table.n_rows, dtype=object)
        for i, v in enumerate(vals):
            k = int(v)
            out[i] = labels[k] if 0 <= k < len(labels) else "__unknown__"
        return _append_meta(table, p.output_col or f"{p.input_col}_str", out)


# ------------------------------------------------------------ VectorIndexer
@dataclasses.dataclass(frozen=True)
class VectorIndexerParams(Params):
    max_categories: int = 20     # MLlib maxCategories
    handle_invalid: str = "error"  # MLlib handleInvalid: 'error' | 'keep'


class VectorIndexerModel(Model):
    def __init__(self, params, category_maps):
        self.params = params
        # {col_index: sorted distinct values} for detected categorical cols
        self.category_maps = category_maps

    @property
    def state_pytree(self):
        return {}

    def transform(self, table: TpuTable) -> TpuTable:
        X = table.X
        new_attrs = list(table.domain.attributes)
        for j, cats in self.category_maps.items():
            # re-encode values -> category ordinals with one [n_cats] compare
            c = jnp.asarray(cats)
            col = table.X[:, j]
            hit = col[:, None] == c[None, :]
            matched = jnp.any(hit, axis=1)
            enc = jnp.argmax(hit, axis=1).astype(jnp.float32)
            values = tuple(str(v) for v in cats)
            if self.params.handle_invalid == "keep":
                # unseen categories -> extra '__unknown__' ordinal, MLlib 'keep'
                enc = jnp.where(matched, enc, float(len(cats)))
                values = values + ("__unknown__",)
            else:
                bad = jnp.any(~matched & (table.W > 0))
                if bool(jax.device_get(bad)):
                    raise ValueError(
                        f"column {new_attrs[j].name!r} has values unseen at fit "
                        "time (handle_invalid='error'; use 'keep' to bucket them)"
                    )
            X = X.at[:, j].set(enc)
            new_attrs[j] = DiscreteVariable(new_attrs[j].name, values)
        domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        return table.with_X(X, domain)


class VectorIndexer(Estimator):
    """Detects low-cardinality columns and re-types them as categorical with
    ordinal re-encoding — MLlib's automatic categorical feature detection."""

    ParamsCls = VectorIndexerParams
    params: VectorIndexerParams

    def _fit(self, table: TpuTable) -> VectorIndexerModel:
        p = self.params
        X = np.asarray(jax.device_get(table.X))
        live = np.asarray(jax.device_get(table.W)) > 0
        maps = {}
        for j in range(X.shape[1]):
            u = np.unique(X[live, j])
            if len(u) <= p.max_categories:
                maps[j] = u.astype(np.float32).tolist()
        return VectorIndexerModel(p, maps)


# ------------------------------------------- VarianceThresholdSelector
@dataclasses.dataclass(frozen=True)
class VarianceThresholdSelectorParams(Params):
    variance_threshold: float = 0.0  # MLlib varianceThreshold


class VarianceThresholdSelector(Estimator):
    ParamsCls = VarianceThresholdSelectorParams
    params: VarianceThresholdSelectorParams

    def _fit(self, table: TpuTable):
        X, W = table.X, table.W
        sw = jnp.maximum(jnp.sum(W), 1e-12)
        mean = jnp.sum(X * W[:, None], axis=0) / sw
        var = jnp.sum(((X - mean) ** 2) * W[:, None], axis=0) / sw
        keep_mask = np.asarray(jax.device_get(var)) > self.params.variance_threshold
        names = _attr_names(table)
        keep = [n for n, k in zip(names, keep_mask) if k]
        return _ColumnSelectorModel(self.params, tuple(keep))


class _ColumnSelectorModel(Model):
    def __init__(self, params, selected):
        self.params = params
        self.selected = tuple(selected)  # MLlib selectedFeatures (as names)

    @property
    def state_pytree(self):
        return {}

    def transform(self, table: TpuTable) -> TpuTable:
        return table.select(self.selected)


# ------------------------------------- ChiSqSelector / UnivariateFeatureSelector
@dataclasses.dataclass(frozen=True)
class UnivariateFeatureSelectorParams(Params):
    feature_type: str = "continuous"   # MLlib featureType
    label_type: str = "categorical"    # MLlib labelType
    selection_mode: str = "numTopFeatures"  # | 'percentile' | 'fpr'
    selection_threshold: float = 50    # top-N count / keep-fraction / fpr alpha
    n_bins: int = 16                   # binning for chi² on continuous feats


def _anova_f(X, y, w, k: int):
    """Per-column one-way ANOVA F statistic against k classes (weighted).
    Delegates to the shared kernel in models/stat.py (ANOVATest) so the
    statistic cannot drift between the selector and the stat API."""
    from orange3_spark_tpu.models.stat import _anova_kernel

    return _anova_kernel(X, y, w, k=k)[0]


def _chi2_stat(X, y, w, k: int, n_bins: int):
    """Per-column chi² of binned feature vs label."""
    d = X.shape[1]
    live = w[:, None] > 0
    # mask dead/padding rows out of the bin-edge stats (they carry X=0)
    lo = jnp.min(jnp.where(live, X, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(live, X, -jnp.inf), axis=0)
    width = jnp.maximum((hi - lo) / n_bins, 1e-12)
    b = jnp.clip(((X - lo) / width).astype(jnp.int32), 0, n_bins - 1)  # [N,d]
    yi = y.astype(jnp.int32)
    onehot_y = jax.nn.one_hot(yi, k, dtype=jnp.float32) * w[:, None]   # [N,k]
    stats = []
    for j in range(d):  # d is small metadata-size; rows stay sharded
        onehot_b = jax.nn.one_hot(b[:, j], n_bins, dtype=jnp.float32)
        table_jk = onehot_b.T @ onehot_y                               # [bins,k]
        rs = jnp.sum(table_jk, axis=1, keepdims=True)
        cs = jnp.sum(table_jk, axis=0, keepdims=True)
        tot = jnp.maximum(jnp.sum(table_jk), 1e-12)
        expected = rs @ cs / tot
        stats.append(jnp.sum(
            jnp.where(expected > 0, (table_jk - expected) ** 2 / jnp.maximum(expected, 1e-12), 0.0)
        ))
    return jnp.stack(stats)


class UnivariateFeatureSelector(Estimator):
    """Scores each feature against the label (ANOVA-F for continuous/
    categorical, chi² for binned categorical pairs, squared-correlation F for
    continuous labels) and keeps the top ones — MLlib's selector family
    (ChiSqSelector is the feature_type='categorical' special case)."""

    ParamsCls = UnivariateFeatureSelectorParams
    params: UnivariateFeatureSelectorParams

    def _fit(self, table: TpuTable):
        p = self.params
        if table.y is None:
            raise ValueError("selector needs a label column")
        X, y, w = table.X, table.y, table.W
        names = _attr_names(table)
        if p.label_type == "categorical":
            # mask W==0 so filtered rows' labels can't inflate the class count
            k = int(np.asarray(jax.device_get(
                jnp.max(jnp.where(w > 0, y, 0.0))
            )).item()) + 1
            if p.feature_type == "categorical":
                scores = _chi2_stat(X, y, w, k, p.n_bins)
            else:
                scores = _anova_f(X, y, w, k)
        else:  # continuous label: F from squared Pearson correlation —
            # the shared FValueTest kernel (models/stat.py)
            from orange3_spark_tpu.models.stat import _fvalue_kernel

            scores = _fvalue_kernel(X, y, w)[0]
        s = np.asarray(jax.device_get(scores))
        if p.selection_mode == "numTopFeatures":
            top = np.argsort(-s)[: int(p.selection_threshold)]
        elif p.selection_mode == "percentile":
            n_keep = max(1, int(round(p.selection_threshold * len(s))))
            top = np.argsort(-s)[:n_keep]
        elif p.selection_mode == "fpr":
            # keep features with p-value < alpha under the score's null dist
            from scipy import stats as sps

            n_eff = float(np.asarray(jax.device_get(jnp.sum(w))))
            if p.label_type == "categorical" and p.feature_type == "categorical":
                dof = (p.n_bins - 1) * (k - 1)
                pvals = sps.chi2.sf(s, dof)
            elif p.label_type == "categorical":
                pvals = sps.f.sf(s, k - 1, max(n_eff - k, 1.0))
            else:
                pvals = sps.f.sf(s, 1, max(n_eff - 2, 1.0))
            top = np.flatnonzero(pvals < p.selection_threshold)
        else:
            raise ValueError(f"unknown selection_mode {p.selection_mode!r}")
        keep = [names[i] for i in sorted(top)]
        return _ColumnSelectorModel(p, tuple(keep))


class ChiSqSelector(UnivariateFeatureSelector):
    """MLlib ChiSqSelector = UnivariateFeatureSelector with chi² scoring."""

    def __init__(self, params=None, **kwargs):
        kwargs.setdefault("feature_type", "categorical")
        kwargs.setdefault("label_type", "categorical")
        super().__init__(params, **kwargs)


# ------------------------------------------------------------ SQLTransformer
@dataclasses.dataclass(frozen=True)
class SQLTransformerParams(Params):
    statement: str = "SELECT * FROM __THIS__"  # MLlib statement


class SQLTransformer(Transformer):
    """The useful subset of MLlib's SQLTransformer:

        SELECT *, <expr> AS <name> [, ...] FROM __THIS__ [WHERE <cond>]

    Expressions are parsed with Python's ``ast`` (arithmetic, comparisons,
    and/or, unary minus over column names and literals) and evaluated as
    jitted jnp column math — a tiny Catalyst: the SQL string becomes one
    fused XLA elementwise program over the sharded table. WHERE becomes a
    weight-mask filter (static shapes — Spark's shrinking DataFrame has no
    XLA analogue)."""

    ParamsCls = SQLTransformerParams

    _BIN = {ast.Add: jnp.add, ast.Sub: jnp.subtract, ast.Mult: jnp.multiply,
            ast.Div: jnp.divide, ast.Mod: jnp.mod, ast.Pow: jnp.power}
    _CMP = {ast.Gt: jnp.greater, ast.Lt: jnp.less, ast.GtE: jnp.greater_equal,
            ast.LtE: jnp.less_equal, ast.Eq: jnp.equal, ast.NotEq: jnp.not_equal}

    def _eval(self, node, env):
        if isinstance(node, ast.Expression):
            return self._eval(node.body, env)
        if isinstance(node, ast.Name):
            if node.id not in env:
                raise ValueError(f"unknown column {node.id!r}")
            return env[node.id]
        if isinstance(node, ast.Constant):
            return jnp.float32(node.value)
        if isinstance(node, ast.BinOp) and type(node.op) in self._BIN:
            return self._BIN[type(node.op)](
                self._eval(node.left, env), self._eval(node.right, env)
            )
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            return -self._eval(node.operand, env)
        if isinstance(node, ast.Compare) and len(node.ops) == 1:
            return self._CMP[type(node.ops[0])](
                self._eval(node.left, env), self._eval(node.comparators[0], env)
            ).astype(jnp.float32)
        if isinstance(node, ast.BoolOp):
            vals = [self._eval(v, env) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                out = (out * v) if isinstance(node.op, ast.And) else jnp.maximum(out, v)
            return out
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            fns = {"abs": jnp.abs, "log": jnp.log, "exp": jnp.exp,
                   "sqrt": jnp.sqrt, "sin": jnp.sin, "cos": jnp.cos}
            if node.func.id in fns and len(node.args) == 1:
                return fns[node.func.id](self._eval(node.args[0], env))
        raise ValueError(f"unsupported SQL expression node {ast.dump(node)}")

    def transform(self, table: TpuTable) -> TpuTable:
        stmt = self.params.statement.strip().rstrip(";")
        m = re.match(
            r"(?is)^SELECT\s+(.*?)\s+FROM\s+__THIS__(?:\s+WHERE\s+(.*))?$", stmt
        )
        if not m:
            raise ValueError(
                "statement must be 'SELECT ... FROM __THIS__ [WHERE ...]'"
            )
        select_part, where_part = m.group(1), m.group(2)
        env = {v.name: table.X[:, j]
               for j, v in enumerate(table.domain.attributes)}
        out = table
        new_vars, new_cols = [], []
        star = False
        for item in re.split(r",(?![^(]*\))", select_part):
            item = item.strip()
            if item == "*":
                star = True
                continue
            am = re.match(r"(?is)^(.*?)\s+AS\s+(\w+)$", item)
            if not am:
                raise ValueError(f"each non-* select item needs 'expr AS name': {item!r}")
            expr, name = am.group(1), am.group(2)
            col = self._eval(ast.parse(expr, mode="eval"), env)
            new_vars.append(ContinuousVariable(name))
            new_cols.append(col[:, None])
        if not star and not new_cols:
            raise ValueError("empty select list")
        if new_cols:
            out = _append_cols(out, new_vars, jnp.concatenate(new_cols, axis=1))
        if not star:
            out = out.select([v.name for v in new_vars])
        if where_part:
            cond = self._eval(ast.parse(where_part, mode="eval"), env)
            out = out.filter(cond > 0)
        return out


# ------------------------------------------------------------------- LSH
@dataclasses.dataclass(frozen=True)
class BucketedRandomProjectionLSHParams(Params):
    bucket_length: float = 1.0   # MLlib bucketLength
    num_hash_tables: int = 1     # MLlib numHashTables
    seed: int = 0
    output_prefix: str = "lsh"


class _LSHModelBase(Model):
    """Shared approx-neighbor machinery over the hash columns."""

    def _hashes(self, table: TpuTable) -> jnp.ndarray:
        raise NotImplementedError

    def _distance(self, A: jnp.ndarray, B: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def _hash_cols(self, H):
        """Bucket ids as float32-exact column values (override if raw ids
        exceed the 2^24 float32 integer range)."""
        return H.astype(jnp.float32)

    def transform(self, table: TpuTable) -> TpuTable:
        H = self._hash_cols(self._hashes(table))
        names = [f"{self.params.output_prefix}_{j}" for j in range(H.shape[1])]
        return _append_cols(
            table, [ContinuousVariable(n) for n in names], H
        )

    def approx_nearest_neighbors(self, table: TpuTable, key: np.ndarray, k: int = 2):
        """MLlib approxNearestNeighbors: candidate rows sharing >=1 hash
        bucket with the key, ranked by true distance. Returns (indices, dists)."""
        key = jnp.asarray(np.asarray(key, dtype=np.float32))[None, :]
        Hk = self._hash_raw(key)                     # [1, T]
        Ht = self._hash_raw(table.X)                 # [N, T]
        cand = jnp.any(Ht == Hk, axis=1) & (table.W > 0)
        d = self._distance(table.X, key)[:, 0]
        d = jnp.where(cand, d, jnp.inf)
        idx = jnp.argsort(d)[:k]
        dists = d[idx]
        idx_np = np.asarray(idx)
        d_np = np.asarray(dists)
        ok = np.isfinite(d_np)
        return idx_np[ok], d_np[ok]

    def approx_similarity_join(self, a: TpuTable, b: TpuTable, threshold: float):
        """Pairs (i, j, dist) with a shared bucket and dist <= threshold.
        Materializes the dense [Na, Nb] candidate mask on device — suited to
        join sides up to ~10^4 rows each; chunk the larger side above that."""
        Ha = self._hash_raw(a.X)
        Hb = self._hash_raw(b.X)
        share = jnp.any(Ha[:, None, :] == Hb[None, :, :], axis=2)
        dist = self._distance(a.X, b.X)
        mask = share & (dist <= threshold) & (a.W[:, None] > 0) & (b.W[None, :] > 0)
        ii, jj = np.nonzero(np.asarray(mask))
        dd = np.asarray(dist)[ii, jj]
        keep = ii < a.n_rows
        keep &= jj < b.n_rows
        return ii[keep], jj[keep], dd[keep]


class BucketedRandomProjectionLSHModel(_LSHModelBase):
    def __init__(self, params, R):
        self.params = params
        self.R = R  # f32[d, T] random projection directions

    @property
    def state_pytree(self):
        return {"R": self.R}

    def _hash_raw(self, X):
        return jnp.floor((X @ self.R) / self.params.bucket_length)

    def _hashes(self, table: TpuTable):
        return self._hash_raw(table.X)

    def _distance(self, A, B):
        a2 = jnp.sum(A * A, axis=1, keepdims=True)
        b2 = jnp.sum(B * B, axis=1)
        cross = A @ B.T
        return jnp.sqrt(jnp.maximum(a2 - 2 * cross + b2[None, :], 0.0))


class BucketedRandomProjectionLSH(Estimator):
    """Euclidean LSH: h(x) = floor(x·r / bucketLength), one random unit
    direction per hash table — hashing is a single [N,d]@[d,T] MXU matmul."""

    ParamsCls = BucketedRandomProjectionLSHParams
    params: BucketedRandomProjectionLSHParams

    def _fit(self, table: TpuTable) -> BucketedRandomProjectionLSHModel:
        p = self.params
        rng = np.random.default_rng(p.seed)
        d = table.X.shape[1]
        R = rng.standard_normal((d, p.num_hash_tables)).astype(np.float32)
        R /= np.linalg.norm(R, axis=0, keepdims=True)
        return BucketedRandomProjectionLSHModel(
            p, jax.device_put(jnp.asarray(R), table.session.replicated)
        )


@dataclasses.dataclass(frozen=True)
class MinHashLSHParams(Params):
    num_hash_tables: int = 1
    seed: int = 0
    output_prefix: str = "minhash"


_MINHASH_PRIME = 2038074743  # MLlib's prime


class MinHashLSHModel(_LSHModelBase):
    def __init__(self, params, a, b):
        self.params = params
        self.a = np.asarray(a, dtype=np.int64)  # [T] hash coefficients (host)
        self.b = np.asarray(b, dtype=np.int64)

    @property
    def state_pytree(self):
        return {}

    def _hash_raw(self, X):
        # h_t(x) = min over nonzero indices i of (a_t·(i+1) + b_t) mod prime.
        # The [d,T] hash-value table is computed HOST-side in int64 (JAX x64
        # is off; device int64 would silently wrap in int32) — post-mod values
        # fit int32 and only the min-reduction runs on device. One table at a
        # time: peak device memory stays [N,d], never [N,d,T].
        d = X.shape[1]
        idx = np.arange(1, d + 1, dtype=np.int64)
        hv = ((self.a[None, :] * idx[:, None] + self.b[None, :])
              % _MINHASH_PRIME).astype(np.int32)                      # [d,T]
        nz = X > 0                                                    # [N,d]
        big = jnp.int32(_MINHASH_PRIME)
        cols = []
        for t in range(hv.shape[1]):
            masked = jnp.where(nz, jnp.asarray(hv[:, t])[None, :], big)
            cols.append(jnp.min(masked, axis=1))
        return jnp.stack(cols, axis=1)                                # [N,T] i32

    def _hashes(self, table: TpuTable):
        return self._hash_raw(table.X)

    def _hash_cols(self, H):
        # raw ids reach ~2·10^9 — float32 only represents ints below 2^24
        # exactly, so distinct buckets would collide in the output column.
        # A deterministic mod-2^24 fold preserves true-bucket equality
        # (h1==h2 => h1%m==h2%m) at a ~6·10^-8 per-pair false-merge rate.
        return (H % (1 << 24)).astype(jnp.float32)

    def _distance(self, A, B):
        """Jaccard distance between binarized rows."""
        a = (A > 0).astype(jnp.float32)
        b = (B > 0).astype(jnp.float32)
        inter = a @ b.T
        na = jnp.sum(a, axis=1, keepdims=True)
        nb = jnp.sum(b, axis=1)
        union = jnp.maximum(na + nb[None, :] - inter, 1e-12)
        return 1.0 - inter / union


class MinHashLSH(Estimator):
    """Jaccard LSH over binary (nonzero-support) rows — MLlib MinHashLSH."""

    ParamsCls = MinHashLSHParams
    params: MinHashLSHParams

    def _fit(self, table: TpuTable) -> MinHashLSHModel:
        p = self.params
        rng = np.random.default_rng(p.seed)
        a = rng.integers(1, _MINHASH_PRIME, size=p.num_hash_tables)
        b = rng.integers(0, _MINHASH_PRIME, size=p.num_hash_tables)
        return MinHashLSHModel(p, a, b)
