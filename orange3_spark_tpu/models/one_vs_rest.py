"""OneVsRest — parity with ``pyspark.ml.classification.OneVsRest``.

MLlib reduces a k-class problem to k binary fits of a caller-supplied base
classifier, then predicts the class whose binary model is most confident
(SURVEY.md §2b Estimator protocol row — reconstructed, mount empty). Spark
runs the k fits as k separate Spark jobs; here each relabeling is a pure
device op (``y == c`` — no data copy, the [N,d] features are shared across
all k fits) and the per-class confidences stack into one [N,k] argmax. The
base estimator is arbitrary, so the k fits run as k XLA program launches
over the same sharded arrays rather than one vmapped program — the data
stays resident on device between them, which is the part Spark pays shuffle
for.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params, infer_class_values


@dataclasses.dataclass(frozen=True)
class OneVsRestParams(Params):
    parallelism: int = 1  # MLlib parallelism (thread pool); device fits are
                          # already async-dispatched, so this is accepted for
                          # API parity but not a throughput lever here


def _binary_table(table: TpuTable, cls_index: int) -> TpuTable:
    """Relabel y -> 1{y == cls_index} without touching X (device-only op)."""
    y_bin = (table.y == float(cls_index)).astype(jnp.float32)[:, None]
    domain = Domain(
        table.domain.attributes,
        DiscreteVariable("_ovr_target", ("rest", "this")),
        table.domain.metas,
    )
    return TpuTable(domain, table.X, y_bin, table.W, table.metas,
                    table.n_rows, table.session)


def _confidence(model: Model, table: TpuTable) -> np.ndarray:
    """Per-row confidence for the positive class of a fitted binary model."""
    proba = getattr(model, "predict_proba", None)
    if proba is not None:
        return np.asarray(proba(table))[:, 1]
    dec = getattr(model, "decision_function", None)
    if dec is not None:
        return np.asarray(dec(table))
    raise TypeError(
        f"{type(model).__name__} exposes neither predict_proba nor "
        "decision_function; OneVsRest cannot rank its confidence"
    )


class OneVsRestModel(Model):
    def __init__(self, params, models, class_values):
        self.params = params
        self.models = list(models)      # k fitted binary models
        self.class_values = tuple(class_values)

    @property
    def state_pytree(self):
        return {
            f"class{i}": m.state_pytree for i, m in enumerate(self.models)
        }

    def load_state_pytree(self, state):
        for key, sub in state.items():
            self.models[int(key.removeprefix("class"))].load_state_pytree(sub)
        self._touch_serving_state()

    def _serve_state_token(self):
        return (getattr(self, "_serve_state_version", 0),
                tuple(m._serve_state_token() for m in self.models))

    def _scores(self, table: TpuTable) -> np.ndarray:
        return np.stack(
            [_confidence(m, table) for m in self.models], axis=1
        )  # [n, k] host-side stack of device-computed confidences

    def predict(self, table: TpuTable) -> np.ndarray:
        s = self._scores(table)
        return np.argmax(s, axis=1).astype(np.float32)[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        s = self._scores(table)  # [n_rows, k] — base models strip padding
        pred = np.zeros((table.n_pad,), np.float32)
        pred[: table.n_rows] = np.argmax(s, axis=1)[: table.n_rows]
        new_attrs = list(table.domain.attributes) + [
            DiscreteVariable("prediction", self.class_values)
        ]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, jnp.asarray(pred)[:, None]], axis=1)
        return table.with_X(X, new_domain)


class OneVsRest(Estimator):
    ParamsCls = OneVsRestParams
    params: OneVsRestParams

    def __init__(self, classifier: Estimator, params=None, **kwargs):
        super().__init__(params, **kwargs)
        self.classifier = classifier  # MLlib's `classifier` Param

    def _fit(self, table: TpuTable) -> OneVsRestModel:
        class_values = infer_class_values(table)
        base_params = self.classifier.params
        models = []
        for c in range(len(class_values)):
            est = type(self.classifier)(base_params)
            models.append(est.fit(_binary_table(table, c)))
        return OneVsRestModel(self.params, models, class_values)
