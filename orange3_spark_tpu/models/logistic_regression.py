"""LogisticRegression — the reference's flagship estimator, TPU-native.

Capability parity target: ``pyspark.ml.classification.LogisticRegression``
as wrapped by the add-on's auto-generated OWSparkLogisticRegression-style
widget (SURVEY.md §2b; reconstructed — reference mount empty). Param names
mirror MLlib's (maxIter→max_iter etc.) so widget auto-generation and ported
user code line up.

Design: multinomial softmax fit by the fused L-BFGS program in _linear.py —
one XLA computation for the whole fit, gradients all-reduced over ICI by
GSPMD instead of MLlib's per-iteration treeAggregate shuffle.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models._linear import column_inv_std, fit_linear
from orange3_spark_tpu.models.base import concrete_or_none, Estimator, Model, Params, infer_class_values


@dataclasses.dataclass(frozen=True)
class LogisticRegressionParams(Params):
    max_iter: int = 100            # MLlib maxIter
    reg_param: float = 0.0         # MLlib regParam (L2 when elastic_net=0)
    elastic_net_param: float = 0.0 # MLlib elasticNetParam (L1 mixing, OWLQN)
    tol: float = 1e-6              # MLlib tol
    fit_intercept: bool = True     # MLlib fitIntercept
    family: str = "auto"           # 'auto' | 'binomial' | 'multinomial'
    standardization: bool = True   # MLlib standardization
    threshold: float = 0.5         # MLlib threshold (binomial decision cut)
    compute_dtype: str = "float32" # 'bfloat16' for MXU-rate fits on big data


class LogisticRegressionModel(Model):
    def __init__(self, params, coef, intercept, class_values):
        self.params = params
        self.coef = coef              # f32[d, k]
        self.intercept = intercept    # f32[k]
        self.class_values = tuple(class_values)
        self.n_iter_: int | None = None

    @property
    def state_pytree(self):
        return {"coef": self.coef, "intercept": self.intercept}

    @staticmethod
    def _prob_pred(X, coef, intercept, threshold):
        """Shared (unjitted) decision body — the single copy of the
        threshold semantics both jitted kernels trace through."""
        logits = X @ coef + intercept
        prob = jax.nn.softmax(logits, axis=-1)
        if coef.shape[1] == 2:
            # MLlib binomial semantics: predict class 1 iff P(1) > threshold
            pred = (prob[:, 1] > threshold).astype(jnp.float32)
        else:
            pred = jnp.argmax(logits, axis=-1).astype(jnp.float32)
        return prob, pred

    @staticmethod
    @jax.jit
    def _predict_kernel(X, coef, intercept, threshold):
        return LogisticRegressionModel._prob_pred(X, coef, intercept,
                                                  threshold)

    def _predict(self, X):
        return self._predict_kernel(
            X, self.coef, self.intercept, jnp.float32(self.params.threshold)
        )

    def _device_predict(self, table: TpuTable):
        """Serving hook (serve/context.py): device-pure per-row predictions
        — what the AOT bucketed executable compiles for ``predict``."""
        _, pred = self._predict(table.X)
        return pred

    @staticmethod
    @jax.jit
    def _transform_kernel(X, coef, intercept, threshold):
        """The WHOLE transform as one program (kernel + column concat).
        One dispatch instead of two — and, load-bearing for serving: the
        AOT bucketed executable traces transform into a single fused
        module, so the eager path must fuse identically or XLA's
        fusion-dependent transcendental codegen drifts the probability
        columns by an ulp across the two paths (observed on this jaxlib;
        pinned bitwise in tests/test_serving.py)."""
        prob, pred = LogisticRegressionModel._prob_pred(X, coef, intercept,
                                                        threshold)
        return jnp.concatenate([X, prob, pred[:, None]], axis=1)

    def transform(self, table: TpuTable) -> TpuTable:
        """Append probability_<c> and prediction columns (Spark's
        probability/prediction output columns on the transformed DataFrame)."""
        X = self._transform_kernel(
            table.X, self.coef, self.intercept,
            jnp.float32(self.params.threshold),
        )
        new_attrs = list(table.domain.attributes) + [
            ContinuousVariable(f"probability_{c}") for c in self.class_values
        ] + [DiscreteVariable("prediction", self.class_values)]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        return table.with_X(X, new_domain)

    def predict(self, table: TpuTable) -> np.ndarray:
        _, pred = self._predict(table.X)
        return np.asarray(pred)[: table.n_rows]

    def predict_proba(self, table: TpuTable) -> np.ndarray:
        prob, _ = self._predict(table.X)
        return np.asarray(prob)[: table.n_rows]

    def summary(self, table: TpuTable) -> dict:
        """MLlib ``model.summary``-style metrics computed on ``table``
        (Spark evaluates its TrainingSummary on the training data; pass
        any labeled table here — a holdout gives the honest version).
        Returns accuracy / f1 / weightedPrecision / weightedRecall, plus
        areaUnderROC / areaUnderPR for binomial models — each a device
        reduction through the pyspark.ml.evaluation twins."""
        from orange3_spark_tpu.models.evaluation import (
            BinaryClassificationEvaluator, MulticlassClassificationEvaluator,
        )

        scored = self.transform(table)
        ev = MulticlassClassificationEvaluator()
        C = ev.confusion(scored)   # one device reduction for all four
        out = {
            m: ev.from_confusion(C, m)
            for m in ("accuracy", "f1", "weightedPrecision",
                      "weightedRecall")
        }
        if len(self.class_values) == 2:
            for m in ("areaUnderROC", "areaUnderPR"):
                out[m] = BinaryClassificationEvaluator(metric_name=m
                                                       ).evaluate(scored)
        return out


class LogisticRegression(Estimator):
    ParamsCls = LogisticRegressionParams
    params: LogisticRegressionParams

    def _fit(self, table: TpuTable) -> LogisticRegressionModel:
        p = self.params
        y = table.y
        class_values = infer_class_values(table)
        k = len(class_values)
        if p.family == "binomial" and k != 2:
            raise ValueError(f"binomial family needs 2 classes, got {k}")

        X, w = table.X, table.W
        # scale-only standardization folded INTO the fit matmul (no scaled
        # copy of the [N,d] data is ever materialized), MLlib-style
        inv_std = column_inv_std(X, w) if p.standardization else None
        # MLlib regParam/elasticNetParam -> (L2, L1) split; alpha=0 keeps the
        # pure-L2 fused L-BFGS path, alpha>0 switches to the fused OWLQN
        alpha = p.elastic_net_param
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"elastic_net_param must be in [0, 1], got {alpha}")
        result = fit_linear(
            X, y, w,
            jnp.float32(p.reg_param * (1.0 - alpha)),
            jnp.float32(p.tol),
            jnp.int32(p.max_iter),
            inv_std,
            jnp.float32(p.reg_param * alpha) if p.reg_param * alpha > 0.0 else None,
            loss_kind="logistic",
            k=k,
            fit_intercept=p.fit_intercept,
            compute_dtype=jnp.dtype(p.compute_dtype),
        )
        coef = result.coef
        if inv_std is not None:
            coef = coef * inv_std[:, None]  # back to original feature space
        model = LogisticRegressionModel(p, coef, result.intercept, class_values)
        model.n_iter_ = concrete_or_none(result.n_iter, int)
        return model
