"""Model selection — ``pyspark.ml.tuning`` parity (ParamGridBuilder,
CrossValidator, TrainValidationSplit).

Folds are weight masks (static shapes: every fold sees the same padded
arrays, train/val membership is carried in W), so one XLA program shape
serves all folds — no per-fold recompilation, the TPU analogue of Spark's
per-fold DataFrame filters. (SURVEY.md §2b; reconstructed, mount empty.)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params


class ParamGridBuilder:
    """pyspark.ml.tuning.ParamGridBuilder: cartesian grid over param names."""

    def __init__(self):
        self._grid: dict[str, Sequence[Any]] = {}

    def add_grid(self, name: str, values: Sequence[Any]) -> "ParamGridBuilder":
        self._grid[name] = list(values)
        return self

    def build(self) -> list[dict[str, Any]]:
        import itertools

        names = list(self._grid)
        combos = itertools.product(*(self._grid[n] for n in names))
        return [dict(zip(names, c)) for c in combos]


def _with_params(estimator: Estimator, point: dict[str, Any]) -> Estimator:
    """Clone an estimator with grid-point params applied.

    Shallow-copies the instance (preserving constructor extras) and swaps the
    frozen params; unknown param names raise with a clear message.

    For a ``Pipeline`` estimator the grid keys are routed INTO the stages —
    MLlib's primary CV pattern, where grid Params belong to individual
    pipeline stages. A plain key (``"reg_param"``) goes to the LAST stage
    whose params declare that field (the final estimator, typically); an
    explicit ``"<stage_index>__reg_param"`` key pins a specific stage.
    """
    import copy

    from orange3_spark_tpu.models.base import Pipeline

    clone = copy.copy(estimator)
    if not point:
        return clone
    if isinstance(estimator, Pipeline):
        stages = [copy.copy(s) for s in estimator.stages]
        for name, value in point.items():
            if "__" in name:
                idx_str, field = name.split("__", 1)
                try:
                    idx = int(idx_str)
                except ValueError:
                    raise ValueError(
                        f"grid key {name!r}: stage prefix must be an integer "
                        f"index ('<stage_index>__param'), got {idx_str!r}"
                    ) from None
                if not 0 <= idx < len(stages):
                    raise ValueError(f"grid key {name!r}: no pipeline stage {idx}")
                stage_params = getattr(stages[idx], "params", None)
                if stage_params is None or field not in {
                    f.name for f in dataclasses.fields(stage_params)
                }:
                    raise ValueError(
                        f"grid key {name!r}: stage {idx} "
                        f"({type(stages[idx]).__name__}) has no param {field!r}"
                    )
            else:
                field = name
                matches = [
                    i for i, s in enumerate(stages)
                    if getattr(s, "params", None) is not None
                    and field in {f.name for f in dataclasses.fields(s.params)}
                ]
                if not matches:
                    raise ValueError(
                        f"grid param {name!r} matches no pipeline stage; stages: "
                        f"{[type(s).__name__ for s in stages]}"
                    )
                idx = matches[-1]
            stages[idx].params = stages[idx].params.replace(**{field: value})
        clone.stages = stages
        return clone
    clone.params = estimator.params.replace(**point)
    return clone


def _metric_larger_better(evaluator) -> bool:
    metric = getattr(evaluator.params, "metric_name", "") or getattr(
        evaluator, "default_metric", ""
    )
    return metric not in ("rmse", "mse", "mae")


@dataclasses.dataclass(frozen=True)
class CrossValidatorParams(Params):
    num_folds: int = 3   # MLlib numFolds
    seed: int = 0
    parallel_folds: bool = True  # reserved (folds already share one program)


class CrossValidatorModel(Model):
    def __init__(self, params, best_model: Model, best_params: dict,
                 avg_metrics: list[float]):
        self.params = params
        self.best_model = best_model
        self.best_params = best_params
        self.avg_metrics = avg_metrics  # one per grid point (MLlib avgMetrics)

    @property
    def state_pytree(self):
        return self.best_model.state_pytree

    def transform(self, table: TpuTable) -> TpuTable:
        return self.best_model.transform(table)


class CrossValidator(Estimator):
    """estimator + param grid + evaluator -> best refit model (MLlib CV)."""

    ParamsCls = CrossValidatorParams

    def __init__(self, estimator: Estimator, param_grid: list[dict],
                 evaluator, num_folds: int = 3, seed: int = 0):
        super().__init__(CrossValidatorParams(num_folds=num_folds, seed=seed))
        self.estimator = estimator
        self.param_grid = param_grid or [{}]
        self.evaluator = evaluator

    def _fold_masks(self, table: TpuTable):
        p = self.params
        fold_of = jax.random.randint(
            jax.random.PRNGKey(p.seed), (table.n_pad,), 0, p.num_folds
        )
        return fold_of

    def _fit(self, table: TpuTable) -> CrossValidatorModel:
        p = self.params
        fold_of = self._fold_masks(table)
        larger_better = _metric_larger_better(self.evaluator)
        avg_metrics: list[float] = []
        for point in self.param_grid:
            est = _with_params(self.estimator, point)
            scores = []
            for f in range(p.num_folds):
                train = table.with_weights(jnp.where(fold_of != f, table.W, 0.0))
                val = table.with_weights(jnp.where(fold_of == f, table.W, 0.0))
                model = est.fit(train)
                scores.append(self.evaluator.evaluate(model.transform(val)))
            avg_metrics.append(float(np.mean(scores)))
        best_i = int(np.argmax(avg_metrics) if larger_better else np.argmin(avg_metrics))
        best_params = self.param_grid[best_i]
        best_model = _with_params(self.estimator, best_params).fit(table)
        # ^ refit on ALL data (MLlib behavior)
        return CrossValidatorModel(p, best_model, best_params, avg_metrics)


@dataclasses.dataclass(frozen=True)
class TrainValidationSplitParams(Params):
    train_ratio: float = 0.75  # MLlib trainRatio
    seed: int = 0


class TrainValidationSplit(Estimator):
    ParamsCls = TrainValidationSplitParams

    def __init__(self, estimator: Estimator, param_grid: list[dict],
                 evaluator, train_ratio: float = 0.75, seed: int = 0):
        super().__init__(TrainValidationSplitParams(train_ratio=train_ratio, seed=seed))
        self.estimator = estimator
        self.param_grid = param_grid or [{}]
        self.evaluator = evaluator

    def _fit(self, table: TpuTable) -> CrossValidatorModel:
        from orange3_spark_tpu.ops.relational import train_test_split

        p = self.params
        train, val = train_test_split(table, 1.0 - p.train_ratio, p.seed)
        larger_better = _metric_larger_better(self.evaluator)
        metrics = []
        for point in self.param_grid:
            model = _with_params(self.estimator, point).fit(train)
            metrics.append(float(self.evaluator.evaluate(model.transform(val))))
        best_i = int(np.argmax(metrics) if larger_better else np.argmin(metrics))
        best_params = self.param_grid[best_i]
        best_model = _with_params(self.estimator, best_params).fit(table)
        return CrossValidatorModel(p, best_model, best_params, metrics)
