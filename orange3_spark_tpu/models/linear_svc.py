"""LinearSVC — parity with ``pyspark.ml.classification.LinearSVC``.

Binary hinge-loss classifier (SURVEY.md §2b row "LogisticRegression /
LinearSVC"; reconstructed, mount empty). Same fused L-BFGS program as
LogisticRegression with the hinge objective; MLlib drives this with OWLQN over
treeAggregate, we let GSPMD all-reduce the hinge subgradients over ICI.
``loss='squared_hinge'`` is offered because L-BFGS likes smooth objectives —
default stays 'hinge' for MLlib parity.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models._linear import column_inv_std, fit_linear
from orange3_spark_tpu.models.base import concrete_or_none, Estimator, Model, Params


@dataclasses.dataclass(frozen=True)
class LinearSVCParams(Params):
    max_iter: int = 100          # MLlib maxIter
    reg_param: float = 0.0       # MLlib regParam
    elastic_net_param: float = 0.0  # L1 mixing — extension: MLlib LinearSVC
    # is L2-only; offered here because the OWLQN path makes it free. Use
    # loss='squared_hinge' with L1 (OWLQN assumes a smooth data term).
    tol: float = 1e-6            # MLlib tol
    fit_intercept: bool = True   # MLlib fitIntercept
    standardization: bool = True # MLlib standardization
    threshold: float = 0.0       # MLlib threshold (on the raw margin)
    loss: str = "hinge"          # 'hinge' (MLlib) | 'squared_hinge'
    compute_dtype: str = "float32"


class LinearSVCModel(Model):
    def __init__(self, params, coef, intercept, class_values):
        self.params = params
        self.coef = coef            # f32[d, 1]
        self.intercept = intercept  # f32[1]
        self.class_values = tuple(class_values)
        self.n_iter_: int | None = None

    @property
    def state_pytree(self):
        return {"coef": self.coef, "intercept": self.intercept}

    @staticmethod
    @jax.jit
    def _margin_kernel(X, coef, intercept):
        return (X @ coef + intercept)[:, 0]

    def decision_function(self, table: TpuTable) -> np.ndarray:
        m = self._margin_kernel(table.X, self.coef, self.intercept)
        return np.asarray(m)[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        """Append rawPrediction (margin) and prediction columns."""
        margin = self._margin_kernel(table.X, self.coef, self.intercept)
        pred = (margin > self.params.threshold).astype(jnp.float32)
        new_attrs = list(table.domain.attributes) + [
            ContinuousVariable("rawPrediction"),
            DiscreteVariable("prediction", self.class_values),
        ]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        X = jnp.concatenate([table.X, margin[:, None], pred[:, None]], axis=1)
        return table.with_X(X, new_domain)

    def predict(self, table: TpuTable) -> np.ndarray:
        margin = self._margin_kernel(table.X, self.coef, self.intercept)
        pred = (margin > self.params.threshold).astype(jnp.float32)
        return np.asarray(pred)[: table.n_rows]


class LinearSVC(Estimator):
    ParamsCls = LinearSVCParams
    params: LinearSVCParams

    def _fit(self, table: TpuTable) -> LinearSVCModel:
        p = self.params
        y = table.y
        cvar = table.domain.class_var
        class_values = (
            cvar.values if isinstance(cvar, DiscreteVariable) and cvar.values
            else ("0", "1")
        )
        if len(class_values) != 2:
            raise ValueError(
                f"LinearSVC is binary (MLlib parity); got {len(class_values)} classes"
            )
        X, w = table.X, table.W
        inv_std = column_inv_std(X, w) if p.standardization else None
        alpha = p.elastic_net_param
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"elastic_net_param must be in [0, 1], got {alpha}")
        if alpha > 0.0 and p.reg_param > 0.0 and p.loss == "hinge":
            raise ValueError(
                "elastic_net_param > 0 needs a smooth data term for OWLQN; "
                "use loss='squared_hinge'"
            )
        result = fit_linear(
            X, y, w,
            jnp.float32(p.reg_param * (1.0 - alpha)),
            jnp.float32(p.tol),
            jnp.int32(p.max_iter),
            inv_std,
            jnp.float32(p.reg_param * alpha) if p.reg_param * alpha > 0.0 else None,
            loss_kind=p.loss,
            k=1,
            fit_intercept=p.fit_intercept,
            compute_dtype=jnp.dtype(p.compute_dtype),
        )
        coef = result.coef
        if inv_std is not None:
            coef = coef * inv_std[:, None]
        model = LinearSVCModel(p, coef, result.intercept, class_values)
        model.n_iter_ = concrete_or_none(result.n_iter, int)
        return model
