"""Frequent pattern mining — parity with ``pyspark.ml.fpm``: FPGrowth
(frequent itemsets + association rules) and PrefixSpan (sequential patterns).

MLlib runs PFP (parallel FP-Growth, Li et al.) — items partitioned across
executors, each building conditional FP-trees — and a distributed PrefixSpan
(SURVEY.md §2b; reconstructed, mount empty — public API: FPGrowth(minSupport
=0.3, minConfidence=0.8, itemsCol), model.freqItemsets, associationRules,
transform = rule-consequent prediction; PrefixSpan(minSupport,
maxPatternLength, maxLocalProjDBSize)). TPU-native placement:

* transactions become a **binary incidence matrix** ``X: f32[N, n_items]``
  (rows sharded over the mesh). Support counting — the entire hot loop of
  Apriori/FP-growth — is then ``(X[:, mask-products]ᵀ · W)``: candidate
  itemset supports for a whole level are ONE [N,c]@[c→reduce] masked-product
  + matmul batch on the MXU, with the row contraction GSPMD all-reduced over
  ICI (the treeAggregate moment). Level-wise candidate generation (tiny,
  set-algebra on item ids) stays host-side — it is pointer-chasing the TPU
  should never see.
* PrefixSpan keeps its projected-database recursion on host (inherently
  sequential/data-dependent), but counts every candidate extension level on
  device the same masked-matmul way when sequences are dense-encodable;
  gated to host counting otherwise.

Orange parity note: Orange3's own add-on family ships an 'Associate' add-on
(frequent itemsets) — this module covers the same canvas role.
"""

from __future__ import annotations

import dataclasses
import itertools

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, HasParams, Model, Params
from orange3_spark_tpu.models.text import _meta_col


@dataclasses.dataclass(frozen=True)
class FPGrowthParams(Params):
    min_support: float = 0.3      # MLlib minSupport (fraction of rows)
    min_confidence: float = 0.8   # MLlib minConfidence (rules)
    items_col: str = ""           # meta column of item lists; "" => X is binary
    max_pattern_length: int = 10  # guard on itemset size


def _incidence(table: TpuTable, items_col: str):
    """(binary incidence [N_pad, n_items] device array, item names)."""
    if not items_col:
        names = [v.name for v in table.domain.attributes]
        return (table.X > 0).astype(jnp.float32), names
    col = _meta_col(table, items_col)
    vocab: dict[str, int] = {}
    rows, cols = [], []
    for i, items in enumerate(col):
        items = items if isinstance(items, (list, tuple)) else str(items).split()
        for it in set(items):
            j = vocab.setdefault(str(it), len(vocab))
            rows.append(i)
            cols.append(j)
    M = np.zeros((table.n_pad, len(vocab)), dtype=np.float32)
    M[rows, cols] = 1.0
    names = [w for w, _ in sorted(vocab.items(), key=lambda kv: kv[1])]
    return jax.device_put(M, table.session.row_sharding), names


@jax.jit
def _support_chunk(B, W, members):
    hits = B @ members.T                                   # [N, c]
    sizes = jnp.sum(members, axis=1)                       # [c]
    full = (hits >= sizes[None, :] - 0.5).astype(jnp.float32)
    return full.T @ W                                      # [c] psum'd support


_SUPPORT_CHUNK_ROWS = 1 << 22  # f32 integers are exact below 2^24


def _support_batch(B, W, members):
    """Support of a batch of candidate itemsets.

    B: f32[N, m] binary incidence; members: f32[c, m] one row per candidate
    (1 where the item belongs). A row supports a candidate iff it contains
    every member item: count(row·members_row) == |candidate| — ONE
    [N,m]@[m,c] MXU matmul + compare per chunk, no per-candidate scan.

    Device accumulation is f32, whose integers are exact only below 2^24;
    row chunks are therefore capped at 2^22 and the per-chunk counts summed
    host-side in float64 (MLlib counts in 64-bit longs).
    """
    n = B.shape[0]
    if n <= _SUPPORT_CHUNK_ROWS:
        return np.asarray(jax.device_get(_support_chunk(B, W, members))).astype(np.float64)
    total = np.zeros((members.shape[0],), dtype=np.float64)
    for s in range(0, n, _SUPPORT_CHUNK_ROWS):
        e = min(s + _SUPPORT_CHUNK_ROWS, n)
        total += np.asarray(jax.device_get(_support_chunk(B[s:e], W[s:e], members)))
    return total


class FPGrowthModel(Model):
    def __init__(self, params, item_names, freq_itemsets, n_rows_weighted):
        self.params = params
        self.item_names = tuple(item_names)
        # list[(frozenset[int] item ids, float support_count)]
        self.freq_itemsets_ = freq_itemsets
        self.n_rows_weighted = n_rows_weighted
        self.association_rules_ = self._rules()

    @property
    def state_pytree(self):
        return {}

    def freq_itemsets(self):
        """MLlib freqItemsets frame: [{'items': [names], 'freq': count}]."""
        return [
            {"items": sorted(self.item_names[i] for i in s), "freq": c}
            for s, c in self.freq_itemsets_
        ]

    def _rules(self):
        """antecedent => consequent with confidence/lift/support (MLlib)."""
        sup = {s: c for s, c in self.freq_itemsets_}
        rules = []
        for s, c in self.freq_itemsets_:
            if len(s) < 2:
                continue
            # MLlib AssociationRules: exactly ONE consequent item per rule
            for cons_item in sorted(s):
                ante = s - {cons_item}
                if ante not in sup:
                    continue
                conf = c / sup[ante]
                if conf >= self.params.min_confidence:
                    cons_sup = sup.get(frozenset([cons_item]))
                    lift = (
                        conf / (cons_sup / self.n_rows_weighted)
                        if cons_sup else float("nan")
                    )
                    rules.append({
                        "antecedent": sorted(self.item_names[i] for i in ante),
                        "consequent": [self.item_names[cons_item]],
                        "confidence": conf,
                        "lift": lift,
                        "support": c / self.n_rows_weighted,
                    })
        return rules

    def transform(self, table: TpuTable) -> TpuTable:
        """MLlib transform: per row, union of rule consequents whose
        antecedent is contained in the row's items — emitted as one binary
        'pred_<item>' column per predictable item."""
        B, names = _incidence(table, self.params.items_col)
        name_to_id = {n: j for j, n in enumerate(names)}
        pred_items = sorted({it for r in self.association_rules_
                             for it in r["consequent"]})
        # batch ALL rules: one [N,m]@[m,R] antecedent matmul + one [N,R]@[R,P]
        # consequent mapping — never a per-rule device dispatch
        m = B.shape[1]
        usable = [r for r in self.association_rules_
                  if all(a in name_to_id for a in r["antecedent"])]
        if usable:
            ante_members = np.zeros((len(usable), m), dtype=np.float32)
            cons_map = np.zeros((len(usable), len(pred_items)), dtype=np.float32)
            for ri, r in enumerate(usable):
                ante_members[ri, [name_to_id[a] for a in r["antecedent"]]] = 1.0
                for it in r["consequent"]:
                    cons_map[ri, pred_items.index(it)] = 1.0
            AM = jnp.asarray(ante_members)
            sizes = jnp.sum(AM, axis=1)
            has_ante = (B @ AM.T >= sizes[None, :] - 0.5).astype(jnp.float32)
            fired = (has_ante @ jnp.asarray(cons_map)) > 0          # [N,P]
            has_item = jnp.stack(
                [B[:, name_to_id[it]] > 0 if it in name_to_id
                 else jnp.zeros((B.shape[0],), bool) for it in pred_items],
                axis=1,
            )
            # predict only items the row does not already contain (MLlib)
            out = (fired & ~has_item).astype(jnp.float32)
        else:
            out = jnp.zeros((B.shape[0], len(pred_items)), dtype=jnp.float32)
        new_attrs = list(table.domain.attributes) + [
            ContinuousVariable(f"pred_{it}") for it in pred_items
        ]
        domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        return table.with_X(jnp.concatenate([table.X, out], axis=1), domain)


class FPGrowth(Estimator):
    ParamsCls = FPGrowthParams
    params: FPGrowthParams

    def _fit(self, table: TpuTable) -> FPGrowthModel:
        p = self.params
        B, names = _incidence(table, p.items_col)
        W = table.W
        m = len(names)
        total_w = float(jax.device_get(jnp.sum(W)))
        min_count = p.min_support * total_w
        # level 1: single-item supports (chunked f64 accumulation)
        sup1 = _support_batch(B, W, jnp.eye(m, dtype=jnp.float32))
        freq: list[tuple[frozenset, float]] = []
        current = []
        for j in range(m):
            if sup1[j] >= min_count:
                s = frozenset([j])
                freq.append((s, float(sup1[j])))
                current.append(s)
        level = 1
        # level-wise growth (Apriori over the incidence matrix): candidate
        # generation host-side; support counting one batched matmul per level
        while current and level < p.max_pattern_length:
            level += 1
            cand = sorted({
                a | b for a, b in itertools.combinations(current, 2)
                if len(a | b) == level
            })
            # prune: all (level-1)-subsets must be frequent (Apriori property)
            fset = {s for s, _ in freq}
            cand = [
                c for c in cand
                if all(frozenset(sub) in fset
                       for sub in itertools.combinations(c, level - 1))
            ]
            if not cand:
                break
            members = np.zeros((len(cand), m), dtype=np.float32)
            for ci, s in enumerate(cand):
                members[ci, sorted(s)] = 1.0
            sup = _support_batch(B, W, jnp.asarray(members))
            current = []
            for ci, s in enumerate(cand):
                if sup[ci] >= min_count:
                    freq.append((s, float(sup[ci])))
                    current.append(s)
        return FPGrowthModel(p, names, freq, total_w)


# ------------------------------------------------------------------ PrefixSpan
@dataclasses.dataclass(frozen=True)
class PrefixSpanParams(Params):
    min_support: float = 0.1        # MLlib minSupport
    max_pattern_length: int = 10    # MLlib maxPatternLength
    max_local_proj_db_size: int = 32_000_000  # parity; host recursion here
    sequence_col: str = "sequence"  # meta column of item-list sequences


def _seq_contains(seq, pat) -> bool:
    """Itemset-subsequence containment: each pattern element must be a subset
    of a strictly later sequence element (greedy match is exact here)."""
    i = 0
    for elem in seq:
        if pat[i] <= elem:
            i += 1
            if i == len(pat):
                return True
    return False


class PrefixSpan(HasParams):
    """Sequential pattern mining (Pei et al.). Mirrors MLlib's API shape:
    no fit/model — ``find_frequent_sequential_patterns(table)`` returns the
    pattern frame. DFS over the pattern lattice with BOTH extension kinds:
    s-extension (item starts a new element) and i-extension (item joins the
    prefix's last itemset), so multi-item elements like <(a b)> are found.
    The recursion is host-side (inherently sequential control flow); each
    candidate's support is one containment scan over the sequences."""

    ParamsCls = PrefixSpanParams

    def find_frequent_sequential_patterns(self, table: TpuTable):
        p = self.params
        col = _meta_col(table, p.sequence_col)
        live = np.asarray(jax.device_get(table.W))[: len(col)] > 0
        seqs = []
        for i, s in enumerate(col):
            if not live[i]:
                continue
            if isinstance(s, (list, tuple)):
                seqs.append([
                    frozenset(e) if isinstance(e, (list, tuple, set, frozenset))
                    else frozenset([e])
                    for e in s
                ])
            else:
                seqs.append([frozenset([tok]) for tok in str(s).split()])
        n = len(seqs)
        min_count = max(p.min_support * n, 1.0)
        item_counts: dict[str, int] = {}
        for sq in seqs:
            for it in {x for e in sq for x in e}:
                item_counts[it] = item_counts.get(it, 0) + 1
        freq_items = sorted(it for it, c in item_counts.items() if c >= min_count)
        results: list[tuple[tuple, int]] = []

        def count(pat) -> int:
            return sum(1 for sq in seqs if _seq_contains(sq, pat))

        def explore(pat, total_items):
            if total_items >= p.max_pattern_length:
                return
            for it in freq_items:
                # s-extension: item opens a new element
                cand = pat + [frozenset([it])]
                c = count(cand)
                if c >= min_count:
                    results.append((tuple(tuple(sorted(e)) for e in cand), c))
                    explore(cand, total_items + 1)
                # i-extension: item joins the last element (dedup: only items
                # lexically after everything already in it)
                if pat and all(it > x for x in pat[-1]):
                    cand = pat[:-1] + [pat[-1] | {it}]
                    c = count(cand)
                    if c >= min_count:
                        results.append((tuple(tuple(sorted(e)) for e in cand), c))
                        explore(cand, total_items + 1)

        explore([], 0)
        return [
            {"sequence": [list(e) for e in pat], "freq": c}
            for pat, c in sorted(results, key=lambda r: (-r[1], r[0]))
        ]
