from orange3_spark_tpu.models.base import Estimator, Model, Params, Pipeline, PipelineModel, Transformer

__all__ = ["Estimator", "Model", "Params", "Pipeline", "PipelineModel", "Transformer"]
