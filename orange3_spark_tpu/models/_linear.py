"""Shared sharded L-BFGS trainer for linear models (LogReg, LinearSVC).

MLlib fits its linear classifiers with L-BFGS/OWLQN where each iteration's
loss+gradient is one ``treeAggregate`` over the cluster (SURVEY.md §3 step 3;
reconstructed, mount empty). TPU-native redesign: the ENTIRE optimization loop
— L-BFGS direction, zoom linesearch, convergence test — is a single jitted
``lax.while_loop``. The per-iteration all-reduce falls out of GSPMD: X is
sharded P('data', None), the loss contracts over the row axis, XLA inserts the
ICI all-reduce exactly where Spark would shuffle partial gradients to the
driver. No host round-trip per iteration (Spark pays driver↔executor latency
every step; we pay zero).

The matmuls  X @ coef  ([N,d] @ [d,k]) are the FLOP carriers and map straight
onto the MXU; optionally computed in bfloat16 with f32 accumulation.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
import optax.tree_utils as otu


class LinearFitResult(NamedTuple):
    coef: jax.Array       # [d, k]
    intercept: jax.Array  # [k]
    n_iter: jax.Array     # []
    final_loss: jax.Array # []


def lbfgs_minimize(value_fn, theta0, tol, max_iter, *, memory_size: int = 10):
    """Shared fused L-BFGS driver: minimize value_fn over the theta0 pytree
    inside one ``lax.while_loop`` (optax.lbfgs + zoom linesearch). Returns
    (theta, n_iter, final_value). Trace-time only — call from inside jit.

    This is the one implementation of the optimizer loop; fit_linear, AFT,
    and the MLP trainer all route through it.
    """
    opt = optax.lbfgs(memory_size=memory_size)
    value_and_grad = optax.value_and_grad_from_state(value_fn)

    def step(carry):
        theta, state = carry
        value, grad = value_and_grad(theta, state=state)
        updates, state = opt.update(
            grad, state, theta, value=value, grad=grad, value_fn=value_fn
        )
        theta = optax.apply_updates(theta, updates)
        return theta, state

    def keep_going(carry):
        _, state = carry
        count = otu.tree_get(state, "count")
        grad = otu.tree_get(state, "grad")
        gnorm = otu.tree_norm(grad)
        # first iteration always runs (grad in fresh state is zero), but
        # max_iter=0 must return the zero init, matching MLlib maxIter=0
        return (max_iter > 0) & ((count == 0) | ((count < max_iter) & (gnorm > tol)))

    theta, state = jax.lax.while_loop(keep_going, step, (theta0, opt.init(theta0)))
    n_iter = otu.tree_get(state, "count")
    # converged loss is already in the linesearch state; only the max_iter=0
    # path (state still holds optax's inf sentinel) pays a fresh evaluation
    final_value = jax.lax.cond(
        n_iter == 0, lambda: value_fn(theta), lambda: otu.tree_get(state, "value")
    )
    return theta, n_iter, final_value


def _make_objective(loss_kind: str, fit_intercept: bool, compute_dtype):
    """Builds loss(theta, X, y, w, reg_l2, sum_w) -> scalar.

    Losses (all per-row, weighted, normalized by total weight — MLlib's
    objective convention: (1/Σw) Σ wᵢ·lossᵢ + regParam·R(coef), intercept
    unregularized):
      * 'logistic'      — softmax cross-entropy over k classes
      * 'hinge'         — binary SVM hinge on the first logit (LinearSVC)
      * 'squared_hinge' — smooth hinge variant (plays nicer with L-BFGS)
      * 'squared'       — least squares (LinearRegression)
    """

    def objective(theta, X, y, w, reg_l2, sum_w, col_scale):
        coef = theta["coef"]
        intercept = theta["intercept"]
        Xc = X.astype(compute_dtype)
        # fold per-column standardization into the coefficient side: X@(s*B)
        # keeps the [N,d] operand untouched (no scaled copy of the data ever
        # materializes — XLA fuses the [d,k] scale into the matmul epilogue)
        logits = jnp.dot(Xc, (coef * col_scale[:, None]).astype(compute_dtype),
                         preferred_element_type=jnp.float32)
        if fit_intercept:
            logits = logits + intercept
        if loss_kind == "logistic":
            logp = jax.nn.log_softmax(logits, axis=-1)
            row_loss = -jnp.take_along_axis(
                logp, y.astype(jnp.int32)[:, None], axis=1
            )[:, 0]
        elif loss_kind in ("hinge", "squared_hinge"):
            sign = 2.0 * y - 1.0
            margin = jnp.maximum(0.0, 1.0 - sign * logits[:, 0])
            row_loss = margin if loss_kind == "hinge" else margin**2
        elif loss_kind == "squared":
            row_loss = 0.5 * (logits[:, 0] - y) ** 2
        else:  # pragma: no cover
            raise ValueError(loss_kind)
        data_loss = jnp.sum(row_loss * w) / sum_w
        return data_loss + 0.5 * reg_l2 * jnp.sum(coef * coef)

    return objective


@partial(
    jax.jit,
    static_argnames=("loss_kind", "k", "fit_intercept", "memory_size", "compute_dtype"),
)
def fit_linear(
    X,             # f32[N_pad, d]  sharded P('data', None)
    y,             # f32[N_pad]     labels (class index, ±target, or regression y)
    w,             # f32[N_pad]     weights; 0 on padding
    reg_l2,        # f32[] L2 regParam
    tol,           # f32[] gradient-norm tolerance
    max_iter,      # i32[]
    col_scale=None,  # f32[d] standardization scale folded into the matmul
    *,
    loss_kind: str,
    k: int,
    fit_intercept: bool = True,
    memory_size: int = 10,
    compute_dtype=jnp.float32,
):
    """One fused XLA program: full L-BFGS fit of a linear model.

    Note: with ``col_scale`` the optimization runs in the scaled space; the
    returned coef is the SCALED-space coefficient — callers multiply by the
    scale to return to original feature space (MLlib does the same rescale).
    """
    d = X.shape[1]
    if col_scale is None:
        col_scale = jnp.ones((d,), jnp.float32)
    theta0 = {
        "coef": jnp.zeros((d, k), jnp.float32),
        "intercept": jnp.zeros((k,), jnp.float32),
    }
    sum_w = jnp.maximum(jnp.sum(w), EPS_TOTAL_WEIGHT)
    objective = _make_objective(loss_kind, fit_intercept, compute_dtype)

    def value_fn(theta):
        return objective(theta, X, y, w, reg_l2, sum_w, col_scale)

    theta, n_iter, final_loss = lbfgs_minimize(
        value_fn, theta0, tol, max_iter, memory_size=memory_size
    )
    return LinearFitResult(
        coef=theta["coef"],
        intercept=theta["intercept"] if fit_intercept else jnp.zeros((k,)),
        n_iter=n_iter,
        final_loss=final_loss,
    )


# MLlib-style scale-only standardization factor; shared stats kernels.
from orange3_spark_tpu.ops.stats import (  # noqa: E402
    EPS_TOTAL_WEIGHT,
    inv_std_scale as column_inv_std,
)
