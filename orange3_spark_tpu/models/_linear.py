"""Shared sharded L-BFGS trainer for linear models (LogReg, LinearSVC).

MLlib fits its linear classifiers with L-BFGS/OWLQN where each iteration's
loss+gradient is one ``treeAggregate`` over the cluster (SURVEY.md §3 step 3;
reconstructed, mount empty). TPU-native redesign: the ENTIRE optimization loop
— L-BFGS direction, zoom linesearch, convergence test — is a single jitted
``lax.while_loop``. The per-iteration all-reduce falls out of GSPMD: X is
sharded P('data', None), the loss contracts over the row axis, XLA inserts the
ICI all-reduce exactly where Spark would shuffle partial gradients to the
driver. No host round-trip per iteration (Spark pays driver↔executor latency
every step; we pay zero).

The matmuls  X @ coef  ([N,d] @ [d,k]) are the FLOP carriers and map straight
onto the MXU; optionally computed in bfloat16 with f32 accumulation.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
import optax.tree_utils as otu

from orange3_spark_tpu.exec.donate import donating_jit, donation_enabled

# optax 0.2.4 renamed tree_l2_norm -> tree_norm; container pins vary, so
# accept either (same quantity: the global L2 norm of the pytree)
_tree_norm = getattr(otu, "tree_norm", None) or otu.tree_l2_norm


class LinearFitResult(NamedTuple):
    coef: jax.Array       # [d, k]
    intercept: jax.Array  # [k]
    n_iter: jax.Array     # []
    final_loss: jax.Array # []


def lbfgs_minimize(value_fn, theta0, tol, max_iter, *, memory_size: int = 10):
    """Shared fused L-BFGS driver: minimize value_fn over the theta0 pytree
    inside one ``lax.while_loop`` (optax.lbfgs + zoom linesearch). Returns
    (theta, n_iter, final_value). Trace-time only — call from inside jit.

    This is the one implementation of the optimizer loop; fit_linear, AFT,
    and the MLP trainer all route through it.
    """
    opt = optax.lbfgs(memory_size=memory_size)
    value_and_grad = optax.value_and_grad_from_state(value_fn)

    def step(carry):
        theta, state = carry
        value, grad = value_and_grad(theta, state=state)
        updates, state = opt.update(
            grad, state, theta, value=value, grad=grad, value_fn=value_fn
        )
        theta = optax.apply_updates(theta, updates)
        return theta, state

    def keep_going(carry):
        _, state = carry
        count = otu.tree_get(state, "count")
        grad = otu.tree_get(state, "grad")
        gnorm = _tree_norm(grad)
        # first iteration always runs (grad in fresh state is zero), but
        # max_iter=0 must return the zero init, matching MLlib maxIter=0
        return (max_iter > 0) & ((count == 0) | ((count < max_iter) & (gnorm > tol)))

    theta, state = jax.lax.while_loop(keep_going, step, (theta0, opt.init(theta0)))
    n_iter = otu.tree_get(state, "count")
    # converged loss is already in the linesearch state; only the max_iter=0
    # path (state still holds optax's inf sentinel) pays a fresh evaluation
    final_value = jax.lax.cond(
        n_iter == 0, lambda: value_fn(theta), lambda: otu.tree_get(state, "value")
    )
    return theta, n_iter, final_value


def owlqn_minimize(
    smooth_fn,
    x0,
    l1_weight,
    tol,
    max_iter,
    *,
    memory_size: int = 10,
    max_backtracks: int = 25,
):
    """Orthant-Wise Limited-memory Quasi-Newton (Andrew & Gao 2007), fused
    into ONE ``lax.while_loop``: minimizes  smooth_fn(x) + Σ l1_weight·|x|.

    MLlib fits elasticNetParam>0 linear models with Breeze's OWLQN, one
    treeAggregate per iteration (SURVEY.md §2b row "LogisticRegression /
    LinearSVC"; reconstructed, mount empty). Here the whole solver — pseudo-
    gradient, two-loop recursion over fixed-size (m, n) memory buffers,
    orthant-projected backtracking linesearch — is a single XLA program; the
    gradient all-reduce falls out of GSPMD like the L2 path's.

    Args:
      smooth_fn: x[n] -> scalar, the differentiable part of the objective.
      l1_weight: f32[n] per-coordinate L1 penalty (0 on unpenalized coords,
        e.g. the intercept).
    Returns (x, n_iter, final_full_value). Trace-time only — call under jit.
    """
    m = memory_size
    c1 = 1e-4
    grad_fn = jax.value_and_grad(smooth_fn)

    def full_value(x):
        return smooth_fn(x) + jnp.sum(l1_weight * jnp.abs(x))

    def pseudo_grad(x, g):
        # subgradient of minimum norm: steepest-descent direction of F
        right = g + l1_weight
        left = g - l1_weight
        return jnp.where(
            x > 0, right,
            jnp.where(
                x < 0, left,
                jnp.where(right < 0, right, jnp.where(left > 0, left, 0.0)),
            ),
        )

    def two_loop(gp, S, Y, rho, n_mem):
        # newest pair at slot m-1; the last n_mem slots are valid
        valid = jnp.arange(m) >= (m - n_mem)

        def bwd(j, carry):
            q, alpha = carry
            i = m - 1 - j
            a_i = jnp.where(valid[i], rho[i] * jnp.dot(S[i], q), 0.0)
            return q - a_i * Y[i], alpha.at[i].set(a_i)

        q, alpha = jax.lax.fori_loop(0, m, bwd, (gp, jnp.zeros((m,), gp.dtype)))
        sy = jnp.dot(S[m - 1], Y[m - 1])
        yy = jnp.dot(Y[m - 1], Y[m - 1])
        gamma = jnp.where(n_mem > 0, sy / jnp.maximum(yy, 1e-30), 1.0)

        def fwd(i, r):
            b_i = jnp.where(valid[i], rho[i] * jnp.dot(Y[i], r), 0.0)
            return r + S[i] * (alpha[i] - b_i)

        return jax.lax.fori_loop(0, m, fwd, gamma * q)

    def linesearch(x, F, gp, d, n_mem):
        # orthant of the current point (sign forced by -gp on zero coords);
        # every trial point is projected back into it
        xi = jnp.where(x != 0, jnp.sign(x), jnp.sign(-gp))
        t0 = jnp.where(
            n_mem > 0, 1.0, 1.0 / jnp.maximum(jnp.linalg.norm(d), 1e-12)
        )

        def body(carry):
            t, k, _, _, _ = carry
            x_t = jnp.where((x + t * d) * xi > 0, x + t * d, 0.0)
            F_t = full_value(x_t)
            ok = F_t <= F + c1 * jnp.dot(gp, x_t - x)
            return t * 0.5, k + 1, x_t, F_t, ok

        def cond(carry):
            _, k, _, _, ok = carry
            return (~ok) & (k < max_backtracks)

        _, _, x_t, F_t, ok = jax.lax.while_loop(
            cond, body, (t0, 0, x, F, False)
        )
        # an exhausted linesearch must NOT adopt its rejected trial point —
        # keep the last accepted iterate and let the stalled flag end the loop
        x_t = jnp.where(ok, x_t, x)
        F_t = jnp.where(ok, F_t, F)
        return x_t, F_t, ok

    def step(carry):
        x, F, g, _, S, Y, rho, n_mem, it, _ = carry
        gp = pseudo_grad(x, g)
        d = -two_loop(gp, S, Y, rho, n_mem)
        d = jnp.where(d * gp < 0, d, 0.0)  # keep only descent-aligned coords
        # a fully-zeroed direction would make the linesearch accept x_t == x
        # (Armijo holds trivially at step 0) and spin to max_iter — treat it
        # as converged/stalled instead
        d_zero = ~jnp.any(d != 0.0)
        x_new, F_new, ok = linesearch(x, F, gp, d, n_mem)
        _, g_new = grad_fn(x_new)
        s, yv = x_new - x, g_new - g
        sy = jnp.dot(s, yv)
        keep = sy > 1e-10  # curvature condition: only well-posed pairs enter
        S = jnp.where(keep, jnp.roll(S, -1, axis=0).at[m - 1].set(s), S)
        Y = jnp.where(keep, jnp.roll(Y, -1, axis=0).at[m - 1].set(yv), Y)
        rho = jnp.where(
            keep, jnp.roll(rho, -1).at[m - 1].set(1.0 / sy), rho
        )
        n_mem = jnp.where(keep, jnp.minimum(n_mem + 1, m), n_mem)
        gpnorm = jnp.linalg.norm(pseudo_grad(x_new, g_new))
        return x_new, F_new, g_new, gpnorm, S, Y, rho, n_mem, it + 1, ~ok | d_zero

    def keep_going(carry):
        _, _, _, gpnorm, *_, it, stalled = carry
        return (it < max_iter) & (gpnorm > tol) & (~stalled)

    n = x0.shape[0]
    f0, g0 = grad_fn(x0)
    F0 = f0 + jnp.sum(l1_weight * jnp.abs(x0))
    init = (
        x0, F0, g0, jnp.linalg.norm(pseudo_grad(x0, g0)),
        jnp.zeros((m, n), x0.dtype), jnp.zeros((m, n), x0.dtype),
        jnp.zeros((m,), x0.dtype), jnp.int32(0), jnp.int32(0), False,
    )
    x, F, _, _, _, _, _, _, n_iter, _ = jax.lax.while_loop(
        keep_going, step, init
    )
    return x, n_iter, F


def _make_objective(loss_kind: str, fit_intercept: bool, compute_dtype):
    """Builds loss(theta, X, y, w, reg_l2, sum_w) -> scalar.

    Losses (all per-row, weighted, normalized by total weight — MLlib's
    objective convention: (1/Σw) Σ wᵢ·lossᵢ + regParam·R(coef), intercept
    unregularized):
      * 'logistic'      — softmax cross-entropy over k classes
      * 'hinge'         — binary SVM hinge on the first logit (LinearSVC)
      * 'squared_hinge' — smooth hinge variant (plays nicer with L-BFGS)
      * 'squared'       — least squares (LinearRegression)
    """

    def objective(theta, X, y, w, reg_l2, sum_w, col_scale):
        coef = theta["coef"]
        intercept = theta["intercept"]
        # THE in-scan decode point for compressed caches (io/codec.py):
        # a bf16-cached X widens here — one fused convert-on-load, so the
        # streaming replay scan reads half the HBM/spill bytes while the
        # matmul accumulates in f32 exactly as before (f32 input: no-op).
        # tests/test_cache_codec.py pins the bf16-vs-f32 fit divergence.
        Xc = X.astype(compute_dtype)
        # fold per-column standardization into the coefficient side: X@(s*B)
        # keeps the [N,d] operand untouched (no scaled copy of the data ever
        # materializes — XLA fuses the [d,k] scale into the matmul epilogue)
        logits = jnp.dot(Xc, (coef * col_scale[:, None]).astype(compute_dtype),
                         preferred_element_type=jnp.float32)
        if fit_intercept:
            logits = logits + intercept
        row_loss = per_row_loss(loss_kind, logits, y)
        data_loss = jnp.sum(row_loss * w) / sum_w
        return data_loss + 0.5 * reg_l2 * jnp.sum(coef * coef)

    return objective


def per_row_loss(loss_kind: str, logits, y):
    """Per-row loss from precomputed logits — the ONE implementation shared
    by the dense objective, the streaming step, and the hashed-sparse path
    (whose logits come from an embedding gather, not a matmul)."""
    if loss_kind == "logistic":
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(
            logp, y.astype(jnp.int32)[:, None], axis=1
        )[:, 0]
    if loss_kind == "binary_logistic":
        # single-logit sigmoid form (k=1): numerically stable softplus(z)-z*y.
        # Identical optimum to 2-column softmax but HALF the embedding-table
        # gather/scatter traffic — the hashed Criteo path's hot bytes.
        z = logits[:, 0]
        return jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    if loss_kind in ("hinge", "squared_hinge"):
        sign = 2.0 * y - 1.0
        margin = jnp.maximum(0.0, 1.0 - sign * logits[:, 0])
        return margin if loss_kind == "hinge" else margin**2
    if loss_kind == "squared":
        return 0.5 * (logits[:, 0] - y) ** 2
    raise ValueError(loss_kind)  # pragma: no cover


@donating_jit(
    static_argnames=("loss_kind", "k", "fit_intercept", "memory_size",
                     "compute_dtype"),
    donate_argnums=(0, 1, 2),
)
def _fit_linear_jit(
    X, y, w, reg_l2, tol, max_iter, col_scale, reg_l1,
    *,
    loss_kind: str,
    k: int,
    fit_intercept: bool = True,
    memory_size: int = 10,
    compute_dtype=jnp.float32,
):
    d = X.shape[1]
    if col_scale is None:
        col_scale = jnp.ones((d,), jnp.float32)
    theta0 = {
        "coef": jnp.zeros((d, k), jnp.float32),
        "intercept": jnp.zeros((k,), jnp.float32),
    }
    sum_w = jnp.maximum(jnp.sum(w), EPS_TOTAL_WEIGHT)
    objective = _make_objective(loss_kind, fit_intercept, compute_dtype)

    def value_fn(theta):
        return objective(theta, X, y, w, reg_l2, sum_w, col_scale)

    if reg_l1 is not None:
        from jax.flatten_util import ravel_pytree

        x0, unravel = ravel_pytree(theta0)
        # L1 hits the coefficients only — never the intercept (MLlib)
        l1_mask, _ = ravel_pytree(
            {"coef": jnp.ones((d, k), jnp.float32),
             "intercept": jnp.zeros((k,), jnp.float32)}
        )
        x, n_iter, final_loss = owlqn_minimize(
            lambda x: value_fn(unravel(x)),
            x0, reg_l1 * l1_mask, tol, max_iter, memory_size=memory_size,
        )
        theta = unravel(x)
    else:
        theta, n_iter, final_loss = lbfgs_minimize(
            value_fn, theta0, tol, max_iter, memory_size=memory_size
        )
    return LinearFitResult(
        coef=theta["coef"],
        intercept=theta["intercept"] if fit_intercept else jnp.zeros((k,)),
        n_iter=n_iter,
        final_loss=final_loss,
    )


def fit_linear(
    X,             # f32[N_pad, d]  sharded P('data', None)
    y,             # f32[N_pad]     labels (class index, ±target, or regression y)
    w,             # f32[N_pad]     weights; 0 on padding
    reg_l2,        # f32[] L2 regParam
    tol,           # f32[] gradient-norm tolerance
    max_iter,      # i32[]
    col_scale=None,  # f32[d] standardization scale folded into the matmul
    reg_l1=None,     # f32[] L1 strength (elasticNet); None -> pure-L2 L-BFGS
    *,
    loss_kind: str,
    k: int,
    fit_intercept: bool = True,
    memory_size: int = 10,
    compute_dtype=jnp.float32,
    donate_data: bool = False,
):
    """One fused XLA program: full L-BFGS (or OWLQN when reg_l1 is given)
    fit of a linear model.

    MLlib's regParam/elasticNetParam split maps to
    ``reg_l2 = regParam*(1-alpha), reg_l1 = regParam*alpha``; with
    standardization the L1 applies in the SCALED space, matching MLlib.

    Note: with ``col_scale`` the optimization runs in the scaled space; the
    returned coef is the SCALED-space coefficient — callers multiply by the
    scale to return to original feature space (MLlib does the same rescale).

    ``donate_data=True`` donates the (X, y, w) buffers to the fit (the
    exec/donate.py sweep): the estimator entry points pass table-BORROWED
    arrays that must survive for transform/evaluate, so donation is opt-in
    for callers feeding one-shot transient batches (tuning folds, staged
    refit loops) — it frees the batch's HBM the moment the fit consumes
    it. Bit-identical either way (donation is pure buffer aliasing).
    """
    jitted = (_fit_linear_jit.donated
              if donate_data and donation_enabled()
              else _fit_linear_jit.plain)
    return jitted(
        X, y, w, reg_l2, tol, max_iter, col_scale, reg_l1,
        loss_kind=loss_kind, k=k, fit_intercept=fit_intercept,
        memory_size=memory_size, compute_dtype=compute_dtype,
    )


# MLlib-style scale-only standardization factor; shared stats kernels.
from orange3_spark_tpu.ops.stats import (  # noqa: E402
    EPS_TOTAL_WEIGHT,
    inv_std_scale as column_inv_std,
)
