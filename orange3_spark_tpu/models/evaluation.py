"""Evaluators — ``pyspark.ml.evaluation`` capability parity.

BinaryClassificationEvaluator (areaUnderROC/PR), MulticlassClassification-
Evaluator (accuracy/f1/precision/recall), RegressionEvaluator (rmse/mse/mae/r2),
ClusteringEvaluator (silhouette). All computed as weighted device reductions
over the sharded prediction columns a model's transform() appended.
(SURVEY.md §2b — reconstructed, mount empty; evaluator widgets in the add-on
wrap these MLlib classes.)
"""

from __future__ import annotations

import dataclasses
from functools import partial as _partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.exec.donate import donating_jit
from orange3_spark_tpu.models.base import Params
from orange3_spark_tpu.ops.stats import EPS_TOTAL_WEIGHT


def _col(table: TpuTable, name: str):
    return table.column(name)


@dataclasses.dataclass(frozen=True)
class EvaluatorParams(Params):
    metric_name: str = ""
    prediction_col: str = "prediction"
    label_col: str = ""          # default: the table's class var
    probability_col: str = ""    # binary: score column (default probability_<pos>)


class _Evaluator:
    ParamsCls = EvaluatorParams
    default_metric = ""

    def __init__(self, params: EvaluatorParams | None = None, **kwargs):
        self.params = params or EvaluatorParams(**kwargs)

    def _label(self, table: TpuTable):
        p = self.params
        return _col(table, p.label_col) if p.label_col else table.y

    def evaluate(self, table: TpuTable) -> float:
        metric = self.params.metric_name or self.default_metric
        return float(self._compute(table, metric))

    def _compute(self, table: TpuTable, metric: str):
        raise NotImplementedError


@jax.jit
def _weighted_auc(score, label, w):
    """Weighted ROC AUC via the rank statistic, O(N log N) device sort.

    Tied scores get the exact weighted MIDRANK of their tie group (cumulative
    weight before the group + half the group's weight), so the result is
    independent of sort order among ties — all-equal scores give exactly 0.5.
    """
    n = score.shape[0]
    order = jnp.argsort(score)
    s, y, ww = score[order], label[order], w[order]
    cw = jnp.cumsum(ww)
    # tie groups: group id = number of strict increases seen so far
    new_group = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                 (s[1:] > s[:-1]).astype(jnp.int32)])
    gid = jnp.cumsum(new_group)                      # [N] in [0, n)
    group_w = jax.ops.segment_sum(ww, gid, num_segments=n)
    group_end_cw = jax.ops.segment_max(cw, gid, num_segments=n)
    midrank_g = group_end_cw - group_w / 2.0
    rank = midrank_g[gid]
    pos_w = jnp.sum(jnp.where(y > 0, ww, 0.0))
    neg_w = jnp.sum(jnp.where(y <= 0, ww, 0.0))
    sum_pos_ranks = jnp.sum(jnp.where(y > 0, rank * ww, 0.0))
    auc = (sum_pos_ranks / jnp.maximum(pos_w, EPS_TOTAL_WEIGHT)
           - pos_w / 2.0) / jnp.maximum(neg_w, EPS_TOTAL_WEIGHT)
    return jnp.clip(auc, 0.0, 1.0)


@jax.jit
def _weighted_auc_pr(score, label, w):
    """Weighted area under the precision-recall curve: step integration at
    descending score thresholds, with tied scores collapsed to one curve
    point (the tie-group end), matching sklearn's average_precision on
    distinct scores and remaining order-independent under ties."""
    n = score.shape[0]
    order = jnp.argsort(-score)
    s, y, ww = score[order], label[order], w[order]
    tp = jnp.cumsum(jnp.where(y > 0, ww, 0.0))
    fp = jnp.cumsum(jnp.where(y <= 0, ww, 0.0))
    pos_w = jnp.maximum(tp[-1], EPS_TOTAL_WEIGHT)
    precision = tp / jnp.maximum(tp + fp, EPS_TOTAL_WEIGHT)
    recall = tp / pos_w
    # dense tie-group ids (descending order -> strict decrease starts a group)
    gid = jnp.cumsum(jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                      (s[1:] < s[:-1]).astype(jnp.int32)]))
    is_end = jnp.concatenate([(s[1:] < s[:-1]), jnp.ones((1,), bool)])
    # per-group curve point = values at the group's end element
    g_recall = jax.ops.segment_sum(jnp.where(is_end, recall, 0.0), gid, num_segments=n)
    g_prec = jax.ops.segment_sum(jnp.where(is_end, precision, 0.0), gid, num_segments=n)
    prev_recall = jnp.concatenate([jnp.zeros((1,)), g_recall[:-1]])
    # empty trailing group slots have g_prec == g_recall == 0 -> zero step
    steps = jnp.maximum(g_recall - prev_recall, 0.0) * g_prec
    return jnp.clip(jnp.sum(steps), 0.0, 1.0)


def _labeled_chunk_stream(source, session, chunk_rows):
    """Shared chunk plumbing for the streaming evaluators: rechunk a
    labeled (X, y[, w]) source into padded device triples with
    parse/DMA-vs-compute overlap (the same engine the streaming fits
    use)."""
    from orange3_spark_tpu.core.session import TpuSession
    from orange3_spark_tpu.io.multihost import put_sharded
    from orange3_spark_tpu.io.streaming import (
        _pad_chunk, _rechunk, prefetch_map,
    )

    session = session or TpuSession.builder_get_or_create()
    pad_rows = session.pad_rows(chunk_rows)
    row_sh, vec_sh = session.row_sharding, session.vector_sharding

    def prep(chunk):
        X_np, y_np, w_np = chunk
        if y_np is None:
            raise ValueError("streaming evaluation needs labeled chunks")
        Xp, yp, wp = _pad_chunk(X_np, y_np, w_np, pad_rows, X_np.shape[1])
        return (put_sharded(Xp, row_sh), put_sharded(yp, vec_sh),
                put_sharded(wp, vec_sh))

    return prefetch_map(prep, _rechunk(source(), pad_rows), depth=2)


def _bound(steps, token):
    from orange3_spark_tpu.utils.dispatch import bound_dispatch

    bound_dispatch(steps, token, period=8)


@donating_jit(static_argnames=("n_bins",), donate_argnums=(0,))
def _binary_stream_fold(acc, s, y, w, *, n_bins: int):
    """Fold one scored chunk into the per-class score histograms (binned
    AUC, error O(1/n_bins)) and return the chunk's weighted
    logloss/correct/count sums as separate scalars — those are summed in
    f64 on the host at finalize, because a single f32 running scalar
    drifts ~1e-4 relative by 1B rows (ulp 64 at 1e9)."""
    s = jnp.clip(s, 1e-7, 1.0 - 1e-7)
    b = jnp.clip((s * n_bins).astype(jnp.int32), 0, n_bins - 1)
    y = (y > 0.5).astype(jnp.float32)
    acc = {
        "hp": acc["hp"].at[b].add(w * y),
        "hn": acc["hn"].at[b].add(w * (1.0 - y)),
    }
    ll = -jnp.sum(w * (y * jnp.log(s) + (1.0 - y) * jnp.log1p(-s)))
    ok = jnp.sum(w * ((s > 0.5) == (y > 0.5)).astype(jnp.float32))
    return acc, (ll, ok, jnp.sum(w))


def evaluate_binary_stream(score_fn, source, *, session=None,
                           chunk_rows: int = 1 << 18,
                           n_bins: int = 4096) -> dict:
    """Binary metrics over a chunk stream — evaluate a 1B-row holdout
    without holding it (the in-memory evaluator's exact-sort AUC needs
    every score resident; Spark's BinaryClassificationMetrics bins the
    same way).

    ``score_fn(X_device) -> P(y=1)`` per padded chunk (e.g. a fitted
    model's probability head); ``source`` yields ``(X, y[, w])`` tuple
    chunks. One jitted fold per chunk (donated accumulator): per-class
    score histograms give AUC to O(1/n_bins); logloss/accuracy/count are
    per-chunk device sums totalled in f64 on host (exact at any scale). Returns {'auc', 'logloss', 'accuracy', 'count'}.
    """
    acc = {
        "hp": jnp.zeros((n_bins,), jnp.float32),
        "hn": jnp.zeros((n_bins,), jnp.float32),
    }
    chunk_sums = []      # tiny device scalars; fetched once at the end
    for steps, (Xd, yd, wd) in enumerate(
            _labeled_chunk_stream(source, session, chunk_rows), start=1):
        acc, sums = _binary_stream_fold(acc, score_fn(Xd), yd, wd,
                                        n_bins=n_bins)
        chunk_sums.append(sums)
        _bound(steps, sums[2])
    if not chunk_sums:
        # match the multiclass/regression evaluators: a misconfigured
        # source must fail loudly, not return plausible-looking zeros
        raise ValueError("stream produced no chunks")
    host = jax.device_get(acc)
    sums = np.asarray(jax.device_get(chunk_sums), np.float64)
    ll_tot, ok_tot, n_tot = (float(sums[:, j].sum()) for j in range(3))
    hp = np.asarray(host["hp"], np.float64)
    hn = np.asarray(host["hn"], np.float64)
    P, N = hp.sum(), hn.sum()
    cum_neg_below = np.concatenate([[0.0], np.cumsum(hn)[:-1]])
    auc = (float(np.sum(hp * (cum_neg_below + 0.5 * hn)) / (P * N))
           if P > 0 and N > 0 else float("nan"))
    n = max(n_tot, 1e-12)
    return {
        "auc": auc,
        "logloss": ll_tot / n,
        "accuracy": ok_tot / n,
        "count": n_tot,
    }


@_partial(jax.jit, static_argnames=("n_classes",))
def _oor_weight(p, y, w, n_classes):
    """Weight of rows one_hot would silently zero out (class id outside
    [0, n_classes)) — surfaced instead of vanishing."""
    bad = ((p < 0) | (p >= n_classes) | (y < 0) | (y >= n_classes))
    return jnp.sum(jnp.where(bad, w, 0.0))


def evaluate_multiclass_stream(predict_fn, source, *, n_classes: int,
                               session=None,
                               chunk_rows: int = 1 << 18) -> dict:
    """Multiclass metrics over a chunk stream: per-chunk [k, k] weighted
    confusion matrices, totalled in f64 on host (a single f32 running
    matrix drifts ~1e-4 by 1e9 rows — the binary path's lesson), every
    confusion-derived metric computed from the total —
    MulticlassMetrics' role at 1B-holdout scale. ``predict_fn(X_device)
    -> class ids``. Returns accuracy/f1/weightedPrecision/weightedRecall
    /count + the confusion matrix + ``dropped_weight`` (rows whose label
    or prediction falls outside [0, n_classes) leave every metric; a
    nonzero value means n_classes is wrong)."""
    chunk_cs = []
    chunk_oor = []
    for steps, (Xd, yd, wd) in enumerate(
            _labeled_chunk_stream(source, session, chunk_rows), start=1):
        p = predict_fn(Xd)
        chunk_cs.append(_confusion_weighted(p, yd, wd, n_classes))
        chunk_oor.append(_oor_weight(p, yd, wd, n_classes))
        _bound(steps, chunk_cs[-1])
    if not chunk_cs:
        raise ValueError("stream produced no chunks")
    Ch = np.asarray(jax.device_get(chunk_cs), np.float64).sum(axis=0)
    out = {m: MulticlassClassificationEvaluator.from_confusion(Ch, m)
           for m in ("accuracy", "f1", "weightedPrecision",
                     "weightedRecall")}
    out["count"] = float(Ch.sum())
    out["confusion"] = Ch
    out["dropped_weight"] = float(
        np.asarray(jax.device_get(chunk_oor), np.float64).sum())
    return out


@jax.jit
def _regression_stream_sums(s, y, w, shift):
    """Per-chunk weighted sums for streaming regression metrics; the
    label moments accumulate on y - shift (r2's ss_tot is
    shift-invariant, and the raw identity loses f32 bits on large-mean
    labels — fares, timestamps)."""
    err = s - y
    z = y - shift
    return (jnp.sum(w), jnp.sum(w * err * err),
            jnp.sum(w * jnp.abs(err)), jnp.sum(w * z),
            jnp.sum(w * z * z))


def evaluate_regression_stream(predict_fn, source, *, session=None,
                               chunk_rows: int = 1 << 18) -> dict:
    """Regression metrics over a chunk stream — exact weighted
    rmse/mse/mae/r2 from per-chunk device sums totalled in f64 on host
    (RegressionMetrics' role at any scale). ``predict_fn(X_device) ->
    predictions``."""
    chunk_sums = []
    shift = None
    for steps, (Xd, yd, wd) in enumerate(
            _labeled_chunk_stream(source, session, chunk_rows), start=1):
        if shift is None:
            # first chunk's weighted label mean anchors the accumulation
            tot = jnp.maximum(jnp.sum(wd), EPS_TOTAL_WEIGHT)
            shift = jnp.sum(yd * wd) / tot
        sums = _regression_stream_sums(predict_fn(Xd), yd, wd, shift)
        chunk_sums.append(sums)
        _bound(steps, sums[0])
    if not chunk_sums:
        raise ValueError("stream produced no chunks")
    S = np.asarray(jax.device_get(chunk_sums), np.float64).sum(axis=0)
    n, ss_err, abs_err, sz, szz = S
    n = max(n, 1e-12)
    mse = ss_err / n
    ss_tot = max(szz - sz * sz / n, 1e-12)
    return {
        "rmse": float(np.sqrt(mse)), "mse": float(mse),
        "mae": float(abs_err / n),
        "r2": float(1.0 - ss_err / ss_tot),
        "count": float(S[0]),
    }


class BinaryClassificationEvaluator(_Evaluator):
    default_metric = "areaUnderROC"

    def _compute(self, table: TpuTable, metric: str):
        p = self.params
        label = self._label(table)
        names = [v.name for v in table.domain.attributes]
        if p.probability_col:
            score = _col(table, p.probability_col)
        elif "probability_1" in names:
            score = _col(table, "probability_1")
        elif any(n.startswith("probability_") for n in names):
            score = _col(table, [n for n in names if n.startswith("probability_")][-1])
        elif "rawPrediction" in names:
            score = _col(table, "rawPrediction")
        else:
            raise ValueError("no probability/rawPrediction column; transform first")
        if metric == "areaUnderROC":
            return _weighted_auc(score, label, table.W)
        if metric == "areaUnderPR":
            return _weighted_auc_pr(score, label, table.W)
        raise ValueError(f"unknown metric {metric!r}")


@_partial(jax.jit, static_argnames=("n_classes",))
def _confusion_weighted(pred, label, w, n_classes):
    oh_p = jax.nn.one_hot(pred.astype(jnp.int32), n_classes) * w[:, None]
    oh_l = jax.nn.one_hot(label.astype(jnp.int32), n_classes)
    return oh_l.T @ oh_p  # [true, pred] weighted counts


class MulticlassClassificationEvaluator(_Evaluator):
    default_metric = "accuracy"

    def confusion(self, table: TpuTable) -> np.ndarray:
        """The weighted [true, pred] confusion matrix — ONE device pass;
        callers needing several metrics (model.summary) derive them all
        from this instead of re-reducing per metric."""
        pred = _col(table, self.params.prediction_col)
        label = self._label(table)
        n_classes = int(np.asarray(
            jnp.maximum(jnp.max(pred), jnp.max(label))).item()) + 1
        return np.asarray(
            _confusion_weighted(pred, label, table.W, n_classes))

    @staticmethod
    def from_confusion(C: np.ndarray, metric: str) -> float:
        tp = np.diag(C)
        tot = max(C.sum(), 1e-12)
        if metric == "accuracy":
            return float(tp.sum() / tot)
        prec = tp / np.maximum(C.sum(axis=0), 1e-12)
        rec = tp / np.maximum(C.sum(axis=1), 1e-12)
        support = C.sum(axis=1) / tot
        if metric == "weightedPrecision":
            return float(np.sum(prec * support))
        if metric == "weightedRecall":
            return float(np.sum(rec * support))
        if metric == "f1":
            f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-12)
            return float(np.sum(f1 * support))
        raise ValueError(f"unknown metric {metric!r}")

    def _compute(self, table: TpuTable, metric: str):
        return self.from_confusion(self.confusion(table), metric)


class RegressionEvaluator(_Evaluator):
    default_metric = "rmse"

    def _compute(self, table: TpuTable, metric: str):
        pred = _col(table, self.params.prediction_col)
        label = self._label(table)
        w = table.W
        tot = jnp.maximum(jnp.sum(w), EPS_TOTAL_WEIGHT)
        err = pred - label
        if metric in ("rmse", "mse"):
            mse = jnp.sum(err * err * w) / tot
            return jnp.sqrt(mse) if metric == "rmse" else mse
        if metric == "mae":
            return jnp.sum(jnp.abs(err) * w) / tot
        if metric == "r2":
            mean_y = jnp.sum(label * w) / tot
            ss_res = jnp.sum(err * err * w)
            ss_tot = jnp.maximum(jnp.sum((label - mean_y) ** 2 * w), EPS_TOTAL_WEIGHT)
            return 1.0 - ss_res / ss_tot
        raise ValueError(f"unknown metric {metric!r}")


class ClusteringEvaluator(_Evaluator):
    """Silhouette (simplified squared-Euclidean form, like Spark): uses
    cluster centroids rather than all-pairs distances — O(N*k) on device."""

    default_metric = "silhouette"

    def _compute(self, table: TpuTable, metric: str):
        if metric != "silhouette":
            raise ValueError(f"unknown metric {metric!r}")
        pred = _col(table, self.params.prediction_col
                    if self.params.prediction_col != "prediction" else "cluster")
        X_names = [v.name for v in table.domain.attributes]
        feat_idx = [i for i, n in enumerate(X_names)
                    if n not in ("cluster", "prediction")]
        X = jnp.take(table.X, jnp.asarray(feat_idx), axis=1)
        w = table.W
        k = int(np.asarray(jnp.max(pred)).item()) + 1
        return float(_silhouette_centroid(X, pred, w, k))


from functools import partial  # noqa: E402


@partial(jax.jit, static_argnames=("k",))
def _silhouette_centroid(X, pred, w, k: int):
    onehot = jax.nn.one_hot(pred.astype(jnp.int32), k) * w[:, None]
    counts = jnp.maximum(jnp.sum(onehot, axis=0), EPS_TOTAL_WEIGHT)
    centroids = (onehot.T @ X) / counts[:, None]
    d2 = (
        jnp.sum(X * X, axis=1, keepdims=True)
        - 2.0 * X @ centroids.T
        + jnp.sum(centroids * centroids, axis=1)
    )  # [N, k]
    own = jnp.take_along_axis(d2, pred.astype(jnp.int32)[:, None], axis=1)[:, 0]
    other = jnp.min(
        jnp.where(
            jax.nn.one_hot(pred.astype(jnp.int32), k) > 0, jnp.inf, d2
        ),
        axis=1,
    )
    s = (other - own) / jnp.maximum(jnp.maximum(own, other), EPS_TOTAL_WEIGHT)
    tot = jnp.maximum(jnp.sum(w), EPS_TOTAL_WEIGHT)
    return jnp.sum(s * w) / tot


# --------------------------------------------------------------------------
# Set-valued evaluators (pyspark.ml.evaluation RankingEvaluator /
# MultilabelClassificationEvaluator, Spark 3.0). Spark evaluates DataFrames
# with ARRAY columns; this table model has no ragged arrays, so both take
# fixed-width padded id matrices — pred [n, P] and truth [n, T] integer ids
# with -1 padding — the same static-shape convention as the rest of the
# framework (and exactly what ALSModel.recommend_for_all_users emits).
# --------------------------------------------------------------------------

def _pair_hits(pred, truth):
    """[n, P] bool: is pred slot j a member of the row's truth set.
    -1 pads never match (-1 == -1 is masked explicitly)."""
    pred = jnp.asarray(pred, jnp.int32)
    truth = jnp.asarray(truth, jnp.int32)
    eq = pred[:, :, None] == truth[:, None, :]
    eq = eq & (truth[:, None, :] >= 0)
    return jnp.any(eq, axis=2) & (pred >= 0)


@dataclasses.dataclass(frozen=True)
class RankingEvaluatorParams(Params):
    metric_name: str = "meanAveragePrecision"
    k: int = 10


class RankingEvaluator:
    """pyspark.ml.evaluation.RankingEvaluator parity (RankingMetrics):
    meanAveragePrecision, meanAveragePrecisionAtK, precisionAtK, recallAtK,
    ndcgAtK — binary relevance, predictions ordered best-first.

    evaluate(pred_ids [n, P], true_ids [n, T]) -> float; -1 pads ignored.
    """

    ParamsCls = RankingEvaluatorParams
    METRICS = ("meanAveragePrecision", "meanAveragePrecisionAtK",
               "precisionAtK", "recallAtK", "ndcgAtK")

    def __init__(self, params: RankingEvaluatorParams | None = None, **kw):
        self.params = params or RankingEvaluatorParams(**kw)

    def evaluate(self, pred_ids, true_ids) -> float:
        p = self.params
        m = p.metric_name
        if m not in self.METRICS:
            raise ValueError(f"unknown metric {m!r}; one of {self.METRICS}")
        return float(_ranking_metric(
            jnp.asarray(pred_ids, jnp.int32), jnp.asarray(true_ids, jnp.int32),
            metric=m, k=p.k,
        ))


@partial(jax.jit, static_argnames=("metric", "k"))
def _ranking_metric(pred, truth, *, metric: str, k: int):
    n, P = pred.shape
    hits = _pair_hits(pred, truth).astype(jnp.float32)         # [n, P]
    n_rel = jnp.sum((truth >= 0).astype(jnp.float32), axis=1)  # [n]
    ranks = jnp.arange(1, P + 1, dtype=jnp.float32)
    topk = (ranks <= k).astype(jnp.float32)
    safe_rel = jnp.maximum(n_rel, 1.0)
    if metric == "precisionAtK":
        # MLlib divides by k even when fewer than k predictions exist
        row = jnp.sum(hits * topk, axis=1) / k
    elif metric == "recallAtK":
        row = jnp.sum(hits * topk, axis=1) / safe_rel
    elif metric == "meanAveragePrecision":
        prec_at = jnp.cumsum(hits, axis=1) / ranks
        row = jnp.sum(prec_at * hits, axis=1) / safe_rel
    elif metric == "meanAveragePrecisionAtK":
        prec_at = jnp.cumsum(hits, axis=1) / ranks
        row = (jnp.sum(prec_at * hits * topk, axis=1)
               / jnp.maximum(jnp.minimum(n_rel, float(k)), 1.0))
    else:  # ndcgAtK, binary relevance
        disc = 1.0 / jnp.log2(ranks + 1.0)
        dcg = jnp.sum(hits * disc * topk, axis=1)
        # ideal DCG sums min(|rel|, k) discount terms INDEPENDENT of the
        # prediction width P (Spark ndcgAt) — a too-short prediction list
        # must lower the score, not the ideal
        ideal_n = jnp.minimum(n_rel, float(k))
        iranks = jnp.arange(1, k + 1, dtype=jnp.float32)
        idisc = jnp.where(iranks[None, :] <= ideal_n[:, None],
                          1.0 / jnp.log2(iranks[None, :] + 1.0), 0.0)
        idcg = jnp.maximum(jnp.sum(idisc, axis=1), 1e-12)
        row = dcg / idcg
    # rows with an empty truth set contribute 0 (MLlib logs-and-zeros them)
    row = jnp.where(n_rel > 0, row, 0.0)
    return jnp.mean(row)


@dataclasses.dataclass(frozen=True)
class MultilabelEvaluatorParams(Params):
    metric_name: str = "f1Measure"


class MultilabelClassificationEvaluator:
    """pyspark.ml.evaluation.MultilabelClassificationEvaluator parity
    (MultilabelMetrics): subsetAccuracy, accuracy, hammingLoss, precision,
    recall, f1Measure, microPrecision, microRecall, microF1Measure.

    evaluate(pred_ids [n, P], true_ids [n, T]) -> float; -1 pads ignored;
    ids within a row are treated as SETS (duplicates undefined, like
    Spark). hammingLoss normalizes by MLlib's numLabels = the distinct
    count of TRUE labels only (predicted ids absent from every truth row
    do not deflate it). Convention note: per-row 'accuracy' here returns
    1.0 when BOTH the prediction and truth sets are empty; Spark's 0/0
    yields NaN for such rows — we treat an exactly-matched empty set as
    correct rather than poisoning the mean.
    """

    ParamsCls = MultilabelEvaluatorParams
    METRICS = ("subsetAccuracy", "accuracy", "hammingLoss", "precision",
               "recall", "f1Measure", "microPrecision", "microRecall",
               "microF1Measure")

    def __init__(self, params: MultilabelEvaluatorParams | None = None, **kw):
        self.params = params or MultilabelEvaluatorParams(**kw)

    def evaluate(self, pred_ids, true_ids) -> float:
        m = self.params.metric_name
        if m not in self.METRICS:
            raise ValueError(f"unknown metric {m!r}; one of {self.METRICS}")
        pred = jnp.asarray(pred_ids, jnp.int32)
        truth = jnp.asarray(true_ids, jnp.int32)
        if m == "hammingLoss":
            # MLlib's numLabels = distinct count of TRUE labels only —
            # predicted ids absent from every truth row must not deflate it
            ids = np.asarray(truth).ravel()
            n_labels = len(np.unique(ids[ids >= 0]))
            return float(_multilabel_metric(pred, truth, metric=m)
                         / max(n_labels, 1))
        return float(_multilabel_metric(pred, truth, metric=m))


@partial(jax.jit, static_argnames=("metric",))
def _multilabel_metric(pred, truth, *, metric: str):
    hit_p = _pair_hits(pred, truth).astype(jnp.float32)   # pred slot in truth
    np_ = jnp.sum((pred >= 0).astype(jnp.float32), axis=1)
    nt = jnp.sum((truth >= 0).astype(jnp.float32), axis=1)
    inter = jnp.sum(hit_p, axis=1)
    union = np_ + nt - inter
    if metric == "subsetAccuracy":
        return jnp.mean(((inter == np_) & (inter == nt)).astype(jnp.float32))
    if metric == "accuracy":
        return jnp.mean(jnp.where(union > 0, inter / jnp.maximum(union, 1.0),
                                  1.0))
    if metric == "hammingLoss":
        # symmetric difference summed over rows; caller divides by
        # n * numLabels (numLabels needs a host-side distinct count)
        return jnp.sum(union - inter) / pred.shape[0]
    if metric == "precision":
        return jnp.mean(jnp.where(np_ > 0, inter / jnp.maximum(np_, 1.0), 0.0))
    if metric == "recall":
        return jnp.mean(jnp.where(nt > 0, inter / jnp.maximum(nt, 1.0), 0.0))
    if metric == "f1Measure":
        return jnp.mean(jnp.where(
            np_ + nt > 0, 2.0 * inter / jnp.maximum(np_ + nt, 1.0), 0.0))
    tot_i, tot_p, tot_t = jnp.sum(inter), jnp.sum(np_), jnp.sum(nt)
    if metric == "microPrecision":
        return tot_i / jnp.maximum(tot_p, 1e-12)
    if metric == "microRecall":
        return tot_i / jnp.maximum(tot_t, 1e-12)
    return 2.0 * tot_i / jnp.maximum(tot_p + tot_t, 1e-12)  # microF1Measure
