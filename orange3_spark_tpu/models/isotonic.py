"""IsotonicRegression — parity with ``pyspark.ml.regression.IsotonicRegression``.

MLlib runs pool-adjacent-violators (PAV) per partition then a final merge on
the driver (SURVEY.md §2b; reconstructed, mount empty — public API:
isotonic=True|False (antitonic), featureIndex, weightCol; model exposes
``boundaries``, ``predictions``, and transform = linear interpolation between
boundaries). TPU-native placement decision:

* PAV's pooling is inherently sequential, data-dependent control flow —
  O(n) pointer-chasing, zero FLOPs. Tracing that into XLA would serialize
  the TPU; MLlib itself finishes the merge single-threaded on the driver.
  So the FIT runs host-side on a stack-based O(n) numpy PAV (the driver-
  merge role), after a device-side sort key extraction.
* TRANSFORM (the hot path — scoring N rows) IS jitted: a
  ``jnp.searchsorted`` + linear interpolation over the fitted boundary
  arrays, fully batched and shardable over rows.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params


@dataclasses.dataclass(frozen=True)
class IsotonicRegressionParams(Params):
    isotonic: bool = True    # MLlib isotonic: True=nondecreasing, False=antitonic
    feature_index: int = 0   # MLlib featureIndex


def _pav(x: np.ndarray, y: np.ndarray, w: np.ndarray):
    """Stack-based pool-adjacent-violators on (x-sorted) data. O(n)."""
    # blocks: (mean, weight, x_lo, x_hi)
    means: list[float] = []
    weights: list[float] = []
    x_lo: list[float] = []
    x_hi: list[float] = []
    for xi, yi, wi in zip(x, y, w):
        means.append(float(yi))
        weights.append(float(wi))
        x_lo.append(float(xi))
        x_hi.append(float(xi))
        while len(means) > 1 and means[-2] > means[-1]:
            m2, w2 = means.pop(), weights.pop()
            hi = x_hi.pop(); x_lo.pop()
            m1, w1 = means[-1], weights[-1]
            tot = w1 + w2
            means[-1] = (m1 * w1 + m2 * w2) / tot if tot > 0 else (m1 + m2) / 2
            weights[-1] = tot
            x_hi[-1] = hi
    bx, by = [], []
    for m, lo, hi in zip(means, x_lo, x_hi):
        bx.append(lo)
        by.append(m)
        if hi > lo:
            bx.append(hi)
            by.append(m)
    return np.asarray(bx, dtype=np.float32), np.asarray(by, dtype=np.float32)


@jax.jit
def _interp(x, bx, by):
    """Piecewise-linear interpolation with flat extrapolation (MLlib semantics)."""
    return jnp.interp(x, bx, by)


class IsotonicRegressionModel(Model):
    def __init__(self, params, boundaries, predictions):
        self.params = params
        self.boundaries = boundaries    # f32[m] ascending feature values
        self.predictions = predictions  # f32[m] fitted values at boundaries

    @property
    def state_pytree(self):
        return {"boundaries": self.boundaries, "predictions": self.predictions}

    def predict(self, table: TpuTable) -> np.ndarray:
        x = table.X[:, self.params.feature_index]
        return np.asarray(_interp(x, self.boundaries, self.predictions))[: table.n_rows]

    def transform(self, table: TpuTable) -> TpuTable:
        x = table.X[:, self.params.feature_index]
        pred = _interp(x, self.boundaries, self.predictions)
        new_attrs = list(table.domain.attributes) + [ContinuousVariable("prediction")]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        return table.with_X(
            jnp.concatenate([table.X, pred[:, None]], axis=1), new_domain
        )


class IsotonicRegression(Estimator):
    ParamsCls = IsotonicRegressionParams
    params: IsotonicRegressionParams

    def _fit(self, table: TpuTable) -> IsotonicRegressionModel:
        p = self.params
        if table.y is None:
            raise ValueError("IsotonicRegression needs a target column")
        x = np.asarray(jax.device_get(table.X[:, p.feature_index]))
        y = np.asarray(jax.device_get(table.y))
        w = np.asarray(jax.device_get(table.W))
        live = w > 0
        x, y, w = x[live], y[live], w[live]
        if not p.isotonic:
            y = -y
        order = np.argsort(x, kind="stable")
        bx, by = _pav(x[order], y[order], w[order])
        if not p.isotonic:
            by = -by
        rep = table.session.replicated
        return IsotonicRegressionModel(
            p, jax.device_put(bx, rep), jax.device_put(by, rep)
        )
