"""BisectingKMeans — parity with ``pyspark.ml.clustering.BisectingKMeans``.

MLlib grows a binary tree divisively: all rows start in one cluster; the
largest divisible leaf is repeatedly split by a local 2-means until there are
k leaves (SURVEY.md §2b; reconstructed, mount empty — public API: k,
maxIter=20, minDivisibleClusterSize=1.0, seed; model exposes clusterCenters,
computeCost, predict). TPU-native redesign:

* the outer split loop runs on host — it is O(k) with k small and static,
  exactly the kind of data-dependent control flow that should NOT be traced;
* each inner 2-means reuses the jitted ``lax.while_loop`` Lloyd kernel from
  ``kmeans.py`` with the candidate cluster selected by **weight masking**
  (rows outside the cluster get W=0) — no shape-changing compaction, every
  split is the same fused XLA computation on the full sharded table;
* prediction is flat nearest-center over the final leaf centers (same
  observable behavior as MLlib's tree descent for points the tree was built
  on, and O(k) instead of tree-walking — compiler-friendly).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Params
from orange3_spark_tpu.models.kmeans import KMeansModel, _assign, _lloyd


@dataclasses.dataclass(frozen=True)
class BisectingKMeansParams(Params):
    k: int = 4                            # MLlib k (leaf clusters)
    max_iter: int = 20                    # MLlib maxIter (inner Lloyd iters)
    min_divisible_cluster_size: float = 1.0  # MLlib minDivisibleClusterSize
    seed: int = 0                         # MLlib seed
    tol: float = 1e-4


class BisectingKMeansModel(KMeansModel):
    """Flat nearest-center prediction over the leaf centers — all of
    predict/compute_cost/transform are inherited from KMeansModel."""


class BisectingKMeans(Estimator):
    ParamsCls = BisectingKMeansParams
    params: BisectingKMeansParams

    def _two_means(self, X, w_masked, seed: int):
        """One local 2-means on the weight-masked table; returns (2,d) centers."""
        rng = np.random.default_rng(seed)
        live = np.flatnonzero(np.asarray(jax.device_get(w_masked)) > 0)
        if len(live) < 2:
            return None
        idx = np.sort(live[rng.choice(len(live), size=2, replace=False)])
        c0 = jax.device_get(X[idx]).astype(np.float32)
        centers, _, _, _ = _lloyd(
            X, w_masked, jnp.asarray(c0), jnp.float32(self.params.tol),
            k=2, max_iter=self.params.max_iter,
        )
        return centers

    def _fit(self, table: TpuTable) -> BisectingKMeansModel:
        p = self.params
        X, W = table.X, table.W
        w_np = np.asarray(jax.device_get(W))
        # leaf state, host side: list of center rows + per-leaf member masks
        total_w = float(w_np.sum())
        mean0 = (jax.device_get(jnp.sum(X * W[:, None], axis=0)) / max(total_w, 1e-12))
        leaves = [np.asarray(mean0, dtype=np.float32)]
        masks = [w_np > 0]
        sizes = [total_w]
        divisible = [True]
        # MLlib: minDivisibleClusterSize >= 1 is an absolute point count,
        # in (0, 1) it is a fraction of the total (weighted) row count
        min_size = (
            p.min_divisible_cluster_size
            if p.min_divisible_cluster_size >= 1.0
            else p.min_divisible_cluster_size * total_w
        )
        step = 0
        while len(leaves) < p.k:
            # largest divisible leaf first (MLlib splits by size)
            order = np.argsort(sizes)[::-1]
            split_at = None
            for j in order:
                if divisible[j] and sizes[j] >= min_size and masks[j].sum() >= 2:
                    split_at = int(j)
                    break
            if split_at is None:
                break  # nothing divisible — fewer than k clusters, like MLlib
            w_masked = jnp.asarray(np.where(masks[split_at], w_np, 0.0))
            centers2 = self._two_means(X, w_masked, p.seed + 31 * step)
            step += 1
            if centers2 is None:
                divisible[split_at] = False  # <2 distinct live rows in leaf
                continue
            assign, _ = _assign(X, centers2, w_masked)
            a = np.asarray(jax.device_get(assign))
            m_left = masks[split_at] & (a == 0)
            m_right = masks[split_at] & (a == 1)
            if m_left.sum() == 0 or m_right.sum() == 0:
                # degenerate split (identical points): this leaf can't divide,
                # but others might — keep going
                divisible[split_at] = False
                continue
            c2 = np.asarray(jax.device_get(centers2))
            leaves[split_at] = c2[0]
            masks[split_at] = m_left
            sizes[split_at] = float(w_np[m_left].sum())
            leaves.append(c2[1])
            masks.append(m_right)
            sizes.append(float(w_np[m_right].sum()))
            divisible.append(True)
        centers = jax.device_put(
            np.stack(leaves).astype(np.float32), table.session.replicated
        )
        model = BisectingKMeansModel(p, centers)
        assign, cost = _assign(X, centers, W)
        model.training_cost_ = float(cost)
        # MLlib summary.clusterSizes: live rows per final-center assignment
        from orange3_spark_tpu.models.kmeans import live_cluster_sizes

        model.cluster_sizes_ = live_cluster_sizes(W, assign, len(leaves))
        return model
