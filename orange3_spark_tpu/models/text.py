"""Text feature pipeline — parity with ``pyspark.ml.feature``'s text stack:
Tokenizer, RegexTokenizer, StopWordsRemover, NGram, HashingTF,
CountVectorizer, IDF, Word2Vec.

Placement design (the TPU-native split): free text lives HOST-SIDE in
``table.metas`` — exactly where Orange keeps string columns and where the
reference funnels Spark string columns on collect (SURVEY.md §2b
"Orange Table ⇄ distributed table bridge"; reconstructed, mount empty).
String munging (tokenize/stop-words/ngram/hashing) is pointer-chasing with
zero FLOPs, so it stays on host; the moment text becomes NUMBERS
(term-count vectors, IDF weights, word embeddings) it moves into the sharded
``X`` matrix and every downstream op is jitted device compute:

* HashingTF/CountVectorizer append dense count columns to X (our table is
  columnar-dense; MLlib's 2^18-wide sparse vectors become a configurable
  dense width — the MXU wants dense anyway);
* IDF fit/transform is jitted: document frequencies are one masked
  reduction over the sharded row axis (GSPMD all-reduce = the
  treeAggregate), scaling is a fused elementwise multiply;
* Word2Vec trains skip-gram with negative sampling as one jitted
  ``lax.fori_loop``: embedding gathers + a [P,D]·[P,D] contraction per
  step — MLlib's per-executor hogwild loop becomes data-parallel SGD.
"""

from __future__ import annotations

import dataclasses
import re
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import (
    ContinuousVariable,
    Domain,
    StringVariable,
)
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params, Transformer

# a compact default English stop list (MLlib loads its list from resources)
_DEFAULT_STOP_WORDS = (
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with i me my we "
    "our you your he him his she her its them what which who whom am been "
    "being have has had having do does did doing would should could ought"
).split()


def _meta_col(table: TpuTable, name: str) -> np.ndarray:
    if table.metas is None:
        raise ValueError("table has no meta columns")
    names = [v.name for v in table.domain.metas]
    if name not in names:
        raise ValueError(f"no meta column {name!r} (have {names})")
    return table.metas[:, names.index(name)]


def _append_meta(table: TpuTable, name: str, values: np.ndarray) -> TpuTable:
    """New table with an extra host-side meta column (token lists etc.)."""
    col = np.empty((len(values), 1), dtype=object)
    col[:, 0] = values
    metas = col if table.metas is None else np.concatenate([table.metas, col], axis=1)
    domain = Domain(
        table.domain.attributes, table.domain.class_vars,
        list(table.domain.metas) + [StringVariable(name)],
    )
    return TpuTable(domain, table.X, table.Y, table.W, metas, table.n_rows,
                    table.session)


def _append_x(table: TpuTable, names: list[str], cols_np: np.ndarray) -> TpuTable:
    """Append host-computed numeric columns (padded + sharded) to X."""
    pad = np.zeros((table.n_pad, cols_np.shape[1]), dtype=np.float32)
    pad[: cols_np.shape[0]] = cols_np
    dev = jax.device_put(pad, table.session.row_sharding)
    domain = Domain(
        list(table.domain.attributes) + [ContinuousVariable(n) for n in names],
        table.domain.class_vars, table.domain.metas,
    )
    return table.with_X(jnp.concatenate([table.X, dev], axis=1), domain)


# ---------------------------------------------------------------- tokenizers
@dataclasses.dataclass(frozen=True)
class TokenizerParams(Params):
    input_col: str = "text"
    output_col: str = "tokens"


class Tokenizer(Transformer):
    """MLlib Tokenizer: lowercase, split on whitespace."""

    ParamsCls = TokenizerParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        texts = _meta_col(table, p.input_col)
        toks = np.empty(len(texts), dtype=object)
        for i, t in enumerate(texts):
            toks[i] = str(t).lower().split()
        return _append_meta(table, p.output_col, toks)


@dataclasses.dataclass(frozen=True)
class RegexTokenizerParams(Params):
    input_col: str = "text"
    output_col: str = "tokens"
    pattern: str = r"\s+"         # MLlib pattern
    gaps: bool = True             # pattern matches gaps (split) vs tokens (findall)
    min_token_length: int = 1     # MLlib minTokenLength
    to_lowercase: bool = True     # MLlib toLowercase


class RegexTokenizer(Transformer):
    ParamsCls = RegexTokenizerParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        rx = re.compile(p.pattern)
        texts = _meta_col(table, p.input_col)
        toks = np.empty(len(texts), dtype=object)
        for i, t in enumerate(texts):
            s = str(t).lower() if p.to_lowercase else str(t)
            parts = rx.split(s) if p.gaps else rx.findall(s)
            toks[i] = [w for w in parts if len(w) >= p.min_token_length]
        return _append_meta(table, p.output_col, toks)


@dataclasses.dataclass(frozen=True)
class StopWordsRemoverParams(Params):
    input_col: str = "tokens"
    output_col: str = "filtered"
    stop_words: tuple = tuple(_DEFAULT_STOP_WORDS)  # MLlib stopWords
    case_sensitive: bool = False                    # MLlib caseSensitive


class StopWordsRemover(Transformer):
    ParamsCls = StopWordsRemoverParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        stop = set(p.stop_words if p.case_sensitive
                   else (w.lower() for w in p.stop_words))
        toks = _meta_col(table, p.input_col)
        out = np.empty(len(toks), dtype=object)
        for i, ts in enumerate(toks):
            ts = ts if isinstance(ts, list) else str(ts).split()
            out[i] = [w for w in ts
                      if (w if p.case_sensitive else w.lower()) not in stop]
        return _append_meta(table, p.output_col, out)


@dataclasses.dataclass(frozen=True)
class NGramParams(Params):
    input_col: str = "tokens"
    output_col: str = "ngrams"
    n: int = 2  # MLlib n


class NGram(Transformer):
    ParamsCls = NGramParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        toks = _meta_col(table, p.input_col)
        out = np.empty(len(toks), dtype=object)
        for i, ts in enumerate(toks):
            ts = ts if isinstance(ts, list) else str(ts).split()
            out[i] = [" ".join(ts[j: j + p.n]) for j in range(len(ts) - p.n + 1)]
        return _append_meta(table, p.output_col, out)


# ---------------------------------------------------------- vectorization
@dataclasses.dataclass(frozen=True)
class HashingTFParams(Params):
    input_col: str = "tokens"
    output_prefix: str = "tf"
    num_features: int = 1024  # MLlib numFeatures (2^18 sparse; dense here —
                              # pick the width your vocab needs)
    binary: bool = False      # MLlib binary


class HashingTF(Transformer):
    """Feature hashing: term -> crc32(term) mod num_features (stable across
    processes, unlike Python's salted hash; plays MLlib's murmur3 role)."""

    ParamsCls = HashingTFParams

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        toks = _meta_col(table, p.input_col)
        counts = np.zeros((len(toks), p.num_features), dtype=np.float32)
        for i, ts in enumerate(toks):
            ts = ts if isinstance(ts, list) else str(ts).split()
            for w in ts:
                counts[i, zlib.crc32(w.encode()) % p.num_features] += 1.0
        if p.binary:
            counts = (counts > 0).astype(np.float32)
        names = [f"{p.output_prefix}_{j}" for j in range(p.num_features)]
        return _append_x(table, names, counts)


@dataclasses.dataclass(frozen=True)
class CountVectorizerParams(Params):
    input_col: str = "tokens"
    output_prefix: str = "cv"
    vocab_size: int = 1024   # MLlib vocabSize
    min_df: float = 1.0      # MLlib minDF (>=1: count, <1: fraction of docs)
    min_tf: float = 1.0      # MLlib minTF (per-doc filter)
    binary: bool = False


class CountVectorizerModel(Model):
    def __init__(self, params, vocabulary):
        self.params = params
        self.vocabulary = tuple(vocabulary)

    @property
    def state_pytree(self):
        return {}

    def transform(self, table: TpuTable) -> TpuTable:
        p = self.params
        lut = {w: j for j, w in enumerate(self.vocabulary)}
        toks = _meta_col(table, p.input_col)
        counts = np.zeros((len(toks), len(self.vocabulary)), dtype=np.float32)
        for i, ts in enumerate(toks):
            ts = ts if isinstance(ts, list) else str(ts).split()
            for w in ts:
                j = lut.get(w)
                if j is not None:
                    counts[i, j] += 1.0
            min_tf = p.min_tf if p.min_tf >= 1.0 else p.min_tf * max(len(ts), 1)
            counts[i][counts[i] < min_tf] = 0.0
        if p.binary:
            counts = (counts > 0).astype(np.float32)
        names = [f"{p.output_prefix}_{w}" for w in self.vocabulary]
        return _append_x(table, names, counts)


class CountVectorizer(Estimator):
    ParamsCls = CountVectorizerParams
    params: CountVectorizerParams

    def _fit(self, table: TpuTable) -> CountVectorizerModel:
        p = self.params
        toks = _meta_col(table, p.input_col)
        live = np.asarray(jax.device_get(table.W))[: len(toks)] > 0
        tf: dict[str, float] = {}
        df: dict[str, int] = {}
        n_docs = 0
        for i, ts in enumerate(toks):
            if not live[i]:
                continue
            n_docs += 1
            ts = ts if isinstance(ts, list) else str(ts).split()
            for w in ts:
                tf[w] = tf.get(w, 0.0) + 1.0
            for w in set(ts):
                df[w] = df.get(w, 0) + 1
        min_df = p.min_df if p.min_df >= 1.0 else p.min_df * max(n_docs, 1)
        eligible = [w for w in tf if df[w] >= min_df]
        # MLlib: vocabulary ordered by corpus term frequency, capped
        eligible.sort(key=lambda w: (-tf[w], w))
        return CountVectorizerModel(p, eligible[: p.vocab_size])


@dataclasses.dataclass(frozen=True)
class IDFParams(Params):
    input_cols: tuple = ()   # term-count attribute names; () => all attributes
    min_doc_freq: int = 0    # MLlib minDocFreq


class IDFModel(Model):
    def __init__(self, params, idf, col_idx):
        self.params = params
        self.idf = idf          # f32[m] per-term idf weights
        self.col_idx = col_idx  # i32[m] attribute indices scaled in-place

    @property
    def state_pytree(self):
        return {"idf": self.idf}

    def transform(self, table: TpuTable) -> TpuTable:
        X = table.X
        scaled = X[:, self.col_idx] * self.idf[None, :]
        X = X.at[:, self.col_idx].set(scaled)
        return table.with_X(X, table.domain)


class IDF(Estimator):
    """idf = log((n_docs + 1) / (df + 1)) — MLlib's smoothed formula; the df
    reduction runs jitted over the sharded row axis."""

    ParamsCls = IDFParams
    params: IDFParams

    def _fit(self, table: TpuTable) -> IDFModel:
        p = self.params
        names = [v.name for v in table.domain.attributes]
        cols = list(p.input_cols) if p.input_cols else names
        idx = jnp.asarray([names.index(c) for c in cols], dtype=jnp.int32)
        X, W = table.X, table.W
        sub = X[:, idx]
        df = jnp.sum(((sub > 0) & (W[:, None] > 0)).astype(jnp.float32), axis=0)
        n_docs = jnp.sum((W > 0).astype(jnp.float32))
        idf = jnp.log((n_docs + 1.0) / (df + 1.0))
        idf = jnp.where(df >= p.min_doc_freq, idf, 0.0)
        return IDFModel(p, idf, idx)


# ----------------------------------------------------------------- Word2Vec
@dataclasses.dataclass(frozen=True)
class Word2VecParams(Params):
    input_col: str = "tokens"
    output_prefix: str = "w2v"
    vector_size: int = 100    # MLlib vectorSize
    min_count: int = 5        # MLlib minCount
    window_size: int = 5      # MLlib windowSize
    max_iter: int = 1         # MLlib maxIter (epochs)
    step_size: float = 0.025  # MLlib stepSize
    negative: int = 5         # negative samples (MLlib uses hierarchical
                              # softmax; neg-sampling is the batched-friendly
                              # formulation of the same skip-gram objective)
    max_pairs: int = 1 << 20  # cap on (center, context) pairs per epoch
    seed: int = 0


class Word2VecModel(Model):
    def __init__(self, params, vocabulary, vectors):
        self.params = params
        self.vocabulary = tuple(vocabulary)
        self.vectors = vectors  # f32[V, D]
        self._lut = {w: i for i, w in enumerate(self.vocabulary)}

    @property
    def state_pytree(self):
        return {"vectors": self.vectors}

    def get_vectors(self) -> np.ndarray:
        return np.asarray(self.vectors)

    def find_synonyms(self, word: str, num: int = 5):
        """MLlib findSynonyms: top cosine-similar vocabulary words."""
        if word not in self._lut:
            raise ValueError(f"word {word!r} not in vocabulary")
        V = np.asarray(self.vectors)
        q = V[self._lut[word]]
        sims = V @ q / (np.linalg.norm(V, axis=1) * np.linalg.norm(q) + 1e-12)
        order = np.argsort(sims)[::-1]
        out = [(self.vocabulary[i], float(sims[i])) for i in order
               if self.vocabulary[i] != word]
        return out[:num]

    def transform(self, table: TpuTable) -> TpuTable:
        """Doc vector = mean of its words' vectors (MLlib's doc embedding)."""
        p = self.params
        toks = _meta_col(table, p.input_col)
        V = np.asarray(self.vectors)
        out = np.zeros((len(toks), p.vector_size), dtype=np.float32)
        for i, ts in enumerate(toks):
            ts = ts if isinstance(ts, list) else str(ts).split()
            ids = [self._lut[w] for w in ts if w in self._lut]
            if ids:
                out[i] = V[ids].mean(axis=0)
        names = [f"{p.output_prefix}_{j}" for j in range(p.vector_size)]
        return _append_x(table, names, out)


def _sgns_epoch(params, centers, contexts, key, *, negative, step_size, probs):
    """One full-batch skip-gram negative-sampling step set."""
    E_in, E_out = params

    def loss_fn(params):
        E_in, E_out = params
        vc = E_in[centers]                           # [P,D] gather
        uo = E_out[contexts]                         # [P,D]
        pos = jax.nn.log_sigmoid(jnp.sum(vc * uo, axis=1))
        neg_ids = jax.random.categorical(
            key, jnp.log(probs)[None, :], shape=(centers.shape[0], negative)
        )                                            # [P,neg]
        un = E_out[neg_ids]                          # [P,neg,D]
        neg = jnp.sum(jax.nn.log_sigmoid(-jnp.einsum("pd,pnd->pn", vc, un)), axis=1)
        return -jnp.mean(pos + neg)

    g = jax.grad(loss_fn)(params)
    return (E_in - step_size * g[0], E_out - step_size * g[1])


class Word2Vec(Estimator):
    ParamsCls = Word2VecParams
    params: Word2VecParams

    def _fit(self, table: TpuTable) -> Word2VecModel:
        p = self.params
        toks = _meta_col(table, p.input_col)
        live = np.asarray(jax.device_get(table.W))[: len(toks)] > 0
        counts: dict[str, int] = {}
        docs = []
        for i, ts in enumerate(toks):
            if not live[i]:
                continue
            ts = ts if isinstance(ts, list) else str(ts).split()
            docs.append(ts)
            for w in ts:
                counts[w] = counts.get(w, 0) + 1
        vocab = sorted((w for w, c in counts.items() if c >= p.min_count),
                       key=lambda w: (-counts[w], w))
        if not vocab:
            raise ValueError(f"no words with count >= min_count={p.min_count}")
        lut = {w: i for i, w in enumerate(vocab)}
        rng = np.random.default_rng(p.seed)
        centers, contexts = [], []
        for ts in docs:
            ids = [lut[w] for w in ts if w in lut]
            for j, c in enumerate(ids):
                win = rng.integers(1, p.window_size + 1)
                for k in range(max(0, j - win), min(len(ids), j + win + 1)):
                    if k != j:
                        centers.append(c)
                        contexts.append(ids[k])
        if not centers:
            raise ValueError("no (center, context) pairs — docs too short?")
        centers = np.asarray(centers, dtype=np.int32)
        contexts = np.asarray(contexts, dtype=np.int32)
        if len(centers) > p.max_pairs:
            sel = rng.choice(len(centers), p.max_pairs, replace=False)
            centers, contexts = centers[sel], contexts[sel]
        # unigram^0.75 negative-sampling distribution (word2vec standard)
        freq = np.asarray([counts[w] for w in vocab], dtype=np.float64) ** 0.75
        probs = jnp.asarray((freq / freq.sum()).astype(np.float32))
        V, D = len(vocab), p.vector_size
        key = jax.random.PRNGKey(p.seed)
        key, k1 = jax.random.split(key)
        E_in = (jax.random.uniform(k1, (V, D), jnp.float32) - 0.5) / D
        E_out = jnp.zeros((V, D), jnp.float32)
        epoch = jax.jit(
            lambda params, key: _sgns_epoch(
                params, centers, contexts, key,
                negative=p.negative, step_size=p.step_size, probs=probs,
            )
        )
        # several SGD steps per "epoch" (full-batch grad ≈ one pass over pairs)
        steps = max(p.max_iter * 10, 10)
        params = (E_in, E_out)
        for _ in range(steps):
            key, sub = jax.random.split(key)
            params = epoch(params, sub)
        return Word2VecModel(p, vocab, params[0])
