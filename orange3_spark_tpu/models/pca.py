"""PCA — parity with ``pyspark.ml.feature.PCA``.

MLlib computes a distributed Gramian (RowMatrix.computeCovariance via
treeAggregate) then a local SVD (SURVEY.md §2b row "PCA"; reconstructed,
mount empty). Identical shape here: one ICI-all-reduced [d,d] Gramian matmul,
then ``jnp.linalg.eigh`` on the replicated covariance — d is small, N is the
distributed dimension.

Transform follows Orange's PCA widget semantics: the output table's
attributes ARE the principal components (PC1..PCk); original columns are
replaced (Spark instead appends a vector column — same information, flat
columnar form).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params
from orange3_spark_tpu.parallel.collectives import distributed_gramian


@dataclasses.dataclass(frozen=True)
class PCAParams(Params):
    k: int = 2          # MLlib k: number of principal components
    center: bool = True # Orange centers; MLlib PCA does too (covariance)


class PCAModel(Model):
    def __init__(self, params, components, mean, explained_variance, total_variance):
        self.params = params
        self.components = components                  # f32[d, k] (columns = PCs)
        self.mean = mean                              # f32[d]
        self.explained_variance = explained_variance  # f32[k]
        self.total_variance = total_variance          # f32[] trace of covariance

    @property
    def state_pytree(self):
        return {
            "components": self.components,
            "mean": self.mean,
            "explained_variance": self.explained_variance,
            "total_variance": self.total_variance,
        }

    @property
    def explained_variance_ratio_(self) -> np.ndarray:
        ev = np.asarray(self.explained_variance)
        tot = float(self.total_variance)
        return ev / tot if tot > 0 else ev

    @staticmethod
    @jax.jit
    def _project(X, components, mean):
        return (X - mean) @ components  # [N,d]@[d,k] on the MXU

    def transform(self, table: TpuTable) -> TpuTable:
        Z = self._project(table.X, self.components, self.mean)
        k = self.components.shape[1]
        new_domain = Domain(
            [ContinuousVariable(f"PC{i + 1}") for i in range(k)],
            table.domain.class_vars,
            table.domain.metas,
        )
        return table.with_X(Z, new_domain)


class PCA(Estimator):
    ParamsCls = PCAParams
    params: PCAParams

    def _fit(self, table: TpuTable) -> PCAModel:
        p = self.params
        if p.k > table.n_attrs:
            raise ValueError(f"k={p.k} exceeds n_features={table.n_attrs}")
        G, mean, tot = distributed_gramian(table.X, table.W, center=p.center)
        return self._finalize(G / tot, mean)

    def _finalize(self, cov, mean) -> PCAModel:
        p = self.params
        eigvals, eigvecs = jnp.linalg.eigh(cov)   # ascending
        order = jnp.argsort(eigvals)[::-1][: p.k]
        components = eigvecs[:, order]
        explained = jnp.maximum(eigvals[order], 0.0)
        total = jnp.maximum(jnp.trace(cov), 0.0)
        if not p.center:
            mean = jnp.zeros_like(mean)
        return PCAModel(p, components, mean, explained, total)

    def fit_stream(self, source, *, session=None,
                   chunk_rows: int = 1 << 18,
                   stage_times: dict | None = None) -> PCAModel:
        """Out-of-core fit: ONE pass accumulating the (shift-centered)
        weighted Gramian — one MXU matmul per chunk — plus column means
        over a chunk stream (io/streaming.stream_feature_stats), then the
        same eigh finalize as the in-memory path; the 1B-row taxi
        pipeline's PCA no longer needs the rows in memory.

        The Gramian fold donates its accumulator (exec/donate.py sweep:
        the running [d, d] stats never leave HBM and the fold reuses the
        buffer) and the parse/DMA of chunk t+1 overlaps the fold of chunk
        t; ``stage_times`` receives the pass's measured ``overlap_pct``
        and ``dispatches`` (exec/pipeline.py)."""
        from orange3_spark_tpu.io.streaming import stream_feature_stats

        # validate k BEFORE the pass — an invalid k must fail in one chunk,
        # not after a multi-hour out-of-core Gramian sweep
        first = next(iter(source()), None)
        if first is not None:
            X0 = first[0] if isinstance(first, tuple) else first
            if self.params.k > X0.shape[1]:
                raise ValueError(f"k={self.params.k} exceeds n_features="
                                 f"{X0.shape[1]}")
        st = stream_feature_stats(source, session=session,
                                  chunk_rows=chunk_rows, gramian=True,
                                  stage_times=stage_times)
        cov = jnp.asarray(
            st["cov"] if self.params.center else st["second_moment"],
            jnp.float32)
        return self._finalize(cov, jnp.asarray(st["mean"], jnp.float32))
