"""MLlib-style Estimator / Transformer / Pipeline protocol.

The reference exposes ``pyspark.ml.Estimator.fit(df) -> Model`` and
``Transformer.transform(df) -> df``, with hyper-parameters as introspectable
``Param`` objects that the add-on uses to auto-generate widget GUIs
(SURVEY.md §2b "Estimator/Transformer/Pipeline API"; reconstructed, mount
empty — the auto-generation-from-params pattern is the add-on's signature
design and is preserved here). TPU-native redesign: params are frozen
dataclasses (hashable → usable as jit static args; introspectable via
``dataclasses.fields`` → widget auto-generation in widgets/autogen.py), and a
fitted Model is a host object wrapping a **pytree of device arrays** so it
can be checkpointed, donated, and passed through staged workflow graphs.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Sequence

import jax
import numpy as np

from orange3_spark_tpu.core.table import TpuTable


def _serve_routed(kind: str, raw_fn):
    """Route a subclass-defined ``transform``/``predict`` through the
    serving path (serve/context.py) when a ServingContext is active.
    With no active context this is one None-check of overhead; inside a
    serving trace the per-thread reentrancy guard short-circuits straight
    to the raw method."""

    @functools.wraps(raw_fn)
    def wrapper(self, *args, **kwargs):
        from orange3_spark_tpu.serve.context import route

        return route(kind, raw_fn, self, *args, **kwargs)

    wrapper.__serve_raw__ = raw_fn
    return wrapper


@dataclasses.dataclass(frozen=True)
class Params:
    """Base for estimator hyper-parameter dataclasses.

    Frozen (hashable) so a params instance can be a jit static argument and a
    dict key in compile caches. ``describe()`` yields (name, type, default)
    triples — the introspection surface the widget auto-generator consumes,
    playing the role of ``pyspark.ml.param.Param`` metadata in the reference.
    """

    def replace(self, **kwargs) -> "Params":
        return dataclasses.replace(self, **kwargs)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def describe(cls) -> list[tuple[str, type, Any]]:
        return [(f.name, f.type, f.default) for f in dataclasses.fields(cls)]


class HasParams:
    """The one params-dataclass constructor: subclasses declare ``ParamsCls``
    and get ``Cls(**kwargs)`` / ``Cls(params)`` / ``Cls(params, override=...)``
    for free. Shared by Transformer, Estimator, and the fit-less algorithm
    entry points (PrefixSpan, PowerIterationClustering)."""

    ParamsCls: type["Params"] | None = None

    def __init__(self, params: "Params | None" = None, **kwargs):
        if self.ParamsCls is None:
            if params is not None or kwargs:
                raise TypeError(f"{type(self).__name__} takes no params")
            return
        if params is None:
            params = self.ParamsCls(**kwargs)
        elif kwargs:
            params = params.replace(**kwargs)
        self.params = params


def concrete_or_none(x, cast=float):
    """``cast(x)`` for concrete device scalars, ``None`` under a jit trace.

    Fit methods record host-side convenience scalars (``n_iter_``,
    ``training_cost_``) — pure diagnostics, not model state. When a fit runs
    INSIDE a trace (staged refit, workflow/staging.py ``refit=True``), those
    reads would force a concretization error; the honest value there is
    "not available", not a crash."""
    if isinstance(x, jax.core.Tracer):
        return None
    return cast(x)


class Transformer(HasParams):
    """transform(table) -> table. Stateless or carrying fitted state.

    Subclasses that declare ``ParamsCls`` get the standard params-dataclass
    constructor from HasParams; ones with custom state define their own
    __init__.

    Every subclass-defined ``transform``/``predict`` is wrapped at class
    creation to route through the serving subsystem (serve/) when a
    ``ServingContext`` is active — shape-bucketed padding, AOT executable
    cache, optional micro-batching. Without a context the raw method runs
    untouched.
    """

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        for kind in ("transform", "predict"):
            fn = cls.__dict__.get(kind)
            if fn is not None and callable(fn) \
                    and not hasattr(fn, "__serve_raw__"):
                setattr(cls, kind, _serve_routed(kind, fn))

    def transform(self, table: TpuTable) -> TpuTable:
        raise NotImplementedError

    def __call__(self, table: TpuTable) -> TpuTable:
        return self.transform(table)


class Model(Transformer):
    """A fitted model: hyper-params + a pytree of device arrays.

    Subclasses set ``self.params`` and expose fitted state through
    ``state_pytree`` for checkpointing (utils/checkpoint.py). Pickling
    converts every jax array (including ones nested in pytrees like tree
    ensembles) to numpy so checkpoints are host-portable; jnp ops re-promote
    them lazily on first use after load.
    """

    params: Params

    def __getstate__(self):
        return jax.tree.map(
            lambda x: np.asarray(x) if isinstance(x, jax.Array) else x,
            dict(self.__dict__),
            is_leaf=lambda x: isinstance(x, jax.Array) or not isinstance(
                x, (dict, list, tuple)
            ),
        )

    def __setstate__(self, state):
        self.__dict__.update(state)

    @property
    def state_pytree(self) -> dict[str, Any]:
        raise NotImplementedError

    def _touch_serving_state(self) -> None:
        """Move the serving fingerprint after an in-place state change:
        the AOT cache bakes fitted state into compiled programs
        (serve/context folds this version into the model fingerprint), so
        every ``load_state_pytree`` — base or override — must call this."""
        self._serve_state_version = (
            getattr(self, "_serve_state_version", 0) + 1)

    def _serve_state_token(self):
        """The version token serve/context folds into the fingerprint.
        Containers (PipelineModel, OneVsRestModel) include their
        children's tokens: reloading a NESTED sub-model must move the
        container's key too — its executables bake the child state in."""
        return getattr(self, "_serve_state_version", 0)

    def load_state_pytree(self, state: dict[str, Any]) -> None:
        for k, v in state.items():
            setattr(self, k, v)
        self._touch_serving_state()


class Estimator:
    """fit(table) -> Model.  Subclasses define ``ParamsCls`` and ``_fit``."""

    ParamsCls: type[Params] = Params

    def __init__(self, params: Params | None = None, **kwargs):
        if params is None:
            params = self.ParamsCls(**kwargs)
        elif kwargs:
            params = params.replace(**kwargs)
        self.params = params
        self.last_fit_metrics: dict[str, float] = {}

    def fit(self, table: TpuTable) -> Model:
        from orange3_spark_tpu.obs.trace import refreshed_enabled as obs_enabled
        from orange3_spark_tpu.obs.trace import span

        # the outer obs bracket rides the OTPU_OBS kill-switch: under
        # OTPU_OBS=0 no report is built (its counter snapshots are the
        # only per-fit obs cost here). unique=True: a streaming _fit's
        # fit_stream opens its own richer "fit" span — record only the
        # outermost so traces never show fit ⊃ fit.
        report = None
        if obs_enabled():
            from orange3_spark_tpu.obs.report import RunReport

            report = RunReport("fit", estimator=type(self).__name__,
                               n_rows=table.n_rows)
        from orange3_spark_tpu.obs.context import trace_scope

        t0 = time.perf_counter()
        # mint the fit's run id here (reused — not shadowed — by a
        # streaming _fit's own @traced("fit") entry), so every span and
        # typed anomaly under this fit carries one identity
        with trace_scope("fit", reuse=True):
            with span("fit", unique=True, estimator=type(self).__name__):
                model = self._fit(table)
                if isinstance(model, Model):
                    try:
                        # don't time async dispatch
                        jax.block_until_ready(model.state_pytree)
                    except NotImplementedError:
                        pass
        # else: stateless result (e.g. QuantileDiscretizer -> Bucketizer)
        dt = time.perf_counter() - t0
        # rows/sec/chip is THE baseline metric (BASELINE.json "metric").
        # NOTE: first call includes XLA compile; benchmark harnesses must warm
        # up (bench.py fits twice and reports the second timing).
        n_chips = table.session.n_devices
        self.last_fit_metrics = {
            "fit_seconds": dt,
            "rows_per_sec_per_chip": table.n_rows / dt / max(n_chips, 1),
        }
        if report is not None and isinstance(model, Model):
            # a streaming _fit already attached its richer fit_stream
            # report — the outer bracket must not clobber it
            if getattr(model, "run_report_", None) is None:
                model.run_report_ = report.finish()
        return model

    def _fit(self, table: TpuTable) -> Model:
        raise NotImplementedError

    def fit_transform(self, table: TpuTable) -> TpuTable:
        return self.fit(table).transform(table)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"{type(self).__name__}({self.params})"


class Pipeline(Estimator):
    """Chain of estimators/transformers (pyspark.ml.Pipeline equivalent)."""

    def __init__(self, stages: Sequence[Estimator | Transformer]):
        super().__init__(Params())
        self.stages = list(stages)

    def _fit(self, table: TpuTable) -> "PipelineModel":
        fitted: list[Transformer] = []
        for stage in self.stages:
            if isinstance(stage, Estimator):
                model = stage.fit(table)
                fitted.append(model)
                table = model.transform(table)
            else:
                fitted.append(stage)
                table = stage.transform(table)
        return PipelineModel(fitted)


class PipelineModel(Model):
    def __init__(self, stages: Sequence[Transformer]):
        self.params = Params()
        self.stages = list(stages)

    def transform(self, table: TpuTable) -> TpuTable:
        for stage in self.stages:
            table = stage.transform(table)
        return table

    @property
    def state_pytree(self) -> dict[str, Any]:
        return {
            f"stage{i}": s.state_pytree
            for i, s in enumerate(self.stages)
            if isinstance(s, Model)
        }

    def load_state_pytree(self, state: dict[str, Any]) -> None:
        for key, sub in state.items():
            idx = int(key.removeprefix("stage"))
            stage = self.stages[idx]
            if not isinstance(stage, Model):
                raise ValueError(f"checkpoint has state for non-model stage {idx}")
            stage.load_state_pytree(sub)
        # the pipeline itself can be the served object (its executables
        # bake STAGE state), so its fingerprint must move too
        self._touch_serving_state()

    def _serve_state_token(self):
        return (getattr(self, "_serve_state_version", 0),
                tuple(s._serve_state_token() for s in self.stages
                      if isinstance(s, Model)))


def infer_class_values(table: TpuTable) -> tuple[str, ...]:
    """Class labels from the domain, or '0'..'max(y)' when untyped.

    The fallback max only looks at LIVE rows (W > 0) — filtered rows' labels
    must not inflate the class count.
    """
    import jax.numpy as jnp

    cvar = table.domain.class_var
    from orange3_spark_tpu.core.domain import DiscreteVariable

    if isinstance(cvar, DiscreteVariable) and cvar.values:
        return tuple(cvar.values)
    y_max = jnp.max(jnp.where(table.W > 0, table.y, 0.0))
    return tuple(str(i) for i in range(int(np.asarray(y_max).item()) + 1))


def predictions_to_numpy(table: TpuTable, column: str = "prediction") -> np.ndarray:
    """Collect one prediction column to host, stripping padding.

    Padding is stripped from the VALIDITY MASK, not just ``n_rows``: a
    serving-bucketed table whose caller did not track the logical row
    count (``n_rows == n_pad``) still carries W == 0 on every pad row, so
    the trailing zero-weight run is trimmed too. Interior zero-weight
    rows (``filter()``ed) are logical rows and are kept.

    Carve-out: on an exactly pad-aligned table a trailing zero-weight run
    is INDISTINGUISHABLE from trailing ``filter()``ed logical rows, and
    this function treats it as padding. Callers that filter trailing rows
    and need them back must track the logical row count (``n_rows <
    n_pad``) — that branch returns every logical row unconditionally."""
    col = np.asarray(jax.device_get(table.column(column)))[: table.n_rows]
    if table.n_rows < table.n_pad:
        # caller tracked the row count; pads already sliced away above —
        # every logical row is returned even if filter() zeroed them all
        return col
    W = np.asarray(jax.device_get(table.W))[: table.n_rows]
    live = np.flatnonzero(W > 0)
    if live.size == 0:
        return col[:0]
    return col[: int(live[-1]) + 1]
