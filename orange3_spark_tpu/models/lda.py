"""LDA — parity with ``pyspark.ml.clustering.LDA``.

MLlib's default optimizer is online variational Bayes (Hoffman et al.) over a
doc-term count matrix, one distributed aggregate of expected sufficient
statistics per iteration (SURVEY.md §2b; reconstructed, mount empty — public
API: k, maxIter, docConcentration, topicConcentration, learningOffset=1024,
learningDecay=0.51; model exposes topicsMatrix, describeTopics,
logLikelihood, logPerplexity, transform -> topicDistribution). TPU-native
redesign:

* documents are rows of the dense sharded count matrix ``X: f32[N, V]`` —
  the E-step inner loop (gamma/phi updates) is three matmuls
  (``expElogtheta @ expElogbeta``, ``(X/phinorm) @ expElogbetaᵀ``) per pass,
  batched over ALL docs at once on the MXU instead of per-doc Python loops;
* the sufficient-statistics reduction ``expElogthetaᵀ @ (X/phinorm)`` is the
  treeAggregate moment — its row-axis contraction GSPMD all-reduces over ICI;
* the outer VB loop is a jitted ``lax.fori_loop`` with Hoffman's learning
  rate ``(offset + t)^-decay``; full-corpus batches (subsamplingRate is
  accepted for API parity but the full batch is used — on TPU the full
  corpus fits the step budget that MLlib needed minibatches for).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orange3_spark_tpu.core.domain import ContinuousVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.base import Estimator, Model, Params


@dataclasses.dataclass(frozen=True)
class LDAParams(Params):
    k: int = 10                      # MLlib k
    max_iter: int = 20               # MLlib maxIter
    doc_concentration: float = -1.0  # MLlib docConcentration (alpha); -1 => 1/k
    topic_concentration: float = -1.0  # MLlib topicConcentration (eta); -1 => 1/k
    learning_offset: float = 1024.0  # MLlib learningOffset (tau0)
    learning_decay: float = 0.51     # MLlib learningDecay (kappa)
    subsampling_rate: float = 1.0    # accepted for parity; full batch used
    gamma_iters: int = 25            # inner E-step passes (MLlib: until tol)
    seed: int = 0


def _dirichlet_expectation(a):
    """E[log x] under Dirichlet(a), row-wise."""
    return jax.scipy.special.digamma(a) - jax.scipy.special.digamma(
        jnp.sum(a, axis=-1, keepdims=True)
    )


@partial(jax.jit, static_argnames=("k", "gamma_iters"))
def _e_step(X, W, lam, alpha, *, k: int, gamma_iters: int):
    """Batched variational E-step over all docs. Returns (gamma, sstats, bound-ish)."""
    n = X.shape[0]
    expElogbeta = jnp.exp(_dirichlet_expectation(lam))           # [k,V]
    gamma0 = jnp.ones((n, k), dtype=jnp.float32)

    def one_pass(gamma, _):
        expElogtheta = jnp.exp(_dirichlet_expectation(gamma))    # [N,k]
        phinorm = expElogtheta @ expElogbeta + 1e-30             # [N,V] MXU
        gamma = alpha + expElogtheta * ((X / phinorm) @ expElogbeta.T)
        return gamma, None

    gamma, _ = jax.lax.scan(one_pass, gamma0, None, length=gamma_iters)
    expElogtheta = jnp.exp(_dirichlet_expectation(gamma))
    phinorm = expElogtheta @ expElogbeta + 1e-30
    # sstats[k,V] = sum_n W_n * expElogtheta[n,k] * X[n,v]/phinorm[n,v]
    sstats = (expElogtheta * W[:, None]).T @ (X / phinorm)       # GSPMD psum
    sstats = sstats * expElogbeta
    return gamma, sstats


@partial(jax.jit, static_argnames=("k", "max_iter", "gamma_iters"))
def _online_vb(X, W, lam0, alpha, eta, tau0, kappa, *, k, max_iter, gamma_iters):
    def body(t, lam):
        _, sstats = _e_step(X, W, lam, alpha, k=k, gamma_iters=gamma_iters)
        rho = (tau0 + t) ** (-kappa)
        return (1.0 - rho) * lam + rho * (eta + sstats)

    return jax.lax.fori_loop(0, max_iter, body, lam0)


@partial(jax.jit, static_argnames=("k", "gamma_iters"))
def _bound(X, W, lam, alpha, eta, *, k: int, gamma_iters: int):
    """Variational lower bound on log p(docs) (Hoffman eq. 3, corpus part)."""
    gamma, _ = _e_step(X, W, lam, alpha, k=k, gamma_iters=gamma_iters)
    Elogtheta = _dirichlet_expectation(gamma)                    # [N,k]
    Elogbeta = _dirichlet_expectation(lam)                       # [k,V]
    # E[log p(docs|theta,beta)]: sum_nv X * logsumexp_k(Elogtheta+Elogbeta).
    # logsumexp over k == log(expElogtheta @ expElogbeta): one [N,V] matmul,
    # never the [N,k,V] broadcast (E[log·] terms are ≤ 0, so exp is stable).
    phinorm = jnp.exp(Elogtheta) @ jnp.exp(Elogbeta) + 1e-30     # [N,V] MXU
    ll_docs = jnp.sum(W[:, None] * X * jnp.log(phinorm))
    gln = jax.scipy.special.gammaln
    # E[log p(theta|alpha) - log q(theta|gamma)] per doc
    ll_theta = jnp.sum(
        W
        * (
            jnp.sum((alpha - gamma) * Elogtheta, axis=1)
            + jnp.sum(gln(gamma), axis=1)
            - gln(jnp.sum(gamma, axis=1))
            + gln(k * alpha)
            - k * gln(alpha)
        )
    )
    return ll_docs + ll_theta


class LDAModel(Model):
    def __init__(self, params, lam, vocab_size):
        self.params = params
        self.lam = lam                 # f32[k, V] variational topic params
        self.vocab_size = vocab_size
        self.n_docs_: int | None = None

    @property
    def state_pytree(self):
        return {"lam": self.lam}

    def topics_matrix(self) -> np.ndarray:
        """MLlib topicsMatrix: [V, k] column-normalized topic-word weights."""
        lam = np.asarray(self.lam)
        return (lam / lam.sum(axis=1, keepdims=True)).T

    def describe_topics(self, max_terms: int = 10):
        """MLlib describeTopics: per topic, top term indices + weights."""
        tm = self.topics_matrix()  # [V,k]
        out = []
        for c in range(self.params.k):
            order = np.argsort(tm[:, c])[::-1][:max_terms]
            out.append({"topic": c, "termIndices": order.tolist(),
                        "termWeights": tm[order, c].tolist()})
        return out

    def _alpha(self):
        p = self.params
        return jnp.float32(p.doc_concentration if p.doc_concentration > 0 else 1.0 / p.k)

    def _gamma(self, table: TpuTable):
        gamma, _ = _e_step(
            table.X, table.W, self.lam, self._alpha(),
            k=self.params.k, gamma_iters=self.params.gamma_iters,
        )
        return gamma

    def transform(self, table: TpuTable) -> TpuTable:
        """Append topicDistribution_{i} columns (normalized gamma)."""
        gamma = self._gamma(table)
        theta = gamma / jnp.sum(gamma, axis=1, keepdims=True)
        k = self.params.k
        new_attrs = list(table.domain.attributes) + [
            ContinuousVariable(f"topicDistribution_{i}") for i in range(k)
        ]
        new_domain = Domain(new_attrs, table.domain.class_vars, table.domain.metas)
        return table.with_X(jnp.concatenate([table.X, theta], axis=1), new_domain)

    def log_likelihood(self, table: TpuTable) -> float:
        p = self.params
        eta = jnp.float32(p.topic_concentration if p.topic_concentration > 0 else 1.0 / p.k)
        return float(
            _bound(table.X, table.W, self.lam, self._alpha(), eta,
                   k=p.k, gamma_iters=p.gamma_iters)
        )

    def log_perplexity(self, table: TpuTable) -> float:
        """MLlib logPerplexity: -logLikelihood / total token count."""
        tokens = float(jnp.sum(table.X * table.W[:, None]))
        return -self.log_likelihood(table) / max(tokens, 1.0)


class LDA(Estimator):
    ParamsCls = LDAParams
    params: LDAParams

    def _fit(self, table: TpuTable) -> LDAModel:
        p = self.params
        v = table.X.shape[1]
        alpha = jnp.float32(p.doc_concentration if p.doc_concentration > 0 else 1.0 / p.k)
        eta = jnp.float32(p.topic_concentration if p.topic_concentration > 0 else 1.0 / p.k)
        rng = np.random.default_rng(p.seed)
        lam0 = jax.device_put(
            rng.gamma(100.0, 0.01, size=(p.k, v)).astype(np.float32),
            table.session.replicated,
        )
        lam = _online_vb(
            table.X, table.W, lam0, alpha, eta,
            jnp.float32(p.learning_offset), jnp.float32(p.learning_decay),
            k=p.k, max_iter=p.max_iter, gamma_iters=p.gamma_iters,
        )
        model = LDAModel(p, lam, v)
        model.n_docs_ = table.n_rows
        return model
