"""Trace-context propagation — Dapper-style request identity end-to-end.

PR-7's spans answer "what regions ran"; they could not answer "which
REQUEST was that" — a micro-batched predict's submit, coalesced flush and
device dispatch land as unrelated events on three threads, and a typed
failure in production names no request at all. This module is the missing
identity layer:

* every routed serve call gets a **trace id** at ``route()`` /
  ``served_array()`` entry; every fit gets a **run id** at its
  ``fit_stream`` entry (the ``@traced("fit")`` chokepoint);
* the id rides a ``contextvars.ContextVar`` through the caller's whole
  request path — admission slots, the micro-batcher submit, the bucketed
  dispatch — and is explicitly adopted by worker threads that continue a
  request's work on another stack (the prefetch producer via
  :func:`adopt`; the micro-batcher carries per-request ids on the queued
  requests themselves, since one flush serves many traces);
* every span recorded while a context is active carries
  ``trace_id``/``span_id``/``parent_id`` (obs/trace.py), and the typed
  anomalies (``OverloadShedError``, ``MicroBatchTimeoutError``,
  ``DispatchWedgedError``, ``NumericalDivergenceError``) carry the trace
  id of the request they killed;
* **tail-biased retention**: under load, recording every fast-OK serve
  trace would wash the ring with the traces nobody debugs. With
  ``OTPU_TRACE_SAMPLE < 1`` a serve trace is sampled by a deterministic
  per-trace-id coin; an UNSAMPLED trace buffers its spans on the context
  and flushes them into the ring only if the request turned out
  interesting — it erred, was shed (:func:`flag_current_trace`), or ran
  slower than ``OTPU_TRACE_SLOW_MS`` — so slow/shed/erroring traces stay
  WHOLE in the ring while fast-OK ones pay one dropped list. Fit run
  contexts never sample (one fit is never ring-washing volume).

The scope is inert (shared no-op) under ``OTPU_OBS=0`` — zero allocation,
no contextvar writes.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import time
import zlib

from contextvars import ContextVar

from orange3_spark_tpu.utils import knobs

__all__ = [
    "TraceContext",
    "adopt",
    "current_trace",
    "current_trace_id",
    "flag_current_trace",
    "new_trace_id",
    "propagated_scope",
    "trace_scope",
]

#: the active TraceContext for this thread/task (workers inherit nothing —
#: they must adopt() the owning request's context explicitly)
_CTX: ContextVar["TraceContext | None"] = ContextVar(
    "otpu_trace_ctx", default=None)

_ids = itertools.count(1)


def new_trace_id(kind: str) -> str:
    """Process-unique, kind-prefixed id: ``serve-<pid>-<n>`` — readable in
    a Perfetto args pane and greppable in a flight bundle."""
    return f"{kind}-{os.getpid():x}-{next(_ids):06x}"


class TraceContext:
    """One request's (or one fit's) identity + retention state."""

    __slots__ = ("trace_id", "kind", "buffer", "flagged", "t0_ns")

    def __init__(self, trace_id: str, kind: str, sampled: bool):
        self.trace_id = trace_id
        self.kind = kind
        # None = record straight to the ring; a list = tail-retention
        # buffer (flushed on flag/error/slow, dropped otherwise)
        self.buffer: list | None = None if sampled else []
        self.flagged = False
        self.t0_ns = time.perf_counter_ns()

    def flag(self) -> None:
        """Mark this trace interesting: its buffered spans (if any) will
        flush into the ring at scope exit regardless of latency."""
        self.flagged = True


def current_trace() -> TraceContext | None:
    return _CTX.get()


def current_trace_id() -> str | None:
    """The active trace/run id, or None — what typed errors and flight
    bundles stamp themselves with."""
    ctx = _CTX.get()
    return ctx.trace_id if ctx is not None else None


def flag_current_trace() -> None:
    """Anomaly chokepoints (sheds, wedges, divergence) call this so an
    unsampled trace that hit one is retained whole."""
    ctx = _CTX.get()
    if ctx is not None:
        ctx.flag()


def _sampled(trace_id: str, sample: bool) -> bool:
    if not sample:
        return True
    rate = float(knobs.get_float("OTPU_TRACE_SAMPLE"))
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    # deterministic per-id coin (the fault-injection crc32 convention):
    # the same trace id samples the same way in a test and a subprocess
    return zlib.crc32(trace_id.encode()) / 0xFFFFFFFF < rate


class _NullScope:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullScope()


class _Scope:
    __slots__ = ("ctx", "_token")

    def __init__(self, ctx: TraceContext):
        self.ctx = ctx
        self._token = None

    def __enter__(self) -> TraceContext:
        self._token = _CTX.set(self.ctx)
        return self.ctx

    def __exit__(self, exc_type, exc, tb) -> bool:
        _CTX.reset(self._token)
        ctx = self.ctx
        buf = ctx.buffer
        if buf is not None:
            # tail-biased retention: keep the whole trace when it erred,
            # was flagged (shed/wedge), or ran slow; drop it otherwise
            slow_ns = float(knobs.get_float("OTPU_TRACE_SLOW_MS")) * 1e6
            if (ctx.flagged or exc_type is not None
                    or time.perf_counter_ns() - ctx.t0_ns >= slow_ns):
                from orange3_spark_tpu.obs import trace

                trace.flush_buffered(buf)
            buf.clear()
        return False


def trace_scope(kind: str = "serve", *, reuse: bool = False,
                sample: bool = False):
    """Bind a fresh trace context over a block. ``reuse=True`` keeps an
    already-active context instead of nesting a new identity (a fit
    bracketed by ``Estimator.fit`` must not mint two run ids);
    ``sample=True`` applies the ``OTPU_TRACE_SAMPLE`` tail-retention coin
    (serve requests — fits always record). No-op under ``OTPU_OBS=0``."""
    from orange3_spark_tpu.obs import trace

    if not trace.enabled():
        return _NULL
    if reuse and _CTX.get() is not None:
        return _NULL
    trace_id = new_trace_id(kind)
    return _Scope(TraceContext(trace_id, kind, _sampled(trace_id, sample)))


def propagated_scope(trace_id: str | None, kind: str = "serve"):
    """Adopt a trace id minted in ANOTHER process — the fleet RPC header
    (``X-OTPU-Trace``, fleet/rpc.py): the replica's serve/dispatch spans
    then carry the router-minted identity, so one trace spans
    router → replica → device dispatch across the process boundary.
    Propagated requests never tail-sample (the router already owns the
    retention decision for the trace; a replica dropping its half would
    leave every exported cross-process trace dangling). No-op under
    ``OTPU_OBS=0`` or with no id to adopt."""
    from orange3_spark_tpu.obs import trace

    if not trace_id or not trace.enabled():
        return _NULL
    return _Scope(TraceContext(trace_id, kind, sampled=True))


@contextlib.contextmanager
def adopt(ctx: TraceContext | None):
    """Worker threads continuing a request's work on another stack (the
    prefetch producer) adopt the owning context so their spans carry the
    same trace id. None adopts nothing (plain passthrough)."""
    if ctx is None:
        yield
        return
    token = _CTX.set(ctx)
    try:
        yield
    finally:
        _CTX.reset(token)
