"""Typed, thread-safe metrics registry — the one place counters live.

The reference stack scrapes Spark's metrics servlet; after six PRs our
equivalent was three process-global counter dicts in ``utils/profiling.py``
all serialized on ONE shared lock and readable only by bench.py. This
registry replaces them underneath (the legacy ``exec_counters()`` /
``serve_counters()`` / ``resilience_counters()`` shims keep their exact
field contract) and adds what a production operator needs:

* typed metrics — ``Counter`` (monotonic, float-valued), ``Gauge``
  (set/inc/dec), ``Histogram`` (fixed bucket bounds + sum/count, percentile
  estimation by linear interpolation inside the landing bucket);
* labels — each metric holds one value per label-tuple (``retries_total``
  broken out by ``cause=``, etc.), created on first touch;
* per-metric locking — two subsystems ticking different metrics never
  contend (the old design put the xla-compile listener, every serve tick
  and every dispatch tick behind one ``_exec_lock``);
* two exports — ``snapshot()`` (JSON-able nested dict: the bench ``obs``
  key, the run-report counter deltas) and ``to_prometheus()`` (text
  exposition format 0.0.4: the ``/metrics`` endpoint body).

The registry itself is always live — the ``OTPU_OBS=0`` kill-switch
no-ops spans and the telemetry endpoint, but the counter shims (and every
test/bench reading them) keep working unchanged.
"""

from __future__ import annotations

import math
import threading
from typing import Iterable

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "get_registry",
]

# prometheus-style defaults, widened for the second-to-minutes range our
# stage timings span (seconds everywhere — the unit rides the metric name)
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_NO_LABELS = ()


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items())) if labels else _NO_LABELS


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() else repr(f)


def _escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _label_str(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{name}="{_escape(str(val))}"' for name, val in key)
    return "{" + inner + "}"


class _Metric:
    """Shared label-child plumbing; one lock per metric."""

    kind = "untyped"

    def __init__(self, name: str, doc: str = ""):
        self.name = name
        self.doc = doc
        self._lock = threading.Lock()
        self._children: dict = {}

    def labels(self) -> list[dict]:
        with self._lock:
            return [dict(k) for k in self._children]

    def reset(self) -> None:
        with self._lock:
            self._children.clear()


class Counter(_Metric):
    """Monotonic float counter (``_total`` naming convention)."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (inc {n})")
        key = _label_key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + n

    def value(self, **labels) -> float:
        key = _label_key(labels)
        with self._lock:
            return self._children.get(key, 0.0)

    def total(self) -> float:
        """Sum across every label child (the legacy flat-counter view)."""
        with self._lock:
            return sum(self._children.values())

    def per_label(self, label_name: str) -> dict:
        """{label value: count} for one label dimension (the legacy
        ``retries_by_cause``-style breakdown)."""
        out: dict = {}
        with self._lock:
            for key, v in self._children.items():
                for name, val in key:
                    if name == label_name:
                        out[val] = out.get(val, 0.0) + v
        return out


class Gauge(_Metric):
    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._children[_label_key(labels)] = float(v)

    def inc(self, n: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._children.get(_label_key(labels), 0.0)


class Histogram(_Metric):
    """Fixed-bound bucket histogram (per-child: counts[], sum, count)."""

    kind = "histogram"

    def __init__(self, name: str, doc: str = "",
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(name, doc)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"{name}: needs at least one bucket bound")
        self.buckets = bs

    def _child(self, key):
        c = self._children.get(key)
        if c is None:
            # counts has one extra slot for the +Inf overflow bucket
            c = self._children[key] = {
                "counts": [0] * (len(self.buckets) + 1),
                "sum": 0.0, "count": 0,
            }
        return c

    def observe(self, v: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            c = self._child(key)
            i = 0
            for i, b in enumerate(self.buckets):  # noqa: B007
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            c["counts"][i] += 1
            c["sum"] += v
            c["count"] += 1

    def count(self, **labels) -> int:
        with self._lock:
            c = self._children.get(_label_key(labels))
            return c["count"] if c else 0

    def sum(self, **labels) -> float:
        with self._lock:
            c = self._children.get(_label_key(labels))
            return c["sum"] if c else 0.0

    def percentile(self, q: float, **labels) -> float | None:
        """Estimated q-th percentile (0..100) by linear interpolation
        inside the landing bucket; None on an empty child. The overflow
        bucket has no upper bound — its estimate is the last bound."""
        if not 0 <= q <= 100:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        with self._lock:
            c = self._children.get(_label_key(labels))
            if c is None or c["count"] == 0:
                return None
            counts = list(c["counts"])
            total = c["count"]
        rank = q / 100.0 * total
        cum = 0
        for i, n in enumerate(counts):
            if cum + n >= rank and n > 0:
                lo = 0.0 if i == 0 else self.buckets[i - 1]
                hi = self.buckets[min(i, len(self.buckets) - 1)]
                frac = (rank - cum) / n if n else 0.0
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += n
        return self.buckets[-1]


class MetricsRegistry:
    """Name -> metric map with get-or-create constructors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    # ------------------------------------------------------ constructors
    def _get_or_create(self, cls, name, doc, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, doc, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, doc: str = "") -> Counter:
        return self._get_or_create(Counter, name, doc)

    def gauge(self, name: str, doc: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, doc)

    def histogram(self, name: str, doc: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, doc, buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self, names: Iterable[str] | None = None) -> None:
        """Zero the named metrics (all when None) — values clear, the
        metric objects (and callers' references to them) stay registered."""
        with self._lock:
            targets = [self._metrics[n] for n in names
                       if n in self._metrics] if names is not None \
                else list(self._metrics.values())
        for m in targets:
            m.reset()

    # ----------------------------------------------------------- exports
    @staticmethod
    def _copy_children(m) -> dict:
        """Deep-enough copy UNDER the metric lock: the histogram counts
        list must be duplicated too, or a concurrent observe() mutates
        the list a reader is iterating outside the lock and the exported
        buckets disagree with the copied count/sum."""
        with m._lock:
            return {
                k: ({"counts": list(v["counts"]), "sum": v["sum"],
                     "count": v["count"]} if isinstance(v, dict) else v)
                for k, v in m._children.items()
            }

    def snapshot(self) -> dict:
        """JSON-able nested view of every metric's current children."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict = {}
        for m in metrics:
            children = self._copy_children(m)
            values = []
            for key, v in sorted(children.items()):
                entry: dict = {"labels": dict(key)}
                if m.kind == "histogram":
                    entry["count"] = v["count"]
                    entry["sum"] = round(v["sum"], 9)
                    entry["buckets"] = {
                        _fmt_value(b): c for b, c in zip(
                            list(m.buckets) + [math.inf], v["counts"])}
                else:
                    entry["value"] = v
                values.append(entry)
            out[m.name] = {"type": m.kind, "doc": m.doc, "values": values}
        return out

    def to_prometheus(self) -> str:
        """Text exposition format 0.0.4 (the ``/metrics`` body)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in sorted(metrics, key=lambda m: m.name):
            if m.doc:
                lines.append(f"# HELP {m.name} {_escape(m.doc)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            children = self._copy_children(m)
            if not children and m.kind != "histogram":
                # exposing the zero keeps scraped dashboards continuous
                lines.append(f"{m.name} 0")
            for key, v in sorted(children.items()):
                if m.kind == "histogram":
                    base = m.name
                    cum = 0
                    for b, c in zip(list(m.buckets) + [math.inf],
                                    v["counts"]):
                        cum += c
                        lk = list(key) + [("le", _fmt_value(b))]
                        lines.append(
                            f"{base}_bucket{_label_str(tuple(lk))} {cum}")
                    lines.append(
                        f"{base}_sum{_label_str(key)} {_fmt_value(v['sum'])}")
                    lines.append(
                        f"{base}_count{_label_str(key)} {v['count']}")
                else:
                    lines.append(
                        f"{m.name}{_label_str(key)} {_fmt_value(v)}")
        return "\n".join(lines) + "\n"


#: the process-wide registry every subsystem ticks into
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
