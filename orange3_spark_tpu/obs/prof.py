"""Goodput & memory attribution plane (docs/observability.md §goodput).

The obs stack through PR 11 can say *that* a request was slow or a fit
wedged; this module answers **where the time and the HBM went**. Three
coupled pieces, one kill-switch (``OTPU_PROF=0`` restores the pre-prof
behavior bitwise — no accounting, no ledger ticks, deep capture refused):

* **Step-time decomposition** (:class:`GoodputAccountant`) — an
  always-on, low-overhead accountant fed by the existing exec
  chokepoints: ``PipelinedExecutor`` queue waits (input), the
  ``bound_dispatch`` periodic sync (the one place the driver observes
  device pace), explicit barriers (epoch walls, the fused-replay final
  sync) and the codec/plan encode seconds off ``PipelineStats``. Each
  fit's wall decomposes into five disjoint fractions —
  ``device_compute`` / ``input_wait`` / ``host_encode`` / ``sync_wait``
  / ``framework`` — that sum to 1.0 by construction (``framework`` is
  the measured residual: python step-issue overhead, seeding, report
  building). Per epoch the bottleneck is classified input-bound vs
  compute-bound vs sync-bound with hysteresis (``OTPU_PROF_HYST``) so a
  fit oscillating at a boundary never flaps. Exposed as
  ``otpu_goodput_fraction{stage=}`` gauges, a ``goodput`` section in
  every ``RunReport``, and per-replica through the fleet digest.

  Attribution semantics (the host's view of an async pipeline): queue
  waits are *input*; the periodic dispatch sync is *device compute*
  (the driver only ever observes the device by blocking on it, and the
  periodic sync blocks exactly while the device drains queued steps);
  explicit barriers (epoch-boundary ``block_until_ready``, the
  fused-replay final sync) are *synchronization*; encode/plan seconds
  run on the prefetch thread, so only the part that could not hide
  behind device work — ``min(encode_s, input_wait)`` — is charged as
  *host_encode* (the rest was free).

* **Device-memory ledger** (:class:`DeviceMemoryLedger`) — a registry
  of named device-resident allocations: ``_DeviceCache`` chunks
  (codec-aware bytes, the owner ``cache_chunks``), model/optimizer
  state (``model_state``), serving ``ExecutableCache`` entries
  (``serve_executables``, bytes best-effort via the executable's
  ``memory_analysis``), and the fused-replay stacks incl. sparse plans
  (``replay_plans``). Live bytes per owner ride
  ``otpu_device_bytes{owner=}``; per-fit peak watermarks land in the
  report's ``device_memory`` section; :meth:`reconcile` compares the
  ledger total against ``jax.live_arrays()`` and the backend's
  ``memory_stats()`` where available — the delta is *reported*, never
  asserted (JAX holds internal buffers the ledger doesn't name).

* **On-demand deep capture** (:func:`capture`) — ``POST
  /debug/profile?duration_ms=`` on the obs server (loopback only,
  rate-limited by ``OTPU_PROF_RATE_S`` → 429, serialized → 409) runs
  ``jax.profiler.trace`` plus a goodput+ledger+registry snapshot into
  one atomic artifact directory under ``OTPU_PROF_DIR``
  (``capture-<ns>-<reason>/`` with ``snapshot.json`` + ``jax_trace/``;
  written into a ``.tmp`` sibling and renamed, so a reader never sees a
  half-written capture). ``utils.profiling.profile_trace`` routes
  through the same serialized + rate-limited + atomic path
  (:func:`trace_capture`), keeping its public signature; manual pulls:
  ``tools/obs_dump.py --profile``, rendered by ``tools/goodput_view.py``.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import threading
import time

from orange3_spark_tpu.obs import trace as _trace
from orange3_spark_tpu.obs.registry import REGISTRY
from orange3_spark_tpu.utils import knobs

__all__ = [
    "BOTTLENECKS",
    "CaptureBusyError",
    "CaptureDisabledError",
    "CaptureRateLimitedError",
    "DeviceMemoryLedger",
    "GoodputAccountant",
    "LEDGER",
    "PROF_SCHEMA_VERSION",
    "STAGES",
    "attach_fit_report",
    "begin_fit",
    "capture",
    "capture_snapshot",
    "current",
    "end_fit",
    "force_disabled",
    "force_enabled",
    "last_goodput",
    "ledger_release",
    "ledger_set",
    "note_input_wait",
    "note_sync",
    "prof_enabled",
    "refreshed_enabled",
    "reset_rate_limit",
    "trace_capture",
]

log = logging.getLogger("orange3_spark_tpu")

PROF_SCHEMA_VERSION = 1

#: the five disjoint wall fractions, in reporting order
STAGES = ("device_compute", "input_wait", "host_encode", "sync_wait",
          "framework")

#: stage -> bottleneck label. host_encode counts toward input_bound
#: (exposed encode IS input-pipeline slowness — the fix is the same:
#: feed the device faster); framework classifies as its own label, so a
#: compile/python-dominated run is never mislabeled as one of the
#: measured waits it dwarfs.
BOTTLENECKS = {
    "input_wait": "input_bound",
    "host_encode": "input_bound",
    "device_compute": "compute_bound",
    "sync_wait": "sync_bound",
    "framework": "framework_bound",
}

_M_GOODPUT = REGISTRY.gauge(
    "otpu_goodput_fraction",
    "per-stage fraction of the last finished fit's wall "
    "(device_compute/input_wait/host_encode/sync_wait/framework)")
_M_DEVICE_BYTES = REGISTRY.gauge(
    "otpu_device_bytes",
    "live device-resident bytes per ledger owner (cache_chunks / "
    "model_state / serve_executables / replay_plans)")
_M_CAPTURES = REGISTRY.counter(
    "otpu_prof_captures_total",
    "deep-profile capture attempts, by outcome "
    "(ok/busy/rate_limited/error)")


def prof_enabled() -> bool:
    """The ``OTPU_PROF`` kill-switch, re-resolved per call (the
    OTPU_DONATE convention: chokepoints re-read, never a cached latch).
    Called once per fit entry / ledger mutation / capture — never inside
    the per-step hot path (that path gates on :func:`current` being
    None, a bare contextvar read)."""
    return knobs.get_bool("OTPU_PROF")


# Alias so chokepoints read the same way as trace.refreshed_enabled().
refreshed_enabled = prof_enabled


@contextlib.contextmanager
def _force(value: str):
    """Env-backed temporary OTPU_PROF override (the bench A/B arms)."""
    prev = os.environ.get("OTPU_PROF")
    os.environ["OTPU_PROF"] = value
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("OTPU_PROF", None)
        else:
            os.environ["OTPU_PROF"] = prev


def force_disabled():
    """Temporarily disable the prof plane (the bench A/B's off arm)."""
    return _force("0")


def force_enabled():
    """Temporarily force the prof plane ON (the on arm must measure real
    accounting even under an ambient OTPU_PROF=0)."""
    return _force("1")


# ===================================================== goodput accounting
class GoodputAccountant:
    """One fit's wall-time decomposition. Created at fit entry
    (:func:`begin_fit`), fed by the exec chokepoints through the
    module-level :func:`note_sync` / :func:`note_input_wait` hooks (a
    contextvar lookup — no knob read on the hot path), closed by
    :meth:`finish`.

    The measured buckets are *driver-thread blocked seconds* and are
    disjoint by construction (the driver can only block in one place at
    a time); ``host_encode`` is carved out of ``input_wait`` at result
    time (``min(encode_s, input_wait_raw)`` — encode hidden behind
    device work cost the fit nothing); ``framework`` is the residual.
    Fractions therefore sum to exactly 1.0 (bench-gated at ±0.02 after
    rounding)."""

    def __init__(self, kind: str = "fit", hysteresis: float | None = None):
        self.kind = kind
        self.hysteresis = float(
            hysteresis if hysteresis is not None
            else knobs.get_float("OTPU_PROF_HYST"))
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        # cumulative driver-thread blocked seconds
        self._dev = 0.0          # periodic dispatch syncs (device pace)
        self._sync = 0.0         # explicit barriers
        self._wait = 0.0         # prefetch queue waits
        self._encode = 0.0       # external cumulative feed (prefetch thread)
        # per-epoch classification state
        self._mark = (0.0, 0.0, 0.0, 0.0, self._t0)
        self.epochs: list[dict] = []
        self.bottleneck: str | None = None
        self._wm = LEDGER.watermark()
        # the watermark dict is walked on EVERY ledger mutation: an
        # accountant abandoned by an ABORTED fit (no finish, no
        # end_fit) must still close its watermark when it dies — the
        # next begin_fit drops the contextvar's ref, GC does the rest.
        # Deferred (lock-free) close: GC finalizers must never take the
        # ledger lock. The callback holds no reference back to this
        # accountant, so the finalizer cannot keep it alive.
        import weakref

        weakref.finalize(self, LEDGER.defer_watermark_close,
                         self._wm._key)
        self._result: dict | None = None

    # ------------------------------------------------------------- feeds
    def add(self, stage: str, seconds: float) -> None:
        """Accumulate driver-blocked seconds into one measured bucket."""
        if seconds <= 0.0:
            return
        with self._lock:
            if stage == "device_compute":
                self._dev += seconds
            elif stage == "sync_wait":
                self._sync += seconds
            elif stage == "input_wait":
                self._wait += seconds
            else:
                raise ValueError(
                    f"goodput: unknown measured stage {stage!r} "
                    f"(framework/host_encode are derived, not fed)")

    def feed_encode(self, encode_s: float) -> None:
        """Set the CUMULATIVE encode/plan seconds (prefetch-thread work,
        read off PipelineStats at epoch boundaries / finish)."""
        with self._lock:
            self._encode = max(self._encode, float(encode_s))

    # -------------------------------------------------------- epoch feed
    @staticmethod
    def _decompose(wall, dev, sync, wait, encode):
        """(seconds per stage, disjoint, clamped to wall)."""
        host_encode = min(max(encode, 0.0), max(wait, 0.0))
        input_wait = max(wait - host_encode, 0.0)
        measured = dev + sync + input_wait + host_encode
        if wall > 0 and measured > wall:
            # overlapping/duplicated measurement can only ever overshoot
            # by noise; scale down so the buckets stay a partition
            scale = wall / measured
            dev, sync = dev * scale, sync * scale
            input_wait, host_encode = (input_wait * scale,
                                       host_encode * scale)
            measured = wall
        return {
            "device_compute": dev,
            "input_wait": input_wait,
            "host_encode": host_encode,
            "sync_wait": sync,
            "framework": max(wall - measured, 0.0),
        }

    def _classify(self, fractions: dict) -> str:
        """Hysteresis classifier over the SUMMED label fractions: the
        incumbent keeps the title unless a challenger's fraction beats
        it by ``hysteresis`` (absolute). A fresh accountant (no
        incumbent) takes the plain argmax; nothing measured at all
        (wall 0) reads framework_bound."""
        cands: dict[str, float] = {}
        for stage, label in BOTTLENECKS.items():
            cands[label] = cands.get(label, 0.0) + fractions.get(stage,
                                                                 0.0)
        best = max(cands, key=cands.get)
        if cands[best] <= 0.0:
            return "framework_bound"
        if self.bottleneck is None or self.bottleneck not in cands:
            return best
        if cands[best] > cands[self.bottleneck] + self.hysteresis:
            return best
        return self.bottleneck

    def epoch_boundary(self, epoch: int, *,
                       encode_s: float | None = None) -> dict:
        """Close one epoch's window: per-epoch stage deltas, classify
        with hysteresis, record. Emits a ``bottleneck`` instant on
        CHANGE only (the timeline shows regime shifts, not every
        epoch)."""
        if encode_s is not None:
            self.feed_encode(encode_s)
        now = time.perf_counter()
        with self._lock:
            dev0, sync0, wait0, enc0, t0 = self._mark
            wall = max(now - t0, 0.0)
            secs = self._decompose(wall, self._dev - dev0,
                                   self._sync - sync0,
                                   self._wait - wait0,
                                   self._encode - enc0)
            self._mark = (self._dev, self._sync, self._wait,
                          self._encode, now)
        fracs = {s: (v / wall if wall > 0 else 0.0)
                 for s, v in secs.items()}
        prev = self.bottleneck
        label = self._classify(fracs)
        self.bottleneck = label
        entry = {"epoch": int(epoch), "bottleneck": label,
                 "wall_s": round(wall, 6),
                 "fractions": {s: round(f, 4) for s, f in fracs.items()}}
        self.epochs.append(entry)
        if label != prev and prev is not None:
            _trace.instant("bottleneck", epoch=int(epoch), was=prev,
                           now=label)
        return entry

    # ------------------------------------------------------------ result
    def finish(self, *, encode_s: float | None = None,
               wall_s: float | None = None) -> dict:
        """Freeze the decomposition (idempotent — first call wins), set
        the ``otpu_goodput_fraction`` gauges, publish as the process's
        :func:`last_goodput`."""
        global _last_goodput
        if self._result is not None:
            return self._result
        if encode_s is not None:
            self.feed_encode(encode_s)
        wall = (float(wall_s) if wall_s is not None
                else time.perf_counter() - self._t0)
        with self._lock:
            secs = self._decompose(wall, self._dev, self._sync,
                                   self._wait, self._encode)
        # fractions off UNROUNDED seconds, then rounded: the residual
        # construction makes them sum to 1.0 exactly, rounding moves the
        # sum by < 5 * 5e-5 — comfortably inside the ±0.02 bench gate
        fracs = {s: round(v / wall, 4) if wall > 0 else 0.0
                 for s, v in secs.items()}
        if self.bottleneck is None:
            self.bottleneck = self._classify(fracs)
        self._result = {
            "schema": PROF_SCHEMA_VERSION,
            "kind": self.kind,
            "wall_s": round(wall, 6),
            "fractions": fracs,
            "seconds": {s: round(v, 6) for s, v in secs.items()},
            "bottleneck": self.bottleneck,
            "epochs": list(self.epochs),
            "peak_device_bytes": self._wm.close(),
        }
        for s, f in fracs.items():
            _M_GOODPUT.set(f, stage=s)
        _last_goodput = self._result
        return self._result


#: the current fit's accountant on this thread of control (contextvars:
#: the dispatch hook reads it lock-free; None = prof off or no fit live)
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "otpu_prof_accountant", default=None)
_last_goodput: dict | None = None


def current() -> GoodputAccountant | None:
    return _CURRENT.get()


def begin_fit(kind: str = "fit") -> GoodputAccountant | None:
    """Fit-entry chokepoint: a live accountant under ``OTPU_PROF``,
    None under the kill-switch (every downstream hook then no-ops on a
    bare contextvar read — the PR-11 path, bitwise). Always (re)sets
    the contextvar, so an earlier fit that aborted mid-flight cannot
    leave its stale accountant collecting this fit's waits."""
    if not prof_enabled():
        _CURRENT.set(None)
        return None
    acc = GoodputAccountant(kind)
    # plain set, NOT a reset token: fits never nest, and a token chain
    # would keep every abandoned (aborted-fit) accountant alive through
    # its predecessor reference — defeating the watermark finalizer
    _CURRENT.set(acc)
    return acc


def end_fit(acc: GoodputAccountant | None) -> None:
    """Clear the contextvar (finish() may run before or after). An
    accountant abandoned without finish() (an aborted fit, the bench
    A/B arms) closes its ledger watermark here — the watermark dict is
    iterated on EVERY ledger mutation, so a leak is a per-process
    slowdown, not just bookkeeping."""
    if acc is None:
        return
    if acc._result is None:
        acc._wm.close()
    if _CURRENT.get() is acc:
        _CURRENT.set(None)


def note_sync(seconds: float, *, barrier: bool = False) -> None:
    """The ``bound_dispatch`` / explicit-barrier hook: charge driver
    seconds blocked on the device. Periodic syncs are device pace
    (``device_compute``); explicit barriers (``barrier=True``) are
    ``sync_wait``. A bare contextvar read when no fit is live."""
    acc = _CURRENT.get()
    if acc is not None:
        acc.add("sync_wait" if barrier else "device_compute", seconds)


def note_input_wait(seconds: float) -> None:
    """The ``PipelinedExecutor`` consumer hook: driver seconds blocked
    on the prefetch queue."""
    acc = _CURRENT.get()
    if acc is not None:
        acc.add("input_wait", seconds)


def last_goodput() -> dict | None:
    """The most recent finished fit's decomposition (what a serving
    process's deep capture reports when no fit is live)."""
    return _last_goodput


# ===================================================== device-memory ledger
class DeviceMemoryLedger:
    """Named device-resident allocations: ``set(owner, name, nbytes)`` /
    ``release(owner, name)``, live bytes per owner on
    ``otpu_device_bytes{owner=}``, a running peak, per-fit peaks via
    :meth:`watermark`, and best-effort reconciliation against the JAX
    runtime. Thread-safe; every mutation is a no-op under
    ``OTPU_PROF=0`` (release always applies, so a mid-process kill-
    switch flip cannot strand entries)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[tuple[str, str], int] = {}
        self._total = 0
        self._peak = 0
        self._watermarks: dict[int, "DeviceMemoryLedger._Watermark"] = {}
        self._wm_seq = 0
        # GC-finalizer inbox: weakref.finalize callbacks run
        # synchronously on whatever thread triggered cyclic GC — which
        # can be a thread ALREADY inside this ledger's (non-reentrant)
        # lock, since the methods allocate while holding it. Finalizers
        # therefore only append here (deque.append is atomic, no lock)
        # and every ledger operation drains the inbox at lock entry.
        import collections

        self._pending: "collections.deque" = collections.deque()

    # ------------------------------------------- finalizer-safe deferral
    def defer_release(self, owner: str, name: str) -> None:
        """Release an entry from a GC-finalizer context: lock-free
        enqueue, applied by the next ledger operation."""
        self._pending.append(("release", owner, name))

    def defer_watermark_close(self, key: int) -> None:
        self._pending.append(("wm", key, None))

    def _drain_pending_locked(self) -> None:
        touched: set[str] = set()
        while self._pending:
            try:
                kind, a, b = self._pending.popleft()
            except IndexError:
                break
            if kind == "release":
                prev = self._entries.pop((a, b), None)
                if prev is not None:
                    self._total -= prev
                    touched.add(a)
            else:
                self._watermarks.pop(a, None)
        for owner in touched:
            owner_total = sum(v for (o, _n), v in self._entries.items()
                              if o == owner)
            _M_DEVICE_BYTES.set(owner_total, owner=owner)

    class _Watermark:
        """Max ledger total observed since creation (a fit's HBM peak)."""

        def __init__(self, ledger: "DeviceMemoryLedger", key: int,
                     start: int):
            self._ledger = ledger
            self._key = key
            self.high = start

        def peak(self) -> int:
            return self.high

        def close(self) -> int:
            with self._ledger._lock:
                self._ledger._watermarks.pop(self._key, None)
            return self.high

    def watermark(self) -> "DeviceMemoryLedger._Watermark":
        with self._lock:
            self._drain_pending_locked()
            self._wm_seq += 1
            wm = self._Watermark(self, self._wm_seq, self._total)
            self._watermarks[self._wm_seq] = wm
            return wm

    # -------------------------------------------------------- mutations
    # The gauge writes happen INSIDE the ledger lock: published outside
    # it, two racing mutations of one owner could land their .set calls
    # out of order and pin phantom bytes on the gauge the fleet digest
    # (and the ROADMAP-3 autoscaler) reads until the owner next moves.
    # Lock order is ledger -> metric; nothing takes them the other way.
    def set(self, owner: str, name: str, nbytes: int) -> None:
        if not prof_enabled():
            return
        nbytes = max(int(nbytes), 0)
        with self._lock:
            self._drain_pending_locked()
            key = (owner, name)
            self._total += nbytes - self._entries.get(key, 0)
            self._entries[key] = nbytes
            self._peak = max(self._peak, self._total)
            for wm in self._watermarks.values():
                wm.high = max(wm.high, self._total)
            owner_total = sum(v for (o, _n), v in self._entries.items()
                              if o == owner)
            _M_DEVICE_BYTES.set(owner_total, owner=owner)

    def release(self, owner: str, name: str) -> None:
        with self._lock:
            self._drain_pending_locked()
            prev = self._entries.pop((owner, name), None)
            if prev is None:
                return
            self._total -= prev
            owner_total = sum(v for (o, _n), v in self._entries.items()
                              if o == owner)
            _M_DEVICE_BYTES.set(owner_total, owner=owner)

    # ------------------------------------------------------------- views
    def get(self, owner: str, name: str) -> int | None:
        with self._lock:
            self._drain_pending_locked()
            return self._entries.get((owner, name))

    def owner_bytes(self) -> dict[str, int]:
        out: dict[str, int] = {}
        with self._lock:
            self._drain_pending_locked()
            for (owner, _name), v in self._entries.items():
                out[owner] = out.get(owner, 0) + v
        return dict(sorted(out.items()))

    def total(self) -> int:
        with self._lock:
            self._drain_pending_locked()
            return self._total

    def peak(self) -> int:
        with self._lock:
            return self._peak

    def snapshot(self, max_entries: int = 64) -> dict:
        """The ledger table (flight bundles, reports, captures): per-
        owner totals plus the largest entries by name — an OOM-adjacent
        post-mortem finally names the tenant."""
        with self._lock:
            self._drain_pending_locked()
            # ONE lock hold for entries + owners + total: a snapshot
            # racing mutators must stay internally consistent (owner
            # sums == total == entry sums), or a post-mortem reader
            # chases phantom leaks
            entries = sorted(
                ({"owner": o, "name": n, "bytes": v}
                 for (o, n), v in self._entries.items()),
                key=lambda e: -e["bytes"])
            owners: dict[str, int] = {}
            for (owner, _name), v in self._entries.items():
                owners[owner] = owners.get(owner, 0) + v
            total, peak = self._total, self._peak
        dropped = max(len(entries) - max_entries, 0)
        out = {
            "prof_schema": PROF_SCHEMA_VERSION,
            "owners": dict(sorted(owners.items())),
            "total_bytes": total,
            "peak_bytes": peak,
            "entries": entries[:max_entries],
        }
        if dropped:
            out["entries_truncated"] = dropped
        return out

    def reconcile(self) -> dict:
        """Ledger total vs what the runtime reports — DELTA reported,
        never asserted: ``jax.live_arrays()`` includes every array the
        process holds (constants, RNG keys, results the caller kept) and
        backend ``memory_stats()`` exists only on some runtimes."""
        out: dict = {"ledger_bytes": self.total(),
                     "jax_live_bytes": None,
                     "backend_bytes_in_use": None,
                     "delta_vs_live_bytes": None}
        try:
            import jax

            live = sum(getattr(a, "nbytes", 0) for a in jax.live_arrays())
            out["jax_live_bytes"] = int(live)
            out["delta_vs_live_bytes"] = int(live) - out["ledger_bytes"]
            stats = None
            devs = jax.local_devices()
            if devs:
                ms = getattr(devs[0], "memory_stats", None)
                stats = ms() if callable(ms) else None
            if stats:
                out["backend_bytes_in_use"] = int(
                    stats.get("bytes_in_use", 0))
        except Exception:  # noqa: BLE001 - reconciliation is best-effort
            pass
        return out

    def clear(self) -> None:
        """Tests only: forget every entry (gauges re-zero per owner)."""
        with self._lock:
            self._drain_pending_locked()
            owners = {o for (o, _n) in self._entries}
            self._entries.clear()
            self._total = 0
            self._peak = 0
            for o in owners:
                _M_DEVICE_BYTES.set(0, owner=o)


#: the process-wide ledger every subsystem registers into
LEDGER = DeviceMemoryLedger()


class _LedgerGuard:
    """Frame-scoped release guard (see :func:`ledger_guard`)."""

    __slots__ = ("__weakref__", "finalizer")


def ledger_guard(owner: str, name: str) -> _LedgerGuard:
    """An object whose death releases the named ledger entry — bind it
    to the owning stack frame so an exception path cannot strand the
    entry (release is idempotent: an explicit release first makes the
    guard's firing a no-op). ``guard.finalizer.detach()`` hands
    ownership elsewhere (e.g. to a model's own finalizer) when the
    happy path wants the entry to outlive the frame. The finalizer body
    is the LOCK-FREE deferred release: cyclic GC may run it on a thread
    already holding the ledger lock."""
    import weakref

    g = _LedgerGuard()
    g.finalizer = weakref.finalize(g, LEDGER.defer_release, owner, name)
    return g


def ledger_release_on_gc(owner: str, name: str) -> None:
    """Finalizer-safe release for ``weakref.finalize`` callbacks: only
    a lock-free enqueue (see ``DeviceMemoryLedger.defer_release``) —
    a finalizer that took the ledger lock could self-deadlock the
    thread whose in-lock allocation triggered the GC pass."""
    LEDGER.defer_release(owner, name)


def tree_device_bytes(tree) -> int:
    """Total ``nbytes`` across a pytree's array leaves (the ledger's
    standard sizing rule — codec-encoded dict leaves count as stored)."""
    import jax

    return int(sum(getattr(x, "nbytes", 0) for x in jax.tree.leaves(tree)))


def ledger_set(owner: str, name: str, nbytes: int) -> None:
    LEDGER.set(owner, name, nbytes)


def ledger_release(owner: str, name: str) -> None:
    LEDGER.release(owner, name)


def attach_fit_report(report, acc: GoodputAccountant | None, *,
                      encode_s: float | None = None,
                      cache_key: str | None = None) -> None:
    """Fit-end chokepoint: freeze the accountant, attach the ``goodput``
    and ``device_memory`` sections to the RunReport (absent — not null —
    under the kill-switch, so a PR-11 consumer sees the PR-11 dict).
    ``cache_key`` names the fit's own ``cache_chunks`` ledger entry so
    the bench can cross-check it against the legacy ``cache_bytes``
    stage key without ambiguity from other live caches."""
    if acc is None:
        return
    result = acc.finish(encode_s=encode_s)
    dm = LEDGER.snapshot()
    dm["peak_bytes_fit"] = result["peak_device_bytes"]
    dm["reconciliation"] = LEDGER.reconcile()
    if cache_key is not None:
        dm["cache_entry_bytes"] = LEDGER.get("cache_chunks", cache_key)
    if report is not None:
        report.goodput = result
        report.device_memory = dm
    end_fit(acc)


# ========================================================== deep capture
class CaptureDisabledError(RuntimeError):
    """Deep capture refused: the prof plane is off (``OTPU_PROF=0``)."""


class CaptureBusyError(RuntimeError):
    """A deep capture is already running — captures are serialized (one
    ``jax.profiler`` session at a time; the endpoint answers 409)."""


class CaptureRateLimitedError(RuntimeError):
    """Inside the ``OTPU_PROF_RATE_S`` window since the last capture
    (the endpoint answers 429)."""


_capture_lock = threading.Lock()
_rate_lock = threading.Lock()
_last_capture = 0.0            # monotonic; 0 = never


def reset_rate_limit() -> None:
    """Tests: forget the last capture time."""
    global _last_capture
    with _rate_lock:
        _last_capture = 0.0


def _claim_rate_slot() -> tuple[float, float]:
    """Claim the rate slot BEFORE the (slow) capture — two concurrent
    requests produce one capture; returns ``(previous stamp, claimed
    stamp)`` so a failed capture can hand the slot back."""
    global _last_capture
    min_gap = float(knobs.get_float("OTPU_PROF_RATE_S"))
    now = time.monotonic()
    with _rate_lock:
        if _last_capture and now - _last_capture < min_gap:
            _M_CAPTURES.inc(1, outcome="rate_limited")
            raise CaptureRateLimitedError(
                f"deep capture rate-limited: last capture "
                f"{now - _last_capture:.1f}s ago "
                f"(OTPU_PROF_RATE_S={min_gap})")
        prev, _last_capture = _last_capture, now
    return prev, now


def _release_rate_slot(prev: float, claimed_at: float) -> None:
    global _last_capture
    with _rate_lock:
        if _last_capture == claimed_at:
            _last_capture = prev


@contextlib.contextmanager
def _capture_session():
    """The shared serialize + rate-slot + outcome accounting EVERY deep
    capture runs under (one definition, so :func:`capture` and
    :func:`trace_capture` cannot drift): non-blocking lock → busy
    (409-class), rate window → rate_limited (429-class), a failing
    capture hands its claimed slot back and ticks ``error``, a clean
    one ticks ``ok``. The body owns only the artifact work."""
    if not _capture_lock.acquire(blocking=False):
        _M_CAPTURES.inc(1, outcome="busy")
        raise CaptureBusyError(
            "a deep capture is already running (captures serialize — "
            "one jax.profiler session at a time)")
    try:
        prev, claimed_at = _claim_rate_slot()
        try:
            yield
        except BaseException:
            # one transiently-failed capture must not silence the
            # whole rate window (the flight recorder's convention)
            _release_rate_slot(prev, claimed_at)
            _M_CAPTURES.inc(1, outcome="error")
            raise
        _M_CAPTURES.inc(1, outcome="ok")
    finally:
        _capture_lock.release()


def capture_snapshot(reason: str, duration_ms: float | None = None,
                     **extra) -> dict:
    """The JSON half of a deep capture: the last goodput decomposition,
    the ledger table + reconciliation, the full registry and the
    resolved knob table — everything a profile needs for context."""
    snap = {
        "prof_schema": PROF_SCHEMA_VERSION,
        "written_at": time.time(),
        "pid": os.getpid(),
        "reason": reason,
        "duration_ms": duration_ms,
        "goodput": last_goodput(),
        "ledger": LEDGER.snapshot(),
        "reconciliation": LEDGER.reconcile(),
        "registry": REGISTRY.snapshot(),
        "knobs": knobs.resolved(),
    }
    if extra:
        snap["extra"] = extra
    return snap


def _jax_trace(out_dir: str):
    """The profiler context, guarded: a jax build without a working
    profiler must degrade the capture to snapshot-only, not kill it."""
    try:
        import jax

        return jax.profiler.trace(out_dir)
    except Exception as e:  # noqa: BLE001 - profiler is best-effort
        log.warning("prof: jax.profiler unavailable (%s: %s); capture "
                    "carries the snapshot only", type(e).__name__, e)
        return None


def capture(duration_ms: float | None = None, *, reason: str = "manual",
            body=None) -> dict:
    """One serialized, rate-limited deep capture into an atomic artifact
    dir. ``duration_ms`` holds the jax profiler open that long (clamped
    to ``OTPU_PROF_MAX_MS``) — the serving shape, capturing whatever the
    process runs meanwhile; ``body`` (a callable) is traced instead when
    given (the tool shape). Returns ``{"path", "reason", "duration_ms",
    "snapshot"}``."""
    if not prof_enabled():
        raise CaptureDisabledError(
            "deep capture disabled (OTPU_PROF=0)")
    with _capture_session():
        max_ms = float(knobs.get_float("OTPU_PROF_MAX_MS"))
        if duration_ms is not None:
            duration_ms = min(max(float(duration_ms), 0.0), max_ms)
        directory = knobs.get_str("OTPU_PROF_DIR")
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:48]
        final = os.path.join(directory,
                             f"capture-{time.time_ns()}-{safe}")
        tmp = f"{final}.tmp-{os.getpid()}"
        try:
            os.makedirs(os.path.join(tmp, "jax_trace"), exist_ok=True)
            _trace.instant("profile_capture", reason=reason,
                           duration_ms=duration_ms)
            traced_err = None
            ctx = _jax_trace(os.path.join(tmp, "jax_trace"))
            try:
                if ctx is not None:
                    ctx.__enter__()
                try:
                    if body is not None:
                        body()
                    elif duration_ms:
                        time.sleep(duration_ms / 1e3)
                finally:
                    if ctx is not None:
                        ctx.__exit__(None, None, None)
            except Exception as e:  # noqa: BLE001 - snapshot still lands
                traced_err = f"{type(e).__name__}: {e}"
            snap = capture_snapshot(reason, duration_ms)
            if traced_err:
                snap["jax_trace_error"] = traced_err
            with open(os.path.join(tmp, "snapshot.json"), "w") as f:
                json.dump(snap, f, default=str)
            os.rename(tmp, final)   # atomic publish: never a torn capture
        except BaseException:
            # a failed write must leave no .tmp litter retention never
            # prunes; the session hands the rate slot back
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return {"path": final, "reason": reason,
                "duration_ms": duration_ms, "snapshot": snap}


def _merge_move(src: str, dst: str) -> None:
    """Move a completed capture tree into place: plain rename when the
    destination is fresh; merge dirs recursively otherwise (files
    overwrite via ``os.replace`` — e.g. a repeat run's snapshot.json)."""
    if not os.path.exists(dst):
        os.rename(src, dst)
        return
    if os.path.isdir(src) and os.path.isdir(dst):
        for name in os.listdir(src):
            _merge_move(os.path.join(src, name), os.path.join(dst, name))
        os.rmdir(src)
    else:
        os.replace(src, dst)


@contextlib.contextmanager
def trace_capture(log_dir: str):
    """The ``utils.profiling.profile_trace`` back end: the same
    serialized + rate-limited capture machinery, writing into the
    CALLER's directory atomically (trace into a ``.tmp`` sibling,
    rename/merge on exit) and dropping a ``snapshot.json`` beside the
    profile. Under ``OTPU_PROF=0`` this is a bare ``jax.profiler.trace``
    — the pre-prof behavior, bitwise."""
    import jax

    if not prof_enabled():
        with jax.profiler.trace(log_dir):
            yield
        return
    body_err: BaseException | None = None
    with _capture_session():
        tmp = f"{log_dir.rstrip(os.sep)}.tmp-{os.getpid()}"
        try:
            os.makedirs(tmp, exist_ok=True)
            _trace.instant("profile_capture", reason="profile_trace")
            try:
                with jax.profiler.trace(tmp):
                    yield
            except BaseException as e:  # noqa: BLE001 - re-raised below
                # the profiler's __exit__ already stopped and wrote the
                # trace — a failing body is the capture you MOST want a
                # profile of, so PUBLISH the artifact (error noted in
                # the snapshot), then re-raise the body's exception
                # AFTER the session closed clean (outcome stays ok)
                body_err = e
            snap = capture_snapshot("profile_trace")
            if body_err is not None:
                snap["body_error"] = (f"{type(body_err).__name__}: "
                                      f"{body_err}")
            with open(os.path.join(tmp, "snapshot.json"), "w") as f:
                json.dump(snap, f, default=str)
            # publish: one rename when the caller's dir is fresh;
            # repeat runs into the SAME dir merge recursively (jax
            # nests plugins/profile/<ts>/ — a flat child replace would
            # ENOTEMPTY on the shared plugins/ level). Either way
            # nothing lands until the capture finished.
            _merge_move(tmp, log_dir)
        except BaseException:
            # the CAPTURE itself failed (profiler refused, full disk,
            # unmovable dir): no artifact landed — leave no .tmp
            # litter; the session hands the rate slot back
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
            raise
    if body_err is not None:
        raise body_err
