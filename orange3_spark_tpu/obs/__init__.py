"""Unified observability subsystem (docs/observability.md).

Four pieces, one kill-switch (``OTPU_OBS=0``):

* ``registry``  — typed thread-safe metrics (counters/gauges/histograms,
  labels, JSON snapshot, Prometheus text exposition). Always live: the
  legacy ``utils.profiling`` counter shims are views over it.
* ``trace``     — low-overhead structured spans (lock-free ring buffer,
  Chrome trace-event export, ``jax.profiler`` alignment). No-ops under
  the kill-switch.
* ``report``    — per-run structured reports (``model.run_report_``,
  ``ServingContext.report()``).
* ``server``    — opt-in stdlib ``/metrics`` + ``/healthz`` endpoint on
  serving processes (``OTPU_OBS_PORT``). Never binds under the
  kill-switch.
"""

from orange3_spark_tpu.obs.registry import (  # noqa: F401
    REGISTRY, Counter, Gauge, Histogram, MetricsRegistry, get_registry,
)
from orange3_spark_tpu.obs.report import RunReport  # noqa: F401
from orange3_spark_tpu.obs.server import (  # noqa: F401
    TelemetryServer, maybe_start_from_env,
)
from orange3_spark_tpu.obs.trace import (  # noqa: F401
    export_chrome_trace, instant, span, span_iter, validate_chrome_trace,
)
from orange3_spark_tpu.obs import trace  # noqa: F401


def obs_enabled() -> bool:
    """The master switch (``OTPU_OBS``): spans/endpoint on or off. The
    registry and the legacy counter shims stay live either way."""
    return trace.enabled()
