"""Unified observability subsystem (docs/observability.md).

Six pieces, one kill-switch (``OTPU_OBS=0``):

* ``registry``  — typed thread-safe metrics (counters/gauges/histograms,
  labels, JSON snapshot, Prometheus text exposition). Always live: the
  legacy ``utils.profiling`` counter shims are views over it.
* ``trace``     — low-overhead structured spans (lock-free ring buffer,
  trace/span/parent ids, Chrome trace-event + flow-event export,
  ``jax.profiler`` alignment). No-ops under the kill-switch.
* ``context``   — Dapper-style trace-context propagation: per-request
  trace ids minted at the serving entry, per-fit run ids at fit entry,
  carried via contextvars with tail-biased retention
  (``OTPU_TRACE_SAMPLE``).
* ``flight``    — anomaly flight recorder: a rate-limited ``dump()``
  writing a versioned JSON black-box bundle (spans, breaker states,
  queue depths, knobs, all-thread stacks), fired automatically at the
  typed-anomaly raise sites (``OTPU_FLIGHT=0`` disables).
* ``report``    — per-run structured reports (``model.run_report_``,
  ``ServingContext.report()``), linking into the trace ring via the
  top-k slowest trace trees.
* ``server``    — opt-in stdlib ``/metrics`` + ``/healthz`` +
  ``/debug/flight`` + ``/debug/stacks`` endpoint on serving processes
  (``OTPU_OBS_PORT``). Never binds under the kill-switch.
* ``fleetobs``  — the fleet telemetry plane (its own kill-switch,
  ``OTPU_FLEETOBS``): router-side /metrics aggregation over every
  replica's scrape, cross-process trace assembly, the SLO burn-rate
  engine, fleet incident bundles and the FleetDigest load-signal
  snapshot (docs/observability.md §fleet telemetry).
* ``prof``      — the goodput & memory attribution plane (its own
  kill-switch, ``OTPU_PROF``): five-way step-time decomposition with
  per-epoch bottleneck classification, the named device-memory ledger
  (``otpu_device_bytes{owner=}``), and on-demand deep-profile capture
  (``POST /debug/profile``) — docs/observability.md §goodput.
"""

from orange3_spark_tpu.obs.registry import (  # noqa: F401
    REGISTRY, Counter, Gauge, Histogram, MetricsRegistry, get_registry,
)
from orange3_spark_tpu.obs.report import RunReport  # noqa: F401
from orange3_spark_tpu.obs.server import (  # noqa: F401
    TelemetryServer, maybe_start_from_env,
)
from orange3_spark_tpu.obs.trace import (  # noqa: F401
    export_chrome_trace, instant, span, span_iter, validate_chrome_trace,
)
from orange3_spark_tpu.obs import context, flight, trace  # noqa: F401
from orange3_spark_tpu.obs.context import (  # noqa: F401
    current_trace_id, trace_scope,
)


def obs_enabled() -> bool:
    """The master switch (``OTPU_OBS``): spans/endpoint on or off. The
    registry and the legacy counter shims stay live either way."""
    return trace.enabled()
