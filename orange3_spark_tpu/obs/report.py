"""Per-run structured reports — one object instead of scattered plumbing.

Before this subsystem, answering "where did this fit's time go" meant
threading a ``stage_times=`` dict through the estimator, diffing three
process-global counter dicts around the call yourself, and knowing which
keys each PR happened to add. A ``RunReport`` does the bracketing once:

* created at run entry, it snapshots the process counters;
* the run's stage timings / resolved decisions land in ``stage_times``
  (the estimators keep accepting a caller ``stage_times=`` dict — it gets
  the same keys, so no bench/test call site changed);
* ``finish()`` freezes the wall clock and the COUNTER DELTAS attributable
  to this run (dispatches, prefetch overlap, cache economics, retries,
  faults, compiles);
* the result rides the artifact: ``model.run_report_`` on every fitted
  model, ``ctx.report()`` on a ServingContext — JSON-dumpable via
  ``to_json()``.

Deltas are per-RUN attribution only insofar as runs don't overlap: two
concurrent fits in one process both see the shared counters move (the
registry is process-global by design — same caveat the legacy dicts had).
"""

from __future__ import annotations

import json
import time

__all__ = ["REPORT_SCHEMA_VERSION", "RunReport", "counter_families"]

#: bumped when the report dict gains/changes sections. 2 = the goodput
#: (step-time decomposition) and device_memory (ledger) sections from
#: obs/prof.py — both ABSENT (not null) under OTPU_PROF=0, so a
#: schema-1 consumer reading a kill-switched process sees the schema-1
#: keys plus only this version marker (emitted unconditionally — a
#: versioned dict must always say which version it is).
REPORT_SCHEMA_VERSION = 2

#: derived ratio fields recomputed by the shims — meaningless to delta
_DERIVED = {"overlap_pct", "pad_overhead", "mb_merge_factor"}


def counter_families() -> dict:
    """Current {family: counters} view of the three legacy shim families
    plus the compile counter."""
    from orange3_spark_tpu.utils.profiling import (
        exec_counters, resilience_counters, serve_counters,
        xla_compile_count,
    )

    return {
        "exec": exec_counters(),
        "serve": serve_counters(),
        "resilience": resilience_counters(),
        "xla_compiles": xla_compile_count(),
    }


def _delta(before, after):
    if isinstance(after, dict):
        out = {}
        for k, v in after.items():
            if k in _DERIVED:
                out[k] = v          # end-state ratio, not a difference
                continue
            d = _delta((before or {}).get(k), v)
            if d or not isinstance(d, dict):
                out[k] = d
        return out
    if isinstance(after, (int, float)) and isinstance(
            before, (int, float)):
        d = after - before
        return round(d, 9) if isinstance(d, float) else d
    return after


class RunReport:
    """See module docstring. ``kind`` names the run ("fit_stream",
    "serving", ...); free-form ``meta`` identifies the subject."""

    def __init__(self, kind: str, **meta):
        self.kind = kind
        self.meta = dict(meta)
        self.stage_times: dict = {}
        self.started_at = time.time()
        self._t0 = time.perf_counter()
        self._t0_ns = time.perf_counter_ns()
        self._c0 = counter_families()
        self.wall_s: float | None = None
        self.counters: dict | None = None
        self.slow_traces: list | None = None
        # obs/prof.py sections (attach_fit_report): the wall-time
        # decomposition and the device-memory ledger view at fit end
        self.goodput: dict | None = None
        self.device_memory: dict | None = None

    def _slow_traces(self) -> list:
        """Top-k slowest trace trees among spans recorded since this run
        started — the report's link into the trace ring (a report names
        the trace ids an operator can pull from the exported Chrome
        trace or a flight bundle)."""
        from orange3_spark_tpu.obs.trace import slowest_traces

        return slowest_traces(5, since_ns=self._t0_ns)

    def add(self, **fields) -> "RunReport":
        """Merge run-level facts (resolved decisions, warmup info)."""
        self.meta.update(fields)
        return self

    def finish(self) -> "RunReport":
        """Freeze the wall clock, counter deltas and the slow-trace view
        (idempotent: the first call wins, so a fit's report isn't
        re-bracketed by its caller)."""
        if self.wall_s is None:
            self.wall_s = round(time.perf_counter() - self._t0, 6)
            self.counters = _delta(self._c0, counter_families())
            self.slow_traces = self._slow_traces()
        return self

    def to_dict(self) -> dict:
        """Current view — a finished report's frozen numbers, a live one's
        deltas-so-far (``ctx.report()`` polls a long-lived context)."""
        if self.wall_s is not None:
            wall, counters = self.wall_s, self.counters
            slow = self.slow_traces if self.slow_traces is not None else []
        else:
            wall = round(time.perf_counter() - self._t0, 6)
            counters = _delta(self._c0, counter_families())
            slow = self._slow_traces()
        out = {
            "report_schema": REPORT_SCHEMA_VERSION,
            "kind": self.kind,
            "meta": dict(self.meta),
            "started_at": self.started_at,
            "wall_s": wall,
            "stage_times": dict(self.stage_times),
            "counters": counters,
            "slow_traces": slow,
        }
        if self.goodput is not None:
            out["goodput"] = self.goodput
        if self.device_memory is not None:
            out["device_memory"] = self.device_memory
        return out

    def to_json(self, path: str | None = None, **dump_kw) -> str:
        text = json.dumps(self.to_dict(), default=str, **dump_kw)
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        state = "finished" if self.wall_s is not None else "live"
        return f"RunReport({self.kind!r}, {state}, meta={self.meta!r})"
