"""Fleet telemetry plane — the observability layer ABOVE the process.

PR 10 turned one serving process into a supervised multi-replica fleet,
but every telemetry surface stopped at the process boundary: each
replica serves its own ``/metrics``/``/healthz``, keeps its own span
ring and writes its own flight bundles, so an operator of the paper's
production shape (one TPU backend, many users' canvases) has N disjoint
views and no fleet-level SLO. This module is the missing plane:

* :class:`FleetCollector` — scrapes every replica's ``/metrics`` (the
  fleet RPC port already serves it) on a deterministically-jittered
  cadence (``OTPU_FLEETOBS_SCRAPE_S``) into a router-side sample store;
  the fleet exposition re-exports every series with a ``replica=`` label
  plus computed aggregates (counters summed, gauges min/max'd,
  histograms bucket-merged) under ``replica="_fleet"`` — one valid
  Prometheus body for the whole fleet. A replica whose last successful
  scrape is older than ``OTPU_FLEETOBS_STALE_X`` scrape periods gets
  every series ``stale="1"``-flagged instead of silently frozen, and
  counts into the ``otpu_fleetobs_stale_replicas`` gauge. ``/fleetz``
  (obs/server.py, when a collector is attached) serves the JSON view.
* **Cross-process trace assembly** — replicas expose their span ring via
  ``GET /debug/spans?trace_id=`` (fleet/rpc.py); :func:`assemble_trace`
  stitches router- and replica-side spans (ids already propagate via the
  ``X-OTPU-Trace`` header) into ONE Chrome trace. Ring timestamps are
  process-local ``perf_counter_ns`` values, so every spans payload
  carries a wall/perf clock anchor and the assembler rebases onto the
  shared wall clock; each process keeps its own ``pid`` lane, and a
  synthesized ``xproc`` flow event links the router's ``serve`` span to
  the replica's dispatch across the process boundary.
* :class:`SLOEngine` — declarative specs (``OTPU_SLO_SPEC``: availability
  %, p99 latency bound) evaluated over sliding per-second windows with
  the SRE-workbook multi-window burn-rate rule: alert when the error
  budget burns ≥ threshold× in BOTH a long window and its 1/12 confirm
  window (fast rule = page, slow rule = ticket). Alerts are typed
  (:class:`SLOAlert`), land as ``slo_burn`` obs instants, tick
  ``otpu_slo_burn_total{slo=,rule=}`` / set
  ``otpu_slo_budget_remaining{slo=}``, can feed the rollout canary
  breaker (``Rollout(slo_engine=...)``) and trigger the fleet incident
  recorder.
* **Fleet incident bundles** — on an SLO alert (or any caller-named
  anomaly) :func:`auto_fleet_dump` pulls every live replica's
  ``/debug/flight`` plus the router's own bundle into one versioned
  ``fleet-*.json`` bundle (``fleet_flight_schema``), rate-limited like
  the single-process recorder and written through the same atomic
  tmp+rename path (obs/flight.py).
* :class:`FleetDigest` — the load-signal snapshot ROADMAP item 3's
  autoscaler needs (per-replica queue depth, shed rate, in-flight,
  brownout level, plus the router's EWMA-p95), built each scrape and
  published on the supervisor hook (``ReplicaManager.publish_digest``)
  and any registered callback; ``tools/fleet_top.py`` renders it live.

Kill-switch: ``OTPU_FLEETOBS=0`` restores the PR-10 fleet exactly — the
collector refuses to start, the router records no serve span and feeds
no SLO sample, and no fleet bundle is ever written.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import re
import threading
import time
import zlib

from orange3_spark_tpu.obs.registry import (
    REGISTRY, _fmt_value, _label_str,
)
from orange3_spark_tpu.utils import knobs

__all__ = [
    "FLEET_FLIGHT_SCHEMA_VERSION",
    "FleetCollector",
    "FleetDigest",
    "ReplicaLoad",
    "SLOAlert",
    "SLOEngine",
    "SLOSpec",
    "assemble_trace",
    "auto_fleet_dump",
    "collect_fleet_bundle",
    "fleetobs_enabled",
    "parse_prometheus",
    "parse_slo_spec",
]

FLEET_FLIGHT_SCHEMA_VERSION = 1
FLEETZ_SCHEMA_VERSION = 1

_M_SCRAPES = REGISTRY.counter(
    "otpu_fleetobs_scrapes_total",
    "fleet collector /metrics scrapes, by replica and outcome")
_M_STALE = REGISTRY.gauge(
    "otpu_fleetobs_stale_replicas",
    "replicas whose last successful scrape is older than the staleness "
    "budget (their fleet series are stale-flagged)")
_M_BURN = REGISTRY.counter(
    "otpu_slo_burn_total",
    "SLO burn-rate alerts fired, by slo and rule (fast=page, slow=ticket)")
_M_BUDGET = REGISTRY.gauge(
    "otpu_slo_budget_remaining",
    "fraction of the slow-window error budget left, per slo (1 = clean)")


def fleetobs_enabled() -> bool:
    """The fleet-telemetry kill-switch (read per call, the OTPU_DONATE
    convention): ``OTPU_FLEETOBS=0`` restores the plain PR-10 fleet."""
    return knobs.get_bool("OTPU_FLEETOBS")


# ===================================================== prometheus parsing
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(.*)\})?'
    r'\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape(s: str) -> str:
    # ONE left-to-right scan: sequential str.replace would let the 'n'
    # after a literal backslash ('C:\\new' escaped as 'C:\\\\new') be
    # misread as a \n escape and corrupt the label value
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), s)


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    return float(s)


def parse_prometheus(text: str) -> dict:
    """Parse text exposition format 0.0.4 (what ``to_prometheus`` on the
    other side of the scrape emits) into::

        {name: {"type": kind, "values": {label_key: float}}}          # or
        {name: {"type": "histogram",
                "values": {label_key: {"bounds": [...], "cum": [...],
                                       "sum": f, "count": n}}}}

    ``label_key`` is the registry's sorted ``((name, value), ...)`` tuple
    convention, so scraped samples and local registry snapshots compare
    directly. Histogram ``cum`` keeps the exposition's CUMULATIVE bucket
    counts (summing cumulative arrays across replicas stays cumulative —
    the merge the fleet aggregate needs)."""
    types: dict[str, str] = {}
    out: dict[str, dict] = {}
    hist_parts: dict[str, dict] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labels_s, value_s = m.group(1), m.group(2), m.group(3)
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(labels_s or "")}
        try:
            value = _parse_value(value_s)
        except ValueError:
            continue
        # histogram children ride as <base>_bucket/_sum/_count
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and types.get(name[:-len(suffix)]) \
                    == "histogram":
                base = name[:-len(suffix)]
                break
        if base is not None:
            le = labels.pop("le", None)
            key = tuple(sorted(labels.items()))
            h = hist_parts.setdefault(base, {}).setdefault(
                key, {"buckets": [], "sum": 0.0, "count": 0})
            if name.endswith("_bucket") and le is not None:
                h["buckets"].append((_parse_value(le), value))
            elif name.endswith("_sum"):
                h["sum"] = value
            else:
                h["count"] = int(value)
            continue
        key = tuple(sorted(labels.items()))
        metric = out.setdefault(
            name, {"type": types.get(name, "untyped"), "values": {}})
        metric["values"][key] = value
    for base, children in hist_parts.items():
        values = {}
        for key, h in children.items():
            bs = sorted(h["buckets"])
            values[key] = {
                "bounds": [b for b, _ in bs],
                "cum": [int(c) for _, c in bs],
                "sum": h["sum"], "count": h["count"],
            }
        out[base] = {"type": "histogram", "values": values}
    return out


# ============================================================= SLO engine
@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective: ``target`` is the good-request fraction
    (0..1); ``p99_ms`` switches the kind to latency — a completed request
    slower than the bound burns budget like a failure does."""

    name: str
    target: float                      # good fraction, e.g. 0.999
    p99_ms: float | None = None        # latency bound; None = availability

    @property
    def kind(self) -> str:
        return "latency" if self.p99_ms is not None else "availability"

    def good(self, ok: bool, latency_s: float | None) -> bool:
        if not ok:
            return False
        if self.p99_ms is not None:
            return latency_s is not None and latency_s * 1e3 <= self.p99_ms

        return True


def parse_slo_spec(spec: str) -> list[SLOSpec]:
    """``OTPU_SLO_SPEC`` grammar: ``;``-separated items, each
    ``name:key=val[,key=val...]`` with ``target=`` the good-percent
    (required) and ``p99_ms=`` the optional latency bound. Malformed
    items raise naming the item — an operator typo must fail loudly at
    engine construction, not silently drop an objective."""
    specs: list[SLOSpec] = []
    for item in (spec or "").split(";"):
        item = item.strip()
        if not item:
            continue
        name, sep, params = item.partition(":")
        name = name.strip()
        if not sep or not name:
            raise ValueError(f"SLO spec item {item!r}: want "
                             "'name:target=99.9[,p99_ms=250]'")
        target = None
        p99_ms = None
        for kv in params.split(","):
            k, sep2, v = kv.partition("=")
            k = k.strip()
            if not sep2:
                raise ValueError(f"SLO spec {name!r}: bad param {kv!r}")
            try:
                fv = float(v)
            except ValueError:
                raise ValueError(
                    f"SLO spec {name!r}: {k}={v!r} is not a number"
                ) from None
            if k == "target":
                if not 0.0 < fv <= 100.0:
                    raise ValueError(
                        f"SLO spec {name!r}: target must be in (0, 100]")
                target = fv / 100.0
            elif k == "p99_ms":
                p99_ms = fv
            else:
                raise ValueError(f"SLO spec {name!r}: unknown param {k!r} "
                                 "(want target= or p99_ms=)")
        if target is None:
            raise ValueError(f"SLO spec {name!r}: target= is required")
        specs.append(SLOSpec(name, target, p99_ms))
    return specs


@dataclasses.dataclass(frozen=True)
class SLOAlert:
    """One burn-rate alert (the typed event): which objective, which
    rule (``fast`` = page, ``slow`` = ticket), the long/confirm-window
    burn rates that tripped it, and the budget left."""

    slo: str
    rule: str
    burn_long: float
    burn_short: float
    window_s: float
    budget_remaining: float
    at_wall: float


class SLOEngine:
    """Sliding-window multi-burn-rate evaluation over a shared request
    feed. ``record(ok, latency_s)`` is the one ingest point (the fleet
    router calls it per predict); per-second buckets hold (total, bad
    per spec) so a week-long window costs O(window) ints, not O(events).

    Burn rate over a window = (bad / total) / (1 - target): how many
    times faster than uniform the error budget is burning. A rule fires
    when burn ≥ threshold in BOTH its long window and the 1/12 confirm
    window (fast detection without single-blip pages — the Google SRE
    workbook shape). Alerts fire on the RISING edge per (slo, rule) and
    re-arm once both windows drop back under."""

    def __init__(self, specs: list[SLOSpec] | None = None, *,
                 fast_s: float | None = None, slow_s: float | None = None,
                 burn_fast: float | None = None,
                 burn_slow: float | None = None,
                 clock=time.monotonic):
        self.specs = list(specs) if specs is not None else parse_slo_spec(
            knobs.get_str("OTPU_SLO_SPEC"))
        self.fast_s = float(fast_s if fast_s is not None
                            else knobs.get_float("OTPU_SLO_WINDOW_FAST_S"))
        self.slow_s = float(slow_s if slow_s is not None
                            else knobs.get_float("OTPU_SLO_WINDOW_SLOW_S"))
        self.burn_fast = float(
            burn_fast if burn_fast is not None
            else knobs.get_float("OTPU_SLO_BURN_FAST"))
        self.burn_slow = float(
            burn_slow if burn_slow is not None
            else knobs.get_float("OTPU_SLO_BURN_SLOW"))
        self.clock = clock
        self._lock = threading.Lock()
        self._buckets: dict[int, dict] = {}
        self._active: set[tuple[str, str]] = set()
        self._cbs: list = []
        self.alerts: list[SLOAlert] = []
        self.last_verdicts: list[dict] = []
        self._last_eval = -math.inf

    # ------------------------------------------------------------- ingest
    def on_alert(self, cb) -> None:
        """Register a rising-edge alert callback (the collector wires the
        fleet incident dump here; a rollout wires its canary breaker)."""
        self._cbs.append(cb)

    def record(self, ok: bool, latency_s: float | None = None) -> None:
        now = self.clock()
        sec = int(now)
        with self._lock:
            b = self._buckets.get(sec)
            if b is None:
                b = self._buckets[sec] = {
                    "total": 0, "bad": {s.name: 0 for s in self.specs}}
            b["total"] += 1
            for s in self.specs:
                if not s.good(ok, latency_s):
                    b["bad"][s.name] += 1
            due = now - self._last_eval >= max(
                min(1.0, self.fast_s / 12.0), 0.05)
        if due:
            self.evaluate()

    # --------------------------------------------------------- evaluation
    def _counts(self, name: str, window_s: float, now: float):
        lo = now - window_s
        bad = total = 0
        for sec, b in self._buckets.items():
            if lo < sec <= now:
                total += b["total"]
                bad += b["bad"].get(name, 0)
        return bad, total

    @staticmethod
    def _burn(bad: int, total: int, budget: float) -> float:
        if total == 0:
            return 0.0
        ratio = bad / total
        if budget <= 0.0:
            return math.inf if bad else 0.0
        return ratio / budget

    def evaluate(self) -> list[dict]:
        """One evaluation pass: per-spec verdict dicts (burn rates,
        budget, which rules are alerting), metric updates, and rising-
        edge alert dispatch. Returns (and stores) the verdicts."""
        from orange3_spark_tpu.obs import trace

        now = self.clock()
        fired: list[SLOAlert] = []
        verdicts: list[dict] = []
        with self._lock:
            self._last_eval = now
            # prune past the slow window (+slack for the confirm reads)
            horizon = now - self.slow_s * 1.25 - 2
            for sec in [s for s in self._buckets if s < horizon]:
                del self._buckets[sec]
            for spec in self.specs:
                budget = 1.0 - spec.target
                rules = {}
                for rule, window_s, thresh in (
                        ("fast", self.fast_s, self.burn_fast),
                        ("slow", self.slow_s, self.burn_slow)):
                    short_s = max(window_s / 12.0, 1.0)
                    bl, tl = self._counts(spec.name, window_s, now)
                    bs, ts = self._counts(spec.name, short_s, now)
                    burn_long = self._burn(bl, tl, budget)
                    burn_short = self._burn(bs, ts, budget)
                    alerting = (burn_long >= thresh
                                and burn_short >= thresh)
                    rules[rule] = {
                        "window_s": window_s, "threshold": thresh,
                        "burn_long": burn_long, "burn_short": burn_short,
                        "alerting": alerting,
                    }
                bad_slow, total_slow = self._counts(
                    spec.name, self.slow_s, now)
                allowed = total_slow * budget
                if allowed > 0:
                    remaining = max(0.0, min(1.0, 1.0 - bad_slow / allowed))
                else:
                    remaining = 1.0 if bad_slow == 0 else 0.0
                verdicts.append({
                    "slo": spec.name, "kind": spec.kind,
                    "target": spec.target, "p99_ms": spec.p99_ms,
                    "rules": rules,
                    "budget_remaining": round(remaining, 6),
                    "window_events": total_slow,
                    "window_bad": bad_slow,
                    "alerting": any(r["alerting"] for r in rules.values()),
                })
                _M_BUDGET.set(remaining, slo=spec.name)
                for rule, r in rules.items():
                    key = (spec.name, rule)
                    if r["alerting"] and key not in self._active:
                        self._active.add(key)
                        _M_BURN.inc(1, slo=spec.name, rule=rule)
                        alert = SLOAlert(
                            slo=spec.name, rule=rule,
                            burn_long=r["burn_long"],
                            burn_short=r["burn_short"],
                            window_s=r["window_s"],
                            budget_remaining=remaining,
                            at_wall=time.time())
                        self.alerts.append(alert)
                        fired.append(alert)
                    elif not r["alerting"] and key in self._active:
                        self._active.discard(key)
            self.last_verdicts = verdicts
        for alert in fired:
            trace.instant("slo_burn", slo=alert.slo, rule=alert.rule,
                          burn=round(alert.burn_long, 3),
                          budget_remaining=round(
                              alert.budget_remaining, 4))
            for cb in list(self._cbs):
                try:
                    cb(alert)
                except Exception:  # noqa: BLE001 - alerting must not die
                    pass
        return verdicts

    def active_alerts(self) -> set[tuple[str, str]]:
        with self._lock:
            return set(self._active)


# ======================================================= fleet incident
_fleet_rate_lock = threading.Lock()
_last_fleet_dump = 0.0


def collect_fleet_bundle(reason: str, clients,
                         error: BaseException | None = None, *,
                         digest: dict | None = None,
                         slo: list | None = None, **extra) -> dict:
    """Assemble one fleet incident bundle: the router's OWN flight
    bundle plus every live replica's ``/debug/flight`` pull (a dead
    replica contributes its transport error, not silence). ``clients``
    is ``[(name, client), ...]`` (the collector's normalized list)."""
    import os

    from orange3_spark_tpu.obs import flight

    replicas: dict[str, dict] = {}
    for name, client in clients:
        try:
            status, body = client.get_json("/debug/flight", timeout_s=10.0)
            # liveness = a schema-complete bundle came back; a replica
            # bundle carries its OWN "error" field (None on a manual
            # pull), so presence of that key is NOT a failed pull
            replicas[name] = (body if status == 200
                              and "flight_schema" in (body or {})
                              else {"pull_error": f"http_{status}"})
        except Exception as e:  # noqa: BLE001 - a dead replica is data
            replicas[name] = {"pull_error": f"{type(e).__name__}: {e}"}
    bundle = {
        "fleet_flight_schema": FLEET_FLIGHT_SCHEMA_VERSION,
        "written_at": time.time(),
        "pid": os.getpid(),
        "reason": reason,
        "error": ({"type": type(error).__name__, "message": str(error)}
                  if error is not None else None),
        "router": flight.collect_bundle(reason, error),
        "replicas": replicas,
        "live_replicas": sorted(n for n, b in replicas.items()
                                if "flight_schema" in b),
        "digest": digest,
        "slo": slo,
    }
    if extra:
        bundle["extra"] = extra
    return bundle


def auto_fleet_dump(reason: str, clients,
                    error: BaseException | None = None,
                    **kw) -> str | None:
    """Rate-limited fleet incident dump (the SLO-alert hook): never
    raises, shares ``OTPU_FLIGHT_RATE_S`` with the single-process
    recorder but keeps its OWN slot (a replica-local shed bundle must
    not silence the fleet-wide incident view, and vice versa). Writes a
    ``fleet-*.json`` bundle through obs/flight.py's atomic path."""
    global _last_fleet_dump
    try:
        from orange3_spark_tpu.obs import flight

        if not fleetobs_enabled() or not flight.flight_enabled():
            return None
        min_gap = float(knobs.get_float("OTPU_FLIGHT_RATE_S"))
        now = time.monotonic()
        with _fleet_rate_lock:
            if _last_fleet_dump and now - _last_fleet_dump < min_gap:
                return None
            prev, _last_fleet_dump = _last_fleet_dump, now
        try:
            bundle = collect_fleet_bundle(reason, clients, error, **kw)
            return flight.dump(reason, error, bundle=bundle,
                               prefix="fleet")
        except Exception:  # noqa: BLE001 - best-effort evidence
            with _fleet_rate_lock:
                if _last_fleet_dump == now:
                    _last_fleet_dump = prev
            return None
    except Exception:  # noqa: BLE001 - never raise from an alert path
        return None


def reset_fleet_rate_limit() -> None:
    """Tests/bench: forget the last automatic fleet dump time."""
    global _last_fleet_dump
    with _fleet_rate_lock:
        _last_fleet_dump = 0.0


# ====================================================== trace assembly
def assemble_trace(trace_id: str, sources: list[tuple[str, dict]]) -> dict:
    """Stitch per-process spans payloads (``trace.spans_payload`` shape)
    into ONE Chrome trace object for ``trace_id``. Each source keeps its
    own ``pid`` lane (named via process_name metadata); timestamps are
    rebased onto the wall clock through each payload's anchor, so router
    and replica spans line up on one axis; a synthesized ``xproc`` flow
    event (``s`` in the router's ``serve`` span, ``f`` in the replica's
    dispatch) draws the cross-process arrow Perfetto renders. The result
    passes :func:`~orange3_spark_tpu.obs.trace.validate_chrome_trace`."""
    trace_events: list[dict] = []
    # per-source best flow anchor: (is_router, pref, pid, tid, ts, dur)
    anchors: dict[str, dict] = {}
    for sname, payload in sources:
        pid = int(payload["pid"])
        anchor = payload["anchor"]
        off_ns = int(anchor["wall_ns"]) - int(anchor["perf_ns"])
        tid_map: dict[int, int] = {}
        for ev in payload["events"]:
            ph, name, t0_ns, dur_ns, ident, args, tid_, sid, par = ev
            if tid_ != trace_id:
                continue
            tid = tid_map.setdefault(ident, len(tid_map))
            ts_us = (int(t0_ns) + off_ns) / 1e3
            d: dict = {"name": name, "ph": ph, "cat": "otpu",
                       "pid": pid, "tid": tid, "ts": ts_us}
            a = dict(args) if args else {}
            if ph == "X":
                d["dur"] = dur_ns / 1e3
            elif ph == "i":
                d["s"] = "t"
            elif ph in ("s", "t", "f"):
                d["id"] = str(a.pop("id", "") or trace_id)
                d["bp"] = "e"
            a["trace_id"] = tid_
            if sid is not None:
                a["span_id"] = sid
            if par is not None:
                a["parent_id"] = par
            a["source"] = sname
            d["args"] = a
            trace_events.append(d)
            if ph == "X" and name in ("serve", "serve_dispatch"):
                best = anchors.get(sname)
                # prefer the innermost dispatch span on the replica side
                pref = 1 if name == "serve_dispatch" else 0
                if best is None or pref >= best["pref"]:
                    anchors[sname] = {
                        "pref": pref, "pid": pid, "tid": tid,
                        "ts": ts_us, "dur": dur_ns / 1e3, "name": name}
        trace_events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": sname}})
    # the cross-process flow link: router serve -> each replica dispatch
    router = anchors.get("router")
    if router is not None:
        for sname, a in anchors.items():
            if sname == "router" or a["pid"] == router["pid"]:
                continue
            mid = min(a["dur"], router["dur"]) / 2.0
            trace_events.append({
                "name": "xproc", "ph": "s", "cat": "otpu",
                "pid": router["pid"], "tid": router["tid"],
                "ts": router["ts"] + min(mid, router["dur"] / 2.0),
                "id": trace_id, "bp": "e",
                "args": {"trace_id": trace_id, "to": sname}})
            trace_events.append({
                "name": "xproc", "ph": "f", "cat": "otpu",
                "pid": a["pid"], "tid": a["tid"],
                "ts": a["ts"] + min(mid, a["dur"] / 2.0),
                "id": trace_id, "bp": "e",
                "args": {"trace_id": trace_id, "from": "router"}})
    trace_events.sort(key=lambda e: (e["ph"] == "M", e.get("ts", 0.0)))
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


# ============================================================== digest
@dataclasses.dataclass
class ReplicaLoad:
    """One replica's load signals as last scraped (None = never seen)."""

    replica: str
    up: bool
    stale: bool
    scrape_age_s: float | None
    inflight: float = 0.0
    queue_depth: float = 0.0
    shed_total: float = 0.0
    brownout_level: float = 0.0
    rpc_requests: float = 0.0
    router_inflight: int | None = None
    # goodput & memory attribution plane (obs/prof.py, scraped off the
    # replica's own gauges): per-stage wall fractions of its last fit
    # (None until one ran) and live device bytes per ledger owner —
    # tools/fleet_top.py renders both, the ROADMAP-3 autoscaler reads
    # device_bytes as the capacity half of its load signal
    goodput: dict | None = None
    device_bytes: dict = dataclasses.field(default_factory=dict)
    # weighted-fair tenancy (serve/tenancy.py): this replica's typed
    # quota sheds, held slots and total grants per tenant — the fleet
    # view of who is over quota (tools/fleet_top.py renders the table)
    tenant_sheds: dict = dataclasses.field(default_factory=dict)
    tenant_inflight: dict = dataclasses.field(default_factory=dict)
    tenant_granted: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FleetDigest:
    """The load-signal surface the ROADMAP-3 autoscaler consumes: one
    snapshot per collector tick, published on the supervisor hook."""

    at_wall: float
    scrape_s: float
    replicas: list[ReplicaLoad]
    ewma_p95_ms: float | None
    slo: list[dict]
    stale_replicas: int
    # data-plane fast path (fleet/fastwire.py): router-side connection
    # pool totals (reuse %), coalescer merge stats and SHM byte counts —
    # None when no router is attached or the fast wire never ran
    wire: dict | None = None
    # control plane (fleet/control.py): the active autoscaler's state()
    # block as of this tick — None when none is attached
    autoscaler: dict | None = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["replicas"] = [r.to_dict() if isinstance(r, ReplicaLoad) else r
                         for r in self.replicas]
        return d


@dataclasses.dataclass
class _Scrape:
    samples: dict
    at: float                 # collector clock of last SUCCESS
    at_wall: float
    scrapes: int = 0
    errors: int = 0
    last_error: str | None = None


def _values_total(parsed: dict, name: str) -> float:
    m = parsed.get(name)
    if not m or m["type"] == "histogram":
        return 0.0
    return float(sum(m["values"].values()))


def _values_by_label(parsed: dict, name: str, label: str) -> dict:
    """{label value: metric value} for one label dimension of one scraped
    metric (the per-owner/per-stage view of the prof-plane gauges)."""
    m = parsed.get(name)
    if not m or m["type"] == "histogram":
        return {}
    out: dict = {}
    for key, v in m["values"].items():
        for k, val in key:
            if k == label:
                out[val] = out.get(val, 0.0) + float(v)
    return out


# =========================================================== collector
#: per-process collector instance numbering: part of each collector's
#: jitter seed, so two collectors over the same endpoints decorrelate
_COLLECTOR_SEQ = itertools.count()


class FleetCollector:
    """See module docstring. ``endpoints`` accepts the supervisor's
    ``(id, host, port)`` tuples, router ``ReplicaEndpoint`` objects
    (their clients are reused) or anything with ``.name`` +
    ``get_text``/``get_json`` (test fakes)."""

    def __init__(self, endpoints, *, router=None, supervisor=None,
                 slo: SLOEngine | None = None,
                 scrape_s: float | None = None,
                 stale_x: float | None = None,
                 clock=time.monotonic):
        from orange3_spark_tpu.fleet.rpc import FleetClient

        self.clients: list[tuple[str, object]] = []
        for ep in endpoints:
            if isinstance(ep, tuple):
                rid, host, port = ep
                name = f"replica-{rid}"
                self.clients.append(
                    (name, FleetClient(host, port, name=name)))
            elif hasattr(ep, "client"):
                self.clients.append((ep.name, ep.client))
            else:
                self.clients.append((ep.name, ep))
        self.router = router
        self.supervisor = supervisor
        self.slo = slo
        self.scrape_s = float(
            scrape_s if scrape_s is not None
            else knobs.get_float("OTPU_FLEETOBS_SCRAPE_S"))
        stale_x = float(stale_x if stale_x is not None
                        else knobs.get_float("OTPU_FLEETOBS_STALE_X"))
        self.stale_after_s = max(self.scrape_s * stale_x, self.scrape_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._scrapes: dict[str, _Scrape] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._ticks = 0
        ident = ("|".join(n for n, _ in self.clients)
                 + f"#{next(_COLLECTOR_SEQ)}")
        self._jitter_seed = zlib.crc32(ident.encode())
        self._digest_cbs: list = []
        self.last_incident_path: str | None = None
        self._incident_threads: list[threading.Thread] = []
        if slo is not None:
            slo.on_alert(self._on_alert)

    # ----------------------------------------------------------- control
    @property
    def active(self) -> bool:
        return self._thread is not None

    def on_digest(self, cb) -> None:
        self._digest_cbs.append(cb)

    def start(self) -> "FleetCollector":
        """Start the scrape loop; a no-op (no thread, no scrapes) under
        ``OTPU_FLEETOBS=0`` — the PR-10 fleet exactly."""
        if not fleetobs_enabled() or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="otpu-fleetobs-scrape")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def __enter__(self) -> "FleetCollector":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 - scraping must never die
                pass
            # deterministic ±10% jitter (the crc32 seeding convention),
            # seeded per collector (endpoint set + instance number): two
            # collectors started together must NOT scrape in lockstep
            frac = zlib.crc32(
                f"fleetobs:{self._jitter_seed}:{self._ticks}".encode()) \
                / 0xFFFFFFFF
            self._stop.wait(self.scrape_s * (0.9 + 0.2 * frac))

    # ----------------------------------------------------------- scraping
    def scrape_once(self) -> FleetDigest:
        """One sweep: pull every replica's /metrics, refresh staleness,
        evaluate the SLO engine, build + publish the digest."""
        now = self.clock()
        for name, client in self.clients:
            try:
                status, text = client.get_text("/metrics", timeout_s=5.0)
                if status != 200:
                    raise RuntimeError(f"/metrics answered HTTP {status}")
                samples = parse_prometheus(text)
                with self._lock:
                    prev = self._scrapes.get(name)
                    self._scrapes[name] = _Scrape(
                        samples, at=now, at_wall=time.time(),
                        scrapes=(prev.scrapes if prev else 0) + 1,
                        errors=prev.errors if prev else 0)
                _M_SCRAPES.inc(1, replica=name, outcome="ok")
            except Exception as e:  # noqa: BLE001 - a dead replica is data
                with self._lock:
                    prev = self._scrapes.get(name)
                    if prev is not None:
                        prev.errors += 1
                        prev.last_error = f"{type(e).__name__}: {e}"
                    else:
                        self._scrapes[name] = _Scrape(
                            {}, at=-math.inf, at_wall=0.0, errors=1,
                            last_error=f"{type(e).__name__}: {e}")
                _M_SCRAPES.inc(1, replica=name, outcome="error")
        _M_STALE.set(len(self.stale_replicas()))
        if self.slo is not None:
            self.slo.evaluate()
        digest = self.digest()
        if self.supervisor is not None:
            try:
                self.supervisor.publish_digest(digest)
            except Exception:  # noqa: BLE001 - the hook is best-effort
                pass
        for cb in list(self._digest_cbs):
            try:
                cb(digest)
            except Exception:  # noqa: BLE001
                pass
        self._ticks += 1
        return digest

    def staleness(self) -> dict[str, float | None]:
        """Per-replica seconds since the last SUCCESSFUL scrape (None =
        never scraped successfully)."""
        now = self.clock()
        out: dict[str, float | None] = {}
        with self._lock:
            for name, _client in self.clients:
                sc = self._scrapes.get(name)
                out[name] = (None if sc is None or sc.at == -math.inf
                             else now - sc.at)
        return out

    def stale_replicas(self) -> list[str]:
        return sorted(n for n, age in self.staleness().items()
                      if age is None or age > self.stale_after_s)

    # --------------------------------------------------------- exposition
    def _sources(self, include_local: bool):
        """(name, parsed, stale, is_local) per source. The ROUTER process
        itself is one more source named ``router`` — so the fleet
        /metrics is one body with one TYPE line per metric, never a
        concatenation of two expositions fighting over the same names —
        but it is NOT a replica: its series ride re-labeled only and
        never fold into the ``_fleet`` aggregates (its registry holds a
        zero for every registered-but-untouched gauge, which would pin
        every ``_fleet`` minimum to 0)."""
        stale = set(self.stale_replicas())
        out = []
        if include_local:
            out.append(("router",
                        parse_prometheus(REGISTRY.to_prometheus()),
                        False, True))
        with self._lock:
            for name, _client in self.clients:
                sc = self._scrapes.get(name)
                if sc is not None and sc.samples:
                    out.append((name, sc.samples, name in stale, False))
        return out

    @staticmethod
    def _tagged(key: tuple, source: str, stale: bool) -> tuple:
        """Add the source label to a child's label key: ``replica=`` by
        convention; ``scraped_from=`` when the child already carries its
        own ``replica`` label (the router's per-replica gauges do)."""
        label = ("scraped_from" if any(k == "replica" for k, _ in key)
                 else "replica")
        tagged = list(key) + [(label, source)]
        if stale:
            tagged.append(("stale", "1"))
        return tuple(sorted(tagged))

    def to_prometheus(self, include_local: bool = True) -> str:
        """The fleet exposition: every source's series re-labeled with
        its replica, plus computed aggregates under ``replica="_fleet"``
        — aggregated over REPLICAS only (the router's own series ride
        re-labeled but never fold in): counters summed (stale replicas'
        last-known counts still count: counters are monotonic), gauges
        min/max over FRESH replicas only (a frozen gauge is not a load
        signal), histograms bucket-merged where bounds agree."""
        sources = self._sources(include_local)
        names: dict[str, str] = {}
        for _sname, parsed, _st, _loc in sources:
            for mname, m in parsed.items():
                names.setdefault(mname, m["type"])
        lines: list[str] = []
        for mname in sorted(names):
            mtype = names[mname]
            lines.append(f"# TYPE {mname} {mtype}")
            agg_counter: dict[tuple, float] = {}
            agg_gauge: dict[tuple, list[float]] = {}
            agg_hist: dict[tuple, dict] = {}
            for sname, parsed, st, local in sources:
                m = parsed.get(mname)
                if m is None or m["type"] != mtype:
                    continue
                for key, v in sorted(m["values"].items()):
                    tkey = self._tagged(key, sname, st)
                    if mtype == "histogram":
                        self._emit_hist(lines, mname, tkey, v)
                        if local:
                            continue
                        h = agg_hist.get(key)
                        if h is None:
                            agg_hist[key] = {
                                "bounds": list(v["bounds"]),
                                "cum": list(v["cum"]),
                                "sum": v["sum"], "count": v["count"]}
                        elif h["bounds"] == v["bounds"]:
                            h["cum"] = [a + b for a, b in
                                        zip(h["cum"], v["cum"])]
                            h["sum"] += v["sum"]
                            h["count"] += v["count"]
                        continue
                    lines.append(
                        f"{mname}{_label_str(tkey)} {_fmt_value(v)}")
                    if local:
                        continue
                    if mtype == "counter":
                        agg_counter[key] = agg_counter.get(key, 0.0) + v
                    elif mtype == "gauge" and not st:
                        agg_gauge.setdefault(key, []).append(v)
            for key, total in sorted(agg_counter.items()):
                fkey = self._tagged(key, "_fleet", False)
                lines.append(
                    f"{mname}{_label_str(fkey)} {_fmt_value(total)}")
            for key, vals in sorted(agg_gauge.items()):
                for agg, v in (("max", max(vals)), ("min", min(vals))):
                    fkey = tuple(sorted(
                        list(self._tagged(key, "_fleet", False))
                        + [("agg", agg)]))
                    lines.append(
                        f"{mname}{_label_str(fkey)} {_fmt_value(v)}")
            for key, h in sorted(agg_hist.items()):
                fkey = self._tagged(key, "_fleet", False)
                self._emit_hist(lines, mname, fkey, h)
        return "\n".join(lines) + "\n"

    @staticmethod
    def _emit_hist(lines: list, base: str, key: tuple, h: dict) -> None:
        for b, cum in zip(h["bounds"], h["cum"]):
            lk = tuple(sorted(list(key) + [("le", _fmt_value(b))]))
            lines.append(f"{base}_bucket{_label_str(lk)} {int(cum)}")
        lines.append(f"{base}_sum{_label_str(key)} "
                     f"{_fmt_value(h['sum'])}")
        lines.append(f"{base}_count{_label_str(key)} {int(h['count'])}")

    def fleetz(self) -> dict:
        """The JSON fleet view (``GET /fleetz`` on the router's obs
        server): per-replica scrape health, counter aggregates, the SLO
        verdicts and the current digest."""
        stale = set(self.stale_replicas())
        replicas: dict[str, dict] = {}
        aggregates: dict[str, float] = {}
        with self._lock:
            for name, _client in self.clients:
                sc = self._scrapes.get(name)
                age = (None if sc is None or sc.at == -math.inf
                       else self.clock() - sc.at)
                replicas[name] = {
                    "up": sc is not None and sc.at != -math.inf,
                    "stale": name in stale,
                    "scrape_age_s": (round(age, 3)
                                     if age is not None else None),
                    "scrapes": sc.scrapes if sc else 0,
                    "errors": sc.errors if sc else 0,
                    "last_error": sc.last_error if sc else None,
                }
                if sc is not None:
                    for mname, m in sc.samples.items():
                        if m["type"] == "counter":
                            aggregates[mname] = (
                                aggregates.get(mname, 0.0)
                                + sum(m["values"].values()))
        digest = self.digest()
        out = {
            "fleetz_schema": FLEETZ_SCHEMA_VERSION,
            "at": time.time(),
            "scrape_s": self.scrape_s,
            "stale_after_s": self.stale_after_s,
            "ticks": self._ticks,
            "replicas": replicas,
            "aggregates": {k: round(v, 6)
                           for k, v in sorted(aggregates.items())},
            "slo": (self.slo.last_verdicts
                    if self.slo is not None else []),
            "digest": digest.to_dict(),
            "last_incident_path": self.last_incident_path,
        }
        # control plane (PR 20): present only when it has state, so a
        # tenant-less fixed-size fleet's body keeps the exact old keys.
        # Per-tenant sheds aggregate the replicas' scraped counters plus
        # THIS process's ledger (the router sheds caller-side too).
        from orange3_spark_tpu.serve.tenancy import tenant_shed_counts

        tenants: dict[str, float] = {}
        for r in digest.replicas:
            for t, v in (r.tenant_sheds or {}).items():
                tenants[t] = tenants.get(t, 0.0) + float(v)
        for t, reasons in tenant_shed_counts().items():
            tenants[t] = tenants.get(t, 0.0) + float(sum(reasons.values()))
        if tenants:
            out["tenants"] = {"sheds": {t: round(v, 6) for t, v
                                        in sorted(tenants.items())}}
        if digest.autoscaler is not None:
            out["autoscaler"] = digest.autoscaler
        return out

    # -------------------------------------------------------------- digest
    def digest(self) -> FleetDigest:
        stale = set(self.stale_replicas())
        router_inflight: dict[str, int] = {}
        ewma_p95_ms = None
        if self.router is not None:
            try:
                for ep in self.router.endpoints:
                    router_inflight[ep.name] = ep.inflight
                ewma_p95_ms = round(
                    self.router.schedule.p_estimate_s() * 1e3, 3)
            except Exception:  # noqa: BLE001 - best-effort signals
                pass
        loads: list[ReplicaLoad] = []
        with self._lock:
            for name, _client in self.clients:
                sc = self._scrapes.get(name)
                up = sc is not None and sc.at != -math.inf
                age = (None if not up else self.clock() - sc.at)
                samples = sc.samples if sc else {}
                goodput = _values_by_label(
                    samples, "otpu_goodput_fraction", "stage")
                loads.append(ReplicaLoad(
                    replica=name, up=up, stale=name in stale,
                    scrape_age_s=(round(age, 3)
                                  if age is not None else None),
                    inflight=_values_total(samples, "otpu_serve_inflight"),
                    queue_depth=_values_total(
                        samples, "otpu_admission_queue_depth"),
                    shed_total=_values_total(samples, "otpu_shed_total"),
                    brownout_level=_values_total(
                        samples, "otpu_brownout_level"),
                    rpc_requests=_values_total(
                        samples, "otpu_fleet_rpc_requests_total"),
                    router_inflight=router_inflight.get(name),
                    goodput=goodput or None,
                    device_bytes=_values_by_label(
                        samples, "otpu_device_bytes", "owner"),
                    tenant_sheds=_values_by_label(
                        samples, "otpu_tenant_sheds_total", "tenant"),
                    tenant_inflight=_values_by_label(
                        samples, "otpu_tenant_inflight", "tenant"),
                    tenant_granted=_values_by_label(
                        samples, "otpu_tenant_granted_total", "tenant"),
                ))
        from orange3_spark_tpu.fleet.control import active_autoscaler_state

        return FleetDigest(
            at_wall=time.time(), scrape_s=self.scrape_s, replicas=loads,
            ewma_p95_ms=ewma_p95_ms,
            slo=(self.slo.last_verdicts if self.slo is not None else []),
            stale_replicas=len(stale),
            wire=self._wire_stats(),
            autoscaler=active_autoscaler_state())

    def _wire_stats(self) -> dict | None:
        """Aggregate the fast-wire signals off the attached router:
        conn-pool reuse across its clients, coalescer merge factor, SHM
        bytes moved (best-effort — absent pieces just drop out)."""
        if self.router is None:
            return None
        wire: dict = {}
        try:
            opened = reused = stale_retries = 0
            for ep in self.router.endpoints:
                pool = getattr(ep.client, "pool", None)
                if pool is None:
                    continue
                s = pool.stats()
                opened += s["opened"]
                reused += s["reused"]
                stale_retries += s["stale_retries"]
            total = opened + reused
            wire["conn"] = {
                "opened": opened, "reused": reused,
                "stale_retries": stale_retries,
                "reuse_pct": round(100.0 * reused / total, 2)
                             if total else 0.0,
            }
        except Exception:  # noqa: BLE001 - best-effort signals
            pass
        try:
            co = getattr(self.router, "coalescer", None)
            if co is not None:
                wire["coalesce"] = co.stats()
        except Exception:  # noqa: BLE001 - best-effort signals
            pass
        try:
            from orange3_spark_tpu.fleet import fastwire

            wire["shm"] = fastwire.shm_stats()
        except Exception:  # noqa: BLE001 - best-effort signals
            pass
        return wire or None

    # ------------------------------------------------------- trace assembly
    def assemble_trace(self, trace_id: str,
                       include_local: bool = True) -> dict:
        """Pull ``/debug/spans?trace_id=`` from every replica, join with
        the router's own ring, return the stitched Chrome trace (see
        :func:`assemble_trace`)."""
        from orange3_spark_tpu.obs import trace

        sources: list[tuple[str, dict]] = []
        if include_local:
            sources.append(("router", trace.spans_payload(trace_id)))
        for name, client in self.clients:
            try:
                status, payload = client.get_json(
                    f"/debug/spans?trace_id={trace_id}", timeout_s=5.0)
            except Exception:  # noqa: BLE001 - a dead replica has no spans
                continue
            if status == 200 and payload.get("events") is not None:
                sources.append((name, payload))
        return assemble_trace(trace_id, sources)

    # -------------------------------------------------------------- alerts
    def _on_alert(self, alert: SLOAlert) -> None:
        """The SLO-alert hook: one rate-limited fleet incident bundle
        carrying every live replica's flight data — collected on a
        DEDICATED thread. Alerts rise inside ``SLOEngine.record``, i.e.
        on a serving caller's thread (the router's predict ``finally``),
        and a bundle pull is seconds of replica HTTP at exactly peak
        overload: blocking the unlucky request on it is the same stall
        the PR-9 shed-dump hardening removed."""
        # prune finished dumps at append time (the PR-9 _OPEN-stack
        # convention): a router alerting for weeks must not accumulate
        # dead Thread objects — nothing on the production path joins
        if len(self._incident_threads) > 8:
            self._incident_threads = [
                x for x in self._incident_threads if x.is_alive()]
        t = threading.Thread(
            target=self._dump_incident, args=(alert,), daemon=True,
            name="otpu-fleetobs-incident")
        self._incident_threads.append(t)
        t.start()

    def _dump_incident(self, alert: SLOAlert) -> None:
        try:
            path = auto_fleet_dump(
                f"slo_{alert.slo}_{alert.rule}", self.clients,
                digest=self.digest().to_dict(),
                slo=(self.slo.last_verdicts
                     if self.slo is not None else []),
                alert=dataclasses.asdict(alert))
            if path is not None:
                self.last_incident_path = path
        except Exception:  # noqa: BLE001 - incident IO must never leak
            pass

    def join_incident_dump(self, timeout_s: float = 15.0) -> None:
        """Block until every in-flight incident dump finishes (tests and
        the bench read ``last_incident_path`` deterministically). ALL
        spawned threads are joined, not just the newest: the rate-limit
        slot belongs to whichever alert arrived first, so the thread
        still writing may well be an older one."""
        deadline = time.monotonic() + timeout_s
        for t in list(self._incident_threads):
            t.join(max(0.0, deadline - time.monotonic()))
        self._incident_threads = [
            t for t in self._incident_threads if t.is_alive()]
