"""Structured span tracing — the host-side timeline a Spark UI would show.

``jax.profiler.trace`` already captures DEVICE time (PAPER §5's
``profile_trace``); what it cannot show is the framework's own structure —
which fit, which epoch, which chunk, which dispatch the host was inside
when the device stalled. This module records that structure as spans:

    with span("epoch", i):          # or: for i in span_iter("epoch", rng)
        ...
    instant("retry", cause="source")   # point events (retries, wedges)

Design constraints, in order:

* **lock-free fast path** — recording a span is one ``perf_counter_ns``
  pair, one atomic-under-the-GIL ``itertools.count`` bump, a push/pop on
  the thread's open-span stack and one list slot store; no lock anywhere
  on the hot path. With ``OTPU_OBS=0`` the ``span()`` call returns a
  shared no-op context manager (one global read, zero allocation) — the
  bench obs A/B arm pins the overhead < 2%.
* **bounded** — events land in a ring buffer (``OTPU_OBS_TRACE_CAP``,
  default 65536); a week-long serving process overwrites, never grows.
* **request identity** — every span carries ``trace_id`` (the active
  :mod:`obs.context` trace/run id), a process-unique ``span_id`` and the
  ``parent_id`` of the enclosing span on its thread, so one request's
  events are joinable across threads (the flight recorder and the
  slow-trace report both group by trace id). Cross-thread hops record
  Chrome **flow events** (:func:`flow`) linking a micro-batched submit to
  its coalesced flush and dispatch.
* **standard export** — ``export_chrome_trace()`` emits Chrome
  trace-event JSON (loads in Perfetto / ``chrome://tracing``); span
  nesting is by time containment per thread, the viewer convention, and
  flow arrows render from the ``s``/``t``/``f`` events.
* **device alignment** — when recording, each span also enters a
  ``jax.profiler.TraceAnnotation``, so running a fit under
  ``utils.profiling.profile_trace`` shows the SAME host span names lined
  up against the XLA device timeline.

Span taxonomy (docs/observability.md): ``fit`` ⊃ ``epoch`` ⊃ ``chunk`` ⊃
``dispatch`` for the streaming estimators, ``prefetch`` on the pipeline
worker thread, ``serve``/``mb_flush``/``serve_dispatch`` on the serving
path, ``timed:*`` for ``@timed`` functions; instants ``retry``/``fault``/
``wedge``/``crc_failure``/``shed``/``divergence``/``brownout`` from the
resilience subsystem; flows ``req`` across the micro-batcher's threads.

Ring-event layout (consumed by flight.py and the tests):
``(ph, name, t0_ns, dur_ns, thread_ident, args, trace_id, span_id,
parent_id)`` — the first six slots are the PR-7 layout, unchanged.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from typing import Iterable, Iterator

from orange3_spark_tpu.obs import context as _context
from orange3_spark_tpu.utils import knobs

__all__ = [
    "clear",
    "enabled",
    "events",
    "export_chrome_trace",
    "flow",
    "flush_buffered",
    "force_disabled",
    "force_enabled",
    "instant",
    "open_spans",
    "refresh",
    "refreshed_enabled",
    "set_enabled",
    "slowest_traces",
    "span",
    "span_iter",
    "spans_payload",
    "validate_chrome_trace",
]

_enabled: bool = knobs.get_bool("OTPU_OBS")
_cap: int = max(16, int(knobs.get_int("OTPU_OBS_TRACE_CAP")))
_ring: list = [None] * _cap
_seq = itertools.count()
#: span ids are their own sequence (ring slots recycle, identities don't)
_span_ids = itertools.count(1)

# TraceAnnotation is a cheap TraceMe when no profiler is active; resolved
# once so a jax build without it degrades to pure-host spans
try:
    import jax

    _ANNOTATION = getattr(jax.profiler, "TraceAnnotation", None)
except Exception:  # noqa: BLE001 - obs must import anywhere
    _ANNOTATION = None


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Programmatic switch — env-backed (writes ``OTPU_OBS``) so the
    fit-entry re-resolve (``refreshed_enabled``) cannot silently unwind
    an explicit override at the next fit."""
    global _enabled
    os.environ["OTPU_OBS"] = "1" if on else "0"
    _enabled = bool(on)


def refresh() -> None:
    """Re-read ``OTPU_OBS`` (tests and the bench A/B flip it mid-process)."""
    global _enabled
    _enabled = knobs.get_bool("OTPU_OBS")


def refreshed_enabled() -> bool:
    """Re-resolve the knob, then report it — the fit-entry/activation
    chokepoints use this so a mid-process env flip takes effect at the
    next run (the OTPU_DONATE/OTPU_SPARSE_UPDATE convention), while the
    per-span hot path keeps reading the cached flag lock-free. A
    ``set_enabled``/``force_disabled`` override is env-backed too (the
    bench A/B uses force_disabled around whole probe arms), so the
    re-read cannot unwind an active override mid-arm: spans and entry
    points flip together."""
    refresh()
    return _enabled


@contextlib.contextmanager
def _force(value: str):
    """Env-backed temporary override — so the fit-entry re-resolve
    (``refreshed_enabled``) agrees with the cached flag instead of
    silently unwinding the override mid-window."""
    prev = os.environ.get("OTPU_OBS")
    os.environ["OTPU_OBS"] = value
    refresh()
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("OTPU_OBS", None)
        else:
            os.environ["OTPU_OBS"] = prev
        refresh()


def force_disabled():
    """Temporarily no-op spans (the bench A/B's OTPU_OBS=0 arm)."""
    return _force("0")


def force_enabled():
    """Temporarily force spans ON (the bench A/B's obs-on arm must
    measure real instrumentation even when the ambient env carries
    OTPU_OBS=0 — a no-op-vs-no-op comparison would bank a vacuous
    overhead claim)."""
    return _force("1")


def _record(ph: str, name: str, t0_ns: int, dur_ns: int, args, *,
            trace_id=None, span_id=None, parent_id=None,
            buffer=None) -> None:
    ev = (ph, name, t0_ns, dur_ns, threading.get_ident(),
          args or None, trace_id, span_id, parent_id)
    if buffer is not None:
        # tail-retention (obs/context.py): an unsampled trace buffers its
        # events on the context; they reach the ring only if the request
        # turns out slow/shed/erroring — a plain append, still lock-free
        buffer.append(ev)
        return
    # single slot store — atomic under the GIL, no lock
    _ring[next(_seq) % _cap] = ev


def flush_buffered(evs: list) -> None:
    """Move a retained trace's buffered events into the ring (called by
    the obs.context scope exit — events carry their own thread idents)."""
    for ev in evs:
        _ring[next(_seq) % _cap] = ev


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


_TLS = threading.local()

# thread ident -> that thread's open-span stack. The stack object itself
# is only ever mutated by its owning thread (append/pop, GIL-atomic); the
# dict is written once per thread under the lock and read by the flight
# recorder, which copies each stack before walking it.
_OPEN: dict[int, list] = {}
_OPEN_LOCK = threading.Lock()


def _prune_dead_stacks_locked() -> None:
    """Drop _OPEN entries whose thread no longer exists (caller holds
    _OPEN_LOCK). sys._current_frames() is the ground truth for 'has a
    frame right now' — an abandoned-but-alive dispatch waiter stays, a
    finished pool thread goes, along with any span it never exited."""
    import sys

    live = set(sys._current_frames())
    for ident in [i for i in _OPEN if i not in live]:
        del _OPEN[ident]


def _open_stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
        with _OPEN_LOCK:
            if len(_OPEN) >= 64:    # short-lived-thread churn (serving
                #                     pools): don't grow without bound
                _prune_dead_stacks_locked()
            _OPEN[threading.get_ident()] = st
    return st


class _Span:
    __slots__ = ("name", "args", "t0", "ann", "uniq",
                 "trace_id", "span_id", "parent_id", "_buf")

    def __init__(self, name: str, args: dict | None, uniq: bool = False):
        self.name = name
        self.args = args
        self.ann = None
        self.uniq = uniq
        self.t0 = None
        self.trace_id = None
        self.span_id = None
        self.parent_id = None
        self._buf = None

    def __enter__(self):
        if self.uniq:
            open_names = getattr(_TLS, "open", None)
            if open_names is None:
                open_names = _TLS.open = set()
            open_names.add(self.name)
        ctx = _context.current_trace()
        if ctx is not None:
            self.trace_id = ctx.trace_id
            self._buf = ctx.buffer
        st = _open_stack()
        self.parent_id = st[-1].span_id if st else None
        self.span_id = next(_span_ids)
        st.append(self)
        if _ANNOTATION is not None:
            try:
                self.ann = _ANNOTATION(self.name)
                self.ann.__enter__()
            except Exception:  # noqa: BLE001 - annotation is best-effort
                self.ann = None
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t0 = self.t0
        _record("X", self.name, t0, time.perf_counter_ns() - t0, self.args,
                trace_id=self.trace_id, span_id=self.span_id,
                parent_id=self.parent_id, buffer=self._buf)
        st = getattr(_TLS, "stack", None)
        if st:
            if st[-1] is self:
                st.pop()
            else:  # mis-nested exit (generator-driven spans): best effort
                try:
                    st.remove(self)
                except ValueError:
                    pass
        if self.ann is not None:
            self.ann.__exit__(*exc)
        if self.uniq:
            _TLS.open.discard(self.name)
        return False


def span(name: str, index=None, unique: bool = False, **args):
    """Context manager timing one named region; ``index`` is shorthand for
    the ``i=`` arg (``span("epoch", 3)``). No-op (shared instance, zero
    allocation) when obs is disabled. ``unique=True`` records only the
    OUTERMOST same-named span per thread — ``Estimator.fit`` brackets a
    streaming ``fit_stream`` that opens its own "fit" span, and a trace
    with fit ⊃ fit would double-count fit time for anyone aggregating by
    span name."""
    if not _enabled:
        return _NULL
    if unique and name in getattr(_TLS, "open", ()):
        return _NULL
    if index is not None:
        args["i"] = index
    return _Span(name, args or None, uniq=unique)


def span_iter(name: str, iterable: Iterable) -> Iterator:
    """Wrap each ITERATION of a for-loop body in a span — the one-line way
    to instrument an existing loop without re-indenting it::

        for epoch in span_iter("epoch", range(n)):   # body spans "epoch"

    The span covers the loop body (yield -> resume), indexed per pass."""
    if not _enabled:
        yield from iterable
        return
    for i, item in enumerate(iterable):
        sp = span(name, i)
        sp.__enter__()
        try:
            yield item
        finally:
            sp.__exit__(None, None, None)


def instant(name: str, **args) -> None:
    """Record a point event (retries, wedges, faults) on the timeline."""
    if not _enabled:
        return
    ctx = _context.current_trace()
    _record("i", name, time.perf_counter_ns(), 0, args or None,
            trace_id=(ctx.trace_id if ctx is not None else None),
            buffer=(ctx.buffer if ctx is not None else None))


def flow(ph: str, flow_id, name: str = "req") -> None:
    """Record a Chrome flow event: ``ph`` is ``'s'`` (start), ``'t'``
    (step) or ``'f'`` (end); same ``flow_id`` + ``name`` across the three
    draws one arrow in Perfetto. The micro-batcher uses the request's
    trace id as the flow id, linking each caller's submit to the merged
    flush and its device dispatch across threads. Flow events bypass the
    tail-retention buffer on purpose: the worker-side ``t``/``f`` hops
    record from a context-less thread straight into the ring, so a
    sampled-out caller buffering its ``s`` would leave dangling
    steps/ends in every export."""
    if not _enabled:
        return
    if ph not in ("s", "t", "f"):
        raise ValueError(f"flow phase must be 's'/'t'/'f', got {ph!r}")
    ctx = _context.current_trace()
    _record(ph, name, time.perf_counter_ns(), 0, {"id": str(flow_id)},
            trace_id=(ctx.trace_id if ctx is not None else None))


def traced(name: str, **fixed_args):
    """Decorator form: the call body becomes one ``name`` span (unique
    per thread — a re-entrant/bracketed call records only the outermost,
    see ``span(unique=)``) AND a trace-context chokepoint: a fit entry
    mints the run id every span under it carries (an already-active
    context — the ``Estimator.fit`` bracket — is reused, never shadowed)."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            # fit entries are the chokepoint where a mid-process
            # OTPU_OBS flip takes effect (the kill-switch convention)
            if not refreshed_enabled():
                return fn(*a, **kw)
            # the run id's kind is the span name ("fit-<pid>-<n>" for
            # @traced("fit")) — a future @traced("score") mints an
            # honestly-labeled id, not a fake fit
            with _context.trace_scope(name, reuse=True):
                with span(name, unique=True, **fixed_args):
                    return fn(*a, **kw)

        return wrapper

    return deco


def events() -> list:
    """Recorded events, oldest first (chronological even after ring wrap)."""
    evs = [e for e in list(_ring) if e is not None]
    evs.sort(key=lambda e: e[2])
    return evs


def open_spans() -> list[dict]:
    """Currently-OPEN spans across every thread — the flight recorder's
    "what was each thread inside when the anomaly fired" view (a wedged
    dispatch's span is open at dump time: it only reaches the ring when
    the raise unwinds it). Best-effort snapshot: each stack is copied
    before walking, so a concurrent push/pop can cost one entry, never a
    torn read."""
    now = time.perf_counter_ns()
    with _OPEN_LOCK:
        _prune_dead_stacks_locked()   # a dead thread's abandoned spans
        #                               must not pollute post-mortems
        stacks = [(ident, list(st)) for ident, st in _OPEN.items()]
    out = []
    for ident, st in stacks:
        for sp in st:
            t0 = sp.t0
            if t0 is None:
                continue
            out.append({
                "thread": ident, "name": sp.name,
                "args": dict(sp.args) if sp.args else None,
                "trace_id": sp.trace_id, "span_id": sp.span_id,
                "parent_id": sp.parent_id,
                "age_ms": round((now - t0) / 1e6, 3), "open": True,
            })
    return out


def spans_payload(trace_id: str | None = None,
                  limit: int = 4096) -> dict:
    """JSON-able view of this PROCESS's ring (optionally filtered to one
    trace id) for cross-process trace assembly (obs/fleetobs.py): the
    fleet ``GET /debug/spans?trace_id=`` body. Ring tuples are process-
    local ``perf_counter_ns`` values, so the payload carries a
    wall/perf **clock anchor** sampled at build time — the assembler
    rebases every timestamp as ``wall_ns + (t_ns - perf_ns)``, putting
    router- and replica-side spans on one shared wall-clock axis."""
    evs = events()
    if trace_id is not None:
        evs = [e for e in evs if e[6] == trace_id]
    opened = open_spans()
    if trace_id is not None:
        opened = [s for s in opened if s["trace_id"] == trace_id]
    return {
        "pid": os.getpid(),
        "anchor": {"wall_ns": time.time_ns(),
                   "perf_ns": time.perf_counter_ns()},
        "events": [[ph, name, t0, dur, ident,
                    dict(args) if args else None, tid, sid, pid_]
                   for (ph, name, t0, dur, ident, args, tid, sid, pid_)
                   in evs[-max(limit, 0):]],
        "open_spans": opened,
    }


def clear() -> None:
    """Drop every recorded event (benches/tests bracket with this)."""
    global _ring, _seq
    _ring = [None] * _cap
    _seq = itertools.count()


_MAX_TREE_CHILDREN = 16


def slowest_traces(k: int = 5, since_ns: int | None = None) -> list[dict]:
    """Top-``k`` slowest traces currently in the ring, as span trees —
    the report hook that links a run report straight into the trace ring.
    A trace's duration is its longest ROOT span (the serve/fit bracket);
    ``since_ns`` (a ``perf_counter_ns`` value) restricts to events after
    a run's start. Children are capped at 16 per node (``truncated``
    marks the cut) so a many-chunk fit report stays readable."""
    by_trace: dict = {}
    for ev in events():
        if ev[0] != "X" or ev[6] is None:
            continue
        if since_ns is not None and ev[2] < since_ns:
            continue
        by_trace.setdefault(ev[6], []).append(ev)
    ranked = []
    for trace_id, evs in by_trace.items():
        recorded = {e[7] for e in evs}
        # roots = spans whose parent never reached the ring: true roots
        # (parent None) AND orphans whose parent span is still OPEN — a
        # report frozen mid-fit sees the epochs under a not-yet-closed
        # fit span, and they must all anchor the tree, not just one
        roots = [e for e in evs if e[8] is None or e[8] not in recorded]
        anchor = max(roots or evs, key=lambda e: e[3])
        ranked.append((anchor[3], trace_id, anchor, roots or [anchor], evs))
    ranked.sort(key=lambda r: (-r[0], r[1]))

    def node(e, children_by_parent):
        kids = sorted(children_by_parent.get(e[7], ()), key=lambda c: c[2])
        out = {
            "name": e[1], "dur_ms": round(e[3] / 1e6, 3),
            "args": dict(e[5]) if e[5] else None,
            "children": [node(c, children_by_parent)
                         for c in kids[:_MAX_TREE_CHILDREN]],
        }
        if len(kids) > _MAX_TREE_CHILDREN:
            out["truncated"] = len(kids) - _MAX_TREE_CHILDREN
        return out

    out = []
    for dur_ns, trace_id, anchor, roots, evs in ranked[:max(k, 0)]:
        children_by_parent: dict = {}
        for e in evs:
            children_by_parent.setdefault(e[8], []).append(e)
        roots = sorted(roots, key=lambda e: e[2])
        if len(roots) == 1:
            tree = node(roots[0], children_by_parent)
        else:                       # multi-root: synthesized container
            tree = {
                "name": "(trace)", "dur_ms": round(dur_ns / 1e6, 3),
                "args": None,
                "children": [node(r, children_by_parent)
                             for r in roots[:_MAX_TREE_CHILDREN]],
            }
            if len(roots) > _MAX_TREE_CHILDREN:
                tree["truncated"] = len(roots) - _MAX_TREE_CHILDREN
        out.append({
            "trace_id": trace_id, "span": anchor[1],
            "dur_ms": round(dur_ns / 1e6, 3), "n_spans": len(evs),
            "tree": tree,
        })
    return out


def export_chrome_trace(path: str | None = None) -> dict:
    """Chrome trace-event JSON of every recorded event. Loads in Perfetto
    / ``chrome://tracing``; ``ts``/``dur`` are microseconds on the
    process-local ``perf_counter`` clock; trace/span/parent ids ride the
    ``args`` pane; flow events carry their required top-level ``id``.
    Writes to ``path`` when given; returns the trace object either way."""
    pid = os.getpid()
    tid_map: dict[int, int] = {}
    trace_events: list[dict] = []
    for ph, name, t_ns, dur_ns, ident, args, trace_id, span_id, parent_id \
            in events():
        tid = tid_map.setdefault(ident, len(tid_map))
        ev: dict = {
            "name": name, "ph": ph, "cat": "otpu",
            "pid": pid, "tid": tid, "ts": t_ns / 1e3,
        }
        a = dict(args) if args else {}
        if ph == "X":
            ev["dur"] = dur_ns / 1e3
        elif ph == "i":
            ev["s"] = "t"
        elif ph in ("s", "t", "f"):
            # the flow-event contract: matching (cat, name, id) triples
            # draw one arrow; bind to the enclosing slice
            ev["id"] = str(a.pop("id", ""))
            ev["bp"] = "e"
        if trace_id is not None:
            a["trace_id"] = trace_id
            if span_id is not None:
                a["span_id"] = span_id
            if parent_id is not None:
                a["parent_id"] = parent_id
        if a:
            ev["args"] = a
        trace_events.append(ev)
    # thread-name metadata rows make the Perfetto view self-describing
    for ident, tid in tid_map.items():
        trace_events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": f"thread-{ident}"},
        })
    out = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(out, f)
    return out


def validate_chrome_trace(obj) -> list[dict]:
    """Raise ValueError unless ``obj`` (a dict or a JSON string) is valid
    Chrome trace-event JSON by the format's object-form rules; returns the
    event list. Used by tools/obs_dump.py and the trace tests."""
    if isinstance(obj, (str, bytes)):
        obj = json.loads(obj)
    if not isinstance(obj, dict) or not isinstance(
            obj.get("traceEvents"), list):
        raise ValueError("not object-form Chrome trace JSON "
                         "(missing 'traceEvents' list)")
    for ev in obj["traceEvents"]:
        if not isinstance(ev, dict):
            raise ValueError(f"non-object trace event: {ev!r}")
        for field in ("name", "ph", "pid", "tid"):
            if field not in ev:
                raise ValueError(f"trace event missing {field!r}: {ev!r}")
        if ev["ph"] in ("X", "B", "E", "i", "s", "t", "f") \
                and not isinstance(ev.get("ts"), (int, float)):
            raise ValueError(f"trace event missing numeric ts: {ev!r}")
        if ev["ph"] == "X" and not isinstance(
                ev.get("dur"), (int, float)):
            raise ValueError(f"complete event missing dur: {ev!r}")
        if ev["ph"] in ("s", "t", "f") and not ev.get("id"):
            raise ValueError(f"flow event missing id: {ev!r}")
    return obj["traceEvents"]
