"""Structured span tracing — the host-side timeline a Spark UI would show.

``jax.profiler.trace`` already captures DEVICE time (PAPER §5's
``profile_trace``); what it cannot show is the framework's own structure —
which fit, which epoch, which chunk, which dispatch the host was inside
when the device stalled. This module records that structure as spans:

    with span("epoch", i):          # or: for i in span_iter("epoch", rng)
        ...
    instant("retry", cause="source")   # point events (retries, wedges)

Design constraints, in order:

* **lock-free fast path** — recording a span is one ``perf_counter_ns``
  pair, one atomic-under-the-GIL ``itertools.count`` bump and one list
  slot store; no lock anywhere on the hot path. With ``OTPU_OBS=0`` the
  ``span()`` call returns a shared no-op context manager (one global read,
  zero allocation) — the bench obs A/B arm pins the overhead < 2%.
* **bounded** — events land in a ring buffer (``OTPU_OBS_TRACE_CAP``,
  default 65536); a week-long serving process overwrites, never grows.
* **standard export** — ``export_chrome_trace()`` emits Chrome
  trace-event JSON (loads in Perfetto / ``chrome://tracing``); span
  nesting is by time containment per thread, the viewer convention.
* **device alignment** — when recording, each span also enters a
  ``jax.profiler.TraceAnnotation``, so running a fit under
  ``utils.profiling.profile_trace`` shows the SAME host span names lined
  up against the XLA device timeline.

Span taxonomy (docs/observability.md): ``fit`` ⊃ ``epoch`` ⊃ ``chunk`` ⊃
``dispatch`` for the streaming estimators, ``prefetch`` on the pipeline
worker thread, ``serve``/``mb_flush`` on the serving path, ``timed:*``
for ``@timed`` functions; instants ``retry``/``fault``/``wedge``/
``crc_failure`` from the resilience subsystem.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from typing import Iterable, Iterator

from orange3_spark_tpu.utils import knobs

__all__ = [
    "clear",
    "enabled",
    "events",
    "export_chrome_trace",
    "force_disabled",
    "force_enabled",
    "instant",
    "refresh",
    "refreshed_enabled",
    "set_enabled",
    "span",
    "span_iter",
    "validate_chrome_trace",
]

_enabled: bool = knobs.get_bool("OTPU_OBS")
_cap: int = max(16, int(knobs.get_int("OTPU_OBS_TRACE_CAP")))
_ring: list = [None] * _cap
_seq = itertools.count()

# TraceAnnotation is a cheap TraceMe when no profiler is active; resolved
# once so a jax build without it degrades to pure-host spans
try:
    import jax

    _ANNOTATION = getattr(jax.profiler, "TraceAnnotation", None)
except Exception:  # noqa: BLE001 - obs must import anywhere
    _ANNOTATION = None


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Programmatic switch — env-backed (writes ``OTPU_OBS``) so the
    fit-entry re-resolve (``refreshed_enabled``) cannot silently unwind
    an explicit override at the next fit."""
    global _enabled
    os.environ["OTPU_OBS"] = "1" if on else "0"
    _enabled = bool(on)


def refresh() -> None:
    """Re-read ``OTPU_OBS`` (tests and the bench A/B flip it mid-process)."""
    global _enabled
    _enabled = knobs.get_bool("OTPU_OBS")


def refreshed_enabled() -> bool:
    """Re-resolve the knob, then report it — the fit-entry/activation
    chokepoints use this so a mid-process env flip takes effect at the
    next run (the OTPU_DONATE/OTPU_SPARSE_UPDATE convention), while the
    per-span hot path keeps reading the cached flag lock-free. A
    ``set_enabled``/``force_disabled`` override is env-backed too (the
    bench A/B uses force_disabled around whole probe arms), so the
    re-read cannot unwind an active override mid-arm: spans and entry
    points flip together."""
    refresh()
    return _enabled


@contextlib.contextmanager
def _force(value: str):
    """Env-backed temporary override — so the fit-entry re-resolve
    (``refreshed_enabled``) agrees with the cached flag instead of
    silently unwinding the override mid-window."""
    prev = os.environ.get("OTPU_OBS")
    os.environ["OTPU_OBS"] = value
    refresh()
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("OTPU_OBS", None)
        else:
            os.environ["OTPU_OBS"] = prev
        refresh()


def force_disabled():
    """Temporarily no-op spans (the bench A/B's OTPU_OBS=0 arm)."""
    return _force("0")


def force_enabled():
    """Temporarily force spans ON (the bench A/B's obs-on arm must
    measure real instrumentation even when the ambient env carries
    OTPU_OBS=0 — a no-op-vs-no-op comparison would bank a vacuous
    overhead claim)."""
    return _force("1")


def _record(ph: str, name: str, t0_ns: int, dur_ns: int, args) -> None:
    # single slot store — atomic under the GIL, no lock
    _ring[next(_seq) % _cap] = (
        ph, name, t0_ns, dur_ns, threading.get_ident(), args or None)


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


_TLS = threading.local()


class _Span:
    __slots__ = ("name", "args", "t0", "ann", "uniq")

    def __init__(self, name: str, args: dict | None, uniq: bool = False):
        self.name = name
        self.args = args
        self.ann = None
        self.uniq = uniq

    def __enter__(self):
        if self.uniq:
            open_names = getattr(_TLS, "open", None)
            if open_names is None:
                open_names = _TLS.open = set()
            open_names.add(self.name)
        if _ANNOTATION is not None:
            try:
                self.ann = _ANNOTATION(self.name)
                self.ann.__enter__()
            except Exception:  # noqa: BLE001 - annotation is best-effort
                self.ann = None
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t0 = self.t0
        _record("X", self.name, t0, time.perf_counter_ns() - t0, self.args)
        if self.ann is not None:
            self.ann.__exit__(*exc)
        if self.uniq:
            _TLS.open.discard(self.name)
        return False


def span(name: str, index=None, unique: bool = False, **args):
    """Context manager timing one named region; ``index`` is shorthand for
    the ``i=`` arg (``span("epoch", 3)``). No-op (shared instance, zero
    allocation) when obs is disabled. ``unique=True`` records only the
    OUTERMOST same-named span per thread — ``Estimator.fit`` brackets a
    streaming ``fit_stream`` that opens its own "fit" span, and a trace
    with fit ⊃ fit would double-count fit time for anyone aggregating by
    span name."""
    if not _enabled:
        return _NULL
    if unique and name in getattr(_TLS, "open", ()):
        return _NULL
    if index is not None:
        args["i"] = index
    return _Span(name, args or None, uniq=unique)


def span_iter(name: str, iterable: Iterable) -> Iterator:
    """Wrap each ITERATION of a for-loop body in a span — the one-line way
    to instrument an existing loop without re-indenting it::

        for epoch in span_iter("epoch", range(n)):   # body spans "epoch"

    The span covers the loop body (yield -> resume), indexed per pass."""
    if not _enabled:
        yield from iterable
        return
    for i, item in enumerate(iterable):
        sp = span(name, i)
        sp.__enter__()
        try:
            yield item
        finally:
            sp.__exit__(None, None, None)


def instant(name: str, **args) -> None:
    """Record a point event (retries, wedges, faults) on the timeline."""
    if not _enabled:
        return
    _record("i", name, time.perf_counter_ns(), 0, args or None)


def traced(name: str, **fixed_args):
    """Decorator form: the call body becomes one ``name`` span (unique
    per thread — a re-entrant/bracketed call records only the outermost,
    see ``span(unique=)``)."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            # fit entries are the chokepoint where a mid-process
            # OTPU_OBS flip takes effect (the kill-switch convention)
            if not refreshed_enabled():
                return fn(*a, **kw)
            with span(name, unique=True, **fixed_args):
                return fn(*a, **kw)

        return wrapper

    return deco


def events() -> list:
    """Recorded events, oldest first (chronological even after ring wrap)."""
    evs = [e for e in list(_ring) if e is not None]
    evs.sort(key=lambda e: e[2])
    return evs


def clear() -> None:
    """Drop every recorded event (benches/tests bracket with this)."""
    global _ring, _seq
    _ring = [None] * _cap
    _seq = itertools.count()


def export_chrome_trace(path: str | None = None) -> dict:
    """Chrome trace-event JSON of every recorded event. Loads in Perfetto
    / ``chrome://tracing``; ``ts``/``dur`` are microseconds on the
    process-local ``perf_counter`` clock. Writes to ``path`` when given;
    returns the trace object either way."""
    pid = os.getpid()
    tid_map: dict[int, int] = {}
    trace_events: list[dict] = []
    for ph, name, t_ns, dur_ns, ident, args in events():
        tid = tid_map.setdefault(ident, len(tid_map))
        ev: dict = {
            "name": name, "ph": ph, "cat": "otpu",
            "pid": pid, "tid": tid, "ts": t_ns / 1e3,
        }
        if ph == "X":
            ev["dur"] = dur_ns / 1e3
        elif ph == "i":
            ev["s"] = "t"
        if args:
            ev["args"] = dict(args)
        trace_events.append(ev)
    # thread-name metadata rows make the Perfetto view self-describing
    for ident, tid in tid_map.items():
        trace_events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": f"thread-{ident}"},
        })
    out = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(out, f)
    return out


def validate_chrome_trace(obj) -> list[dict]:
    """Raise ValueError unless ``obj`` (a dict or a JSON string) is valid
    Chrome trace-event JSON by the format's object-form rules; returns the
    event list. Used by tools/obs_dump.py and the trace tests."""
    if isinstance(obj, (str, bytes)):
        obj = json.loads(obj)
    if not isinstance(obj, dict) or not isinstance(
            obj.get("traceEvents"), list):
        raise ValueError("not object-form Chrome trace JSON "
                         "(missing 'traceEvents' list)")
    for ev in obj["traceEvents"]:
        if not isinstance(ev, dict):
            raise ValueError(f"non-object trace event: {ev!r}")
        for field in ("name", "ph", "pid", "tid"):
            if field not in ev:
                raise ValueError(f"trace event missing {field!r}: {ev!r}")
        if ev["ph"] in ("X", "B", "E", "i") and not isinstance(
                ev.get("ts"), (int, float)):
            raise ValueError(f"trace event missing numeric ts: {ev!r}")
        if ev["ph"] == "X" and not isinstance(
                ev.get("dur"), (int, float)):
            raise ValueError(f"complete event missing dur: {ev!r}")
    return obj["traceEvents"]
