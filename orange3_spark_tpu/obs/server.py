"""Live telemetry endpoint — a scrapeable serving process, zero deps.

Spark serves its metrics servlet on the driver UI port; the equivalent
here is a stdlib ``ThreadingHTTPServer`` on a daemon thread exposing:

* ``GET /metrics``  — Prometheus text exposition of the whole registry
  (the aot/bucket/mb serving counters, dispatches, retries, histograms);
* ``GET /readyz``   — JSON **readiness** (distinct from liveness): 200
  only when a ``ServingContext`` is active, its warmup has completed
  (``ServingContext.warmup`` notes it), and the process is not draining
  (fleet/rpc.py sets the drain flag on SIGTERM / ``POST /drain``);
  otherwise 503 with a ``reason``. This is what a fleet router routes
  on — a replica mid-warmup or mid-drain is *alive* (``/healthz`` 200)
  but must receive no new traffic;
* ``GET /healthz``  — JSON liveness: seconds since the last progress beat
  (``utils.dispatch.beat`` — every step loop, prefetch worker, routed
  serve call and micro-batch flush ticks it), in-flight/wedge/retry
  counts, the micro-batcher queue depth, admission-control shed totals
  and the memory-pressure ``brownout_level``
  (resilience/overload.py). Returns **503** once the
  beat is older than ``OTPU_OBS_STALE_S`` (default 60 s) WHILE work is
  in flight — the round-4 wedged-dispatch signature. An idle process
  (nothing in flight, nothing to beat about) reports ``idle`` and stays
  200-healthy, so a load balancer acting on this endpoint never ejects
  a backend for a quiet minute.

With a fleet collector attached (``TelemetryServer(fleet=...)``, the
router-side shape — obs/fleetobs.py), ``/metrics`` serves the FLEET
exposition (per-replica-labeled series + aggregates) and ``/fleetz``
the JSON fleet view; ``/debug/spans?trace_id=`` serves this process's
span-ring payload for cross-process trace assembly either way.

Opt-in by ``OTPU_OBS_PORT`` (0 = ephemeral, for tests): ``ServingContext``
activation starts it, the last deactivation stops it. Inert under
``OTPU_OBS=0`` — the endpoint never binds. Binds 127.0.0.1 only; exposing
it beyond the host is a reverse proxy's job, not a data-plane library's.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from orange3_spark_tpu.utils import knobs

__all__ = [
    "TelemetryServer",
    "is_draining",
    "maybe_start_from_env",
    "note_warmup_complete",
    "profile_capture_body",
    "ready_body",
    "reset_readiness",
    "set_draining",
]

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# ---------------------------------------------------------------- readiness
# Process-wide readiness state, distinct from the liveness heartbeat:
# /healthz answers "is this process making progress", /readyz answers
# "should a router send this process NEW work". Warmup completion is noted
# by ServingContext.warmup(); the drain flag is raised by the fleet
# replica's SIGTERM handler / POST /drain hook (fleet/rpc.py). A fresh
# serving window (first ServingContext activation with none already
# active) resets warmup — a context is not ready until it is warm.
_READY_LOCK = threading.Lock()
_warmup_complete = False
_draining = False


def note_warmup_complete(done: bool = True) -> None:
    """ServingContext.warmup() calls this on success — the readiness
    half of "warmed ahead of traffic"."""
    global _warmup_complete
    with _READY_LOCK:
        _warmup_complete = bool(done)


def set_draining(on: bool = True) -> None:
    """Raise/clear the process drain flag (fleet SIGTERM / POST /drain):
    a draining process fails /readyz so routers stop sending new work,
    while in-flight requests finish."""
    global _draining
    with _READY_LOCK:
        _draining = bool(on)


def is_draining() -> bool:
    return _draining


def reset_readiness() -> None:
    """Fresh serving window: not warm, not draining."""
    global _warmup_complete, _draining
    with _READY_LOCK:
        _warmup_complete = False
        _draining = False


def ready_body(context=None) -> tuple[dict, bool]:
    """(/readyz body, ready?). Ready means: an active ServingContext,
    warmup complete, and not draining — in that *reporting* order, with
    draining outranking the rest (a draining replica must advertise WHY
    it refuses work, not a stale warmup state)."""
    from orange3_spark_tpu.serve.context import active_serving_context

    ctx = context if context is not None else active_serving_context()
    with _READY_LOCK:
        draining, warm = _draining, _warmup_complete
    if draining:
        reason = "draining"
    elif ctx is None:
        reason = "no_active_context"
    elif not warm:
        reason = "warmup_pending"
    else:
        reason = None
    ready = reason is None
    body = {
        "status": "ready" if ready else "unready",
        "ready": ready,
        "reason": reason,
        "draining": draining,
        "warmup_complete": warm,
        "context_active": ctx is not None,
    }
    # control-plane status (PR 20): keys appear ONLY when the control
    # plane has something to say — tenant-less processes with no
    # autoscaler keep the exact pre-tenancy body, byte for byte
    from orange3_spark_tpu.fleet.control import active_autoscaler_state
    from orange3_spark_tpu.serve.tenancy import tenant_shed_counts

    sheds = tenant_shed_counts()
    if sheds:
        body["tenants"] = {"sheds": sheds}
    scaler = active_autoscaler_state()
    if scaler is not None:
        body["autoscaler"] = scaler
    return body, ready


def spans_body(path: str) -> dict:
    """The shared ``GET /debug/spans?trace_id=`` body (this server AND
    the fleet RPC port): this process's span-ring payload, optionally
    filtered to the trace id in the query string."""
    from urllib.parse import parse_qs, urlsplit

    from orange3_spark_tpu.obs import trace

    q = parse_qs(urlsplit(path).query)
    tid = (q.get("trace_id") or [None])[0] or None
    return trace.spans_payload(tid)


def stacks_body() -> dict:
    """The shared ``GET /debug/stacks`` body: every thread's Python
    stack plus the open spans each was inside."""
    from orange3_spark_tpu.obs import flight, trace

    return {"stacks": flight.thread_stacks(),
            "open_spans": trace.open_spans()}


def profile_capture_body(path: str) -> tuple[int, dict]:
    """The ``POST /debug/profile?duration_ms=`` body (obs/prof.py deep
    capture): status mapping is part of the contract — 503 under the
    ``OTPU_PROF=0`` kill-switch, 409 while another capture runs
    (captures serialize), 429 inside the ``OTPU_PROF_RATE_S`` window,
    200 with the artifact path. The response is a summary, not the full
    snapshot — the artifact dir holds the real thing."""
    from urllib.parse import parse_qs, urlsplit

    from orange3_spark_tpu.obs import prof

    q = parse_qs(urlsplit(path).query)
    raw = (q.get("duration_ms") or [None])[0]
    try:
        duration_ms = float(raw) if raw not in (None, "") else 500.0
    except ValueError:
        return 400, {"error": "bad_duration_ms", "duration_ms": raw}
    try:
        out = prof.capture(duration_ms, reason="debug_endpoint")
    except prof.CaptureDisabledError as e:
        return 503, {"error": "prof_disabled", "message": str(e)}
    except prof.CaptureBusyError as e:
        return 409, {"error": "capture_busy", "message": str(e)}
    except prof.CaptureRateLimitedError as e:
        return 429, {"error": "rate_limited", "message": str(e)}
    except Exception as e:  # noqa: BLE001 - typed to the caller
        return 500, {"error": type(e).__name__, "message": str(e)[:500]}
    snap = out["snapshot"]
    return 200, {
        "path": out["path"],
        "reason": out["reason"],
        "duration_ms": out["duration_ms"],
        "ledger_total_bytes": snap["ledger"]["total_bytes"],
        "goodput": snap["goodput"],
    }


class _Handler(BaseHTTPRequestHandler):
    server_version = "otpu-obs/1"
    # HTTP/1.1 so fleet proxies reuse their keep-alive connection to us:
    # every response goes through _send, which sets Content-Length — the
    # invariant that makes connection reuse safe (audited in
    # tests/test_fastwire.py)
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # serving stdout is not an access log
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        owner: "TelemetryServer" = self.server._otpu_owner
        try:
            route = self.path.split("?")[0]
            if route == "/metrics":
                from orange3_spark_tpu.obs.registry import REGISTRY

                fleet = owner._fleet
                if fleet is not None:
                    # the fleet exposition: this process's registry is
                    # one more source ("router") beside every scraped
                    # replica, re-labeled + aggregated by the collector
                    body = fleet.to_prometheus().encode()
                else:
                    body = REGISTRY.to_prometheus().encode()
                self._send(200, body, PROM_CONTENT_TYPE)
            elif route == "/fleetz":
                fleet = owner._fleet
                if fleet is None:
                    self._send(404, b"no fleet collector attached\n",
                               "text/plain")
                else:
                    self._send(200,
                               json.dumps(fleet.fleetz(),
                                          default=str).encode(),
                               "application/json")
            elif route == "/debug/spans":
                self._send(200,
                           json.dumps(spans_body(self.path),
                                      default=str).encode(),
                           "application/json")
            elif route == "/healthz":
                body, healthy = owner.health()
                self._send(200 if healthy else 503,
                           json.dumps(body).encode(), "application/json")
            elif route == "/readyz":
                body, ready = ready_body(owner._context)
                self._send(200 if ready else 503,
                           json.dumps(body).encode(), "application/json")
            elif route == "/debug/flight":
                # the manual black-box pull on a LIVE process: write a
                # bundle (no rate limit — the operator asked) and return
                # it; loopback-only like everything on this listener
                from orange3_spark_tpu.obs import flight

                bundle = flight.debug_bundle(context=owner._context)
                self._send(200, json.dumps(bundle, default=str).encode(),
                           "application/json")
            elif route == "/debug/stacks":
                self._send(200,
                           json.dumps(stacks_body(),
                                      default=str).encode(),
                           "application/json")
            else:
                self._send(404, b"not found: try /metrics, /healthz, "
                                b"/readyz, /fleetz, /debug/flight, "
                                b"/debug/stacks, /debug/spans or "
                                b"POST /debug/profile\n",
                           "text/plain")
        except Exception as e:  # noqa: BLE001 - never kill the listener
            try:
                self._send(500, f"{type(e).__name__}: {e}\n".encode(),
                           "text/plain")
            except Exception:  # noqa: BLE001 - client went away
                pass

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        try:
            # drain the request body before responding: unread bytes on
            # a keep-alive connection are parsed as the next request
            n = int(self.headers.get("Content-Length") or 0)
            if n:
                self.rfile.read(n)
            route = self.path.split("?")[0]
            if route == "/debug/profile":
                # on-demand deep capture (obs/prof.py): loopback-only
                # like everything on this listener, serialized (409),
                # rate-limited (429), refused under OTPU_PROF=0 (503)
                code, body = profile_capture_body(self.path)
                self._send(code, json.dumps(body, default=str).encode(),
                           "application/json")
            else:
                self._send(404, b"not found: POST /debug/profile\n",
                           "text/plain")
        except Exception as e:  # noqa: BLE001 - never kill the listener
            try:
                self._send(500, f"{type(e).__name__}: {e}\n".encode(),
                           "text/plain")
            except Exception:  # noqa: BLE001 - client went away
                pass


class TelemetryServer:
    """One /metrics + /healthz listener; start() binds, stop() joins."""

    def __init__(self, port: int = 0, *, stale_s: float | None = None,
                 context=None, fleet=None):
        self.port = port
        self.stale_s = (stale_s if stale_s is not None
                        else float(knobs.get_float("OTPU_OBS_STALE_S")))
        self._context = context      # owning ServingContext (queue depth)
        # attached FleetCollector (obs/fleetobs.py): /metrics becomes the
        # fleet exposition and /fleetz serves the JSON fleet view — the
        # router-side shape of this server
        self._fleet = fleet
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ control
    def start(self) -> "TelemetryServer":
        httpd = ThreadingHTTPServer(("127.0.0.1", self.port), _Handler)
        httpd.daemon_threads = True
        httpd._otpu_owner = self
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._thread = threading.Thread(
            target=httpd.serve_forever, daemon=True, name="otpu-obs-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # ------------------------------------------------------------- health
    def health(self) -> tuple[dict, bool]:
        """(/healthz body, healthy?). Unhealthy means WEDGED, not idle:
        a stale heartbeat only degrades the status while serve calls are
        in flight (or micro-batch work is queued) — that is the round-4
        hang signature the watchdog exists for. An idle process has
        nothing to beat about and must stay healthy, or a load balancer
        acting on this endpoint would permanently eject every backend
        that sees a quiet minute."""
        from orange3_spark_tpu.obs.registry import REGISTRY
        from orange3_spark_tpu.resilience.overload import (
            brownout_level, shed_total,
        )
        from orange3_spark_tpu.utils.dispatch import last_beat
        from orange3_spark_tpu.utils.profiling import (
            exec_counters, resilience_counters,
        )

        age = time.monotonic() - last_beat()
        res = resilience_counters()
        ex = exec_counters()
        depth = None
        ctx = self._context
        mb = getattr(ctx, "micro_batcher", None) if ctx is not None else None
        if mb is not None:
            depth = mb._q.qsize()
        g = REGISTRY.get("otpu_serve_inflight")
        inflight = int(g.value()) if g is not None else 0
        busy = inflight > 0 or bool(depth)
        stale = age >= self.stale_s
        healthy = not (stale and busy)
        return {
            "status": ("ok" if not stale else
                       "stale" if busy else "idle"),
            "last_beat_age_s": round(age, 3),
            "stale_after_s": self.stale_s,
            "in_flight": inflight,
            "wedges": res["wedges"],
            "retries": res["retries"],
            "crc_failures": res["crc_failures"],
            "dispatches": ex["dispatches"],
            "mb_queue_depth": depth,
            # overload-protection state (resilience/overload.py): how
            # hard admission control is shedding, and which brownout
            # rung the memory-pressure ladder lands on — RECOMPUTED per
            # scrape (a level-3 spike during a finished fit must not be
            # echoed forever), so a load balancer can steer AWAY from a
            # browned-out backend and return once pressure subsides
            "sheds": shed_total(),
            "brownout_level": brownout_level(consume=False),
        }, healthy


def maybe_start_from_env(context=None) -> TelemetryServer | None:
    """The ServingContext hook: bind iff ``OTPU_OBS_PORT`` is set AND obs
    is enabled (``OTPU_OBS=0`` => the endpoint never binds). A bind
    failure (port taken) warns and returns None — serving must not die
    for its telemetry."""
    from orange3_spark_tpu.obs import trace

    raw = knobs.get_raw("OTPU_OBS_PORT")
    # refreshed_enabled: activation is a chokepoint where a mid-process
    # OTPU_OBS flip must take effect (never bind under the kill-switch)
    if raw in (None, "") or not trace.refreshed_enabled():
        return None
    import logging

    port = knobs.get_int("OTPU_OBS_PORT")
    if port is None:
        # malformed port: the declared default (None) means "no server" —
        # binding a surprise ephemeral port would break the operator's
        # scrape silently, so warn and stay unbound instead
        logging.getLogger("orange3_spark_tpu").warning(
            "obs: OTPU_OBS_PORT=%r is not a port number; telemetry "
            "server not started", raw)
        return None
    try:
        return TelemetryServer(int(port), context=context).start()
    except OSError as e:
        logging.getLogger("orange3_spark_tpu").warning(
            "obs: telemetry server failed to bind port %s (%s); "
            "serving continues without it", port, e)
        return None
