"""Anomaly flight recorder — the black box that dumps itself on failure.

When one of the typed anomalies fires in production
(``DispatchWedgedError``, ``OverloadShedError``,
``NumericalDivergenceError``, ``SpillCorruptionError``), the state that
explains it — the recent span timeline, which spans were still OPEN on
which thread, breaker states, admission/micro-batch queue depths, the
brownout level, the resolved knob table and every thread's Python stack —
is gone by the time anyone attaches a debugger. This module freezes all
of it into ONE versioned JSON bundle at the raise site, so a production
incident is debuggable from an artifact instead of a live repro.

* :func:`auto_dump` — the raise-site hook: rate-limited
  (``OTPU_FLIGHT_RATE_S`` between automatic bundles — an overload storm
  must not turn the recorder into its own IO storm), never raises (a
  failing black box must not mask the anomaly it records), inert under
  ``OTPU_FLIGHT=0`` and under the obs master switch ``OTPU_OBS=0``.
* :func:`dump` — the manual pull (``ServingContext.dump_flight()``, the
  ``/debug/flight`` endpoint, ``tools/obs_dump.py --flight``): same
  bundle, no rate limit.
* Bundles land in ``OTPU_FLIGHT_DIR`` as ``flight-<ns>-<reason>.json``,
  written atomically (tmp + rename: a reader never sees torn JSON), and
  the directory keeps at most ``OTPU_FLIGHT_MAX`` bundles (oldest
  deleted) — a misbehaving week cannot fill a disk.
* ``tools/flight_view.py`` renders a bundle one-shot.

Bundle schema (``flight_schema`` = 1, docs/observability.md):
``reason`` / ``error`` / ``trace_id`` identify the anomaly; ``events``
(the last-N ring events, Chrome-ish dicts) + ``open_spans`` give the
timeline; ``registry`` is the full metrics snapshot; ``breakers`` /
``admission`` / ``mb_queue_depth`` / ``brownout_level`` / ``sheds`` give
the control-plane state; ``knobs`` is the resolved env-knob table;
``stacks`` holds every thread's Python frames via
``sys._current_frames()``.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback

from orange3_spark_tpu.obs.registry import REGISTRY
from orange3_spark_tpu.utils import knobs

__all__ = [
    "FLIGHT_SCHEMA_VERSION",
    "auto_dump",
    "bundles_written",
    "collect_bundle",
    "dump",
    "flight_enabled",
    "thread_stacks",
]

log = logging.getLogger("orange3_spark_tpu")

FLIGHT_SCHEMA_VERSION = 1

#: ring events included in a bundle (the newest; the full ring can be
#: 65536 events — a bundle wants the recent past, not a 40 MB artifact)
MAX_BUNDLE_EVENTS = 512

_M_BUNDLES = REGISTRY.counter(
    "otpu_flight_bundles_total",
    "anomaly flight bundles written, by reason")

_rate_lock = threading.Lock()
_last_auto_dump = 0.0          # monotonic; 0 = never


def flight_enabled() -> bool:
    """Both switches: the obs master (``OTPU_OBS``) and the recorder's own
    kill-switch (``OTPU_FLIGHT``). Re-resolved per call — an operator can
    silence a dump storm live."""
    from orange3_spark_tpu.obs import trace

    return trace.refreshed_enabled() and knobs.get_bool("OTPU_FLIGHT")


def bundles_written() -> int:
    """Total flight bundles this process has written (all reasons)."""
    return int(_M_BUNDLES.total())


def thread_stacks() -> dict:
    """Every thread's current Python stack, keyed ``"<name> (<ident>)"``
    — ``sys._current_frames()`` reaches threads blocked in C calls (the
    abandoned dispatch waiter parked in the runtime shows up here, which
    is exactly the thread a wedge post-mortem needs)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, 'unknown')} ({ident})"
        out[key] = [ln.rstrip("\n")
                    for ln in traceback.format_stack(frame)]
    return out


def _control_plane(context=None) -> dict:
    """Breakers, admission/mb queue depths, brownout — best-effort (each
    piece guarded: a half-torn serving context must not kill the dump)."""
    out: dict = {"breakers": {}, "admission": None, "mb_queue_depth": None,
                 "brownout_level": None, "sheds": None}
    try:
        from orange3_spark_tpu.resilience.overload import (
            current_brownout_level, shed_total, wedge_breaker,
        )

        out["breakers"]["dispatch"] = wedge_breaker().state()
        out["brownout_level"] = current_brownout_level()
        out["sheds"] = shed_total()
    except Exception:  # noqa: BLE001 - diagnostics only
        pass
    try:
        if context is None:
            from orange3_spark_tpu.serve.context import (
                active_serving_context,
            )

            context = active_serving_context()
        if context is not None:
            out["breakers"].update(context.breaker_states())
            adm = getattr(context, "admission", None)
            if adm is not None:
                out["admission"] = {"inflight": adm.inflight,
                                    "queue_depth": adm.queue_depth,
                                    "max_inflight": adm.max_inflight,
                                    "max_queue": adm.max_queue}
            mb = getattr(context, "micro_batcher", None)
            if mb is not None:
                d = mb.diagnostics()    # the batcher's own accessor —
                #                         queue depth + worker liveness
                out["mb_queue_depth"] = d.get("queue_depth")
                out["mb"] = d
    except Exception:  # noqa: BLE001 - diagnostics only
        pass
    try:
        # weighted-fair tenancy (serve/tenancy.py): who was over quota
        # when the incident froze — present only once a tenant exists,
        # so tenant-less bundles keep their exact pre-tenancy shape
        from orange3_spark_tpu.serve.tenancy import tenant_shed_counts

        tenants: dict = {}
        adm = getattr(context, "admission", None) if context else None
        if adm is not None:
            table = adm.tenancy_snapshot()
            if table:
                tenants["fair_share"] = table
        sheds = tenant_shed_counts()
        if sheds:
            tenants["sheds"] = sheds
        if tenants:
            out["tenants"] = tenants
    except Exception:  # noqa: BLE001 - diagnostics only
        pass
    return out


def _event_dict(ev) -> dict:
    ph, name, t_ns, dur_ns, ident, args, trace_id, span_id, parent_id = ev
    d = {"ph": ph, "name": name, "ts_us": round(t_ns / 1e3, 3),
         "thread": ident}
    if ph == "X":
        d["dur_us"] = round(dur_ns / 1e3, 3)
    if args:
        d["args"] = dict(args)
    if trace_id is not None:
        d["trace_id"] = trace_id
        if span_id is not None:
            d["span_id"] = span_id
        if parent_id is not None:
            d["parent_id"] = parent_id
    return d


def collect_bundle(reason: str, error: BaseException | None = None,
                   context=None, **extra) -> dict:
    """Assemble the bundle dict (no IO). Safe to call concurrently with
    active span recording and registry ticks: the ring snapshot copies
    slot references (each slot an immutable tuple) and the registry
    snapshot copies under per-metric locks — no torn reads either way."""
    from orange3_spark_tpu.obs import trace
    from orange3_spark_tpu.obs.context import current_trace_id

    events = [_event_dict(e) for e in trace.events()[-MAX_BUNDLE_EVENTS:]]
    trace_id = getattr(error, "trace_id", None) or current_trace_id()
    bundle = {
        "flight_schema": FLIGHT_SCHEMA_VERSION,
        "written_at": time.time(),
        "pid": os.getpid(),
        "reason": reason,
        "trace_id": trace_id,
        "error": ({"type": type(error).__name__, "message": str(error)}
                  if error is not None else None),
        "events": events,
        "open_spans": trace.open_spans(),
        "slow_traces": trace.slowest_traces(5),
        "registry": REGISTRY.snapshot(),
        "knobs": knobs.resolved(),
        "stacks": thread_stacks(),
    }
    try:
        # device-memory ledger table (obs/prof.py): an OOM-adjacent
        # brownout post-mortem finally names the tenant. Best-effort —
        # and the ledger itself is cheap to snapshot (one lock, no IO)
        from orange3_spark_tpu.obs.prof import LEDGER

        dm = LEDGER.snapshot()
        dm["reconciliation"] = LEDGER.reconcile()
        bundle["device_memory"] = dm
    except Exception:  # noqa: BLE001 - diagnostics only
        pass
    bundle.update(_control_plane(context))
    if extra:
        bundle["extra"] = extra
    return bundle


def _flight_dir() -> str:
    return knobs.get_str("OTPU_FLIGHT_DIR")


def _prune(directory: str, keep: int, prefix: str = "flight") -> None:
    names = sorted(n for n in os.listdir(directory)
                   if n.startswith(prefix + "-") and n.endswith(".json"))
    for n in names[:max(0, len(names) - keep)]:
        try:
            os.remove(os.path.join(directory, n))
        except OSError:
            pass


def debug_bundle(context=None) -> dict:
    """The shared ``GET /debug/flight`` body (obs server AND the fleet
    RPC port): collect one bundle NOW — the manual black-box pull, no
    rate limit, the operator asked — write it, and return it with its
    ``path`` so the caller sees where it landed."""
    bundle = collect_bundle("debug_endpoint", context=context)
    path = dump("debug_endpoint", bundle=bundle)
    bundle["path"] = path
    return bundle


def dump(reason: str, error: BaseException | None = None, *,
         context=None, path: str | None = None, bundle: dict | None = None,
         prefix: str = "flight", **extra) -> str | None:
    """Write one flight bundle NOW; returns its path (None when the
    recorder is disabled). The manual entry point — no rate limit.
    Atomic write (tmp + ``os.replace``): a concurrent reader always sees
    complete, valid JSON. ``bundle`` reuses an already-collected bundle
    (the /debug/flight endpoint collects once, returns AND writes it).
    ``prefix`` names the bundle family — single-process bundles are
    ``flight-*``, the fleet incident recorder (obs/fleetobs.py) writes
    ``fleet-*`` through the same atomic-write + per-family retention."""
    if not flight_enabled():
        return None
    if bundle is None:
        bundle = collect_bundle(reason, error, context, **extra)
    in_flight_dir = path is None
    if in_flight_dir:
        directory = _flight_dir()
        os.makedirs(directory, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:48]
        path = os.path.join(
            directory, f"{prefix}-{time.time_ns()}-{safe}.json")
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)
    except BaseException:
        # a failed write (full disk — exactly auto_dump's swallowed
        # case) must not leave orphan .tmp files retention never prunes
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    _M_BUNDLES.inc(1, reason=reason)
    if in_flight_dir:        # retention applies to OUR directory only —
        #                      an explicit path is the caller's business
        keep = int(knobs.get_int("OTPU_FLIGHT_MAX"))
        if keep > 0:
            _prune(os.path.dirname(path) or ".", keep, prefix)
    return path


def auto_dump(reason: str, error: BaseException | None = None,
              context=None, **extra) -> str | None:
    """The raise-site hook: rate-limited :func:`dump` that NEVER raises —
    an anomaly's flight bundle is best-effort evidence, and a full disk
    or unwritable ``OTPU_FLIGHT_DIR`` must not mask the typed error the
    caller is about to deliver. Returns the path, or None (disabled,
    rate-limited, or write failed)."""
    global _last_auto_dump
    try:
        if not flight_enabled():
            return None
        min_gap = float(knobs.get_float("OTPU_FLIGHT_RATE_S"))
        now = time.monotonic()
        with _rate_lock:
            if _last_auto_dump and now - _last_auto_dump < min_gap:
                return None
            # claim the slot BEFORE the (slow) write: two concurrent
            # anomalies produce one bundle, not a pile-up
            prev, _last_auto_dump = _last_auto_dump, now
        try:
            return dump(reason, error, context=context, **extra)
        except Exception as e:  # noqa: BLE001 - must not mask the anomaly
            log.warning("flight: bundle write failed for %s (%s: %s); "
                        "the anomaly itself is unaffected",
                        reason, type(e).__name__, e)
            # release the claimed slot: one transiently-full disk must
            # not silence the whole incident window's bundles
            with _rate_lock:
                if _last_auto_dump == now:
                    _last_auto_dump = prev
            return None
    except Exception:  # noqa: BLE001 - never raise from a raise site
        return None


def reset_rate_limit() -> None:
    """Tests: forget the last automatic dump time."""
    global _last_auto_dump
    with _rate_lock:
        _last_auto_dump = 0.0
