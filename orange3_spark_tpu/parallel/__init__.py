from orange3_spark_tpu.parallel.collectives import (
    data_parallel_sum,
    distributed_gramian,
    tree_aggregate,
)
from orange3_spark_tpu.parallel.partitioner import (
    BasePartitioner,
    DataParallelPartitioner,
    SPMDPartitioner,
)

__all__ = [
    "data_parallel_sum",
    "distributed_gramian",
    "tree_aggregate",
    "BasePartitioner",
    "DataParallelPartitioner",
    "SPMDPartitioner",
]
