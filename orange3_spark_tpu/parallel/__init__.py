from orange3_spark_tpu.parallel.collectives import (
    data_parallel_sum,
    distributed_gramian,
    tree_aggregate,
)

__all__ = ["data_parallel_sum", "distributed_gramian", "tree_aggregate"]
