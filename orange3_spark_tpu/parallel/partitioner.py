"""SPMD partitioners — one object owns the multi-process training geometry.

The SNIPPETS-[2] pattern: a partitioner owns the mesh, the input/state
shardings and the donated jit wrapper, so estimator code NEVER branches on
process count. ``fit_stream`` already reads everything geometric from its
``TpuSession`` (pad_rows / row_sharding / vector_sharding); a partitioner
therefore plugs in as a session factory plus an ingestion facade:

    part = DataParallelPartitioner()            # owns mesh + session
    src = part.shard_csv(path, "label", n_total=rows, chunk_rows=4096)
    model = est.fit_stream(src, n_features=d, session=part.session)

``DataParallelPartitioner``  — rows split over the ``data`` mesh axis,
state replicated (the LogReg / linear / k-means regime).
``SPMDPartitioner``          — rows over ``data`` AND the hashed embedding
table model-sharded over ``model`` (models/hashed_linear.py shards the
table whenever the session's model axis is wider than 1, so SPMD falls out
of the mesh shape alone).

Kill-switch: under ``OTPU_MULTIHOST=0`` every partitioner degrades to an
inert facade over the current single-process session — same mesh, plain
``device_put``, identity sources: the pre-multihost path, bitwise.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import numpy as np

from orange3_spark_tpu.core.session import DATA_AXIS, MODEL_AXIS, TpuSession
from orange3_spark_tpu.io.multihost import put_sharded
from orange3_spark_tpu.utils import knobs

__all__ = ["BasePartitioner", "DataParallelPartitioner", "SPMDPartitioner"]


class BasePartitioner:
    """Mesh + shardings + donated-dispatch owner (SNIPPETS-[2] style)."""

    data_axis = DATA_AXIS
    model_axis = MODEL_AXIS

    #: state-dict keys whose leading dim shards over the model axis (the
    #: hashed table); everything else replicates
    model_sharded_keys: tuple = ()

    def __init__(self, devices: Sequence[jax.Device] | None = None):
        self.enabled = knobs.get_bool("OTPU_MULTIHOST")
        if not self.enabled:
            # kill-switch: facade over the active single-process session —
            # same mesh and placements the estimators use today, bitwise
            self.session = TpuSession.builder_get_or_create()
            self.mesh = self.session.mesh
            return
        devs = list(devices if devices is not None else jax.devices())
        self.mesh = self._build_mesh(devs)
        self.session = TpuSession(self.mesh)

    # ------------------------------------------------------------- geometry
    def _build_mesh(self, devices: list):
        raise NotImplementedError

    @property
    def n_processes(self) -> int:
        return jax.process_count()

    # ------------------------------------------------------------ shardings
    def state_sharding(self, name: str, value) -> Any:
        """Placement for one optimizer/model state leaf (by dict key)."""
        if (self.enabled and name in self.model_sharded_keys
                and np.ndim(value) >= 2
                and self.session.model_axis is not None):
            return self.session.sharding(self.model_axis, None)
        return self.session.replicated

    # ------------------------------------------------------------ placement
    def shard_batch(self, X, y=None, w=None):
        """Per-host row blocks -> global sharded device arrays.

        Single-process: plain ``device_put`` (the kill-switch path).
        Multi-process: every gang member contributes its block and
        ``put_sharded`` assembles the global array (typed ragged-block
        validation included)."""
        s = self.session
        out = [put_sharded(np.ascontiguousarray(X), s.row_sharding)]
        for v in (y, w):
            out.append(None if v is None
                       else put_sharded(np.ascontiguousarray(v),
                                        s.vector_sharding))
        return tuple(out)

    def shard_state(self, state: dict) -> dict:
        """Place a (possibly nested) state dict: model-sharded keys over
        the ``model`` axis, everything else replicated on this mesh."""
        def place(name, v):
            if isinstance(v, dict):
                return {k: place(k, x) for k, x in v.items()}
            return jax.device_put(v, self.state_sharding(name, v))
        return {k: place(k, v) for k, v in state.items()}

    def partition(self, step_fn: Callable, *,
                  donate_state: bool = True) -> Callable:
        """Donated jit wrapper for ``step_fn(state, *batch)``.

        The shardings travel on the arrays themselves (``shard_state`` /
        ``shard_batch`` commit the placements), so the wrapper adds the
        one thing arrays can't carry: DONATION of positional arg 0 — XLA
        reuses the sharded optimizer-state buffers in place across steps,
        exactly like the estimators' ``donating_jit``."""
        return jax.jit(step_fn,
                       donate_argnums=(0,) if donate_state else ())

    # ------------------------------------------------------------ ingestion
    def shard_csv(self, path, class_col: str = "", *, n_total: int,
                  chunk_rows: int = 1 << 20, **kw) -> Callable:
        """Per-host CSV source in this partitioner's geometry: each process
        parses only its row block, lockstep-padded (inert single-file
        pass-through under the kill-switch)."""
        from orange3_spark_tpu.io.streaming import sharded_csv_chunk_source
        return sharded_csv_chunk_source(
            path, class_col, shard_total_rows=n_total,
            chunk_rows=chunk_rows, **kw)

    def shard_parquet(self, path, class_col: str = "", *,
                      chunk_rows: int = 1 << 20, **kw) -> Callable:
        """Per-host parquet source: this process's contiguous row-group
        range (Spark's parquet input splits; inert under the
        kill-switch)."""
        from orange3_spark_tpu.io.streaming import parquet_chunk_source
        return parquet_chunk_source(path, class_col, chunk_rows=chunk_rows,
                                    shard=True, **kw)


class DataParallelPartitioner(BasePartitioner):
    """Rows over ``data``, state replicated — LogReg/linear/k-means."""

    def _build_mesh(self, devices: list):
        return TpuSession.default_mesh(devices)


class SPMDPartitioner(BasePartitioner):
    """Rows over ``data`` AND the hashed embedding table sharded over
    ``model``: mesh (n_devices // model_parallel, model_parallel). The
    estimators pick the table sharding up from the mesh shape alone
    (models/hashed_linear.py), so SPMD needs no estimator changes."""

    model_sharded_keys = ("emb",)

    def __init__(self, devices: Sequence[jax.Device] | None = None, *,
                 model_parallel: int = 2):
        self.model_parallel = int(model_parallel)
        super().__init__(devices)

    def _build_mesh(self, devices: list):
        from jax.sharding import Mesh
        mp = self.model_parallel
        n = len(devices)
        if mp < 1 or n % mp:
            raise ValueError(
                f"SPMDPartitioner: model_parallel={mp} does not divide "
                f"the {n}-device pod")
        return Mesh(np.asarray(devices).reshape(n // mp, mp),
                    (DATA_AXIS, MODEL_AXIS))
