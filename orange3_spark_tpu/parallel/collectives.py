"""treeAggregate → psum: the distributed reduction backbone.

Spark's MLlib drives every iterative fit through ``RDD.treeAggregate`` — a
multi-level shuffle reduce over executors (SURVEY.md §2b "Collectives
backend"; reconstructed, mount empty). On TPU the same role is played by XLA
collectives over ICI: ``lax.psum`` under ``shard_map`` for explicit SPMD, or
GSPMD-inserted all-reduces when a jitted computation consumes P('data')
-sharded rows and produces replicated outputs. Both paths are provided:

* ``tree_aggregate`` — explicit shard_map+psum, the literal treeAggregate
  analogue, for callers that want hand-controlled SPMD;
* plain jit + NamedSharding inputs everywhere else — idiomatic GSPMD, letting
  XLA choose reduce-scatter/all-reduce scheduling on the ICI torus.

There is deliberately NO custom transport layer (no NCCL/MPI translation):
the mesh + collectives ARE the communication backend, multi-host included
(same program, DCN-spanning mesh).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # newer jax exports shard_map at the top level
    _shard_map = jax.shard_map
except AttributeError:  # 0.4.x keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map

from orange3_spark_tpu.core.session import TpuSession


def tree_aggregate(
    seq_op: Callable[..., Any],
    *arrays,
    session: TpuSession | None = None,
):
    """Per-shard map + global psum — MLlib ``treeAggregate(zero, seqOp, combOp)``.

    ``seq_op`` receives each array's local shard (rows on this device) and
    returns a pytree of partial sums; the pytree is psum'd over the data axis
    and returned replicated. All arrays must be row-sharded P('data', ...).
    """
    session = session or TpuSession.active()
    axis = session.data_axis

    def shard_fn(*shards):
        partial_sums = seq_op(*shards)
        return jax.tree.map(lambda x: jax.lax.psum(x, axis), partial_sums)

    specs = tuple(P(axis) if a.ndim == 1 else P(axis, *(None,) * (a.ndim - 1))
                  for a in arrays)
    return _shard_map(
        shard_fn, mesh=session.mesh, in_specs=specs, out_specs=P()
    )(*arrays)


def data_parallel_sum(values, session: TpuSession | None = None):
    """Sum row-sharded arrays over all rows, returning replicated results."""
    return tree_aggregate(
        lambda *xs: tuple(jnp.sum(x, axis=0) for x in xs), *values,
        session=session,
    )


@partial(jax.jit, static_argnames=("center",))
def _gramian_kernel(X, W, center: bool):
    from orange3_spark_tpu.ops.stats import weighted_moments

    w = W[:, None]
    mean, _, tot = weighted_moments(X, W)
    Xc = X - mean if center else X  # center is trace-time static
    # (d,d) matmul contraction over the sharded row axis — GSPMD turns this
    # into local matmuls + one all-reduce over ICI (the treeAggregate moment).
    G = (Xc * w).T @ Xc
    return G, mean, tot


def distributed_gramian(X, W, center: bool = True):
    """Weighted Gramian  Xᶜᵀ diag(W) Xᶜ  with one ICI all-reduce.

    The building block for PCA (covariance eigendecomposition) and linear
    model normal equations, replacing MLlib's RowMatrix.computeGramianMatrix.
    Returns (G, mean, total_weight), all replicated.
    """
    return _gramian_kernel(X, W, center)
