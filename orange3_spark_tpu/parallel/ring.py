"""Sequence/context parallelism: ring attention and all-to-all (Ulysses)
attention over a mesh axis.

The reference stack is tabular (no transformer path — SURVEY.md §5 "absent
in the reference"), but the framework's parallel substrate must handle
long-sequence workloads at the same scale its distributed runtime targets,
so these are core ``parallel/`` primitives, not model code:

* ``ring_attention`` — sequence axis sharded over the mesh; K/V blocks
  rotate around the ring with ``jax.lax.ppermute`` (ICI neighbor hops, no
  all-gather memory spike) while each device folds one block per hop into a
  flash-style online softmax (running max / normalizer / accumulator).
  Memory per device: O(S_local·S_local) scores — never the full S×S.
  Causal masking uses global block offsets from ``jax.lax.axis_index``.
* ``ulysses_attention`` — the all-to-all alternative: reshard sequence →
  heads with one ``all_to_all``, run dense local attention over the FULL
  sequence for the local head group, reshard back. One collective pair per
  call; best when n_heads % axis_size == 0 and S×S fits per device.

Both run under ``shard_map`` over a named mesh axis and are differentiable
(pure jnp + collectives, so jax.grad traces through the ppermute ring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # newer jax exports shard_map at the top level
    _shard_map = jax.shard_map
except AttributeError:  # 0.4.x keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map


def _online_block(q, k, v, m, l, o, mask):
    """Fold one K/V block into the flash accumulator (q: [B,Sq,H,Dh])."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)                              # [B,H,Sq]
    m_new = jnp.maximum(m, m_blk)
    # guard -inf - -inf (fully masked row so far)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_new, -jnp.inf))
    p = jnp.exp(jnp.where(jnp.isfinite(s), s - m_new[..., None], -jnp.inf))
    l_new = alpha * l + jnp.sum(p, axis=-1)
    o_new = alpha[..., None] * o + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v, preferred_element_type=jnp.float32
    )
    return m_new, l_new, o_new


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp", *, causal: bool = False):
    """Attention with Q/K/V sharded over ``axis`` along the sequence dim.

    q, k, v: f32[batch, seq, heads, head_dim] (seq divisible by axis size).
    Returns the attention output with the same sharding.
    """
    n = mesh.shape[axis]
    spec = P(None, axis, None, None)

    def local(qb, kb, vb):
        # qb/kb/vb: [B, S_loc, H, Dh] — this device's sequence block
        idx = jax.lax.axis_index(axis)
        b, s_loc, h, dh = qb.shape
        # mark the accumulators device-varying for the manual-axes carry check
        # (they start as replicated literals but each device's values diverge);
        # older jax has neither pcast nor the check — pass through unchanged
        _pcast = getattr(jax.lax, "pcast", None)

        def _varying(x):
            return _pcast(x, (axis,), to="varying") if _pcast else x

        m = _varying(jnp.full((b, h, s_loc), -jnp.inf, jnp.float32))
        l = _varying(jnp.zeros((b, h, s_loc), jnp.float32))
        o = _varying(jnp.zeros((b, h, s_loc, dh), jnp.float32))
        q_pos = idx * s_loc + jnp.arange(s_loc)              # global Q rows

        def block_mask(t):
            if not causal:
                return jnp.ones((1, 1, s_loc, s_loc), bool)
            src_idx = (idx - t) % n                          # whose block this is
            k_pos = src_idx * s_loc + jnp.arange(s_loc)
            return (q_pos[:, None] >= k_pos[None, :])[None, None, :, :]

        def fold(t, m, l, o, kb, vb):
            return _online_block(qb, kb, vb, m, l, o, block_mask(t))

        def hop(t, carry):
            m, l, o, kb, vb = carry
            m, l, o = fold(t, m, l, o, kb, vb)
            # rotate K/V one step around the ring (neighbor ICI hop)
            perm = [(i, (i + 1) % n) for i in range(n)]
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return m, l, o, kb, vb

        # n-1 fold+rotate hops, then fold the final block WITHOUT rotating —
        # the last ppermute's result would be discarded, but as a loop carry
        # XLA could not DCE the send/recv pair
        m, l, o, kb, vb = jax.lax.fori_loop(0, n - 1, hop, (m, l, o, kb, vb))
        m, l, o = fold(n - 1, m, l, o, kb, vb)
        out = o / jnp.maximum(l[..., None], 1e-30)           # [B,H,Sq,Dh]
        return out.transpose(0, 2, 1, 3)                     # [B,Sq,H,Dh]

    return _shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp", *,
                      causal: bool = False):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern).

    Seq-sharded [B, S/n, H, Dh] --all_to_all--> head-sharded [B, S, H/n, Dh],
    dense local attention over the full sequence, then all_to_all back.
    Requires heads % axis_size == 0.
    """
    n = mesh.shape[axis]
    if q.shape[2] % n != 0:
        raise ValueError(f"heads={q.shape[2]} not divisible by {axis} size {n}")
    spec = P(None, axis, None, None)

    def local(qb, kb, vb):
        # [B, S_loc, H, Dh] -> [B, S, H_loc, Dh]: split heads, gather seq
        def seq_to_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        qh, kh, vh = seq_to_heads(qb), seq_to_heads(kb), seq_to_heads(vb)
        return heads_to_seq(_dense_attention(qh, kh, vh, causal=causal))

    return _shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)


def _dense_attention(q, k, v, *, causal: bool = False):
    """Scaled dot-product attention over full [B,S,H,Dh] operands."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v,
                      preferred_element_type=jnp.float32)


def reference_attention(q, k, v, *, causal: bool = False):
    """Single-device dense attention (numerics oracle for the tests)."""
    return _dense_attention(q, k, v, causal=causal)
