"""Fleet-style multi-process training coordinator (docs/multihost.md).

``MultihostLauncher`` is to TRAINING what ``fleet/supervisor.py`` is to
serving: it spawns N training processes as one GANG rendezvousing through
``jax.distributed.initialize``, watches them, and owns the cross-host
resilience ladder —

  * a process that dies (crash, SIGKILL, OOM) is detected TYPED within the
    poll interval: the collective the survivors are blocked in can never
    complete, so the launcher kills the remainder of the gang instead of
    letting it hang (the PR-6 watchdog pattern, applied across processes);
  * the whole gang restarts after a seeded exponential backoff
    (``resilience/retry.py RetryPolicy`` — the supervisor's schedule), up
    to ``OTPU_MULTIHOST_RESTARTS`` times;
  * before each restart the per-rank epoch-boundary checkpoints are
    ALIGNED to the newest step every rank holds (a kill can land between
    two ranks' saves) so the resumed gang re-enters lockstep at one common
    step — each worker's shard source then fast-forwards through the
    replayed prefix exactly like ``resilient_source`` replays a lost
    chunk;
  * a gang still running past ``OTPU_MULTIHOST_WALL_S`` is a WEDGE, not
    a slow fit: it is killed and counted as a lost host.

Budget exhausted -> :class:`HostLostError` (typed, carrying the rank, exit
code and log tail) — never a hang.

``cross_process_collectives_supported()`` is the ONE probe for "can this
jaxlib actually run a cross-process CPU computation" — tests and the bench
all route through it (its reason string is the canonical skip message,
naming the jaxlib version).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import shutil
import socket
import subprocess
import sys
import tempfile
import time

from orange3_spark_tpu.obs.registry import REGISTRY
from orange3_spark_tpu.resilience.retry import RetryPolicy
from orange3_spark_tpu.utils import knobs
from orange3_spark_tpu.utils.procs import kill_process_group

__all__ = ["HostLostError", "GangResult", "MultihostLauncher",
           "cross_process_collectives_supported"]

_M_GANGS = REGISTRY.counter(
    "otpu_multihost_gang_starts_total",
    "Training-gang launches (initial attempts plus restarts).")
_M_LOST = REGISTRY.counter(
    "otpu_multihost_hosts_lost_total",
    "Training processes lost mid-gang (crash/SIGKILL/wall-budget wedge).")
_M_RESTARTS = REGISTRY.counter(
    "otpu_multihost_gang_restarts_total",
    "Gang restarts taken after a lost host (resume from aligned "
    "epoch-boundary checkpoints).")


class HostLostError(RuntimeError):
    """A training host died (or wedged) and the restart budget is spent.

    Typed — the launcher never lets a dead rank surface as a hang: the
    surviving ranks' collectives are killed with it. Carries the first
    failed ``rank`` (-1 for a wall-budget wedge with no dead process),
    its exit code, the restarts already taken, and the rank's log tail."""

    def __init__(self, rank: int, returncode, restarts: int, tail: str = ""):
        self.rank, self.returncode, self.restarts = rank, returncode, restarts
        self.tail = tail
        what = (f"wedged past the OTPU_MULTIHOST_WALL_S budget"
                if rank < 0 else
                f"rank {rank} exited {returncode}")
        super().__init__(
            f"multihost gang lost: {what} after {restarts} gang "
            f"restart(s); log tail:\n{tail}")


@dataclasses.dataclass
class GangResult:
    """One successful gang run (possibly after restarts)."""
    n_processes: int
    gang_starts: int
    gang_restarts: int
    hosts_lost: int
    wall_s: float
    coord_addr: str
    log_paths: list


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _tail(path: str, n_bytes: int = 2000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(0, f.tell() - n_bytes))
            return f.read().decode(errors="replace")
    except OSError:
        return ""


class MultihostLauncher:
    """Spawn and supervise one N-process training gang.

    ``argv_for_rank(rank, n_processes, coord_addr) -> list[str]`` builds
    each rank's command line (usually ``python -m
    orange3_spark_tpu.parallel.mh_worker ...``). Ranks log to per-rank
    files under ``log_dir`` (pipes would deadlock a chatty gang)."""

    def __init__(self, argv_for_rank, n_processes: int | None = None, *,
                 env: dict | None = None, log_dir: str | None = None,
                 max_gang_restarts: int | None = None,
                 wall_s: float | None = None,
                 coord_port: int | None = None,
                 align_ckpt_dir: str | None = None,
                 poll_s: float = 0.05, seed: int = 0):
        self.argv_for_rank = argv_for_rank
        self.n = int(n_processes
                     or (knobs.get_int("OTPU_MULTIHOST_PROCS") or 2))
        self.env = dict(env) if env is not None else dict(os.environ)
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="otpu-mh-")
        os.makedirs(self.log_dir, exist_ok=True)
        self.max_gang_restarts = (knobs.get_int("OTPU_MULTIHOST_RESTARTS")
                                  if max_gang_restarts is None
                                  else int(max_gang_restarts))
        self.wall_s = (knobs.get_float("OTPU_MULTIHOST_WALL_S")
                       if wall_s is None else float(wall_s))
        self.coord_port = (knobs.get_int("OTPU_MULTIHOST_COORD_PORT")
                           if coord_port is None else int(coord_port))
        self.align_ckpt_dir = align_ckpt_dir
        self.poll_s = poll_s
        # the supervisor's seeded backoff schedule, one ladder per gang
        self._policy = RetryPolicy.from_env(seed=seed)

    # ------------------------------------------------------------ restarts
    @staticmethod
    def align_checkpoints(ckpt_dir: str, n_processes: int) -> int:
        """Coordinated-resume rule: every rank must re-enter the gang at
        ONE common step (a kill can land after rank 0's epoch save but
        before rank 1's — mismatched resume points diverge the lockstep
        collectives). The common step is the newest one ALL ranks can
        reach: the minimum saved step. A rank holding a different step
        gets a COPY of a common-step donor snapshot — legal because the
        data-parallel optimizer state is replicated, so any rank's
        snapshot at step S is every rank's state at step S. If no rank
        holds a usable snapshot (common == 0) all checkpoints are
        dropped and the gang restarts from scratch. Returns the common
        step."""
        steps = {}
        for rank in range(n_processes):
            path = os.path.join(ckpt_dir, f"rank{rank}.ckpt")
            try:
                with open(path, "rb") as f:
                    steps[path] = int(pickle.load(f)["step"])
            except (OSError, KeyError, ValueError, EOFError,
                    pickle.UnpicklingError):
                steps[path] = 0
        common = min(steps.values()) if steps else 0
        if common == 0:
            for path in steps:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            return 0
        donor = next(p for p, s in steps.items() if s == common)
        for path, step in steps.items():
            if step != common:
                shutil.copyfile(donor, path)
        return common

    # ----------------------------------------------------------------- run
    def run(self) -> GangResult:
        t0 = time.perf_counter()
        restarts = lost = 0
        log_paths = [os.path.join(self.log_dir, f"rank{r}.log")
                     for r in range(self.n)]
        while True:
            _M_GANGS.inc()
            port = self.coord_port or _free_port()
            coord = f"127.0.0.1:{port}"
            procs, logs = [], []
            try:
                for r in range(self.n):
                    f = open(log_paths[r], "ab")
                    logs.append(f)
                    procs.append(subprocess.Popen(
                        self.argv_for_rank(r, self.n, coord),
                        stdout=f, stderr=subprocess.STDOUT,
                        env=self.env, start_new_session=True))
                failed_rank, failed_rc = self._watch(procs)
            finally:
                for p in procs:
                    if p.poll() is None:
                        kill_process_group(p, grace_s=0.0, drain_s=2.0)
                for f in logs:
                    f.close()
            if failed_rank is None:
                return GangResult(
                    n_processes=self.n,
                    gang_starts=restarts + 1,
                    gang_restarts=restarts,
                    hosts_lost=lost,
                    wall_s=round(time.perf_counter() - t0, 3),
                    coord_addr=coord,
                    log_paths=log_paths)
            lost += 1
            _M_LOST.inc()
            tail = _tail(log_paths[max(failed_rank, 0)])
            if restarts >= self.max_gang_restarts:
                raise HostLostError(failed_rank, failed_rc, restarts, tail)
            _M_RESTARTS.inc()
            if self.align_ckpt_dir:
                self.align_checkpoints(self.align_ckpt_dir, self.n)
            time.sleep(self._policy.delay(restarts))
            restarts += 1

    def _watch(self, procs) -> tuple:
        """Poll the gang. Returns ``(None, None)`` when every rank exited
        0; otherwise the first failed rank and its exit code (``(-1,
        None)`` for a wall-budget wedge)."""
        deadline = time.monotonic() + self.wall_s
        while True:
            codes = [p.poll() for p in procs]
            for r, rc in enumerate(codes):
                if rc is not None and rc != 0:
                    return r, rc
            if all(rc == 0 for rc in codes):
                return None, None
            if time.monotonic() >= deadline:
                return -1, None
            time.sleep(self.poll_s)


# ===================================================== capability probe

_PROBE_SRC = r"""
import os, sys
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
rank, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n,
                           process_id=rank)
from jax.sharding import Mesh, NamedSharding, PartitionSpec
devs = np.asarray(jax.devices())
mesh = Mesh(devs, ("data",))
sh = NamedSharding(mesh, PartitionSpec("data"))
local = np.arange(len(jax.local_devices()), dtype=np.float32) + 1.0
g = jax.make_array_from_process_local_data(sh, local)
out = float(jax.jit(lambda a: a.sum())(g))
print("OTPU_PROBE xproc sum", out, flush=True)
"""

#: the definitive can't-ever-work signature (vs a transient sandbox error)
_DEFINITIVE = "aren't implemented on the CPU backend"


def _probe_cache_path() -> str:
    import jaxlib
    ver = getattr(jaxlib, "__version__", "unknown")
    key = hashlib.sha1(_PROBE_SRC.encode()).hexdigest()[:8]
    return os.path.join(
        tempfile.gettempdir(),
        f"otpu_xproc_{os.getuid()}_{ver}_{key}.json")


def cross_process_collectives_supported(*, force_refresh: bool = False):
    """-> ``(ok, reason)``: can this jaxlib run a REAL cross-process CPU
    computation? Probes once with a 2-process gang (``jax.distributed``
    bring-up + global assembly + one jitted all-device sum) and caches
    the verdict per jaxlib version in the tempdir (own-uid files only —
    the conftest XLA-flag probe's trust protocol). A negative verdict is
    cached only on the definitive "not implemented on this backend"
    signature so a transient sandbox failure re-probes next run.

    ``reason`` names the jaxlib version — it is THE skip message for
    every true-multi-process test, and the bench's fallback-mode note."""
    import jaxlib
    ver = getattr(jaxlib, "__version__", "unknown")
    cache = _probe_cache_path()
    if not force_refresh:
        try:
            if os.stat(cache).st_uid == os.getuid():
                with open(cache) as f:
                    d = json.load(f)
                return bool(d["ok"]), str(d.get("reason", ""))
        except (OSError, ValueError, KeyError):
            pass

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p)
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PROBE_SRC, str(i), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=env, start_new_session=True) for i in range(2)]
    outs, timed_out = [], False
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode(errors="replace"))
        except subprocess.TimeoutExpired:
            timed_out = True
            kill_process_group(p, grace_s=0.0, drain_s=2.0)
            outs.append("<probe timeout>")
    ok = (not timed_out) and all(p.returncode == 0 for p in procs)
    if ok:
        reason = ""
    else:
        tail = "\n".join(o.strip()[-400:] for o in outs)
        reason = (f"jaxlib {ver} cannot run cross-process CPU "
                  f"collectives: {tail}")
    definitive = ok or any(_DEFINITIVE in o for o in outs)
    if definitive:
        tmp = cache + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"ok": ok, "reason": reason}, f)
            os.replace(tmp, cache)
        except OSError:
            pass
    return ok, reason
