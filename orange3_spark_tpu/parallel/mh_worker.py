"""One rank of a MultihostLauncher training gang.

Run as::

    python -m orange3_spark_tpu.parallel.mh_worker \
        --rank R --nprocs N --coord HOST:PORT \
        --csv data.csv --class-col y --n-total ROWS --n-features D \
        --chunk-rows C --epochs E --step-size LR --out-dir OUT \
        [--ckpt-dir CK] [--die-after-saves K] [--model-parallel MP]

Each rank: ``jax.distributed.initialize`` (when N > 1), builds a
``DataParallelPartitioner`` (or ``SPMDPartitioner`` with
``--model-parallel``), streams ONLY its row block of the shared CSV
through ``sharded_csv_chunk_source``, and runs the ordinary
``StreamingLinearEstimator.fit_stream`` — the estimator never knows how
many processes exist. Epoch-boundary checkpoints (``--ckpt-dir``) are the
gang's resume points; rank 0 writes ``theta.npz`` and every rank writes
``host_R.json`` carrying its goodput/ledger attribution (the PR-12 digest
the bench folds per host).

``--die-after-saves K`` arms the lost-host DRILL: the rank SIGKILLs its
own process right after its K-th checkpoint save lands — but only on a
run that started from scratch (a ``rankR.died`` marker disarms the bomb
after the restart, so the drill kills exactly once).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def _parse(argv):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--coord", default="")
    ap.add_argument("--csv", required=True)
    ap.add_argument("--class-col", default="y")
    ap.add_argument("--n-total", type=int, required=True)
    ap.add_argument("--n-features", type=int, required=True)
    ap.add_argument("--chunk-rows", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--step-size", type=float, default=0.1)
    ap.add_argument("--out-dir", required=True)
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--die-after-saves", type=int, default=0)
    ap.add_argument("--model-parallel", type=int, default=0)
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = _parse(argv)
    sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
    import jax

    if args.nprocs > 1:
        jax.distributed.initialize(args.coord, num_processes=args.nprocs,
                                   process_id=args.rank)
    import numpy as np

    from orange3_spark_tpu.io.streaming import (StreamingLinearEstimator,
                                                sharded_csv_chunk_source)
    from orange3_spark_tpu.parallel.partitioner import (
        DataParallelPartitioner, SPMDPartitioner)
    from orange3_spark_tpu.utils.fault import StreamCheckpointer

    part = (SPMDPartitioner(model_parallel=args.model_parallel)
            if args.model_parallel > 1 else DataParallelPartitioner())
    src = part.shard_csv(args.csv, args.class_col, n_total=args.n_total,
                         chunk_rows=args.chunk_rows)

    ck, resumed_from = None, 0
    if args.ckpt_dir:
        os.makedirs(args.ckpt_dir, exist_ok=True)
        path = os.path.join(args.ckpt_dir, f"rank{args.rank}.ckpt")
        ck = StreamCheckpointer(path, every_steps=10 ** 9)
        resumed_from = ck.load()[0]
        marker = os.path.join(args.ckpt_dir, f"rank{args.rank}.died")
        if args.die_after_saves > 0 and not os.path.exists(marker):
            # the drill bomb: die right AFTER the Kth epoch snapshot
            # lands on disk (atomic rename done), the worst-case instant
            # for the rest of the gang
            ck = _DieAfterSaves(path, every_steps=10 ** 9,
                                after=args.die_after_saves, marker=marker)

    est = StreamingLinearEstimator(
        loss="logistic", epochs=args.epochs, step_size=args.step_size,
        chunk_rows=args.chunk_rows, replay_granularity="epoch",
        checkpoint_every_epochs=1 if ck is not None else 0)
    t0 = time.perf_counter()
    model = est.fit_stream(src, n_features=args.n_features,
                           session=part.session, cache_device=True,
                           checkpointer=ck)
    jax.block_until_ready(model.coef)
    wall = time.perf_counter() - t0

    os.makedirs(args.out_dir, exist_ok=True)
    report = getattr(model, "run_report_", None)
    rep = report.to_dict() if report is not None else {}
    host = {
        "rank": args.rank,
        "nprocs": args.nprocs,
        "rows_local": args.n_total // max(1, args.nprocs),
        "n_steps": int(model.n_steps_),
        "fit_wall_s": round(wall, 4),
        "resumed_from_step": int(resumed_from),
        "goodput": rep.get("goodput", {}),
        "device_memory": rep.get("device_memory", {}),
    }
    with open(os.path.join(args.out_dir, f"host_{args.rank}.json"),
              "w") as f:
        json.dump(host, f)
    if args.rank == 0:
        np.savez(os.path.join(args.out_dir, "theta.npz"),
                 coef=np.asarray(model.coef),
                 intercept=np.asarray(model.intercept),
                 n_steps=np.asarray(model.n_steps_))
    print(f"OTPU_LIVE mh_worker rank={args.rank} steps={model.n_steps_} "
          f"wall={wall:.3f}s resumed_from={resumed_from}", flush=True)
    return 0


def _die_now(marker: str) -> None:
    with open(marker, "w") as f:
        f.write("killed by --die-after-saves\n")
    os.kill(os.getpid(), signal.SIGKILL)


class _DieAfterSaves:
    """Checkpointer proxy that SIGKILLs the process right after its
    ``after``-th save completes — the drill's fault injector (the marker
    file is written FIRST so the restarted run disarms)."""

    def __init__(self, path: str, *, every_steps: int, after: int,
                 marker: str):
        from orange3_spark_tpu.utils.fault import StreamCheckpointer
        self._inner = StreamCheckpointer(path, every_steps=every_steps)
        self.path = self._inner.path
        self.every_steps = self._inner.every_steps
        self._after = after
        self._saves = 0
        self._marker = marker

    def save(self, step, state, meta=None):
        self._inner.save(step, state, meta)
        self._saves += 1
        if self._saves >= self._after:
            _die_now(self._marker)

    def maybe_save(self, step, state, meta=None):
        if step % self.every_steps != 0:
            return False
        self.save(step, state, meta)
        return True

    def load(self, expect_meta=None):
        return self._inner.load(expect_meta)

    def delete(self):
        self._inner.delete()


if __name__ == "__main__":
    sys.exit(main())
