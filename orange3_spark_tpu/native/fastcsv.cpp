// fastcsv — multithreaded CSV -> float32 columnar chunks.
//
// Plays the role of Spark's native ingest substrate (the JVM CSV reader +
// Tungsten columnar memory behind `spark.read.csv`; SURVEY.md §2b "Data
// ingest" — reconstructed, reference mount empty). The TPU framework's hot
// ingest path must keep the single host core from becoming the bottleneck
// between disk and `jax.device_put`, so parsing is:
//
//   * chunked: the file is read in large blocks clipped to line boundaries,
//     so a 1B-row file streams through a fixed host-memory window
//     (out-of-core — the NYC-Taxi/Criteo configs never fit in RAM);
//   * parallel: each chunk's rows are split across threads; every thread
//     writes disjoint [row, col] slots of the caller's buffer, no locks;
//   * allocation-free in steady state: one pass memchr's newline offsets,
//     then a hand-rolled float parser (no strtof locale machinery) fills
//     the row-major float32 buffer the Python side hands in (which is the
//     exact layout device_put wants for P('data', None) sharding).
//
// C API only (extern "C") — bound from Python with ctypes; no pybind11.
//
// Dialect: RFC-4180-ish. Quoted cells may contain the delimiter ("" escapes
// a quote); numeric quoted content parses, text becomes NaN. Embedded
// NEWLINES inside quoted cells are NOT supported (the chunker's newline scan
// is quote-blind by design — it is what keeps chunk splitting O(memchr)) —
// use io/readers.py (pyarrow) for such files.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <string>
#include <thread>
#include <vector>

namespace {

struct CsvHandle {
  FILE* f = nullptr;
  char delim = ',';
  std::vector<std::string> colnames;
  int ncols = 0;
  // carry: bytes of a trailing partial line from the previous block
  std::vector<char> carry;
  bool eof = false;
  long rows_read = 0;
};

// fast float parser: [-+]?digits[.digits][(e|E)[-+]digits]; NaN on garbage.
// Returns value, advances *p to the first unconsumed char.
inline float parse_float(const char* p, const char* end, const char** out) {
  const char* s = p;
  while (s < end && (*s == ' ' || *s == '\t')) ++s;
  bool neg = false;
  if (s < end && (*s == '-' || *s == '+')) { neg = (*s == '-'); ++s; }
  double val = 0.0;
  bool any = false;
  while (s < end && *s >= '0' && *s <= '9') {
    val = val * 10.0 + (*s - '0');
    any = true;
    ++s;
  }
  if (s < end && *s == '.') {
    ++s;
    double frac = 0.1;
    while (s < end && *s >= '0' && *s <= '9') {
      val += (*s - '0') * frac;
      frac *= 0.1;
      any = true;
      ++s;
    }
  }
  if (any && s < end && (*s == 'e' || *s == 'E')) {
    const char* es = s + 1;
    bool eneg = false;
    if (es < end && (*es == '-' || *es == '+')) { eneg = (*es == '-'); ++es; }
    int ev = 0;
    bool eany = false;
    while (es < end && *es >= '0' && *es <= '9') {
      ev = ev * 10 + (*es - '0');
      eany = true;
      ++es;
    }
    if (eany) {
      val *= std::pow(10.0, eneg ? -ev : ev);
      s = es;
    }
  }
  *out = s;
  if (!any) return std::nanf("");
  return static_cast<float>(neg ? -val : val);
}

// parse rows [r0, r1) given newline offsets; writes out[row*ncols + col].
void parse_rows(const char* buf, const std::vector<size_t>& starts,
                const std::vector<size_t>& ends, size_t r0, size_t r1,
                int ncols, char delim, float* out) {
  for (size_t r = r0; r < r1; ++r) {
    const char* p = buf + starts[r];
    const char* end = buf + ends[r];
    float* row = out + r * ncols;
    int c = 0;
    while (c < ncols) {
      const char* next;
      if (p < end && *p == '"') {
        // quoted cell: delimiters inside the quotes belong to the cell
        // ("" escapes a quote). Numeric content still parses; text -> NaN.
        const char* q = p + 1;
        row[c] = parse_float(q, end, &next);
        while (q < end) {
          if (*q == '"') {
            if (q + 1 < end && q[1] == '"') { q += 2; continue; }
            ++q;  // closing quote
            break;
          }
          ++q;
        }
        p = q;
      } else {
        row[c] = parse_float(p, end, &next);
        p = next;
      }
      // skip to the delimiter (unquoted junk until the delimiter belongs to
      // this cell; non-numeric cells came back NaN)
      while (p < end && *p != delim) ++p;
      if (p < end) ++p;  // eat delimiter
      ++c;
      if (p >= end) break;
    }
    for (; c < ncols; ++c) row[c] = std::nanf("");
  }
}

}  // namespace

extern "C" {

void* fcsv_open(const char* path, char delim, int header) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  auto* h = new CsvHandle();
  h->f = f;
  h->delim = delim;
  // read the first line for the schema (names or column count)
  std::string line;
  int ch;
  while ((ch = std::fgetc(f)) != EOF && ch != '\n') line.push_back((char)ch);
  if (!line.empty() && line.back() == '\r') line.pop_back();
  int ncols = 1;
  for (char c : line) ncols += (c == delim);
  h->ncols = ncols;
  size_t start = 0;
  for (int j = 0; j < ncols; ++j) {
    size_t pos = line.find(delim, start);
    std::string name = line.substr(
        start, pos == std::string::npos ? std::string::npos : pos - start);
    h->colnames.push_back(header ? name : ("c" + std::to_string(j)));
    start = (pos == std::string::npos) ? line.size() : pos + 1;
  }
  if (!header) {
    // first line was data — replay it through the carry buffer
    h->carry.assign(line.begin(), line.end());
    h->carry.push_back('\n');
  }
  return h;
}

int fcsv_ncols(void* hv) { return static_cast<CsvHandle*>(hv)->ncols; }

const char* fcsv_colname(void* hv, int j) {
  auto* h = static_cast<CsvHandle*>(hv);
  if (j < 0 || j >= h->ncols) return "";
  return h->colnames[j].c_str();
}

// Parse up to max_rows rows into out (row-major f32 [max_rows, ncols]).
// Returns rows produced; 0 => EOF. nthreads <= 0 => hardware concurrency.
long fcsv_read_chunk(void* hv, float* out, long max_rows, int nthreads) {
  auto* h = static_cast<CsvHandle*>(hv);
  if (max_rows <= 0) return 0;
  const int ncols = h->ncols;
  // target block: ~48 bytes/cell upper bound keeps us under max_rows lines
  // in almost all cases; loop tops up if lines are shorter.
  std::vector<char> buf(std::move(h->carry));
  h->carry.clear();
  std::vector<size_t> starts, ends;
  starts.reserve(max_rows);
  ends.reserve(max_rows);
  size_t scan_from = 0;
  long nrows = 0;
  while (nrows < max_rows) {
    // find line breaks in what we have
    while (nrows < max_rows) {
      const char* base = buf.data();
      const char* nl = static_cast<const char*>(
          memchr(base + scan_from, '\n', buf.size() - scan_from));
      if (!nl) break;
      size_t line_end = nl - base;
      size_t line_start = scan_from;
      scan_from = line_end + 1;
      if (line_end > line_start && base[line_end - 1] == '\r') --line_end;
      if (line_end > line_start) {  // skip blank lines
        starts.push_back(line_start);
        ends.push_back(line_end);
        ++nrows;
      }
    }
    if (nrows >= max_rows || h->eof) break;
    // top up the buffer
    size_t old = buf.size();
    size_t want = 4u << 20;  // 4 MB reads
    buf.resize(old + want);
    size_t got = std::fread(buf.data() + old, 1, want, h->f);
    buf.resize(old + got);
    if (got == 0) {
      h->eof = true;
      // trailing line without newline
      if (scan_from < buf.size()) {
        size_t line_end = buf.size();
        if (line_end > scan_from && buf[line_end - 1] == '\r') --line_end;
        if (line_end > scan_from && nrows < max_rows) {
          starts.push_back(scan_from);
          ends.push_back(line_end);
          scan_from = buf.size();
          ++nrows;
        }
      }
      break;
    }
  }
  // stash the tail (unconsumed bytes) for the next chunk
  if (scan_from < buf.size()) {
    h->carry.assign(buf.begin() + scan_from, buf.end());
  }
  if (nrows == 0) return 0;
  int T = nthreads > 0 ? nthreads
                       : (int)std::thread::hardware_concurrency();
  if (T < 1) T = 1;
  if ((long)T > nrows) T = (int)nrows;
  if (T == 1) {
    parse_rows(buf.data(), starts, ends, 0, nrows, ncols, h->delim, out);
  } else {
    std::vector<std::thread> threads;
    size_t per = (nrows + T - 1) / T;
    for (int t = 0; t < T; ++t) {
      size_t r0 = t * per;
      size_t r1 = std::min<size_t>(r0 + per, nrows);
      if (r0 >= r1) break;
      threads.emplace_back(parse_rows, buf.data(), std::cref(starts),
                           std::cref(ends), r0, r1, ncols, h->delim, out);
    }
    for (auto& th : threads) th.join();
  }
  h->rows_read += nrows;
  return nrows;
}

void fcsv_close(void* hv) {
  auto* h = static_cast<CsvHandle*>(hv);
  if (h->f) std::fclose(h->f);
  delete h;
}

}  // extern "C"
